#!/usr/bin/env bash
# Tier-4 static/CI checks (the reference's `make presubmit` analog,
# Makefile:14,95-124): bytecode-compile every module (syntax/import-time
# errors), build the native core, compile-check the graft entry points on
# the virtual CPU mesh, then run the full suite.
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== compileall =="
python -m compileall -q karpenter_tpu tests bench.py __graft_entry__.py

# the `go vet` analog: dataflow passes (analysis/core/) for tracer-safety
# in the kernels, device-residency (DTX9xx) over the solve path, clock
# discipline (CLK10xx) and order discipline (DET11xx — unordered sources
# to order-sensitive sinks, the PYTHONHASHSEED interning class) over the
# determinism surface, kernel-arg registry consistency (ARG12xx — the
# six hand-aligned SOLVE_ARG_NAMES surfaces), retry hygiene, lock
# ordering / callback-under-lock over the whole threaded tree (LCK2xx),
# guarded-by inference with explicit thread roots (GRD13xx) plus
# check-then-act windows and cross-module lock-order cycles (ATM14xx)
# over the same surface, blocking calls in
# reconcile paths, schema<->CRD drift, kernel-twin parity skeletons
# (pack / pack_classed / solve_core.cc via `// parity:` anchors), and
# axis/dtype shape discipline over ops/+solver/ (karpenter_tpu/analysis/).
# Fast lane: the incremental set (`git diff --name-only HEAD` +
# untracked). The full run — the only mode that audits stale
# suppressions — moves to the slow lane below, behind a wall-time
# budget. Exit-code enforced by set -e: any unsuppressed finding fails.
echo "== static analysis (changed-only fast lane) =="
python -m karpenter_tpu.analysis --changed-only

# style tier: pycodestyle/pyflakes subset via ruff ([tool.ruff] in
# pyproject.toml). Gated: the container doesn't bake ruff in, and the
# analyzer above carries the correctness-critical checks either way.
if command -v ruff >/dev/null 2>&1; then
  echo "== ruff =="
  ruff check .
else
  echo "== ruff == (not installed; skipping style tier)"
fi

echo "== native build =="
python -c "from karpenter_tpu import native; native.build(force=True); print('ok')"

# deliberately conftest-free: the round driver invokes __graft_entry__
# directly (no pytest bootstrap), so this validates that exact path even
# though tests/test_parallel.py covers the same entry points under pytest
echo "== graft entry + multichip dryrun (virtual CPU mesh) =="
XLA_FLAGS="--xla_force_host_platform_device_count=8" python - <<'PY'
import jax
jax.config.update("jax_platforms", "cpu")
import __graft_entry__ as g
fn, args = g.entry()
out = jax.jit(fn)(*args)
jax.block_until_ready(out)
assert int(out[2]) > 0
g.dryrun_multichip(8)
PY

# bench smoke with tracing enabled: the emitted Chrome trace must
# validate against the checked-in minimal schema (hack/trace_schema.json
# — no dangling span ids, monotonic timestamps), the decision-path phases
# must be present, and the audit trail must have recorded the solve
echo "== trace smoke (bench smoke with tracing) =="
python hack/trace_smoke.py

# twin smoke: a fixed-seed cluster twin replays a few simulated minutes
# of churn (spot reclaim + ICE wave included) over the full roster with
# the per-minute SLO wall asserting, re-runs, and pins the canonical
# audit artifact byte-identical — all inside a wall-time budget (the
# replay-determinism fast lane; the day-scale soak is `slow`-marked)
echo "== twin smoke (fixed seed, SLO wall, budgeted) =="
python hack/twin_smoke.py

# group-heavy smoke (ISSUE 13): a fixed-seed diverse shape must stay
# fully kernel-routed (fallback_solves=0), relaxation-vs-exact decisions
# must pin (both the routed separable bulk and the all-residual diverse
# mix), and the warm solve must hold the kernel-ms budget — the
# order-of-magnitude group-axis work stays honest under regression
echo "== group-heavy smoke (sparse/segment axis + relax parity) =="
python hack/group_smoke.py

# fleet-sharding smoke (ISSUE 14): a fixed-seed constrained shape solved
# through the driver on the virtual 8-device mesh must pin decisions
# against single-device, stay fully kernel-routed, keep the warm path
# (REUSE + row deltas) mesh-resident, and hold the scenario batch at
# <= 2 dispatches — all inside a wall-time budget
echo "== mesh smoke (virtual 8-device mesh, parity + warm path) =="
python hack/mesh_smoke.py

# tenant-isolation smoke (ISSUE 20): two tenants through one resident
# service under a fixed-seed chaos plan aimed at tenant A — tenant B's
# decisions must stay byte-identical to its fault-free solo run, its
# rung must stay `batched`, and A must quarantine then recover on the
# injected clock — all inside a wall-time budget
echo "== tenant smoke (noisy-neighbor isolation, fixed seed) =="
python hack/tenant_smoke.py

# slow lane: the full analysis over every default target, with the
# stale-suppression audit (STALE001) on, behind a wall-time budget —
# analyzer-speed regressions fail here before they bloat every local
# `--changed-only` run (the SARIF run properties carry the same per-pass
# timings as a BENCH-adjacent artifact)
echo "== static analysis (full, slow lane, budgeted) =="
python - <<'PY'
import time

from karpenter_tpu.analysis.cli import main

BUDGET_SECONDS = 60.0  # full-tree dataflow run: ~7s today, 60s ceiling
t0 = time.perf_counter()
rc = main(["--all"])
elapsed = time.perf_counter() - t0
assert rc == 0, f"full analysis run found gating findings (rc={rc})"
assert elapsed < BUDGET_SECONDS, (
    f"full analysis run took {elapsed:.1f}s, over the "
    f"{BUDGET_SECONDS:.0f}s budget — profile passSeconds in the SARIF "
    "run properties"
)
print(f"full analysis OK in {elapsed:.1f}s (budget {BUDGET_SECONDS:.0f}s)")
PY

echo "== test suite =="
python -m pytest tests/ -q

# fixed-seed chaos smoke: the operator under seeded fault plans (solver
# crash + corrupt solve, provider ICE, registration stalls, store
# conflicts) must quarantine bad solves, never orphan/double-delete, and
# converge once faults clear — deterministically (tests/e2e/test_chaos.py).
# The full-length soak is marked `slow` and excluded here so tier-1 wall
# time is unchanged.
echo "== chaos smoke (fixed seeds) =="
python -m pytest tests/e2e -k chaos -m 'not slow' -q

# the race tier re-runs with different hash seeds (dict/set iteration
# orders) — the deflake analog of the reference's `-race` + `-count`
# loops (Makefile:78,85-93); the full suite above already ran it once.
# test_concurrency.py rides along: the warm-path churn hammer is the
# dynamic half of the GRD/ATM static contract
echo "== race tier (reseeded) =="
for seed in 7 23; do
  PYTHONHASHSEED=$seed python -m pytest tests/test_races.py tests/test_concurrency.py -q
done

# mechanical perf-regression gate (benchstat analog): enforced when a
# previous same-platform grid exists next to the current one
if [[ -f bench_grid_prev.json && -f bench_grid.json ]]; then
  echo "== bench grid comparison =="
  python bench.py --compare bench_grid_prev.json bench_grid.json
fi

echo "presubmit OK"
