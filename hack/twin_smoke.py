#!/usr/bin/env python
"""Presubmit fast-lane twin smoke (ISSUE 12).

A fixed-seed cluster twin replays a few simulated minutes of churn —
including one spot-reclaim and one ICE wave — over the full operator
roster with the per-minute SLO wall ASSERTING, then re-runs and pins
the canonical audit artifact byte-identical (the replay-determinism
contract), all under a wall-time budget like the analyzer's 60 s lane.
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

BUDGET_SECONDS = 90.0  # ~25 s today; headroom for slower hosts


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    if os.environ.get("JAX_PLATFORMS", "cpu") in ("cpu", "axon"):
        jax.config.update("jax_platforms", "cpu")

    from karpenter_tpu.sim import trace as trace_mod
    from karpenter_tpu.sim.slo import SLOConfig
    from karpenter_tpu.sim.twin import ClusterProfile, ClusterTwin, TwinConfig

    t0 = time.perf_counter()
    profile = ClusterProfile(nodes=80, pods_per_node=6)
    events = trace_mod.generate(
        3,
        trace_mod.ChurnProfile(
            minutes=4, pods_per_minute=4,
            reclaim_minutes=(1,), ice_minutes=(2,),
        ),
    )
    cfg = TwinConfig(
        seed=3, minutes=4, steps_per_minute=2,
        slo=SLOConfig(cost_check_every=2),
    )

    def one_run():
        with ClusterTwin(events, profile=profile, config=cfg) as twin:
            reports = twin.run()  # SLO wall asserts per minute
            return twin.canonical_audit(), reports, twin

    audit_a, reports, twin = one_run()
    audit_b, _, _ = one_run()
    assert audit_a == audit_b, "twin replay is not byte-deterministic"
    assert len(reports) == cfg.minutes
    assert all(not r.violations for r in reports)
    worst = max(reports, key=lambda r: r.p99_latency_ms)
    elapsed = time.perf_counter() - t0
    assert elapsed < BUDGET_SECONDS, (
        f"twin smoke took {elapsed:.1f}s, over the {BUDGET_SECONDS:.0f}s "
        "budget — profile the replay loop (binder, scenario.build, "
        "consolidation probe budget)"
    )
    print(
        f"twin smoke OK in {elapsed:.1f}s (budget {BUDGET_SECONDS:.0f}s): "
        f"{cfg.minutes} simulated minutes, worst-minute "
        f"p99={worst.p99_latency_ms:.0f}ms, zero SLO violations, "
        "byte-identical replay"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
