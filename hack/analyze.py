#!/usr/bin/env python
"""Wrapper for the static-analysis tier: ``hack/analyze.py [args...]``.

Equivalent to ``python -m karpenter_tpu.analysis`` run from the repo root;
exists so presubmit and editors have a stable path that works from any cwd.
"""

import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

if __name__ == "__main__":
    sys.path.insert(0, REPO_ROOT)
    from karpenter_tpu.analysis.cli import main

    argv = sys.argv[1:]
    if "--root" not in argv:
        argv = ["--root", REPO_ROOT] + argv
    raise SystemExit(main(argv))
