"""Multi-chip scaling measurement over the virtual CPU mesh (r06 layout).

Runs the constrained north-star snapshot through the sharded solve at
1/2/4/8 devices over the r06 factorizations — data (the segment live-pair
axis), model (types), and mixed — asserting output equality against the
single-device program and recording, per configuration, the wall time AND
the compiled scan structure (collectives inside the packing scan's while
bodies, parallel.mesh.scan_collective_report). CPU virtual devices share
the host's cores, so wall times measure GSPMD partitioning + collective
overhead (the scaling *shape*), not real ICI speedup — the structure
columns are the host-independent signal: the r05 G-sharded layout paid an
all-gather per scan step (12x); the r06 data axis pays zero.

Usage: XLA_FLAGS=--xla_force_host_platform_device_count=8 \
           python hack/mesh_scaling.py [n_pods] [n_types]
Writes hack/mesh_scaling.json and prints a markdown table for PARITY.md.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "2")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update(
    "jax_compilation_cache_dir", os.path.expanduser("~/.cache/jax")
)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

import numpy as np  # noqa: E402


def build_snapshot(n_pods: int, n_types: int):
    from karpenter_tpu.cloudprovider import corpus
    from karpenter_tpu.kube import Client, TestClock
    from karpenter_tpu.scheduling.topology import Topology
    from karpenter_tpu.solver import TpuSolver
    from karpenter_tpu.solver import encode as enc
    from karpenter_tpu.solver.example import example_nodepool
    from karpenter_tpu.solver.workloads import constrained_mix

    pods = constrained_mix(n_pods)
    pools = [example_nodepool()]
    its_by_pool = {pools[0].name: corpus.generate(n_types)}
    topology = Topology(Client(TestClock()), [], pools, its_by_pool, pods)
    solver = TpuSolver(pools, its_by_pool, topology)
    groups, rest = enc.partition_and_group(pods, topology=topology)
    assert not rest, f"{len(rest)} pods not tensorizable"
    templates = solver.oracle.templates
    snap = enc.encode(
        groups, templates,
        {t.node_pool_name: t.instance_type_options for t in templates},
        daemon_overhead=solver.oracle.daemon_overhead,
    )
    a_tzc, res_cap0, a_res = solver._offering_availability(snap)
    fit = solver._fit_matrix(snap)
    nmax = solver._estimate_nmax(snap, fit)
    statics = dict(
        nmax=nmax,
        zone_kid=snap.zone_kid,
        ct_kid=snap.ct_kid,
        has_domains=bool((snap.g_dmode > 0).any()),
        has_contrib=bool(snap.g_hcontrib.any() or snap.g_dcontrib.any()),
        wf_iters=solver._wf_iters(snap),
        sparse_groups=True,
    )
    args = snap.solve_args(a_tzc, res_cap0, a_res)
    return args, statics


def time_fn(run, reps=3):
    run()  # warm (compile)
    best = float("inf")
    out = None
    for _ in range(reps):
        t0 = time.perf_counter()
        out = run()
        jax.block_until_ready(out)
        best = min(best, time.perf_counter() - t0)
    return best, out


def main():
    n_pods = int(sys.argv[1]) if len(sys.argv) > 1 else 50_000
    n_types = int(sys.argv[2]) if len(sys.argv) > 2 else 800
    from karpenter_tpu.ops.solve import solve_all
    from karpenter_tpu.parallel.mesh import (
        make_mesh, pad_args_for_mesh, scan_collective_report,
        sharded_solve_fn,
    )

    args, statics = build_snapshot(n_pods, n_types)
    G, T = args[0].shape[0], args[28].shape[0]
    print(
        f"snapshot: pods={n_pods} types={n_types} G={G} T={T}"
        f" nmax={statics['nmax']}",
        file=sys.stderr,
    )

    base_t, base_out = time_fn(lambda: solve_all(*args, **statics))
    rows = [{
        "devices": 1, "scenario": 1, "data": 1, "model": 1,
        "solve_ms": round(base_t * 1000, 1),
        "scan_collectives": 0, "scan_collectives_scalar": 0,
        "total_collectives": 0,
    }]
    print(f"single-device: solve={base_t * 1000:.0f}ms", file=sys.stderr)

    ref = [np.asarray(x) for x in jax.device_get(base_out)]
    n_open = int(ref[2])

    configs = []
    for n in (2, 4, 8):
        for data in (1, 2, 4, 8):
            if data <= n and n % data == 0:
                configs.append((n, data, n // data))
    for n, data, model in configs:
        mesh = make_mesh(n, data=data)
        margs = pad_args_for_mesh(args, mesh)
        fn = sharded_solve_fn(mesh, **statics)

        def run():
            with mesh:
                return fn(*margs)

        t, out = time_fn(run)
        got = [np.asarray(x) for x in jax.device_get(out)]
        assert int(got[2]) == n_open, (n, data, model, int(got[2]), n_open)
        np.testing.assert_array_equal(
            got[0][:n_open], ref[0][:n_open], err_msg="c_pool"
        )
        np.testing.assert_array_equal(
            got[5][:, : ref[5].shape[1]][: ref[5].shape[0]],
            ref[5],
            err_msg="claim_fills",
        )
        report = scan_collective_report(fn.lower(*margs).compile().as_text())
        rows.append({
            "devices": n, "scenario": 1, "data": data, "model": model,
            "solve_ms": round(t * 1000, 1),
            "scan_collectives": report["collectives_in_scan_data"],
            "scan_collectives_scalar": report["collectives_in_scan_scalar"],
            "total_collectives": report["collectives_total"],
        })
        print(
            f"mesh d{data}xm{model} ({n} dev): solve={t * 1000:.0f}ms"
            f" scan_coll={report['collectives_in_scan_data']}"
            f" total_coll={report['collectives_total']} (outputs equal)",
            file=sys.stderr,
        )

    out_path = os.path.join(os.path.dirname(__file__), "mesh_scaling.json")
    with open(out_path, "w") as fh:
        json.dump(
            {"pods": n_pods, "types": n_types, "G": G, "T": T,
             "platform": "cpu-virtual", "layout": "r06", "rows": rows},
            fh, indent=1,
        )
    print("\n| devices | data x model | solve ms | scan data-collectives |"
          " program collectives |")
    print("|---|---|---|---|---|")
    for r in rows:
        print(
            f"| {r['devices']} | {r['data']}x{r['model']} |"
            f" {r['solve_ms']} | {r['scan_collectives']} |"
            f" {r['total_collectives']} |"
        )


if __name__ == "__main__":
    main()
