"""Multi-chip scaling measurement over the virtual CPU mesh.

Runs the constrained north-star snapshot through the sharded solve at
1/2/4/8 devices and every (data, model) factorization, asserting output
equality against the single-device program and timing (a) the full fused
solve and (b) the feasibility stage alone under the same shardings. CPU
virtual devices share the host's cores, so the numbers measure GSPMD
partitioning + collective overhead (the scaling *shape*), not real ICI
speedup — exactly what can be validated without multi-chip hardware.

Usage: XLA_FLAGS=--xla_force_host_platform_device_count=8 \
           python hack/mesh_scaling.py [n_pods] [n_types]
Writes hack/mesh_scaling.json and prints a markdown table for PARITY.md.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update(
    "jax_compilation_cache_dir", os.path.expanduser("~/.cache/jax")
)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

import numpy as np  # noqa: E402


def build_snapshot(n_pods: int, n_types: int):
    from karpenter_tpu.cloudprovider import corpus
    from karpenter_tpu.kube import Client, TestClock
    from karpenter_tpu.scheduling.topology import Topology
    from karpenter_tpu.solver import TpuSolver
    from karpenter_tpu.solver import encode as enc
    from karpenter_tpu.solver.example import example_nodepool
    from karpenter_tpu.solver.workloads import constrained_mix

    pods = constrained_mix(n_pods)
    pools = [example_nodepool()]
    its_by_pool = {pools[0].name: corpus.generate(n_types)}
    topology = Topology(Client(TestClock()), [], pools, its_by_pool, pods)
    solver = TpuSolver(pools, its_by_pool, topology)
    groups, rest = enc.partition_and_group(pods, topology=topology)
    assert not rest, f"{len(rest)} pods not tensorizable"
    templates = solver.oracle.templates
    snap = enc.encode(
        groups, templates,
        {t.node_pool_name: t.instance_type_options for t in templates},
        daemon_overhead=solver.oracle.daemon_overhead,
    )
    a_tzc, res_cap0, a_res = solver._offering_availability(snap)
    fit = solver._fit_matrix(snap)
    nmax = solver._estimate_nmax(snap, fit)
    statics = dict(
        nmax=nmax,
        zone_kid=snap.zone_kid,
        ct_kid=snap.ct_kid,
        has_domains=bool((snap.g_dmode > 0).any()),
        has_contrib=bool(snap.g_hcontrib.any() or snap.g_dcontrib.any()),
        wf_iters=solver._wf_iters(snap),
    )
    args = snap.solve_args(a_tzc, res_cap0, a_res)
    return args, statics


def time_fn(run, reps=3):
    run()  # warm (compile)
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        out = run()
        jax.block_until_ready(out)
        best = min(best, time.perf_counter() - t0)
    return best, out


def feasibility_only_fn(mesh, statics):
    """The feasibility stage alone, under the same input shardings — the
    embarrassingly-parallel part whose scaling the mesh exists for."""
    from karpenter_tpu.ops.solve import _feasibility_tables
    from karpenter_tpu.parallel.mesh import snapshot_shardings

    def feas(*args):
        (
            g_count, g_req, g_def, g_neg, g_mask, g_hcap, g_haff,
            g_dmode, g_dkey, g_dskew, g_dmin0, g_dprior, g_dreg, g_drank,
            g_hstg, g_hscap, g_dtg, g_hself, g_hcontrib, g_dcontrib,
            p_def, p_neg, p_mask, p_daemon, p_limit, p_has_limit, p_tol,
            p_titype_ok,
            t_def, t_mask, t_alloc, t_cap,
            o_avail, o_zone, o_ct, a_tzc, res_cap0, a_res,
            n_def, n_mask, n_avail, n_base, n_tol, n_hcnt, n_dzone, n_dct,
            nh_cnt0, dd0, dtg_key, well_known,
        ) = args
        return _feasibility_tables(
            g_count, g_def, g_neg, g_mask, g_req,
            p_def, p_neg, p_mask, p_daemon, p_tol, p_titype_ok,
            t_def, t_mask, t_alloc,
            o_avail, o_zone, o_ct,
            n_def, n_mask, n_avail, n_base, n_tol,
            well_known,
            zone_kid=statics["zone_kid"],
            ct_kid=statics["ct_kid"],
            tile_feasibility=False,
        )

    if mesh is None:
        return jax.jit(feas)
    return jax.jit(
        feas,
        in_shardings=snapshot_shardings(mesh),
        out_shardings=jax.sharding.NamedSharding(
            mesh, jax.sharding.PartitionSpec()
        ),
    )


def main():
    n_pods = int(sys.argv[1]) if len(sys.argv) > 1 else 50_000
    n_types = int(sys.argv[2]) if len(sys.argv) > 2 else 800
    from karpenter_tpu.ops.solve import solve_all
    from karpenter_tpu.parallel.mesh import (
        make_mesh, pad_args_for_mesh, sharded_solve_fn,
    )

    args, statics = build_snapshot(n_pods, n_types)
    G, T = args[0].shape[0], args[30].shape[0]
    print(
        f"snapshot: pods={n_pods} types={n_types} G={G} T={T}"
        f" nmax={statics['nmax']}",
        file=sys.stderr,
    )

    base_t, base_out = time_fn(lambda: solve_all(*args, **statics))
    feas1 = feasibility_only_fn(None, statics)
    base_feas_t, _ = time_fn(lambda: feas1(*args))
    rows = [{
        "devices": 1, "data": 1, "model": 1,
        "solve_ms": round(base_t * 1000, 1),
        "feas_ms": round(base_feas_t * 1000, 1),
    }]
    print(
        f"single-device: solve={base_t * 1000:.0f}ms"
        f" feas={base_feas_t * 1000:.0f}ms",
        file=sys.stderr,
    )

    ref = [np.asarray(x) for x in jax.device_get(base_out)]
    n_open = int(ref[2])

    configs = []
    for n in (2, 4, 8):
        for data in (1, 2, 4, 8):
            if data <= n and n % data == 0:
                configs.append((n, data, n // data))
    for n, data, model in configs:
        mesh = make_mesh(n, data=data)
        margs = pad_args_for_mesh(args, mesh)
        fn = sharded_solve_fn(mesh, **statics)

        def run():
            with mesh:
                return fn(*margs)

        t, out = time_fn(run)
        got = [np.asarray(x) for x in jax.device_get(out)]
        assert int(got[2]) == n_open, (n, data, model, int(got[2]), n_open)
        np.testing.assert_array_equal(
            got[0][:n_open], ref[0][:n_open], err_msg="c_pool"
        )
        np.testing.assert_array_equal(
            got[5][:, : ref[5].shape[1]][: ref[5].shape[0]],
            ref[5],
            err_msg="claim_fills",
        )
        feas = feasibility_only_fn(mesh, statics)

        def run_feas():
            with mesh:
                return feas(*margs)

        ft, _ = time_fn(run_feas)
        rows.append({
            "devices": n, "data": data, "model": model,
            "solve_ms": round(t * 1000, 1),
            "feas_ms": round(ft * 1000, 1),
        })
        print(
            f"mesh {data}x{model} ({n} dev): solve={t * 1000:.0f}ms"
            f" feas={ft * 1000:.0f}ms (outputs equal)",
            file=sys.stderr,
        )

    out_path = os.path.join(os.path.dirname(__file__), "mesh_scaling.json")
    with open(out_path, "w") as fh:
        json.dump(
            {"pods": n_pods, "types": n_types, "G": G, "T": T,
             "platform": "cpu-virtual", "rows": rows},
            fh, indent=1,
        )
    print(f"\n| devices | data x model | solve ms | feasibility ms |")
    print("|---|---|---|---|")
    for r in rows:
        print(
            f"| {r['devices']} | {r['data']}x{r['model']} |"
            f" {r['solve_ms']} | {r['feas_ms']} |"
        )


if __name__ == "__main__":
    main()
