"""Presubmit multi-tenant isolation smoke (ISSUE 20).

One resident solver service, two tenants, a fixed-seed tenant-scoped
chaos plan aimed at tenant A (kernel dispatch crash, corrupt kernel
output, corrupt encode delta, service-level solve crash) while tenant B
keeps solving through the SAME service. The gate:

- tenant B's decisions are BYTE-IDENTICAL to its fault-free solo run,
  its rung stays ``batched``, and its ``fallback_solves``/``rejected``
  counters stay 0 (the noisy-neighbor isolation wall);
- tenant A actually suffered: the corrupt output tripped the invariant
  guard into quarantine, its rung degraded, and the service-level crash
  surfaced to its caller;
- once the faults clear and the breaker cool-down elapses on the
  injected clock, tenant A re-closes its ladder (recovery);
- the whole smoke finishes inside a wall-time budget.

Everything is seeded and clock-injected; a failure here is a real
isolation leak or a ladder regression, not a flake.
"""

from __future__ import annotations

import os
import sys
import time

os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "2")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

if (jax.config.jax_platforms or "axon").split(",")[0] == "axon":
    jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

SEED = 7
N_ROUNDS = 4
BUDGET_S = 90.0  # measured ~15 s cold on the fallback host; ~6x headroom


def _signature(results):
    """Order-independent canonical form of a Results — the byte-identity
    basis (mirrors tests/helpers.decision_signature)."""
    return (
        sorted(
            (
                c.template.node_pool_name,
                tuple(sorted(p.uid for p in c.pods)),
                tuple(sorted(it.name for it in c.instance_type_options)),
            )
            for c in results.new_node_claims
        ),
        sorted(
            (n.name, tuple(sorted(p.uid for p in pods)))
            for n, pods in results.existing_nodes
        ),
        sorted(results.pod_errors),
    )


def main() -> int:
    from karpenter_tpu import faults
    from karpenter_tpu.cloudprovider import corpus
    from karpenter_tpu.kube import TestClock
    from karpenter_tpu.solver import wire
    from karpenter_tpu.solver.driver import SolverConfig
    from karpenter_tpu.solver.example import example_nodepool
    from karpenter_tpu.solver.service import TenantService
    from karpenter_tpu.solver.tenancy import TenantRegistry
    from karpenter_tpu.solver.workloads import mixed_pods

    t_start = time.perf_counter()
    pools = [example_nodepool()]
    its = {pools[0].name: corpus.generate(16)}

    # request bytes encoded ONCE per (tenant, round) — decoding the same
    # bytes for the chaos run and the baseline pins identical pod uids,
    # which the byte-identity witness keys on
    def requests(prefix, sizes):
        out = []
        for i, n in enumerate(sizes):
            pods = mixed_pods(n, seed=SEED + i, gpu_fraction=0.0)
            for j, p in enumerate(pods):
                p.metadata.name = f"{prefix}{i}-{j}"
                p.metadata.uid = f"uid-{prefix}{i}-{j}"
            out.append(
                wire.encode_solve_request(
                    pods,
                    pools,
                    its,
                    solver_options={"reserved_capacity_enabled": False},
                )
            )
        return out

    a_reqs = requests("a", [12 + 2 * i for i in range(N_ROUNDS)])
    b_reqs = requests("b", [10 + 2 * i for i in range(N_ROUNDS)])

    def chaos_rules(victim):
        def only_victim(ctx):
            return ctx.get("tenant") == victim

        def corrupt_fills(outs):
            outs = list(outs)
            outs[5] = np.asarray(outs[5]) - 7  # claim_fills negative
            return tuple(outs)

        return [
            faults.FaultRule(
                faults.SOLVER_DISPATCH, times=1, match=only_victim
            ),
            # times=2: the guard's first rejection on a warm encoding
            # takes the delta-fallback half-step (shed + full re-encode
            # retry); the corruption must persist through the retry to
            # reach the quarantine leg
            faults.FaultRule(
                faults.SOLVER_OUTPUT,
                mutate=corrupt_fills,
                times=2,
                match=only_victim,
            ),
            faults.FaultRule(
                faults.ENCODE_DELTA,
                mutate=lambda vals: np.asarray(vals) + 13,
                match=only_victim,
            ),
            faults.FaultRule(
                faults.TENANT_SOLVE, times=1, after=1, match=only_victim
            ),
        ]

    # -- fault-free solo baseline for tenant B ----------------------------
    baseline_svc = TenantService(config=SolverConfig(relax=False))
    baseline = [
        _signature(baseline_svc.solve_for("b", wire.decode_solve_request(r)))
        for r in b_reqs
    ]

    # -- the chaos run: A's fault plan fires, B keeps solving -------------
    clock = TestClock()
    svc = TenantService(
        registry=TenantRegistry(clock=clock),
        config=SolverConfig(relax=False),
    )
    inj = faults.install(
        faults.FaultInjector(chaos_rules("a"), seed=SEED, clock=clock)
    )
    b_sigs = []
    a_errors = 0
    try:
        for a_req, b_req in zip(a_reqs, b_reqs):
            try:
                svc.solve_for("a", wire.decode_solve_request(a_req))
            except faults.InjectedFault:
                a_errors += 1
            b_sigs.append(
                _signature(svc.solve_for("b", wire.decode_solve_request(b_req)))
            )

        fired_sites = {s for s, _, _ in inj.log}
        assert faults.SOLVER_OUTPUT in fired_sites, sorted(fired_sites)
        assert faults.SOLVER_DISPATCH in fired_sites, sorted(fired_sites)
        assert faults.TENANT_SOLVE in fired_sites, sorted(fired_sites)
        a = svc.registry.get("a")
        assert a.health.quarantines >= 1, "corrupt output never quarantined"
        assert a.health.level() > 0, "victim's ladder never degraded"
        assert a_errors >= 1, "service-level crash never surfaced to A"

        b = svc.registry.get("b")
        assert b_sigs == baseline, (
            "ISOLATION LEAK: bystander decisions moved under neighbor chaos"
        )
        assert b.health.RUNGS[b.health.level()] == "batched", (
            "bystander rung moved"
        )
        assert b.health.quarantines == 0
        assert b.stats()["fallback_solves"] == 0, b.stats()
        assert b.stats()["rejected"] == 0, b.stats()

        # -- recovery: faults clear, cool-down elapses, ladder re-closes --
        inj.clear()
        clock.step(130.0)  # past the 120 s breaker cool-down
        recover = svc.solve_for("a", wire.decode_solve_request(a_reqs[0]))
        assert recover.all_pods_scheduled()
        assert a.health.level() == 0, "victim never re-closed its ladder"
    finally:
        faults.uninstall()

    elapsed = time.perf_counter() - t_start
    assert elapsed < BUDGET_S, (
        f"tenant smoke took {elapsed:.1f}s, over the {BUDGET_S:.0f}s budget"
    )
    print(
        f"tenant smoke OK in {elapsed:.1f}s (budget {BUDGET_S:.0f}s):"
        f" {N_ROUNDS} interleaved rounds, victim"
        f" quarantines={a.health.quarantines}"
        f" errors={a_errors} then recovered; bystander byte-identical,"
        f" rung=batched, fallback_solves=0"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
