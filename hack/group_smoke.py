"""Presubmit group-heavy smoke (ISSUE 13).

A small fixed-seed diverse shape (the group-heavy reference mix: ~5
classes fragmenting into hundreds of tiny groups with spread /
anti-affinity topology) must:

- stay fully kernel-routed (``fallback_solves == 0``);
- produce decisions IDENTICAL between the relax-enabled production path
  and a forced-exact solve (the relaxation decision-parity gate — on
  this mix nothing is separable, so the planner must route the full
  residual), and identical between relax-enabled runs of a separable
  bulk batch and its forced-exact twin (the routed-path parity gate);
- finish the warm solve inside a kernel-ms budget (the order-of-
  magnitude kernel-work regression wall; generous vs the measured
  number so scheduler jitter cannot flake presubmit).
"""

from __future__ import annotations

import os
import sys
import time

os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "2")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

if (jax.config.jax_platforms or "axon").split(",")[0] == "axon":
    jax.config.update("jax_platforms", "cpu")

N_PODS = 600
N_TYPES = 60
SEED = 13
# warm end-to-end budget on the CPU fallback host: measured ~42 ms for
# this shape after the segment/bucketing/NMAX work; ~10x headroom for CI
# noise (the pre-PR kernel ran this shape at ~5x the budget)
BUDGET_MS = 400.0


def _solve(pods, relax: bool):
    from karpenter_tpu.cloudprovider import corpus
    from karpenter_tpu.kube import Client, TestClock
    from karpenter_tpu.scheduling.topology import Topology
    from karpenter_tpu.solver import TpuSolver
    from karpenter_tpu.solver.driver import EncodeCache, SolverConfig
    from karpenter_tpu.solver.example import example_nodepool

    pools = [example_nodepool()]
    its = {pools[0].name: corpus.generate(N_TYPES)}
    cache = EncodeCache()

    def once():
        topology = Topology(Client(TestClock()), [], pools, its, pods)
        return TpuSolver(
            pools, its, topology,
            config=SolverConfig(relax=relax), encode_cache=cache,
        )

    once().solve(pods)  # a-priori NMAX compile
    once().solve(pods)  # adaptive NMAX compile
    s = once()
    t0 = time.perf_counter()
    r = s.solve(pods)
    return s, r, (time.perf_counter() - t0) * 1000.0


def _canon(results):
    return (
        sorted(
            (
                c.template.node_pool_name,
                tuple(sorted(p.uid for p in c.pods)),
                tuple(sorted(it.name for it in c.instance_type_options)),
            )
            for c in results.new_node_claims
        ),
        sorted(results.pod_errors),
    )


def main() -> int:
    from karpenter_tpu.api import labels as labels_mod
    from karpenter_tpu.api import resources as res
    from karpenter_tpu.api.objects import ObjectMeta, Pod, PodSpec
    from karpenter_tpu.solver.workloads import diverse_reference_mix

    pods = diverse_reference_mix(N_PODS, seed=SEED)
    s_relax, r_relax, warm_ms = _solve(pods, relax=True)
    assert s_relax.fallback_solves == 0, (
        f"group-heavy smoke fell off the kernel path: "
        f"{s_relax.last_fallback_reasons}"
    )
    assert not r_relax.pod_errors, r_relax.pod_errors
    assert s_relax.relax_rejects == 0, "relax guard rejected on the smoke"
    # diverse: nothing separable — the planner must hand the exact kernel
    # the full batch, and decisions must pin against forced-exact
    assert s_relax.last_relax_pods == 0
    s_exact, r_exact, _ = _solve(pods, relax=False)
    assert _canon(r_relax) == _canon(r_exact), (
        "relax-enabled diverse decisions diverged from forced-exact"
    )

    # routed-path parity: a separable bulk (one uniform deployment per
    # zone) must route through the relaxation and still pin decisions
    zones = ["test-zone-a", "test-zone-b", "test-zone-c"]
    bulk = [
        Pod(
            metadata=ObjectMeta(name=f"bulk-{i}"),
            spec=PodSpec(
                requests={
                    res.CPU: (1 + i % 3) * 500,
                    res.MEMORY: 2**30 * res.MILLI,
                },
                node_selector={labels_mod.TOPOLOGY_ZONE: zones[i % 3]},
            ),
        )
        for i in range(300)
    ]
    sb, rb, _ = _solve(bulk, relax=True)
    assert sb.last_relax_pods == len(bulk), "separable bulk did not route"
    sbe, rbe, _ = _solve(bulk, relax=False)
    assert _canon(rb) == _canon(rbe), (
        "relax-routed bulk decisions diverged from forced-exact"
    )

    assert warm_ms < BUDGET_MS, (
        f"group-heavy warm solve {warm_ms:.0f} ms over the "
        f"{BUDGET_MS:.0f} ms budget"
    )
    print(
        f"group smoke OK: {N_PODS} diverse pods warm={warm_ms:.0f}ms "
        f"(budget {BUDGET_MS:.0f}), fallback_solves=0, relax parity "
        f"pinned (diverse residual=all, bulk routed={sb.last_relax_pods})"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
