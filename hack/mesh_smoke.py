"""Presubmit fleet-sharding smoke (ISSUE 14).

A fixed-seed constrained shape solved THROUGH the driver on the virtual
8-device mesh (r06 layout: segment live-pair axis on 'data', scan state
replicated) must:

- produce decisions IDENTICAL to the single-device solver (the mesh
  parity gate);
- stay fully kernel-routed on both paths (``fallback_solves == 0``);
- hit the content-hash REUSE outcome on an unchanged warm re-solve with
  the staged buffers still mesh-resident, and ride a ROW DELTA (not a
  full re-encode) across a small churn tick (the sharding-aware warm
  path);
- keep a scenario batch at <= 2 dispatches under the scenario-major mesh;
- finish the whole smoke inside a wall-time budget (compile included —
  generous so CI scheduler jitter cannot flake it).

The per-scan-step-collective structure itself is pinned host-independently
by tests/test_parallel.py (compiled-HLO audit); this smoke is the
end-to-end driver-path gate.
"""

from __future__ import annotations

import os
import sys
import time

os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "2")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

if (jax.config.jax_platforms or "axon").split(",")[0] == "axon":
    jax.config.update("jax_platforms", "cpu")

N_PODS = 800
N_TYPES = 40
SEED = 11  # constrained_mix's default seed — the parity suites' shape
BUDGET_S = 180.0  # measured ~35 s cold on the fallback host; ~5x headroom


def _canon(results):
    return (
        sorted(
            (
                c.template.node_pool_name,
                tuple(sorted(p.uid for p in c.pods)),
                tuple(sorted(it.name for it in c.instance_type_options)),
            )
            for c in results.new_node_claims
        ),
        sorted(results.pod_errors),
    )


def main() -> int:
    from karpenter_tpu.cloudprovider import corpus
    from karpenter_tpu.kube import Client, TestClock
    from karpenter_tpu.parallel.mesh import make_mesh
    from karpenter_tpu.scheduling.topology import Topology
    from karpenter_tpu.solver import TpuSolver
    from karpenter_tpu.solver.driver import (
        EncodeCache, Scenario, SolverConfig,
    )
    from karpenter_tpu.solver.example import example_nodepool
    from karpenter_tpu.solver.workloads import constrained_mix

    t_start = time.perf_counter()
    if len(jax.devices()) < 8:
        print("mesh smoke SKIP: needs 8 virtual devices", file=sys.stderr)
        return 0
    mesh = make_mesh(8)
    pods = constrained_mix(N_PODS, seed=SEED)
    pools = [example_nodepool()]
    its = {pools[0].name: corpus.generate(N_TYPES)}

    def solver_for(cfg, cache, current_pods):
        topology = Topology(Client(TestClock()), [], pools, its, current_pods)
        return TpuSolver(
            pools, its, topology, config=cfg, encode_cache=cache
        )

    # -- parity gate: mesh decisions == single-device decisions -----------
    mesh_cache = EncodeCache()
    s_mesh = solver_for(SolverConfig(mesh=mesh), mesh_cache, pods)
    r_mesh = s_mesh.solve(pods)
    s_one = solver_for(SolverConfig(), EncodeCache(), pods)
    r_one = s_one.solve(pods)
    assert _canon(r_mesh) == _canon(r_one), (
        "mesh decisions diverged from single-device"
    )
    assert s_mesh.fallback_solves == 0, s_mesh.last_fallback_reasons
    assert s_one.fallback_solves == 0, s_one.last_fallback_reasons
    assert not r_mesh.pod_errors, r_mesh.pod_errors

    # -- sharding-aware warm path: REUSE, then a row delta ----------------
    s2 = solver_for(SolverConfig(mesh=mesh), mesh_cache, pods)
    s2.solve(pods)
    assert s2.last_encode_reused, "unchanged mesh re-solve missed REUSE"
    assert s2.fallback_solves == 0
    churned = list(pods)
    churned[0], churned[1] = churned[1], churned[0]
    churned[2] = constrained_mix(N_PODS, seed=SEED + 1)[0]
    s3 = solver_for(SolverConfig(mesh=mesh), mesh_cache, churned)
    s3.solve(churned)
    assert not s3.last_encode_reused
    full_encode = not s3.last_encode_reused and s3.last_delta_rows == 0
    assert not full_encode, "churn tick forced a FULL re-encode on the mesh"
    store = mesh_cache.device_store
    assert store is not None and store._mesh_key == mesh, (
        "staged buffers are not mesh-resident"
    )

    # -- scenario axis: a consolidation-shaped batch in <= 2 dispatches ---
    # (mixed shape: constrained_mix's self-anti-affinity groups are a
    # documented scenario-batch decline remnant, PARITY.md)
    from karpenter_tpu.solver.workloads import mixed_pods

    mpods = mixed_pods(400, gpu_fraction=0.0)
    scens = [Scenario(pods=mpods[: 80 * (i + 1)]) for i in range(5)]
    s4 = solver_for(SolverConfig(mesh=mesh), mesh_cache, mpods)
    r4 = s4.solve_scenarios(scens)
    assert r4 is not None, "scenario batch declined under the mesh"
    assert s4.last_scenario_dispatches <= 2, s4.last_scenario_dispatches
    assert s4.fallback_solves == 0

    elapsed = time.perf_counter() - t_start
    assert elapsed < BUDGET_S, (
        f"mesh smoke took {elapsed:.1f}s, over the {BUDGET_S:.0f}s budget"
    )
    print(
        f"mesh smoke OK in {elapsed:.1f}s (budget {BUDGET_S:.0f}s):"
        f" {N_PODS} constrained pods on"
        f" {dict(zip(mesh.axis_names, mesh.devices.shape))},"
        f" parity pinned, fallback_solves=0, warm REUSE +"
        f" delta_rows={s3.last_delta_rows} mesh-resident,"
        f" scenario dispatches={s4.last_scenario_dispatches}"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
