"""Presubmit trace smoke: the bench smoke with tracing enabled.

Runs a small solver config (bench.py's workload builders) under an
installed tracer, then asserts:

- the emitted Chrome trace validates against the checked-in minimal
  schema (hack/trace_schema.json): required keys, no dangling span ids,
  non-negative durations, monotonic timestamps;
- the decision-path phases the ROADMAP's delta-encode item needs
  (encode / dispatch / decode) actually appear, so a refactor can't
  silently unthread the tracer from the solve path;
- the decision audit trail recorded the solve with a kernel-rung verdict.

Exit nonzero on any violation (hack/presubmit.sh runs this).
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from bench import _build  # noqa: E402
from karpenter_tpu import obs  # noqa: E402

SCHEMA_PATH = os.path.join(os.path.dirname(__file__), "trace_schema.json")


def main() -> int:
    with open(SCHEMA_PATH, encoding="utf-8") as fh:
        schema = json.load(fh)

    make_solver, pods = _build("identical", 200, 10)
    make_solver().solve(pods)  # warm the compile cache untraced

    audit_before = len(obs.AUDIT.query(kind="solve"))
    tracer = obs.install(obs.Tracer(obs.PerfClock(), seed=0))
    try:
        results = make_solver().solve(pods)
    finally:
        obs.uninstall()

    assert not results.pod_errors, "smoke workload must schedule fully"

    doc = tracer.export_chrome()
    problems = obs.validate_chrome_trace(doc, schema)
    if problems:
        for p in problems:
            print(f"trace-smoke: INVALID: {p}", file=sys.stderr)
        return 1

    totals = tracer.phase_totals()
    for phase in ("solve", "solve.encode", "solve.dispatch", "solve.decode"):
        if phase not in totals:
            print(
                f"trace-smoke: phase {phase!r} missing from the trace "
                f"(got {sorted(totals)})",
                file=sys.stderr,
            )
            return 1

    records = obs.AUDIT.query(kind="solve")[audit_before:]
    if not records:
        print("trace-smoke: no decision audit record emitted", file=sys.stderr)
        return 1
    rec = records[-1]
    if rec.rung != "kernel" or rec.guard != "ok" or not rec.encode_hash:
        print(
            f"trace-smoke: malformed audit record: rung={rec.rung}"
            f" guard={rec.guard} encode_hash={rec.encode_hash!r}",
            file=sys.stderr,
        )
        return 1

    # one churn tick traced (ISSUE 8): a count-level delta must ride the
    # device-resident path — the solve.delta_apply span proves the rows
    # went as an in-place update, and the audit record must carry the
    # incremental-encode provenance fields
    churned = pods[:-1]  # one pod gone: same group shapes, new count
    tracer2 = obs.install(obs.Tracer(obs.PerfClock(), seed=1))
    try:
        results2 = make_solver().solve(churned)
    finally:
        obs.uninstall()
    assert not results2.pod_errors, "churn tick must schedule fully"
    totals2 = tracer2.phase_totals()
    if "solve.delta_apply" not in totals2:
        print(
            "trace-smoke: churn tick missing the solve.delta_apply span "
            f"(got {sorted(totals2)})",
            file=sys.stderr,
        )
        return 1
    rec2 = obs.AUDIT.query(kind="solve")[-1]
    if rec2.encode_reused is None or rec2.delta_rows is None:
        print(
            "trace-smoke: audit record missing incremental-encode fields: "
            f"encode_reused={rec2.encode_reused!r} delta_rows={rec2.delta_rows!r}",
            file=sys.stderr,
        )
        return 1
    if rec2.delta_rows < 1:
        print(
            f"trace-smoke: churn tick reported no delta rows "
            f"(delta_rows={rec2.delta_rows})",
            file=sys.stderr,
        )
        return 1

    n_events = len(doc["traceEvents"])
    print(
        f"trace-smoke OK: {n_events} events, phases "
        + " ".join(
            f"{k.split('.')[-1]}={v * 1000:.1f}ms"
            for k, v in sorted(totals.items())
            if k.startswith("solve.")
        )
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
