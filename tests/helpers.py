"""Object builders for tests (role of the reference's pkg/test fixtures)."""

from __future__ import annotations

import itertools
from typing import Dict, List, Optional, Sequence, Tuple

from karpenter_tpu.api import labels as labels_mod
from karpenter_tpu.api import resources as res
from karpenter_tpu.api.objects import (
    DaemonSet,
    LabelSelector,
    Node,
    NodeAffinity,
    NodePool,
    NodePoolSpec,
    NodeClaimTemplate,
    NodeClaimSpec,
    NodeSelectorRequirement,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodAffinityTerm,
    PodSpec,
    PreferredSchedulingTerm,
    Taint,
    Toleration,
    TopologySpreadConstraint,
)

_seq = itertools.count(1)


def _as_host_ports(ports: Sequence) -> List:
    from karpenter_tpu.api.objects import HostPort

    return [p if isinstance(p, HostPort) else HostPort(port=int(p)) for p in ports]


def make_pod(
    name: Optional[str] = None,
    cpu: str = "1",
    memory: str = "1Gi",
    labels: Optional[Dict[str, str]] = None,
    node_selector: Optional[Dict[str, str]] = None,
    requirements: Sequence[NodeSelectorRequirement] = (),
    preferred: Sequence[PreferredSchedulingTerm] = (),
    tolerations: Sequence[Toleration] = (),
    spread: Sequence[TopologySpreadConstraint] = (),
    pod_affinity: Sequence[PodAffinityTerm] = (),
    pod_anti_affinity: Sequence[PodAffinityTerm] = (),
    extra_requests: Optional[Dict[str, str]] = None,
    node_name: str = "",
    phase: str = "Pending",
    host_ports: Sequence[int] = (),
    volumes: Sequence = (),
) -> Pod:
    i = next(_seq)
    requests = {"cpu": res.parse_quantity(cpu), "memory": res.parse_quantity(memory)}
    for k, v in (extra_requests or {}).items():
        requests[k] = res.parse_quantity(v)
    affinity = None
    if requirements or preferred:
        affinity = NodeAffinity(
            required=[tuple(requirements)] if requirements else [],
            preferred=list(preferred),
        )
    pod = Pod(
        metadata=ObjectMeta(name=name or f"pod-{i}", labels=dict(labels or {})),
        spec=PodSpec(
            node_selector=dict(node_selector or {}),
            node_affinity=affinity,
            tolerations=list(tolerations),
            requests=requests,
            topology_spread_constraints=list(spread),
            pod_affinity=list(pod_affinity),
            pod_anti_affinity=list(pod_anti_affinity),
            node_name=node_name,
            host_ports=_as_host_ports(host_ports),
            volumes=list(volumes),
        ),
    )
    pod.status.phase = phase
    return pod


def make_pods(count: int, **kwargs) -> List[Pod]:
    return [make_pod(**kwargs) for _ in range(count)]


def make_nodepool(
    name: str = "default",
    weight: int = 1,
    limits: Optional[Dict[str, str]] = None,
    taints: Sequence[Taint] = (),
    requirements: Sequence[NodeSelectorRequirement] = (),
    labels: Optional[Dict[str, str]] = None,
) -> NodePool:
    return NodePool(
        metadata=ObjectMeta(name=name),
        spec=NodePoolSpec(
            template=NodeClaimTemplate(
                labels=dict(labels or {}),
                spec=NodeClaimSpec(
                    requirements=list(requirements),
                    taints=list(taints),
                ),
            ),
            limits={k: res.parse_quantity(v) for k, v in (limits or {}).items()},
            weight=weight,
        ),
    )


def make_state_node(
    name: str = "node-1",
    cpu: str = "16",
    memory: str = "64Gi",
    zone: str = "test-zone-a",
    extra_labels: Optional[Dict[str, str]] = None,
):
    """A ready Node wrapped in a StateNode — the shared scaffold for tests
    that need existing cluster capacity."""
    from karpenter_tpu.controllers.state import StateNode

    node = Node(
        metadata=ObjectMeta(
            name=name,
            labels={
                labels_mod.TOPOLOGY_ZONE: zone,
                labels_mod.HOSTNAME: name,
                **(extra_labels or {}),
            },
        ),
    )
    node.status.capacity = {
        "cpu": res.parse_quantity(cpu),
        "memory": res.parse_quantity(memory),
        "pods": res.parse_quantity("110"),
    }
    node.status.allocatable = dict(node.status.capacity)
    node.status.ready = True
    return StateNode(node=node)


def spread_constraint(
    topology_key: str,
    max_skew: int = 1,
    labels: Optional[Dict[str, str]] = None,
    when_unsatisfiable: str = "DoNotSchedule",
    min_domains: Optional[int] = None,
) -> TopologySpreadConstraint:
    return TopologySpreadConstraint(
        max_skew=max_skew,
        topology_key=topology_key,
        when_unsatisfiable=when_unsatisfiable,
        label_selector=LabelSelector(match_labels=dict(labels or {})),
        min_domains=min_domains,
    )


def affinity_term(topology_key: str, labels: Dict[str, str]) -> PodAffinityTerm:
    return PodAffinityTerm(
        topology_key=topology_key,
        label_selector=LabelSelector(match_labels=dict(labels)),
    )


def snapshot_args(
    pods,
    node_pools=None,
    n_types: int = 20,
    state_nodes=(),
    require_full_routing: bool = True,
):
    """Kernel solve_args + statics for a pod batch — the one shared
    scaffold for tests that drive solve_core/solve_all directly."""
    from karpenter_tpu.cloudprovider import corpus as _corpus
    from karpenter_tpu.kube import Client, TestClock
    from karpenter_tpu.scheduling.topology import Topology
    from karpenter_tpu.solver import TpuSolver
    from karpenter_tpu.solver import encode as enc

    node_pools = node_pools or [make_nodepool()]
    its_by_pool = {np_.name: _corpus.generate(n_types) for np_ in node_pools}
    topo = Topology(
        Client(TestClock()), list(state_nodes), node_pools, its_by_pool, pods
    )
    solver = TpuSolver(
        node_pools, its_by_pool, topo, state_nodes=list(state_nodes)
    )
    groups, rest = enc.partition_and_group(pods, topology=topo)
    if require_full_routing:
        assert not rest, "batch must tensorize fully"
    templates = solver.oracle.templates
    snap = enc.encode(
        groups,
        templates,
        {t.node_pool_name: t.instance_type_options for t in templates},
        existing_nodes=solver.oracle.existing_nodes,
        daemon_overhead=solver.oracle.daemon_overhead,
        pool_limits=solver.pool_limits,
    )
    a_tzc, res_cap0, a_res = solver._offering_availability(snap)
    nmax = solver._estimate_nmax(snap, solver._fit_matrix(snap))
    statics = dict(
        nmax=nmax,
        zone_kid=snap.zone_kid,
        ct_kid=snap.ct_kid,
        has_domains=bool((snap.g_dmode > 0).any()),
        has_contrib=bool(snap.g_hcontrib.any() or snap.g_dcontrib.any()),
    )
    return snap.solve_args(a_tzc, res_cap0, a_res), statics


def decision_signature(results):
    """Canonical, order-independent serialization of one solve's decisions
    (the byte-identity witness shared by the concurrency storm and the
    multi-tenant isolation suite)."""
    return (
        sorted(
            (
                c.template.node_pool_name,
                tuple(sorted(p.uid for p in c.pods)),
                tuple(sorted(it.name for it in c.instance_type_options)),
                repr(sorted(map(repr, c.requirements))),
            )
            for c in results.new_node_claims
        ),
        sorted(
            (en.name, tuple(sorted(p.uid for p in en.pods)))
            for en in results.existing_nodes
        ),
        sorted(results.pod_errors),
    )
