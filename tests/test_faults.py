"""The robustness tier: fault injector, backoff, circuit breaker /
degradation ladder, post-solve invariant guard, and their solver/provider
integrations.

The zero-overhead contract is pinned here: with no injector installed
(and with an installed-but-empty one) the solver's decisions are
identical to an uninstrumented run — the fault seams may not perturb the
hot path.
"""

import copy

import numpy as np
import pytest

from karpenter_tpu import faults
from karpenter_tpu.api.objects import NodeClaim, ObjectMeta
from karpenter_tpu.cloudprovider import corpus
from karpenter_tpu.cloudprovider.fake import FakeCloudProvider
from karpenter_tpu.cloudprovider.icecache import InsufficientCapacityCache
from karpenter_tpu.cloudprovider.kwok import KwokCloudProvider
from karpenter_tpu.cloudprovider.types import (
    CloudProviderError,
    InsufficientCapacityError,
    NodeClaimNotFoundError,
)
from karpenter_tpu.faults.backoff import Backoff, RetryTracker
from karpenter_tpu.faults.breaker import (
    CircuitBreaker, DegradationLadder, SolverHealth,
)
from karpenter_tpu.faults.guard import SolverIntegrityError, check_solution
from karpenter_tpu.kube import Client, TestClock
from karpenter_tpu.kube.store import ConflictError
from karpenter_tpu.scheduling.topology import Topology
from karpenter_tpu.solver import TpuSolver
from karpenter_tpu.solver.driver import SolverConfig

from helpers import make_nodepool, make_pod, make_pods


@pytest.fixture(autouse=True)
def _no_leaked_injector():
    yield
    faults.uninstall()


def build_solver(pods, config=None, n_types=10):
    node_pools = [make_nodepool()]
    its_by_pool = {np_.name: corpus.generate(n_types) for np_ in node_pools}
    topo = Topology(Client(TestClock()), [], node_pools, its_by_pool, pods)
    return TpuSolver(node_pools, its_by_pool, topo, config=config)


def results_signature(results):
    """Decision-level fingerprint: claim pools, option names, pod uids,
    and errors — what the controller would commit."""
    claims = sorted(
        (
            c.template.node_pool_name,
            tuple(sorted(p.uid for p in c.pods)),
            tuple(it.name for it in c.instance_type_options),
        )
        for c in results.new_node_claims
    )
    return claims, dict(results.pod_errors)


class TestFaultInjector:
    def test_deterministic_replay(self):
        def run(seed):
            inj = faults.FaultInjector(
                [faults.FaultRule("x", probability=0.5)], seed=seed
            )
            log = []
            for i in range(50):
                try:
                    inj.hit("x")
                except faults.InjectedFault:
                    log.append(i)
            return log, list(inj.log)

        a = run(7)
        b = run(7)
        c = run(8)
        assert a == b
        assert a[0] and a != c  # fires, and the seed matters

    def test_after_times_and_match(self):
        inj = faults.FaultInjector(
            [
                faults.FaultRule(
                    "s", after=2, times=1,
                    match=lambda ctx: ctx.get("kind") == "Node",
                )
            ]
        )
        inj.hit("s", kind="Node")          # call 1: skipped (after)
        inj.hit("s", kind="Node")          # call 2: skipped (after)
        inj.hit("s", kind="Pod")           # call 3: no match
        with pytest.raises(faults.InjectedFault):
            inj.hit("s", kind="Node")      # call 4: fires
        inj.hit("s", kind="Node")          # call 5: times exhausted
        assert inj.fired("s") == 1

    def test_until_clears_on_the_injected_clock(self):
        clock = TestClock()
        inj = faults.FaultInjector(
            [faults.FaultRule("s", until=clock.now() + 10.0)], clock=clock
        )
        with pytest.raises(faults.InjectedFault):
            inj.hit("s")
        clock.step(11.0)
        inj.hit("s")  # faults cleared by time passing
        assert inj.fired("s") == 1

    def test_typed_error_factory_and_mutation(self):
        inj = faults.FaultInjector(
            [
                faults.FaultRule(
                    "e", error=lambda: ConflictError("injected")
                ),
                faults.FaultRule("m", mutate=lambda v: v + 1),
            ]
        )
        with pytest.raises(ConflictError):
            inj.hit("e")
        assert inj.mutate("m", 41) == 42

    def test_clear_makes_injector_inert(self):
        inj = faults.FaultInjector([faults.FaultRule("s")])
        with pytest.raises(faults.InjectedFault):
            inj.hit("s")
        inj.clear()
        inj.hit("s")
        assert inj.mutate("m", 1) == 1

    def test_latency_rule_advances_injected_clock(self):
        clock = TestClock()
        inj = faults.FaultInjector(
            [faults.FaultRule("s", latency=3.0)], clock=clock
        )
        t0 = clock.now()
        inj.hit("s")  # latency-only: sleeps, does not raise
        assert clock.now() == t0 + 3.0


class TestBackoff:
    def test_delays_grow_and_cap(self):
        b = Backoff(TestClock(), initial=1.0, factor=2.0, max_delay=5.0,
                    jitter=0.0)
        assert [b.delay(i) for i in range(4)] == [1.0, 2.0, 4.0, 5.0]

    def test_jitter_deterministic_per_seed(self):
        mk = lambda: Backoff(TestClock(), jitter=0.5, seed=3)
        assert [mk().delay(i) for i in range(3)] == [
            mk().delay(i) for i in range(3)
        ]

    def test_call_retries_on_injected_clock_then_raises(self):
        clock = TestClock()
        b = Backoff(clock, initial=1.0, jitter=0.0, max_attempts=3)
        attempts = []

        def flaky():
            attempts.append(clock.now())
            raise ConflictError("still conflicting")

        t0 = clock.now()
        with pytest.raises(ConflictError):
            b.call(flaky, retriable=(ConflictError,))
        assert len(attempts) == 3
        assert clock.now() == t0 + 1.0 + 2.0  # slept BETWEEN attempts only

    def test_call_recovers(self):
        clock = TestClock()
        b = Backoff(clock, max_attempts=3, jitter=0.0)
        state = {"n": 0}

        def flaky():
            state["n"] += 1
            if state["n"] < 3:
                raise ConflictError("conflict")
            return "ok"

        assert b.call(flaky, retriable=(ConflictError,)) == "ok"

    def test_tracker_gates_and_clears(self):
        clock = TestClock()
        t = RetryTracker(clock, initial=4.0, jitter=0.0)
        assert t.ready("k")
        d = t.failure("k")
        assert d == 4.0 and not t.ready("k")
        clock.step(4.0)
        assert t.ready("k")
        t.failure("k")  # second failure: 8s
        clock.step(4.0)
        assert not t.ready("k")
        t.success("k")
        assert t.ready("k") and t.attempts("k") == 0

    def test_tracker_prune(self):
        t = RetryTracker(TestClock())
        t.failure("gone")
        t.failure("kept")
        t.prune(["kept"])
        assert t.ready("gone") and not t.ready("kept")


class TestBreakerAndLadder:
    def test_breaker_trips_cools_reprobes(self):
        clock = TestClock()
        b = CircuitBreaker(clock, failure_threshold=2, cooldown=30.0)
        assert b.allow()
        b.record_failure()
        assert b.allow()
        b.record_failure()  # trip
        assert not b.allow()
        clock.step(30.0)
        assert b.allow()  # half-open probe
        b.record_failure()  # re-trip immediately
        assert not b.allow()
        clock.step(30.0)
        assert b.allow()
        b.record_success()
        assert b.allow() and b.state == "closed"

    def test_ladder_degrades_and_recovers(self):
        clock = TestClock()
        ladder = DegradationLadder(
            clock, ("batched", "kernel", "oracle"),
            failure_threshold=1, cooldown=60.0,
        )
        assert ladder.current() == "batched"
        ladder.record("batched", ok=False)
        assert ladder.current() == "kernel"
        ladder.record("kernel", ok=False)
        assert ladder.current() == "oracle"  # last rung unconditional
        clock.step(60.0)
        assert ladder.current() == "batched"  # cool-down re-probe upward

    def test_solver_health_quarantine_and_events(self):
        from karpenter_tpu.events import Recorder

        clock = TestClock()
        recorder = Recorder(clock)
        h = SolverHealth(clock, recorder=recorder, cooldown=60.0)
        assert h.allow_kernel() and h.allow_batched()
        h.quarantine("kernel", "conservation violated")
        assert not h.allow_kernel()
        assert not h.allow_batched()  # batched rides the same kernels
        assert recorder.for_reason("SolverQuarantined")
        clock.step(60.0)
        assert h.allow_kernel()  # half-open re-probe
        h.record_kernel(True)
        assert recorder.for_reason("SolverRestored")


class TestInvariantGuard:
    def _clean(self):
        """A tiny hand-built solution: 1 group of 3 pods, 1 claim taking
        2, 1 existing node taking 1."""
        return dict(
            g_count=np.array([3]),
            g_req=np.array([[1.0, 2.0]]),
            c_pool=np.array([0, 0]),
            c_tmask=np.array([[True, True], [False, False]]),
            n_open=1,
            exist_fills=np.array([[1]]),
            claim_fills=np.array([[2, 0]]),
            unplaced=np.array([0]),
            t_alloc=np.array([[4.0, 8.0], [2.0, 4.0]]),
            n_avail=np.array([[2.0, 4.0]]),
            nmax=2,
            P=1,
        )

    def test_clean_solution_passes(self):
        assert check_solution(**self._clean()) == []

    def test_conservation_violation(self):
        bad = self._clean()
        bad["unplaced"] = np.array([5])
        assert any("conservation" in v for v in check_solution(**bad))

    def test_negative_fills(self):
        bad = self._clean()
        bad["claim_fills"] = np.array([[-2, 0]])
        assert any("negative" in v for v in check_solution(**bad))

    def test_nan_fills(self):
        bad = self._clean()
        bad["exist_fills"] = np.array([[np.nan]])
        assert any("non-finite" in v for v in check_solution(**bad))

    def test_capacity_violation_on_claim(self):
        bad = self._clean()
        bad["claim_fills"] = np.array([[9, 0]])  # 9 pods > any type fits
        bad["g_count"] = np.array([10])
        assert any("instance type" in v for v in check_solution(**bad))

    def test_existing_node_overfill(self):
        bad = self._clean()
        bad["exist_fills"] = np.array([[3]])  # 3*1cpu > 2 available
        bad["g_count"] = np.array([5])
        assert any("existing node" in v for v in check_solution(**bad))

    def test_n_open_out_of_bounds(self):
        bad = self._clean()
        bad["n_open"] = 99
        assert any("n_open" in v for v in check_solution(**bad))

    def test_domain_pin_out_of_range(self):
        bad = self._clean()
        bad.update(
            c_dzone=np.array([99, -1]), c_dct=np.array([-1, -1]),
            zone_vals=3, ct_vals=2,
        )
        assert any("c_dzone" in v for v in check_solution(**bad))
        ok = self._clean()
        ok.update(
            c_dzone=np.array([2, -1]), c_dct=np.array([-1, -1]),
            zone_vals=3, ct_vals=2,
        )
        assert check_solution(**ok) == []

    def test_pool_limit_violation(self):
        bad = self._clean()
        bad.update(
            templates_pool=["default"],
            p_limit=np.array([[1.0, 100.0]]),
            p_has_limit=np.array([[True, False]]),
        )
        # the claim's 2 pods want 2 cpu > pool limit 1
        assert any("limits" in v for v in check_solution(**bad))


class TestSolverIntegration:
    def test_zero_overhead_when_off_byte_identical(self):
        """No injector vs installed-but-empty injector vs plain run: the
        committed decisions are identical (the acceptance pin)."""
        pods_a = make_pods(40, cpu="1", memory="2Gi")
        baseline = results_signature(
            build_solver(copy.deepcopy(pods_a)).solve(copy.deepcopy(pods_a))
        )
        faults.install(faults.FaultInjector([], seed=0))
        with_empty = results_signature(
            build_solver(copy.deepcopy(pods_a)).solve(copy.deepcopy(pods_a))
        )
        faults.uninstall()
        again = results_signature(
            build_solver(copy.deepcopy(pods_a)).solve(copy.deepcopy(pods_a))
        )
        assert baseline == with_empty == again

    def test_dispatch_fault_degrades_to_oracle(self):
        pods = make_pods(12, cpu="1", memory="1Gi")
        clock = TestClock()
        health = SolverHealth(clock, failure_threshold=1, cooldown=60.0)
        faults.install(
            faults.FaultInjector([faults.FaultRule(faults.SOLVER_DISPATCH)])
        )
        try:
            solver = build_solver(
                copy.deepcopy(pods), config=SolverConfig(health=health)
            )
            results = solver.solve(copy.deepcopy(pods))
        finally:
            faults.uninstall()
        # every pod still placed — by the oracle rung
        assert not results.pod_errors
        assert results.new_node_claims
        assert not health.allow_kernel()  # breaker tripped (threshold 1)
        # same decisions as an explicit force_oracle run
        oracle = results_signature(
            build_solver(
                copy.deepcopy(pods), config=SolverConfig(force_oracle=True)
            ).solve(copy.deepcopy(pods))
        )
        assert results_signature(results) == oracle

    def test_dispatch_fault_propagates_without_health(self):
        pods = make_pods(4)
        faults.install(
            faults.FaultInjector([faults.FaultRule(faults.SOLVER_DISPATCH)])
        )
        try:
            with pytest.raises(faults.InjectedFault):
                build_solver(pods).solve(pods)
        finally:
            faults.uninstall()

    def test_corrupt_output_quarantined_never_committed(self):
        """A kernel emitting garbage fills is caught by the guard BEFORE
        decode; with a ladder the batch re-solves on the oracle, without
        one the integrity error surfaces."""

        def corrupt(outs):
            outs = list(outs)
            outs[5] = np.asarray(outs[5]) - 7  # claim_fills negative
            return tuple(outs)

        pods = make_pods(10, cpu="1", memory="1Gi")
        rule = faults.FaultRule(faults.SOLVER_OUTPUT, mutate=corrupt)
        faults.install(faults.FaultInjector([rule]))
        try:
            # relax=False pins the EXACT route: these identical plain pods
            # would otherwise ride the relaxation bulk, leaving the exact
            # dispatch empty (its corrupted rows are dead padding — the
            # relax-route corruption twin lives in tests/test_relax.py)
            with pytest.raises(SolverIntegrityError):
                build_solver(
                    copy.deepcopy(pods), config=SolverConfig(relax=False)
                ).solve(copy.deepcopy(pods))
        finally:
            faults.uninstall()

        clock = TestClock()
        health = SolverHealth(clock, cooldown=60.0)
        faults.install(
            faults.FaultInjector(
                [faults.FaultRule(faults.SOLVER_OUTPUT, mutate=corrupt)]
            )
        )
        try:
            results = build_solver(
                copy.deepcopy(pods),
                config=SolverConfig(health=health, relax=False),
            ).solve(copy.deepcopy(pods))
        finally:
            faults.uninstall()
        assert not results.pod_errors  # oracle placed everything
        assert health.quarantines == 1
        assert not health.allow_kernel()

    def test_corrupt_domain_pins_quarantined_pre_decode(self):
        """The decode-crash vector: garbage c_dzone ids would raise
        IndexError mid-commit; the guard must reject them pre-decode."""

        def corrupt_pins(outs):
            outs = list(outs)
            outs[7] = np.asarray(outs[7]) + 500  # c_dzone → out of vocab
            return tuple(outs)

        pods = make_pods(6, cpu="1", memory="1Gi")
        faults.install(
            faults.FaultInjector(
                [faults.FaultRule(faults.SOLVER_OUTPUT, mutate=corrupt_pins)]
            )
        )
        try:
            # relax=False: pin the exact route (see the corrupt-output
            # test above; relax-route coverage in tests/test_relax.py)
            with pytest.raises(SolverIntegrityError):
                build_solver(
                    copy.deepcopy(pods), config=SolverConfig(relax=False)
                ).solve(copy.deepcopy(pods))
        finally:
            faults.uninstall()

    def test_scenario_fault_declines_batch(self):
        """An injected scenario-dispatch failure makes solve_scenarios
        return None (the documented per-probe fallback), recording the
        batched rung failure."""
        from karpenter_tpu.solver.driver import Scenario

        pods = make_pods(8, cpu="1", memory="1Gi")
        clock = TestClock()
        health = SolverHealth(clock, failure_threshold=1, cooldown=60.0)
        faults.install(
            faults.FaultInjector(
                [faults.FaultRule(faults.SOLVER_SCENARIOS)]
            )
        )
        try:
            solver = build_solver(
                copy.deepcopy(pods), config=SolverConfig(health=health)
            )
            out = solver.solve_scenarios([Scenario(pods=pods)])
        finally:
            faults.uninstall()
        assert out is None
        assert not health.allow_batched()
        # the per-probe kernel rung is NOT taken down by a batched failure
        assert health.allow_kernel()


class TestProviderFaults:
    def _pool_and_claim(self, client):
        client.create(make_nodepool())
        claim = NodeClaim(metadata=ObjectMeta(name="c1"))
        return claim

    def test_kwok_ice_marks_cache_and_masks_offerings(self):
        client = Client(TestClock())
        provider = KwokCloudProvider(client, corpus.generate(4))
        claim = self._pool_and_claim(client)
        ctx = {}

        def remember(c):
            ctx.update(c)
            return True

        faults.install(
            faults.FaultInjector(
                [
                    faults.FaultRule(
                        faults.PROVIDER_CREATE,
                        error=lambda: InsufficientCapacityError("injected"),
                        times=1,
                        match=remember,
                    )
                ]
            )
        )
        try:
            with pytest.raises(InsufficientCapacityError):
                provider.create(claim)
        finally:
            faults.uninstall()
        assert len(provider.ice_cache) == 1
        assert provider.ice_cache.is_unavailable(
            ctx["instance_type"], ctx["zone"], ctx["capacity_type"]
        )
        # the failed offering reads unavailable through the catalog
        masked = {
            (it.name, o.zone(), o.capacity_type())
            for it in provider.get_instance_types(None)
            for o in it.offerings
            if not o.available
        }
        assert (
            ctx["instance_type"], ctx["zone"], ctx["capacity_type"]
        ) in masked
        # retry routes around the cached cell (different offering/type)
        claim2 = NodeClaim(metadata=ObjectMeta(name="c2"))
        provider.create(claim2)
        from karpenter_tpu.api import labels as labels_mod

        got = (
            claim2.metadata.labels[labels_mod.INSTANCE_TYPE],
            claim2.metadata.labels[labels_mod.TOPOLOGY_ZONE],
            claim2.metadata.labels[labels_mod.CAPACITY_TYPE_LABEL_KEY],
        )
        assert got != (
            ctx["instance_type"], ctx["zone"], ctx["capacity_type"]
        )
        # TTL expiry restores the cell
        client.clock.step(1000.0)
        assert len(provider.ice_cache) == 0
        assert all(
            o.available or True
            for it in provider.get_instance_types(None)
            for o in it.offerings
        )

    def test_ice_cache_ttl_clock_driven(self):
        clock = TestClock()
        ice = InsufficientCapacityCache(clock, ttl=30.0)
        ice.mark_unavailable("t", "z", "spot")
        assert ice.is_unavailable("t", "z", "spot") and ice.active()
        clock.step(29.0)
        assert ice.is_unavailable("t", "z", "spot")
        clock.step(1.0)
        assert not ice.is_unavailable("t", "z", "spot")
        assert not ice.active()

    def test_fake_provider_ice_cache(self):
        clock = TestClock()
        provider = FakeCloudProvider(corpus.generate(3), clock=clock)
        it = provider.get_instance_types(None)[0]
        o = next(o for o in it.offerings if o.available)
        provider.mark_insufficient_capacity(
            it.name, o.zone(), o.capacity_type()
        )
        masked = next(
            t for t in provider.get_instance_types(None) if t.name == it.name
        )
        assert any(
            not m.available
            for m in masked.offerings
            if m.zone() == o.zone() and m.capacity_type() == o.capacity_type()
        )
        clock.step(1000.0)
        fresh = next(
            t for t in provider.get_instance_types(None) if t.name == it.name
        )
        assert all(
            m.available
            for m in fresh.offerings
            if m.zone() == o.zone() and m.capacity_type() == o.capacity_type()
        )

    def test_kwok_registration_fault_defers(self):
        client = Client(TestClock())
        provider = KwokCloudProvider(client, corpus.generate(4))
        claim = self._pool_and_claim(client)
        provider.create(claim)
        faults.install(
            faults.FaultInjector(
                [faults.FaultRule(faults.PROVIDER_REGISTER, times=2)]
            )
        )
        try:
            assert provider.process_registrations() == []
            client.clock.step(2.0)
            assert provider.process_registrations() == []
            client.clock.step(2.0)
            created = provider.process_registrations()
        finally:
            faults.uninstall()
        assert [n.name for n in created] == ["c1"]
