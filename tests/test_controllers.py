"""Control-plane behavior tests: provisioning, lifecycle, termination,
disruption conditions, expiration, GC, housekeeping, and the full
pending-pod -> running-node -> consolidation loop through the Operator.
"""

import pytest

from karpenter_tpu.api import labels, resources as res
from karpenter_tpu.api.objects import (
    Budget,
    COND_CONSOLIDATABLE,
    COND_DRIFTED,
    COND_INITIALIZED,
    COND_LAUNCHED,
    COND_REGISTERED,
    Node,
    NodeClaim,
    NodePool,
    Pod,
)
from karpenter_tpu.cloudprovider import corpus
from karpenter_tpu.cloudprovider.kwok import KwokCloudProvider
from karpenter_tpu.kube import Client, TestClock
from karpenter_tpu.operator import Operator, OperatorOptions
from karpenter_tpu.sim import Binder

from helpers import make_nodepool, make_pod, make_pods


@pytest.fixture
def env():
    clock = TestClock()
    client = Client(clock)
    provider = KwokCloudProvider(client, corpus.generate(20))
    operator = Operator(client, provider)
    binder = Binder(client)
    return clock, client, provider, operator, binder


def provision_cycle(env, n_steps=6):
    clock, client, provider, operator, binder = env
    for _ in range(n_steps):
        operator.step(force_provision=True)
        binder.bind_all()
        clock.step(1)


class TestProvisioningCycle:
    def test_pending_pod_to_running_node(self, env):
        clock, client, provider, operator, binder = env
        client.create(make_nodepool())
        pods = make_pods(5, cpu="1", memory="2Gi")
        for p in pods:
            client.create(p)
        provision_cycle(env)
        claims = client.list(NodeClaim)
        assert len(claims) == 1
        claim = claims[0]
        assert claim.conds().is_true(COND_LAUNCHED)
        assert claim.conds().is_true(COND_REGISTERED)
        assert claim.conds().is_true(COND_INITIALIZED)
        nodes = client.list(Node)
        assert len(nodes) == 1
        for p in pods:
            assert p.spec.node_name == nodes[0].name

    def test_batcher_debounce(self, env):
        clock, client, provider, operator, binder = env
        client.create(make_nodepool())
        client.create(make_pod())
        # within idle window: not ready
        assert operator.provisioner.reconcile() is None
        clock.step(1.1)  # idle window elapsed
        results = operator.provisioner.reconcile()
        assert results is not None and results.node_count() == 1

    def test_no_pods_no_claims(self, env):
        clock, client, provider, operator, binder = env
        client.create(make_nodepool())
        provision_cycle(env)
        assert client.list(NodeClaim) == []

    def test_unschedulable_pod_reported(self, env):
        clock, client, provider, operator, binder = env
        client.create(make_nodepool())
        client.create(make_pod(cpu="9999"))
        clock.step(1.1)
        results = operator.provisioner.reconcile()
        assert results is not None and len(results.pod_errors) == 1
        assert client.list(NodeClaim) == []


class TestLifecycle:
    def test_insufficient_capacity_deletes_claim(self, env):
        clock, client, provider, operator, binder = env
        from karpenter_tpu.api.objects import NodeClaimSpec, NodeSelectorRequirement, ObjectMeta

        claim = NodeClaim(
            metadata=ObjectMeta(name="bad", labels={labels.NODEPOOL_LABEL_KEY: "default"}),
            spec=NodeClaimSpec(
                requirements=[NodeSelectorRequirement(labels.TOPOLOGY_ZONE, "In", ("mars",))]
            ),
        )
        claim.metadata.finalizers.append(labels.TERMINATION_FINALIZER)
        client.create(claim)
        operator.lifecycle.reconcile_all()
        assert client.try_get(NodeClaim, "bad") is None

    def test_liveness_deletes_unregistered(self, env):
        clock, client, provider, operator, binder = env
        client.create(make_nodepool())
        client.create(make_pod())
        clock.step(1.1)
        operator.provisioner.reconcile()
        # block registration by never processing provider registrations
        provider._registration_delay = 10**9
        provider._pending = [(clock.now() + 10**9, i) for _, i in provider._pending]
        operator.lifecycle.reconcile_all()  # launch
        clock.step(16 * 60)
        operator.lifecycle.reconcile_all()  # liveness fires
        assert client.list(NodeClaim) == []


class TestTermination:
    def test_node_delete_drains_and_removes(self, env):
        clock, client, provider, operator, binder = env
        client.create(make_nodepool())
        for p in make_pods(3):
            client.create(p)
        provision_cycle(env)
        node = client.list(Node)[0]
        node.metadata.finalizers.append(labels.TERMINATION_FINALIZER)
        client.delete(node)
        for _ in range(5):
            operator.step()
            clock.step(1)
        assert client.list(Node) == []
        assert client.list(NodeClaim) == []
        # pods evicted
        assert all(not p.spec.node_name or p.metadata.deletion_timestamp
                   for p in client.list(Pod))


class TestConditions:
    def test_consolidatable_after_quiet_period(self, env):
        clock, client, provider, operator, binder = env
        pool = make_nodepool()
        pool.spec.disruption.consolidate_after = 30.0
        client.create(pool)
        client.create(make_pod())
        provision_cycle(env)
        claim = client.list(NodeClaim)[0]
        assert not claim.conds().is_true(COND_CONSOLIDATABLE)
        clock.step(31)
        operator.nodeclaim_disruption.reconcile_all()
        assert claim.conds().is_true(COND_CONSOLIDATABLE)

    def test_drift_on_nodepool_change(self, env):
        clock, client, provider, operator, binder = env
        pool = make_nodepool()
        client.create(pool)
        client.create(make_pod())
        provision_cycle(env)
        claim = client.list(NodeClaim)[0]
        # stamp the current hash, then change the pool template
        from karpenter_tpu.controllers.nodeclaim_disruption import nodepool_hash

        claim.metadata.annotations[labels.NODEPOOL_HASH_ANNOTATION_KEY] = nodepool_hash(pool)
        operator.nodeclaim_disruption.reconcile_all()
        assert not claim.conds().is_true(COND_DRIFTED)
        pool.spec.template.labels["team"] = "new"
        client.update(pool)
        operator.nodeclaim_disruption.reconcile_all()
        assert claim.conds().is_true(COND_DRIFTED)


class TestExpiration:
    def test_claims_expire(self, env):
        clock, client, provider, operator, binder = env
        pool = make_nodepool()
        pool.spec.template.spec.expire_after = 3600.0
        client.create(pool)
        client.create(make_pod())
        provision_cycle(env)
        assert len(client.list(NodeClaim)) == 1
        clock.step(3601)
        operator.expiration.reconcile_all()
        claim = client.list(NodeClaim)[0]
        assert claim.metadata.deletion_timestamp is not None


class TestGarbageCollection:
    def test_leaked_instance_collected(self, env):
        clock, client, provider, operator, binder = env
        from karpenter_tpu.api.objects import ObjectMeta, NodeClaimSpec

        leaked = NodeClaim(metadata=ObjectMeta(name="leak"), spec=NodeClaimSpec())
        provider.create(leaked)  # instance exists, no NodeClaim CR
        assert len(provider.list()) == 1
        operator.garbage_collection.reconcile()
        assert provider.list() == []


class TestEmptinessConsolidation:
    def test_empty_node_deleted(self, env):
        clock, client, provider, operator, binder = env
        pool = make_nodepool()
        pool.spec.disruption.consolidate_after = 30.0
        client.create(pool)
        pod = make_pod()
        client.create(pod)
        provision_cycle(env)
        assert len(client.list(Node)) == 1
        # pod goes away; node becomes empty and consolidatable
        pod.status.phase = "Succeeded"
        client.update(pod)
        clock.step(31)
        operator.nodeclaim_disruption.reconcile_all()
        cmd = operator.disruption.reconcile(force=True)
        assert cmd is not None and cmd.decision == "delete"
        assert cmd.reason == "Empty"
        # the command executes after the 15s validation TTL; the queue then
        # completes the deletion (no replacements to wait for)
        for _ in range(30):
            operator.step()
            clock.step(1)
        assert client.list(Node) == []


class TestBudgets:
    def test_zero_budget_blocks_disruption(self, env):
        clock, client, provider, operator, binder = env
        pool = make_nodepool()
        pool.spec.disruption.consolidate_after = 30.0
        pool.spec.disruption.budgets = [Budget(nodes="0")]
        client.create(pool)
        pod = make_pod()
        client.create(pod)
        provision_cycle(env)
        pod.status.phase = "Succeeded"
        client.update(pod)
        clock.step(31)
        operator.nodeclaim_disruption.reconcile_all()
        cmd = operator.disruption.reconcile(force=True)
        assert cmd is None or cmd.decision == "no-op"
        assert len(client.list(Node)) == 1


class TestMultiNodeConsolidation:
    def test_spot_consolidation_gated_off_by_default(self, env):
        # both nodes are spot; with the SpotToSpotConsolidation gate off the
        # reference refuses to consolidate (consolidation.go:232-238)
        clock, client, provider, operator, binder = env
        pool = make_nodepool()
        pool.spec.disruption.consolidate_after = 10.0
        client.create(pool)
        for _ in range(2):
            client.create(make_pod(cpu="1", memory="1Gi"))
            provision_cycle(env)
        assert len(client.list(Node)) == 2
        clock.step(11)
        operator.nodeclaim_disruption.reconcile_all()
        cmd = operator.disruption.reconcile(force=True)
        assert cmd is None or cmd.decision == "no-op"

    def test_underutilized_nodes_consolidate_after_pods_complete(self, env):
        # Two nodes sized for 2x750m pods each; one pod per node completes,
        # leaving each node underutilized. Multi-node consolidation packs the
        # two leftovers onto one cheaper replacement.
        clock, client, provider, operator, binder = env
        operator.disruption.ctx.spot_to_spot_enabled = True
        pool = make_nodepool()
        pool.spec.disruption.consolidate_after = 10.0
        client.create(pool)
        rounds = []
        for _ in range(2):
            batch = [make_pod(cpu="750m", memory="1Gi") for _ in range(2)]
            for p in batch:
                client.create(p)
            provision_cycle(env)
            rounds.append(batch)
        assert len(client.list(Node)) == 2
        # one pod per node completes
        for batch in rounds:
            batch[0].status.phase = "Succeeded"
            client.update(batch[0])
        # past consolidate_after AND the 20s pod-nomination window
        clock.step(25)
        operator.nodeclaim_disruption.reconcile_all()
        cmd = operator.disruption.reconcile(force=True)
        # either outcome shrinks the cluster: delete a node whose leftover pod
        # fits on the other's free capacity, or replace both with one cheaper
        assert cmd is not None and cmd.decision in ("delete", "replace")
        if cmd.decision == "replace":
            from karpenter_tpu.cloudprovider import types as cp

            rep = cmd.replacements[0]
            rep_price = min(
                cp.min_compatible_price(it, rep.requirements)
                for it in rep.instance_type_options
            )
            assert rep_price < sum(c.price for c in cmd.candidates)

    def test_consolidation_completes_via_queue(self, env):
        clock, client, provider, operator, binder = env
        operator.disruption.ctx.spot_to_spot_enabled = True
        pool = make_nodepool()
        pool.spec.disruption.consolidate_after = 10.0
        client.create(pool)
        rounds = []
        for _ in range(2):
            batch = [make_pod(cpu="750m", memory="1Gi") for _ in range(2)]
            for p in batch:
                client.create(p)
            provision_cycle(env)
            rounds.append(batch)
        for batch in rounds:
            batch[0].status.phase = "Succeeded"
            client.update(batch[0])
        clock.step(25)
        operator.nodeclaim_disruption.reconcile_all()
        cmd = operator.disruption.reconcile(force=True)
        assert cmd is not None and cmd.decision in ("delete", "replace")
        # run the world until the command survives its validation TTL,
        # executes, and the candidates die
        for _ in range(20):
            operator.step()
            binder.bind_all()
            clock.step(2)
        nodes = client.list(Node)
        assert len(nodes) == 1
        # surviving (non-terminal) pods landed on the replacement
        for p in client.list(Pod):
            if p.status.phase in ("Succeeded", "Failed"):
                continue
            if p.spec.node_name:
                assert p.spec.node_name == nodes[0].name


class TestClaimCRHygiene:
    def test_no_hostname_requirement_in_created_claims(self, env):
        # reference FinalizeScheduling strips the scheduling hostname
        # placeholder before launch (nodeclaim.go:242-258)
        clock, client, provider, operator, binder = env
        client.create(make_nodepool())
        client.create(make_pod())
        provision_cycle(env)
        claim = client.list(NodeClaim)[0]
        assert all(r.key != labels.HOSTNAME for r in claim.spec.requirements)


class TestDisruptionEncodeCache:
    def test_probes_reuse_static_encode(self, env):
        """Every scheduling simulation the disruption engine runs shares one
        catalog-fingerprinted EncodeCache: the second probe must find (and
        keep) the static arrays the first probe encoded, instead of paying
        the full vocab+table encode per binary-search step."""
        clock, client, provider, operator, binder = env
        pool = make_nodepool()
        pool.spec.disruption.consolidate_after = 10.0
        client.create(pool)
        for _ in range(2):
            client.create(make_pod(cpu="750m", memory="1Gi"))
            provision_cycle(env)
        clock.step(25)  # past the pod-nomination window
        operator.nodeclaim_disruption.reconcile_all()

        from karpenter_tpu.controllers.disruption.helpers import (
            get_candidates, simulate_scheduling,
        )

        ctx = operator.disruption.ctx
        assert ctx.encode_cache is not None
        cands = get_candidates(ctx.client, ctx.cluster, ctx.cloud_provider, clock)
        assert cands

        simulate_scheduling(
            ctx.client, ctx.cluster, ctx.cloud_provider, cands[:1],
            encode_cache=ctx.encode_cache,
        )
        cache1 = ctx.encode_cache.cache
        static_ids = {
            k: id(v)
            for k, v in cache1.items()
            if isinstance(k, tuple) and k and k[0] != "a_tzc"
        }
        assert static_ids, "first probe must populate the shared static cache"

        simulate_scheduling(
            ctx.client, ctx.cluster, ctx.cloud_provider, cands[:1],
            encode_cache=ctx.encode_cache,
        )
        # same catalog -> same cache dict, same static array objects
        assert ctx.encode_cache.cache is cache1
        for k, obj_id in static_ids.items():
            assert id(cache1[k]) == obj_id, f"static entry {k} was re-encoded"


def _consolidatable_two_node_env(env):
    """Two underutilized nodes ready for consolidation (shared setup)."""
    clock, client, provider, operator, binder = env
    operator.disruption.ctx.spot_to_spot_enabled = True
    pool = make_nodepool()
    pool.spec.disruption.consolidate_after = 10.0
    client.create(pool)
    rounds = []
    for _ in range(2):
        batch = [make_pod(cpu="750m", memory="1Gi") for _ in range(2)]
        for p in batch:
            client.create(p)
        provision_cycle(env)
        rounds.append(batch)
    for batch in rounds:
        batch[0].status.phase = "Succeeded"
        client.update(batch[0])
    clock.step(25)
    operator.nodeclaim_disruption.reconcile_all()
    return pool


class TestOrchestrationQueue:
    """Failure/un-taint/requeue behavior (orchestration/queue.go:51-189)."""

    def _queued_command(self, env):
        clock, client, provider, operator, binder = env
        _consolidatable_two_node_env(env)
        cmd = operator.disruption.reconcile(force=True)
        assert cmd is not None and cmd.decision in ("delete", "replace")
        # run past the validation TTL so the command executes + enqueues
        for _ in range(20):
            clock.step(1)
            operator.disruption.reconcile(force=True)
            if operator.disruption.queue.items:
                break
        return cmd

    def test_replacement_disappearance_untaints_and_releases(self, env):
        from karpenter_tpu.api.objects import Taint
        from karpenter_tpu.controllers.disruption.helpers import get_candidates
        from karpenter_tpu.controllers.disruption.types import Command

        clock, client, provider, operator, binder = env
        _consolidatable_two_node_env(env)
        ctx = operator.disruption.ctx
        cands = get_candidates(ctx.client, ctx.cluster, ctx.cloud_provider, clock)
        assert cands
        cand = cands[0]
        # execution state: candidate tainted + marked for deletion
        node = client.get(Node, cand.node.name)
        node.taints.append(
            Taint(key=labels.DISRUPTED_TAINT_KEY, effect="NoSchedule")
        )
        client.update(node)
        ctx.cluster.mark_for_deletion(cand.provider_id)
        queue = operator.disruption.queue
        # the replacement NodeClaim does not exist -> the queue must fail
        # the item, un-taint the candidate, and release the deletion mark
        queue.add(
            Command(candidates=[cand], reason="Underutilized"),
            ["replacement-that-never-was"],
        )
        queue.reconcile()
        assert not queue.items
        node = client.try_get(Node, cand.node.name)
        assert node is not None, "failed command must not delete candidates"
        assert not any(
            t.key == labels.DISRUPTED_TAINT_KEY for t in node.taints
        )
        sn = ctx.cluster.node_for_provider_id(cand.provider_id)
        assert sn is not None and not sn.mark_for_deletion

    def test_uninitialized_replacement_backs_off_then_times_out(self, env):
        from karpenter_tpu.controllers.disruption.controller import (
            QueueItem, QUEUE_TIMEOUT,
        )
        from karpenter_tpu.controllers.disruption.types import Command

        clock, client, provider, operator, binder = env
        _consolidatable_two_node_env(env)
        queue = operator.disruption.queue
        # fabricate an in-flight command whose replacement never initializes
        from karpenter_tpu.api.objects import NodeClaimSpec, ObjectMeta

        stuck = NodeClaim(
            metadata=ObjectMeta(name="stuck-replacement"), spec=NodeClaimSpec()
        )
        client.create(stuck)
        cands = []
        from karpenter_tpu.controllers.disruption.helpers import get_candidates

        ctx = operator.disruption.ctx
        cands = get_candidates(ctx.client, ctx.cluster, ctx.cloud_provider, clock)
        assert cands
        queue.add(
            Command(candidates=cands[:1], reason="Underutilized"),
            ["stuck-replacement"],
        )
        item = queue.items[0]
        queue.reconcile()
        assert item.attempts == 1 and item.next_try > clock.now()
        before = item.next_try
        clock.step(2)
        queue.reconcile()
        assert item.attempts == 2 and item.next_try >= before  # backoff grows
        # past the 10-minute deadline the item fails out of the queue
        clock.step(QUEUE_TIMEOUT + 1)
        queue.reconcile()
        assert not queue.items
        node = client.try_get(Node, cands[0].node.name)
        assert node is not None  # candidate survived


class TestCronBudgetWindows:
    """Budget schedule windows (nodepool.go:296-367, 5-field cron)."""

    _seq = iter(range(1000))

    def _allowed(self, env, budget, at_epoch):
        from karpenter_tpu.controllers.disruption.helpers import (
            allowed_disruptions,
        )

        clock, client, provider, operator, binder = env
        pool = make_nodepool(name=f"budget-{next(self._seq)}")
        pool.spec.disruption.budgets = [budget]
        client.create(pool)
        client.create(make_pod())
        provision_cycle(env)
        nodes = operator.disruption.ctx.cluster.nodes()
        return allowed_disruptions(pool, nodes, "Underutilized", at_epoch)

    def test_budget_outside_window_is_inactive(self, env):
        import calendar
        import time as _time

        # zero-budget active 09:00-10:00 daily; at 12:00 it must not apply
        budget = Budget(nodes="0", schedule="0 9 * * *", duration=3600.0)
        noon = calendar.timegm(_time.strptime("2026-01-05 12:00", "%Y-%m-%d %H:%M"))
        assert self._allowed(env, budget, noon) == 1

    def test_budget_inside_window_applies(self, env):
        import calendar
        import time as _time

        budget = Budget(nodes="0", schedule="0 9 * * *", duration=3600.0)
        t930 = calendar.timegm(_time.strptime("2026-01-05 09:30", "%Y-%m-%d %H:%M"))
        assert self._allowed(env, budget, t930) == 0

    def test_window_edge_inclusive_start_exclusive_end(self, env):
        import calendar
        import time as _time

        from karpenter_tpu.controllers.disruption.helpers import budget_active

        budget = Budget(nodes="0", schedule="0 9 * * *", duration=1800.0)

        def at(hm):
            return calendar.timegm(
                _time.strptime(f"2026-01-05 {hm}", "%Y-%m-%d %H:%M")
            )

        assert budget_active(budget, at("09:00"))  # opens AT the tick
        assert budget_active(budget, at("09:29"))
        assert not budget_active(budget, at("09:35"))  # 35min > 30min window
        assert not budget_active(budget, at("08:59"))

    def test_reason_scoped_budget_ignores_other_reasons(self, env):
        from karpenter_tpu.controllers.disruption.helpers import (
            allowed_disruptions,
        )

        clock, client, provider, operator, binder = env
        pool = make_nodepool()
        pool.spec.disruption.budgets = [Budget(nodes="0", reasons=("Drifted",))]
        client.create(pool)
        client.create(make_pod())
        provision_cycle(env)
        nodes = operator.disruption.ctx.cluster.nodes()
        assert allowed_disruptions(pool, nodes, "Drifted", clock.now()) == 0
        assert allowed_disruptions(pool, nodes, "Underutilized", clock.now()) == 1


class TestEvictionBlockedByPDB:
    def test_pdb_blocks_drain_until_disruptions_allowed(self, env):
        from karpenter_tpu.api.objects import (
            LabelSelector, PodDisruptionBudget,
        )

        clock, client, provider, operator, binder = env
        client.create(make_nodepool())
        app = {"app": "guarded"}
        pod = make_pod(labels=app)
        client.create(pod)
        provision_cycle(env)
        pdb = PodDisruptionBudget(
            metadata=__import__(
                "karpenter_tpu.api.objects", fromlist=["ObjectMeta"]
            ).ObjectMeta(name="pdb-guard"),
            selector=LabelSelector(match_labels=dict(app)),
            min_available="1",
        )
        client.create(pdb)
        node = client.list(Node)[0]
        node.metadata.finalizers.append(labels.TERMINATION_FINALIZER)
        client.delete(node)
        for _ in range(5):
            operator.step()
            clock.step(1)
        # the PDB admits zero disruptions: the pod survives, the node's
        # finalizer holds (termination loops, terminator.go:94-138)
        assert client.try_get(Node, node.metadata.name) is not None
        live = client.get_by_uid(pod.uid)
        assert live.metadata.deletion_timestamp is None
        # relax the PDB; drain completes
        pdb.min_available = "0"
        client.update(pdb)
        for _ in range(6):
            operator.step()
            clock.step(1)
        assert client.try_get(Node, node.metadata.name) is None


class TestDriftEdges:
    def test_hash_annotation_mismatch_drifts(self, env):
        from karpenter_tpu.controllers.nodeclaim_disruption import nodepool_hash

        clock, client, provider, operator, binder = env
        pool = make_nodepool()
        client.create(pool)
        client.create(make_pod())
        provision_cycle(env)
        claim = client.list(NodeClaim)[0]
        claim.metadata.annotations[labels.NODEPOOL_HASH_ANNOTATION_KEY] = "stale"
        operator.nodeclaim_disruption.reconcile_all()
        assert claim.conds().is_true(COND_DRIFTED)
        # re-stamping the current hash clears the condition
        claim.metadata.annotations[labels.NODEPOOL_HASH_ANNOTATION_KEY] = (
            nodepool_hash(pool)
        )
        operator.nodeclaim_disruption.reconcile_all()
        assert not claim.conds().is_true(COND_DRIFTED)

    def test_requirement_drift(self, env):
        from karpenter_tpu.api.objects import NodeSelectorRequirement

        clock, client, provider, operator, binder = env
        pool = make_nodepool()
        client.create(pool)
        client.create(make_pod())
        provision_cycle(env)
        claim = client.list(NodeClaim)[0]
        operator.nodeclaim_disruption.reconcile_all()
        assert not claim.conds().is_true(COND_DRIFTED)
        # the pool now requires a zone the claim is not in
        other = (
            "test-zone-b"
            if claim.metadata.labels.get(labels.TOPOLOGY_ZONE) != "test-zone-b"
            else "test-zone-c"
        )
        pool.spec.template.spec.requirements = [
            NodeSelectorRequirement(labels.TOPOLOGY_ZONE, "In", (other,))
        ]
        client.update(pool)
        # clear hash drift so requirement drift is what fires
        from karpenter_tpu.controllers.nodeclaim_disruption import nodepool_hash

        claim.metadata.annotations[labels.NODEPOOL_HASH_ANNOTATION_KEY] = (
            nodepool_hash(pool)
        )
        operator.nodeclaim_disruption.reconcile_all()
        assert claim.conds().is_true(COND_DRIFTED)

    def test_instance_type_withdrawn_drifts(self, env):
        clock, client, provider, operator, binder = env
        pool = make_nodepool()
        client.create(pool)
        client.create(make_pod())
        provision_cycle(env)
        claim = client.list(NodeClaim)[0]
        from karpenter_tpu.controllers.nodeclaim_disruption import nodepool_hash

        claim.metadata.annotations[labels.NODEPOOL_HASH_ANNOTATION_KEY] = (
            nodepool_hash(pool)
        )
        operator.nodeclaim_disruption.reconcile_all()
        assert not claim.conds().is_true(COND_DRIFTED)
        # withdraw the claim's instance type from the provider catalog
        it_name = claim.metadata.labels[labels.INSTANCE_TYPE]
        provider._instance_types = [
            it for it in provider._instance_types if it.name != it_name
        ]
        operator.nodeclaim_disruption.reconcile_all()
        assert claim.conds().is_true(COND_DRIFTED)


class TestVolumeDetachWait:
    def test_termination_waits_for_volume_detach(self, env):
        """Instance termination waits for drained pods' VolumeAttachments
        to be cleaned up (termination/controller.go:193-243)."""
        from karpenter_tpu.api.objects import ObjectMeta, VolumeAttachment

        clock, client, provider, operator, binder = env
        client.create(make_nodepool())
        client.create(make_pod())
        provision_cycle(env)
        node = client.list(Node)[0]
        va = VolumeAttachment(
            metadata=ObjectMeta(name="va-1"),
            node_name=node.metadata.name,
            pv_name="pv-1",
        )
        client.create(va)
        node.metadata.finalizers.append(labels.TERMINATION_FINALIZER)
        client.delete(node)
        for _ in range(5):
            operator.step()
            clock.step(1)
        # drained, but the attachment still exists: the node must persist
        assert client.try_get(Node, node.metadata.name) is not None
        # the attacher detaches; termination completes
        client.delete(va)
        for _ in range(6):
            operator.step()
            clock.step(1)
        assert client.try_get(Node, node.metadata.name) is None

    def test_nondrainable_pod_volumes_do_not_block(self, env):
        """Attachments backing NON-drainable pods (static/mirror pods) are
        filtered out of the wait (termination/controller.go:208-243)."""
        from karpenter_tpu.api.objects import (
            ObjectMeta, PersistentVolumeClaim, PersistentVolumeClaimRef,
            VolumeAttachment,
        )

        clock, client, provider, operator, binder = env
        client.create(make_nodepool())
        client.create(make_pod())
        provision_cycle(env)
        node = client.list(Node)[0]
        # a static (node-owned) pod with a mounted volume stays through
        # drain; its attachment must not block termination
        static = make_pod(name="static-1", node_name=node.metadata.name)
        static.metadata.annotations["kubernetes.io/config.source"] = "file"
        static.spec.volumes.append(PersistentVolumeClaimRef(claim_name="pvc-1"))
        static.status.phase = "Running"
        client.create(static)
        client.create(
            PersistentVolumeClaim(
                metadata=ObjectMeta(name="pvc-1"), volume_name="pv-keep"
            )
        )
        client.create(
            VolumeAttachment(
                metadata=ObjectMeta(name="va-keep"),
                node_name=node.metadata.name,
                pv_name="pv-keep",
            )
        )
        assert operator.termination._volumes_detached(node)
        node.metadata.finalizers.append(labels.TERMINATION_FINALIZER)
        client.delete(node)
        for _ in range(6):
            operator.step()
            clock.step(1)
        assert client.try_get(Node, node.metadata.name) is None


class TestClusterStateGauges:
    def test_sync_gauges_track_state(self, env):
        from karpenter_tpu.controllers.state import (
            CLUSTER_STATE_NODE_COUNT, CLUSTER_STATE_SYNCED,
            CLUSTER_STATE_UNSYNCED_SECONDS,
        )
        from karpenter_tpu.api.objects import NodeClaimSpec, ObjectMeta

        clock, client, provider, operator, binder = env
        cluster = operator.disruption.ctx.cluster
        assert cluster.synced()
        assert CLUSTER_STATE_SYNCED.value() == 1.0
        assert CLUSTER_STATE_UNSYNCED_SECONDS.value() == 0.0

        # a NodeClaim with a provider id the cluster has never seen
        ghost = NodeClaim(
            metadata=ObjectMeta(name="ghost"), spec=NodeClaimSpec()
        )
        ghost.status.provider_id = "ghost://1"
        # bypass the watch so state stays behind the store (a real create
        # with watchers silenced — poking client._objects directly would
        # also bypass the store's own kind/label indexes, which list()
        # reads)
        saved, client._watchers = client._watchers, []
        try:
            client.create(ghost)
        finally:
            client._watchers = saved
        assert not cluster.synced()
        assert CLUSTER_STATE_SYNCED.value() == 0.0
        clock.step(7)
        cluster.synced()
        assert CLUSTER_STATE_UNSYNCED_SECONDS.value() >= 7.0


class TestLeaderElection:
    def test_single_leader_reconciles(self, env):
        from karpenter_tpu.operator import Operator, OperatorOptions

        clock, client, provider, operator, binder = env
        opts = OperatorOptions(leader_election=True)
        a = Operator(client, provider, options=opts)
        b = Operator(client, provider, options=opts)
        assert a.is_leader()
        assert not b.is_leader()  # lease held by a
        # a keeps renewing through steps
        clock.step(5)
        assert a.is_leader() and not b.is_leader()
        # a goes dark past the lease duration: b steals the lease
        clock.step(20)
        assert b.is_leader()
        assert not a.is_leader()

    def test_nonleader_step_does_not_reconcile(self, env):
        from karpenter_tpu.operator import Operator, OperatorOptions

        clock, client, provider, operator, binder = env
        opts = OperatorOptions(leader_election=True)
        a = Operator(client, provider, options=opts)
        b = Operator(client, provider, options=opts)
        assert a.is_leader()
        client.create(make_nodepool())
        client.create(make_pod())
        clock.step(1.1)
        b.step(force_provision=True)  # standby: must not provision
        assert client.list(NodeClaim) == []
        a.step(force_provision=True)
        assert len(client.list(NodeClaim)) == 1


class TestSchemaValidation:
    """CRD/CEL-tier validation (api/validation.py; reference
    nodepool_validation.go, nodeclaim_validation.go, CEL rules in
    nodepool.go:79,176-184)."""

    def test_valid_pool_is_ready(self, env):
        clock, client, provider, operator, binder = env
        from karpenter_tpu.api.objects import COND_READY

        pool = make_nodepool()
        client.create(pool)
        operator.nodepool_status.reconcile_all()
        assert pool.conds().is_true(COND_READY)

    def test_invalid_requirement_blocks_readiness(self, env):
        from karpenter_tpu.api.objects import COND_READY, NodeSelectorRequirement

        clock, client, provider, operator, binder = env
        pool = make_nodepool(
            requirements=[NodeSelectorRequirement(labels.TOPOLOGY_ZONE, "In", ())]
        )
        client.create(pool)
        operator.nodepool_status.reconcile_all()
        conds = pool.conds()
        assert not conds.is_true(COND_READY)
        assert conds.get(COND_READY).reason == "ValidationFailed"

    def test_rule_catalog(self):
        from karpenter_tpu.api import validation
        from karpenter_tpu.api.objects import (
            Budget, NodeSelectorRequirement, Taint,
        )

        R = NodeSelectorRequirement
        # In must have values (CEL nodepool.go:176)
        assert validation.validate_requirement(R("team", "In", ()))
        # Gt/Lt single positive integer (CEL nodepool.go:177)
        assert validation.validate_requirement(R("cpu-gen", "Gt", ("a",)))
        assert validation.validate_requirement(R("cpu-gen", "Gt", ("1", "2")))
        assert not validation.validate_requirement(R("cpu-gen", "Gt", ("3",)))
        # minValues bound (CEL nodepool.go:178)
        assert validation.validate_requirement(
            R(labels.TOPOLOGY_ZONE, "In", ("a",), min_values=2)
        )
        # restricted label (labels.go:109-118)
        assert validation.validate_requirement(
            R("kubernetes.io/hostname", "In", ("n1",))
        )
        # well-known labels always pass the restriction
        assert not validation.validate_requirement(
            R(labels.TOPOLOGY_ZONE, "In", ("test-zone-a",))
        )
        # unsupported operator
        assert validation.validate_requirement(R("team", "NotAnOp", ("x",)))
        # malformed key / value syntax
        assert validation.validate_requirement(R("-bad-", "In", ("x",)))
        assert validation.validate_requirement(R("team", "In", ("bad value",)))

        pool = make_nodepool(taints=[
            Taint(key="a", value="v", effect="NoSchedule"),
            Taint(key="a", value="w", effect="NoSchedule"),
        ])
        errs = validation.validate_node_pool(pool)
        assert any("duplicate taint" in e for e in errs)

        pool = make_nodepool(name="w")
        pool.spec.weight = 500
        assert any("weight" in e for e in validation.validate_node_pool(pool))

        # budget: schedule requires duration (CEL nodepool.go:79) + cron syntax
        pool = make_nodepool(name="b")
        pool.spec.disruption.budgets = [Budget(nodes="10%", schedule="0 9 * * *")]
        assert any("duration" in e for e in validation.validate_node_pool(pool))
        pool.spec.disruption.budgets = [
            Budget(nodes="10%", schedule="not cron", duration=60.0)
        ]
        assert any("cron" in e for e in validation.validate_node_pool(pool))
        pool.spec.disruption.budgets = [Budget(nodes="nope")]
        assert any("nodes" in e for e in validation.validate_node_pool(pool))
        pool.spec.disruption.budgets = [
            Budget(nodes="20%", schedule="0 9 * * 1-5", duration=3600.0)
        ]
        assert validation.validate_node_pool(pool) == []
