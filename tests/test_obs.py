"""Decision-path tracing + the decision audit trail (karpenter_tpu/obs/).

The acceptance pins live here:

- a DISABLED tracer reproduces byte-identical solver decisions (the same
  zero-overhead contract tests/test_faults.py pins for the injector);
- trace ids propagate through the RemoteSolver gRPC hop (sidecar spans
  stitch into the caller's trace) and through the in-process fallback;
- the decision audit trail is complete across all three degradation
  rungs (batched / kernel / oracle) and records quarantine verdicts and
  fired fault sites;
- the Chrome trace export validates against the checked-in minimal
  schema (hack/trace_schema.json);
- the Prometheus renderer (registry.render / Registry.dump) emits full
  text exposition, and no non-identity metric exceeds the bounded
  label-series size;
- MetricsCloudProvider reads the inner provider's injected clock, so
  chaos-soak latency histograms replay deterministically.
"""

from __future__ import annotations

import copy
import json
import os

import grpc
import pytest

from karpenter_tpu import faults, obs
from karpenter_tpu.cloudprovider import corpus
from karpenter_tpu.cloudprovider.kwok import KwokCloudProvider
from karpenter_tpu.cloudprovider.metrics import (
    METHOD_DURATION,
    MetricsCloudProvider,
)
from karpenter_tpu.faults.breaker import SolverHealth
from karpenter_tpu.kube import Client, TestClock
from karpenter_tpu.metrics import Counter, Gauge, Histogram, Registry, REGISTRY
from karpenter_tpu.operator import Operator, OperatorOptions
from karpenter_tpu.scheduling.topology import Topology
from karpenter_tpu.solver import TpuSolver
from karpenter_tpu.solver.driver import Scenario, SolverConfig
from karpenter_tpu.solver.service import InjectedRpcError, RemoteSolver, serve

from helpers import make_nodepool, make_pod, make_pods

HERE = os.path.dirname(os.path.abspath(__file__))
SCHEMA_PATH = os.path.join(
    os.path.dirname(HERE), "hack", "trace_schema.json"
)


@pytest.fixture(autouse=True)
def _clean_obs():
    obs.uninstall()
    yield
    obs.uninstall()
    faults.uninstall()


def load_schema() -> dict:
    with open(SCHEMA_PATH, encoding="utf-8") as fh:
        return json.load(fh)


def build_solver(pods, config=None, n_types=10):
    node_pools = [make_nodepool()]
    its_by_pool = {np_.name: corpus.generate(n_types) for np_ in node_pools}
    topo = Topology(Client(TestClock()), [], node_pools, its_by_pool, pods)
    return TpuSolver(node_pools, its_by_pool, topo, config=config)


def results_signature(results):
    claims = sorted(
        (
            c.template.node_pool_name,
            tuple(sorted(p.uid for p in c.pods)),
            tuple(it.name for it in c.instance_type_options),
        )
        for c in results.new_node_claims
    )
    return claims, dict(results.pod_errors)


# -- tracer core -------------------------------------------------------------


class TestTracer:
    def test_seeded_deterministic_ids(self):
        def run(seed):
            tracer = obs.Tracer(TestClock(), seed=seed)
            with tracer.span("a", x=1):
                with tracer.span("b"):
                    pass
            with tracer.span("c"):
                pass
            return [
                (s.name, s.span_id, s.trace_id, s.parent_id)
                for s in tracer.finished()
            ]

        assert run(42) == run(42)  # chaos replays produce identical traces
        assert run(42) != run(43)

    def test_clock_injected_durations(self):
        clock = TestClock()
        tracer = obs.Tracer(clock, seed=0)
        with tracer.span("phase"):
            clock.sleep(2.5)
        (span,) = tracer.finished()
        assert span.duration == pytest.approx(2.5)

    def test_nesting_and_trace_propagation(self):
        tracer = obs.Tracer(TestClock())
        with tracer.span("root") as root:
            with tracer.span("child") as child:
                assert child.parent_id == root.span_id
                assert child.trace_id == root.trace_id
        # sibling trace gets a fresh trace id
        with tracer.span("other") as other:
            assert other.trace_id != root.trace_id

    def test_span_buffer_bounded(self):
        tracer = obs.Tracer(TestClock(), max_spans=4)
        for i in range(10):
            with tracer.span(f"s{i}"):
                pass
        assert len(tracer.finished()) == 4
        assert tracer.dropped == 6

    def test_phase_histogram_fed(self):
        before = obs.PHASE_DURATION.count(labels={"phase": "ph-test"})
        tracer = obs.Tracer(TestClock())
        with tracer.span("ph-test"):
            pass
        after = obs.PHASE_DURATION.count(labels={"phase": "ph-test"})
        assert after == before + 1

    def test_error_annotated_and_reraised(self):
        tracer = obs.Tracer(TestClock())
        with pytest.raises(ValueError):
            with tracer.span("boom"):
                raise ValueError("x")
        (span,) = tracer.finished()
        assert span.attrs["error"] == "ValueError"

    def test_event_lands_on_current_span(self):
        tracer = obs.install(obs.Tracer(TestClock()))
        with obs.span("holder"):
            obs.event("happened", detail=7)
        (span,) = tracer.finished()
        assert span.events and span.events[0][1] == "happened"

    def test_noop_when_uninstalled(self):
        assert obs.span("anything") is obs.NOOP_SPAN
        obs.event("dropped")  # must not raise
        assert obs.current_span() is None


# -- zero-overhead / byte-identical contract ---------------------------------


class TestDisabledTracerContract:
    def test_disabled_tracer_byte_identical_decisions(self):
        """No tracer vs installed tracer vs uninstalled again: the
        committed decisions are identical (the acceptance pin mirroring
        the PR-5 injector contract)."""
        pods = make_pods(40, cpu="1", memory="2Gi")
        baseline = results_signature(
            build_solver(copy.deepcopy(pods)).solve(copy.deepcopy(pods))
        )
        obs.install(obs.Tracer(TestClock(), seed=3))
        traced = results_signature(
            build_solver(copy.deepcopy(pods)).solve(copy.deepcopy(pods))
        )
        obs.uninstall()
        again = results_signature(
            build_solver(copy.deepcopy(pods)).solve(copy.deepcopy(pods))
        )
        assert baseline == traced == again


# -- chrome export -----------------------------------------------------------


class TestChromeExport:
    def test_export_validates_against_checked_in_schema(self):
        clock = TestClock()
        tracer = obs.install(obs.Tracer(clock, seed=1))
        with obs.span("solve", pods=3):
            clock.sleep(0.1)
            with obs.span("solve.encode"):
                clock.sleep(0.2)
            with obs.span("solve.dispatch"):
                obs.event("fault.fired", site="solver.dispatch")
                clock.sleep(0.3)
        doc = tracer.export_chrome()
        assert obs.validate_chrome_trace(doc, load_schema()) == []
        # timestamps are monotonic in export order under the injected clock
        ts = [e["ts"] for e in doc["traceEvents"] if e["ph"] == "X"]
        assert ts == sorted(ts)

    def test_dangling_parent_detected(self):
        tracer = obs.Tracer(TestClock())
        with tracer.span("only"):
            pass
        doc = tracer.export_chrome()
        doc["traceEvents"][0]["args"]["parent_id"] = "feedfacedeadbeef"
        problems = obs.validate_chrome_trace(doc, load_schema())
        assert any("dangling parent" in p for p in problems)

    def test_remote_parented_span_not_flagged_as_dangling(self):
        """A sidecar's OWN trace dump contains spans whose parent lives in
        the caller process's tracer (stitched via gRPC metadata): marked
        remote_parent, they must validate instead of reading as leaks."""
        tracer = obs.Tracer(TestClock())
        with tracer.span(
            "sidecar.solve",
            trace_id="aaaaaaaaaaaaaaaa",
            parent_id="bbbbbbbbbbbbbbbb",  # exists only in the caller
        ):
            pass
        doc = tracer.export_chrome()
        assert obs.validate_chrome_trace(doc, load_schema()) == []

    def test_dump_is_loadable_json(self, tmp_path):
        tracer = obs.Tracer(TestClock())
        with tracer.span("x"):
            pass
        path = tmp_path / "trace.json"
        tracer.dump(str(path))
        doc = json.loads(path.read_text())
        assert doc["traceEvents"][0]["name"] == "x"


# -- decision audit trail ----------------------------------------------------


class TestAuditTrail:
    def test_kernel_rung_record_complete(self):
        obs.install(obs.Tracer(TestClock(), seed=0))
        pods = make_pods(12, cpu="1", memory="1Gi")
        build_solver(pods).solve(pods)
        rec = obs.AUDIT.last()
        assert rec.kind == "solve"
        assert rec.rung == "kernel"
        assert rec.guard == "ok"
        assert rec.encode_hash  # content-addressed catalog hash
        assert rec.pods == 12
        assert rec.claims >= 1
        assert rec.dispatches >= 1
        assert rec.cost is not None and rec.cost > 0
        assert rec.trace_id  # correlated with the span trace
        assert rec.fault_sites == []
        assert rec.decision_id.startswith("d")

    def test_oracle_rung_via_tripped_breaker(self):
        clock = TestClock()
        health = SolverHealth(clock, failure_threshold=1, cooldown=60.0)
        health.quarantine("kernel", "seeded")
        pods = make_pods(8, cpu="1", memory="1Gi")
        solver = build_solver(pods, config=SolverConfig(health=health))
        solver.solve(pods)
        rec = obs.AUDIT.last()
        assert rec.rung == "oracle"
        assert rec.guard == "ok"
        assert rec.claims >= 1

    def test_batched_rung_scenarios_record(self):
        pods = make_pods(8, cpu="1", memory="1Gi")
        solver = build_solver(pods)
        results = solver.solve_scenarios(
            [Scenario(pods=pods[:4]), Scenario(pods=pods)]
        )
        assert results is not None and len(results) == 2
        rec = obs.AUDIT.last()
        assert rec.kind == "scenarios"
        assert rec.rung == "batched"
        assert rec.scenario_count == 2
        assert rec.dispatches >= 1
        assert rec.guard == "ok"

    def test_quarantine_guard_verdict_and_fault_sites(self):
        """A corrupt kernel output leaves an audit record naming the
        guard verdict AND the injected fault site that caused it — the
        chaos-soak correlation the audit trail exists for."""
        import numpy as np

        def corrupt(outs):
            outs = list(outs)
            outs[5] = np.asarray(outs[5]) - 7  # claim_fills negative
            return tuple(outs)

        clock = TestClock()
        health = SolverHealth(clock, failure_threshold=1, cooldown=60.0)
        faults.install(
            faults.FaultInjector(
                [faults.FaultRule(faults.SOLVER_OUTPUT, mutate=corrupt)]
            )
        )
        pods = make_pods(10, cpu="1", memory="1Gi")
        # relax=False pins the exact route: identical plain pods would
        # otherwise ride the relaxation bulk and the corrupted exact rows
        # would be dead padding (relax-route twin: tests/test_relax.py)
        solver = build_solver(
            pods, config=SolverConfig(health=health, relax=False)
        )
        results = solver.solve(pods)
        faults.uninstall()
        assert not results.pod_errors  # oracle re-solve succeeded
        rec = obs.AUDIT.last()
        assert rec.rung == "oracle"
        assert rec.guard.startswith("quarantined:")
        assert faults.SOLVER_OUTPUT in rec.fault_sites

    def test_scenario_dispatch_crash_leaves_audit_record(self):
        """A crashed batched dispatch declines the batch AND lands in the
        audit trail with the error — the trail must show WHY the caller
        replayed per-probe, not just quarantines."""
        clock = TestClock()
        health = SolverHealth(clock, failure_threshold=5, cooldown=60.0)
        faults.install(
            faults.FaultInjector(
                [faults.FaultRule(faults.SOLVER_SCENARIOS, times=1)]
            )
        )
        pods = make_pods(8, cpu="1", memory="1Gi")
        solver = build_solver(pods, config=SolverConfig(health=health))
        try:
            results = solver.solve_scenarios([Scenario(pods=pods)])
        finally:
            faults.uninstall()
        assert results is None  # declined; caller replays per-probe
        rec = obs.AUDIT.last()
        assert rec.kind == "scenarios"
        assert "InjectedFault" in rec.attrs.get("error", "")
        assert faults.SOLVER_SCENARIOS in rec.fault_sites

    def test_timestamps_share_one_timebase(self):
        """All records stamp from ONE clock (the installed tracer's), so
        query(since=...) compares like with like."""

        def rec():
            return obs.AUDIT.record(
                kind="solve", trace_id="", duration_ms=0.0, encode_hash="",
                pods=0, claims=0, errors=0, scenario_count=0, dispatches=0,
                rung="kernel", guard="ok",
            )

        clock = TestClock()
        clock.set(5000.0)
        obs.install(obs.Tracer(clock))
        first = rec()
        assert first.timestamp == 5000.0
        clock.set(6000.0)
        second = rec()
        assert second.timestamp == 6000.0
        since = obs.AUDIT.query(since=5500.0)
        assert second.decision_id in {r.decision_id for r in since}
        assert first.decision_id not in {r.decision_id for r in since}

    def test_consolidation_record_aggregates_same_trace_solves(self):
        """The decision-level consolidation record derives rung/guard from
        the SAME-TRACE per-solve records, so a mid-search quarantine is
        visible at decision level; untraced searches report 'untracked'
        instead of claiming a verdict."""
        from types import SimpleNamespace

        from karpenter_tpu.controllers.disruption.methods import (
            _audit_consolidation,
        )
        from karpenter_tpu.controllers.disruption.types import Command

        method = SimpleNamespace(
            ctx=SimpleNamespace(
                solver_config=None,
                encode_cache=SimpleNamespace(content_hash="abc"),
                clock=TestClock(),
            ),
            last_probes=3,
            last_dispatches=1,
        )
        # traced: seed two same-trace solve records, one quarantined
        obs.AUDIT.record(
            kind="solve", trace_id="t1", duration_ms=0.0, encode_hash="",
            pods=0, claims=0, errors=0, scenario_count=0, dispatches=1,
            rung="batched", guard="ok",
        )
        obs.AUDIT.record(
            kind="solve", trace_id="t1", duration_ms=0.0, encode_hash="",
            pods=0, claims=0, errors=0, scenario_count=0, dispatches=1,
            rung="oracle", guard="quarantined: seeded",
        )
        sp = SimpleNamespace(trace_id="t1", duration=0.01)
        _audit_consolidation(method, "consolidation-multi", sp, Command())
        rec = obs.AUDIT.last()
        assert rec.rung == "oracle"  # worst rung the search used
        assert rec.guard == "quarantined: seeded"
        # untraced: no correlation possible → honest "untracked"
        sp_off = SimpleNamespace(trace_id="", duration=0.0)
        _audit_consolidation(method, "consolidation-multi", sp_off, Command())
        assert obs.AUDIT.last().guard == "untracked"

    def test_all_three_rungs_queryable(self):
        """One log, three rungs: the degradation ladder's whole story is
        reconstructable from AUDIT.query alone."""
        obs.AUDIT.clear()
        pods = make_pods(8, cpu="1", memory="1Gi")
        # batched
        solver = build_solver(pods)
        assert solver.solve_scenarios([Scenario(pods=pods)]) is not None
        # kernel
        build_solver(pods).solve(pods)
        # oracle
        build_solver(
            pods, config=SolverConfig(force_oracle=True)
        ).solve(pods)
        rungs = {r.rung for r in obs.AUDIT.query()}
        assert rungs == {"batched", "kernel", "oracle"}
        assert len(obs.AUDIT.query(rung="oracle")) == 1
        for rec in obs.AUDIT.query():
            assert rec.encode_hash or rec.rung == "oracle"
            assert rec.duration_ms >= 0

    def test_ring_buffer_bounded_and_ordered(self):
        log = obs.AuditLog(maxlen=3)
        for i in range(5):
            log.record(
                kind="solve", trace_id="", timestamp=float(i),
                duration_ms=0.0, encode_hash="", pods=0, claims=0,
                errors=0, scenario_count=0, dispatches=0, rung="kernel",
                guard="ok",
            )
        assert len(log) == 3
        ids = [r.decision_id for r in log.query()]
        assert ids == ["d000003", "d000004", "d000005"]
        assert json.loads(log.to_json())[0]["decision_id"] == "d000003"


# -- remote trace propagation ------------------------------------------------


@pytest.fixture(scope="module")
def sidecar():
    server = serve("127.0.0.1:0")
    yield f"127.0.0.1:{server._bound_port}"
    server.stop(0)


class TestRemoteTracePropagation:
    def _remote(self, sidecar):
        pools = [make_nodepool(name="default")]
        types = {"default": corpus.generate(12)}
        return RemoteSolver(sidecar, pools, types)

    def test_sidecar_span_stitches_into_caller_trace(self, sidecar):
        """The trace id crosses the gRPC hop via metadata: the sidecar's
        solve spans carry the CALLER's trace id and parent on the caller's
        remote.solve span (the sidecar serves from this process's thread
        pool, so its spans land in the same tracer)."""
        tracer = obs.install(obs.Tracer(obs.PerfClock(), seed=5))
        pods = make_pods(6, cpu="1", memory="1Gi")
        results = self._remote(sidecar).solve(pods)
        obs.uninstall()
        assert not results.pod_errors
        (remote_span,) = tracer.finished("remote.solve")
        (sidecar_span,) = tracer.finished("sidecar.solve")
        assert sidecar_span.trace_id == remote_span.trace_id
        assert sidecar_span.parent_id == remote_span.span_id
        assert tracer.finished("remote.dispatch")  # the RPC leg itself
        assert not tracer.finished("remote.fallback")

    def test_fallback_span_stays_in_callers_trace(self, sidecar):
        """When the sidecar is out, the in-process fallback runs under a
        remote.fallback span in the SAME trace — so a stitched trace shows
        the degradation instead of silently losing the solve."""
        tracer = obs.install(obs.Tracer(obs.PerfClock(), seed=6))
        faults.install(
            faults.FaultInjector(
                [
                    faults.FaultRule(
                        faults.REMOTE_SOLVE,
                        error=lambda: InjectedRpcError(
                            grpc.StatusCode.UNAVAILABLE
                        ),
                    )
                ]
            )
        )
        pods = make_pods(6, cpu="1", memory="1Gi")
        try:
            results = self._remote(sidecar).solve(pods)
        finally:
            faults.uninstall()
            obs.uninstall()
        assert not results.pod_errors
        (remote_span,) = tracer.finished("remote.solve")
        (fallback_span,) = tracer.finished("remote.fallback")
        assert fallback_span.trace_id == remote_span.trace_id
        assert not tracer.finished("sidecar.solve")  # never reached


# -- prometheus renderer + cardinality guard ---------------------------------


class TestRegistryRenderer:
    def _scoped(self):
        reg = Registry()
        c = Counter("render_total", "help text here", registry=reg)
        g = Gauge("render_depth", "gauge help", registry=reg)
        h = Histogram(
            "render_duration_seconds", "hist help",
            buckets=(0.1, 1.0), registry=reg,
        )
        c.inc(labels={"method": "a"})
        c.inc(labels={"method": "a"})
        c.inc(labels={"method": "b"})
        g.set(3.5)
        h.observe(0.05)
        h.observe(0.5)
        h.observe(5.0)
        return reg

    def test_render_full_exposition(self):
        text = self._scoped().render()
        assert "# HELP karpenter_tpu_render_total help text here" in text
        assert "# TYPE karpenter_tpu_render_total counter" in text
        assert 'karpenter_tpu_render_total{method="a"} 2.0' in text
        assert "# TYPE karpenter_tpu_render_depth gauge" in text
        assert "karpenter_tpu_render_depth 3.5" in text
        assert (
            "# TYPE karpenter_tpu_render_duration_seconds histogram" in text
        )
        # cumulative buckets: 1 obs <= 0.1, 2 obs <= 1.0, 3 total
        assert (
            'karpenter_tpu_render_duration_seconds_bucket{le="0.1"} 1'
            in text
        )
        assert (
            'karpenter_tpu_render_duration_seconds_bucket{le="1.0"} 2'
            in text
        )
        assert (
            'karpenter_tpu_render_duration_seconds_bucket{le="+Inf"} 3'
            in text
        )
        assert "karpenter_tpu_render_duration_seconds_count 3" in text
        assert "karpenter_tpu_render_duration_seconds_sum 5.55" in text

    def test_label_escaping(self):
        reg = Registry()
        c = Counter("esc_total", "", registry=reg)
        c.inc(labels={"msg": 'say "hi"\nplease\\now'})
        text = reg.render()
        assert '\\"hi\\"' in text and "\\n" in text and "\\\\" in text

    def test_dump_writes_file(self, tmp_path):
        path = tmp_path / "metrics.prom"
        self._scoped().dump(str(path))
        assert "# TYPE" in path.read_text()

    def test_cardinality_guard_flags_unbounded_labels(self):
        reg = Registry()
        c = Counter("runaway_total", "", registry=reg)
        for i in range(70):
            c.inc(labels={"pod_uid": f"uid-{i}"})  # the sin the guard exists for
        flagged = reg.check_cardinality(bound=64)
        assert flagged == {"karpenter_tpu_runaway_total": 70}
        assert reg.check_cardinality(bound=64, exempt=("karpenter_tpu_runaway",)) == {}

    # per-node/per-pod gauges mirror the reference's identity-labeled
    # metrics and scale with cluster size by design; every OTHER metric
    # must stay bounded regardless of how much of the suite ran first
    IDENTITY_PREFIXES = (
        "karpenter_tpu_node_",
        "karpenter_tpu_pod_",
    )

    def test_global_registry_label_cardinality_bounded(self):
        flagged = REGISTRY.check_cardinality(exempt=self.IDENTITY_PREFIXES)
        assert flagged == {}, (
            f"metrics with unbounded label series: {flagged} — a label is "
            "carrying identity (pod uid, node name); drop it or add the "
            "metric to the documented identity exemptions"
        )


# -- clocked cloud-provider metrics ------------------------------------------


class _ClockedDummyProvider:
    """Minimal provider carrying an injected clock; get_instance_types
    advances it by a fixed simulated latency."""

    def __init__(self, clock, latency=0.25):
        self.clock = clock
        self.latency = latency
        self._types = corpus.generate(3)

    def name(self):
        return "clocked-dummy"

    def get_instance_types(self, node_pool):
        self.clock.sleep(self.latency)
        return list(self._types)


class TestMetricsProviderClock:
    def test_injected_clock_durations_deterministic(self):
        def run():
            clock = TestClock()
            provider = MetricsCloudProvider(
                _ClockedDummyProvider(clock, latency=0.25)
            )
            provider.get_instance_types(None)
            provider.get_instance_types(None)
            labels = {
                "method": "GetInstanceTypes", "provider": "clocked-dummy",
            }
            return (
                METHOD_DURATION.count(labels),
                METHOD_DURATION.sum(labels),
            )

        c1, s1 = run()
        c2, s2 = run()
        # deterministic under replay: each run adds exactly 2 observations
        # of exactly 0.25 simulated seconds
        assert c2 - c1 == 2
        assert s2 - s1 == pytest.approx(0.5)

    def test_wall_clock_fallback_without_inner_clock(self):
        class Clockless:
            def name(self):
                return "clockless-dummy"

            def list(self):
                return []

        provider = MetricsCloudProvider(Clockless())
        labels = {"method": "List", "provider": "clockless-dummy"}
        before = METHOD_DURATION.count(labels)
        provider.list()
        assert METHOD_DURATION.count(labels) == before + 1


# -- operator integration ----------------------------------------------------


class TestOperatorTracing:
    def test_reconcile_spans_and_shutdown_artifacts(self, tmp_path):
        clock = TestClock()
        client = Client(clock)
        provider = KwokCloudProvider(client, corpus.generate(12))
        trace_path = tmp_path / "trace.json"
        metrics_path = tmp_path / "metrics.prom"
        operator = Operator(
            client,
            provider,
            OperatorOptions(
                enable_tracing=True,
                trace_seed=11,
                trace_path=str(trace_path),
                metrics_dump_path=str(metrics_path),
            ),
        )
        assert obs.active() is operator.tracer
        client.create(make_nodepool())
        client.create(make_pod())
        clock.step(1.1)
        operator.step(force_provision=True)
        names = {s.name for s in operator.tracer.finished()}
        assert "reconcile.provisioner" in names
        assert "provision.schedule" in names
        assert "solve" in names  # the decision path threads to the solver
        # the provisioning solve left a correlated audit record
        rec = obs.AUDIT.query(kind="solve")[-1]
        assert rec.rung in ("kernel", "oracle") and rec.trace_id
        operator.shutdown()
        assert obs.active() is None  # installation released
        doc = json.loads(trace_path.read_text())
        assert obs.validate_chrome_trace(doc, load_schema()) == []
        assert "# TYPE" in metrics_path.read_text()

    def test_tracing_off_by_default(self):
        clock = TestClock()
        client = Client(clock)
        provider = KwokCloudProvider(client, corpus.generate(4))
        operator = Operator(client, provider)
        assert operator.tracer is None
        assert obs.active() is None
