"""Resource quantity algebra tests (mirrors reference pkg/utils/resources semantics)."""

import pytest

from karpenter_tpu.api import resources as res


class TestParseQuantity:
    def test_whole_units(self):
        assert res.parse_quantity("1") == 1000
        assert res.parse_quantity("16") == 16000
        assert res.parse_quantity(2) == 2000

    def test_milli(self):
        assert res.parse_quantity("100m") == 100
        assert res.parse_quantity("1500m") == 1500

    def test_binary_suffixes(self):
        assert res.parse_quantity("1Ki") == 1024 * 1000
        assert res.parse_quantity("1Gi") == 2**30 * 1000
        assert res.parse_quantity("1.5Gi") == int(1.5 * 2**30) * 1000

    def test_decimal_suffixes(self):
        assert res.parse_quantity("1k") == 10**3 * 1000
        assert res.parse_quantity("2G") == 2 * 10**9 * 1000

    def test_scientific(self):
        assert res.parse_quantity("1e3") == 10**3 * 1000

    def test_decimal_fraction(self):
        assert res.parse_quantity("0.5") == 500
        assert res.parse_quantity("2.5") == 2500

    def test_invalid(self):
        with pytest.raises(ValueError):
            res.parse_quantity("abc")
        with pytest.raises(ValueError):
            res.parse_quantity("1Zi")

    def test_roundtrip_format(self):
        assert res.format_quantity(res.parse_quantity("2")) == "2"
        assert res.format_quantity(res.parse_quantity("100m")) == "100m"


class TestResourceListOps:
    def test_merge(self):
        a = {"cpu": 1000, "memory": 2000}
        b = {"cpu": 500, "pods": 1000}
        assert res.merge(a, b) == {"cpu": 1500, "memory": 2000, "pods": 1000}

    def test_merge_empty(self):
        assert res.merge() == {}

    def test_subtract_keeps_lhs_keys_only(self):
        # reference: resources.go:81-94 — rhs-only keys are dropped
        a = {"cpu": 1000, "memory": 2000}
        b = {"cpu": 400, "gpu": 7}
        assert res.subtract(a, b) == {"cpu": 600, "memory": 2000}

    def test_fits_basic(self):
        assert res.fits({"cpu": 500}, {"cpu": 1000, "memory": 100})
        assert not res.fits({"cpu": 1500}, {"cpu": 1000})

    def test_fits_missing_total_resource_is_zero(self):
        assert not res.fits({"gpu": 1}, {"cpu": 1000})
        assert res.fits({"gpu": 0}, {"cpu": 1000})

    def test_fits_negative_total_never_fits(self):
        # reference: resources.go:218-222
        assert not res.fits({}, {"cpu": -1})
        assert not res.fits({"memory": 1}, {"cpu": -5, "memory": 100})

    def test_max_resources(self):
        assert res.max_resources({"cpu": 1, "memory": 5}, {"cpu": 3}) == {"cpu": 3, "memory": 5}

    def test_resource_names_ordering(self):
        names = res.resource_names([{"gpu": 1}, {"cpu": 2, "foo": 3}])
        assert names[:2] == ["cpu", "memory"]
        assert set(names) == {"cpu", "memory", "gpu", "foo"}


class TestNegativeQuantities:
    def test_negative_whole(self):
        assert res.parse_quantity("-2") == -2000

    def test_negative_fraction_ceils(self):
        # milli-scale ceiling: ceil(-1.5) == -1
        assert res.parse_quantity("-1.5m") == -1
