"""In-test performance floor + cost bound.

The reference asserts >=100 pods/sec for batches >100 pods inside its
benchmark test (scheduling_benchmark_test.go:51, 229-233); BASELINE.json
bounds the packing-cost regression at <=2%. These are the in-test
equivalents, running on whatever backend the suite uses (the virtual CPU
platform in CI — the TPU path only gets faster).
"""

import time

import pytest

from karpenter_tpu.cloudprovider import corpus
from karpenter_tpu.kube import Client, TestClock
from karpenter_tpu.scheduling.topology import Topology
from karpenter_tpu.solver import TpuSolver
from karpenter_tpu.solver.driver import SolverConfig
from karpenter_tpu.solver.example import example_nodepool
from karpenter_tpu.solver.workloads import constrained_mix, mixed_pods

MIN_PODS_PER_SEC = 100.0  # the reference's asserted floor
COST_DELTA_BOUND = 0.02  # BASELINE.json


def _floor(config: str, n_pods: int) -> float:
    """The throughput floor for a config: a quarter of the last recorded
    same-platform measurement when bench_floors.json carries one
    (regenerate with `python bench.py --record-floors`), else the
    reference's 100 pods/s. Pinning to measured numbers makes this tier
    catch real regressions, not just catastrophes (VERDICT r4 weak #7);
    the 4x headroom absorbs CPU contention when the full suite runs these
    tests alongside heavier files — floors are recorded on an idle
    machine, asserted on a loaded one."""
    import json
    import os

    import jax

    path = os.path.join(os.path.dirname(__file__), "..", "bench_floors.json")
    try:
        with open(path) as fh:
            floors = json.load(fh)
    except (OSError, ValueError):
        return MIN_PODS_PER_SEC
    plat = jax.devices()[0].platform
    val = floors.get(plat, {}).get(f"{config}-{n_pods}")
    if not val:
        return MIN_PODS_PER_SEC
    return max(val * 0.25, MIN_PODS_PER_SEC)


def _solve(pods, n_types=100, force_oracle=False):
    pools = [example_nodepool()]
    its = {pools[0].name: corpus.generate(n_types)}
    topo = Topology(Client(TestClock()), [], pools, its, pods)
    solver = TpuSolver(
        pools, its, topo, config=SolverConfig(force_oracle=force_oracle)
    )
    t0 = time.perf_counter()
    results = solver.solve(pods)
    return results, time.perf_counter() - t0


class TestPerfFloor:
    @pytest.mark.parametrize("n_pods", [500, 2000])
    def test_mixed_throughput_floor(self, n_pods):
        pods = mixed_pods(n_pods, gpu_fraction=0.0)
        # warm-up compiles the shape bucket; the floor is about steady state
        _solve(pods)
        results, dt = _solve(pods)
        assert results.all_pods_scheduled()
        floor = _floor("mixed", n_pods)
        assert n_pods / dt >= floor, f"{n_pods / dt:.0f} < {floor:.0f} pods/sec"

    def test_constrained_throughput_floor(self):
        pods = constrained_mix(2000)
        _solve(pods)
        results, dt = _solve(pods)
        assert results.all_pods_scheduled()
        floor = _floor("constrained", 2000)
        assert 2000 / dt >= floor, f"{2000 / dt:.0f} < {floor:.0f} pods/sec"


class TestCostBound:
    @pytest.mark.parametrize("n_pods", [500, 2000])
    def test_mixed_cost_delta(self, n_pods):
        pods = mixed_pods(n_pods, gpu_fraction=0.0)
        tpu_r, _ = _solve(pods)
        oracle_r, _ = _solve(pods, force_oracle=True)
        assert tpu_r.all_pods_scheduled() and oracle_r.all_pods_scheduled()
        o_cost = oracle_r.total_price()
        delta = (tpu_r.total_price() - o_cost) / o_cost
        assert delta <= COST_DELTA_BOUND, f"cost delta {delta:.4f}"

    def test_constrained_cost_delta(self):
        pods = constrained_mix(1500)
        tpu_r, _ = _solve(pods)
        oracle_r, _ = _solve(pods, force_oracle=True)
        assert tpu_r.all_pods_scheduled() and oracle_r.all_pods_scheduled()
        o_cost = oracle_r.total_price()
        delta = (tpu_r.total_price() - o_cost) / o_cost
        assert delta <= COST_DELTA_BOUND, f"cost delta {delta:.4f}"
