"""Solver service seam tests: wire codec round-trip + gRPC solve parity."""

import pytest

from karpenter_tpu.api import resources as res
from karpenter_tpu.api.objects import NodeSelectorRequirement, Toleration
from karpenter_tpu.api.requirements import Operator, Requirement, Requirements
from karpenter_tpu.cloudprovider import corpus
from karpenter_tpu.kube import Client, TestClock
from karpenter_tpu.scheduling.scheduler import Scheduler
from karpenter_tpu.scheduling.topology import Topology
from karpenter_tpu.solver import wire
from karpenter_tpu.solver.service import RemoteSolver, serve

from helpers import make_nodepool, make_pod, make_pods, spread_constraint


class TestWireCodec:
    def test_pod_round_trip(self):
        pod = make_pod(
            cpu="2", memory="4Gi",
            labels={"app": "web"},
            node_selector={"zone": "a"},
            tolerations=[Toleration(key="gpu", operator="Exists")],
            spread=[spread_constraint("topology.kubernetes.io/zone",
                                      labels={"app": "web"})],
        )
        back = wire.from_wire(wire.to_wire(pod))
        assert back.uid == pod.uid
        assert back.spec.requests == pod.spec.requests
        assert back.spec.node_selector == pod.spec.node_selector
        assert back.spec.tolerations[0].key == "gpu"
        assert back.spec.topology_spread_constraints[0].topology_key == (
            "topology.kubernetes.io/zone")
        assert back.metadata.labels == {"app": "web"}

    def test_requirement_round_trip(self):
        for r in (
            Requirement("k", Operator.IN, ["a", "b"]),
            Requirement("k", Operator.NOT_IN, ["c"]),
            Requirement("k", Operator.EXISTS),
            Requirement("k", Operator.DOES_NOT_EXIST),
            Requirement("k", Operator.GT, ["5"]),
            Requirement("k", Operator.IN, ["a", "b", "c"], min_values=2),
        ):
            back = wire.from_wire(wire.to_wire(r))
            assert back == r, r

    def test_requirements_round_trip(self):
        reqs = Requirements(
            Requirement("a", Operator.IN, ["x"]),
            Requirement("b", Operator.NOT_IN, ["y"]),
        )
        back = wire.from_wire(wire.to_wire(reqs))
        assert back == reqs

    def test_nodepool_round_trip(self):
        pool = make_nodepool(
            name="p", weight=7, limits={"cpu": "100"},
            requirements=[NodeSelectorRequirement(
                "karpenter.sh/capacity-type", "In", ["on-demand"])],
        )
        back = wire.from_wire(wire.to_wire(pool))
        assert back.name == "p"
        assert back.spec.weight == 7
        assert back.spec.limits == {"cpu": res.parse_quantity("100")}
        assert back.spec.template.spec.requirements[0].values == ["on-demand"]

    def test_instance_type_round_trip(self):
        it = corpus.generate(3)[0]
        back = wire.from_wire(wire.to_wire(it))
        assert back.name == it.name
        assert back.capacity == it.capacity
        assert back.requirements == it.requirements
        assert len(back.offerings) == len(it.offerings)
        assert back.offerings[0].price == it.offerings[0].price
        assert back.allocatable() == it.allocatable()


@pytest.fixture(scope="module")
def sidecar():
    server = serve("127.0.0.1:0")
    yield f"127.0.0.1:{server._bound_port}"
    server.stop(0)


class TestSolverService:
    def _local_results(self, pods, pools, types):
        client = Client(TestClock())
        topology = Topology(client, [], pools, types, pods)
        return Scheduler(pools, types, topology).solve(pods)

    def test_remote_matches_local(self, sidecar):
        pools = [make_nodepool(name="default")]
        types = {"default": corpus.generate(12)}
        pods = make_pods(20, cpu="1", memory="2Gi")
        remote = RemoteSolver(sidecar, pools, types)
        got = remote.solve(pods)
        want = self._local_results(pods, pools, types)
        assert not got.pod_errors
        assert len(got.new_node_claims) == len(want.new_node_claims)
        got_counts = sorted(len(c.pods) for c in got.new_node_claims)
        want_counts = sorted(len(c.pods) for c in want.new_node_claims)
        assert got_counts == want_counts
        remote.close()

    def test_remote_claims_reference_local_objects(self, sidecar):
        pools = [make_nodepool(name="default")]
        types = {"default": corpus.generate(8)}
        pods = make_pods(5)
        remote = RemoteSolver(sidecar, pools, types)
        results = remote.solve(pods)
        local_types = set(map(id, types["default"]))
        for claim in results.new_node_claims:
            for it in claim.instance_type_options:
                assert id(it) in local_types  # reassembled, not copies
            for p in claim.pods:
                assert p in pods
        remote.close()

    def test_unschedulable_pod_error_travels(self, sidecar):
        pools = [make_nodepool(name="default")]
        types = {"default": corpus.generate(4)}
        giant = make_pod(cpu="10000")
        remote = RemoteSolver(sidecar, pools, types)
        results = remote.solve([giant])
        assert giant.uid in results.pod_errors
        assert not results.new_node_claims
        remote.close()

    def test_remote_matches_local_with_existing_nodes(self, sidecar):
        """RemoteSolver ≡ in-process TpuSolver on a NON-EMPTY cluster: the
        sidecar must pack onto shipped state nodes first (scheduler.go:
        357-425) instead of opening fresh claims for everything."""
        from karpenter_tpu.api import labels as labels_mod
        from karpenter_tpu.api.objects import Node, ObjectMeta
        from karpenter_tpu.controllers.state import StateNode
        from karpenter_tpu.solver import TpuSolver

        pools = [make_nodepool(name="default")]
        types = {"default": corpus.generate(12)}

        def build_state_nodes():
            sns = []
            for i in range(3):
                node = Node(
                    metadata=ObjectMeta(
                        name=f"existing-{i}",
                        labels={
                            labels_mod.TOPOLOGY_ZONE: "test-zone-a",
                            labels_mod.HOSTNAME: f"existing-{i}",
                            labels_mod.NODEPOOL_LABEL_KEY: "default",
                        },
                    ),
                )
                node.status.capacity = {
                    "cpu": res.parse_quantity("16"),
                    "memory": res.parse_quantity("64Gi"),
                    "pods": res.parse_quantity("110"),
                }
                node.status.allocatable = dict(node.status.capacity)
                node.status.ready = True
                sn = StateNode(node=node)
                # partially filled: a bound pod consumes half the cpu
                bound = make_pod(
                    cpu="8", memory="8Gi", node_name=f"existing-{i}",
                    phase="Running",
                )
                sn.update_pod(bound, is_daemon=False)
                sns.append(sn)
            return sns

        pods = make_pods(40, cpu="1", memory="1Gi")

        remote_sns = build_state_nodes()
        remote = RemoteSolver(sidecar, pools, types, state_nodes=remote_sns)
        got = remote.solve(pods)

        local_sns = build_state_nodes()
        client = Client(TestClock())
        for sn in local_sns:
            client.create(sn.node)
            for p in sn.pods:
                client.create(p)
        topology = Topology(client, local_sns, pools, types, pods)
        want = TpuSolver(
            pools, types, topology, state_nodes=local_sns
        ).solve(pods)

        assert not got.pod_errors and not want.pod_errors
        # existing nodes absorb pods before any claim opens, identically
        got_exist = sorted(
            (e.name, sorted(p.uid for p in e.pods))
            for e in got.existing_nodes
        )
        want_exist = sorted(
            (e.name, sorted(p.uid for p in e.pods))
            for e in want.existing_nodes
        )
        assert got_exist == want_exist
        assert any(pods_ for _, pods_ in got_exist), (
            "existing nodes took no pods — the remote seam dropped them"
        )
        assert len(got.new_node_claims) == len(want.new_node_claims)
        got_counts = sorted(len(c.pods) for c in got.new_node_claims)
        want_counts = sorted(len(c.pods) for c in want.new_node_claims)
        assert got_counts == want_counts
        remote.close()

    def test_remote_honors_csi_attach_limits(self, sidecar):
        """A node at its CSI attach limit must refuse volume-bearing pods
        remotely exactly as in-process: volume_usage travels with the state
        node and PVC/PV objects travel so the sidecar resolver answers
        identically (volumeusage.go exceedsLimits)."""
        from karpenter_tpu.api import labels as labels_mod
        from karpenter_tpu.api.objects import (
            Node, ObjectMeta, PersistentVolume, PersistentVolumeClaim,
            PersistentVolumeClaimRef,
        )
        from karpenter_tpu.controllers.state import StateNode
        from karpenter_tpu.scheduling.volumeusage import VolumeResolver
        from karpenter_tpu.solver import TpuSolver

        pools = [make_nodepool(name="default")]
        types = {"default": corpus.generate(12)}
        driver = "csi.example.com"

        def build():
            node = Node(
                metadata=ObjectMeta(
                    name="vol-node",
                    labels={
                        labels_mod.HOSTNAME: "vol-node",
                        labels_mod.NODEPOOL_LABEL_KEY: "default",
                    },
                ),
            )
            node.status.capacity = {
                "cpu": res.parse_quantity("32"),
                "memory": res.parse_quantity("64Gi"),
                "pods": res.parse_quantity("110"),
            }
            node.status.allocatable = dict(node.status.capacity)
            node.status.ready = True
            sn = StateNode(node=node)
            sn.volume_limits = {driver: 1}  # one attachment, already used
            bound = make_pod(
                cpu="1", node_name="vol-node", phase="Running",
                volumes=[PersistentVolumeClaimRef(claim_name="used")],
            )
            sn.update_pod(
                bound, is_daemon=False,
                resolved_volumes=[(driver, "pv-used", ())],
            )
            return sn

        pvc = PersistentVolumeClaim(
            metadata=ObjectMeta(name="fresh", namespace="default"),
            volume_name="pv-fresh",
        )
        pv = PersistentVolume(
            metadata=ObjectMeta(name="pv-fresh"), driver=driver
        )
        pod = make_pod(
            cpu="1",
            volumes=[PersistentVolumeClaimRef(claim_name="fresh")],
        )

        remote_sn = build()
        remote = RemoteSolver(
            sidecar, pools, types,
            state_nodes=[remote_sn], volume_objects=[pvc, pv],
        )
        got = remote.solve([pod])

        local_sn = build()
        client = Client(TestClock())
        client.create(local_sn.node)
        for p in local_sn.pods:
            client.create(p)
        client.create(pvc)
        client.create(pv)
        topology = Topology(client, [local_sn], pools, types, [pod])
        want = TpuSolver(
            pools, types, topology, state_nodes=[local_sn],
            volume_resolver=VolumeResolver(client),
        ).solve([pod])

        # the node is attach-limited: both paths must open a fresh claim
        # instead of placing onto it
        for res_ in (got, want):
            assert not res_.pod_errors
            assert len(res_.new_node_claims) == 1
            assert not any(e.pods for e in res_.existing_nodes)
        remote.close()

    def test_state_node_round_trip(self):
        from karpenter_tpu.api import labels as labels_mod
        from karpenter_tpu.api.objects import Node, ObjectMeta
        from karpenter_tpu.controllers.state import StateNode

        node = Node(
            metadata=ObjectMeta(
                name="sn-1",
                labels={labels_mod.HOSTNAME: "sn-1"},
            ),
        )
        node.status.capacity = {"cpu": res.parse_quantity("8")}
        node.status.allocatable = dict(node.status.capacity)
        node.status.ready = True
        sn = StateNode(node=node)
        daemon = make_pod(cpu="1", node_name="sn-1", phase="Running")
        workload = make_pod(
            cpu="2", node_name="sn-1", phase="Running", host_ports=(8080,)
        )
        sn.update_pod(daemon, is_daemon=True)
        sn.update_pod(workload, is_daemon=False)
        sn.volume_limits = {"csi.example.com": 16}
        sn.mark_for_deletion = True
        back = wire.decode_state_node(wire.encode_state_node(sn))
        assert back.name == "sn-1"
        assert back.labels() == sn.labels()
        assert back.available() == sn.available()
        assert sorted(p.uid for p in back.pods) == sorted(
            p.uid for p in sn.pods
        )
        assert set(back.daemonset_requests) == {daemon.uid}
        assert back.volume_limits == {"csi.example.com": 16}
        assert back.mark_for_deletion is True
        # host-port usage traveled: a new pod on the same port must conflict
        assert back.hostport_usage.conflicts(
            make_pod(host_ports=(8080,))
        )

    def test_constrained_pods(self, sidecar):
        pools = [make_nodepool(name="default")]
        types = {"default": corpus.generate(12)}
        pods = [
            make_pod(
                requirements=[NodeSelectorRequirement(
                    "topology.kubernetes.io/zone", "In", ["test-zone-a"])],
            )
            for _ in range(4)
        ]
        remote = RemoteSolver(sidecar, pools, types)
        results = remote.solve(pods)
        assert not results.pod_errors
        for claim in results.new_node_claims:
            zone_req = claim.requirements.get("topology.kubernetes.io/zone")
            assert zone_req.has("test-zone-a")
        remote.close()


class TestOperatorSidecarSplit:
    def test_controller_routes_solves_to_sidecar(self, sidecar, monkeypatch):
        """The deployable split (deploy/docker-compose.yml): an Operator
        configured with solver_address must ship its provisioning solves
        through RemoteSolver to the sidecar — and the pods still land."""
        from karpenter_tpu.cloudprovider.kwok import KwokCloudProvider
        from karpenter_tpu.operator import Operator, OperatorOptions
        from karpenter_tpu.sim import Binder
        from karpenter_tpu.solver.service import RemoteSolver

        calls = []
        orig = RemoteSolver.solve

        def spy(self, pods):
            calls.append(len(pods))
            return orig(self, pods)

        monkeypatch.setattr(RemoteSolver, "solve", spy)

        clock = TestClock()
        client = Client(clock)
        provider = KwokCloudProvider(client, corpus.generate(12))
        op = Operator(
            client, provider,
            OperatorOptions(solver_address=sidecar),
        )
        binder = Binder(client)
        client.create(make_nodepool(name="default"))
        for i in range(8):
            client.create(make_pod(name=f"split-{i}", cpu="1", memory="1Gi"))
        for _ in range(6):
            op.step(force_provision=True)
            binder.bind_all()
            clock.step(1)
        assert calls and sum(calls) >= 8, calls
        from karpenter_tpu.api.objects import Pod

        unbound = [p for p in client.list(Pod) if not p.spec.node_name]
        assert not unbound

    def test_options_env_fallback(self, monkeypatch):
        from karpenter_tpu.options import parse_options

        monkeypatch.setenv("KARPENTER_SOLVER_ADDRESS", "solver:50099")
        opts = parse_options([])
        assert opts.solver_address == "solver:50099"


class TestRemoteRobustness:
    """The gRPC seam's degradation ladder: deadline on every dispatch, one
    bounded retry on UNAVAILABLE/DEADLINE_EXCEEDED, then an in-process
    solve of the same shipped cluster view; the sidecar maps decode/solve
    failures to proper status codes instead of crashing the stream."""

    def _remote(self, sidecar, pods=None, **kw):
        pools = [make_nodepool(name="default")]
        types = {"default": corpus.generate(8)}
        return RemoteSolver(sidecar, pools, types, **kw), pools, types

    def test_config_deadline_used(self, sidecar):
        from karpenter_tpu.solver.driver import SolverConfig

        remote, _, _ = self._remote(
            sidecar, config=SolverConfig(solve_deadline=7.5)
        )
        assert remote.timeout == 7.5
        remote.close()

    def test_transient_unavailable_retried_once(self, sidecar):
        import grpc

        from karpenter_tpu import faults
        from karpenter_tpu.solver.service import InjectedRpcError

        remote, _, _ = self._remote(sidecar)
        faults.install(
            faults.FaultInjector(
                [
                    faults.FaultRule(
                        faults.REMOTE_SOLVE,
                        error=lambda: InjectedRpcError(
                            grpc.StatusCode.UNAVAILABLE
                        ),
                        times=1,
                    )
                ]
            )
        )
        try:
            results = remote.solve(make_pods(6, cpu="1", memory="1Gi"))
        finally:
            faults.uninstall()
        assert not results.pod_errors
        assert results.new_node_claims
        assert remote.fallback_solves == 0  # the retry reached the sidecar
        remote.close()

    def test_outage_falls_back_in_process(self, sidecar):
        import grpc

        from karpenter_tpu import faults
        from karpenter_tpu.solver.service import InjectedRpcError

        pods = make_pods(10, cpu="1", memory="2Gi")
        remote, pools, types = self._remote(sidecar)
        faults.install(
            faults.FaultInjector(
                [
                    faults.FaultRule(
                        faults.REMOTE_SOLVE,
                        error=lambda: InjectedRpcError(
                            grpc.StatusCode.DEADLINE_EXCEEDED
                        ),
                    )
                ]
            )
        )
        try:
            got = remote.solve(pods)
        finally:
            faults.uninstall()
        assert remote.fallback_solves == 1
        assert not got.pod_errors
        want = self._local_results_like(pods, pools, types)
        assert sorted(len(c.pods) for c in got.new_node_claims) == sorted(
            len(c.pods) for c in want.new_node_claims
        )
        remote.close()

    def _local_results_like(self, pods, pools, types):
        import copy

        client = Client(TestClock())
        pods = copy.deepcopy(pods)
        topology = Topology(client, [], pools, types, pods)
        return Scheduler(pools, types, topology).solve(pods)

    def test_fallback_does_not_bump_live_resource_versions(self, sidecar):
        import grpc

        from karpenter_tpu import faults
        from karpenter_tpu.solver.service import InjectedRpcError

        pods = make_pods(4)
        rv_before = [p.metadata.resource_version for p in pods]
        remote, _, _ = self._remote(sidecar)
        faults.install(
            faults.FaultInjector(
                [
                    faults.FaultRule(
                        faults.REMOTE_SOLVE,
                        error=lambda: InjectedRpcError(
                            grpc.StatusCode.UNAVAILABLE
                        ),
                    )
                ]
            )
        )
        try:
            remote.solve(pods)
        finally:
            faults.uninstall()
        assert [p.metadata.resource_version for p in pods] == rv_before
        remote.close()

    def test_non_retriable_status_propagates(self, sidecar):
        import grpc

        from karpenter_tpu import faults
        from karpenter_tpu.solver.service import InjectedRpcError

        remote, _, _ = self._remote(sidecar)
        faults.install(
            faults.FaultInjector(
                [
                    faults.FaultRule(
                        faults.REMOTE_SOLVE,
                        error=lambda: InjectedRpcError(
                            grpc.StatusCode.INVALID_ARGUMENT
                        ),
                    )
                ]
            )
        )
        try:
            with pytest.raises(grpc.RpcError):
                remote.solve(make_pods(2))
        finally:
            faults.uninstall()
        assert remote.fallback_solves == 0
        remote.close()

    def test_malformed_request_maps_to_invalid_argument(self, sidecar):
        import grpc

        from karpenter_tpu.solver.service import SOLVE_METHOD

        channel = grpc.insecure_channel(sidecar)
        call = channel.unary_unary(SOLVE_METHOD)
        with pytest.raises(grpc.RpcError) as exc_info:
            call(b"\x00not-msgpack-garbage", timeout=10.0)
        assert exc_info.value.code() == grpc.StatusCode.INVALID_ARGUMENT
        channel.close()

    def test_solve_crash_maps_to_internal(self, monkeypatch):
        import grpc

        from karpenter_tpu.solver import service as service_mod

        def boom(snap, config, encode_cache=None):
            raise RuntimeError("kernel exploded")

        monkeypatch.setattr(service_mod, "_solve_objects", boom)
        server = service_mod.serve("127.0.0.1:0")
        try:
            pools = [make_nodepool(name="default")]
            types = {"default": corpus.generate(4)}
            remote = RemoteSolver(
                f"127.0.0.1:{server._bound_port}", pools, types
            )
            with pytest.raises(grpc.RpcError) as exc_info:
                remote.solve(make_pods(2))
            assert exc_info.value.code() == grpc.StatusCode.INTERNAL
            remote.close()
        finally:
            server.stop(0)
