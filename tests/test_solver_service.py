"""Solver service seam tests: wire codec round-trip + gRPC solve parity."""

import pytest

from karpenter_tpu.api import resources as res
from karpenter_tpu.api.objects import NodeSelectorRequirement, Toleration
from karpenter_tpu.api.requirements import Operator, Requirement, Requirements
from karpenter_tpu.cloudprovider import corpus
from karpenter_tpu.kube import Client, TestClock
from karpenter_tpu.scheduling.scheduler import Scheduler
from karpenter_tpu.scheduling.topology import Topology
from karpenter_tpu.solver import wire
from karpenter_tpu.solver.service import RemoteSolver, serve

from helpers import make_nodepool, make_pod, make_pods, spread_constraint


class TestWireCodec:
    def test_pod_round_trip(self):
        pod = make_pod(
            cpu="2", memory="4Gi",
            labels={"app": "web"},
            node_selector={"zone": "a"},
            tolerations=[Toleration(key="gpu", operator="Exists")],
            spread=[spread_constraint("topology.kubernetes.io/zone",
                                      labels={"app": "web"})],
        )
        back = wire.from_wire(wire.to_wire(pod))
        assert back.uid == pod.uid
        assert back.spec.requests == pod.spec.requests
        assert back.spec.node_selector == pod.spec.node_selector
        assert back.spec.tolerations[0].key == "gpu"
        assert back.spec.topology_spread_constraints[0].topology_key == (
            "topology.kubernetes.io/zone")
        assert back.metadata.labels == {"app": "web"}

    def test_requirement_round_trip(self):
        for r in (
            Requirement("k", Operator.IN, ["a", "b"]),
            Requirement("k", Operator.NOT_IN, ["c"]),
            Requirement("k", Operator.EXISTS),
            Requirement("k", Operator.DOES_NOT_EXIST),
            Requirement("k", Operator.GT, ["5"]),
            Requirement("k", Operator.IN, ["a", "b", "c"], min_values=2),
        ):
            back = wire.from_wire(wire.to_wire(r))
            assert back == r, r

    def test_requirements_round_trip(self):
        reqs = Requirements(
            Requirement("a", Operator.IN, ["x"]),
            Requirement("b", Operator.NOT_IN, ["y"]),
        )
        back = wire.from_wire(wire.to_wire(reqs))
        assert back == reqs

    def test_nodepool_round_trip(self):
        pool = make_nodepool(
            name="p", weight=7, limits={"cpu": "100"},
            requirements=[NodeSelectorRequirement(
                "karpenter.sh/capacity-type", "In", ["on-demand"])],
        )
        back = wire.from_wire(wire.to_wire(pool))
        assert back.name == "p"
        assert back.spec.weight == 7
        assert back.spec.limits == {"cpu": res.parse_quantity("100")}
        assert back.spec.template.spec.requirements[0].values == ["on-demand"]

    def test_instance_type_round_trip(self):
        it = corpus.generate(3)[0]
        back = wire.from_wire(wire.to_wire(it))
        assert back.name == it.name
        assert back.capacity == it.capacity
        assert back.requirements == it.requirements
        assert len(back.offerings) == len(it.offerings)
        assert back.offerings[0].price == it.offerings[0].price
        assert back.allocatable() == it.allocatable()


@pytest.fixture(scope="module")
def sidecar():
    server = serve("127.0.0.1:0")
    yield f"127.0.0.1:{server._bound_port}"
    server.stop(0)


class TestSolverService:
    def _local_results(self, pods, pools, types):
        client = Client(TestClock())
        topology = Topology(client, [], pools, types, pods)
        return Scheduler(pools, types, topology).solve(pods)

    def test_remote_matches_local(self, sidecar):
        pools = [make_nodepool(name="default")]
        types = {"default": corpus.generate(12)}
        pods = make_pods(20, cpu="1", memory="2Gi")
        remote = RemoteSolver(sidecar, pools, types)
        got = remote.solve(pods)
        want = self._local_results(pods, pools, types)
        assert not got.pod_errors
        assert len(got.new_node_claims) == len(want.new_node_claims)
        got_counts = sorted(len(c.pods) for c in got.new_node_claims)
        want_counts = sorted(len(c.pods) for c in want.new_node_claims)
        assert got_counts == want_counts
        remote.close()

    def test_remote_claims_reference_local_objects(self, sidecar):
        pools = [make_nodepool(name="default")]
        types = {"default": corpus.generate(8)}
        pods = make_pods(5)
        remote = RemoteSolver(sidecar, pools, types)
        results = remote.solve(pods)
        local_types = set(map(id, types["default"]))
        for claim in results.new_node_claims:
            for it in claim.instance_type_options:
                assert id(it) in local_types  # reassembled, not copies
            for p in claim.pods:
                assert p in pods
        remote.close()

    def test_unschedulable_pod_error_travels(self, sidecar):
        pools = [make_nodepool(name="default")]
        types = {"default": corpus.generate(4)}
        giant = make_pod(cpu="10000")
        remote = RemoteSolver(sidecar, pools, types)
        results = remote.solve([giant])
        assert giant.uid in results.pod_errors
        assert not results.new_node_claims
        remote.close()

    def test_constrained_pods(self, sidecar):
        pools = [make_nodepool(name="default")]
        types = {"default": corpus.generate(12)}
        pods = [
            make_pod(
                requirements=[NodeSelectorRequirement(
                    "topology.kubernetes.io/zone", "In", ["test-zone-a"])],
            )
            for _ in range(4)
        ]
        remote = RemoteSolver(sidecar, pools, types)
        results = remote.solve(pods)
        assert not results.pod_errors
        for claim in results.new_node_claims:
            zone_req = claim.requirements.get("topology.kubernetes.io/zone")
            assert zone_req.has("test-zone-a")
        remote.close()
