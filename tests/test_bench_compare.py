"""bench.py --compare: the benchstat-analog regression gate.

The reference documents benchstat comparison as its perf workflow
(scheduling_benchmark_test.go:57-69); compare_grids() is the mechanical
equivalent over two bench_grid.json files, enforced in presubmit when a
previous same-platform grid exists.
"""

import json
import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from bench import compare_grids  # noqa: E402


def _grid(platform, entries):
    return {"platform": platform, "grid": entries}


def _entry(config, pods, types, best_ms):
    return {
        "config": config, "pods": pods, "types": types,
        "best_ms": best_ms, "pods_per_sec": pods / best_ms * 1000,
    }


def _write(tmp_path, name, grid):
    p = tmp_path / name
    p.write_text(json.dumps(grid))
    return str(p)


class TestCompareGrids:
    def test_no_regression_passes(self, tmp_path):
        old = _write(tmp_path, "old.json", _grid("tpu", [
            _entry("mixed", 5000, 400, 100.0),
            _entry("constrained", 50000, 800, 420.0),
        ]))
        new = _write(tmp_path, "new.json", _grid("tpu", [
            _entry("mixed", 5000, 400, 95.0),
            _entry("constrained", 50000, 800, 410.0),
        ]))
        assert compare_grids(old, new) == 0

    def test_regression_fails(self, tmp_path):
        old = _write(tmp_path, "old.json", _grid("tpu", [
            _entry("mixed", 5000, 400, 100.0),
        ]))
        new = _write(tmp_path, "new.json", _grid("tpu", [
            _entry("mixed", 5000, 400, 130.0),  # +30% > 20% bound
        ]))
        assert compare_grids(old, new) == 1

    def test_sub_noise_floor_not_enforced(self, tmp_path):
        # a 23 -> 29 ms swing is scheduler jitter, not a regression; the
        # floor keeps the gate meaningful for the configs that matter
        old = _write(tmp_path, "old.json", _grid("tpu", [
            _entry("mixed", 5000, 400, 23.0),
            _entry("constrained", 50000, 800, 420.0),
        ]))
        new = _write(tmp_path, "new.json", _grid("tpu", [
            _entry("mixed", 5000, 400, 29.0),  # +26% but sub-floor
            _entry("constrained", 50000, 800, 410.0),
        ]))
        assert compare_grids(old, new) == 0

    def test_noise_floor_crossing_enforced(self, tmp_path):
        # a config that grows THROUGH the floor is a real regression
        old = _write(tmp_path, "old.json", _grid("tpu", [
            _entry("mixed", 5000, 400, 90.0),
        ]))
        new = _write(tmp_path, "new.json", _grid("tpu", [
            _entry("mixed", 5000, 400, 150.0),
        ]))
        assert compare_grids(old, new) == 1

    def test_big_swing_under_floor_enforced(self, tmp_path):
        # a multi-x slowdown is enforced even when both sides sit under
        # the floor: the jitter exemption also bounds the absolute swing
        old = _write(tmp_path, "old.json", _grid("tpu", [
            _entry("mixed", 5000, 400, 20.0),
        ]))
        new = _write(tmp_path, "new.json", _grid("tpu", [
            _entry("mixed", 5000, 400, 95.0),
        ]))
        assert compare_grids(old, new) == 1

    def test_platform_mismatch_not_enforced(self, tmp_path):
        old = _write(tmp_path, "old.json", _grid("cpu", [
            _entry("mixed", 5000, 400, 100.0),
        ]))
        new = _write(tmp_path, "new.json", _grid("tpu", [
            _entry("mixed", 5000, 400, 500.0),
        ]))
        assert compare_grids(old, new) == 0

    def test_unmatched_configs_ignored(self, tmp_path):
        old = _write(tmp_path, "old.json", _grid("tpu", [
            _entry("mixed", 500, 400, 10.0),
        ]))
        new = _write(tmp_path, "new.json", _grid("tpu", [
            _entry("diverse-ref", 5000, 400, 100.0),
        ]))
        assert compare_grids(old, new) == 0

    def test_consolidation_rows_enforced(self, tmp_path):
        # the consolidation configs (keyed by nodes, not pods x types) are
        # first-class floor rows: a regression in the scenario-batched
        # search must trip the gate exactly like a solve-config regression
        def centry(config, nodes, best_ms):
            return {
                "config": config, "nodes": nodes, "best_ms": best_ms,
                "pods_per_sec": None, "probes": 21, "dispatches": 2,
            }

        old = _write(tmp_path, "old.json", _grid("cpu", [
            centry("consolidation", 2000, 300.0),
            centry("consolidation-single", 2000, 150.0),
        ]))
        new_ok = _write(tmp_path, "new_ok.json", _grid("cpu", [
            centry("consolidation", 2000, 310.0),
            centry("consolidation-single", 2000, 160.0),
        ]))
        assert compare_grids(old, new_ok) == 0
        new_bad = _write(tmp_path, "new_bad.json", _grid("cpu", [
            centry("consolidation", 2000, 450.0),  # +50% > 20% bound
            centry("consolidation-single", 2000, 150.0),
        ]))
        assert compare_grids(old, new_bad) == 1

    def test_churn_rows_enforced(self, tmp_path):
        # ISSUE 8's steady-state churn rows (warm reconcile under 1%/10%
        # pod churn) are first-class compare rows: a warm-path regression
        # (encode/transfer creeping back into best_ms) trips the gate
        def churn_entry(pct, best_ms, encode_ms, transfer_ms):
            return {
                "config": f"churn-{pct}pct", "pods": 5000, "types": 400,
                "best_ms": best_ms, "pods_per_sec": 5000 / best_ms * 1000,
                "encode_ms": encode_ms, "transfer_ms": transfer_ms,
                "delta_rows": 25, "cold_encode_ms": 14.0,
                "cold_transfer_ms": 5.0,
            }

        old = _write(tmp_path, "old.json", _grid("cpu", [
            churn_entry(1, 120.0, 2.0, 1.0),
            churn_entry(10, 130.0, 4.0, 1.0),
        ]))
        new_ok = _write(tmp_path, "new_ok.json", _grid("cpu", [
            churn_entry(1, 125.0, 2.1, 1.1),
            churn_entry(10, 128.0, 4.2, 0.9),
        ]))
        assert compare_grids(old, new_ok) == 0
        new_bad = _write(tmp_path, "new_bad.json", _grid("cpu", [
            churn_entry(1, 190.0, 40.0, 22.0),  # warm path gone cold
            churn_entry(10, 130.0, 4.0, 1.0),
        ]))
        assert compare_grids(old, new_bad) == 1

    def test_constraint_churn_rows_enforced(self, tmp_path):
        # ISSUE 10's constrained-workload churn rows (topology batches on
        # the delta/REUSE contract) are first-class compare rows too
        def centry(cfg, best_ms):
            return {
                "config": cfg, "pods": 5000, "types": 400,
                "best_ms": best_ms, "pods_per_sec": 5000 / best_ms * 1000,
                "delta_rows": 12, "full_encodes": 0,
                "repeat_reused": True, "fallback_solves": 0,
            }

        old = _write(tmp_path, "old.json", _grid("cpu", [
            centry("constrained-churn", 400.0),
            centry("diverse-churn", 900.0),
        ]))
        new_ok = _write(tmp_path, "new_ok.json", _grid("cpu", [
            centry("constrained-churn", 410.0),
            centry("diverse-churn", 880.0),
        ]))
        assert compare_grids(old, new_ok) == 0
        new_bad = _write(tmp_path, "new_bad.json", _grid("cpu", [
            centry("constrained-churn", 900.0),  # +125% > bound
            centry("diverse-churn", 900.0),
        ]))
        assert compare_grids(old, new_bad) == 1

    def test_twin_rows_enforced(self, tmp_path):
        # ISSUE 12's twin row: roster wall time per simulated minute is
        # the compare-gated number — a replay-loop regression (binder,
        # scenario.build gone cold, consolidation sweeping unbudgeted)
        # trips the gate like any solver regression
        def twin_entry(best_ms):
            return {
                "config": "twin", "nodes": 500, "pods": 5000,
                "minutes": 6, "best_ms": best_ms, "pods_per_sec": None,
                "solves_per_sec": 2.0, "worst_minute_p99_ms": 1500.0,
                "p99_margin_ms": 8500.0, "fallback_solves": 0,
                "slo_violations": 0,
            }

        old = _write(tmp_path, "old.json", _grid("cpu", [twin_entry(1200.0)]))
        new_ok = _write(
            tmp_path, "new_ok.json", _grid("cpu", [twin_entry(1280.0)])
        )
        assert compare_grids(old, new_ok) == 0
        new_bad = _write(
            tmp_path, "new_bad.json", _grid("cpu", [twin_entry(2400.0)])
        )
        assert compare_grids(old, new_bad) == 1

    def test_twin_row_live(self):
        """The twin bench row, live at a small shape: sustained decision
        traffic with zero fallbacks and zero SLO violations."""
        import bench

        row = bench.run_twin(60, minutes=3)
        assert row["config"] == "twin"
        assert row["decisions"] > 0
        assert row["fallback_solves"] == 0
        assert row["slo_violations"] == 0
        assert row["best_ms"] > 0

    def test_constraint_churn_zero_fallbacks_live(self):
        """The acceptance gate, live at a small shape: the constrained mix
        churns with ZERO sequential fallbacks, rides row deltas, and an
        unchanged re-solve hits the REUSE outcome — the topology batch is
        on the PR-8 contract."""
        import bench

        row = bench.run_constraint_churn(
            "constrained-churn", 600, n_types=20, ticks=2
        )
        assert row["fallback_solves"] == 0
        assert row["repeat_reused"] is True

    def test_mesh_rows_enforced(self, tmp_path):
        # ISSUE 14's weak-scaling mesh rows: keyed by device count too —
        # the 8-chip row regressing must trip the gate even when a
        # same-shape single-chip row is healthy
        def mesh_entry(devices, pods, best_ms):
            return {
                "config": "mesh-weak", "pods": pods, "types": 2000,
                "devices": devices, "mesh": f"1x{devices}x1",
                "best_ms": best_ms,
                "pods_per_sec": pods / best_ms * 1000,
                "pods_per_chip_per_sec": pods / best_ms * 1000 / devices,
                "fallback_solves": 0, "repeat_reused": True,
            }

        old = _write(tmp_path, "old.json", _grid("cpu", [
            mesh_entry(1, 62500, 4000.0),
            mesh_entry(8, 500000, 5000.0),
        ]))
        new_ok = _write(tmp_path, "new_ok.json", _grid("cpu", [
            mesh_entry(1, 62500, 4100.0),
            mesh_entry(8, 500000, 5200.0),
        ]))
        assert compare_grids(old, new_ok) == 0
        new_bad = _write(tmp_path, "new_bad.json", _grid("cpu", [
            mesh_entry(1, 62500, 4000.0),
            mesh_entry(8, 500000, 9000.0),
        ]))
        assert compare_grids(old, new_bad) == 1

    def test_mesh_rows_keyed_by_devices(self, tmp_path):
        # two rows identical but for the device count must compare
        # independently (the _entry_key devices dimension)
        rows_old = [
            {"config": "mesh-weak", "pods": 1000, "types": 10,
             "devices": 1, "best_ms": 400.0, "pods_per_sec": 2500.0},
            {"config": "mesh-weak", "pods": 1000, "types": 10,
             "devices": 8, "best_ms": 800.0, "pods_per_sec": 1250.0},
        ]
        rows_bad = [dict(rows_old[0]), dict(rows_old[1], best_ms=2000.0)]
        old = _write(tmp_path, "old.json", _grid("cpu", rows_old))
        bad = _write(tmp_path, "bad.json", _grid("cpu", rows_bad))
        assert compare_grids(old, bad) == 1

    def test_mesh_row_live(self):
        """The weak-scaling row, live at a small shape on the virtual
        mesh: decisions parity-pinned against single-device, zero
        fallbacks, warm REUSE mesh-resident."""
        import jax

        import bench

        if len(jax.devices()) < 8:
            pytest.skip("needs 8 virtual devices")
        rows = bench.run_mesh(
            n_pods=800, n_types=20, device_counts=(1, 8)
        )
        assert [r["devices"] for r in rows] == [1, 8]
        top = rows[-1]
        assert top["parity"] is True
        assert top["mesh"] == "1x8x1"
        assert all(r["fallback_solves"] == 0 for r in rows)
        assert all(r["repeat_reused"] for r in rows)
        assert all(r["pods_per_chip_per_sec"] > 0 for r in rows)

    def test_tenants_rows_enforced_and_keyed(self, tmp_path):
        # ISSUE 20's multi-tenant sustained-traffic rows: keyed by the
        # tenant count — the 4-tenant row regressing must trip the gate
        # even when the same-shape 2-tenant row is healthy
        def tenants_entry(tenants, best_ms):
            return {
                "config": "tenants", "tenants": tenants, "pods": 200,
                "types": 100, "best_ms": best_ms,
                "solves_per_sec": 1000.0 / best_ms,
                "p50_ms": best_ms * 1.2, "p99_ms": best_ms * 3,
                "noisy_delta_ms": 5.0,
                "fallback_solves": 0, "rejections": 0,
            }

        old = _write(tmp_path, "old.json", _grid("cpu", [
            tenants_entry(2, 400.0),
            tenants_entry(4, 700.0),
        ]))
        new_ok = _write(tmp_path, "new_ok.json", _grid("cpu", [
            tenants_entry(2, 420.0),
            tenants_entry(4, 730.0),
        ]))
        assert compare_grids(old, new_ok) == 0
        new_bad = _write(tmp_path, "new_bad.json", _grid("cpu", [
            tenants_entry(2, 400.0),
            tenants_entry(4, 1400.0),  # only the 4-tenant row regressed
        ]))
        assert compare_grids(old, new_bad) == 1

    def test_tenants_row_live(self):
        """The sustained-traffic row live at a tiny shape: two tenants,
        zero fallbacks, zero rejections, nobody degraded."""
        import bench

        entry = bench.run_tenants(2, n_pods=40, n_types=20, rounds=2)
        assert entry["tenants"] == 2
        assert entry["fallback_solves"] == 0
        assert entry["rejections"] == 0
        assert entry["degraded_tenants"] == 0
        assert entry["solves_per_sec"] > 0
        assert entry["p99_ms"] >= entry["p50_ms"] >= 0

    def test_cli_entrypoint(self, tmp_path):
        old = _write(tmp_path, "old.json", _grid("tpu", [
            _entry("mixed", 5000, 400, 100.0),
        ]))
        new = _write(tmp_path, "new.json", _grid("tpu", [
            _entry("mixed", 5000, 400, 99.0),
        ]))
        out = subprocess.run(
            [sys.executable, str(REPO / "bench.py"), "--compare", old, new],
            capture_output=True, text=True,
        )
        assert out.returncode == 0, out.stderr
        assert "mixed-5000x400" in out.stderr


class TestGroupShapeColumns:
    """ISSUE 13: every grid row carries the group-axis shape (groups,
    bucketed_groups, live_gt_pairs, antiaffinity_claims) and relaxation
    telemetry (relax_routed_fraction, residual_pods), and entries
    carrying the new columns ride the compare gate unchanged."""

    def _solver_pods(self, n=120):
        from karpenter_tpu.cloudprovider import corpus
        from karpenter_tpu.kube import Client, TestClock
        from karpenter_tpu.scheduling.topology import Topology
        from karpenter_tpu.solver import TpuSolver
        from karpenter_tpu.solver.example import example_nodepool
        from karpenter_tpu.solver.workloads import constrained_mix

        pods = constrained_mix(n, seed=3)
        pools = [example_nodepool()]
        its = {pools[0].name: corpus.generate(16)}
        topology = Topology(Client(TestClock()), [], pools, its, pods)
        return TpuSolver(pools, its, topology), pods

    def test_columns_present_and_bucketed(self):
        from bench import group_shape_columns

        solver, pods = self._solver_pods()
        cols = group_shape_columns(solver, pods)
        assert set(cols) == {
            "groups", "bucketed_groups", "live_gt_pairs",
            "antiaffinity_claims",
        }
        assert cols["groups"] > 0
        b = cols["bucketed_groups"]
        assert b >= cols["groups"] and (b & (b - 1)) == 0
        # constrained pods carry node selectors: live pairs must exist
        assert cols["live_gt_pairs"] > 0

    def test_empty_batch_zero_columns(self):
        from bench import group_shape_columns

        solver, _ = self._solver_pods()
        cols = group_shape_columns(solver, [])
        assert cols["groups"] == 0 and cols["live_gt_pairs"] == 0

    def test_compare_tolerates_new_columns(self, tmp_path):
        def wide(config, best_ms):
            e = _entry(config, 5000, 400, best_ms)
            e.update(
                groups=1897, bucketed_groups=2048, live_gt_pairs=64,
                antiaffinity_claims=1000, relax_routed_fraction=0.0,
                residual_pods=5000, relax_rejects=0,
            )
            return e

        old = _write(tmp_path, "old.json", _grid("tpu", [wide("diverse-ref", 100.0)]))
        new = _write(tmp_path, "new.json", _grid("tpu", [wide("diverse-ref", 101.0)]))
        assert compare_grids(old, new) == 0
        worse = _write(
            tmp_path, "worse.json", _grid("tpu", [wide("diverse-ref", 190.0)])
        )
        assert compare_grids(old, worse) == 1
