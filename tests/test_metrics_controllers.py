"""Per-object metrics controller tests (metrics/{node,nodepool,pod} shape)."""

import pytest

from karpenter_tpu.api.objects import Node, NodeClaim, NodePool
from karpenter_tpu.cloudprovider import corpus
from karpenter_tpu.cloudprovider.kwok import KwokCloudProvider
from karpenter_tpu.controllers.state import (
    CLUSTER_STATE_NODE_COUNT,
    CLUSTER_STATE_SYNCED,
)
from karpenter_tpu.controllers.metrics_controllers import (
    NODE_ALLOCATABLE,
    NODE_TOTAL_POD_REQUESTS,
    NODE_UTILIZATION,
    NODEPOOL_LIMIT,
    NODEPOOL_USAGE,
    POD_BOUND_DURATION,
    POD_PROV_BOUND_DURATION,
    POD_SCHEDULING_UNDECIDED_TIME,
    POD_STARTUP_DURATION,
    POD_STATE,
    POD_UNBOUND_TIME,
)
from karpenter_tpu.kube import Client, TestClock
from karpenter_tpu.operator import Operator
from karpenter_tpu.sim import Binder

from helpers import make_nodepool, make_pod, make_pods


@pytest.fixture
def env():
    clock = TestClock()
    client = Client(clock)
    provider = KwokCloudProvider(client, corpus.generate(20))
    operator = Operator(client, provider)
    binder = Binder(client)
    return clock, client, provider, operator, binder


def provision_cycle(env, n_steps=6):
    clock, client, provider, operator, binder = env
    for _ in range(n_steps):
        operator.step(force_provision=True)
        binder.bind_all()
        clock.step(1)


def _series(gauge, **labels):
    """All collected series whose labels include the given subset."""
    want = set(labels.items())
    return [
        (lbls, v)
        for kind, name, lbls, v in gauge.collect()
        if want.issubset(set(lbls.items()))
    ]


class TestNodeMetrics:
    def test_allocatable_and_requests_published(self, env):
        clock, client, provider, operator, binder = env
        client.create(make_nodepool())
        for p in make_pods(3, cpu="1", memory="2Gi"):
            client.create(p)
        provision_cycle(env)
        node = client.list(Node)[0]
        alloc = _series(NODE_ALLOCATABLE, node_name=node.name, resource_type="cpu")
        assert len(alloc) == 1
        assert alloc[0][1] > 0
        reqs = _series(
            NODE_TOTAL_POD_REQUESTS, node_name=node.name, resource_type="cpu")
        assert len(reqs) == 1
        assert reqs[0][1] == pytest.approx(3.0)  # 3 pods x 1 cpu

    def test_utilization_percent(self, env):
        clock, client, provider, operator, binder = env
        client.create(make_nodepool())
        client.create(make_pod(cpu="1"))
        provision_cycle(env)
        node = client.list(Node)[0]
        util = _series(NODE_UTILIZATION, node_name=node.name, resource_type="cpu")
        assert len(util) == 1
        cpu_alloc = _series(
            NODE_ALLOCATABLE, node_name=node.name, resource_type="cpu")[0][1]
        assert util[0][1] == pytest.approx(100.0 / cpu_alloc)

    def test_series_dropped_after_node_deleted(self, env):
        clock, client, provider, operator, binder = env
        client.create(make_nodepool())
        client.create(make_pod())
        provision_cycle(env)
        node = client.list(Node)[0]
        assert _series(NODE_ALLOCATABLE, node_name=node.name)
        for claim in client.list(NodeClaim):
            client.delete(claim)
        provision_cycle(env)
        assert not _series(NODE_ALLOCATABLE, node_name=node.name)

    def test_cluster_state_gauges(self, env):
        clock, client, provider, operator, binder = env
        client.create(make_nodepool())
        client.create(make_pod())
        provision_cycle(env)
        assert CLUSTER_STATE_NODE_COUNT.value() == 1.0
        assert CLUSTER_STATE_SYNCED.value() == 1.0


class TestNodePoolMetrics:
    def test_limit_and_usage(self, env):
        clock, client, provider, operator, binder = env
        client.create(make_nodepool(name="limited", limits={"cpu": "100"}))
        client.create(make_pod())
        provision_cycle(env)
        lim = _series(NODEPOOL_LIMIT, nodepool="limited", resource_type="cpu")
        assert lim and lim[0][1] == pytest.approx(100.0)
        usage = _series(NODEPOOL_USAGE, nodepool="limited", resource_type="cpu")
        assert usage and usage[0][1] > 0


class TestPodMetrics:
    def test_pod_state_phase(self, env):
        clock, client, provider, operator, binder = env
        client.create(make_nodepool())
        pod = make_pod()
        client.create(pod)
        operator.step(force_provision=True)
        states = _series(POD_STATE, name=pod.name)
        assert states and states[0][0]["phase"] == "Pending"
        provision_cycle(env)
        pod.status.phase = "Running"
        client.update(pod)
        operator.step()
        states = _series(POD_STATE, name=pod.name)
        assert states and states[0][0]["phase"] == "Running"

    def test_bound_and_startup_durations_observed(self, env):
        clock, client, provider, operator, binder = env
        client.create(make_nodepool())
        pod = make_pod()
        client.create(pod)
        before_bound = POD_BOUND_DURATION.count()
        before_start = POD_STARTUP_DURATION.count()
        provision_cycle(env)
        assert POD_BOUND_DURATION.count() == before_bound + 1
        pod.status.phase = "Running"
        client.update(pod)
        operator.step()
        assert POD_STARTUP_DURATION.count() == before_start + 1

    def test_unbound_time_while_pending(self, env):
        clock, client, provider, operator, binder = env
        # no nodepool: the pod can never schedule
        pod = make_pod()
        client.create(pod)
        clock.step(5)
        operator.step(force_provision=True)
        unbound = _series(POD_UNBOUND_TIME, name=pod.name)
        assert unbound and unbound[0][1] >= 5.0

    def test_pods_pending_before_restart_are_acked(self, env):
        clock, client, provider, operator, binder = env
        client.create(make_nodepool())
        pod = make_pod()
        client.create(pod)
        # a fresh operator (restart) never saw the pod's watch event
        operator2 = Operator(client, provider)
        operator2.step(force_provision=True)
        assert operator2.cluster.pod_ack_time(pod.uid) is not None

    def test_provisioning_latency_series(self, env):
        clock, client, provider, operator, binder = env
        client.create(make_nodepool())
        pod = make_pod()
        before = POD_PROV_BOUND_DURATION.count()
        client.create(pod)  # watch event ACKs the pod
        assert operator.cluster.pod_ack_time(pod.uid) is not None
        provision_cycle(env)
        assert POD_PROV_BOUND_DURATION.count() == before + 1
        # decision recorded -> undecided gauge has no series for this pod
        assert operator.cluster.pod_scheduling_success_time(pod.uid) is not None
        assert not _series(POD_SCHEDULING_UNDECIDED_TIME, name=pod.name)
