"""Operator binary tests: HTTP servers, corpus files, options wiring."""

import json
import urllib.request

import pytest

from karpenter_tpu.__main__ import build_operator, serve_health, serve_metrics
from karpenter_tpu.cloudprovider import corpus
from karpenter_tpu.kube import Client, TestClock
from karpenter_tpu.options import parse_options
from karpenter_tpu.sim import Binder

from helpers import make_nodepool, make_pod


def _get(port, path):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}", timeout=5) as r:
        return r.status, r.read().decode()


class TestCorpusFile:
    def test_round_trip(self, tmp_path):
        its = corpus.generate(8)
        path = str(tmp_path / "types.json")
        corpus.dump_file(path, its)
        back = corpus.load_file(path)
        assert [it.name for it in back] == [it.name for it in its]
        assert back[0].capacity == its[0].capacity
        assert back[0].offerings[0].price == its[0].offerings[0].price
        assert back[0].offerings[0].zone() == its[0].offerings[0].zone()

    def test_loaded_corpus_schedules(self, tmp_path):
        path = str(tmp_path / "types.json")
        corpus.dump_file(path, corpus.generate(10))
        opts = parse_options(["--instance-types-file-path", path])
        client = Client(TestClock())
        operator = build_operator(opts, client=client)
        binder = Binder(client)
        client.create(make_nodepool())
        pod = make_pod()
        client.create(pod)
        for _ in range(6):
            operator.step(force_provision=True)
            binder.bind_all()
            client.clock.step(1)
        assert pod.spec.node_name


class TestHTTPServers:
    def test_metrics_endpoint(self):
        server = serve_metrics(0)
        port = server.server_address[1]
        try:
            status, body = _get(port, "/metrics")
            assert status == 200
            assert "karpenter_tpu_" in body
            with pytest.raises(urllib.error.HTTPError):
                _get(port, "/other")
        finally:
            server.shutdown()

    def test_health_endpoints(self):
        client = Client(TestClock())
        operator = build_operator(parse_options([]), client=client)
        server = serve_health(0, operator)
        port = server.server_address[1]
        try:
            status, body = _get(port, "/healthz")
            assert status == 200 and body == "ok"
            # empty cluster state is synced
            status, _ = _get(port, "/readyz")
            assert status == 200
        finally:
            server.shutdown()


class TestOperatorWiring:
    def test_feature_gates_reach_controllers(self):
        opts = parse_options(
            ["--feature-gates", "NodeRepair=true,SpotToSpotConsolidation=true"]
        )
        operator = build_operator(opts, client=Client(TestClock()))
        assert operator.options.node_repair
        assert operator.disruption.ctx.spot_to_spot_enabled

    def test_default_corpus_size(self):
        operator = build_operator(parse_options([]), client=Client(TestClock()))
        pool = make_nodepool()
        assert len(operator.cloud_provider.get_instance_types(pool)) == 144
