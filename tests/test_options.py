"""Options/flag-system tests (pkg/operator/options/options_test.go shape)."""

import pytest

from karpenter_tpu.options import (
    FeatureGates,
    Options,
    parse_duration,
    parse_options,
)
from karpenter_tpu.operator import OperatorOptions


class TestParseDuration:
    def test_simple(self):
        assert parse_duration("10s") == 10.0
        assert parse_duration("1s") == 1.0
        assert parse_duration("100ms") == pytest.approx(0.1)

    def test_compound(self):
        assert parse_duration("1m30s") == 90.0
        assert parse_duration("1h1m1s") == 3661.0

    def test_fractional(self):
        assert parse_duration("1.5s") == 1.5

    def test_negative(self):
        assert parse_duration("-10s") == -10.0

    def test_invalid(self):
        for bad in ("", "10", "abc", "10x", "s10"):
            with pytest.raises(ValueError):
                parse_duration(bad)


class TestFeatureGates:
    def test_defaults_false(self):
        g = FeatureGates.parse("")
        assert not g.node_repair
        assert not g.reserved_capacity
        assert not g.spot_to_spot_consolidation

    def test_parse_all(self):
        g = FeatureGates.parse(
            "NodeRepair=true,ReservedCapacity=true,SpotToSpotConsolidation=true"
        )
        assert g.node_repair and g.reserved_capacity and g.spot_to_spot_consolidation

    def test_partial(self):
        g = FeatureGates.parse("SpotToSpotConsolidation=true")
        assert g.spot_to_spot_consolidation
        assert not g.node_repair

    def test_unknown_gate_tolerated(self):
        g = FeatureGates.parse("FutureGate=true,NodeRepair=true")
        assert g.node_repair

    def test_malformed(self):
        with pytest.raises(ValueError):
            FeatureGates.parse("NodeRepair")
        with pytest.raises(ValueError):
            FeatureGates.parse("NodeRepair=yes")


class TestOptions:
    def test_defaults(self):
        o = parse_options([])
        assert o.metrics_port == 8080
        assert o.health_probe_port == 8081
        assert o.kube_client_qps == 200
        assert o.kube_client_burst == 300
        assert o.batch_max_duration == 10.0
        assert o.batch_idle_duration == 1.0
        assert o.log_level == "info"
        assert not o.feature_gates.node_repair

    def test_flags_override(self):
        o = parse_options(
            [
                "--metrics-port", "9999",
                "--batch-max-duration", "30s",
                "--batch-idle-duration", "500ms",
                "--feature-gates", "NodeRepair=true",
                "--log-level", "debug",
            ]
        )
        assert o.metrics_port == 9999
        assert o.batch_max_duration == 30.0
        assert o.batch_idle_duration == pytest.approx(0.5)
        assert o.feature_gates.node_repair
        assert o.log_level == "debug"

    def test_env_fallback(self, monkeypatch):
        monkeypatch.setenv("METRICS_PORT", "7070")
        monkeypatch.setenv("BATCH_MAX_DURATION", "20s")
        monkeypatch.setenv("FEATURE_GATES", "SpotToSpotConsolidation=true")
        o = parse_options([])
        assert o.metrics_port == 7070
        assert o.batch_max_duration == 20.0
        assert o.feature_gates.spot_to_spot_consolidation

    def test_flag_beats_env(self, monkeypatch):
        monkeypatch.setenv("METRICS_PORT", "7070")
        o = parse_options(["--metrics-port", "6060"])
        assert o.metrics_port == 6060

    def test_invalid_log_level(self):
        with pytest.raises(ValueError):
            parse_options(["--log-level", "verbose"])

    def test_enable_profiling_bool_env(self, monkeypatch):
        monkeypatch.setenv("ENABLE_PROFILING", "true")
        assert parse_options([]).enable_profiling
        monkeypatch.setenv("ENABLE_PROFILING", "maybe")
        with pytest.raises(ValueError):
            parse_options([])


class TestOperatorOptionsBridge:
    def test_from_options(self):
        o = parse_options(
            [
                "--batch-max-duration", "5s",
                "--batch-idle-duration", "2s",
                "--feature-gates",
                "NodeRepair=true,ReservedCapacity=true,SpotToSpotConsolidation=true",
            ]
        )
        oo = OperatorOptions.from_options(o)
        assert oo.batch_max_duration == 5.0
        assert oo.batch_idle_duration == 2.0
        assert oo.node_repair
        assert oo.reserved_capacity
        assert oo.spot_to_spot_consolidation


class TestSolverFlags:
    def test_defaults(self):
        opts = parse_options([])
        assert opts.solver_backend == "tpu" and opts.solver_mesh == ""
        from karpenter_tpu.operator import OperatorOptions

        assert OperatorOptions.from_options(opts).solver_config is None

    def test_native_backend_flag(self):
        opts = parse_options(["--solver-backend", "native"])
        from karpenter_tpu.operator import OperatorOptions

        cfg = OperatorOptions.from_options(opts).solver_config
        assert cfg is not None and cfg.backend == "native"

    def test_mesh_auto_flag(self):
        opts = parse_options(["--solver-mesh", "auto"])
        from karpenter_tpu.operator import OperatorOptions

        cfg = OperatorOptions.from_options(opts).solver_config
        assert cfg is not None and cfg.mesh == "auto"

    def test_invalid_values_rejected(self):
        import pytest

        with pytest.raises(ValueError):
            parse_options(["--solver-backend", "gpu"])
        with pytest.raises(ValueError):
            parse_options(["--solver-mesh", "2x4"])
