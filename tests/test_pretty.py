"""ChangeMonitor (utils/pretty.py): the reference's log-noise gate
(pkg/utils/pretty/changemonitor.go)."""

from karpenter_tpu.kube import TestClock
from karpenter_tpu.utils.pretty import ChangeMonitor


class TestChangeMonitor:
    def test_first_observation_changes(self):
        cm = ChangeMonitor()
        assert cm.has_changed("k", "v")

    def test_same_value_suppressed(self):
        cm = ChangeMonitor()
        cm.has_changed("k", {"a": 1})
        assert not cm.has_changed("k", {"a": 1})

    def test_value_change_fires(self):
        cm = ChangeMonitor()
        cm.has_changed("k", {"a": 1})
        assert cm.has_changed("k", {"a": 2})
        assert not cm.has_changed("k", {"a": 2})

    def test_keys_independent(self):
        cm = ChangeMonitor()
        cm.has_changed("k1", "v")
        assert cm.has_changed("k2", "v")

    def test_dict_order_free(self):
        cm = ChangeMonitor()
        cm.has_changed("k", {"a": 1, "b": [1, 2]})
        assert not cm.has_changed("k", {"b": [1, 2], "a": 1})

    def test_ttl_readmits(self):
        # the 24h default re-admits a line so restarted log collection
        # still captures steady-state discoveries (changemonitor.go:28-31)
        clock = TestClock()
        cm = ChangeMonitor(ttl=100.0, clock=clock)
        cm.has_changed("k", "v")
        clock.step(50)
        assert not cm.has_changed("k", "v")
        clock.step(101)
        assert cm.has_changed("k", "v")
