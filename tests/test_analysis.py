"""Static-analysis tier: each pass must flag its seeded-bad fixture and
stay silent on the clean twin, the CLI must exit nonzero per violation
class, and the real tree must be clean (the presubmit contract)."""

import os
import subprocess
import sys

import pytest

from karpenter_tpu.analysis import blocking, locks, schema_drift, tracer
from karpenter_tpu.analysis.findings import (
    Finding,
    SourceFile,
    filter_suppressed,
    load_baseline,
    write_baseline,
)

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
FIXTURES = os.path.join(HERE, "analysis_fixtures")


def fixture(name: str) -> str:
    return os.path.join(FIXTURES, name)


def rules_of(findings):
    return {f.rule for f in findings}


class TestTracerPass:
    def test_bad_fixture_flags_every_rule(self):
        findings, _ = tracer.check_paths([fixture("bad_tracer.py")])
        assert rules_of(findings) == {"TRC101", "TRC102", "TRC103", "TRC104"}
        # both the @jax.jit decorator and solve_core* naming mark regions
        lines = {f.line for f in findings}
        assert len(findings) >= 8
        assert all(line > 0 for line in lines)

    def test_clean_fixture_silent(self):
        findings, _ = tracer.check_paths([fixture("good_tracer.py")])
        assert findings == []

    def test_real_kernels_clean(self):
        findings, _ = tracer.check_paths(
            [
                os.path.join(REPO, "karpenter_tpu", "ops"),
                os.path.join(REPO, "karpenter_tpu", "solver"),
            ]
        )
        assert findings == []

    def test_jit_wrapper_marks_function_traced(self, tmp_path):
        src = (
            "import jax\n"
            "def core(x):\n"
            "    if x > 0:\n"
            "        return x\n"
            "    return -x\n"
            "wrapped = jax.jit(core, static_argnames=())\n"
        )
        p = tmp_path / "wrapped.py"
        p.write_text(src)
        findings, _ = tracer.check_paths([str(p)])
        assert rules_of(findings) == {"TRC101"}

    def test_vmap_scenario_wrapper_is_traced(self, tmp_path):
        """The scenario axis (ops/solve.py) wraps the kernel in a vmapped
        closure jit-wrapped at module level; a traced branch inside the
        wrapper OR its closure must still be flagged (this pinned the
        coverage check done when the scenario axis landed)."""
        src = (
            "import jax\n"
            "import jax.numpy as jnp\n"
            "def scenarios_core(*args, **statics):\n"
            "    if args[0].sum() > 0:  # traced branch in the wrapper\n"
            "        pass\n"
            "    def one(*a):\n"
            "        if a[0] > 0:  # traced branch in the vmapped closure\n"
            "            return a[0]\n"
            "        return -a[0]\n"
            "    return jax.vmap(one, in_axes=(0,))(*args)\n"
            "wrapped = jax.jit(scenarios_core, static_argnames=())\n"
        )
        p = tmp_path / "scenario_wrapper.py"
        p.write_text(src)
        findings, _ = tracer.check_paths([str(p)])
        assert rules_of(findings) == {"TRC101"}
        assert len(findings) >= 2

    def test_untraced_host_code_not_flagged(self, tmp_path):
        src = (
            "import time\n"
            "def host_helper(values):\n"
            "    time.sleep(0.1)\n"
            "    return [float(v) for v in values if v > 0]\n"
        )
        p = tmp_path / "host.py"
        p.write_text(src)
        findings, _ = tracer.check_paths([str(p)])
        assert findings == []

    def test_unparsable_file_does_not_mask_other_findings(self, tmp_path):
        (tmp_path / "broken.py").write_text("def oops(:\n")
        (tmp_path / "bad.py").write_text(
            "import jax\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    if x > 0:\n"
            "        return x\n"
            "    return -x\n"
        )
        findings, _ = tracer.check_paths([str(tmp_path)])
        assert rules_of(findings) == {"TRC100", "TRC101"}


class TestLocksPass:
    def test_bad_fixture_flags_every_rule(self):
        findings, _ = locks.check_paths([fixture("bad_locks.py")])
        assert rules_of(findings) == {"LCK201", "LCK202", "LCK203"}

    def test_clean_fixture_silent(self):
        findings, _ = locks.check_paths([fixture("good_locks.py")])
        assert findings == []

    def test_real_store_layer_only_suppressed_sites(self):
        targets = [
            os.path.join(REPO, p)
            for p in (
                "karpenter_tpu/kube/store.py",
                "karpenter_tpu/kube/filestore.py",
                "karpenter_tpu/controllers/state.py",
                "karpenter_tpu/solver/driver.py",
                "karpenter_tpu/metrics/registry.py",
            )
        ]
        findings, sources = locks.check_paths(targets)
        # the two known callback sites are flagged AND inline-suppressed:
        # the pass sees them, the suppressions document why they're safe
        assert {f.rule for f in findings} <= {"LCK202"}
        assert filter_suppressed(findings, sources) == []

    def test_cross_class_cycle_through_annotations(self):
        findings, _ = locks.check_paths([fixture("bad_locks.py")])
        cycles = [f for f in findings if f.rule == "LCK201"]
        assert cycles, "ABBA cycle between Store and Index not detected"
        assert "Store._lock" in cycles[0].message
        assert "Index._lock" in cycles[0].message


class TestBlockingPass:
    def test_bad_fixture_flags_every_rule(self):
        findings, _ = blocking.check_paths([fixture("bad_blocking.py")])
        assert rules_of(findings) == {"BLK301", "BLK302", "BLK303"}
        # the dotted-import urlopen site must be among the BLK303 hits
        # (import_aliases: `import a.b` binds `a` -> `a`, not `a` -> `a.b`)
        blk303_lines = {f.line for f in findings if f.rule == "BLK303"}
        assert len(blk303_lines) == 2

    def test_clean_fixture_silent(self):
        findings, _ = blocking.check_paths([fixture("good_blocking.py")])
        assert findings == []

    def test_real_controllers_only_suppressed_sites(self):
        findings, sources = blocking.check_paths(
            [
                os.path.join(REPO, "karpenter_tpu", "controllers"),
                os.path.join(REPO, "karpenter_tpu", "__main__.py"),
            ]
        )
        # the wall-clock latency gauges carry inline suppressions; nothing
        # unsuppressed may remain (the __main__ sleep now goes via clock)
        assert filter_suppressed(findings, sources) == []
        assert not any(f.rule == "BLK301" for f in findings)


class TestSchemaDriftPass:
    def test_drifted_fixture_flags_all_three_shapes(self):
        findings, _ = schema_drift.check_schema(
            fixture("drift_schema.py"), fixture("drift_crds")
        )
        assert rules_of(findings) == {"SCH401", "SCH402", "SCH403"}
        messages = "\n".join(f.message for f in findings)
        assert "weight" in messages  # missing from YAML
        assert "bogus" in messages  # stale in YAML
        assert "consolidationPolicy" in messages  # enum truncated

    def test_real_artifacts_in_sync(self):
        findings, _ = schema_drift.check_schema(
            os.path.join(REPO, "karpenter_tpu", "api", "schema.py"),
            os.path.join(REPO, "karpenter_tpu", "api", "crds"),
        )
        assert findings == []

    def test_missing_artifact_reported(self, tmp_path):
        findings, _ = schema_drift.check_schema(
            fixture("drift_schema.py"), str(tmp_path)
        )
        assert "SCH404" in rules_of(findings)

    def test_module_level_schema_call_evaluates(self, tmp_path):
        # a module-level `X = some_schema()` routes through the function
        # memo during construction; must evaluate, not crash
        src = (
            "def nodepool_schema():\n"
            "    return {'kind': 'NodePoolSchema'}\n"
            "def nodeclaim_schema():\n"
            "    return {'kind': 'NodeClaimSchema'}\n"
            "CACHED = nodepool_schema()\n"
        )
        schema_py = tmp_path / "schema.py"
        schema_py.write_text(src)
        crds = tmp_path / "crds"
        crds.mkdir()
        (crds / "karpenter_tpu_nodepools.yaml").write_text(
            "kind: NodePoolSchema\n"
        )
        (crds / "karpenter_tpu_nodeclaims.yaml").write_text(
            "kind: NodeClaimSchema\n"
        )
        findings, _ = schema_drift.check_schema(str(schema_py), str(crds))
        assert findings == []


class TestSuppressions:
    def _finding(self, line, rule="TRC101", path="x.py"):
        return Finding(rule, "error", path, line, "msg")

    def test_inline_marker_suppresses_own_and_next_line(self):
        src = SourceFile(
            path="x.py",
            text=(
                "a = 1\n"
                "b = risky()  # analysis: ignore[TRC101] reason\n"
                "c = 3\n"
                "# analysis: ignore[TRC102]\n"
                "d = risky2()\n"
            ),
        )
        sources = {"x.py": src}
        kept = filter_suppressed(
            [
                self._finding(2),  # on the marker line
                self._finding(5, rule="TRC102"),  # line under a marker
                self._finding(1),  # out of any marker's reach
                self._finding(2, rule="LCK202"),  # marker names a different rule
            ],
            sources,
        )
        assert [(f.line, f.rule) for f in kept] == [(1, "TRC101"), (2, "LCK202")]

    def test_baseline_roundtrip(self, tmp_path):
        baseline_path = str(tmp_path / "baseline.txt")
        findings = [self._finding(10), self._finding(20, rule="BLK301")]
        write_baseline(baseline_path, findings)
        baseline = load_baseline(baseline_path)
        # line numbers don't participate: shifted findings still match
        shifted = [self._finding(11), self._finding(99, rule="BLK301")]
        assert filter_suppressed(shifted, {}, baseline) == []
        other = [self._finding(1, rule="SCH401")]
        assert filter_suppressed(other, {}, baseline) == other


class TestCli:
    """The acceptance contract: nonzero per seeded violation, zero on the
    final tree, runnable as `python -m karpenter_tpu.analysis`."""

    def _run(self, *args):
        return subprocess.run(
            [sys.executable, "-m", "karpenter_tpu.analysis", *args],
            capture_output=True,
            text=True,
            cwd=REPO,
        )

    @pytest.mark.parametrize(
        "pass_name,target",
        [
            ("tracer", "bad_tracer.py"),
            ("locks", "bad_locks.py"),
            ("blocking", "bad_blocking.py"),
        ],
    )
    def test_cli_nonzero_on_seeded_violation(self, pass_name, target):
        proc = self._run("--pass", pass_name, fixture(target))
        assert proc.returncode == 1, proc.stdout + proc.stderr
        assert "error[" in proc.stdout

    def test_cli_nonzero_on_schema_drift(self):
        proc = self._run(
            "--pass", "schema", fixture("drift_schema.py"),
            fixture("drift_crds"),
        )
        assert proc.returncode == 1, proc.stdout + proc.stderr
        assert "SCH4" in proc.stdout

    def test_cli_clean_on_final_tree(self):
        proc = self._run()
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "0 finding(s)" in proc.stderr

    def test_wrapper_clean_on_final_tree(self):
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "hack", "analyze.py")],
            capture_output=True,
            text=True,
            cwd="/",  # wrapper must work from any cwd
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
