"""Static-analysis tier: each pass must flag its seeded-bad fixture and
stay silent on the clean twin, the CLI must exit nonzero per violation
class, and the real tree must be clean (the presubmit contract)."""

import os
import subprocess
import sys

import pytest

from karpenter_tpu.analysis import (
    all_rules,
    args_registry,
    atomicity,
    blocking,
    clock,
    det,
    device,
    guarded,
    locks,
    obs,
    parity,
    retry,
    schema_drift,
    shapes,
    stale,
    tracer,
)
from karpenter_tpu.analysis.findings import (
    Finding,
    SourceFile,
    filter_suppressed,
    load_baseline,
    partition_findings,
    write_baseline,
)

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
FIXTURES = os.path.join(HERE, "analysis_fixtures")


def fixture(name: str) -> str:
    return os.path.join(FIXTURES, name)


def rules_of(findings):
    return {f.rule for f in findings}


class TestTracerPass:
    def test_bad_fixture_flags_every_rule(self):
        findings, _ = tracer.check_paths([fixture("bad_tracer.py")])
        assert rules_of(findings) == {"TRC101", "TRC102", "TRC103", "TRC104"}
        # both the @jax.jit decorator and solve_core* naming mark regions
        lines = {f.line for f in findings}
        assert len(findings) >= 8
        assert all(line > 0 for line in lines)

    def test_clean_fixture_silent(self):
        findings, _ = tracer.check_paths([fixture("good_tracer.py")])
        assert findings == []

    def test_real_kernels_clean(self):
        findings, _ = tracer.check_paths(
            [
                os.path.join(REPO, "karpenter_tpu", "ops"),
                os.path.join(REPO, "karpenter_tpu", "solver"),
            ]
        )
        assert findings == []

    def test_jit_wrapper_marks_function_traced(self, tmp_path):
        src = (
            "import jax\n"
            "def core(x):\n"
            "    if x > 0:\n"
            "        return x\n"
            "    return -x\n"
            "wrapped = jax.jit(core, static_argnames=())\n"
        )
        p = tmp_path / "wrapped.py"
        p.write_text(src)
        findings, _ = tracer.check_paths([str(p)])
        assert rules_of(findings) == {"TRC101"}

    def test_vmap_scenario_wrapper_is_traced(self, tmp_path):
        """The scenario axis (ops/solve.py) wraps the kernel in a vmapped
        closure jit-wrapped at module level; a traced branch inside the
        wrapper OR its closure must still be flagged (this pinned the
        coverage check done when the scenario axis landed)."""
        src = (
            "import jax\n"
            "import jax.numpy as jnp\n"
            "def scenarios_core(*args, **statics):\n"
            "    if args[0].sum() > 0:  # traced branch in the wrapper\n"
            "        pass\n"
            "    def one(*a):\n"
            "        if a[0] > 0:  # traced branch in the vmapped closure\n"
            "            return a[0]\n"
            "        return -a[0]\n"
            "    return jax.vmap(one, in_axes=(0,))(*args)\n"
            "wrapped = jax.jit(scenarios_core, static_argnames=())\n"
        )
        p = tmp_path / "scenario_wrapper.py"
        p.write_text(src)
        findings, _ = tracer.check_paths([str(p)])
        assert rules_of(findings) == {"TRC101"}
        assert len(findings) >= 2

    def test_untraced_host_code_not_flagged(self, tmp_path):
        src = (
            "import time\n"
            "def host_helper(values):\n"
            "    time.sleep(0.1)\n"
            "    return [float(v) for v in values if v > 0]\n"
        )
        p = tmp_path / "host.py"
        p.write_text(src)
        findings, _ = tracer.check_paths([str(p)])
        assert findings == []

    def test_unparsable_file_does_not_mask_other_findings(self, tmp_path):
        (tmp_path / "broken.py").write_text("def oops(:\n")
        (tmp_path / "bad.py").write_text(
            "import jax\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    if x > 0:\n"
            "        return x\n"
            "    return -x\n"
        )
        findings, _ = tracer.check_paths([str(tmp_path)])
        assert rules_of(findings) == {"TRC100", "TRC101"}


class TestLocksPass:
    def test_bad_fixture_flags_every_rule(self):
        findings, _ = locks.check_paths([fixture("bad_locks.py")])
        assert rules_of(findings) == {"LCK201", "LCK202", "LCK203"}

    def test_clean_fixture_silent(self):
        findings, _ = locks.check_paths([fixture("good_locks.py")])
        assert findings == []

    def test_real_store_layer_only_suppressed_sites(self):
        targets = [
            os.path.join(REPO, p)
            for p in (
                "karpenter_tpu/kube/store.py",
                "karpenter_tpu/kube/filestore.py",
                "karpenter_tpu/controllers/state.py",
                "karpenter_tpu/solver/driver.py",
                "karpenter_tpu/metrics/registry.py",
            )
        ]
        findings, sources = locks.check_paths(targets)
        # the two known callback sites are flagged AND inline-suppressed:
        # the pass sees them, the suppressions document why they're safe
        assert {f.rule for f in findings} <= {"LCK202"}
        assert filter_suppressed(findings, sources) == []

    def test_cross_class_cycle_through_annotations(self):
        findings, _ = locks.check_paths([fixture("bad_locks.py")])
        cycles = [f for f in findings if f.rule == "LCK201"]
        assert cycles, "ABBA cycle between Store and Index not detected"
        assert "Store._lock" in cycles[0].message
        assert "Index._lock" in cycles[0].message

    def test_real_threaded_tree_only_suppressed_sites(self):
        # the pass generalized tree-wide (ISSUE 19): the whole threaded
        # surface, not just the five store-layer files, carries nothing
        # but the documented inline-suppressed callback sites
        from karpenter_tpu.analysis.cli import _THREADED_TREE

        targets = [os.path.join(REPO, p) for p in _THREADED_TREE]
        findings, sources = locks.check_paths(targets)
        kept, _suppressed, _sanctioned = partition_findings(findings, sources)
        assert kept == [], [f.render() for f in kept]


class TestGuardedPass:
    """GRD13xx: per-class guarded-by inference with explicit thread
    roots — mixed guarded/lock-free access reachable from two sides,
    guarded mutable state escaping by reference, locking callbacks
    published from ``__init__``."""

    def test_bad_fixture_flags_every_rule(self):
        findings, _ = guarded.check_paths([fixture("bad_guarded.py")])
        assert sorted((f.rule, f.line) for f in findings) == [
            ("GRD1301", 22), ("GRD1302", 28), ("GRD1303", 35),
        ], [f.render() for f in findings]
        # the inferred guard is named in the mixed-access message
        mixed = next(f for f in findings if f.rule == "GRD1301")
        assert "_lock" in mixed.message and "_items" in mixed.message

    def test_clean_fixture_silent(self):
        findings, _ = guarded.check_paths([fixture("good_guarded.py")])
        assert findings == [], [f.render() for f in findings]

    def test_real_threaded_tree_single_sanctioned_site(self):
        """The dogfood contract: the whole threaded surface is clean save
        the ONE documented boundary — Cluster.__init__ registering its
        informer callback (the store notifies outside its own lock, so
        the callback taking Cluster._lock cannot deadlock; pinned
        dynamically by tests/test_races.py)."""
        from karpenter_tpu.analysis.cli import _THREADED_TREE

        targets = [os.path.join(REPO, p) for p in _THREADED_TREE]
        findings, sources = guarded.check_paths(targets)
        kept, suppressed, sanctioned = partition_findings(findings, sources)
        assert kept == [], [f.render() for f in kept]
        assert suppressed == []
        assert [f.rule for f in sanctioned] == ["GRD1303"]
        assert sanctioned[0].path.endswith("state.py")

    def test_private_helper_not_an_entry(self, tmp_path):
        # a private helper only ever reached from a locked public method
        # is NOT its own thread entry: walking it lock-free used to yield
        # a bogus unguarded access (the dogfood FP class)
        src = (
            "import threading\n"
            "class Box:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._items = []\n"
            "    def add(self, x):\n"
            "        with self._lock:\n"
            "            self._index(x)\n"
            "    def size(self):\n"
            "        with self._lock:\n"
            "            return len(self._items)\n"
            "    def _index(self, x):\n"
            "        self._items.append(x)\n"
        )
        p = tmp_path / "box.py"
        p.write_text(src)
        findings, _ = guarded.check_paths([str(p)])
        assert findings == [], [f.render() for f in findings]

    def test_thread_target_makes_private_method_an_entry(self, tmp_path):
        # ...but the SAME helper named as a Thread target is a root: its
        # lock-free writes now race the guarded public reads
        src = (
            "import threading\n"
            "class Box:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._items = []\n"
            "    def start(self):\n"
            "        threading.Thread(target=self._pump).start()\n"
            "    def size(self):\n"
            "        with self._lock:\n"
            "            return len(self._items)\n"
            "    def _pump(self):\n"
            "        self._items.append(1)\n"
        )
        p = tmp_path / "box.py"
        p.write_text(src)
        findings, _ = guarded.check_paths([str(p)])
        assert any(
            f.rule == "GRD1301" and "_items" in f.message for f in findings
        ), [f.render() for f in findings]

    def test_unparsable_file_reported(self, tmp_path):
        (tmp_path / "broken.py").write_text("def oops(:\n")
        findings, _ = guarded.check_paths([str(tmp_path)])
        assert rules_of(findings) == {"GRD1300"}


class TestAtomicityPass:
    """ATM14xx: check-then-act split across a lock release, and the
    cross-module lock-order cycles LCK201's module-local scan cannot
    connect."""

    def test_bad_fixtures_flag_every_rule(self):
        findings, _ = atomicity.check_paths(
            [fixture("bad_atomicity.py"), fixture("bad_atomicity_peer.py")]
        )
        assert rules_of(findings) == {"ATM1401", "ATM1402"}
        cta = next(f for f in findings if f.rule == "ATM1401")
        assert cta.line == 17 and cta.path.endswith("bad_atomicity.py")
        assert "_hint" in cta.message and "lost" in cta.message
        cyc = next(f for f in findings if f.rule == "ATM1402")
        assert "across modules" in cyc.message

    def test_cross_module_cycle_needs_both_halves(self):
        # scanning one module alone sees no cycle: the whole point of
        # hosting ATM1402 on the tree-wide call-graph core
        findings, _ = atomicity.check_paths([fixture("bad_atomicity.py")])
        assert "ATM1402" not in rules_of(findings)

    def test_clean_fixture_silent(self):
        findings, _ = atomicity.check_paths([fixture("good_atomicity.py")])
        assert findings == [], [f.render() for f in findings]

    def test_real_threaded_tree_clean(self):
        from karpenter_tpu.analysis.cli import _THREADED_TREE

        targets = [os.path.join(REPO, p) for p in _THREADED_TREE]
        findings, sources = atomicity.check_paths(targets)
        kept, _suppressed, _sanctioned = partition_findings(findings, sources)
        assert kept == [], [f.render() for f in kept]

    def test_rebound_local_severs_taint(self, tmp_path):
        # a local recomputed after the release no longer carries the
        # stale read: deciding on the fresh value is fine
        src = (
            "import threading\n"
            "class Slot:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._v = 0\n"
            "    def bump(self, n):\n"
            "        with self._lock:\n"
            "            cur = self._v\n"
            "        cur = n - 1\n"
            "        if n > cur:\n"
            "            with self._lock:\n"
            "                self._v = n\n"
        )
        p = tmp_path / "slot.py"
        p.write_text(src)
        findings, _ = atomicity.check_paths([str(p)])
        assert findings == [], [f.render() for f in findings]

    def test_unparsable_file_reported(self, tmp_path):
        (tmp_path / "broken.py").write_text("def oops(:\n")
        findings, _ = atomicity.check_paths([str(tmp_path)])
        assert rules_of(findings) == {"ATM1400"}


class TestBlockingPass:
    def test_bad_fixture_flags_every_rule(self):
        findings, _ = blocking.check_paths([fixture("bad_blocking.py")])
        assert rules_of(findings) == {"BLK301", "BLK302", "BLK303"}
        # the dotted-import urlopen site must be among the BLK303 hits
        # (import_aliases: `import a.b` binds `a` -> `a`, not `a` -> `a.b`)
        blk303_lines = {f.line for f in findings if f.rule == "BLK303"}
        assert len(blk303_lines) == 2

    def test_clean_fixture_silent(self):
        findings, _ = blocking.check_paths([fixture("good_blocking.py")])
        assert findings == []

    def test_real_controllers_only_suppressed_sites(self):
        findings, sources = blocking.check_paths(
            [
                os.path.join(REPO, "karpenter_tpu", "controllers"),
                os.path.join(REPO, "karpenter_tpu", "__main__.py"),
            ]
        )
        # the wall-clock latency gauges carry inline suppressions; nothing
        # unsuppressed may remain (the __main__ sleep now goes via clock)
        assert filter_suppressed(findings, sources) == []
        assert not any(f.rule == "BLK301" for f in findings)

    def test_sidecar_fixture_flags_every_rule(self):
        # the service/leader coverage extension rides on this seeded twin
        # of the sidecar's solve path and the lease loop
        findings, _ = blocking.check_paths(
            [fixture("bad_blocking_service.py")]
        )
        assert rules_of(findings) == {"BLK301", "BLK302", "BLK303"}

    def test_real_sidecar_and_leader_clean(self):
        # newly-covered targets (solver/service.py, kube/leader.py) must
        # stay on the injected clock / off-thread I/O
        findings, sources = blocking.check_paths(
            [
                os.path.join(REPO, "karpenter_tpu", "solver", "service.py"),
                os.path.join(REPO, "karpenter_tpu", "kube", "leader.py"),
            ]
        )
        assert filter_suppressed(findings, sources) == []


class TestSchemaDriftPass:
    def test_drifted_fixture_flags_all_three_shapes(self):
        findings, _ = schema_drift.check_schema(
            fixture("drift_schema.py"), fixture("drift_crds")
        )
        assert rules_of(findings) == {"SCH401", "SCH402", "SCH403"}
        messages = "\n".join(f.message for f in findings)
        assert "weight" in messages  # missing from YAML
        assert "bogus" in messages  # stale in YAML
        assert "consolidationPolicy" in messages  # enum truncated

    def test_real_artifacts_in_sync(self):
        findings, _ = schema_drift.check_schema(
            os.path.join(REPO, "karpenter_tpu", "api", "schema.py"),
            os.path.join(REPO, "karpenter_tpu", "api", "crds"),
        )
        assert findings == []

    def test_missing_artifact_reported(self, tmp_path):
        findings, _ = schema_drift.check_schema(
            fixture("drift_schema.py"), str(tmp_path)
        )
        assert "SCH404" in rules_of(findings)

    def test_module_level_schema_call_evaluates(self, tmp_path):
        # a module-level `X = some_schema()` routes through the function
        # memo during construction; must evaluate, not crash
        src = (
            "def nodepool_schema():\n"
            "    return {'kind': 'NodePoolSchema'}\n"
            "def nodeclaim_schema():\n"
            "    return {'kind': 'NodeClaimSchema'}\n"
            "CACHED = nodepool_schema()\n"
        )
        schema_py = tmp_path / "schema.py"
        schema_py.write_text(src)
        crds = tmp_path / "crds"
        crds.mkdir()
        (crds / "karpenter_tpu_nodepools.yaml").write_text(
            "kind: NodePoolSchema\n"
        )
        (crds / "karpenter_tpu_nodeclaims.yaml").write_text(
            "kind: NodeClaimSchema\n"
        )
        findings, _ = schema_drift.check_schema(str(schema_py), str(crds))
        assert findings == []


class TestParityPass:
    """PAR5xx: skeleton agreement between pack, pack_classed, and the C++
    core — anchors, constants, dtypes, tie-breaks, state inventory."""

    REAL_PY = os.path.join(REPO, "karpenter_tpu", "ops", "packing.py")
    REAL_CC = os.path.join(
        REPO, "karpenter_tpu", "native", "solve_core.cc"
    )

    def test_real_twins_in_sync(self):
        findings, _ = parity.check_parity(self.REAL_PY, self.REAL_CC)
        assert findings == []

    def test_real_skeletons_are_substantial(self):
        # guard against the pass going quiet by extracting nothing: the
        # real kernels must yield the full phase/const/dtype/tiebreak/state
        # skeleton (a regression here would mask every drift rule)
        import ast as ast_mod

        from karpenter_tpu.analysis.astutil import import_aliases, parse_file
        from karpenter_tpu.analysis.parity import (
            _extract_python_skeleton,
            _module_const_table,
            _state_class_fields,
        )

        src, tree = parse_file(self.REAL_PY)
        functions = {
            n.name: n for n in tree.body
            if isinstance(n, ast_mod.FunctionDef)
        }
        declared = _state_class_fields(tree, "PackState")
        assert len(declared) == 19
        for kname in ("pack", "pack_classed"):
            sk = _extract_python_skeleton(
                kname, self.REAL_PY, src, tree, functions[kname], functions,
                declared, import_aliases(tree), _module_const_table(tree),
            )
            # ISSUE 10 extended the skeleton: dense minValues counting
            # (before the tiers — the per-claim caps are computed from
            # pre-tier state) and the shared spread-counter carry update
            # (after fresh claims) are first-class phases in all three twins
            assert sk.phase_slugs() == [
                "min-values", "existing-nodes", "open-claims",
                "fresh-claims", "spread-counters",
            ]
            assert set(sk.consts) == {
                repr(2**28), repr(2**30), repr(1e-9), repr(0.5)
            }
            assert set(sk.dtypes) == {"float32", "int32", "bool"}
            assert set(sk.tiebreaks) == {
                "argmin", "argmax", "searchsorted", "cumsum"
            }
            assert set(sk.state_fields) == set(declared)

    def test_fixture_twins_in_sync(self):
        findings, _ = parity.check_parity(
            fixture("parity_twin.py"), fixture("parity_good.cc")
        )
        assert findings == []

    def test_seeded_bad_anchors_each_distinct_no_crash(self):
        findings, _ = parity.check_parity(
            fixture("parity_twin.py"), fixture("parity_bad.cc")
        )
        assert rules_of(findings) == {
            "PAR501", "PAR502", "PAR503", "PAR504", "PAR505", "PAR506"
        }
        messages = "\n".join(f.message for f in findings)
        # malformed anchors: empty arg, unevaluable expr, unknown kind
        malformed = [f for f in findings if f.rule == "PAR506"]
        assert len(malformed) == 3
        assert "no argument" in messages
        assert "banana" in messages
        assert "flavor" in messages
        # an anchor with no Python twin is directional, not a crash
        assert "has no twin in pack" in messages
        # a stale anchor after a rename names the missing field
        assert "c_oldname" in messages and "stale after a rename" in messages

    def test_constant_drift_in_one_twin_caught(self, tmp_path):
        """The acceptance contract: mutate ONE constant in ONE twin (a
        fixture copy of the real kernels) and the pass must flag it."""
        with open(self.REAL_PY, encoding="utf-8") as fh:
            text = fh.read()
        assert text.count("1e-9") >= 2  # one occurrence per Python twin
        mutated = text.replace("1e-9", "1e-6", 1)  # pack only
        py = tmp_path / "packing.py"
        py.write_text(mutated)
        with open(self.REAL_CC, encoding="utf-8") as fh:
            cc = tmp_path / "solve_core.cc"
            cc.write_text(fh.read())
        findings, _ = parity.check_parity(str(py), str(cc))
        drift = [f for f in findings if f.rule == "PAR502"]
        assert drift, "mutated constant produced no PAR502 finding"
        messages = "\n".join(f.message for f in drift)
        assert "1e-06" in messages  # the new value has no twin
        assert "1e-09" in messages  # the old value is now missing somewhere

    def test_missing_kernel_reported(self, tmp_path):
        py = tmp_path / "packing.py"
        py.write_text("class PackState:\n    pass\n")
        findings, _ = parity.check_parity(
            str(py), fixture("parity_good.cc")
        )
        assert "PAR500" in rules_of(findings)

    def test_cc_without_anchors_reported(self, tmp_path):
        cc = tmp_path / "core.cc"
        cc.write_text("// no anchors here\nint main() { return 0; }\n")
        findings, _ = parity.check_parity(fixture("parity_twin.py"), str(cc))
        assert any(
            f.rule == "PAR500" and "no '// parity:' anchors" in f.message
            for f in findings
        )

    def test_pathological_anchor_consts_become_findings(self, tmp_path):
        # arithmetic errors and huge exponents in anchor const expressions
        # are PAR506 findings, not analyzer crashes or hangs
        with open(fixture("parity_good.cc"), encoding="utf-8") as fh:
            text = fh.read()
        text += (
            "// parity: const 1/0\n"
            "// parity: const 10.0**400\n"
            "// parity: const 2**2**30\n"
        )
        cc = tmp_path / "core.cc"
        cc.write_text(text)
        findings, _ = parity.check_parity(fixture("parity_twin.py"), str(cc))
        assert rules_of(findings) == {"PAR506"}
        assert len(findings) == 3
        assert all("unevaluable" in f.message for f in findings)

    def test_cc_suppression_comment_honored(self, tmp_path):
        """`// analysis: ignore[PAR...]` next to a C++ anchor suppresses
        like the Python marker does."""
        with open(fixture("parity_good.cc"), encoding="utf-8") as fh:
            text = fh.read()
        text = text.replace(
            "// parity: const 0.25",
            "// analysis: ignore[PAR502] intentional fixed-point rescale\n"
            "// parity: const 0.125",
        )
        cc = tmp_path / "core.cc"
        cc.write_text(text)
        findings, sources = parity.check_parity(
            fixture("parity_twin.py"), str(cc)
        )
        kept = filter_suppressed(findings, sources)
        # the 0.125 anchor's "no twin" finding is suppressed inline; the
        # missing-0.25 direction (reported at the file head) remains
        assert all("0.125" not in f.message for f in kept)
        assert any("0.25" in f.message for f in kept)


class TestShapesPass:
    def test_bad_fixture_flags_every_rule(self):
        findings, _ = shapes.check_paths([fixture("bad_shapes.py")])
        assert rules_of(findings) == {
            "SHP601", "SHP602", "SHP603", "SHP604",
        }
        messages = "\n".join(f.message for f in findings)
        # the six seeded SHP601 shapes: operator join, where join,
        # einsum, transposed matmul contraction, misaligned segment ids,
        # and a segment_sum result joined against the pre-segment axis
        assert len([f for f in findings if f.rule == "SHP601"]) == 6
        assert "segment_sum" in messages
        assert "einsum" in messages
        assert "matmul contracts" in messages
        # widening via constructor, astype, join, and a positional
        # asarray dtype are all distinct hits
        assert len([f for f in findings if f.rule == "SHP602"]) == 4
        # the non-bucketed constructor dim and the reshape literal
        assert len([f for f in findings if f.rule == "SHP603"]) == 2
        assert "1000" in messages
        # the two seeded SHP604 shapes: an inline NamedSharding at a
        # device_put and a name-resolved spec at with_sharding_constraint
        assert len([f for f in findings if f.rule == "SHP604"]) == 2
        assert "pow2 shard padding" in messages or "power of two" in messages

    def test_clean_fixture_silent(self):
        findings, _ = shapes.check_paths([fixture("good_shapes.py")])
        assert findings == []

    def test_real_kernels_clean(self):
        findings, sources = shapes.check_paths(
            [
                os.path.join(REPO, "karpenter_tpu", "ops"),
                os.path.join(REPO, "karpenter_tpu", "solver"),
                os.path.join(REPO, "karpenter_tpu", "parallel"),
            ]
        )
        assert filter_suppressed(findings, sources) == []

    def test_unknown_rank_never_false_positives(self, tmp_path):
        # joining a tracked array against a value the interpreter lost
        # track of must stay silent (the poison-to-unknown rule)
        src = (
            "import jax.numpy as jnp\n"
            "def f(n, r, blob):\n"
            "    a = jnp.zeros((n, r), jnp.float32)\n"
            "    b = blob.some_method()\n"
            "    return a + b\n"
        )
        p = tmp_path / "unknown.py"
        p.write_text(src)
        findings, _ = shapes.check_paths([str(p)])
        assert findings == []

    def test_spec_rebind_through_tuple_poisons(self, tmp_path):
        # a tuple-unpacking reassignment of a name that held a
        # PartitionSpec must clear the tracked spec — checking a sharding
        # the name no longer holds would false-positive SHP604
        src = (
            "import jax\n"
            "import jax.numpy as jnp\n"
            "def f(mesh, m, build):\n"
            "    spec = jax.sharding.PartitionSpec('data')\n"
            "    spec, other = build()\n"
            "    row = jnp.zeros((m,), jnp.float32)\n"
            "    x = jnp.broadcast_to(row[None, :], (48, m))\n"
            "    return jax.device_put(x, spec)\n"
        )
        p = tmp_path / "rebind.py"
        p.write_text(src)
        findings, _ = shapes.check_paths([str(p)])
        assert findings == []

    def test_host_numpy_out_of_scope(self, tmp_path):
        # encode-time np.int64 index math is intentional host code
        src = (
            "import numpy as np\n"
            "def g(spans):\n"
            "    arr = np.asarray(spans, np.int64)\n"
            "    return arr.astype(np.float64)\n"
        )
        p = tmp_path / "host.py"
        p.write_text(src)
        findings, _ = shapes.check_paths([str(p)])
        assert findings == []

    def test_host_numpy_reshape_out_of_scope(self, tmp_path):
        # .reshape literal-dim checks gate on a jnp-tracked receiver,
        # same rationale as .astype: host index math is intentional
        src = (
            "import numpy as np\n"
            "def g(spans):\n"
            "    return np.asarray(spans, np.int64).reshape(5, 1000)\n"
        )
        p = tmp_path / "host_reshape.py"
        p.write_text(src)
        findings, _ = shapes.check_paths([str(p)])
        assert findings == []

    def test_branch_rebinding_never_false_positives(self, tmp_path):
        # a rebinding inside one branch is not a fact on the fall-through
        # path: `a` is still [n, r] when flag is False
        src = (
            "import jax.numpy as jnp\n"
            "def f(n, r, flag):\n"
            "    a = jnp.zeros((n, r), jnp.float32)\n"
            "    if flag:\n"
            "        a = a.T\n"
            "        return a.sum()\n"
            "    return a + jnp.zeros((n, r), jnp.float32)\n"
        )
        p = tmp_path / "branchy.py"
        p.write_text(src)
        findings, _ = shapes.check_paths([str(p)])
        assert findings == []

    def test_unparsable_file_reported(self, tmp_path):
        (tmp_path / "broken.py").write_text("def oops(:\n")
        findings, _ = shapes.check_paths([str(tmp_path)])
        assert rules_of(findings) == {"SHP600"}


class TestRetryPass:
    def test_bad_fixture_flags_every_rule(self):
        findings, _ = retry.check_paths([fixture("bad_retry.py")])
        assert rules_of(findings) == {"RTY701", "RTY702"}
        # the three swallow shapes (broad/bare/continue) and the extra
        # RTY701 inside the spinning loop's handler
        assert sum(1 for f in findings if f.rule == "RTY701") == 4
        assert sum(1 for f in findings if f.rule == "RTY702") == 2

    def test_clean_fixture_silent(self):
        findings, _ = retry.check_paths([fixture("good_retry.py")])
        assert findings == []

    def test_typed_catch_not_flagged(self, tmp_path):
        (tmp_path / "typed.py").write_text(
            "def f(x):\n"
            "    try:\n"
            "        x.go()\n"
            "    except KeyError:\n"
            "        pass\n"
        )
        findings, _ = retry.check_paths([str(tmp_path)])
        assert findings == []

    def test_unparsable_file_reported(self, tmp_path):
        (tmp_path / "broken.py").write_text("def oops(:\n")
        findings, _ = retry.check_paths([str(tmp_path)])
        assert rules_of(findings) == {"RTY700"}

    def test_real_tree_reconcile_paths_clean(self):
        """The dogfood contract: the roster + solver carry no swallowed
        broad excepts or unbounded retry loops (modulo the inline-
        suppressed capability probe in state.py)."""
        findings, sources = retry.check_paths(
            [
                os.path.join(REPO, "karpenter_tpu", "controllers"),
                os.path.join(REPO, "karpenter_tpu", "solver"),
                os.path.join(REPO, "karpenter_tpu", "operator.py"),
            ]
        )
        remaining = filter_suppressed(findings, sources)
        assert remaining == [], [f.render() for f in remaining]


class TestObsPass:
    def test_bad_fixture_flags_every_rule(self):
        findings, _ = obs.check_paths([fixture("bad_obs.py")])
        assert rules_of(findings) == {"OBS801", "OBS802"}
        # three leak shapes (dropped call, assigned-never-closed, module
        # helper) and three per-call metric constructions
        assert sum(1 for f in findings if f.rule == "OBS801") == 3
        assert sum(1 for f in findings if f.rule == "OBS802") == 3

    def test_clean_fixture_silent(self):
        findings, _ = obs.check_paths([fixture("good_obs.py")])
        assert findings == []

    def test_with_statement_and_factory_return_allowed(self, tmp_path):
        (tmp_path / "ok.py").write_text(
            "def f(t):\n"
            "    with t.span('a'):\n"
            "        pass\n"
            "def g(t):\n"
            "    return t.span('b')\n"
        )
        findings, _ = obs.check_paths([str(tmp_path)])
        assert findings == []

    def test_scoped_registry_exempt(self, tmp_path):
        (tmp_path / "scoped.py").write_text(
            "from karpenter_tpu.metrics import Counter, Registry\n"
            "def f():\n"
            "    return Counter('x', registry=Registry())\n"
        )
        findings, _ = obs.check_paths([str(tmp_path)])
        assert findings == []

    def test_unparsable_file_reported(self, tmp_path):
        (tmp_path / "broken.py").write_text("def oops(:\n")
        findings, _ = obs.check_paths([str(tmp_path)])
        assert rules_of(findings) == {"OBS800"}

    def test_real_tree_clean(self):
        """Dogfood: every span in the package is context-managed and every
        metric is module-scoped (or scoped-registry)."""
        findings, sources = obs.check_paths(
            [os.path.join(REPO, "karpenter_tpu")]
        )
        remaining = filter_suppressed(findings, sources)
        assert remaining == [], [f.render() for f in remaining]


class TestDataflowCore:
    """The shared CFG + forward-fixpoint engine every flow-shaped family
    rides (analysis/core/)."""

    def _envs(self, src, init_kinds=None):
        import ast as ast_mod

        from karpenter_tpu.analysis.core.cfg import build_cfg
        from karpenter_tpu.analysis.core.dataflow import Env, run_forward
        from karpenter_tpu.analysis.core.lattice import Lattice

        lattice = Lattice(top=2, default=0)
        tree = ast_mod.parse(src)
        fn = tree.body[0]
        cfg = build_cfg(fn.body)

        def transfer(atom, env):
            node = atom.node
            if atom.kind == "stmt" and isinstance(node, ast_mod.Assign):
                value = node.value
                kind = 0
                if isinstance(value, ast_mod.Name):
                    kind = env.get(value.id)
                elif isinstance(value, ast_mod.Constant):
                    kind = 0
                elif isinstance(value, ast_mod.Call):
                    kind = 2  # "interesting" origin for the test
                elif isinstance(value, ast_mod.BinOp):
                    kinds = [
                        env.get(n.id)
                        for n in (value.left, value.right)
                        if isinstance(n, ast_mod.Name)
                    ]
                    kind = max(kinds, default=0)
                for t in node.targets:
                    if isinstance(t, ast_mod.Name):
                        env.set(t.id, kind)

        init = Env(lattice, dict(init_kinds or {}))
        envs = run_forward(cfg, init, transfer)
        # env AFTER the whole function = join over terminal block exits;
        # approximate with the join over every block-entry env
        final = Env(lattice)
        for env in envs.values():
            final.join_from(env)
        for block in cfg.blocks:
            env = envs.get(block.id)
            if env is None:
                continue
            env = env.clone()
            for atom in block.atoms:
                transfer(atom, env)
            final.join_from(env)
        return final

    def test_branch_join_takes_the_max(self):
        final = self._envs(
            "def f(c):\n"
            "    if c:\n"
            "        x = origin()\n"
            "    else:\n"
            "        x = 1\n"
            "    y = x\n"
        )
        assert final.get("x") == 2  # interesting on SOME path -> joined up
        assert final.get("y") == 2

    def test_loop_carried_kind_reaches_fixpoint(self):
        # x becomes interesting on iteration 1; the back-edge must carry
        # it into iteration 2's view of the loop header
        final = self._envs(
            "def f(items):\n"
            "    x = 0\n"
            "    for i in items:\n"
            "        y = x\n"
            "        x = origin()\n"
        )
        assert final.get("x") == 2
        assert final.get("y") == 2  # only visible via the back-edge

    def test_except_edge_sees_partial_body(self):
        # the exception can fire after `x = origin()`, so the handler's
        # entry env must include that binding
        final = self._envs(
            "def f():\n"
            "    try:\n"
            "        x = origin()\n"
            "        x = 1\n"
            "    except Exception:\n"
            "        y = x\n"
        )
        assert final.get("y") == 2


class TestDevicePass:
    """DTX9xx: device values tracked from jnp/device_put/dispatch origins
    to host-sync sinks on the dataflow core."""

    REAL_TARGETS = [
        os.path.join(REPO, "karpenter_tpu", "ops"),
        os.path.join(REPO, "karpenter_tpu", "solver", "driver.py"),
        os.path.join(REPO, "karpenter_tpu", "solver", "residency.py"),
        os.path.join(REPO, "karpenter_tpu", "faults", "guard.py"),
    ]

    def test_bad_fixture_flags_every_rule(self):
        findings, _ = device.check_paths([fixture("bad_device_sync.py")])
        assert rules_of(findings) == {
            "DTX901", "DTX902", "DTX903", "DTX904", "DTX905", "DTX906",
        }
        # the interprocedural case: a same-module helper returning a jnp
        # result makes the call site a device value (line 62's branch)
        assert any(
            f.rule == "DTX901" and f.line == 62 for f in findings
        ), "helper-laundered device value not tracked"
        # the CFG-join case: both arms of the diamond bind device, so
        # the materialization after the merge still flags
        assert any(
            f.rule == "DTX902" and f.line == 78 for f in findings
        ), "device kind lost at the branch join"

    def test_clean_fixture_silent_with_sanctioned_boundary(self):
        findings, sources = device.check_paths(
            [fixture("good_device_sync.py")]
        )
        kept, suppressed, sanctioned = partition_findings(findings, sources)
        assert kept == [], [f.render() for f in kept]
        assert suppressed == []
        # the fixture's one device_get carries a sanction: emitted,
        # classified as a boundary, never gating
        assert [f.rule for f in sanctioned] == ["DTX906"]

    def test_poison_to_unknown_never_false_positives(self, tmp_path):
        # a device value joined with something untrackable must go
        # silent, not flag (the lattice property, not a special case)
        src = (
            "import jax.numpy as jnp\n"
            "def f(xs, blob):\n"
            "    v = jnp.sum(xs)\n"
            "    v = v + blob.read()\n"
            "    if v > 0:\n"
            "        return float(v)\n"
            "    return None\n"
        )
        p = tmp_path / "poison.py"
        p.write_text(src)
        findings, _ = device.check_paths([str(p)])
        assert findings == []

    def test_device_get_boundary_yields_host(self, tmp_path):
        # after the sanctioned readback the decode side is host numpy
        # and must be silent
        src = (
            "import numpy as np\n"
            "import jax\n"
            "import jax.numpy as jnp\n"
            "def f(xs):\n"
            "    out = jnp.sort(xs)\n"
            "    host = jax.device_get(out)  # analysis: sanctioned[DTX906] t\n"
            "    if host[0] > 0:\n"
            "        return np.asarray(host)\n"
            "    return float(host[0])\n"
        )
        p = tmp_path / "boundary.py"
        p.write_text(src)
        findings, sources = device.check_paths([str(p)])
        kept, _, sanctioned = partition_findings(findings, sources)
        assert kept == [], [f.render() for f in kept]
        assert len(sanctioned) == 1

    def test_real_solve_path_clean_with_single_blessed_readback(self):
        """The device-residency contract (PARITY.md): the ONLY
        device->host crossing in the solve path is driver.py's single
        sanctioned readback — the dispatch queue's drain point (plain,
        classed, scenario, AND sharded-mesh kernels all cross there).
        The delta-encode PR collapsed the former three per-path readbacks
        into the drain; the fleet-sharding PR routed the mesh path's own
        readback through the same queue, retiring its sanctioned site.
        Any further change goes through the documented contract-table
        workflow."""
        findings, sources = device.check_paths(self.REAL_TARGETS)
        kept, suppressed, sanctioned = partition_findings(findings, sources)
        assert kept == [], [f.render() for f in kept]
        assert len(sanctioned) == 1
        assert all(f.rule == "DTX906" for f in sanctioned)
        assert all(f.path.endswith("driver.py") for f in sanctioned)

    def test_resident_attr_bad_fixture_flags_between_solve_crossings(self):
        """The "no host crossing between solves" extension: dev_*/_dev*
        attribute loads are DEVICE-born, so a delta path laundering a
        resident buffer through np.asarray (or truthiness, iteration, an
        unsanctioned device_get) flags even though the carrying object
        is untracked."""
        findings, _ = device.check_paths(
            [fixture("bad_device_resident.py")]
        )
        assert rules_of(findings) == {
            "DTX901", "DTX903", "DTX904", "DTX906",
        }
        # the laundering shape from the contract: np.asarray on a
        # resident buffer between solves
        assert any(f.rule == "DTX903" for f in findings)

    def test_resident_attr_good_fixture_clean_with_sanctioned_drain(self):
        findings, sources = device.check_paths(
            [fixture("good_device_resident.py")]
        )
        kept, _, sanctioned = partition_findings(findings, sources)
        assert kept == [], [f.render() for f in kept]
        assert [f.rule for f in sanctioned] == ["DTX906"]

    def test_unparsable_file_reported(self, tmp_path):
        (tmp_path / "broken.py").write_text("def oops(:\n")
        findings, _ = device.check_paths([str(tmp_path)])
        assert rules_of(findings) == {"DTX900"}

    def test_module_level_sinks_flagged(self, tmp_path):
        # the pass covers module bodies too: a top-level device table
        # fed into host sinks must not slip past the residency contract
        (tmp_path / "toplevel.py").write_text(
            "import jax.numpy as jnp\n"
            "_TABLE = jnp.arange(8)\n"
            "_LIST = list(_TABLE)\n"
            "print(_TABLE)\n"
            "if _TABLE[0] > 0:\n"
            "    _X = float(_TABLE[0])\n"
        )
        findings, _ = device.check_paths([str(tmp_path)])
        assert rules_of(findings) == {
            "DTX901", "DTX902", "DTX904", "DTX905",
        }


class TestClockPass:
    """CLK10xx: every timestamp on the determinism surface flows from an
    injected clock or a documented RealClock seam."""

    REAL_TARGETS = [
        os.path.join(REPO, "karpenter_tpu", "controllers"),
        os.path.join(REPO, "karpenter_tpu", "faults"),
        os.path.join(REPO, "karpenter_tpu", "obs"),
        os.path.join(REPO, "karpenter_tpu", "solver"),
    ]

    def test_bad_fixture_flags_every_rule(self):
        findings, _ = clock.check_paths([fixture("bad_clock.py")])
        assert rules_of(findings) == {"CLK1001", "CLK1002"}
        # the dataflow case: `start = time.monotonic` then `start()` —
        # the call through the binding is a read (line 27)
        assert any(
            f.rule == "CLK1001" and f.line == 27 for f in findings
        ), "wall-clock read through a tracked binding not flagged"

    def test_clean_fixture_silent(self):
        findings, sources = clock.check_paths([fixture("good_clock.py")])
        kept, _, sanctioned = partition_findings(findings, sources)
        assert kept == [], [f.render() for f in kept]
        # the documented diagnostic boundary is sanctioned, not hidden
        assert [f.rule for f in sanctioned] == ["CLK1001"]

    def test_seam_classes_exempt(self, tmp_path):
        src = (
            "import time\n"
            "class RealClock:\n"
            "    def now(self):\n"
            "        return time.time()\n"
            "class NotASeam:\n"
            "    def now(self):\n"
            "        return time.time()\n"
        )
        p = tmp_path / "seams.py"
        p.write_text(src)
        findings, _ = clock.check_paths([str(p)])
        assert [(f.rule, f.line) for f in findings] == [("CLK1001", 7)]

    def test_injected_clock_silent(self, tmp_path):
        src = (
            "def reconcile(clock, store):\n"
            "    t0 = clock.now()\n"
            "    store.stamp(clock.now)\n"
            "    return clock.since(t0)\n"
        )
        p = tmp_path / "injected.py"
        p.write_text(src)
        findings, _ = clock.check_paths([str(p)])
        assert findings == []

    def test_real_determinism_surface_clean(self):
        """Controllers/faults/obs/solver carry no unsanctioned wall-clock
        reads: obs routes its fallbacks through the RealClock seam, the
        driver's audit durations ride obs.now(), and the wall-time
        diagnostics in controllers are sanctioned boundaries."""
        findings, sources = clock.check_paths(self.REAL_TARGETS)
        kept, suppressed, sanctioned = partition_findings(findings, sources)
        assert kept == [], [f.render() for f in kept]
        assert suppressed == []
        assert len(sanctioned) == 10
        assert {f.rule for f in sanctioned} == {"CLK1001"}

    def test_unparsable_file_reported(self, tmp_path):
        (tmp_path / "broken.py").write_text("def oops(:\n")
        findings, _ = clock.check_paths([str(tmp_path)])
        assert rules_of(findings) == {"CLK1000"}


class TestDataflowMigration:
    """The migration contract: re-hosting TRC/RTY on the dataflow core
    loses no findings on the fixture corpus. The expected sets below are
    the AST-walker generation's exact output, captured before the
    migration — a drift in either direction fails."""

    PRE_MIGRATION_TRACER = [
        ("TRC101", 13), ("TRC101", 15), ("TRC102", 23), ("TRC102", 24),
        ("TRC102", 37), ("TRC103", 30), ("TRC103", 31), ("TRC104", 38),
        ("TRC104", 40),
    ]
    PRE_MIGRATION_RETRY = [
        ("RTY701", 9), ("RTY701", 16), ("RTY701", 24), ("RTY701", 32),
        ("RTY702", 29), ("RTY702", 37),
    ]
    # the LCK migration (ISSUE 19: parse via the shared load_modules, the
    # cycle scan parameterized for ATM1402's cross-module half) pins the
    # MESSAGES too: detect_cycles' rendering is shared with ATM1402, so a
    # wording drift here would silently rewrite the LCK201 contract
    PRE_MIGRATION_LOCKS = [
        (
            "LCK201", 33,
            "lock-order cycle: bad_locks.py::Index._lock -> "
            "bad_locks.py::Store._lock -> bad_locks.py::Index._lock "
            "(ABBA deadlock; keep a single global acquisition order)",
        ),
        (
            "LCK202", 22,
            "callback 'handler(...)' invoked while holding "
            "bad_locks.py::Store._lock; release the lock before notifying",
        ),
        (
            "LCK203", 47,
            "non-reentrant lock bad_locks.py::Plain._lock re-acquired "
            "while already held",
        ),
    ]

    def test_tracer_fixture_identical_pre_post_migration(self):
        findings, _ = tracer.check_paths([fixture("bad_tracer.py")])
        assert sorted(
            (f.rule, f.line) for f in findings
        ) == self.PRE_MIGRATION_TRACER

    def test_retry_fixture_identical_pre_post_migration(self):
        findings, _ = retry.check_paths([fixture("bad_retry.py")])
        assert sorted(
            (f.rule, f.line) for f in findings
        ) == self.PRE_MIGRATION_RETRY

    def test_locks_fixture_identical_pre_post_migration(self):
        findings, _ = locks.check_paths([fixture("bad_locks.py")])
        assert sorted(
            (f.rule, f.line, f.message) for f in findings
        ) == self.PRE_MIGRATION_LOCKS
        clean, _ = locks.check_paths([fixture("good_locks.py")])
        assert clean == []

    def test_tracer_interprocedural_reach_through_helper(self, tmp_path):
        # what the migration BUYS: a helper returning a jnp result makes
        # the bare-name call site traced — invisible to the old walker
        src = (
            "import jax\n"
            "import jax.numpy as jnp\n"
            "def make_mask(x):\n"
            "    return jnp.where(x > 0, x, 0)\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    hidden = make_mask(x)\n"
            "    if hidden[0] > 0:\n"
            "        return hidden\n"
            "    return x\n"
        )
        p = tmp_path / "helper.py"
        p.write_text(src)
        findings, _ = tracer.check_paths([str(p)])
        assert any(f.rule == "TRC101" and f.line == 8 for f in findings)

    def test_retry_bound_reach_through_helper(self, tmp_path):
        # a loop whose handler path calls a same-module helper that
        # touches a Backoff is bounded — the old matcher flagged it
        src = (
            "def _pause(bk):\n"
            "    bk.backoff.sleep()\n"
            "def retry_loop(fn, bk):\n"
            "    while True:\n"
            "        try:\n"
            "            return fn()\n"
            "        except Exception:\n"
            "            _pause(bk)\n"
        )
        p = tmp_path / "reach.py"
        p.write_text(src)
        findings, _ = retry.check_paths([str(p)])
        assert not any(f.rule == "RTY702" for f in findings)


class TestSanctionDialect:
    """`# analysis: sanctioned[RULE]` is a documented boundary marker:
    classified apart from suppressions, honored by filter_suppressed."""

    def _finding(self, line, rule="DTX906", path="x.py"):
        return Finding(rule, "error", path, line, "msg")

    def test_partition_separates_the_channels(self):
        src = SourceFile(
            path="x.py",
            text=(
                "a = sync()  # analysis: sanctioned[DTX906] boundary\n"
                "pad = 0\n"
                "b = risky()  # analysis: ignore[DTX906] reason\n"
                "pad = 1\n"
                "c = plain()\n"
            ),
        )
        sources = {"x.py": src}
        kept, suppressed, sanctioned = partition_findings(
            [self._finding(1), self._finding(3), self._finding(5)],
            sources,
        )
        assert [f.line for f in kept] == [5]
        assert [f.line for f in suppressed] == [3]
        assert [f.line for f in sanctioned] == [1]

    def test_filter_suppressed_drops_both_dialects(self):
        src = SourceFile(
            path="x.py",
            text="a = sync()  # analysis: sanctioned[DTX906] boundary\n",
        )
        assert filter_suppressed([self._finding(1)], {"x.py": src}) == []

    def test_placeholder_rule_ids_are_not_markers(self):
        # docstrings write `ignore[RULE]`; a rule id without digits is a
        # placeholder, never a marker (the stale audit relies on this)
        src = SourceFile(
            path="x.py",
            text="# analysis: ignore[RULE] documentation example\n",
        )
        assert src.markers == []


class TestStaleAudit:
    """STALE001: suppressions/sanctions that no longer match anything."""

    def _finding(self, line, rule="TRC101", path="x.py"):
        return Finding(rule, "error", path, line, "msg")

    def test_stale_baseline_entry_flagged_and_prunable(self):
        baseline = {
            ("TRC101", "x.py", "msg"),  # live
            ("LCK202", "gone.py", "old message"),  # stale
        }
        findings, stale_entries = stale.audit(
            [self._finding(5)], {}, baseline, "hack/analysis_baseline.txt"
        )
        assert [f.rule for f in findings] == ["STALE001"]
        assert "LCK202" in findings[0].message
        assert stale_entries == {("LCK202", "gone.py", "old message")}

    def test_stale_and_live_inline_markers(self):
        src = SourceFile(
            path="x.py",
            text=(
                "a = risky()  # analysis: ignore[TRC101] live\n"
                "b = 2  # analysis: ignore[TRC102] stale\n"
                "c = sync()  # analysis: sanctioned[DTX906] live\n"
                "d = 4  # analysis: sanctioned[DTX906] stale\n"
            ),
        )
        produced = [
            self._finding(1, "TRC101"),
            self._finding(3, "DTX906"),
        ]
        findings, _ = stale.audit(
            produced, {"x.py": src}, None, "baseline.txt"
        )
        assert sorted((f.rule, f.line) for f in findings) == [
            ("STALE001", 2), ("STALE001", 4),
        ]

    def test_unscanned_file_rules_not_judged(self):
        # a BLK302 marker in a file the blocking pass never scanned must
        # not be called stale (accuracy gate)
        src = SourceFile(
            path="x.py",
            text="t = now()  # analysis: ignore[BLK302] wall gauge\n",
        )
        findings, _ = stale.audit(
            [], {"x.py": src}, None, "baseline.txt",
            scanned_by_rule={"BLK302": {"other.py"}},
        )
        assert findings == []
        # ...but when the pass DID scan the file, staleness is judged
        findings, _ = stale.audit(
            [], {"x.py": src}, None, "baseline.txt",
            scanned_by_rule={"BLK302": {"x.py"}},
        )
        assert [f.rule for f in findings] == ["STALE001"]


class TestRuleRegistry:
    """The meta-contract: every shipped rule id has at least one seeded-bad
    fixture. Parse-failure rules (x00) are seeded at runtime because a
    committed broken .py would fail presubmit's compileall step."""

    def test_registry_covers_every_pass(self):
        rules = all_rules()
        for prefix in (
            "TRC1", "LCK2", "BLK3", "SCH4", "PAR5", "SHP6", "RTY7", "OBS8",
            "DTX9", "CLK10", "DET11", "ARG12", "GRD13", "ATM14", "STALE",
        ):
            assert any(r.startswith(prefix) for r in rules), prefix

    def test_every_rule_has_seeded_bad_coverage(self, tmp_path):
        broken = tmp_path / "broken.py"
        broken.write_text("def oops(:\n")
        empty_crds = tmp_path / "no_crds"
        empty_crds.mkdir()

        produced = set()
        runs = [
            tracer.check_paths([fixture("bad_tracer.py"), str(broken)]),
            locks.check_paths([fixture("bad_locks.py"), str(broken)]),
            blocking.check_paths(
                [
                    fixture("bad_blocking.py"),
                    fixture("bad_blocking_service.py"),
                    str(broken),
                ]
            ),
            schema_drift.check_schema(
                fixture("drift_schema.py"), fixture("drift_crds")
            ),
            schema_drift.check_schema(str(broken), fixture("drift_crds")),
            schema_drift.check_schema(
                fixture("drift_schema.py"), str(empty_crds)
            ),
            parity.check_parity(
                fixture("parity_twin.py"), fixture("parity_bad.cc")
            ),
            parity.check_parity(str(broken), fixture("parity_good.cc")),
            shapes.check_paths([fixture("bad_shapes.py"), str(broken)]),
            retry.check_paths([fixture("bad_retry.py"), str(broken)]),
            obs.check_paths([fixture("bad_obs.py"), str(broken)]),
            device.check_paths(
                [fixture("bad_device_sync.py"), str(broken)]
            ),
            clock.check_paths([fixture("bad_clock.py"), str(broken)]),
            det.check_paths([fixture("bad_det.py"), str(broken)]),
            args_registry.check_paths([fixture("argreg_bad"), str(broken)]),
            guarded.check_paths([fixture("bad_guarded.py"), str(broken)]),
            atomicity.check_paths(
                [
                    fixture("bad_atomicity.py"),
                    fixture("bad_atomicity_peer.py"),
                    str(broken),
                ]
            ),
            # STALE001's seeded-bad shape is a marker matching nothing
            stale.audit(
                [],
                {
                    "stale_fixture.py": SourceFile(
                        path="stale_fixture.py",
                        text="x = 1  # analysis: ignore[TRC101] stale\n",
                    )
                },
                {("LCK202", "gone.py", "old")},
                "baseline.txt",
            )[:1] + ({},),
        ]
        for findings, _sources in runs:
            produced |= {f.rule for f in findings}
        missing = set(all_rules()) - produced
        assert not missing, (
            f"shipped rule(s) with no seeded-bad fixture: {sorted(missing)}"
        )


class TestSuppressions:
    def _finding(self, line, rule="TRC101", path="x.py"):
        return Finding(rule, "error", path, line, "msg")

    def test_inline_marker_suppresses_own_and_next_line(self):
        src = SourceFile(
            path="x.py",
            text=(
                "a = 1\n"
                "b = risky()  # analysis: ignore[TRC101] reason\n"
                "c = 3\n"
                "# analysis: ignore[TRC102]\n"
                "d = risky2()\n"
            ),
        )
        sources = {"x.py": src}
        kept = filter_suppressed(
            [
                self._finding(2),  # on the marker line
                self._finding(5, rule="TRC102"),  # line under a marker
                self._finding(1),  # out of any marker's reach
                self._finding(2, rule="LCK202"),  # marker names a different rule
            ],
            sources,
        )
        assert [(f.line, f.rule) for f in kept] == [(1, "TRC101"), (2, "LCK202")]

    def test_baseline_roundtrip(self, tmp_path):
        baseline_path = str(tmp_path / "baseline.txt")
        findings = [self._finding(10), self._finding(20, rule="BLK301")]
        write_baseline(baseline_path, findings)
        baseline = load_baseline(baseline_path)
        # line numbers don't participate: shifted findings still match
        shifted = [self._finding(11), self._finding(99, rule="BLK301")]
        assert filter_suppressed(shifted, {}, baseline) == []
        other = [self._finding(1, rule="SCH401")]
        assert filter_suppressed(other, {}, baseline) == other


class TestCli:
    """The acceptance contract: nonzero per seeded violation, zero on the
    final tree, runnable as `python -m karpenter_tpu.analysis`."""

    def _run(self, *args):
        return subprocess.run(
            [sys.executable, "-m", "karpenter_tpu.analysis", *args],
            capture_output=True,
            text=True,
            cwd=REPO,
        )

    @pytest.mark.parametrize(
        "pass_name,target",
        [
            ("tracer", "bad_tracer.py"),
            ("locks", "bad_locks.py"),
            ("blocking", "bad_blocking.py"),
            ("obs", "bad_obs.py"),
        ],
    )
    def test_cli_nonzero_on_seeded_violation(self, pass_name, target):
        proc = self._run("--pass", pass_name, fixture(target))
        assert proc.returncode == 1, proc.stdout + proc.stderr
        assert "error[" in proc.stdout

    def test_cli_nonzero_on_schema_drift(self):
        proc = self._run(
            "--pass", "schema", fixture("drift_schema.py"),
            fixture("drift_crds"),
        )
        assert proc.returncode == 1, proc.stdout + proc.stderr
        assert "SCH4" in proc.stdout

    def test_cli_nonzero_on_parity_drift(self):
        proc = self._run(
            "--pass", "parity", fixture("parity_twin.py"),
            fixture("parity_bad.cc"),
        )
        assert proc.returncode == 1, proc.stdout + proc.stderr
        assert "PAR5" in proc.stdout

    def test_cli_nonzero_on_shape_violations(self):
        proc = self._run("--pass", "shapes", fixture("bad_shapes.py"))
        assert proc.returncode == 1, proc.stdout + proc.stderr
        assert "SHP6" in proc.stdout

    def test_cli_clean_on_final_tree(self):
        proc = self._run()
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "0 finding(s)" in proc.stderr

    def test_sarif_output_on_seeded_violation(self):
        import json

        proc = self._run(
            "--format", "sarif", "--pass", "shapes", fixture("bad_shapes.py")
        )
        assert proc.returncode == 1, proc.stdout + proc.stderr
        doc = json.loads(proc.stdout)
        assert doc["version"] == "2.1.0"
        results = doc["runs"][0]["results"]
        assert {r["ruleId"] for r in results} == {
            "SHP601", "SHP602", "SHP603", "SHP604"
        }
        assert all(r["level"] == "error" for r in results)
        rule_ids = {r["id"] for r in doc["runs"][0]["tool"]["driver"]["rules"]}
        assert rule_ids == {"SHP601", "SHP602", "SHP603", "SHP604"}
        loc = results[0]["locations"][0]["physicalLocation"]
        assert loc["artifactLocation"]["uri"].endswith("bad_shapes.py")
        assert loc["region"]["startLine"] >= 1

    def test_sarif_clean_tree_empty_results(self):
        import json

        proc = self._run("--format", "sarif")
        assert proc.returncode == 0, proc.stdout + proc.stderr
        doc = json.loads(proc.stdout)
        assert doc["runs"][0]["results"] == []

    def test_write_baseline_workflow(self, tmp_path):
        """--write-baseline then --baseline is the designed grandfathering
        loop: seeded violations land in the file, a rerun against it is
        clean, and unrelated rules still gate."""
        baseline = tmp_path / "baseline.txt"
        proc = self._run(
            "--pass", "shapes", fixture("bad_shapes.py"),
            "--baseline", str(baseline), "--write-baseline",
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        text = baseline.read_text()
        assert "SHP601\t" in text and "SHP603\t" in text
        proc = self._run(
            "--pass", "shapes", fixture("bad_shapes.py"),
            "--baseline", str(baseline),
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "suppressed" in proc.stderr

    @pytest.mark.parametrize(
        "pass_name,target",
        [
            ("device", "bad_device_sync.py"),
            ("clock", "bad_clock.py"),
            ("guarded", "bad_guarded.py"),
            ("atomicity", "bad_atomicity.py"),
        ],
    )
    def test_cli_nonzero_on_new_families(self, pass_name, target):
        proc = self._run("--pass", pass_name, fixture(target))
        assert proc.returncode == 1, proc.stdout + proc.stderr
        assert "error[" in proc.stdout

    def test_changed_only_scopes_discovery(self):
        from karpenter_tpu.analysis.cli import PASS_TARGETS, _scope_targets

        changed = {
            os.path.join(REPO, "karpenter_tpu", "ops", "solve.py"),
        }
        tracer_targets = [
            os.path.join(REPO, t) for t in PASS_TARGETS["tracer"]
        ]
        scoped = _scope_targets("tracer", tracer_targets, changed)
        assert scoped == [
            os.path.join(REPO, "karpenter_tpu", "ops", "solve.py")
        ]
        # pair passes run when any half changed, not at all otherwise
        schema_targets = [
            os.path.join(REPO, t) for t in PASS_TARGETS["schema"]
        ]
        assert _scope_targets("schema", schema_targets, changed) == []
        assert _scope_targets(
            "schema", schema_targets,
            {os.path.join(REPO, "karpenter_tpu", "api", "schema.py")},
        ) == schema_targets

    def test_changed_only_cli_smoke(self):
        # fast lane over whatever the working tree has changed: must be
        # clean (same gate as the full run, smaller file set)
        proc = self._run("--changed-only")
        assert proc.returncode == 0, proc.stdout + proc.stderr

    def test_sarif_records_analyzer_runtime(self):
        import json

        proc = self._run(
            "--format", "sarif", "--pass", "clock", fixture("bad_clock.py")
        )
        doc = json.loads(proc.stdout)
        props = doc["runs"][0]["properties"]
        assert props["analysisSeconds"] >= 0
        assert "clock" in props["passSeconds"]

    def test_prune_baseline_drops_stale_entries(self, tmp_path):
        baseline = tmp_path / "baseline.txt"
        baseline.write_text(
            "LCK202\tgone.py\tnever produced anymore\n"
        )
        proc = self._run(
            "--prune-baseline", "--baseline", str(baseline)
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "pruned 1 stale baseline entry" in proc.stdout
        text = baseline.read_text()
        assert "gone.py" not in text

    def test_prune_baseline_rejects_partial_runs(self, tmp_path):
        # pruning on a partial finding set would silently drop live
        # entries; --no-baseline would truncate the whole file
        baseline = tmp_path / "baseline.txt"
        baseline.write_text("TRC101\tx.py\tlive entry\n")
        for extra in (["--no-baseline"], ["--pass", "tracer"]):
            proc = self._run(
                "--prune-baseline", "--baseline", str(baseline), *extra
            )
            assert proc.returncode == 2, proc.stdout + proc.stderr
            assert "prune-baseline" in proc.stderr
        assert "live entry" in baseline.read_text()  # untouched

    def test_full_run_flags_stale_inline_marker(self, tmp_path):
        # a stale marker committed into a scanned tree fails the full
        # run (the STALE001 gate presubmit's slow lane enforces)
        import shutil

        src_dir = tmp_path / "karpenter_tpu" / "controllers"
        src_dir.mkdir(parents=True)
        (tmp_path / "hack").mkdir()
        (src_dir / "__init__.py").write_text("")
        (src_dir / "thing.py").write_text(
            "def f(x):\n"
            "    return x  # analysis: ignore[BLK301] stale marker\n"
        )
        proc = subprocess.run(
            [sys.executable, "-m", "karpenter_tpu.analysis",
             "--root", str(tmp_path)],
            capture_output=True, text=True, cwd=REPO,
        )
        assert proc.returncode == 1, proc.stdout + proc.stderr
        assert "STALE001" in proc.stdout

    def test_wrapper_clean_on_final_tree(self):
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "hack", "analyze.py")],
            capture_output=True,
            text=True,
            cwd="/",  # wrapper must work from any cwd
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr


class TestDetPass:
    """DET11xx: unordered-source values must not reach order-sensitive
    sinks un-sorted on the determinism surface (the PYTHONHASHSEED
    interning class PR 14 fixed dynamically, closed statically)."""

    REAL_TARGETS = [
        os.path.join(REPO, "karpenter_tpu", "solver"),
        os.path.join(REPO, "karpenter_tpu", "ops"),
        os.path.join(REPO, "karpenter_tpu", "sim"),
        os.path.join(REPO, "karpenter_tpu", "obs"),
    ]

    def test_bad_fixture_flags_every_rule(self):
        findings, _ = det.check_paths([fixture("bad_det.py")])
        assert rules_of(findings) == {
            "DET1101", "DET1102", "DET1103", "DET1104"
        }
        # the call-graph case: a set born two helper hops away still
        # taints the consuming loop (line 40)
        assert any(
            f.rule == "DET1101" and f.line == 40 for f in findings
        ), "multi-hop unordered return not flagged"

    def test_clean_fixture_silent(self):
        findings, sources = det.check_paths([fixture("good_det.py")])
        kept, _, sanctioned = partition_findings(findings, sources)
        assert kept == [], [f.render() for f in kept]
        # the commutative-counting boundary is sanctioned, not hidden
        assert [f.rule for f in sanctioned] == ["DET1101"]

    def test_annotated_set_attribute_is_a_source(self, tmp_path):
        src = (
            "from typing import Set\n"
            "class Req:\n"
            "    def __init__(self, values):\n"
            "        self.values: Set[str] = set(values)\n"
            "def consume(r: Req):\n"
            "    return list(r.values)\n"
        )
        p = tmp_path / "attr.py"
        p.write_text(src)
        findings, _ = det.check_paths([str(p)])
        assert [(f.rule, f.line) for f in findings] == [("DET1102", 6)]

    def test_dict_views_ordered_unless_dict_born_unordered(self, tmp_path):
        # plain dicts are insertion-ordered (language guarantee since
        # 3.7); only a dict BUILT from an unordered source inherits its
        # hash order
        src = (
            'clean = {"a": 1, "b": 2}\n'
            "for k in clean:\n"
            "    print(k)\n"
            'pairs = {("a", 1), ("b", 2)}\n'
            "tainted = dict(pairs)\n"
            "for k in tainted:\n"
            "    print(k)\n"
        )
        p = tmp_path / "views.py"
        p.write_text(src)
        findings, _ = det.check_paths([str(p)])
        assert [(f.rule, f.line) for f in findings] == [("DET1101", 6)]

    def test_parameters_are_unknown_never_flagged(self, tmp_path):
        # poison-to-unknown: the pass only flags values whose unordered
        # origin it can SEE; an opaque parameter stays silent
        src = (
            "def f(maybe_set):\n"
            "    for v in maybe_set:\n"
            "        print(v)\n"
            "    return list(maybe_set)\n"
        )
        p = tmp_path / "params.py"
        p.write_text(src)
        findings, _ = det.check_paths([str(p)])
        assert findings == []

    def test_recursive_helpers_collapse_to_unknown(self, tmp_path):
        # a recursive helper cluster cannot vouch for what it returns:
        # SCC collapse pins it to UNKNOWN, which never flags
        src = (
            "def ping(n):\n"
            "    return pong(n - 1) if n else {1, 2}\n"
            "def pong(n):\n"
            "    return ping(n)\n"
            "def consume():\n"
            "    for v in ping(3):\n"
            "        print(v)\n"
        )
        p = tmp_path / "cycle.py"
        p.write_text(src)
        findings, _ = det.check_paths([str(p)])
        assert findings == []

    def test_real_determinism_surface_clean(self):
        """solver/ops/sim/obs carry no unsanctioned order-discipline
        findings: the PR 14 interning fix stays sorted, the demote-set
        materialization and host-count insertion are content-ordered,
        and the provably-commutative set loops are sanctioned."""
        findings, sources = det.check_paths(self.REAL_TARGETS)
        kept, suppressed, sanctioned = partition_findings(findings, sources)
        assert kept == [], [f.render() for f in kept]
        assert suppressed == []
        assert len(sanctioned) == 9
        assert {f.rule for f in sanctioned} == {"DET1101"}

    def test_unparsable_file_reported(self, tmp_path):
        (tmp_path / "broken.py").write_text("def oops(:\n")
        findings, _ = det.check_paths([str(tmp_path)])
        assert rules_of(findings) == {"DET1100"}


class TestArgsRegistryPass:
    """ARG12xx: the kernel-arg registry's hand-aligned surfaces diffed
    against SOLVE_ARG_NAMES."""

    REAL_TARGETS = [
        os.path.join(REPO, "karpenter_tpu", "solver", "encode.py"),
        os.path.join(REPO, "karpenter_tpu", "parallel", "mesh.py"),
        os.path.join(REPO, "karpenter_tpu", "solver", "residency.py"),
        os.path.join(REPO, "karpenter_tpu", "native", "__init__.py"),
        os.path.join(REPO, "karpenter_tpu", "ops", "solve.py"),
    ]

    def test_bad_twin_seeds_one_finding_per_rule(self):
        findings, _ = args_registry.check_paths([fixture("argreg_bad")])
        assert sorted(f.rule for f in findings) == [
            "ARG1201", "ARG1202", "ARG1203", "ARG1204"
        ]

    def test_clean_twin_silent(self):
        # also exercises the BASE + ("more",) scenario-tuple spelling
        findings, _ = args_registry.check_paths([fixture("argreg_good")])
        assert findings == [], [f.render() for f in findings]

    def test_real_registry_surfaces_consistent(self):
        findings, _ = args_registry.check_paths(self.REAL_TARGETS)
        assert findings == [], [f.render() for f in findings]

    def test_native_wrapper_missing_param(self, tmp_path):
        (tmp_path / "names.py").write_text(
            'SOLVE_ARG_NAMES = ("g_count", "g_req")\n'
        )
        (tmp_path / "native.py").write_text(
            "def solve_core_native(g_count, nmax=0):\n"
            "    return g_count\n"
        )
        findings, _ = args_registry.check_paths([str(tmp_path)])
        assert [f.rule for f in findings] == ["ARG1201"]
        assert "g_req" in findings[0].message

    def test_scenario_batching_unknown_arg(self, tmp_path):
        (tmp_path / "m.py").write_text(
            'SOLVE_ARG_NAMES = ("g_count",)\n'
            'SCENARIO_BATCHED_ARGS = ("g_count", "n_ghost")\n'
        )
        findings, _ = args_registry.check_paths([str(tmp_path)])
        assert [f.rule for f in findings] == ["ARG1201"]
        assert "n_ghost" in findings[0].message

    def test_no_authority_in_scope_stays_quiet(self, tmp_path):
        # a partial scan with no SOLVE_ARG_NAMES has nothing to diff
        # against; guessing would make --changed-only noisy
        (tmp_path / "residency.py").write_text(
            'GROUP_ARGS = frozenset({"g_req"})\n'
            'NO_ROW_DELTA = frozenset({"mystery"})\n'
        )
        findings, _ = args_registry.check_paths([str(tmp_path)])
        assert findings == []


class TestStaticMutations:
    """The mutation contract: revert a known determinism/registry fix in
    a scratch copy of the REAL module and the new passes must flag it —
    proof the rules guard the actual shipped code paths, not just
    hand-built fixtures."""

    def test_vocab_unsorted_interning_flagged(self, tmp_path):
        # PR 14's fix in the flesh: revert `sorted(r.values)` to bare
        # set iteration in a copy of solver/vocab.py -> DET1101
        src_path = os.path.join(
            REPO, "karpenter_tpu", "solver", "vocab.py"
        )
        with open(src_path, encoding="utf-8") as fh:
            text = fh.read()
        assert text.count("for v in sorted(r.values):") == 1
        mutated = text.replace(
            "for v in sorted(r.values):", "for v in r.values:"
        )
        p = tmp_path / "vocab.py"
        p.write_text(mutated)
        bad_line = next(
            i for i, line in enumerate(mutated.splitlines(), start=1)
            if line.strip() == "for v in r.values:"
        )
        findings, _ = det.check_paths([str(p)])
        assert any(
            f.rule == "DET1101" and f.line == bad_line for f in findings
        ), [f.render() for f in findings]

    def test_group_args_member_drop_flagged(self, tmp_path):
        # drop gk_g from residency.GROUP_ARGS in a copy: NO_ROW_DELTA
        # still claims it, so the delta classes are inconsistent
        src_path = os.path.join(
            REPO, "karpenter_tpu", "solver", "residency.py"
        )
        encode_path = os.path.join(
            REPO, "karpenter_tpu", "solver", "encode.py"
        )
        with open(src_path, encoding="utf-8") as fh:
            text = fh.read()
        # the GROUP_ARGS spelling ends the set literal with goff_idx and
        # a trailing comma; NO_ROW_DELTA's does not — mutate ONLY the
        # GROUP_ARGS occurrence
        assert text.count('"gk_g", "gk_k", "gk_w", "goff_idx",') == 1
        mutated = text.replace(
            '"gk_g", "gk_k", "gk_w", "goff_idx",',
            '"gk_k", "gk_w", "goff_idx",',
        )
        p = tmp_path / "residency.py"
        p.write_text(mutated)
        findings, _ = args_registry.check_paths([str(p), encode_path])
        assert any(
            f.rule == "ARG1203" and "gk_g" in f.message for f in findings
        ), [f.render() for f in findings]
        # and the unmutated pair is clean (the mutation is the signal)
        clean, _ = args_registry.check_paths([src_path, encode_path])
        assert clean == []

    def test_lock_deletion_in_real_audit_log_flagged(self, tmp_path):
        # delete ONE `with self._lock:` from a copy of the real AuditLog
        # (record()'s, the append path) and the guarded-by inference must
        # notice: _records/_seq stay guarded everywhere else, so the now
        # lock-free writes are exactly the GRD1301 mixed-access shape
        src_path = os.path.join(REPO, "karpenter_tpu", "obs", "audit.py")
        with open(src_path, encoding="utf-8") as fh:
            text = fh.read()
        anchor = (
            '        fields.setdefault("timestamp", self._now())\n'
            "        with self._lock:\n"
        )
        assert text.count(anchor) == 1
        mutated = text.replace(
            anchor,
            '        fields.setdefault("timestamp", self._now())\n'
            "        if True:\n",
        )
        p = tmp_path / "audit.py"
        p.write_text(mutated)
        findings, _ = guarded.check_paths([str(p)])
        flagged = {
            m for f in findings if f.rule == "GRD1301"
            for m in ("_records", "_seq") if m in f.message
        }
        assert flagged == {"_records", "_seq"}, [
            f.render() for f in findings
        ]
        # the unmutated module is clean (the deletion is the signal)
        clean, _ = guarded.check_paths([src_path])
        assert clean == [], [f.render() for f in clean]


class TestCallGraphCore:
    """The tentpole's core contract: bottom-up summary propagation over
    the module-set call graph, with recursion collapsed by SCC."""

    def _load(self, tmp_path, src):
        from karpenter_tpu.analysis.core.summaries import load_modules

        p = tmp_path / "m.py"
        p.write_text(src)
        modules, _, errors = load_modules([str(p)])
        assert not errors
        return str(p), modules

    def test_scc_members_pinned_to_default(self, tmp_path):
        from karpenter_tpu.analysis.core.summaries import (
            SummaryTable, build_call_graph,
        )

        path, modules = self._load(
            tmp_path,
            "def leaf():\n"
            "    return 1\n"
            "def mid():\n"
            "    return leaf()\n"
            "def top():\n"
            "    return mid()\n"
            "def r1():\n"
            "    return r2()\n"
            "def r2():\n"
            "    return r1()\n"
            "def selfie():\n"
            "    return selfie()\n",
        )
        graph = build_call_graph(modules)
        assert (path, "r1") in graph.cycle_members
        assert (path, "r2") in graph.cycle_members
        assert (path, "selfie") in graph.cycle_members
        assert (path, "top") not in graph.cycle_members
        assert (path, "mid") not in graph.cycle_members
        table = SummaryTable(default=0, graph=graph)
        # cycle members read the default WITHOUT running compute
        assert table.get((path, "r1"), lambda: 99) == 0
        assert table.get((path, "selfie"), lambda: 99) == 0

    def test_multi_hop_bottom_up_propagation(self, tmp_path):
        from karpenter_tpu.analysis.core.summaries import (
            SummaryTable, build_call_graph, resolve_local,
        )

        path, modules = self._load(
            tmp_path,
            "def leaf():\n"
            "    return 7\n"
            "def mid():\n"
            "    return leaf()\n"
            "def top():\n"
            "    return mid()\n",
        )
        table = SummaryTable(default=0, graph=build_call_graph(modules))
        mod = modules[path]

        def summarize(name):
            import ast

            fn = mod.index.functions[name]

            def compute():
                # a toy client: a function's summary is 1 if it returns
                # a constant, else whatever its bare-name callee summarizes
                for node in ast.walk(fn):
                    if isinstance(node, ast.Return):
                        if isinstance(node.value, ast.Constant):
                            return 1
                        if isinstance(node.value, ast.Call):
                            callee = node.value.func.id
                            hit = resolve_local(mod, callee, modules)
                            if hit is not None:
                                return summarize(callee)
                return 0

            return table.get((path, name), compute)

        # three hops: top -> mid -> leaf, driven entirely by compute
        # thunks recursing through the shared table
        assert summarize("top") == 1
        # and the intermediate results were memoized bottom-up
        assert table.get((path, "mid"), lambda: 99) == 1
        assert table.get((path, "leaf"), lambda: 99) == 1


class TestAnalyzerPerf:
    """The analyzer's own runtime is a guarded budget: presubmit's slow
    lane gives the full run 60 s of wall, and the SARIF run properties
    are the regression record."""

    def _sarif_run(self, *extra):
        import json

        proc = subprocess.run(
            [sys.executable, "-m", "karpenter_tpu.analysis",
             "--format", "sarif", *extra],
            capture_output=True, text=True, cwd=REPO,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        return json.loads(proc.stdout)["runs"][0]["properties"]

    def test_full_run_within_presubmit_wall_budget(self):
        props = self._sarif_run()
        assert props["analysisSeconds"] < 60, (
            "full analysis blew the presubmit 60s wall budget: "
            f"{props['analysisSeconds']}s"
        )
        # per-pass budget: no single pass may hog the lane (each is a
        # few seconds today; 20s means something superlinear landed)
        for name, seconds in props["passSeconds"].items():
            assert seconds < 20, f"pass {name} took {seconds}s (>20s budget)"
        assert props["sequentialSeconds"] >= max(
            props["passSeconds"].values()
        )

    def test_jobs_pool_runs_and_records(self):
        props = self._sarif_run("--pass", "det", "--pass", "args",
                                "--jobs", "2")
        assert props["jobs"] == 2
        assert set(props["passSeconds"]) == {"det", "args"}
        # sequential-equivalent wall is recorded alongside the actual
        # wall so the pool's effect is measurable per-artifact
        assert props["sequentialSeconds"] == round(
            sum(props["passSeconds"].values()), 4
        )

    def test_jobs_pool_covers_concurrency_passes(self):
        # the GRD/ATM passes ride the same worker pool and record their
        # per-pass wall in the SARIF run properties (the presubmit slow
        # lane's regression record)
        props = self._sarif_run("--pass", "guarded", "--pass", "atomicity",
                                "--jobs", "2")
        assert set(props["passSeconds"]) == {"guarded", "atomicity"}
        for seconds in props["passSeconds"].values():
            assert seconds < 20
