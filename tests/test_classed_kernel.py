"""Class-batched kernel (ops/packing.py:pack_classed) equivalence suite.

The classed kernel is a restructuring of the per-group scan — one scan step
per feasibility class, members placed by an inner loop over exactly the
same sequential semantics — so its outputs must be BIT-IDENTICAL to
pack()'s on every shape: same claims, same pod assignment, same instance
type options, same errors. These tests force both kernels over the same
batches (SolverConfig(classed=...)) and assert full Results equality.

The reference shape this kernel exists for is the 5-class diverse mix
(scheduling_benchmark_test.go:236-249), which fragments into ~1.9k groups
sharing ~30 feasibility classes; tests/test_solver_parity.py pins the
(shared) driver path against the host oracle, so equivalence here extends
the oracle-parity guarantee to the classed kernel.
"""

from __future__ import annotations

import pytest

from karpenter_tpu.api.objects import (
    LabelSelector, Pod, PodAffinityTerm, TopologySpreadConstraint,
)
from karpenter_tpu.api import labels as labels_mod
from karpenter_tpu.cloudprovider import corpus
from karpenter_tpu.kube import Client, TestClock
from karpenter_tpu.scheduling.topology import Topology
from karpenter_tpu.solver import TpuSolver
from karpenter_tpu.solver.driver import EncodeCache, SolverConfig
from karpenter_tpu.solver.example import example_nodepool
from karpenter_tpu.solver.workloads import (
    _pod, constrained_mix, diverse_reference_mix, mixed_pods, spot_od_pools,
)


def _make_solver(pods, classed=None, pools=None, n_types=30, state_nodes=()):
    pools = pools or [example_nodepool()]
    its = corpus.generate(n_types)
    its_by_pool = {p.name: list(its) for p in pools}
    topology = Topology(
        Client(TestClock()), list(state_nodes), pools, its_by_pool, pods
    )
    return TpuSolver(
        pools,
        its_by_pool,
        topology,
        state_nodes=list(state_nodes),
        config=SolverConfig(classed=classed),
        encode_cache=EncodeCache(),
    )


def _solve(pods, classed, pools=None, n_types=30, state_nodes=()):
    return _make_solver(
        pods, classed=classed, pools=pools, n_types=n_types,
        state_nodes=state_nodes,
    ).solve(pods)


def _signature(results):
    claims = sorted(
        (
            c.template.node_pool_name,
            tuple(sorted(p.metadata.name for p in c.pods)),
            tuple(sorted(it.name for it in c.instance_type_options)),
        )
        for c in results.new_node_claims
    )
    existing = sorted(
        (en.name, tuple(sorted(p.metadata.name for p in en.pods)))
        for en in results.existing_nodes
        if getattr(en, "pods", None)
    )
    return claims, existing, sorted(results.pod_errors)


def assert_equivalent(pods, pools=None, n_types=30, state_nodes=()):
    old = _solve(pods, False, pools=pools, n_types=n_types,
                 state_nodes=state_nodes)
    new = _solve(pods, True, pools=pools, n_types=n_types,
                 state_nodes=state_nodes)
    assert _signature(old) == _signature(new)
    assert old.node_count() == new.node_count()
    assert old.total_price() == pytest.approx(new.total_price())
    return new


class TestClassedEquivalence:
    def test_diverse_reference_mix(self):
        # the motivating shape: ~200 groups over ~30 classes at this size
        res = assert_equivalent(diverse_reference_mix(300), n_types=40)
        assert not res.pod_errors

    def test_diverse_mix_more_types(self):
        assert_equivalent(diverse_reference_mix(150), n_types=80)

    def test_constrained_mix(self):
        # ~1 group per class: classed path must still be exact when forced
        assert_equivalent(constrained_mix(400), n_types=40)

    def test_mixed_pods(self):
        assert_equivalent(mixed_pods(500), n_types=40)

    def test_spot_od_limits(self):
        # NodePool limits debit the shared ledger across class members
        assert_equivalent(mixed_pods(300), pools=spot_od_pools(), n_types=40)

    def test_identical_pods_single_class(self):
        pods = [_pod(f"p-{i}", 500, 512) for i in range(200)]
        res = assert_equivalent(pods)
        assert not res.pod_errors

    def test_hostname_anti_affinity_classes(self):
        # one shared TG spanning many request classes, cap 1 per claim
        lbl = {"app": "nginx"}
        pods = [
            _pod(
                f"anti-{i}", 100 + 100 * (i % 5), 256, labels=lbl,
                pod_anti_affinity=[
                    PodAffinityTerm(
                        topology_key=labels_mod.HOSTNAME,
                        label_selector=LabelSelector(match_labels=lbl),
                    )
                ],
            )
            for i in range(60)
        ]
        res = assert_equivalent(pods)
        assert res.node_count() == 60  # one node per pod
        assert not res.pod_errors

    def test_zonal_spread_same_class_different_selectors(self):
        # many spread owners sharing one feasibility class but different
        # selectors — the inner loop's per-member domain quotas
        pods = []
        for i in range(48):
            v = "abc"[i % 3]
            pods.append(
                _pod(
                    f"zs-{i}", 250, 256, labels={"grp": v},
                    topology_spread_constraints=[
                        TopologySpreadConstraint(
                            max_skew=1,
                            topology_key=labels_mod.TOPOLOGY_ZONE,
                            when_unsatisfiable="DoNotSchedule",
                            label_selector=LabelSelector(
                                match_labels={"grp": v}
                            ),
                        )
                    ],
                )
            )
        res = assert_equivalent(pods)
        assert not res.pod_errors

    def test_mixed_domain_axes_split_classes(self):
        """Zone-keyed AND capacity-type-keyed spread owners sharing one
        feasibility class: the class partition must SPLIT the run (the
        head's per-domain tables serve a single axis per class) and stay
        exact."""
        pods = []
        for i in range(20):
            v = "ab"[i % 2]
            pods.append(
                _pod(
                    f"zs-{i}", 500, 512, labels={"mx": v},
                    topology_spread_constraints=[
                        TopologySpreadConstraint(
                            max_skew=1,
                            topology_key=labels_mod.TOPOLOGY_ZONE,
                            when_unsatisfiable="DoNotSchedule",
                            label_selector=LabelSelector(
                                match_labels={"mx": v}
                            ),
                        )
                    ],
                )
            )
        for i in range(20):
            v = "cd"[i % 2]
            pods.append(
                _pod(
                    f"cs-{i}", 500, 512, labels={"mx": v},
                    topology_spread_constraints=[
                        TopologySpreadConstraint(
                            max_skew=1,
                            topology_key=labels_mod.CAPACITY_TYPE_LABEL_KEY,
                            when_unsatisfiable="DoNotSchedule",
                            label_selector=LabelSelector(
                                match_labels={"mx": v}
                            ),
                        )
                    ],
                )
            )
        res = assert_equivalent(pods, n_types=30)
        assert not res.pod_errors
        # and the partition REALLY split on the axis: one signature run,
        # two classes, one per domain key
        from karpenter_tpu.solver import encode as enc

        solver = _make_solver(pods, n_types=30)
        groups, rest = enc.partition_and_group(
            pods, topology=solver.oracle.topology
        )
        assert not rest
        templates = solver.oracle.templates
        snap = enc.encode(
            groups, templates,
            {t.node_pool_name: t.instance_type_options for t in templates},
            daemon_overhead=solver.oracle.daemon_overhead,
        )
        _cs, cl, cdyn, cdk, _inv, _lmax = enc.class_partition(snap)
        real = cl > 0
        assert int(real.sum()) == 2, (cl, cdk)
        assert sorted(cdk[real].tolist()) == [0, 1]  # zone axis + ct axis
        assert cdyn[real].all()

    def test_contributors_interleave_owners(self):
        # plain pods whose labels feed spread constraints owned by later
        # (same-class) groups: carries must evolve member-by-member
        pods = []
        for i in range(30):
            pods.append(_pod(f"c-{i}", 250, 256, labels={"team": "ab"[i % 2]}))
        for i in range(30):
            v = "ab"[i % 2]
            pods.append(
                _pod(
                    f"o-{i}", 250, 256, labels={"team": v},
                    topology_spread_constraints=[
                        TopologySpreadConstraint(
                            max_skew=1,
                            topology_key=labels_mod.HOSTNAME,
                            when_unsatisfiable="DoNotSchedule",
                            label_selector=LabelSelector(
                                match_labels={"team": v}
                            ),
                        )
                    ],
                )
            )
        assert_equivalent(pods)

    def test_zonal_self_affinity_classes(self):
        lbl = {"aff": "x"}
        pods = [
            _pod(
                f"aff-{i}", 100 + 100 * (i % 3), 256, labels=lbl,
                pod_affinity=[
                    PodAffinityTerm(
                        topology_key=labels_mod.TOPOLOGY_ZONE,
                        label_selector=LabelSelector(match_labels=lbl),
                    )
                ],
            )
            for i in range(30)
        ]
        res = assert_equivalent(pods)
        assert not res.pod_errors

    def test_existing_nodes_prefix_fill(self):
        from tests.helpers import make_state_node

        pods = diverse_reference_mix(120)
        nodes = [
            make_state_node(name=f"exists-{i}", cpu="8", memory="16Gi",
                            zone="test-zone-" + "abc"[i % 3])
            for i in range(4)
        ]
        assert_equivalent(pods, state_nodes=nodes, n_types=30)

    def test_overflow_retry_path(self):
        # tiny NMAX forces the overflow-doubling retry through the classed
        # kernel as well
        pods = diverse_reference_mix(200)
        pools = [example_nodepool()]
        its = corpus.generate(30)
        its_by_pool = {p.name: list(its) for p in pools}

        def run(classed):
            topology = Topology(Client(TestClock()), [], pools, its_by_pool, pods)
            return TpuSolver(
                pools, its_by_pool, topology,
                config=SolverConfig(classed=classed, max_claims=8),
                encode_cache=EncodeCache(),
            ).solve(pods)

        assert _signature(run(False)) == _signature(run(True))

    @pytest.mark.parametrize(
        "mk_pods,expect_classed",
        [
            (lambda: diverse_reference_mix(300), True),
            (lambda: mixed_pods(300), False),
        ],
        ids=["diverse-routes-classed", "mixed-routes-per-group"],
    )
    def test_routing_heuristic(self, monkeypatch, mk_pods, expect_classed):
        """Auto mode picks the classed kernel for fragmented batches
        (diverse: ~60 groups/class) and the per-group scan when every
        group is its own class (mixed) — verified by spying on the actual
        routing decision inside a real auto-mode solve."""
        pods = mk_pods()
        pools = [example_nodepool()]
        its = corpus.generate(30)
        its_by_pool = {p.name: list(its) for p in pools}
        topology = Topology(Client(TestClock()), [], pools, its_by_pool, pods)
        solver = TpuSolver(
            pools, its_by_pool, topology, encode_cache=EncodeCache()
        )
        decisions = []
        orig = TpuSolver._classed_partition

        def spy(self, snap_run, res_cap0):
            out = orig(self, snap_run, res_cap0)
            decisions.append(out is not None)
            return out

        monkeypatch.setattr(TpuSolver, "_classed_partition", spy)
        monkeypatch.delenv("KTPU_CLASSED", raising=False)
        solver.solve(pods)
        assert decisions and decisions[-1] is expect_classed
