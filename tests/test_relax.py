"""Relaxation bulk pre-solver (ops/relax.py): oracle-parity suite.

The contract: for every batch, a relax-enabled solve and a forced-exact
solve produce IDENTICAL decisions — which pods land on which claims of
which template with which surviving type options. Separable batches
route their easy mass through the closed-form bulk; non-separable ones
(the diverse / constrained / anti-affinity reference mixes) must route
the full residual to the exact kernel, and a corrupted bulk must trip
the invariant guard and shed to the full exact solve.
"""

from __future__ import annotations

import numpy as np
import pytest

jax = pytest.importorskip("jax")
jax.config.update("jax_platforms", "cpu")

from karpenter_tpu import faults  # noqa: E402
from karpenter_tpu.api import labels as labels_mod  # noqa: E402
from karpenter_tpu.api import resources as res  # noqa: E402
from karpenter_tpu.api.objects import (  # noqa: E402
    LabelSelector, ObjectMeta, Pod, PodAffinityTerm, PodSpec,
)
from karpenter_tpu.cloudprovider import corpus  # noqa: E402
from karpenter_tpu.kube import Client, TestClock  # noqa: E402
from karpenter_tpu.scheduling.topology import Topology  # noqa: E402
from karpenter_tpu.solver import TpuSolver  # noqa: E402
from karpenter_tpu.solver.driver import (  # noqa: E402
    EncodeCache, SolverConfig,
)
from karpenter_tpu.solver.example import example_nodepool  # noqa: E402

ZONES = ["test-zone-a", "test-zone-b", "test-zone-c"]


def _pod(name, cpu_m, zone=None, labels=None, anti=None):
    spec = PodSpec(
        requests={res.CPU: cpu_m, res.MEMORY: 2**30 * res.MILLI},
        node_selector=(
            {labels_mod.TOPOLOGY_ZONE: zone} if zone is not None else None
        ),
    )
    if anti is not None:
        spec.pod_anti_affinity = [
            PodAffinityTerm(
                topology_key=labels_mod.HOSTNAME,
                label_selector=LabelSelector(match_labels=anti),
            )
        ]
    return Pod(metadata=ObjectMeta(name=name, labels=labels or {}), spec=spec)


def _separable_pods(n=600):
    """One uniform deployment per zone: three signature runs with
    mutually exclusive zone masks — provably separable easy mass."""
    return [
        _pod(f"sep-{i}", (1 + i % 3) * 500, zone=ZONES[i % 3])
        for i in range(n)
    ]


def _partial_pods(n_easy=300, n_anti=40):
    """A separable zone-a deployment plus a zone-b anti-affinity class:
    the bulk routes, the anti-affinity residual rides the exact kernel,
    and the disjoint zone masks keep the two from sharing claims."""
    pods = [_pod(f"easy-{i}", 500, zone=ZONES[0]) for i in range(n_easy)]
    lbl = {"app": "nginx"}
    pods += [
        _pod(f"anti-{i}", 700, zone=ZONES[1], labels=lbl, anti=lbl)
        for i in range(n_anti)
    ]
    return pods


def _solve(pods, relax, n_types=24, cache=None):
    pools = [example_nodepool()]
    its = {pools[0].name: corpus.generate(n_types)}
    topology = Topology(Client(TestClock()), [], pools, its, pods)
    s = TpuSolver(
        pools, its, topology,
        config=SolverConfig(relax=relax),
        encode_cache=cache or EncodeCache(),
    )
    return s, s.solve(pods)


def _canon(results):
    return (
        sorted(
            (
                c.template.node_pool_name,
                tuple(sorted(p.uid for p in c.pods)),
                tuple(sorted(it.name for it in c.instance_type_options)),
            )
            for c in results.new_node_claims
        ),
        sorted(results.pod_errors),
    )


class TestRelaxParity:
    def test_separable_bulk_routes_and_matches_exact(self):
        pods = _separable_pods()
        s1, r1 = _solve(pods, relax=True)
        s0, r0 = _solve(pods, relax=False)
        assert s1.last_relax_pods == len(pods)
        assert s1.last_relax_claims == len(r1.new_node_claims)
        assert s1.last_relax_residual_pods == 0
        assert s1.relax_rejects == 0
        assert _canon(r1) == _canon(r0)

    def test_partial_routing_residual_exact(self):
        pods = _partial_pods()
        s1, r1 = _solve(pods, relax=True)
        s0, r0 = _solve(pods, relax=False)
        assert s1.last_relax_pods == 300  # the easy deployment only
        assert s1.last_relax_residual_pods == 40
        assert _canon(r1) == _canon(r0)
        # one claim per anti-affinity pod came from the exact kernel
        assert len(r1.new_node_claims) == len(r0.new_node_claims)

    @pytest.mark.parametrize("mix", ["diverse", "constrained", "anti"])
    def test_reference_mixes_route_full_residual(self, mix):
        from karpenter_tpu.solver.workloads import (
            constrained_mix, diverse_reference_mix,
        )

        if mix == "diverse":
            pods = diverse_reference_mix(250, seed=7)
        elif mix == "constrained":
            pods = constrained_mix(250, seed=5)
        else:
            lbl = {"app": "nginx"}
            pods = [
                _pod(f"an-{i}", 500, labels=lbl, anti=lbl) for i in range(60)
            ]
        s1, r1 = _solve(pods, relax=True)
        s0, r0 = _solve(pods, relax=False)
        # nothing provably separable: the WHOLE batch is the residual
        assert s1.last_relax_pods == 0
        assert _canon(r1) == _canon(r0)

    def test_mixed_shapes_same_selector_not_routed(self):
        # same zone, different shapes: the exact kernel lets the smaller
        # class top up the bigger class's partial claims, so the wall
        # must keep BOTH on the exact path
        pods = [
            _pod(f"m-{i}", 500 + (i % 2) * 700, zone=ZONES[0])
            for i in range(80)
        ]
        s1, r1 = _solve(pods, relax=True)
        s0, r0 = _solve(pods, relax=False)
        assert s1.last_relax_pods == 0
        assert _canon(r1) == _canon(r0)

    def test_warm_churn_keeps_reuse_with_relax(self):
        # the relax path must not disturb the device-residency warm path:
        # only the g_count ARG is overridden, so count-churn still rides
        # REUSE / row-delta staging
        cache = EncodeCache()
        pods = _separable_pods(300)
        s, _ = _solve(pods, relax=True, cache=cache)
        s, _ = _solve(pods, relax=True, cache=cache)
        s2, _ = _solve(pods[:-6] + _separable_pods(300)[:6], relax=True,
                       cache=cache)
        assert s2.last_relax_pods == 300
        assert s2._last_incremental, "relax broke the warm staging path"

    def test_corrupt_bulk_sheds_to_full_exact(self):
        # chaos: zero the bulk's fills — conservation fails, the guard
        # rejects the combined solve, and the driver re-solves fully
        # exact with the true counts (decisions still correct)
        def corrupt(bulk):
            n_r, r_pool, r_tmask, r_fills, r_unplaced = bulk
            return (n_r, r_pool, r_tmask, np.zeros_like(r_fills), r_unplaced)

        inj = faults.FaultInjector(
            [faults.FaultRule(faults.RELAX_OUTPUT, mutate=corrupt)]
        )
        faults.install(inj)
        try:
            pods = _separable_pods(240)
            s1, r1 = _solve(pods, relax=True)
        finally:
            faults.uninstall()
        s0, r0 = _solve(pods, relax=False)
        assert s1.relax_rejects == 1
        assert s1.last_relax_pods == 0  # the committed solve was exact
        assert inj.fired(faults.RELAX_OUTPUT) == 1
        assert _canon(r1) == _canon(r0)
