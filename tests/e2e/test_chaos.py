"""Chaos soak: the whole operator roster under seeded fault plans.

The acceptance contract (ISSUE 5): under injected solver crashes, corrupt
solves, provider insufficient-capacity, registration stalls, and store
conflicts, the operator

- never commits an invariant-violating solve (checked every tick: no node
  holds more than its allocatable),
- never orphans a NodeClaim and never double-deletes a cloud instance,
- converges to the fault-free fixed point within a bounded number of
  ticks once faults clear,

and the whole run REPLAYS: same seed, same fault schedule, same outcome
(faults/__init__.py's determinism contract).

The fast tests here are the presubmit chaos smoke
(``pytest tests/e2e -k chaos -m 'not slow'``); the long soak is marked
``slow`` so tier-1 wall time is unchanged.
"""

import sys
from collections import Counter

import pytest

sys.path.insert(0, __file__.rsplit("/", 2)[0])  # tests/ for helpers

from karpenter_tpu import faults
from karpenter_tpu.api import resources as res
from karpenter_tpu.api.objects import COND_INITIALIZED, Node, NodeClaim, Pod
from karpenter_tpu.cloudprovider.types import InsufficientCapacityError
from karpenter_tpu.kube.store import ConflictError
from karpenter_tpu.utils import pod as pod_utils

from e2e.harness import Scenario, record
from helpers import make_nodepool, make_pod


@pytest.fixture(autouse=True)
def _no_leaked_injector():
    yield
    faults.uninstall()


def _operator_kinds(ctx):
    # only the kinds the OPERATOR writes: the test harness (deployment
    # sim, binder) writes Pods, and a fault crashing the harness itself
    # would test pytest, not the control plane
    return ctx.get("kind") in ("NodeClaim", "Node")


def _inflate_delta_rows(vals):
    """Corrupt a delta on the wire: the device copy of the rows claims
    absurd capacity. The pre-decode guard (checking against the HOST
    snapshot) must reject the solve; the driver sheds the warm encoding
    and retries full (faults/breaker.py:SolverHealth.delta_fallback)."""
    import numpy as np

    return np.full_like(vals, 10_000_000)


def chaos_rules(until):
    return [
        faults.FaultRule(
            faults.STORE_CREATE, probability=0.15, until=until,
            error=lambda: ConflictError("injected store conflict"),
            match=_operator_kinds,
        ),
        faults.FaultRule(
            faults.STORE_UPDATE, probability=0.05, until=until,
            error=lambda: ConflictError("injected store conflict"),
            match=_operator_kinds,
        ),
        faults.FaultRule(
            faults.STORE_DELETE, probability=0.05, until=until,
            error=lambda: ConflictError("injected store conflict"),
            match=_operator_kinds,
        ),
        faults.FaultRule(
            faults.PROVIDER_CREATE, probability=0.2, until=until,
            error=lambda: InsufficientCapacityError("injected ICE"),
        ),
        faults.FaultRule(
            faults.PROVIDER_REGISTER, probability=0.3, until=until,
        ),
        faults.FaultRule(
            faults.SOLVER_DISPATCH, probability=0.15, until=until,
        ),
        # incremental-solving seams (ISSUE 8): crash the dispatch-queue
        # edges and corrupt delta rows in flight — the degradation ladder
        # and the guard's full-re-encode half-step must absorb both
        faults.FaultRule(
            faults.DISPATCH_QUEUE, probability=0.1, until=until,
        ),
        faults.FaultRule(
            faults.ENCODE_DELTA, probability=0.25, until=until,
            mutate=_inflate_delta_rows,
            match=lambda ctx: ctx.get("name") == "n_avail",
        ),
    ]


def _assert_no_overcommit(s):
    """The invariant an invariant-violating commit would break: no node
    ever holds more than its allocatable."""
    pods = s.client.list(Pod)
    for node in s.client.list(Node):
        total = res.merge(
            *(
                p.spec.requests
                for p in pods
                if p.spec.node_name == node.name and pod_utils.is_active(p)
            )
        ) if any(p.spec.node_name == node.name for p in pods) else {}
        assert res.fits(total, node.status.allocatable), (
            f"node {node.name} overcommitted: {total} > "
            f"{node.status.allocatable}"
        )


def _count_successful_deletes(provider):
    """Instrument the provider: successful instance deletions per id."""
    successes = Counter()
    orig = provider.delete

    def counting_delete(claim):
        out = orig(claim)
        successes[claim.status.provider_id] += 1
        return out

    provider.delete = counting_delete
    return successes


def run_chaos(seed, replicas=40, fault_ticks=20, converge_ticks=400,
              rules=chaos_rules, record_as=None):
    s = Scenario()
    s.client.create(make_nodepool())
    dep = s.deployment(
        "chaos", replicas, lambda: make_pod(cpu="1", memory="2Gi")
    )
    deletes = _count_successful_deletes(s.provider)
    until = s.clock.now() + fault_ticks
    injector = faults.install(
        faults.FaultInjector(rules(until), seed=seed, clock=s.clock)
    )
    s.timer.start("chaos")
    for _ in range(fault_ticks):
        s.tick()
        _assert_no_overcommit(s)
    s.timer.end("chaos", fired=injector.fired())
    injector.clear()  # faults over (the until deadline also passed)

    def converged():
        _assert_no_overcommit(s)
        return (
            dep.all_bound()
            and s.monitor.pending_pod_count() == 0
            and all(
                c.conds().is_true(COND_INITIALIZED)
                for c in s.client.list(NodeClaim)
            )
        )

    s.timer.start("converge")
    ticks = s.run_until(converged, converge_ticks, "post-chaos convergence")
    s.timer.end("converge", ticks=ticks)

    # no orphans in either direction: every claim has a live instance and
    # a node, every instance has a claim
    claims = s.client.list(NodeClaim)
    claim_pids = {c.status.provider_id for c in claims}
    cloud_pids = {c.status.provider_id for c in s.provider.list()}
    assert claim_pids == cloud_pids, (claim_pids, cloud_pids)
    node_pids = {n.provider_id for n in s.client.list(Node)}
    assert claim_pids <= node_pids
    # no double-deletes: no instance was successfully deleted twice
    doubles = {pid: n for pid, n in deletes.items() if n > 1}
    assert not doubles, doubles
    if record_as:
        record(record_as, s.timer, faults_fired=injector.fired())
    return s, dep, injector


class TestChaosSmoke:
    def test_chaos_soak_converges_no_orphans(self):
        s, dep, injector = run_chaos(seed=11, record_as="chaos_smoke")
        assert injector.fired() > 0  # the plan actually bit
        assert dep.bound_count() == dep.replicas

    def test_chaos_replay_is_deterministic(self):
        _, _, a = run_chaos(seed=23, replicas=25, fault_ticks=12)
        faults.uninstall()
        _, _, b = run_chaos(seed=23, replicas=25, fault_ticks=12)
        assert a.log == b.log
        assert a.log  # non-trivial schedule
        faults.uninstall()
        _, _, c = run_chaos(seed=24, replicas=25, fault_ticks=12)
        assert c.log != a.log  # the seed is the schedule

    def test_chaos_corrupt_solve_quarantined_then_recovers(self):
        """A kernel emitting garbage: the guard quarantines it (the bad
        solve is never committed), the batch lands via the oracle rung,
        and after the cool-down the ladder re-probes upward."""

        def corrupt(outs):
            import numpy as np

            outs = list(outs)
            outs[5] = np.asarray(outs[5]) - 7  # negative claim fills
            return tuple(outs)

        def rules(until):
            return [
                faults.FaultRule(
                    faults.SOLVER_OUTPUT, mutate=corrupt, times=2,
                )
            ]

        s, dep, injector = run_chaos(
            seed=5, replicas=30, fault_ticks=10, rules=rules,
        )
        health = s.operator.solver_health
        assert injector.fired(faults.SOLVER_OUTPUT) >= 1
        assert health.quarantines >= 1
        # cool-down re-probe upward: past the breaker window the kernel
        # rung admits a half-open probe, and a clean solve closes it
        s.clock.step(130.0)  # > default 120 s cool-down
        assert health.allow_kernel()
        dep.scale(dep.replicas + 1)  # force one fresh solve
        s.run_until(dep.all_bound, 60, "post-quarantine re-probe solve")
        assert health.ladder.breakers["kernel"].state == "closed"


class TestChaosIncrementalEncode:
    def test_corrupt_delta_never_commits_stale_snapshot(self):
        """ISSUE 8: every delta apply of the soak window is corrupted
        (inflated node capacity on the device copy). The pre-decode
        invariant guard must reject each such solve and the driver must
        answer with the full-re-encode half-step — so the cluster
        converges with zero overcommit (asserted every tick by
        run_chaos) and the ladder records fallbacks, not quarantines
        from committed garbage."""

        s = Scenario()
        s.client.create(make_nodepool())
        dep = s.deployment(
            "churn", 10, lambda: make_pod(cpu="1", memory="2Gi")
        )
        until = s.clock.now() + 40
        injector = faults.install(
            faults.FaultInjector(
                [
                    faults.FaultRule(
                        faults.ENCODE_DELTA, until=until,
                        mutate=_inflate_delta_rows,
                        match=lambda ctx: ctx.get("name") == "n_avail",
                    )
                ],
                seed=7, clock=s.clock,
            )
        )
        # steady scale-up keeps the provisioner solving against a growing
        # node set — exactly the steady-state-churn shape whose encode
        # arrives as row deltas
        for t in range(30):
            if t % 3 == 2:
                dep.scale(dep.replicas + 4)
            s.tick()
            _assert_no_overcommit(s)
        injector.clear()
        health = s.operator.solver_health
        assert injector.fired(faults.ENCODE_DELTA) >= 1
        # every corrupted delta was answered pre-commit: fallbacks (the
        # half-step) or, if a retry tripped too, a quarantine — never a
        # committed stale snapshot (the per-tick overcommit assert above)
        assert health.delta_fallbacks >= 1

        def converged():
            _assert_no_overcommit(s)
            return dep.all_bound() and s.monitor.pending_pod_count() == 0

        s.run_until(converged, 400, "post-corrupt-delta convergence")
        assert dep.bound_count() == dep.replicas

    def test_queue_crash_degrades_and_recovers(self):
        """DISPATCH_QUEUE faults at both edges: solves degrade through
        the ladder (oracle stays exact) and the roster converges once
        the plan clears."""

        def rules(until):
            return [
                faults.FaultRule(
                    faults.DISPATCH_QUEUE, probability=0.5, until=until,
                )
            ]

        s, dep, injector = run_chaos(
            seed=13, replicas=25, fault_ticks=12, rules=rules,
        )
        assert dep.bound_count() == dep.replicas
        assert injector.fired(faults.DISPATCH_QUEUE) >= 1


@pytest.mark.slow
class TestChaosSoakFull:
    def test_long_soak_with_scale_down(self):
        """The full-length soak: heavier plan, more replicas, plus a
        scale-down while faults are still firing — consolidation under
        chaos must not strand or double-free capacity either."""
        s, dep, injector = run_chaos(
            seed=101, replicas=120, fault_ticks=60, converge_ticks=900,
            record_as="chaos_soak_full",
        )
        # phase 2: scale down under a fresh fault wave, then converge
        deletes = _count_successful_deletes(s.provider)
        until2 = s.clock.now() + 30
        injector2 = faults.install(
            faults.FaultInjector(chaos_rules(until2), seed=202, clock=s.clock)
        )
        dep.scale(40)
        for _ in range(30):
            s.tick()
            _assert_no_overcommit(s)
        injector2.clear()
        s.run_until(
            lambda: dep.all_bound()
            and s.monitor.pending_pod_count() == 0,
            900,
            "post-scale-down convergence",
        )
        doubles = {pid: n for pid, n in deletes.items() if n > 1}
        assert not doubles, doubles
        assert dep.bound_count() == 40
