"""Cluster twin: deterministic trace replay under chaos, SLO wall.

The acceptance contract (ISSUE 12):

- two twin runs with the same seed, trace, and fault plan produce
  byte-identical canonical audit records and fault logs;
- a twin checkpointed mid-replay and resumed produces an audit trail
  byte-identical to the uninterrupted run;
- the tier-1 scaled replay (~2k nodes / 20k pods, tens of simulated
  minutes, at least one spot-reclaim and one ICE wave, a fault plan at
  the store/provider seams) passes every per-minute SLO assertion with
  zero fallback solves and zero overcommit.

The day-scale soak (simulated day, env-scalable node count) is marked
``slow``.
"""

import os
import pickle
import sys

import pytest

sys.path.insert(0, __file__.rsplit("/", 2)[0])  # tests/ for helpers

from karpenter_tpu import faults, obs
from karpenter_tpu.api.objects import Node, NodeClaim, Pod
from karpenter_tpu.cloudprovider.types import InsufficientCapacityError
from karpenter_tpu.kube.store import ConflictError
from karpenter_tpu.sim import slo as slo_mod
from karpenter_tpu.sim import trace as trace_mod
from karpenter_tpu.sim.twin import (
    ClusterProfile,
    ClusterTwin,
    TwinConfig,
    canonical_audit,
)
from karpenter_tpu.sim.slo import SLOConfig, SLOViolationError


@pytest.fixture(autouse=True)
def _no_leaked_seams():
    yield
    faults.uninstall()
    obs.uninstall_audit()
    if obs.active() is not None:
        obs.uninstall()


def _operator_kinds(ctx):
    return ctx.get("kind") in ("NodeClaim", "Node")


def chaos_plan(clock):
    """Store conflicts + provider ICE + registration stalls for the first
    ~2.5 simulated minutes. Deliberately NO solver-crash rules: the SLO
    wall asserts fallback_solves == 0, which a tripped kernel breaker
    would (correctly) violate — solver chaos has its own suite
    (test_chaos.py)."""
    until = clock.now() + 150.0
    return [
        faults.FaultRule(
            faults.STORE_CREATE, probability=0.1, until=until,
            error=lambda: ConflictError("injected conflict"),
            match=_operator_kinds,
        ),
        faults.FaultRule(
            faults.STORE_UPDATE, probability=0.05, until=until,
            error=lambda: ConflictError("injected conflict"),
            match=_operator_kinds,
        ),
        faults.FaultRule(
            faults.PROVIDER_CREATE, probability=0.15, until=until,
            error=lambda: InsufficientCapacityError("injected ICE"),
        ),
        faults.FaultRule(
            faults.PROVIDER_REGISTER, probability=0.2, until=until,
        ),
    ]


SMALL_PROFILE = ClusterProfile(nodes=30, pods_per_node=5, n_types=24)


def small_trace():
    return trace_mod.generate(
        5,
        trace_mod.ChurnProfile(
            minutes=5, pods_per_minute=4,
            reclaim_minutes=(1,), ice_minutes=(2,),
        ),
    )


def small_config(**overrides):
    base = dict(
        seed=9, minutes=5, steps_per_minute=2,
        slo=SLOConfig(cost_check_every=2),
    )
    base.update(overrides)
    return TwinConfig(**base)


class TestTraceSchema:
    def test_generator_is_seed_deterministic(self):
        profile = trace_mod.ChurnProfile(minutes=6)
        a = trace_mod.dump_jsonl(trace_mod.generate(3, profile))
        b = trace_mod.dump_jsonl(trace_mod.generate(3, profile))
        c = trace_mod.dump_jsonl(trace_mod.generate(4, profile))
        assert a == b
        assert a != c  # the seed is the trace

    def test_jsonl_round_trip(self, tmp_path):
        events = trace_mod.generate(
            7,
            trace_mod.ChurnProfile(
                minutes=4, reclaim_minutes=(1,), ice_minutes=(2,),
            ),
        )
        kinds = {e.kind for e in events}
        assert trace_mod.SPOT_RECLAIM in kinds
        assert trace_mod.ICE_WAVE in kinds
        path = str(tmp_path / "trace.jsonl")
        trace_mod.write_jsonl(events, path)
        back = trace_mod.read_jsonl(path)
        assert trace_mod.dump_jsonl(back) == trace_mod.dump_jsonl(events)

    def test_unknown_kind_rejected(self):
        with pytest.raises(ValueError):
            trace_mod.TraceEvent.from_dict({"t": 0.0, "kind": "nope"})

    def test_deletes_reference_created_pods_only(self):
        events = trace_mod.generate(11, trace_mod.ChurnProfile(minutes=8))
        created = set()
        for ev in sorted(events, key=lambda e: e.t):
            if ev.kind == trace_mod.POD_CREATE:
                created.add(ev.name)
            elif ev.kind in (trace_mod.POD_DELETE, trace_mod.LABEL_FLIP):
                assert ev.name in created


class TestTwinDeterminism:
    def _run(self, seed=9):
        cfg = small_config(seed=seed)
        with ClusterTwin(
            small_trace(), profile=SMALL_PROFILE, config=cfg,
            fault_rules=chaos_plan,
        ) as twin:
            twin.run()
            return (
                twin.canonical_audit(),
                tuple(twin.fault_log()),
                len(twin.audit.query()),
            )

    def test_same_seed_byte_identical_audit_and_fault_log(self):
        audit_a, log_a, n_a = self._run()
        faults.uninstall()
        obs.uninstall_audit()
        audit_b, log_b, n_b = self._run()
        assert n_a > 0  # the replay actually decided things
        assert audit_a == audit_b
        assert log_a == log_b
        assert log_a  # the plan actually bit

    def test_different_seed_diverges(self):
        _, log_a, _ = self._run(seed=9)
        faults.uninstall()
        obs.uninstall_audit()
        _, log_b, _ = self._run(seed=10)
        assert log_a != log_b


class TestTwinCheckpointResume:
    def test_resume_is_byte_identical_to_uninterrupted(self):
        cfg = small_config()
        with ClusterTwin(
            small_trace(), profile=SMALL_PROFILE, config=cfg,
            fault_rules=chaos_plan,
        ) as twin:
            twin.run()
            full_audit = twin.canonical_audit()
            full_log = tuple(twin.fault_log())
        faults.uninstall()
        obs.uninstall_audit()

        interrupted = ClusterTwin(
            small_trace(), profile=SMALL_PROFILE, config=small_config(),
            fault_rules=chaos_plan,
        )
        interrupted.run_minute()
        interrupted.run_minute()
        ckpt = interrupted.checkpoint()
        # the checkpoint must survive a process boundary
        ckpt = pickle.loads(pickle.dumps(ckpt))
        interrupted.close()

        resumed = ClusterTwin.resume(
            ckpt, small_trace(), profile=SMALL_PROFILE,
            config=small_config(), fault_rules=chaos_plan,
        )
        with resumed:
            assert resumed._minute == 2
            resumed.run()
            assert resumed.canonical_audit() == full_audit
            assert tuple(resumed.fault_log()) == full_log

    def test_checkpoint_with_pending_consolidation_command(self):
        """A command awaiting its validation TTL references the method
        that computed it; the checkpoint must survive pickling (the
        method object drags RLocks) and resume must re-bind the LIVE
        method at the same roster index."""
        from karpenter_tpu.controllers.disruption.types import Command

        twin = ClusterTwin(
            small_trace(), profile=SMALL_PROFILE, config=small_config(),
        )
        twin.run_minute()
        op = twin.operator
        op.disruption._pending = (
            Command(), twin.clock.now(), op.disruption.methods[-1],
        )
        ckpt = pickle.loads(pickle.dumps(twin.checkpoint()))
        twin.close()
        resumed = ClusterTwin.resume(
            ckpt, small_trace(), profile=SMALL_PROFILE,
            config=small_config(),
        )
        with resumed:
            pending = resumed.operator.disruption._pending
            assert pending is not None
            assert pending[2] is resumed.operator.disruption.methods[-1]

    def test_resume_without_fault_plan_refuses(self):
        """A checkpoint carrying injector state resumed WITHOUT the plan
        would silently fork the replay — it must raise instead."""
        twin = ClusterTwin(
            small_trace(), profile=SMALL_PROFILE, config=small_config(),
            fault_rules=chaos_plan,
        )
        twin.run_minute()
        ckpt = twin.checkpoint()
        twin.close()
        with pytest.raises(ValueError, match="fault_rules"):
            ClusterTwin.resume(
                ckpt, small_trace(), profile=SMALL_PROFILE,
                config=small_config(),
            )

    def test_checkpoint_restores_store_and_clock(self):
        twin = ClusterTwin(
            small_trace(), profile=SMALL_PROFILE, config=small_config(),
        )
        twin.run_minute()
        ckpt = twin.checkpoint()
        n_pods = len(twin.client.list(Pod))
        n_nodes = len(twin.client.list(Node))
        now = twin.clock.now()
        twin.close()
        resumed = ClusterTwin.resume(
            ckpt, small_trace(), profile=SMALL_PROFILE,
            config=small_config(),
        )
        with resumed:
            assert resumed.clock.now() == now
            assert len(resumed.client.list(Pod)) == n_pods
            assert len(resumed.client.list(Node)) == n_nodes
            # provider rehydrated every live claim's instance
            claim_pids = {
                c.status.provider_id
                for c in resumed.client.list(NodeClaim)
                if c.status.provider_id
            }
            cloud_pids = {
                c.status.provider_id for c in resumed.provider.list()
            }
            assert claim_pids <= cloud_pids | set()


class TestSLOWall:
    def test_latency_wall_trips(self):
        cfg = small_config(
            slo=SLOConfig(p99_decision_latency_ms=0.000001),
        )
        with ClusterTwin(
            small_trace(), profile=SMALL_PROFILE, config=cfg,
        ) as twin:
            with pytest.raises(SLOViolationError) as exc:
                twin.run()
            assert exc.value.report.violations
            assert any(
                v.slo == "p99-decision-latency"
                for v in exc.value.report.violations
            )

    def test_overcommit_sweep_detects_fabricated_violation(self):
        from karpenter_tpu.kube import Client, TestClock
        from helpers import make_pod
        from karpenter_tpu.api.objects import NodeStatus, ObjectMeta

        client = Client(TestClock())
        node = Node(metadata=ObjectMeta(name="n1"))
        node.status.capacity = {"cpu": 1000, "memory": 1024}
        node.status.allocatable = dict(node.status.capacity)
        node.status.ready = True
        client.create(node)
        pod = make_pod(cpu="4", memory="1Gi", node_name="n1", phase="Running")
        client.create(pod)
        assert slo_mod.overcommitted_nodes(client) == ["n1"]

    def test_orphan_sweep_flags_reclaimed_instance(self):
        """A reclaimed instance whose claim the roster never reaps must
        show up in the orphan sweep (in the twin, GC runs every step, so
        a persistent member means the reap path lost it)."""
        twin = ClusterTwin(
            [], profile=ClusterProfile(nodes=4, pods_per_node=2),
            config=small_config(minutes=1),
        )
        with twin:
            claim = twin.client.list(NodeClaim)[0]
            twin.provider.reclaim(claim.status.provider_id)
            # no roster pass in between: the claim is now an orphan once
            # its grace window lapses
            twin.clock.step(twin.config.slo.orphan_grace_s + 1)
            orphans = slo_mod.orphaned_claims(
                twin.client, twin.provider, twin.clock.now(),
                twin.config.slo.orphan_grace_s,
            )
            assert claim.name in orphans

    def test_minute_report_shape(self):
        with ClusterTwin(
            small_trace(), profile=SMALL_PROFILE, config=small_config(),
        ) as twin:
            report = twin.run_minute()
            d = report.as_dict()
            for key in (
                "minute", "records", "p99_latency_ms", "fallback_solves",
                "delta_fallbacks", "guard_bad", "overcommitted",
                "orphaned", "fleet_price", "cost_lower_bound",
                "violations",
            ):
                assert key in d
            assert d["violations"] == []


class TestCanonicalAudit:
    def test_excludes_warm_state_provenance(self):
        """The canonical form must be identical for a warm and a cold
        record that committed the same decision — encode_reused and
        delta_rows are provenance, not decision content."""
        log = obs.AuditLog()
        base = dict(
            kind="solve", trace_id="t1", duration_ms=0.0, encode_hash="h",
            pods=3, claims=1, errors=0, scenario_count=0, dispatches=1,
            rung="kernel", guard="ok", timestamp=1.0,
        )
        log.record(encode_reused=True, delta_rows=7, **base)
        warm = canonical_audit(log.query())
        log2 = obs.AuditLog()
        log2.record(encode_reused=False, delta_rows=0, **base)
        cold = canonical_audit(log2.query())
        assert warm == cold
        # but decision content differences DO show
        log3 = obs.AuditLog()
        log3.record(
            encode_reused=False, delta_rows=0,
            **{**base, "guard": "quarantined: x"},
        )
        assert canonical_audit(log3.query()) != cold

    def test_audit_window_is_half_open(self):
        log = obs.AuditLog()
        for ts in (0.0, 59.9, 60.0):
            log.record(
                kind="solve", trace_id="", duration_ms=0.0, encode_hash="",
                pods=0, claims=0, errors=0, scenario_count=0, dispatches=0,
                rung="kernel", guard="ok", timestamp=ts,
            )
        first = log.window(0.0, 60.0)
        second = log.window(60.0, 120.0)
        assert len(first) == 2
        assert len(second) == 1


class TestHarnessArtifacts:
    def test_record_routes_through_env_dir(self, tmp_path, monkeypatch):
        from e2e import harness

        monkeypatch.setenv("KTPU_E2E_ARTIFACT_DIR", str(tmp_path))
        from karpenter_tpu.kube import TestClock

        timer = harness.PhaseTimer(TestClock())
        timer.start("phase")
        timer.end("phase")
        harness.record("artifact_routing_check", timer)
        assert (tmp_path / "last_run.json").exists()
        assert (tmp_path / "metrics.prom").exists()
        here = os.path.dirname(harness.__file__)
        assert not os.path.exists(os.path.join(here, "last_run.json"))
        assert not os.path.exists(os.path.join(here, "metrics.prom"))


class TestScaledReplay:
    def test_scaled_replay_passes_slo_wall(self):
        """The tier-1 regression wall: ~2k nodes / 20k pods replayed for
        tens of simulated minutes under churn, one spot-reclaim wave, one
        ICE wave, and a store/provider fault plan — every per-minute SLO
        holds, fallback_solves stays 0, overcommit stays 0."""
        profile = ClusterProfile(nodes=2000, pods_per_node=10, n_types=24)
        trace = trace_mod.generate(
            7,
            trace_mod.ChurnProfile(
                minutes=20, pods_per_minute=8,
                reclaim_minutes=(2,), reclaim_count=4,
                ice_minutes=(4,), ice_cells=6,
            ),
        )
        cfg = TwinConfig(
            seed=7, minutes=20, steps_per_minute=2,
            slo=SLOConfig(p99_decision_latency_ms=10_000.0),
        )
        with ClusterTwin(
            trace, profile=profile, config=cfg, fault_rules=chaos_plan,
        ) as twin:
            reports = twin.run()  # raises SLOViolationError on any minute
            assert len(reports) == cfg.minutes
            assert twin.reclaimed >= 1  # the spot wave actually bit
            assert twin.iced_cells >= 1  # the ICE wave actually bit
            assert twin.injector.fired() > 0  # the fault plan actually bit
            assert all(r.fallback_solves == 0 for r in reports)
            assert all(r.overcommitted == 0 for r in reports)
            assert all(r.guard_bad == 0 for r in reports)
            # the replay produced sustained decision traffic
            assert len(twin.audit.query()) >= cfg.minutes


@pytest.mark.slow
class TestTwinDaySoak:
    def test_day_scale_soak(self):
        """A full simulated day of churn with recurring reclaim/ICE
        waves. Node count and minutes scale through the environment
        (KTPU_TWIN_SOAK_NODES / KTPU_TWIN_SOAK_MINUTES) toward the
        100k-node/1M-pod headline config as fleet-sharding lands; the
        registered default (2k nodes / 20k pods x 1440 minutes) is what
        one CPU host sustains today."""
        nodes = int(os.environ.get("KTPU_TWIN_SOAK_NODES", "2000"))
        minutes = int(os.environ.get("KTPU_TWIN_SOAK_MINUTES", "1440"))
        profile = ClusterProfile(nodes=nodes, pods_per_node=10)
        trace = trace_mod.generate(
            101,
            trace_mod.ChurnProfile(
                minutes=minutes, pods_per_minute=8,
                # wave placement scales with the replay length so a
                # reduced-minutes run (env override) still sees weather
                reclaim_minutes=tuple(
                    range(max(1, minutes // 4), minutes, 120)
                ),
                reclaim_count=4,
                ice_minutes=tuple(range(max(2, minutes // 3), minutes, 180)),
            ),
        )
        cfg = TwinConfig(
            seed=101, minutes=minutes, steps_per_minute=2,
            slo=SLOConfig(
                p99_decision_latency_ms=15_000.0, cost_check_every=360,
            ),
        )
        with ClusterTwin(
            trace, profile=profile, config=cfg, fault_rules=chaos_plan,
        ) as twin:
            reports = twin.run()
            assert len(reports) == minutes
            assert twin.reclaimed >= 1
            worst = twin.worst_minute()
            assert worst is not None
            assert worst.p99_latency_ms <= 15_000.0
