"""Tier-3 scenario harness: the whole operator roster at replica scale.

Models the reference's perf suite driver (test/suites/perf/
scheduling_test.go:35-114) and its polling Monitor (test/pkg/environment/
common/monitor.go:53-249): scenarios create Deployments, run the full
reconcile roster — provision → register → initialize → disrupt → drain →
terminate — against the in-process store + kwok provider, and record timed
phases to an artifact.

Three pieces the reference gets from a live cluster are simulated here:

- ``DeploymentSim`` — the ReplicaSet controller: keeps ``replicas`` pods of
  a template alive, recreating any that eviction deleted (drain deletes
  pods outright, controllers/termination.py).
- ``Monitor`` — polling cluster observer: node/claim/pod counts since
  reset, utilization, healthy (bound) pod counts per label selector.
- ``PhaseTimer`` — the TimeIntervalCollector analog: wall + virtual-clock
  durations per named phase, dumped as JSON next to this file.
"""

from __future__ import annotations

import itertools
import json
import os
import time
from typing import Callable, Dict, List, Optional, Sequence

from karpenter_tpu.api import labels as labels_mod
from karpenter_tpu.api import resources as res
from karpenter_tpu.api.objects import Node, NodeClaim, Pod
from karpenter_tpu.utils import pod as pod_utils

_seq = itertools.count(1)


class DeploymentSim:
    """Replica-keeping pod source (the ReplicaSet role). ``make_pod`` is a
    zero-arg factory returning a fresh Pending pod; the sim labels it for
    selector-based monitoring and replaces pods the drain deleted."""

    def __init__(self, client, name: str, replicas: int, make_pod: Callable[[], Pod]):
        self.client = client
        self.name = name
        self.replicas = replicas
        self._make_pod = make_pod
        self._owned: List[str] = []  # live uids, in creation order

    def reconcile(self) -> int:
        """Create pods up to ``replicas``; returns how many were created."""
        live = {p.uid for p in self.client.list(Pod)}
        self._owned = [uid for uid in self._owned if uid in live]
        created = 0
        while len(self._owned) < self.replicas:
            pod = self._make_pod()
            pod.metadata.labels.setdefault("e2e/deployment", self.name)
            pod.metadata.name = f"{self.name}-{next(_seq)}"
            self.client.create(pod)
            self._owned.append(pod.uid)
            created += 1
        return created

    def scale(self, replicas: int) -> None:
        """Scale down deletes surplus pods (newest first), like a
        ReplicaSet; scale up happens on the next reconcile."""
        while len(self._owned) > replicas:
            uid = self._owned.pop()
            for p in self.client.list(Pod):
                if p.uid == uid:
                    self.client.delete(p)
                    break
        self.replicas = replicas

    def bound_count(self) -> int:
        live = {p.uid: p for p in self.client.list(Pod)}
        return sum(
            1
            for uid in self._owned
            if uid in live
            and live[uid].spec.node_name
            and pod_utils.is_active(live[uid])
        )

    def all_bound(self) -> bool:
        return (
            len(self._owned) == self.replicas
            and self.bound_count() == self.replicas
        )


class Monitor:
    """Polling cluster observer (monitor.go:53-249): counts are snapshots
    of the store; ``reset()`` pins the baseline the way the reference pins
    nodesAtReset before each test."""

    def __init__(self, client):
        self.client = client
        self._nodes_at_reset: Dict[str, Node] = {}
        self.reset()

    def reset(self) -> None:
        self._nodes_at_reset = {n.name: n for n in self.client.list(Node)}

    def node_count(self) -> int:
        return len(self.client.list(Node))

    def created_node_count(self) -> int:
        return sum(
            1
            for n in self.client.list(Node)
            if n.name not in self._nodes_at_reset
        )

    def deleted_node_count(self) -> int:
        live = {n.name for n in self.client.list(Node)}
        return sum(1 for name in self._nodes_at_reset if name not in live)

    def claim_count(self) -> int:
        return len(self.client.list(NodeClaim))

    def drifted_claim_count(self) -> int:
        from karpenter_tpu.api.objects import COND_DRIFTED

        return sum(
            1
            for c in self.client.list(NodeClaim)
            if c.conds().is_true(COND_DRIFTED)
        )

    def pending_pod_count(self) -> int:
        return sum(
            1
            for p in self.client.list(Pod)
            if pod_utils.is_provisionable(p)
        )

    def avg_utilization(self, resource: str = res.CPU) -> float:
        """Requested/allocatable over live nodes (monitor.go AvgUtilization)."""
        nodes = self.client.list(Node)
        if not nodes:
            return 0.0
        pods = self.client.list(Pod)
        total_req = 0.0
        total_alloc = 0.0
        for n in nodes:
            total_alloc += float(n.status.allocatable.get(resource, 0))
            total_req += float(
                sum(
                    p.spec.requests.get(resource, 0)
                    for p in pods
                    if p.spec.node_name == n.name and pod_utils.is_active(p)
                )
            )
        return total_req / total_alloc if total_alloc else 0.0


class PhaseTimer:
    """TimeIntervalCollector analog: named phases with wall + virtual-clock
    durations, dumped to JSON for the artifact trail."""

    def __init__(self, clock):
        self.clock = clock
        self._open: Dict[str, tuple] = {}
        self.phases: Dict[str, Dict[str, float]] = {}

    def start(self, name: str) -> None:
        self._open[name] = (time.perf_counter(), self.clock.now())

    def end(self, name: str, **extra) -> None:
        wall0, virt0 = self._open.pop(name)
        entry = {
            "wall_s": round(time.perf_counter() - wall0, 3),
            "virtual_s": round(self.clock.now() - virt0, 1),
        }
        entry.update(extra)
        self.phases[name] = entry


class Scenario:
    """One operator + store + kwok environment with the simulation loop."""

    def __init__(self, n_types: int = 24, operator_options=None,
                 store_root: str = None):
        from karpenter_tpu.cloudprovider import corpus
        from karpenter_tpu.cloudprovider.kwok import KwokCloudProvider
        from karpenter_tpu.kube import Client, FileClient, TestClock
        from karpenter_tpu.operator import Operator
        from karpenter_tpu.sim import Binder

        self.clock = TestClock()
        # store_root switches the scenario onto the file-backed store
        # (kube/filestore.py): every object round-trips serialization and
        # the run is resumable from disk — the envtest-like tier
        self.client = (
            FileClient(self.clock, root=store_root)
            if store_root
            else Client(self.clock)
        )
        self.provider = KwokCloudProvider(self.client, corpus.generate(n_types))
        self.operator = Operator(
            self.client, self.provider, options=operator_options
        )
        self.binder = Binder(self.client)
        self.monitor = Monitor(self.client)
        self.timer = PhaseTimer(self.clock)
        self.deployments: List[DeploymentSim] = []

    def deployment(self, name: str, replicas: int, make_pod) -> DeploymentSim:
        dep = DeploymentSim(self.client, name, replicas, make_pod)
        self.deployments.append(dep)
        return dep

    def tick(self, force: bool = True) -> None:
        for dep in self.deployments:
            dep.reconcile()
        self.operator.step(force_provision=force)
        self.binder.bind_all()
        self.clock.step(1.0)

    def run_until(
        self,
        predicate: Callable[[], bool],
        max_ticks: int,
        what: str,
    ) -> int:
        """Tick the roster until the predicate holds; returns ticks used.
        Raises on timeout — a scenario that can't converge is a failure,
        not a skip (EventuallyExpectHealthyPodCount's role)."""
        for i in range(max_ticks):
            if predicate():
                return i
            self.tick()
        raise AssertionError(
            f"scenario did not reach '{what}' within {max_ticks} ticks: "
            f"nodes={self.monitor.node_count()} "
            f"claims={self.monitor.claim_count()} "
            f"pending={self.monitor.pending_pod_count()}"
        )


def artifact_dir() -> str:
    """Where harness artifacts (phase timings, metrics expositions) land:
    ``$KTPU_E2E_ARTIFACT_DIR`` when set (tests route it through
    ``tmp_path``; CI points it at its artifact store), else a
    per-process temp directory — NEVER the tracked tree (stray
    last_run.json/metrics.prom files under tests/e2e were the failure
    mode this replaces)."""
    d = os.environ.get("KTPU_E2E_ARTIFACT_DIR")
    if not d:
        import tempfile

        d = os.path.join(
            tempfile.gettempdir(), f"ktpu-e2e-{os.getpid()}"
        )
    os.makedirs(d, exist_ok=True)
    return d


def record(scenario_name: str, timer: PhaseTimer, **extra) -> None:
    """Append this scenario's phases to the artifact file
    (``<artifact_dir>/last_run.json``), and flush the metrics registry's
    Prometheus exposition next to it (the sim-harness side of the
    Operator.shutdown dump — scenario runs leave a scrapeable snapshot
    of every counter/gauge/histogram)."""
    out_dir = artifact_dir()
    artifact = os.path.join(out_dir, "last_run.json")
    data = {}
    if os.path.exists(artifact):
        try:
            with open(artifact) as fh:
                data = json.load(fh)
        except Exception:
            data = {}
    entry: Dict[str, object] = dict(timer.phases)
    entry.update(extra)
    data[scenario_name] = entry
    with open(artifact, "w") as fh:
        json.dump(data, fh, indent=1)
    from karpenter_tpu.metrics import REGISTRY

    REGISTRY.dump(os.path.join(out_dir, "metrics.prom"))
