"""Tier-3 E2E scenarios: the full roster at 100-500 replica scale.

Each scenario drives the whole operator — provision → register →
initialize → (disrupt → drain → terminate) — through the in-process store
and kwok provider, with timed phases recorded to last_run.json
(reference: test/suites/perf/scheduling_test.go:35-114).
"""

import sys

import pytest

sys.path.insert(0, __file__.rsplit("/", 2)[0])  # tests/ for helpers

from karpenter_tpu.api import labels
from karpenter_tpu.api.objects import Node, NodeClaim, Pod

from e2e.harness import Scenario, record
from helpers import make_nodepool, make_pod, spread_constraint


class TestProvisioningScale:
    def test_simple_provisioning_500(self):
        """500 one-cpu replicas from an empty cluster: every pod must land
        on a created node (scheduling_test.go:39-55 at 5x its scale)."""
        s = Scenario()
        s.client.create(make_nodepool())
        dep = s.deployment(
            "simple", 500, lambda: make_pod(cpu="1", memory="1Gi")
        )
        s.timer.start("provision")
        ticks = s.run_until(dep.all_bound, 60, "all 500 pods bound")
        s.timer.end(
            "provision",
            replicas=500,
            ticks=ticks,
            nodes=s.monitor.created_node_count(),
        )
        assert s.monitor.created_node_count() > 0
        assert s.monitor.pending_pod_count() == 0
        # every claim made it through the full lifecycle
        from karpenter_tpu.api.objects import COND_INITIALIZED

        for claim in s.client.list(NodeClaim):
            assert claim.conds().is_true(COND_INITIALIZED)
        record(
            "simple_provisioning_500",
            s.timer,
            utilization=round(s.monitor.avg_utilization(), 3),
        )

    def test_provisioning_on_file_store_with_restart(self, tmp_path):
        """The same e2e flow over the file-backed store (kube/filestore.py)
        — every object round-trips serialization end-to-end — then a
        RESTART: a fresh operator over the same directory resumes the
        cluster and keeps it steady (the reference's level-triggered
        recovery against a durable apiserver)."""
        root = str(tmp_path / "store")
        s = Scenario(store_root=root)
        s.client.create(make_nodepool())
        dep = s.deployment(
            "filestore", 120, lambda: make_pod(cpu="1", memory="1Gi")
        )
        s.run_until(dep.all_bound, 60, "all 120 pods bound")
        nodes_before = s.monitor.created_node_count()
        assert nodes_before > 0

        # restart: new store client, new operator, same directory
        s2 = Scenario(store_root=root)
        s2.clock._now = s.clock.now()  # resume simulated time
        assert len(s2.client.list(Node)) == len(s.client.list(Node))
        assert len(s2.client.list(Pod)) == 120
        for _ in range(5):
            s2.tick()
        # steady state: nothing new provisioned, nothing lost
        assert s2.monitor.pending_pod_count() == 0
        assert len(s2.client.list(Node)) == len(s.client.list(Node))

    def test_complex_provisioning_400(self):
        """Diverse deployments — generic, zonal spread, hostname spread,
        zonal node affinity — provision together (MakeDiversePodOptions's
        role, scheduling_test.go:92-114)."""
        from karpenter_tpu.api.objects import NodeSelectorRequirement

        s = Scenario()
        s.client.create(make_nodepool())
        app_z = {"app": "zspread"}
        app_h = {"app": "hspread"}
        deps = [
            s.deployment(
                "generic", 100, lambda: make_pod(cpu="1", memory="2Gi")
            ),
            s.deployment(
                "zonal-affinity",
                100,
                lambda: make_pod(
                    cpu="3",
                    memory="4Gi",
                    requirements=[
                        NodeSelectorRequirement(
                            labels.TOPOLOGY_ZONE,
                            "In",
                            ("test-zone-a", "test-zone-b"),
                        )
                    ],
                ),
            ),
            s.deployment(
                "zonal-spread",
                100,
                lambda: make_pod(
                    cpu="1",
                    labels=dict(app_z),
                    spread=[
                        spread_constraint(labels.TOPOLOGY_ZONE, labels=app_z)
                    ],
                ),
            ),
            s.deployment(
                "host-spread",
                100,
                lambda: make_pod(
                    cpu="1",
                    labels=dict(app_h),
                    spread=[
                        spread_constraint(
                            labels.HOSTNAME, max_skew=2, labels=app_h
                        )
                    ],
                ),
            ),
        ]
        s.timer.start("provision")
        ticks = s.run_until(
            lambda: all(d.all_bound() for d in deps), 80,
            "all 400 diverse pods bound",
        )
        s.timer.end(
            "provision",
            replicas=400,
            ticks=ticks,
            nodes=s.monitor.created_node_count(),
        )
        # zonal spread held: bound zspread pods within maxSkew across zones
        zone_counts = {}
        pods = s.client.list(Pod)
        nodes = {n.name: n for n in s.client.list(Node)}
        for p in pods:
            if p.metadata.labels.get("app") == "zspread" and p.spec.node_name:
                z = nodes[p.spec.node_name].metadata.labels.get(
                    labels.TOPOLOGY_ZONE
                )
                zone_counts[z] = zone_counts.get(z, 0) + 1
        assert zone_counts and max(zone_counts.values()) - min(
            zone_counts.values()
        ) <= 1
        # zonal node affinity held: those pods only landed in allowed zones
        for p in pods:
            if (
                p.metadata.labels.get("e2e/deployment") == "zonal-affinity"
                and p.spec.node_name
            ):
                z = nodes[p.spec.node_name].metadata.labels.get(
                    labels.TOPOLOGY_ZONE
                )
                assert z in ("test-zone-a", "test-zone-b"), z
        record("complex_provisioning_400", s.timer)


class TestDriftReplacement:
    def test_drift_replacement_cycle_100(self):
        """Provision 100 replicas over ~a dozen small nodes, drift the
        pool (template label change), and run the roster until every old
        claim is replaced and the workload is whole again
        (scheduling_test.go:56-91: drift until no claims remain drifted).
        The default 10% disruption budget must gate the rollout: only a
        budgeted number of nodes may be disrupted at any instant."""
        from karpenter_tpu.api.objects import NodeSelectorRequirement
        from karpenter_tpu.cloudprovider.corpus import INSTANCE_CPU_LABEL

        s = Scenario()
        # small nodes force a wide fleet so the budget actually bites
        pool = make_nodepool(
            requirements=[
                NodeSelectorRequirement(INSTANCE_CPU_LABEL, "In", ("8",))
            ]
        )
        pool.spec.disruption.consolidate_after = 30.0
        s.client.create(pool)
        dep = s.deployment(
            "workload", 100, lambda: make_pod(cpu="1", memory="2Gi")
        )
        s.timer.start("provision")
        ticks = s.run_until(dep.all_bound, 40, "100 pods bound")
        s.timer.end("provision", ticks=ticks)

        original = {c.uid for c in s.client.list(NodeClaim)}
        assert len(original) >= 8  # a real fleet, not two jumbo nodes
        import math

        budget = max(1, math.ceil(0.1 * len(original)))  # default "10%"

        # drift: change the pool template (nodepool hash changes)
        pool.spec.template.labels["e2e-drift"] = "true"
        s.client.update(pool)
        s.timer.start("drift")
        s.run_until(
            lambda: s.monitor.drifted_claim_count() > 0,
            20,
            "at least one claim drifted",
        )
        # replacement converges: no drifted claims left, no old claims
        # left, workload fully re-bound — while the 10% budget gates how
        # many original nodes are ever disrupted (tainted) at once
        max_tainted = 0

        def converged():
            nonlocal max_tainted
            tainted = sum(
                1
                for n in s.client.list(Node)
                if any(t.key == labels.DISRUPTED_TAINT_KEY for t in n.taints)
            )
            max_tainted = max(max_tainted, tainted)
            return (
                s.monitor.drifted_claim_count() == 0
                and not (
                    {c.uid for c in s.client.list(NodeClaim)} & original
                )
                and dep.all_bound()
            )

        ticks = s.run_until(
            converged, 600, "all drifted claims replaced and pods re-bound"
        )
        s.timer.end(
            "drift",
            ticks=ticks,
            replaced=len(original),
            nodes=s.monitor.node_count(),
            max_concurrent_disruptions=max_tainted,
        )
        assert max_tainted <= budget, (max_tainted, budget)
        for claim in s.client.list(NodeClaim):
            assert claim.metadata.labels.get("e2e-drift") == "true"
        record("drift_replacement_100", s.timer)


class TestConsolidationScale:
    def test_scale_down_consolidates_200_to_50(self):
        """Scale a 200-replica deployment down to 50: emptiness +
        consolidation must shrink the fleet while the surviving pods stay
        scheduled (the disruption loop's steady-state job)."""
        s = Scenario()
        pool = make_nodepool()
        pool.spec.disruption.consolidate_after = 10.0
        s.client.create(pool)
        dep = s.deployment(
            "workload", 200, lambda: make_pod(cpu="2", memory="2Gi")
        )
        s.timer.start("provision")
        s.run_until(dep.all_bound, 40, "200 pods bound")
        s.timer.end("provision", nodes=s.monitor.created_node_count())
        peak = s.monitor.node_count()
        assert peak >= 2

        dep.scale(50)
        s.timer.start("consolidate")
        ticks = s.run_until(
            lambda: (
                s.monitor.node_count() < peak
                and s.monitor.pending_pod_count() == 0
                and dep.all_bound()
            ),
            600,
            "fleet shrank after scale-down",
        )
        s.timer.end(
            "consolidate",
            ticks=ticks,
            peak_nodes=peak,
            final_nodes=s.monitor.node_count(),
        )
        assert dep.bound_count() == 50
        record("consolidation_200_to_50", s.timer)
