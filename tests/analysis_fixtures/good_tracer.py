"""Clean tracer-safety twin: static branching and lax control flow only."""

import jax
import jax.numpy as jnp
from jax import lax


@jax.jit
def static_branching(x, y, use_bias: bool = True):
    n = x.shape[0]  # shape components are trace-time statics
    if use_bias:  # scalar-annotated parameter: static
        y = y + 1.0
    if n > 4:  # static shape branch specializes per compile, by design
        y = y * 2.0
    return jnp.where(x > 0, y, -y)  # data-dependent select stays on device


@jax.jit
def device_control_flow(x):
    def body(i, acc):
        return acc + x[i % x.shape[0]]

    total = lax.fori_loop(0, 8, body, jnp.zeros(()))
    # traced predicate handed TO lax.cond — the legal form of the branch
    # that bad_tracer.py writes in python
    return lax.cond(total > 0, lambda t: t, lambda t: -t, total)


def solve_core_clean(counts, acc, nmax: int):
    for _ in range(nmax):  # static trip count: unrolls identically per shape
        acc = acc + jnp.sum(counts)
    return acc
