"""Seeded guarded-by violations: a mixed guarded/unguarded attribute, a
guarded container escaping by reference, and an __init__-published
callback that acquires the lock."""

import threading


class Buffered:
    """`_items` is guarded in add() but raced in flush()."""

    def __init__(self):
        self._lock = threading.Lock()
        self._items = []
        self._count = 0

    def add(self, item):
        with self._lock:
            self._items.append(item)
            self._count += 1

    def flush(self):
        out = list(self._items)  # GRD1301: lock-free read of guarded state
        self._items.clear()  # lock-free write widens the race
        return out

    def snapshot(self):
        with self._lock:
            return self._items  # GRD1302: guarded list escapes by reference


class Publisher:
    def __init__(self, bus):
        self._lock = threading.Lock()
        self._state = {}
        bus.subscribe(self._on_event)  # GRD1303: published callback locks

    def _on_event(self, evt):
        with self._lock:
            self._state[evt] = True

    def get(self, key):
        with self._lock:
            return self._state.get(key)
