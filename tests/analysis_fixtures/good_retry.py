"""Clean twin of bad_retry.py: the designed idioms the retry pass must
stay silent on — typed transient catches, recorded failures, and bounded
clock-driven retry loops."""


class ConflictError(ValueError):
    pass


def typed_skip(items):
    # typed transient absorbed by the level-triggered loop: NOT flagged —
    # the type documents exactly which failure requeues
    for it in items:
        try:
            it.reconcile()
        except ConflictError:
            continue


def recorded_broad(items, recorder):
    # broad catch is fine when the failure is surfaced, not swallowed
    for it in items:
        try:
            it.sync()
        except Exception as exc:
            recorder.publish(exc)


def bounded_retry(fn, clock, backoff):
    # the Backoff.call shape: attempt counter + clock-driven sleep
    attempt = 0
    while True:
        try:
            return fn()
        except TimeoutError:
            attempt += 1
            if attempt >= 3:
                raise
            clock.sleep(backoff.delay(attempt - 1))


def doubling_probe(call, nmax):
    # the driver's overflow-doubling loop: no except handler at all
    while True:
        out, overflow = call(nmax)
        if not overflow:
            return out
        nmax *= 2
