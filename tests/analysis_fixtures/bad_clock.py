"""Seeded clock-discipline violations: every CLK rule fires here."""

import time
from datetime import datetime

from time import monotonic as mono


def stamp_record(record):
    record["ts"] = time.time()  # CLK1001: direct wall-clock read
    record["day"] = datetime.now()  # CLK1001: datetime.now read
    return record


def aliased_read():
    return mono()  # CLK1001 through the from-import alias


class Reconciler:
    def __init__(self):
        # CLK1002: the callable escapes into instance state — the
        # injection seams can never replace it
        self._now = time.perf_counter

    def step(self):
        start = time.monotonic  # CLK1002: stashed reference
        t0 = start()  # CLK1001: the stashed reference is called
        return t0


def pass_clock_along(schedule):
    # CLK1002: a wall-clock callable handed to someone else
    schedule(time.monotonic)
