// Native twin of parity_twin.py with every anchor in sync: the PAR5xx
// pass must stay silent on this pair. Never compiled — fixture only.
//
// parity: dtype float32
// parity: dtype int32
// parity: dtype bool
// parity: const kBig = 2**20
// parity: const 0.25
// parity: tiebreak argmin
// parity: tiebreak cumsum
// parity: state c_used, c_npods, overflow
// parity: phase fill
// parity: phase settle
