"""Seeded lock-order violations: an ABBA cycle, a callback under a lock,
and a non-reentrant re-acquisition."""

import threading


class Store:
    """Acquires store -> index."""

    def __init__(self, index: "Index" = None):
        self._lock = threading.RLock()
        self._index = index
        self._watchers = []

    def put(self, key, value):
        with self._lock:
            self._index.add(key)  # LCK201 half: store -> index

    def publish(self, event):
        with self._lock:
            for handler in list(self._watchers):
                handler(event)  # LCK202: callback invoked under the lock


class Index:
    """Acquires index -> store: closes the cycle."""

    def __init__(self, store: Store = None):
        self._lock = threading.RLock()
        self._store = store

    def add(self, key):
        with self._lock:
            return key

    def rebuild(self):
        with self._lock:
            self._store.put("k", "v")  # LCK201 half: index -> store


class Plain:
    def __init__(self):
        self._lock = threading.Lock()

    def nested(self):
        with self._lock:
            with self._lock:  # LCK203: non-reentrant re-acquire deadlocks
                pass
