"""Clean fixture for the OBS8xx pass: every allowed span-closing shape and
the sanctioned metric-construction shapes. Must produce zero findings."""

import contextlib

from karpenter_tpu import obs
from karpenter_tpu.metrics import Counter, Gauge, Registry

# OBS802-clean: metrics constructed once, at module scope
REQUESTS = Counter("fixture_requests_total", "module-scope construction")
DEPTH = Gauge("fixture_depth", "module-scope construction")


def context_managed(tracer):
    with tracer.span("encode"):
        REQUESTS.inc()


def context_managed_with_as(tracer):
    with obs.span("dispatch", kernel="pack") as sp:
        sp.annotate(ok=True)


def returns_span_to_caller(tracer):
    # a factory handing the context manager up for the caller's `with`
    return tracer.span("decode")


def exit_stack(tracer):
    with contextlib.ExitStack() as stack:
        stack.enter_context(tracer.span("guard"))
        REQUESTS.inc()


def finally_closed(tracer):
    sp = tracer.span("commit")
    sp.__enter__()
    try:
        REQUESTS.inc()
    finally:
        sp.__exit__(None, None, None)


def scoped_registry_metric():
    # OBS802-exempt: an explicit scoped registry is the designed way to
    # build metrics dynamically (tests, sandboxed dumps)
    reg = Registry()
    c = Counter("fixture_scoped_total", "scoped", registry=reg)
    c.inc()
    return reg
