"""The bad_atomicity.py shapes done right: check and act in one critical
section (or a commutative merge under the second lock), and a single
global acquisition order."""

import threading


class HintSlot:
    def __init__(self):
        self._lock = threading.Lock()
        self._hint = 0

    def bump(self, n):
        with self._lock:
            # decision and write share the critical section
            if n > self._hint:
                self._hint = n

    def bump_merge(self, n):
        with self._lock:
            self._hint = max(self._hint, n)


class Staging:
    """Acquires staging -> registry; the registry never calls back."""

    def __init__(self, registry: "Registry" = None):
        self._lock = threading.Lock()
        self._registry = registry

    def stage(self):
        with self._lock:
            self._registry.publish()


class Registry:
    def __init__(self):
        self._lock = threading.Lock()

    def publish(self):
        with self._lock:
            return True
