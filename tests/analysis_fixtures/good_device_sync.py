"""Clean device-residency twin: device values stay on device until the
sanctioned decode boundary; every host decision reads host metadata."""

import numpy as np

import jax
import jax.numpy as jnp


def disciplined_solve(xs, nmax: int):
    staged = jax.device_put(xs)
    out = jnp.cumsum(staged)  # device math stays device
    if nmax > 4:  # host branch on host metadata: fine
        out = out * 2
    # analysis: sanctioned[DTX906] fixture decode boundary
    host = jax.device_get(out)
    return np.asarray(host)  # host numpy on a host value: fine


def shape_projections(xs):
    arr = jnp.stack([xs, xs])
    n = arr.shape[0]  # shape/dtype projections are host metadata
    if n > 1:  # fine: branching on a static projection
        return arr
    return arr.T


def poison_to_unknown(xs, blob):
    mixed = jnp.sum(xs) + blob.mystery()  # joins to unknown
    if mixed > 0:  # unknown, not device: silent by design
        return mixed
    for item in blob.rows():  # unknown iterable: silent
        print(item)  # unknown value: silent
    return None


def host_pipeline(spans):
    arr = np.asarray(spans, np.int64)  # host end to end
    total = int(arr.sum())
    return [float(v) for v in arr if v > 0], total
