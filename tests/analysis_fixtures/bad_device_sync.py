"""Seeded device-residency violations: every DTX rule fires here.

Host-side driver-shaped code (NOT jitted — that's bad_tracer.py's
territory): device values leak into host sinks outside any sanctioned
boundary.
"""

import numpy as np

import jax
import jax.numpy as jnp


def branch_on_device(xs):
    scores = jnp.cumsum(xs)  # device origin
    if scores[0] > 0:  # DTX901: truthiness on a device value
        return scores
    while scores.sum() > 0:  # DTX901 again (device while-condition)
        scores = scores - 1
    flag = bool(scores[0])  # DTX901: bool() materializes the predicate
    return flag


def materialize_device(xs):
    total = jnp.sum(xs)
    best = float(total)  # DTX902: host materialization
    exact = total.item()  # DTX902: .item() sync
    rows = total.tolist()  # DTX902: .tolist() sync
    return best, exact, rows


def numpy_on_device(xs):
    staged = jax.device_put(xs)  # device origin via device_put
    host = np.asarray(staged)  # DTX903: implicit device_get
    arr = np.array(staged)  # DTX903 again
    return host, arr


def iterate_device(xs):
    cols = jnp.stack([xs, xs])
    out = []
    for row in cols:  # DTX904: python loop over a device value
        out.append(row)
    return out, list(cols)  # DTX904: list() iterates on host


def print_device(xs):
    mean = jnp.mean(xs)
    print("mean was", mean)  # DTX905: print syncs the value
    return f"mean={mean}"  # DTX905: f-string interpolation


def unsanctioned_readback(xs):
    out = jnp.sort(xs)
    return jax.device_get(out)  # DTX906: readback without a sanction


def helper_launders_device(xs):
    # one-level interprocedural reach: _hidden_origin returns a jnp
    # result, so `masked` is a device value at this call site too
    masked = _hidden_origin(xs)
    if masked[0] > 0:  # DTX901 through the helper summary
        return masked
    return None


def _hidden_origin(xs):
    return jnp.where(xs > 0, xs, 0)


def branch_merge_still_device(xs, use_alt):
    # the CFG join keeps DEVICE through the diamond: both arms bind a
    # device value, so the sink below must still flag
    if use_alt:
        acc = jnp.zeros_like(xs)
    else:
        acc = jnp.ones_like(xs)
    return int(acc[0])  # DTX902 after the join
