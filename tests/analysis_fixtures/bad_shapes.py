"""Seeded-bad fixture for the SHP6xx axis/dtype pass.

Each function below carries exactly the hazard its name says; the pass
must flag every rule at least once and test_analysis.py pins the set.
"""

import jax
import jax.numpy as jnp


def transposed_join(n, r):
    a = jnp.zeros((n, r), jnp.float32)
    b = jnp.zeros((r, n), jnp.float32)
    return a + b  # SHP601: [n, r] + [r, n]


def unexpanded_mask(n, r):
    mask = jnp.zeros((n,), bool)
    x = jnp.ones((n, r), jnp.float32)
    # SHP601: mask needs [:, None] — as written 'n' aligns against 'r'
    return jnp.where(mask, x, 0.0)


def stale_einsum_spec(n, t):
    a = jnp.zeros((n, t), jnp.float32)
    b = jnp.zeros((t, n), jnp.float32)
    # SHP601: letter 'n' binds axis n (from a) AND axis t (from b)
    return jnp.einsum("nt,nt->n", a, b)


def transposed_matmul(n, r, t):
    a = jnp.zeros((n, r), jnp.float32)
    b = jnp.zeros((t, r), jnp.float32)
    return a @ b  # SHP601: contracts r against t (b needs transposing)


def widened_accumulator(n):
    acc = jnp.zeros((n,), jnp.float64)  # SHP602: explicit f64 constructor
    x = jnp.ones((n,), jnp.float32)
    y = x.astype(jnp.float64)  # SHP602: astype to 64-bit
    return acc + x, y  # SHP602: f64/f32 join widens


def widened_positional(spans):
    # SHP602: positional dtype slot, no dtype= keyword
    return jnp.asarray(spans, jnp.float64)


def unbucketed_scratch(n):
    pad = jnp.zeros((n, 1000), jnp.float32)  # SHP603: 1000 is not a bucket
    flat = pad.reshape(n, 40, 25)  # SHP603: literal 40/25 dims
    return flat


def misaligned_segment_ids(l, m, g):
    data = jnp.zeros((l, m), jnp.float32)
    ids = jnp.zeros((m,), jnp.int32)
    # SHP601: ids ride axis m but data's segment axis is l
    return jax.ops.segment_sum(data, ids, num_segments=g)


def segment_result_misjoined(l, m, g):
    data = jnp.zeros((l, m), jnp.float32)
    ids = jnp.zeros((l,), jnp.int32)
    seg = jax.ops.segment_sum(data, ids, num_segments=g)  # [g, m]
    return seg + jnp.zeros((l, m), jnp.float32)  # SHP601: g joined with l


def sharded_unpadded_axis(mesh, m):
    # 48 rows never went through the pow2 shard padding; broadcast_to so
    # the constructor-literal rule (SHP603) stays out of this function
    row = jnp.zeros((m,), jnp.float32)
    x = jnp.broadcast_to(row[None, :], (48, m))
    s = jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec("data"))
    return jax.device_put(x, s)  # SHP604: 'data' shards a 48-dim


def sharded_unpadded_via_names(mesh, m):
    spec = jax.sharding.PartitionSpec(None, "model")
    sh = jax.sharding.NamedSharding(mesh, spec)
    x = jnp.broadcast_to(jnp.zeros((m,), jnp.float32)[:, None], (m, 24))
    # SHP604: the name-resolved spec partitions the literal 24 column axis
    return jax.lax.with_sharding_constraint(x, sh)
