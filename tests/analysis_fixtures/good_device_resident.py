"""Clean device-resident store: buffers held across solves never cross
to host except at the sanctioned drain (the shape the real
solver/residency.py + driver drain implement)."""

import jax
import jax.numpy as jnp


class ResidentStore:
    def __init__(self):
        self._dev_rows = None

    def stage(self, host):
        self._dev_rows = jax.device_put(host)

    def delta_apply(self, idx, vals):
        # on-device row update: no host crossing
        self._dev_rows = self._dev_rows.at[idx].set(jnp.asarray(vals))
        return self._dev_rows

    def shape(self):
        return self._dev_rows.shape  # host metadata, not a sync

    def drain(self):
        # the one blessed readback, sanctioned at the boundary
        return jax.device_get(self._dev_rows)  # analysis: sanctioned[DTX906] test fixture drain point
