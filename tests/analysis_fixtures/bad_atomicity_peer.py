"""The other module of the seeded cross-module lock-order cycle: the
registry acquires its own lock and calls back into Staging.stage()."""

import threading


class Registry:
    def __init__(self, staging: "Staging" = None):
        self._lock = threading.Lock()
        self._staging = staging

    def publish(self):
        with self._lock:
            return True

    def rebuild(self):
        with self._lock:
            self._staging.stage()  # ATM1402 half: registry -> staging
