def solve_core_native(g_count, g_req, t_def, gk_w, nmax=0):
    return (g_count, g_req, t_def, gk_w, nmax)
