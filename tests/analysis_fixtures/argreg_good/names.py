"""Clean twin of argreg_bad: every surface consistent with the
authority tuple."""

SOLVE_ARG_NAMES = ("g_count", "g_req", "t_def", "gk_w")


class EncodedSnapshot:
    def solve_args(self, gk_w):
        return (self.g_count, self.g_req, self.t_def, gk_w)
