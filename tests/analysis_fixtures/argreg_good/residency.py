GROUP_ARGS = frozenset({"g_req", "gk_w"})
GCOUNT_ARGS = frozenset({"g_count"})

NO_ROW_DELTA = frozenset({"gk_w"})

SCENARIO_BATCHED_ARGS = ("g_count",)
SCENARIO_TOPO_BATCHED_ARGS = SCENARIO_BATCHED_ARGS + ("g_req",)
