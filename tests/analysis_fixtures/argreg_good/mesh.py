AXIS_DATA = "data"
AXIS_MODEL = "model"

ARG_SPECS = {
    "g_count": (),
    "g_req": (),
    "t_def": (AXIS_MODEL,),
    "gk_w": (AXIS_DATA,),
}


def pad_axis(arr, axis, mult, fill=0):
    return arr


def pad_args_for_mesh(args, mesh):
    model = mesh.devices.shape[1]
    data = mesh.devices.shape[0]
    byname = dict(zip(("g_count", "g_req", "t_def", "gk_w"), args))
    for name in ("t_def",):
        byname[name] = pad_axis(byname[name], 0, model)
    byname["gk_w"] = pad_axis(byname["gk_w"], 0, data)
    return tuple(byname[name] for name in ("g_count", "g_req", "t_def", "gk_w"))
