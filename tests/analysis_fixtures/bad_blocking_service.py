"""Seeded-bad blocking sites in the shapes BLK3xx newly covers: the
solver sidecar's solve path and the leader-election loop. Both are
reconcile-shaped — level-triggered steps driven by the injected clock —
so wall-clock reads, sleeps, and blocking network I/O are the same hazard
as in controllers/."""

import time
import urllib.request


def solve_snapshot(data):
    start = time.time()  # BLK302: wall-clock read in the solve path
    health = urllib.request.urlopen(  # BLK303: blocking I/O in-band
        "http://controller/healthz"
    )
    return data, health, time.time() - start  # BLK302 again


class LeaderLoop:
    def try_acquire(self, lease):
        if lease.renew_time < time.monotonic():  # BLK302: bypasses Clock
            time.sleep(1.0)  # BLK301: stalls the operator step
            return True
        return False
