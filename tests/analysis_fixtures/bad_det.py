"""Seeded-bad fixture for the DET11xx order-discipline pass.

Every rule in the family appears at least once, including the
multi-hop shape (an unordered value born two helper calls away) that
needs the call-graph summaries to see.
"""

import os
import random

import numpy as np


def intern_values(vocab):
    seen = {"zone-a", "zone-b"}
    for v in seen:                      # DET1101: hash-order interning
        vocab.append(v)
    frozen = list(seen)                 # DET1102: order-fixing freeze
    record = ",".join(seen)             # DET1103: hash-ordered record
    return frozen, record


def env_sweep():
    out = []
    for key in os.environ:              # DET1101: environment order
        out.append(key)
    return out


def _leaf_pool():
    return {"us-east1", "us-west4"}


def _hop():
    return _leaf_pool()


def multi_hop_consumer():
    pool = _hop()
    for zone in pool:                   # DET1101: two hops from the set
        print(zone)


def jitter(items):
    random.shuffle(items)               # DET1104: unseeded global RNG
    return np.random.rand(3)            # DET1104: legacy numpy global
