# Fixture corpus for tests/test_analysis.py: each bad_* file seeds exactly
# the violations its pass must flag; each good_* file is a clean twin that
# must NOT be flagged. These modules are parsed, never imported.
