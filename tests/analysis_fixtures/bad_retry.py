"""Seeded-bad fixture for the retry pass (RTY7xx): every anti-pattern the
rules exist for, in the shapes they take in reconcile code."""


def swallow_broad(items):
    for it in items:
        try:
            it.sync()
        except Exception:  # RTY701: the failure vanishes
            pass


def swallow_bare(obj):
    try:
        obj.delete()
    except:  # noqa: E722  RTY701: bare except, body only pass
        pass


def swallow_continue(items):
    for it in items:
        try:
            it.reconcile()
        except BaseException:  # RTY701: continue-only body
            continue


def spin_forever(fn):
    while True:  # RTY702: no counter, no backoff, no clock, no escape
        try:
            return fn()
        except Exception:
            continue


def spin_forever_fallthrough(fn, log):
    while True:  # RTY702: handler records but the loop never bounds
        try:
            return fn()
        except OSError as exc:
            log.append(exc)
