"""Clean blocking twin: all timing through the injectable clock seam."""


class PatientController:
    def __init__(self, clock):
        self.clock = clock

    def reconcile(self):
        started = self.clock.now()
        self.clock.sleep(0.5)
        return self.clock.since(started)
