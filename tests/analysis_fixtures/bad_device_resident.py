"""Seeded "host crossing between solves" violations (ISSUE 8).

A delta-encode store holds device buffers across solves under the
resident-attribute naming convention (``dev_*`` / ``_dev*``,
solver/residency.py). Laundering one of those buffers through host numpy
— or reading it back outside the sanctioned drain — is exactly the
crossing the device-residency contract forbids BETWEEN solves, and the
poison-to-unknown discipline used to hide it (the carrying ``self`` is
untracked). The resident-origin rule makes every sink below reachable.
"""

import numpy as np

import jax
import jax.numpy as jnp


class ResidentStore:
    def __init__(self):
        self._dev_rows = None
        self.dev_avail = None

    def stage(self, host):
        self._dev_rows = jax.device_put(host)
        self.dev_avail = jnp.zeros((4,))

    def laundered_delta(self, idx):
        # DTX903: np.asarray on a resident buffer between solves — an
        # implicit device_get smuggled through the delta path
        rows = np.asarray(self._dev_rows)
        return rows[idx]

    def peek(self):
        if self.dev_avail[0] > 0:  # DTX901: truthiness on resident buffer
            return True
        return False

    def drain_all(self):
        # DTX906: readback of a resident buffer outside the sanctioned
        # drain point (no sanction annotation)
        return jax.device_get(self._dev_rows)

    def walk(self):
        return list(self.dev_avail)  # DTX904: host iteration per element
