"""Clean clock-discipline twin: injected clocks and the documented
RealClock seams only."""

import time


class RealClock:
    """The documented seam (kube/clock.py shape): the ONLY place a wall
    clock may be read directly."""

    def now(self) -> float:
        return time.time()

    def sleep(self, seconds: float) -> None:
        time.sleep(seconds)


class PerfClock:
    """The obs/trace.py seam twin."""

    @staticmethod
    def now() -> float:
        return time.perf_counter()


def stamp_record(record, clock):
    record["ts"] = clock.now()  # injected clock: fine
    return record


def duration_of(clock, fn):
    t0 = clock.now()
    fn()
    return clock.since(t0)


def sanctioned_diagnostic():
    # a documented real-wall-time boundary, annotated not suppressed
    return time.monotonic()  # analysis: sanctioned[CLK1001] fixture wall-time boundary
