"""Clean twin of bad_shapes.py: the same computations with the axis
order, dtypes, and bucketed literal dims done right — the SHP6xx pass
must stay silent."""

import jax.numpy as jnp


def aligned_join(n, r):
    a = jnp.zeros((n, r), jnp.float32)
    b = jnp.zeros((n, r), jnp.float32)
    return a + b


def expanded_mask(n, r):
    mask = jnp.zeros((n,), bool)
    x = jnp.ones((n, r), jnp.float32)
    return jnp.where(mask[:, None], x, 0.0)


def consistent_einsum_spec(n, t):
    a = jnp.zeros((n, t), jnp.float32)
    b = jnp.zeros((t, n), jnp.float32)
    return jnp.einsum("nt,tn->n", a, b)


def aligned_matmul(n, r, t):
    a = jnp.zeros((n, r), jnp.float32)
    b = jnp.zeros((r, t), jnp.float32)
    return a @ b  # legal contraction: [n, r] @ [r, t] -> [n, t]


def narrow_positional(spans):
    return jnp.asarray(spans, jnp.float32)


def narrow_accumulator(n):
    acc = jnp.zeros((n,), jnp.float32)
    x = jnp.ones((n,), jnp.float32)
    y = x.astype(jnp.int32)
    return acc + x, y


def bucketed_scratch(n):
    pad = jnp.zeros((n, 1024), jnp.float32)
    flat = pad.reshape(n, 32, 32)
    return flat
