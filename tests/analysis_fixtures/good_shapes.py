"""Clean twin of bad_shapes.py: the same computations with the axis
order, dtypes, and bucketed literal dims done right — the SHP6xx pass
must stay silent."""

import jax
import jax.numpy as jnp


def aligned_join(n, r):
    a = jnp.zeros((n, r), jnp.float32)
    b = jnp.zeros((n, r), jnp.float32)
    return a + b


def expanded_mask(n, r):
    mask = jnp.zeros((n,), bool)
    x = jnp.ones((n, r), jnp.float32)
    return jnp.where(mask[:, None], x, 0.0)


def consistent_einsum_spec(n, t):
    a = jnp.zeros((n, t), jnp.float32)
    b = jnp.zeros((t, n), jnp.float32)
    return jnp.einsum("nt,tn->n", a, b)


def aligned_matmul(n, r, t):
    a = jnp.zeros((n, r), jnp.float32)
    b = jnp.zeros((r, t), jnp.float32)
    return a @ b  # legal contraction: [n, r] @ [r, t] -> [n, t]


def narrow_positional(spans):
    return jnp.asarray(spans, jnp.float32)


def narrow_accumulator(n):
    acc = jnp.zeros((n,), jnp.float32)
    x = jnp.ones((n,), jnp.float32)
    y = x.astype(jnp.int32)
    return acc + x, y


def bucketed_scratch(n):
    pad = jnp.zeros((n, 1024), jnp.float32)
    flat = pad.reshape(n, 32, 32)
    return flat


def segment_contraction(l, m, g):
    """The sparse feasibility shape: compacted live pairs summed back to
    the group axis; the result's axes are (g, m) and join silently."""
    data = jnp.zeros((l, m), jnp.float32)
    ids = jnp.zeros((l,), jnp.int32)
    seg = jax.ops.segment_sum(data, ids, num_segments=g)  # [g, m]
    return seg + jnp.zeros((g, m), jnp.float32)


def gather_along_group_axis(g, m):
    seg = jnp.zeros((g, m), jnp.float32)
    idx = jnp.zeros((g, m), jnp.int32)
    picked = jnp.take_along_axis(seg, idx, axis=1)  # axes preserved
    return picked + jnp.zeros((g, m), jnp.float32)


def bucketed_broadcast(g, m):
    row = jnp.zeros((m,), jnp.float32)
    wide = jnp.broadcast_to(row[None, :], (g, m))
    return wide + jnp.zeros((g, m), jnp.float32)


def sharded_padded_axis(mesh, m):
    """The r06 staging shape: a pow2 leading dim under a mesh-axis entry
    divides any pow2 mesh axis — silent."""
    row = jnp.zeros((m,), jnp.float32)
    x = jnp.broadcast_to(row[None, :], (64, m))
    spec = jax.sharding.PartitionSpec("data", None)
    sh = jax.sharding.NamedSharding(mesh, spec)
    y = jax.lax.with_sharding_constraint(x, sh)
    return y + jnp.zeros((64, m), jnp.float32)


def replicated_any_size(mesh, m):
    """A replicated spec places the whole buffer on every device: no
    divisibility constraint, any dim is fine."""
    row = jnp.zeros((m,), jnp.float32)
    x = jnp.broadcast_to(row[None, :], (48, m))
    return jax.device_put(x, jax.sharding.NamedSharding(
        mesh, jax.sharding.PartitionSpec()
    ))


def sharded_named_dim(mesh, n, m):
    """A named (non-literal) dim under a mesh axis: unknowable statically,
    the pass must not guess."""
    row = jnp.zeros((m,), jnp.float32)
    x = jnp.broadcast_to(row[None, :], (n, m))
    return jax.device_put(x, jax.sharding.NamedSharding(
        mesh, jax.sharding.PartitionSpec("data")
    ))
