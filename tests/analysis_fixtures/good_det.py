"""Clean twin of bad_det.py: every consumption is either canonicalized
through sorted(), a commutative reduction, a seeded RNG instance, or a
sanctioned boundary."""

import random

import numpy as np


def intern_values(vocab):
    seen = {"zone-a", "zone-b"}
    for v in sorted(seen):
        vocab.append(v)
    frozen = sorted(seen)
    record = ",".join(sorted(seen))
    count = len(seen)  # commutative reduction: order-free by construction
    return frozen, record, count


def _leaf_pool():
    return {"us-east1", "us-west4"}


def _hop():
    return _leaf_pool()


def multi_hop_consumer():
    for zone in sorted(_hop()):
        print(zone)


def member_check(pool, zone):
    return zone in pool  # membership never observes order


def seeded(seed):
    rng = np.random.default_rng(seed)
    det = random.Random(seed)
    return rng.integers(0, 4), det.random()


def boundary_count():
    pool = {"zone-a", "zone-b"}
    total = 0
    # pure counting commutes, so the hash iteration
    # analysis: sanctioned[DET1101] order cannot reach the sum
    for _item in pool:
        total += 1
    return total
