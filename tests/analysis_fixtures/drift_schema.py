"""Miniature schema module whose CRD artifacts (drift_crds/) have drifted:
the YAML is missing 'weight', carries a stale 'bogus' property, and has a
truncated consolidationPolicy enum."""

_POLICIES = ["WhenEmpty", "WhenEmptyOrUnderutilized"]


def nodepool_schema():
    return {
        "kind": "NodePoolSchema",
        "spec": {
            "type": "object",
            "required": ["template"],
            "properties": {
                "weight": {"type": "integer"},
                "consolidationPolicy": {"type": "string", "enum": _POLICIES},
                "template": {"type": "object"},
            },
        },
    }


def nodeclaim_schema():
    return {
        "kind": "NodeClaimSchema",
        "spec": {
            "type": "object",
            "properties": {"nodePoolName": {"type": "string"}},
        },
    }
