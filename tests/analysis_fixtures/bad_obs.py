"""Seeded-bad fixture for the OBS8xx observability-hygiene pass: span
leaks (OBS801) and per-call metric construction (OBS802). Never imported;
parsed by tests/test_analysis.py."""

from karpenter_tpu import obs
from karpenter_tpu.metrics import Counter, Gauge, Histogram


def leaks_plain_call(tracer):
    tracer.span("encode")  # OBS801: opened and dropped on the floor


def leaks_assigned_span(tracer):
    sp = tracer.span("dispatch")  # OBS801: assigned, never closed
    do_work()
    sp.annotate(done=True)


def leaks_module_helper():
    sp = obs.span("decode")  # OBS801: no with, no finally
    do_work()
    return 1


def churns_counter():
    # OBS802: a new metric registered in the global registry per call
    c = Counter("per_call_counter", "churn")
    c.inc()


def churns_gauge_and_histogram(value):
    Gauge("per_call_gauge", "churn").set(value)  # OBS802
    h = Histogram("per_call_histogram", "churn")  # OBS802
    h.observe(value)


def do_work():
    pass
