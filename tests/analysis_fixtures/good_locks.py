"""Clean lock-order twin: single global order, callbacks outside locks."""

import threading


class Store:
    def __init__(self, index: "Index" = None):
        self._lock = threading.RLock()
        self._index = index
        self._watchers = []

    def put(self, key, value):
        with self._lock:
            self._index.add(key)  # store -> index, the only direction

    def publish(self, event):
        with self._lock:
            snapshot = list(self._watchers)
        for handler in snapshot:  # callbacks run after release
            handler(event)


class Index:
    def __init__(self):
        self._lock = threading.RLock()
        self._entries = {}

    def add(self, key):
        with self._lock:
            self._entries[key] = True

    def size(self):
        with self._lock:
            return len(self._entries)
