GROUP_ARGS = frozenset({"g_req"})
GCOUNT_ARGS = frozenset({"g_count"})

# gk_w is not in GROUP_ARGS -> ARG1203
NO_ROW_DELTA = frozenset({"gk_w"})
