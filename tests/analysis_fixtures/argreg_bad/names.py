"""Mini twin of the kernel-arg registry, seeded with one drift per
ARG12xx rule (the other surfaces live in the sibling files, mirroring
the real encode/mesh/native/residency module split)."""

SOLVE_ARG_NAMES = ("g_count", "g_req", "t_def", "gk_w")


class EncodedSnapshot:
    def solve_args(self, gk_w):
        return (self.g_count, self.g_req, self.t_def, gk_w)
