AXIS_DATA = "data"
AXIS_MODEL = "model"

# gk_w has no entry -> ARG1201
ARG_SPECS = {
    "g_count": (),
    "g_req": (),
    "t_def": (AXIS_MODEL,),
}


def pad_axis(arr, axis, mult, fill=0):
    return arr


def pad_args_for_mesh(args, mesh):
    # t_def is sharded above but never padded here -> ARG1204
    byname = dict(zip(("g_count", "g_req", "t_def", "gk_w"), args))
    return tuple(byname[name] for name in ("g_count", "g_req", "t_def", "gk_w"))
