def solve_core_native(g_count, t_def, g_req, gk_w, nmax=0):
    # t_def / g_req swapped vs SOLVE_ARG_NAMES -> ARG1202
    return (g_count, g_req, t_def, gk_w, nmax)
