"""Seeded blocking-call violations in a reconcile path."""

import subprocess
import time
import urllib.request


class SlowController:
    def reconcile(self):
        time.sleep(0.5)  # BLK301: wall-clock sleep in a reconcile path
        started = time.time()  # BLK302: direct wall-clock read
        subprocess.run(["sync"])  # BLK303: blocking process call
        # BLK303 via a dotted import (`import urllib.request` binds
        # `urllib`, not `urllib.request` — the resolver must not
        # double-append the submodule)
        urllib.request.urlopen("http://example.invalid")
        return started
