// Seeded-bad native twin of parity_twin.py: every anchor failure mode the
// extractor must survive (finding, not crash). Expected findings:
//
//   PAR506 x3 — malformed anchors: empty argument, unevaluable const
//               expression, unknown anchor kind
//   PAR501    — phase 'settle' missing (sequence drift)
//   PAR502    — const 2**19 has no Python twin; const 0.25 missing here
//   PAR503    — dtype bool missing here
//   PAR504    — tiebreak argmax has no Python twin; cumsum missing here
//   PAR505    — state field 'c_oldname' is stale after a rename;
//               'c_npods'/'overflow' never declared here
//
// parity: const
// parity: const banana
// parity: flavor mango
// parity: phase fill
// parity: const 2**20
// parity: const 2**19
// parity: dtype float32
// parity: dtype int32
// parity: tiebreak argmin
// parity: tiebreak argmax
// parity: state c_used, c_oldname
