"""Seeded atomicity violations: a check-then-act split across a lock
release, and (with bad_atomicity_peer.py) one half of a cross-module
lock-order cycle."""

import threading


class HintSlot:
    def __init__(self):
        self._lock = threading.Lock()
        self._hint = 0

    def bump(self, n):
        with self._lock:
            cur = self._hint
        if n > cur:  # decision on the stale read, lock released
            with self._lock:
                self._hint = n  # ATM1401: the gap loses another's bump


class Staging:
    """Acquires staging -> registry (the peer closes the cycle)."""

    def __init__(self, registry: "Registry" = None):
        self._lock = threading.Lock()
        self._registry = registry

    def stage(self):
        with self._lock:
            self._registry.publish()  # ATM1402 half: staging -> registry
