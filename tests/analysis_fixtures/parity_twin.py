"""Miniature Python side of a kernel-twin triple for the PAR5xx extractor
tests: two identical kernels over a tiny PackState, paired with
parity_good.cc (anchors in sync) and parity_bad.cc (every anchor failure
mode seeded)."""

from typing import NamedTuple

import jax.numpy as jnp

_BIG = 2**20


class PackState(NamedTuple):
    c_used: jnp.ndarray
    c_npods: jnp.ndarray
    overflow: jnp.ndarray


def pack(xs, n):
    # parity: phase fill
    state = PackState(
        c_used=jnp.zeros((n,), jnp.float32),
        c_npods=jnp.zeros((n,), jnp.int32),
        overflow=jnp.bool_(False),
    )
    level = jnp.argmin(jnp.where(xs > 0, xs, _BIG))
    # parity: phase settle
    order = jnp.cumsum(xs) * 0.25
    return state._replace(c_used=state.c_used + order), level


def pack_classed(xs, n):
    # parity: phase fill
    state = PackState(
        c_used=jnp.zeros((n,), jnp.float32),
        c_npods=jnp.zeros((n,), jnp.int32),
        overflow=jnp.bool_(False),
    )
    level = jnp.argmin(jnp.where(xs > 0, xs, _BIG))
    # parity: phase settle
    order = jnp.cumsum(xs) * 0.25
    return state._replace(c_used=state.c_used + order), level
