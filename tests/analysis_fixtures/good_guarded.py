"""The bad_guarded.py shapes done right: every access under the lock,
escapes copied out, callbacks published after construction (and the
published one takes no lock)."""

import threading


class Buffered:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = []
        self._count = 0

    def add(self, item):
        with self._lock:
            self._items.append(item)
            self._count += 1

    def flush(self):
        with self._lock:
            out = list(self._items)
            self._items.clear()
        return out

    def snapshot(self):
        with self._lock:
            return list(self._items)  # copied out: no reference escape


class Publisher:
    def __init__(self):
        self._lock = threading.Lock()
        self._state = {}

    def start(self, bus):
        # published after construction, and the callback is lock-free
        bus.subscribe(self._on_event)

    def _on_event(self, evt):
        self.enqueue(evt)

    def enqueue(self, evt):
        with self._lock:
            self._state[evt] = True

    def get(self, key):
        with self._lock:
            return self._state.get(key)
