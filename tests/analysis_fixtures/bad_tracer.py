"""Seeded tracer-safety violations: every TRC rule fires in this module."""

import time

import numpy as np

import jax
import jax.numpy as jnp


@jax.jit
def branches_on_traced(x, y):
    if x > 0:  # TRC101: python branch on a traced value
        y = y + 1
    while jnp.sum(y) > 0:  # TRC101 again (traced while-condition)
        y = y - 1
    return y


@jax.jit
def materializes_host(x):
    total = jnp.sum(x)
    as_float = float(total)  # TRC102: host materialization
    as_list = total.tolist()  # TRC102: host materialization
    return as_float, as_list


@jax.jit
def host_modules(x):
    t0 = time.time()  # TRC103: host module inside jit
    arr = np.asarray(x)  # TRC103: numpy runs at trace time
    return arr, t0


def solve_core_loops(counts, acc):
    # solve_core* naming marks this as a kernel entry even without @jit
    limit = int(jnp.max(counts))  # TRC102: int() on a traced value
    for _ in range(limit):  # TRC104: data-dependent trip count
        acc = acc + 1
    for c in counts:  # TRC104: python loop over a traced array
        acc = acc + c
    return acc
