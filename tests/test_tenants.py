"""Multi-tenant solver service: admission, QoS, noisy-neighbor isolation.

The tentpole contract (PARITY.md "Tenant isolation contract"): many
control planes share one resident solver, but NO warm state and NO
health state crosses tenants. The witness here is byte-identity — a
bystander tenant's decisions during another tenant's chaos plan must
equal its fault-free solo run bit for bit, its rung must stay
``batched``, and its ``fallback_solves`` must stay 0. Everything is
seeded and clock-injected; a failure is a real isolation leak, not a
flake.
"""

import threading

import numpy as np
import pytest

from karpenter_tpu import faults, obs
from karpenter_tpu.cloudprovider import corpus
from karpenter_tpu.kube import TestClock
from karpenter_tpu.metrics import REGISTRY
from karpenter_tpu.solver import wire
from karpenter_tpu.solver.driver import SolverConfig
from karpenter_tpu.solver.service import (
    InjectedRpcError,
    RemoteSolver,
    SolverBackpressure,
    TenantService,
    _batch_key,
    serve,
)
from karpenter_tpu.solver.tenancy import (
    AdmissionError,
    DeadlineOverrunError,
    TenantQoS,
    TenantRegistry,
)

from helpers import (
    decision_signature,
    make_nodepool,
    make_pods,
    make_state_node,
    spread_constraint,
)

POOLS = [make_nodepool(name="default")]
TYPES = {"default": corpus.generate(8)}


@pytest.fixture(autouse=True)
def _clean():
    obs.uninstall()
    faults.uninstall()
    yield
    obs.uninstall()
    faults.uninstall()


def make_request(
    n_pods, prefix, state_nodes=(), pods_kwargs=None, pods=None
) -> bytes:
    """One tenant's solve request, encoded ONCE — decoding the same bytes
    for a chaos run and its fault-free baseline guarantees identical pod
    uids, which the byte-identity witness keys on."""
    if pods is None:
        pods = make_pods(n_pods, **(pods_kwargs or {}))
        for i, p in enumerate(pods):
            p.metadata.name = f"{prefix}-{i}"
            p.metadata.uid = f"uid-{prefix}-{i}"
    return wire.encode_solve_request(
        pods,
        POOLS,
        TYPES,
        solver_options={"reserved_capacity_enabled": False},
        state_nodes=list(state_nodes),
    )


def snap(request: bytes) -> dict:
    return wire.decode_solve_request(request)


# -- admission control --------------------------------------------------------


class TestAdmission:
    def test_rate_limit_refills_on_injected_clock(self):
        clock = TestClock()
        reg = TenantRegistry(
            clock=clock,
            qos={"standard": TenantQoS(rate=1.0, burst=2.0)},
        )
        reg.admit("a").release()
        reg.admit("a").release()
        with pytest.raises(AdmissionError) as exc_info:
            reg.admit("a")
        assert exc_info.value.reason == "rate-limited"
        clock.step(1.5)  # one token refilled
        reg.admit("a").release()

    def test_queue_bound_rejects_not_queues(self):
        reg = TenantRegistry(
            clock=TestClock(),
            qos={"standard": TenantQoS(max_queue=2, burst=10.0)},
        )
        leases = [reg.admit("a"), reg.admit("a")]
        with pytest.raises(AdmissionError) as exc_info:
            reg.admit("a")
        assert exc_info.value.reason == "queue-full"
        leases[0].release()
        reg.admit("a").release()  # slot freed → admitted again
        for lease in leases[1:]:
            lease.release()

    def test_tenant_capacity_bound(self):
        reg = TenantRegistry(clock=TestClock(), max_tenants=2)
        reg.admit("a").release()
        reg.admit("b").release()
        with pytest.raises(AdmissionError) as exc_info:
            reg.admit("c")
        assert exc_info.value.reason == "tenant-capacity"
        # existing tenants are unaffected by the rejected newcomer
        reg.admit("a").release()

    def test_tier_shed_order(self):
        """Under global contention the batch tier is shed first, then
        standard; premium may fill the whole pool."""
        reg = TenantRegistry(
            clock=TestClock(),
            max_inflight=4,
            tiers={"gold": "premium", "bulk": "batch"},
        )
        held = [reg.admit("bulk"), reg.admit("bulk")]  # batch share: 2
        with pytest.raises(AdmissionError) as exc_info:
            reg.admit("bulk")
        assert exc_info.value.reason == "tier-shed"
        held.append(reg.admit("std"))  # standard share: 3
        with pytest.raises(AdmissionError):
            reg.admit("std")
        held.append(reg.admit("gold"))  # premium fills the pool
        with pytest.raises(AdmissionError):
            reg.admit("gold")
        for lease in held:
            lease.release()

    def test_lease_release_idempotent(self):
        reg = TenantRegistry(clock=TestClock())
        lease = reg.admit("a")
        lease.release()
        lease.release()  # second release is a no-op, not a double-free
        stats = reg.stats()[0]
        assert stats["inflight"] == 0


class TestDeadlineOverrun:
    def test_slow_solve_maps_to_deadline_overrun(self):
        clock = TestClock()
        reg = TenantRegistry(
            clock=clock,
            qos={"standard": TenantQoS(solve_deadline=1.0)},
        )
        svc = TenantService(registry=reg)
        inj = faults.install(
            faults.FaultInjector(
                [
                    faults.FaultRule(
                        faults.TENANT_SOLVE,
                        latency=5.0,
                        match=lambda ctx: ctx.get("tenant") == "slow",
                    )
                ],
                clock=clock,
            )
        )
        try:
            with pytest.raises(DeadlineOverrunError) as exc_info:
                svc.solve_for("slow", snap(make_request(2, "slow")))
            assert exc_info.value.tenant == "slow"
            assert exc_info.value.elapsed >= 5.0
            # the overrun consumed the lease — nothing left in flight
            assert reg.get("slow").stats()["inflight"] == 0
            assert reg.get("slow").stats()["deadline_overruns"] == 1
            # an unmatched tenant on the same service is untouched
            results = svc.solve_for("fast", snap(make_request(2, "fast")))
            assert results.all_pods_scheduled()
        finally:
            faults.uninstall()
        assert inj.fired(faults.TENANT_SOLVE) == 1


# -- noisy-neighbor fault isolation ------------------------------------------


def _chaos_rules(victim: str):
    """The tenant-scoped chaos plan: a kernel dispatch crash (absorbed by
    the victim's OWN ladder as a rung failure), corrupt kernel output
    (trips the invariant guard → quarantine), a corrupt encode delta,
    and a service-level solve crash that surfaces to the victim's caller
    — all pinned to ``victim`` via the ambient fault ctx."""

    def only_victim(ctx):
        return ctx.get("tenant") == victim

    def corrupt_fills(outs):
        outs = list(outs)
        outs[5] = np.asarray(outs[5]) - 7  # claim_fills negative
        return tuple(outs)

    return [
        faults.FaultRule(
            faults.SOLVER_DISPATCH, times=1, match=only_victim
        ),
        # times=2: the guard's FIRST rejection on a warm encoding takes
        # the delta-fallback half-step (shed + full re-encode retry), so
        # the corruption must persist through the retry to prove the
        # quarantine leg
        faults.FaultRule(
            faults.SOLVER_OUTPUT,
            mutate=corrupt_fills,
            times=2,
            match=only_victim,
        ),
        faults.FaultRule(
            faults.ENCODE_DELTA,
            mutate=lambda vals: np.asarray(vals) + 13,
            match=only_victim,
        ),
        faults.FaultRule(
            faults.TENANT_SOLVE, times=1, after=1, match=only_victim
        ),
    ]


def _run_chaos(clock, a_reqs, b_reqs, seed=7):
    """One chaos run: tenants a (victim) and b (bystander) interleaved
    through one service while a's fault plan fires. Returns (service,
    injector, b's decision signatures, a's error count)."""
    reg = TenantRegistry(clock=clock)
    svc = TenantService(registry=reg, config=SolverConfig(relax=False))
    inj = faults.install(
        faults.FaultInjector(_chaos_rules("a"), seed=seed, clock=clock)
    )
    b_sigs = []
    a_errors = 0
    try:
        for a_req, b_req in zip(a_reqs, b_reqs):
            try:
                svc.solve_for("a", snap(a_req))
            except faults.InjectedFault:
                a_errors += 1
            b_sigs.append(
                decision_signature(svc.solve_for("b", snap(b_req)))
            )
    finally:
        faults.uninstall()
    return svc, inj, b_sigs, a_errors


class TestFaultIsolation:
    """THE tentpole witness: tenant A's chaos plan must not move tenant
    B's decisions, rung, or fallback count by one bit."""

    N_ROUNDS = 4

    def _requests(self):
        a_reqs = [
            make_request(3 + i, f"a{i}", pods_kwargs={"cpu": "1", "memory": "1Gi"})
            for i in range(self.N_ROUNDS)
        ]
        b_reqs = [
            make_request(2 + i, f"b{i}", pods_kwargs={"cpu": "1", "memory": "1Gi"})
            for i in range(self.N_ROUNDS)
        ]
        return a_reqs, b_reqs

    def test_bystander_byte_identical_under_neighbor_chaos(self):
        a_reqs, b_reqs = self._requests()

        # fault-free solo baseline for tenant B: same request bytes,
        # fresh single-tenant service, no injector
        baseline_svc = TenantService(config=SolverConfig(relax=False))
        baseline = [
            decision_signature(baseline_svc.solve_for("b", snap(r)))
            for r in b_reqs
        ]

        svc, inj, b_sigs, a_errors = _run_chaos(
            TestClock(), a_reqs, b_reqs
        )

        # the chaos plan actually fired on A ...
        fired_sites = {s for s, _, _ in inj.log}
        assert faults.SOLVER_OUTPUT in fired_sites
        assert faults.SOLVER_DISPATCH in fired_sites
        assert faults.TENANT_SOLVE in fired_sites
        a = svc.registry.get("a")
        assert a.health.quarantines >= 1  # corrupt output → quarantine
        assert a.health.level() > 0  # victim rode DOWN its own ladder
        assert a_errors >= 1  # the service-level crash surfaced to A

        # ... and B never noticed: byte-identical decisions, rung still
        # batched, zero in-process fallbacks, zero warm-state sheds
        assert b_sigs == baseline
        b = svc.registry.get("b")
        assert b.health.RUNGS[b.health.level()] == "batched"
        assert b.health.quarantines == 0
        assert b.health.delta_fallbacks == 0
        assert b.stats()["fallback_solves"] == 0
        assert b.stats()["rejected"] == 0  # no overcommit shed B's work

    def test_victim_recovers_after_faults_clear(self):
        a_reqs, b_reqs = self._requests()
        clock = TestClock()
        svc, inj, _, _ = _run_chaos(clock, a_reqs, b_reqs)
        a = svc.registry.get("a")
        assert a.health.level() > 0
        inj.clear()
        clock.step(130.0)  # past the 120 s breaker cool-down
        results = svc.solve_for(
            "a", snap(make_request(3, "a-recover"))
        )
        assert results.all_pods_scheduled()
        # the half-open probe succeeded: the ladder re-closed
        assert a.health.level() == 0

    def test_fault_log_replay_deterministic(self):
        """Two runs of the same seeded plan over the same request bytes
        must produce identical injector logs AND identical victim-side
        outcomes — the chaos schedule is replayable evidence, not noise."""
        a_reqs, b_reqs = self._requests()
        _, inj1, sigs1, errs1 = _run_chaos(TestClock(), a_reqs, b_reqs)
        _, inj2, sigs2, errs2 = _run_chaos(TestClock(), a_reqs, b_reqs)
        assert inj1.log == inj2.log
        assert inj1.log  # the plan fired at least once
        assert sigs1 == sigs2
        assert errs1 == errs2


# -- cross-tenant batching ----------------------------------------------------


class TestCrossTenantBatching:
    def _svc(self, window=0.5):
        return TenantService(
            registry=TenantRegistry(clock=TestClock()),
            batch_window=window,
        )

    def _pair_solve(self, svc, reqs):
        out = {}
        errors = []

        def run(tid, req):
            try:
                out[tid] = svc.solve_for(tid, snap(req))
            except Exception as exc:  # pragma: no cover
                errors.append(exc)

        threads = [
            threading.Thread(target=run, args=item) for item in reqs.items()
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(120)
        assert not errors, errors
        return out

    def test_batched_decisions_match_solo(self):
        """Same-shape solves from two tenants ride ONE grouped dispatch
        and still decide exactly what each would decide alone — including
        existing-node packing against each tenant's own nodes."""
        sn_a = make_state_node(name="a-node", cpu="4", memory="16Gi")
        sn_a.node.provider_id = "ktpu://a-node"
        sn_b = make_state_node(name="b-node", cpu="4", memory="16Gi")
        sn_b.node.provider_id = "ktpu://b-node"
        reqs = {
            "a": make_request(
                4, "a", state_nodes=[sn_a],
                pods_kwargs={"cpu": "1", "memory": "1Gi"},
            ),
            "b": make_request(
                3, "b", state_nodes=[sn_b],
                pods_kwargs={"cpu": "1", "memory": "1Gi"},
            ),
        }
        svc = self._svc()
        out = self._pair_solve(svc, reqs)
        assert svc.batcher.counts()["batched"] == 1

        solo = TenantService(registry=TenantRegistry(clock=TestClock()))
        for tid, req in reqs.items():
            assert decision_signature(out[tid]) == decision_signature(
                solo.solve_for(tid, snap(req))
            ), f"tenant {tid} diverged under batching"
        # each tenant's existing nodes stay its own
        assert {e.name for e in out["a"].existing_nodes} <= {"a-node"}
        assert {e.name for e in out["b"].existing_nodes} <= {"b-node"}

    def test_unbatchable_shapes_decline_to_solo(self):
        """Topology-spread pods and nodes without provider ids can't be
        proven batch-safe — they must solo-solve, never batch wrong."""
        pods = make_pods(3, cpu="1", memory="1Gi")
        for p in pods:
            p.spec.topology_spread_constraints = [
                spread_constraint("topology.kubernetes.io/zone")
            ]
        assert _batch_key(snap(make_request(0, "x", pods=pods))) is None

        anon = make_state_node(name="anon-node")  # no provider id
        assert (
            _batch_key(snap(make_request(2, "y", state_nodes=[anon])))
            is None
        )

        # plain shapes DO get a key, and identical catalogs share it
        k1 = _batch_key(snap(make_request(2, "p")))
        k2 = _batch_key(snap(make_request(5, "q")))
        assert k1 is not None and k1 == k2

    def test_overlapping_provider_ids_decline(self):
        """Two tenants claiming the same node can't share a union solve —
        the grouped path declines and both still get solo answers."""
        def mk(prefix):
            sn = make_state_node(name=f"{prefix}-node")
            sn.node.provider_id = "ktpu://SHARED"  # the conflict
            return make_request(
                2, prefix, state_nodes=[sn],
                pods_kwargs={"cpu": "1", "memory": "1Gi"},
            )

        svc = self._svc()
        out = self._pair_solve(svc, {"a": mk("a"), "b": mk("b")})
        assert svc.batcher.counts()["declined"] == 1
        for res in out.values():
            assert res.all_pods_scheduled()

    def test_degraded_tenant_leaves_the_batch_lane(self):
        """A tenant riding a lower rung solves solo: its degradation must
        not leak latency or rung pressure into the shared batch."""
        svc = self._svc()
        degraded = svc.registry.get_or_create("a")
        degraded.health.quarantine("kernel", "injected")
        assert degraded.health.level() > 0
        results = svc.solve_for("a", snap(make_request(2, "a")))
        assert results.all_pods_scheduled()
        assert svc.batcher.counts() == {"batched": 0, "declined": 0}


# -- sidecar error contract over the gRPC hop ---------------------------------


class TestErrorContract:
    def test_injected_backpressure_raises_never_falls_back(self):
        import grpc

        faults.install(
            faults.FaultInjector(
                [
                    faults.FaultRule(
                        faults.REMOTE_SOLVE,
                        error=lambda: InjectedRpcError(
                            grpc.StatusCode.RESOURCE_EXHAUSTED
                        ),
                    )
                ]
            )
        )
        try:
            remote = RemoteSolver(
                "127.0.0.1:1", POOLS, TYPES, tenant="acme"
            )
            with pytest.raises(SolverBackpressure) as exc_info:
                remote.solve(make_pods(2))
            assert exc_info.value.tenant == "acme"
            # the whole point: backpressure does NOT solve in-process
            assert remote.fallback_solves == 0
            remote.close()
        finally:
            faults.uninstall()

    def test_injected_deadline_still_falls_back_in_process(self):
        import grpc

        faults.install(
            faults.FaultInjector(
                [
                    faults.FaultRule(
                        faults.REMOTE_SOLVE,
                        error=lambda: InjectedRpcError(
                            grpc.StatusCode.DEADLINE_EXCEEDED
                        ),
                    )
                ]
            )
        )
        try:
            remote = RemoteSolver(
                "127.0.0.1:1", POOLS, TYPES, tenant="acme"
            )
            results = remote.solve(make_pods(2))
            assert results.all_pods_scheduled()
            assert remote.fallback_solves == 1
            remote.close()
        finally:
            faults.uninstall()

    def test_real_sidecar_admission_rejection_leg(self):
        """End to end through a real server: an over-quota tenant gets
        RESOURCE_EXHAUSTED → SolverBackpressure; a different tenant on
        the same sidecar is still served."""
        clock = TestClock()
        server = serve(
            registry=TenantRegistry(
                clock=clock,
                qos={"standard": TenantQoS(rate=0.0, burst=1.0)},
            )
        )
        try:
            target = f"127.0.0.1:{server._bound_port}"
            greedy = RemoteSolver(target, POOLS, TYPES, tenant="greedy")
            assert greedy.solve(make_pods(2)).all_pods_scheduled()
            with pytest.raises(SolverBackpressure):
                greedy.solve(make_pods(2))  # bucket empty, rate 0
            assert greedy.fallback_solves == 0
            other = RemoteSolver(target, POOLS, TYPES, tenant="other")
            assert other.solve(make_pods(2)).all_pods_scheduled()
            assert other.fallback_solves == 0
            greedy.close()
            other.close()
        finally:
            server.stop(0)

    def test_real_sidecar_deadline_overrun_leg(self):
        """End to end: a per-tenant deadline overrun maps to
        DEADLINE_EXCEEDED, which the client treats as a slow sidecar —
        retry, then fall back in-process."""
        clock = TestClock()
        faults.install(
            faults.FaultInjector(
                [
                    faults.FaultRule(
                        faults.TENANT_SOLVE,
                        latency=10.0,
                        match=lambda ctx: ctx.get("tenant") == "slow",
                    )
                ],
                clock=clock,
            )
        )
        server = serve(
            registry=TenantRegistry(
                clock=clock,
                qos={"standard": TenantQoS(solve_deadline=1.0)},
            )
        )
        try:
            target = f"127.0.0.1:{server._bound_port}"
            slow = RemoteSolver(target, POOLS, TYPES, tenant="slow")
            results = slow.solve(make_pods(2))
            assert results.all_pods_scheduled()
            assert slow.fallback_solves == 1  # fell back, didn't fail
            slow.close()
        finally:
            server.stop(0)
            faults.uninstall()


# -- metrics hygiene ----------------------------------------------------------


class TestTenantMetricsHygiene:
    def test_tenant_labels_stay_bounded_under_id_spray(self):
        """A client spraying fresh tenant ids must not mint unbounded
        metric series: the registry's max_tenants bound caps every
        tenant label, and capacity rejections collapse onto the fixed
        '(capacity)' label."""
        from karpenter_tpu.solver import tenancy

        reg = TenantRegistry(
            clock=TestClock(),
            max_tenants=6,
            qos={"standard": TenantQoS(rate=0.0, burst=1.0)},
        )
        for i in range(40):
            tid = f"spray-{i}"
            try:
                reg.admit(tid).release()
            except AdmissionError:
                pass
            # drain the one burst token so the NEXT admit rate-limits
            try:
                reg.admit(tid).release()
            except AdmissionError:
                pass
        assert len(reg.tenant_ids()) == 6

        rejection_series = {
            frozenset(labels.items())
            for _, _, labels, _ in tenancy.TENANT_REJECTIONS.collect()
        }
        sprayed = {
            dict(s).get("tenant")
            for s in rejection_series
            if dict(s).get("tenant", "").startswith("spray-")
        }
        assert len(sprayed) <= 6  # only MINTED tenants have labels
        assert any(
            dict(s).get("tenant") == "(capacity)" for s in rejection_series
        )

        from test_obs import TestRegistryRenderer

        offenders = REGISTRY.check_cardinality(
            exempt=TestRegistryRenderer.IDENTITY_PREFIXES
        )
        assert not offenders, offenders

    def test_per_tenant_rung_series(self):
        reg = TenantRegistry(clock=TestClock())
        a = reg.get_or_create("a")
        b = reg.get_or_create("b")
        a.health.quarantine("kernel", "injected")
        from karpenter_tpu.faults.breaker import DEGRADATION_RUNG

        assert DEGRADATION_RUNG.value(labels={"tenant": "a"}) == 2.0
        assert DEGRADATION_RUNG.value(labels={"tenant": "b"}) == 0.0


# -- tenant observability -----------------------------------------------------


class TestTenantObservability:
    def test_spans_audit_and_trace_schema_carry_tenant(self):
        import json
        import os

        tracer = obs.install(obs.Tracer(TestClock(), seed=3))
        svc = TenantService(registry=TenantRegistry(clock=TestClock()))
        svc.solve_for("acme", snap(make_request(2, "acme")))

        tenant_spans = [
            s for s in tracer.finished("tenant.solve")
            if s.attrs.get("tenant") == "acme"
        ]
        assert tenant_spans and tenant_spans[0].attrs["tier"] == "standard"

        # AUDIT is a process-global ring buffer (full-suite runs arrive
        # here at capacity, so length offsets are useless) — key on the
        # tenant attr itself, stitched via this test's trace ids
        trace_ids = {s.trace_id for s in tenant_spans}
        recs = [
            r for r in obs.AUDIT.query() if r.trace_id in trace_ids
        ]
        assert recs and any(
            r.attrs.get("tenant") == "acme" for r in recs
        )

        here = os.path.dirname(os.path.abspath(__file__))
        with open(
            os.path.join(os.path.dirname(here), "hack", "trace_schema.json"),
            encoding="utf-8",
        ) as fh:
            schema = json.load(fh)
        doc = tracer.export_chrome()
        assert obs.validate_chrome_trace(doc, schema) == []
        assert any(
            ev.get("args", {}).get("tenant") == "acme"
            for ev in doc["traceEvents"]
            if ev.get("name") == "tenant.solve"
        )

    def test_sidecar_span_carries_tenant_over_grpc(self):
        tracer = obs.install(obs.Tracer(TestClock(), seed=5))
        server = serve()
        try:
            remote = RemoteSolver(
                f"127.0.0.1:{server._bound_port}", POOLS, TYPES,
                tenant="acme",
            )
            remote.solve(make_pods(2))
            remote.close()
        finally:
            server.stop(0)
        sidecar = tracer.finished("sidecar.solve")
        assert sidecar and sidecar[0].attrs.get("tenant") == "acme"
