"""Scheduler behavior tests.

Scenario coverage mirrors the reference's provisioning suite
(pkg/controllers/provisioning/suite_test.go, scheduling/topology_test.go,
scheduling/instance_selection_test.go) against the host-side oracle
scheduler.
"""

import pytest

from karpenter_tpu.api import labels, resources as res
from karpenter_tpu.api.objects import (
    Node,
    NodeSelectorRequirement,
    ObjectMeta,
    Pod,
    PreferredSchedulingTerm,
    Taint,
    Toleration,
)
from karpenter_tpu.cloudprovider import corpus
from karpenter_tpu.kube import Client, TestClock
from karpenter_tpu.scheduling.scheduler import Scheduler
from karpenter_tpu.scheduling.topology import Topology

from helpers import (
    affinity_term,
    make_nodepool,
    make_pod,
    make_pods,
    spread_constraint,
)


def solve(
    pods,
    node_pools=None,
    instance_types=None,
    state_nodes=(),
    daemonset_pods=(),
    client=None,
):
    client = client or Client(TestClock())
    node_pools = [make_nodepool()] if node_pools is None else node_pools
    its = instance_types if instance_types is not None else corpus.generate(20)
    its_by_pool = {np.name: list(its) for np in node_pools}
    topology = Topology(client, state_nodes, node_pools, its_by_pool, pods)
    scheduler = Scheduler(
        node_pools,
        its_by_pool,
        topology,
        state_nodes=state_nodes,
        daemonset_pods=daemonset_pods,
    )
    return scheduler.solve(pods)


class TestBasicScheduling:
    def test_single_pod_single_node(self):
        results = solve([make_pod()])
        assert results.all_pods_scheduled()
        assert results.node_count() == 1

    def test_identical_pods_pack_together(self):
        # 10 x 1cpu pods should not need 10 nodes given types up to 96 cpu
        results = solve(make_pods(10, cpu="1", memory="1Gi"))
        assert results.all_pods_scheduled()
        assert results.node_count() == 1

    def test_oversized_pod_fails(self):
        results = solve([make_pod(cpu="1000")])
        assert not results.all_pods_scheduled()
        assert results.node_count() == 0

    def test_no_nodepools_fails(self):
        results = solve([make_pod()], node_pools=[])
        assert not results.all_pods_scheduled()

    def test_ffd_order_packs_large_first(self):
        # a 60-cpu pod and many small ones: big pod must land somewhere
        pods = [make_pod(cpu="60")] + make_pods(20, cpu="500m")
        results = solve(pods)
        assert results.all_pods_scheduled()

    def test_pods_requesting_unknown_resource_fail(self):
        results = solve([make_pod(extra_requests={"example.com/fpga": "1"})])
        assert not results.all_pods_scheduled()

    def test_gpu_pod_gets_gpu_node(self):
        results = solve([make_pod(extra_requests={"nvidia.com/gpu": "1"})],
                        instance_types=corpus.generate())
        assert results.all_pods_scheduled()
        claim = results.new_node_claims[0]
        assert all(
            "nvidia.com/gpu" in it.capacity for it in claim.instance_type_options
        )


class TestInstanceSelection:
    def test_node_selector_zone(self):
        results = solve([make_pod(node_selector={labels.TOPOLOGY_ZONE: "test-zone-b"})])
        assert results.all_pods_scheduled()
        claim = results.new_node_claims[0]
        assert claim.requirements.get(labels.TOPOLOGY_ZONE).values == {"test-zone-b"}

    def test_incompatible_zone_fails(self):
        results = solve([make_pod(node_selector={labels.TOPOLOGY_ZONE: "mars"})])
        assert not results.all_pods_scheduled()

    def test_arch_requirement(self):
        results = solve(
            [
                make_pod(
                    requirements=[
                        NodeSelectorRequirement(labels.ARCH, "In", ("arm64",))
                    ]
                )
            ]
        )
        assert results.all_pods_scheduled()
        claim = results.new_node_claims[0]
        for it in claim.instance_type_options:
            assert it.requirements.get(labels.ARCH).has("arm64")

    def test_incompatible_pods_get_separate_nodes(self):
        pods = [
            make_pod(node_selector={labels.ARCH: "amd64"}),
            make_pod(node_selector={labels.ARCH: "arm64"}),
        ]
        results = solve(pods)
        assert results.all_pods_scheduled()
        assert results.node_count() == 2

    def test_custom_label_requires_pool_definition(self):
        # a pod constraining a custom label fails against a pool that doesn't
        # define the key (requirements.go:177-191 asymmetry)
        pods = [
            make_pod(
                requirements=[
                    NodeSelectorRequirement(corpus.INSTANCE_FAMILY_LABEL, "In", ("r",))
                ]
            ),
        ]
        results = solve(pods)
        assert not results.all_pods_scheduled()

    def test_instance_type_filter_tightens_per_pod(self):
        # with the family key defined on the pool, the pod constraint narrows
        # the claim's instance types to that family
        pool = make_nodepool(
            requirements=[
                NodeSelectorRequirement(
                    corpus.INSTANCE_FAMILY_LABEL, "In", ("c", "m", "r")
                )
            ]
        )
        pods = [
            make_pod(),
            make_pod(
                requirements=[
                    NodeSelectorRequirement(corpus.INSTANCE_FAMILY_LABEL, "In", ("r",))
                ]
            ),
        ]
        results = solve(pods, node_pools=[pool], instance_types=corpus.generate())
        assert results.all_pods_scheduled()


class TestNodePools:
    def test_weight_order(self):
        pools = [
            make_nodepool("low", weight=1),
            make_nodepool("high", weight=50),
        ]
        results = solve([make_pod()], node_pools=pools)
        assert results.all_pods_scheduled()
        assert results.new_node_claims[0].template.node_pool_name == "high"

    def test_limits_restrict(self):
        # limit prohibits any instance launch (every type exceeds 1 cpu limit)
        pools = [make_nodepool("limited", limits={"cpu": "1"})]
        results = solve([make_pod()], node_pools=pools)
        assert not results.all_pods_scheduled()

    def test_limits_fall_back_to_other_pool(self):
        pools = [
            make_nodepool("limited", weight=50, limits={"cpu": "1"}),
            make_nodepool("open", weight=1),
        ]
        results = solve([make_pod()], node_pools=pools)
        assert results.all_pods_scheduled()
        assert results.new_node_claims[0].template.node_pool_name == "open"

    def test_taints_respected(self):
        pools = [
            make_nodepool(
                "tainted",
                weight=50,
                taints=[Taint(key="dedicated", value="infra", effect="NoSchedule")],
            ),
            make_nodepool("open", weight=1),
        ]
        results = solve([make_pod()], node_pools=pools)
        assert results.all_pods_scheduled()
        assert results.new_node_claims[0].template.node_pool_name == "open"

    def test_toleration_allows_tainted_pool(self):
        pools = [
            make_nodepool(
                "tainted",
                weight=50,
                taints=[Taint(key="dedicated", value="infra", effect="NoSchedule")],
            ),
            make_nodepool("open", weight=1),
        ]
        pod = make_pod(
            tolerations=[Toleration(key="dedicated", operator="Exists", effect="NoSchedule")]
        )
        results = solve([pod], node_pools=pools)
        assert results.all_pods_scheduled()
        assert results.new_node_claims[0].template.node_pool_name == "tainted"

    def test_pool_requirements_restrict_types(self):
        pools = [
            make_nodepool(
                "amd-only",
                requirements=[NodeSelectorRequirement(labels.ARCH, "In", ("amd64",))],
            )
        ]
        results = solve([make_pod()], node_pools=pools)
        assert results.all_pods_scheduled()
        for it in results.new_node_claims[0].instance_type_options:
            assert it.requirements.get(labels.ARCH).has("amd64")


class TestTopologySpread:
    def test_zonal_spread(self):
        app = {"app": "web"}
        pods = make_pods(
            6, labels=app, spread=[spread_constraint(labels.TOPOLOGY_ZONE, labels=app)]
        )
        results = solve(pods)
        assert results.all_pods_scheduled()
        # count domains across claims
        zone_counts = {}
        for claim in results.new_node_claims:
            zone = claim.requirements.get(labels.TOPOLOGY_ZONE)
            assert not zone.complement and len(zone.values) == 1
            z = next(iter(zone.values))
            zone_counts[z] = zone_counts.get(z, 0) + len(claim.pods)
        assert max(zone_counts.values()) - min(zone_counts.values()) <= 1
        assert len(zone_counts) == 3

    def test_hostname_spread_forces_nodes(self):
        app = {"app": "api"}
        pods = make_pods(
            4, labels=app, spread=[spread_constraint(labels.HOSTNAME, labels=app)]
        )
        results = solve(pods)
        assert results.all_pods_scheduled()
        assert results.node_count() == 4

    def test_hostname_anti_affinity_forces_nodes(self):
        app = {"app": "db"}
        pods = make_pods(
            3, labels=app, pod_anti_affinity=[affinity_term(labels.HOSTNAME, app)]
        )
        results = solve(pods)
        assert results.all_pods_scheduled()
        assert results.node_count() == 3

    def test_zonal_affinity_colocates(self):
        app = {"app": "cache"}
        pods = make_pods(
            5, labels=app, pod_affinity=[affinity_term(labels.TOPOLOGY_ZONE, app)]
        )
        results = solve(pods)
        assert results.all_pods_scheduled()
        zones = set()
        for claim in results.new_node_claims:
            zone = claim.requirements.get(labels.TOPOLOGY_ZONE)
            zones.update(zone.values)
        assert len(zones) == 1

    def test_zonal_anti_affinity_late_committal(self):
        # Reference semantics (topology_test.go:2678-2723): an in-flight claim
        # may land in any of its zones, so zonal self-anti-affinity blocks all
        # possible domains pessimistically — only one pod schedules per batch.
        app = {"app": "zk"}
        pods = make_pods(
            4, labels=app, pod_anti_affinity=[affinity_term(labels.TOPOLOGY_ZONE, app)]
        )
        results = solve(pods)
        assert len(results.pod_errors) == 3
        assert results.node_count() == 1

    def test_zonal_anti_affinity_with_existing_pods(self):
        # once zones are concrete (pods bound to real nodes), anti-affinity
        # pods land in the remaining empty zones
        client = Client(TestClock())
        app = {"app": "zk"}
        for i, zone in enumerate(["test-zone-a", "test-zone-b"]):
            node = Node(
                metadata=ObjectMeta(
                    name=f"n-{i}",
                    labels={labels.TOPOLOGY_ZONE: zone, labels.HOSTNAME: f"n-{i}"},
                )
            )
            client.create(node)
            client.create(
                make_pod(labels=app, node_name=f"n-{i}", phase="Running",
                         pod_anti_affinity=[affinity_term(labels.TOPOLOGY_ZONE, app)])
            )
        pods = make_pods(
            2, labels=app, pod_anti_affinity=[affinity_term(labels.TOPOLOGY_ZONE, app)]
        )
        results = solve(pods, client=client)
        # one lands in test-zone-c, the other can't (every zone blocked)
        assert len(results.pod_errors) == 1
        assert results.node_count() == 1
        claim = results.new_node_claims[0]
        assert claim.requirements.get(labels.TOPOLOGY_ZONE).values == {"test-zone-c"}

    def test_schedule_anyway_spread_is_relaxed(self):
        # A ScheduleAnyway spread over an impossible key is dropped during
        # relaxation. The selector must not select the pod itself: a group
        # whose selector matches the pod keeps applying via counting even
        # after the constraint is removed (topology.go getMatchingTopologies),
        # matching the reference's "violate max-skew ... ConsistOf(1, 2)"
        # behavior where relaxed pods can still fail.
        pods = make_pods(
            2,
            labels={"app": "soft"},
            spread=[
                spread_constraint(
                    "nonexistent.io/key",
                    labels={"app": "other"},
                    when_unsatisfiable="ScheduleAnyway",
                )
            ],
        )
        results = solve(pods)
        assert results.all_pods_scheduled()

    def test_do_not_schedule_spread_matching_self_cannot_relax(self):
        # DoNotSchedule over a domainless key with a self-matching selector
        # fails permanently (reference parity)
        app = {"app": "hard"}
        pods = make_pods(
            2,
            labels=app,
            spread=[spread_constraint("nonexistent.io/key", labels=app)],
        )
        results = solve(pods)
        assert len(results.pod_errors) == 2


class TestPreferenceRelaxation:
    def test_unsatisfiable_preferred_affinity_dropped(self):
        pod = make_pod(
            preferred=[
                PreferredSchedulingTerm(
                    weight=10,
                    requirements=(
                        NodeSelectorRequirement(labels.TOPOLOGY_ZONE, "In", ("mars",)),
                    ),
                )
            ]
        )
        results = solve([pod])
        assert results.all_pods_scheduled()

    def test_satisfiable_preference_honored(self):
        pod = make_pod(
            preferred=[
                PreferredSchedulingTerm(
                    weight=10,
                    requirements=(
                        NodeSelectorRequirement(
                            labels.TOPOLOGY_ZONE, "In", ("test-zone-c",)
                        ),
                    ),
                )
            ]
        )
        results = solve([pod])
        assert results.all_pods_scheduled()
        claim = results.new_node_claims[0]
        assert claim.requirements.get(labels.TOPOLOGY_ZONE).values == {"test-zone-c"}


class TestExistingNodes:
    def _state_node(self, client, cpu="16", zone="test-zone-a"):
        from karpenter_tpu.controllers.state import StateNode

        node = Node(
            metadata=ObjectMeta(
                name="existing-1",
                labels={
                    labels.TOPOLOGY_ZONE: zone,
                    labels.HOSTNAME: "existing-1",
                    labels.ARCH: "amd64",
                    labels.OS: "linux",
                    labels.INSTANCE_TYPE: "m-16x-amd64-linux",
                },
            ),
        )
        node.status.capacity = {
            "cpu": res.parse_quantity(cpu),
            "memory": res.parse_quantity("64Gi"),
            "pods": res.parse_quantity("110"),
        }
        node.status.allocatable = dict(node.status.capacity)
        node.status.ready = True
        client.create(node)
        return StateNode(node=node)

    def test_pods_prefer_existing_capacity(self):
        client = Client(TestClock())
        sn = self._state_node(client)
        results = solve(make_pods(3, cpu="1"), state_nodes=[sn], client=client)
        assert results.all_pods_scheduled()
        assert results.node_count() == 0
        assert len(results.existing_nodes[0].pods) == 3

    def test_overflow_to_new_node(self):
        client = Client(TestClock())
        sn = self._state_node(client, cpu="2")
        results = solve(make_pods(4, cpu="1"), state_nodes=[sn], client=client)
        assert results.all_pods_scheduled()
        assert results.node_count() == 1
        assert len(results.existing_nodes[0].pods) == 2


class TestDaemonOverhead:
    def test_daemon_requests_reserved_on_new_nodes(self):
        daemon = make_pod(cpu="1", memory="1Gi")
        # smallest type is 1 cpu; with 1 cpu daemon overhead a 1-cpu pod
        # cannot fit the 1x types
        results = solve(
            [make_pod(cpu="1")],
            daemonset_pods=[daemon],
            instance_types=corpus.generate(20),
        )
        assert results.all_pods_scheduled()
        claim = results.new_node_claims[0]
        for it in claim.instance_type_options:
            assert it.allocatable()["cpu"] >= res.parse_quantity("2")


class TestResultsTruncation:
    def test_truncate_instance_types(self):
        results = solve(make_pods(2), instance_types=corpus.generate(100))
        results.truncate_instance_types(10)
        for claim in results.new_node_claims:
            assert len(claim.instance_type_options) <= 10

    def test_total_price_positive(self):
        results = solve(make_pods(3))
        assert results.total_price() > 0


class TestTopologyOwnership:
    def test_unconstrained_pods_not_bound_by_others_spread(self):
        # Pods matched by ANOTHER pod's spread selector but carrying no
        # constraint of their own must not be domain-restricted
        # (topology.go:513-528: forward groups apply to owners only)
        app = {"app": "x"}
        spread_pod = make_pod(
            labels=app, spread=[spread_constraint(labels.TOPOLOGY_ZONE, labels=app)]
        )
        plain = make_pods(4, labels=app, cpu="1")
        results = solve([spread_pod] + plain)
        assert results.all_pods_scheduled()
        # plain pods pack together; only the spread pod is zone-pinned
        assert results.node_count() <= 2


class TestRelaxationIsolation:
    def test_relaxation_never_mutates_caller_pods(self):
        """Preference relaxation works on a private copy: the caller's pod
        objects (live store objects; pods shared across disruption probes)
        keep every term (the reference's cache-backed client hands its
        scheduler deep copies)."""
        from karpenter_tpu.api.objects import (
            NodeAffinity, NodeSelectorRequirement, PreferredSchedulingTerm,
        )
        from karpenter_tpu.api import labels as labels_mod

        affinity = NodeAffinity(
            required=[
                (
                    NodeSelectorRequirement(
                        labels_mod.TOPOLOGY_ZONE, "In", ("mars",)
                    ),
                ),
                (
                    NodeSelectorRequirement(
                        labels_mod.TOPOLOGY_ZONE, "In", ("test-zone-a",)
                    ),
                ),
            ],
            preferred=[
                PreferredSchedulingTerm(
                    weight=10,
                    requirements=(
                        NodeSelectorRequirement(
                            labels_mod.TOPOLOGY_ZONE, "In", ("test-zone-b",)
                        ),
                    ),
                )
            ],
        )
        pod = make_pod()
        pod.spec.node_affinity = affinity
        results = solve([pod])
        # the pod scheduled only because relaxation dropped the mars term
        assert pod.uid not in results.pod_errors
        # ...on a COPY: the caller's object is untouched
        assert len(pod.spec.node_affinity.required) == 2
        assert len(pod.spec.node_affinity.preferred) == 1
