"""TPU solver vs host oracle parity.

The BASELINE metric is packing-cost delta, so parity is asserted on node
count and total price (exact-assignment equality is not required — FFD
tie-breaks differ legitimately; see SURVEY.md §7.4.4).
"""

import numpy as np
import pytest

from karpenter_tpu.api import labels, resources as res
from karpenter_tpu.api.objects import NodeSelectorRequirement
from karpenter_tpu.cloudprovider import corpus
from karpenter_tpu.kube import Client, TestClock
from karpenter_tpu.scheduling.scheduler import Scheduler
from karpenter_tpu.scheduling.topology import Topology
from karpenter_tpu.solver import TpuSolver

from helpers import affinity_term, make_nodepool, make_pod, make_pods


def run_both(pods, node_pools=None, instance_types=None, limits=None):
    node_pools = node_pools or [make_nodepool(limits=limits)]
    its = instance_types if instance_types is not None else corpus.generate(20)
    its_by_pool = {np_.name: list(its) for np_ in node_pools}

    def fresh_topology(pods_):
        return Topology(Client(TestClock()), [], node_pools, its_by_pool, pods_)

    import copy

    oracle_pods = copy.deepcopy(pods)
    oracle = Scheduler(node_pools, its_by_pool, fresh_topology(oracle_pods))
    oracle_results = oracle.solve(oracle_pods)

    solver = TpuSolver(node_pools, its_by_pool, fresh_topology(pods))
    tpu_results = solver.solve(pods)
    return oracle_results, tpu_results


def assert_parity(oracle_results, tpu_results, cost_tol=0.0):
    assert len(tpu_results.pod_errors) == len(oracle_results.pod_errors)
    assert tpu_results.node_count() == oracle_results.node_count()
    o_cost, t_cost = oracle_results.total_price(), tpu_results.total_price()
    if o_cost > 0:
        assert abs(t_cost - o_cost) <= cost_tol * o_cost + 1e-9, (t_cost, o_cost)


class TestIdenticalPods:
    def test_config0_500_identical(self):
        """BASELINE config[0]: 500 identical pods, 10 types."""
        oracle_r, tpu_r = run_both(
            make_pods(500, cpu="1", memory="2Gi"), instance_types=corpus.generate(10)
        )
        assert_parity(oracle_r, tpu_r)

    def test_small_batch(self):
        oracle_r, tpu_r = run_both(make_pods(7, cpu="2", memory="4Gi"))
        assert_parity(oracle_r, tpu_r)

    def test_single_pod(self):
        oracle_r, tpu_r = run_both([make_pod()])
        assert_parity(oracle_r, tpu_r)


class TestMixedPods:
    def test_two_shapes(self):
        pods = make_pods(20, cpu="1", memory="1Gi") + make_pods(5, cpu="8", memory="16Gi")
        oracle_r, tpu_r = run_both(pods)
        assert_parity(oracle_r, tpu_r)

    def test_many_shapes(self, rng):
        pods = []
        for _ in range(30):
            cpu = int(rng.integers(1, 8))
            mem = int(rng.integers(1, 16))
            count = int(rng.integers(1, 12))
            pods += make_pods(count, cpu=str(cpu), memory=f"{mem}Gi")
        oracle_r, tpu_r = run_both(pods)
        assert_parity(oracle_r, tpu_r)

    def test_gpu_mix(self):
        pods = make_pods(10, cpu="1", memory="1Gi") + make_pods(
            4, cpu="2", memory="8Gi", extra_requests={"nvidia.com/gpu": "1"}
        )
        oracle_r, tpu_r = run_both(pods, instance_types=corpus.generate())
        assert_parity(oracle_r, tpu_r)


class TestConstrainedPods:
    def test_zone_selector(self):
        pods = make_pods(12, cpu="1", node_selector={labels.TOPOLOGY_ZONE: "test-zone-b"})
        oracle_r, tpu_r = run_both(pods)
        assert_parity(oracle_r, tpu_r)
        for claim in tpu_r.new_node_claims:
            assert claim.requirements.get(labels.TOPOLOGY_ZONE).values == {"test-zone-b"}

    def test_capacity_type_selector(self):
        pods = make_pods(
            6,
            cpu="1",
            node_selector={labels.CAPACITY_TYPE_LABEL_KEY: labels.CAPACITY_TYPE_ON_DEMAND},
        )
        oracle_r, tpu_r = run_both(pods)
        assert_parity(oracle_r, tpu_r)

    def test_arch_requirement(self):
        pods = make_pods(
            5,
            requirements=[NodeSelectorRequirement(labels.ARCH, "In", ("arm64",))],
        )
        oracle_r, tpu_r = run_both(pods)
        assert_parity(oracle_r, tpu_r)

    def test_impossible_zone(self):
        pods = make_pods(3, node_selector={labels.TOPOLOGY_ZONE: "mars"})
        oracle_r, tpu_r = run_both(pods)
        assert len(tpu_r.pod_errors) == 3
        assert_parity(oracle_r, tpu_r)

    def test_oversized(self):
        oracle_r, tpu_r = run_both([make_pod(cpu="1000")])
        assert len(tpu_r.pod_errors) == 1
        assert_parity(oracle_r, tpu_r)


class TestNodePoolInteraction:
    def test_weight_order(self):
        pools = [make_nodepool("low", weight=1), make_nodepool("high", weight=50)]
        oracle_r, tpu_r = run_both(make_pods(4), node_pools=pools)
        assert_parity(oracle_r, tpu_r)
        for claim in tpu_r.new_node_claims:
            assert claim.template.node_pool_name == "high"

    def test_limits_cap_claims(self):
        # cap at 40 cpu; each claim pessimistically debits the largest
        # option capacity
        pools = [make_nodepool("limited", limits={"cpu": "40"})]
        pods = make_pods(200, cpu="1", memory="1Gi")
        oracle_r, tpu_r = run_both(pods, node_pools=pools)
        assert_parity(oracle_r, tpu_r)
        assert len(tpu_r.pod_errors) > 0  # limit prevents scheduling them all

    def test_limits_fall_back(self):
        pools = [
            make_nodepool("limited", weight=50, limits={"cpu": "1"}),
            make_nodepool("open", weight=1),
        ]
        oracle_r, tpu_r = run_both(make_pods(3), node_pools=pools)
        assert_parity(oracle_r, tpu_r)
        for claim in tpu_r.new_node_claims:
            assert claim.template.node_pool_name == "open"


class TestHostnameTopology:
    """Hostname-keyed spread/anti-affinity ride the TPU fast path as
    per-entity caps (ops/packing.py; reference topologygroup.go:253-274,
    340-366)."""

    def test_hostname_spread_rides_fast_path(self):
        from helpers import spread_constraint

        app = {"app": "x"}
        pods = make_pods(6, cpu="1") + make_pods(
            3, labels=app, spread=[spread_constraint(labels.HOSTNAME, labels=app)]
        )
        node_pools = [make_nodepool()]
        its_by_pool = {"default": corpus.generate(20)}
        topo = Topology(Client(TestClock()), [], node_pools, its_by_pool, pods)
        solver = TpuSolver(node_pools, its_by_pool, topo)
        results = solver.solve(pods)
        assert results.all_pods_scheduled()
        # maxSkew=1 hostname spread: one spread pod per claim; plain pods
        # co-pack onto the same claims (no split-brain extra nodes)
        assert results.node_count() == 3
        for claim in results.new_node_claims:
            spread_pods = [p for p in claim.pods if p.metadata.labels.get("app") == "x"]
            assert len(spread_pods) <= 1

    def test_hostname_spread_parity(self):
        from helpers import spread_constraint

        app = {"app": "s"}
        pods = make_pods(
            9, cpu="1", labels=app,
            spread=[spread_constraint(labels.HOSTNAME, labels=app)],
        )
        oracle_r, tpu_r = run_both(pods)
        assert_parity(oracle_r, tpu_r)
        assert tpu_r.node_count() == 9
        for claim in tpu_r.new_node_claims:
            assert len(claim.pods) <= 1

    def test_hostname_spread_skew2_parity(self):
        from helpers import spread_constraint

        app = {"app": "s2"}
        pods = make_pods(
            10, cpu="1", labels=app,
            spread=[spread_constraint(labels.HOSTNAME, max_skew=2, labels=app)],
        )
        oracle_r, tpu_r = run_both(pods)
        assert_parity(oracle_r, tpu_r)
        assert tpu_r.node_count() == 5
        for claim in tpu_r.new_node_claims:
            assert len(claim.pods) <= 2

    def test_hostname_anti_affinity_parity(self):
        from karpenter_tpu.api.objects import LabelSelector, PodAffinityTerm

        app = {"app": "anti"}
        term = PodAffinityTerm(
            topology_key=labels.HOSTNAME,
            label_selector=LabelSelector(match_labels=dict(app)),
        )
        pods = make_pods(8, cpu="1", labels=app, pod_anti_affinity=[term])
        oracle_r, tpu_r = run_both(pods)
        assert_parity(oracle_r, tpu_r)
        assert tpu_r.node_count() == 8
        for claim in tpu_r.new_node_claims:
            assert len(claim.pods) <= 1

    def test_cross_group_selector_rides_contributor_carry(self):
        from karpenter_tpu.solver import encode as enc
        from helpers import spread_constraint

        # the spread selector also matches the plain pods' labels: the
        # plain group becomes a CONTRIBUTOR to the shared hostname carry
        # (its placements count toward the spreaders' skew) and the whole
        # batch stays on the fast path (round-2 behavior demoted all of it)
        app = {"app": "shared"}
        plain = make_pods(4, cpu="2", labels=app)
        spreaders = make_pods(
            3, cpu="1", labels=app,
            spread=[spread_constraint(labels.HOSTNAME, labels=app)],
        )
        pods = plain + spreaders
        node_pools = [make_nodepool()]
        its_by_pool = {"default": corpus.generate(20)}
        topo = Topology(Client(TestClock()), [], node_pools, its_by_pool, pods)
        groups, rest = enc.partition_and_group(pods, topology=topo)
        assert not rest, "contributor batch must tensorize fully"
        contrib = [
            g for g in groups
            if g.topo is not None and g.topo.contrib_h
        ]
        assert contrib, "plain group must carry a contribution row"
        # end-to-end schedules everything; every node holds at most
        # maxSkew selected pods ABOVE the running min — with plain pods
        # counting, a node with a plain pod is as full as one with a
        # spreader (the oracle's record() counts both)
        solver = TpuSolver(node_pools, its_by_pool, topo)
        results = solver.solve(pods)
        assert results.all_pods_scheduled()
        # skew audit: count selected pods (all 7 match app=shared) per
        # entity; hostname spread with maxSkew=1 and global min 0 means no
        # entity may hold more than 1 SPREADER, and spreaders must land on
        # entities where prior selected counts permit them
        for claim in results.new_node_claims:
            n_spread = sum(1 for p in claim.pods if p in spreaders)
            assert n_spread <= 1

    def test_non_self_selecting_spread_is_node_gate(self):
        from helpers import spread_constraint

        # the selector matches nothing pending or bound: counts never move,
        # so the constraint never blocks (0 <= maxSkew) and pods co-pack
        pods = make_pods(
            5, cpu="1", labels={"app": "x"},
            spread=[spread_constraint(labels.HOSTNAME, labels={"app": "other"})],
        )
        oracle_r, tpu_r = run_both(pods)
        assert_parity(oracle_r, tpu_r)
        assert tpu_r.node_count() == 1

    def test_non_self_selecting_anti_blocks_counted_node(self):
        from karpenter_tpu.api.objects import (
            LabelSelector, Node, ObjectMeta, PodAffinityTerm,
        )
        from karpenter_tpu.controllers.state import StateNode

        client = Client(TestClock())
        node = Node(
            metadata=ObjectMeta(
                name="busy-1",
                labels={
                    labels.TOPOLOGY_ZONE: "test-zone-a",
                    labels.HOSTNAME: "busy-1",
                },
            ),
        )
        node.status.capacity = {
            "cpu": res.parse_quantity("16"),
            "memory": res.parse_quantity("64Gi"),
            "pods": res.parse_quantity("110"),
        }
        node.status.allocatable = dict(node.status.capacity)
        node.status.ready = True
        client.create(node)
        blocker = make_pod(labels={"app": "y"}, node_name="busy-1", phase="Running")
        client.create(blocker)
        sn = StateNode(node=node)

        term = PodAffinityTerm(
            topology_key=labels.HOSTNAME,
            label_selector=LabelSelector(match_labels={"app": "y"}),
        )
        pods = make_pods(3, cpu="1", labels={"app": "z"}, pod_anti_affinity=[term])
        node_pools = [make_nodepool()]
        its_by_pool = {"default": corpus.generate(20)}
        topo = Topology(client, [sn], node_pools, its_by_pool, pods)
        solver = TpuSolver(node_pools, its_by_pool, topo, state_nodes=[sn])
        results = solver.solve(pods)
        assert results.all_pods_scheduled()
        # the counted node is gated; the fresh claim may hold all three
        # (their own anti selects app=y, not each other)
        for en in results.existing_nodes:
            assert not en.pods
        assert results.node_count() == 1

    def test_bound_inverse_anti_demotes_plain_group(self):
        from karpenter_tpu.api.objects import (
            LabelSelector, Node, ObjectMeta, PodAffinityTerm,
        )
        from karpenter_tpu.controllers.state import StateNode
        from karpenter_tpu.solver import encode as enc

        client = Client(TestClock())
        node = Node(
            metadata=ObjectMeta(
                name="anti-1",
                labels={
                    labels.TOPOLOGY_ZONE: "test-zone-a",
                    labels.HOSTNAME: "anti-1",
                },
            ),
        )
        node.status.capacity = {
            "cpu": res.parse_quantity("16"),
            "memory": res.parse_quantity("64Gi"),
            "pods": res.parse_quantity("110"),
        }
        node.status.allocatable = dict(node.status.capacity)
        node.status.ready = True
        client.create(node)
        # bound pod repels app=plain from its node
        term = PodAffinityTerm(
            topology_key=labels.HOSTNAME,
            label_selector=LabelSelector(match_labels={"app": "plain"}),
        )
        blocker = make_pod(
            labels={"app": "other"}, node_name="anti-1", phase="Running",
            pod_anti_affinity=[term],
        )
        client.create(blocker)
        sn = StateNode(node=node)

        pods = make_pods(3, cpu="1", labels={"app": "plain"})
        node_pools = [make_nodepool()]
        its_by_pool = {"default": corpus.generate(20)}
        topo = Topology(client, [sn], node_pools, its_by_pool, pods)
        groups, rest = enc.partition_and_group(pods, topology=topo)
        assert not groups and len(rest) == 3  # demoted to the oracle
        solver = TpuSolver(node_pools, its_by_pool, topo, state_nodes=[sn])
        results = solver.solve(pods)
        assert results.all_pods_scheduled()
        for en in results.existing_nodes:
            assert not en.pods  # oracle honors the bound pod's anti-affinity

    def test_cross_group_anti_takes_contributor_carry(self):
        from karpenter_tpu.api.objects import (
            LabelSelector, LabelSelectorRequirement, PodAffinityTerm,
        )
        from karpenter_tpu.solver import encode as enc

        # A's anti selects both its own labels and B's: B becomes a
        # CONTRIBUTOR (its placements block A's entities), both tensorized
        sel = LabelSelector(
            match_expressions=[
                LabelSelectorRequirement(key="app", operator="In", values=("a", "b"))
            ]
        )
        term = PodAffinityTerm(topology_key=labels.HOSTNAME, label_selector=sel)
        a_pods = make_pods(2, cpu="1", labels={"app": "a"}, pod_anti_affinity=[term])
        b_pods = make_pods(2, cpu="2", labels={"app": "b"})
        pods = a_pods + b_pods
        node_pools = [make_nodepool()]
        its_by_pool = {"default": corpus.generate(20)}
        topo = Topology(Client(TestClock()), [], node_pools, its_by_pool, pods)
        groups, rest = enc.partition_and_group(pods, topology=topo)
        assert not rest
        solver = TpuSolver(node_pools, its_by_pool, topo)
        results = solver.solve(pods)
        assert results.all_pods_scheduled()
        # B packs first (FFD: cpu desc), so A must avoid every B entity
        # and spread one-per-entity among themselves
        for claim in results.new_node_claims:
            n_a = sum(1 for p in claim.pods if p in a_pods)
            n_b = sum(1 for p in claim.pods if p in b_pods)
            assert n_a <= 1
            assert not (n_a and n_b), "anti-affinity pod co-located with blocker"

    def test_cross_group_anti_adverse_order_demotes(self):
        from karpenter_tpu.api.objects import (
            LabelSelector, LabelSelectorRequirement, PodAffinityTerm,
        )
        from karpenter_tpu.solver import encode as enc

        # Adverse FFD order: the anti-affinity OWNER group A has larger cpu
        # and packs first; contributor B packs after and is not gated by the
        # kernel, so admitting would let B land on A's entities — a placement
        # the oracle's inverse gating (topology.go:509-525) forbids. The
        # batch must route to the oracle instead.
        sel = LabelSelector(
            match_expressions=[
                LabelSelectorRequirement(key="app", operator="In", values=("a", "b"))
            ]
        )
        term = PodAffinityTerm(topology_key=labels.HOSTNAME, label_selector=sel)
        a_pods = make_pods(2, cpu="4", labels={"app": "a"}, pod_anti_affinity=[term])
        b_pods = make_pods(2, cpu="1", labels={"app": "b"})
        pods = a_pods + b_pods
        node_pools = [make_nodepool()]
        its_by_pool = {"default": corpus.generate(20)}
        topo = Topology(Client(TestClock()), [], node_pools, its_by_pool, pods)
        groups, rest = enc.partition_and_group(pods, topology=topo)
        assert not groups and len(rest) == 4  # oracle-routed, not tensorized
        solver = TpuSolver(node_pools, its_by_pool, topo)
        results = solver.solve(pods)
        assert results.all_pods_scheduled()
        for claim in results.new_node_claims:
            n_a = sum(1 for p in claim.pods if p in a_pods)
            n_b = sum(1 for p in claim.pods if p in b_pods)
            assert n_a <= 1
            assert not (n_a and n_b), "anti-affinity pod co-located with blocker"

    def test_cross_group_anti_gate_owner_order(self):
        from karpenter_tpu.api.objects import (
            LabelSelector, LabelSelectorRequirement, PodAffinityTerm,
        )
        from karpenter_tpu.solver import encode as enc

        # GATE owner: A owns the anti term but is NOT selected by it (the
        # term selects only app=b). Gate-owner placements are uncounted in
        # the kernel carry, so a SELECTED group packing after a gate owner
        # would not see the owner's entities — the oracle's inverse gating
        # forbids landing there. Adverse order (gate owner cpu larger →
        # packs first) must demote; safe order (selected group packs first)
        # stays tensorized.
        sel = LabelSelector(
            match_expressions=[
                LabelSelectorRequirement(key="app", operator="In", values=("b",))
            ]
        )
        term = PodAffinityTerm(topology_key=labels.HOSTNAME, label_selector=sel)
        node_pools = [make_nodepool()]
        its_by_pool = {"default": corpus.generate(20)}

        # adverse: gate owner A (cpu=4) packs before selected B (cpu=1)
        a_pods = make_pods(2, cpu="4", labels={"app": "a"}, pod_anti_affinity=[term])
        b_pods = make_pods(2, cpu="1", labels={"app": "b"})
        pods = a_pods + b_pods
        topo = Topology(Client(TestClock()), [], node_pools, its_by_pool, pods)
        groups, rest = enc.partition_and_group(pods, topology=topo)
        assert not groups and len(rest) == 4
        solver = TpuSolver(node_pools, its_by_pool, topo)
        results = solver.solve(pods)
        assert results.all_pods_scheduled()
        for claim in results.new_node_claims:
            n_a = sum(1 for p in claim.pods if p in a_pods)
            n_b = sum(1 for p in claim.pods if p in b_pods)
            assert not (n_a and n_b), "selected pod co-located with gate owner"

        # safe: selected B (cpu=4) packs before gate owner A (cpu=1)
        a2 = make_pods(2, cpu="1", labels={"app": "a"}, pod_anti_affinity=[term])
        b2 = make_pods(2, cpu="4", labels={"app": "b"})
        pods2 = a2 + b2
        topo2 = Topology(Client(TestClock()), [], node_pools, its_by_pool, pods2)
        groups2, rest2 = enc.partition_and_group(pods2, topology=topo2)
        assert len(groups2) == 2 and not rest2
        solver2 = TpuSolver(node_pools, its_by_pool, topo2)
        results2 = solver2.solve(pods2)
        assert results2.all_pods_scheduled()
        for claim in results2.new_node_claims:
            n_a = sum(1 for p in claim.pods if p in a2)
            n_b = sum(1 for p in claim.pods if p in b2)
            assert not (n_a and n_b), "selected pod co-located with gate owner"

    def test_cross_group_anti_tie_demotes(self):
        from karpenter_tpu.api.objects import (
            LabelSelector, LabelSelectorRequirement, PodAffinityTerm,
        )
        from karpenter_tpu.solver import encode as enc

        # Equal FFD keys: post-sort order of tied groups is build-order-
        # dependent, so order safety cannot be guaranteed — must demote.
        sel = LabelSelector(
            match_expressions=[
                LabelSelectorRequirement(key="app", operator="In", values=("a", "b"))
            ]
        )
        term = PodAffinityTerm(topology_key=labels.HOSTNAME, label_selector=sel)
        a_pods = make_pods(2, cpu="2", labels={"app": "a"}, pod_anti_affinity=[term])
        b_pods = make_pods(2, cpu="2", labels={"app": "b"})
        pods = a_pods + b_pods
        node_pools = [make_nodepool()]
        its_by_pool = {"default": corpus.generate(20)}
        topo = Topology(Client(TestClock()), [], node_pools, its_by_pool, pods)
        groups, rest = enc.partition_and_group(pods, topology=topo)
        assert not groups and len(rest) == 4

    def test_transitive_demotion(self):
        from karpenter_tpu.api.objects import (
            LabelSelector, LabelSelectorRequirement, PodAffinityTerm,
        )
        from karpenter_tpu.solver import encode as enc

        # A's anti selects an ORACLE-ROUTED pod (host ports force it off the
        # fast path): counting would be blind to the oracle's placements, so
        # A demotes — and A's selector then drags B (matched) transitively
        sel = LabelSelector(
            match_expressions=[
                LabelSelectorRequirement(
                    key="app", operator="In", values=("a", "b", "ported")
                )
            ]
        )
        term = PodAffinityTerm(topology_key=labels.HOSTNAME, label_selector=sel)
        a_pods = make_pods(2, cpu="1", labels={"app": "a"}, pod_anti_affinity=[term])
        b_pods = make_pods(2, cpu="2", labels={"app": "b"})
        ported = make_pods(1, cpu="1", labels={"app": "ported"}, host_ports=(8080,))
        pods = a_pods + b_pods + ported
        node_pools = [make_nodepool()]
        its_by_pool = {"default": corpus.generate(20)}
        topo = Topology(Client(TestClock()), [], node_pools, its_by_pool, pods)
        groups, rest = enc.partition_and_group(pods, topology=topo)
        assert not groups and len(rest) == 5

    def test_schedule_anyway_spread_falls_back(self):
        from karpenter_tpu.solver import encode as enc
        from helpers import spread_constraint

        app = {"app": "soft"}
        pods = make_pods(
            3, labels=app,
            spread=[
                spread_constraint(
                    labels.HOSTNAME, labels=app, when_unsatisfiable="ScheduleAnyway"
                )
            ],
        )
        node_pools = [make_nodepool()]
        its_by_pool = {"default": corpus.generate(20)}
        topo = Topology(Client(TestClock()), [], node_pools, its_by_pool, pods)
        groups, rest = enc.partition_and_group(pods, topology=topo)
        assert not groups and len(rest) == 3


class TestHostnameAffinity:
    """Hostname-keyed required pod affinity (co-locate on ONE node) rides
    the kernel's single-entity pin (topologygroup.go:277-324 hostname
    case): bootstrap picks the first fitting entity, priors pin to the
    nodes already holding matching pods, overflow errors instead of
    spilling to a second entity."""

    def _solve(self, pods, state_nodes=(), backend="tpu", n_types=20):
        from karpenter_tpu.solver.driver import SolverConfig

        node_pools = [make_nodepool()]
        its_by_pool = {"default": corpus.generate(n_types)}
        client = Client(TestClock())
        for sn in state_nodes:
            client.create(sn.node)
            for p in sn.pods:
                client.create(p)
        topo = Topology(client, list(state_nodes), node_pools, its_by_pool, pods)
        solver = TpuSolver(
            node_pools, its_by_pool, topo, state_nodes=list(state_nodes),
            config=SolverConfig(backend=backend),
        )
        return solver, solver.solve(pods)

    def _mk_aff_pods(self, n, cpu="1", lbl=None):
        lbl = lbl or {"app": "colo"}
        term = affinity_term(labels.HOSTNAME, lbl)
        return make_pods(n, cpu=cpu, labels=lbl, pod_affinity=[term])

    @pytest.mark.parametrize("backend", ["tpu", "native"])
    def test_bootstrap_colocates_on_one_claim(self, backend):
        from karpenter_tpu.solver import encode as enc

        pods = self._mk_aff_pods(5)
        solver, results = self._solve(pods, backend=backend)
        groups, rest = enc.partition_and_group(
            pods, topology=solver.oracle.topology
        )
        assert groups and not rest  # tensorized, not oracle-routed
        assert results.all_pods_scheduled()
        holders = [c for c in results.new_node_claims if c.pods]
        assert len(holders) == 1 and len(holders[0].pods) == 5

    @pytest.mark.parametrize("backend", ["tpu", "native"])
    def test_prior_pins_to_existing_node(self, backend):
        from karpenter_tpu.api.objects import Node, ObjectMeta
        from karpenter_tpu.controllers.state import StateNode

        lbl = {"app": "colo"}
        node = Node(
            metadata=ObjectMeta(
                name="aff-n1",
                labels={
                    labels.TOPOLOGY_ZONE: "test-zone-a",
                    labels.HOSTNAME: "aff-n1",
                },
            ),
        )
        node.status.capacity = {
            "cpu": res.parse_quantity("16"),
            "memory": res.parse_quantity("64Gi"),
            "pods": res.parse_quantity("110"),
        }
        node.status.allocatable = dict(node.status.capacity)
        node.status.ready = True
        sn = StateNode(node=node)
        bound = make_pod(
            labels=dict(lbl), node_name="aff-n1", phase="Running",
        )
        sn.update_pod(bound, is_daemon=False)

        pods = self._mk_aff_pods(4)
        solver, results = self._solve(pods, state_nodes=[sn], backend=backend)
        assert results.all_pods_scheduled()
        assert not results.new_node_claims  # all followed the prior node
        en = results.existing_nodes[0]
        assert len(en.pods) == 4

    @pytest.mark.parametrize("backend", ["tpu", "native"])
    def test_overflow_errors_not_second_entity(self, backend):
        # pods than no single node type can hold: the remainder must error
        # (the oracle refuses a second hostname domain), never split
        pods = self._mk_aff_pods(400, cpu="1")
        solver, results = self._solve(pods, backend=backend, n_types=8)
        holders = [c for c in results.new_node_claims if c.pods]
        assert len(holders) == 1
        assert len(holders[0].pods) + len(results.pod_errors) == 400
        assert results.pod_errors  # some pods must not fit one node

    @pytest.mark.parametrize("backend", ["tpu", "native"])
    def test_partial_pin_reports_remainder(self, backend):
        from karpenter_tpu.api.objects import Node, ObjectMeta
        from karpenter_tpu.controllers.state import StateNode

        lbl = {"app": "colo"}
        node = Node(
            metadata=ObjectMeta(
                name="aff-small",
                labels={
                    labels.TOPOLOGY_ZONE: "test-zone-a",
                    labels.HOSTNAME: "aff-small",
                },
            ),
        )
        node.status.capacity = {
            "cpu": res.parse_quantity("4"),
            "memory": res.parse_quantity("8Gi"),
            "pods": res.parse_quantity("110"),
        }
        node.status.allocatable = dict(node.status.capacity)
        node.status.ready = True
        sn = StateNode(node=node)
        bound = make_pod(
            cpu="1", labels=dict(lbl), node_name="aff-small", phase="Running",
        )
        sn.update_pod(bound, is_daemon=False)

        # 6 x 1cpu pods onto a node with 3 cpu left: 3 follow the prior,
        # 3 MUST error (the oracle refuses any other hostname domain) —
        # never silently vanish, never land on a fresh claim
        pods = self._mk_aff_pods(6, cpu="1")
        solver, results = self._solve(pods, state_nodes=[sn], backend=backend)
        assert not results.new_node_claims
        placed = sum(len(e.pods) for e in results.existing_nodes)
        assert placed == 3
        assert len(results.pod_errors) == 3

    def test_prior_outside_snapshot_demotes(self):
        from karpenter_tpu.api.objects import Node, ObjectMeta
        from karpenter_tpu.solver import encode as enc

        # the matching bound pod's node is known to the CLIENT but not part
        # of the solve's state nodes (e.g. deleting): the kernel's candidate
        # rows can't express the pin — must route to the oracle
        lbl = {"app": "colo"}
        node_pools = [make_nodepool()]
        its_by_pool = {"default": corpus.generate(20)}
        client = Client(TestClock())
        gone = Node(
            metadata=ObjectMeta(
                name="gone-node", labels={labels.HOSTNAME: "gone-node"}
            ),
        )
        gone.status.ready = True
        client.create(gone)
        bound = make_pod(
            labels=dict(lbl), node_name="gone-node", phase="Running"
        )
        client.create(bound)
        pods = self._mk_aff_pods(3)
        topo = Topology(client, [], node_pools, its_by_pool, pods)
        groups, rest = enc.partition_and_group(pods, topology=topo)
        assert not groups and len(rest) == 3

    def test_matches_oracle_bootstrap(self):
        from karpenter_tpu.solver.driver import SolverConfig

        pods = self._mk_aff_pods(6)
        _, kernel = self._solve(pods)
        node_pools = [make_nodepool()]
        its_by_pool = {"default": corpus.generate(20)}
        topo = Topology(Client(TestClock()), [], node_pools, its_by_pool, pods)
        oracle = TpuSolver(
            node_pools, its_by_pool, topo,
            config=SolverConfig(force_oracle=True),
        ).solve(pods)
        assert oracle.all_pods_scheduled() and kernel.all_pods_scheduled()
        k_hold = [c for c in kernel.new_node_claims if c.pods]
        o_hold = [c for c in oracle.new_node_claims if c.pods]
        assert len(k_hold) == len(o_hold) == 1
        assert len(k_hold[0].pods) == len(o_hold[0].pods) == 6

    def test_gate_affinity_demotes(self):
        from karpenter_tpu.solver import encode as enc

        # owner not selected by its own term: candidates never grow — the
        # oracle's bootstrap right doesn't apply; stays host-side
        term = affinity_term(labels.HOSTNAME, {"app": "other"})
        pods = make_pods(3, labels={"app": "mine"}, pod_affinity=[term])
        node_pools = [make_nodepool()]
        its_by_pool = {"default": corpus.generate(20)}
        topo = Topology(Client(TestClock()), [], node_pools, its_by_pool, pods)
        groups, rest = enc.partition_and_group(pods, topology=topo)
        assert not groups and len(rest) == 3


class TestDiverseReferenceMix:
    """The reference's literal 5-class benchmark mix at unit scale: generic
    + cross-selecting zonal/hostname spread (gates + contributors via the
    shared-constraint carries) + zonal self-affinity families + hostname
    anti-affinity — the heaviest encode machinery in one batch, pinned
    against the oracle (scheduling_benchmark_test.go:236-249)."""

    def test_kernel_matches_oracle(self):
        from karpenter_tpu.solver.driver import EncodeCache, SolverConfig
        from karpenter_tpu.solver.example import example_nodepool
        from karpenter_tpu.solver.workloads import diverse_reference_mix

        pods = diverse_reference_mix(800)
        pools = [example_nodepool()]
        its_by_pool = {pools[0].name: corpus.generate(60)}
        cache = EncodeCache()

        def solve(force):
            topo = Topology(
                Client(TestClock()), [], pools, its_by_pool, pods
            )
            return TpuSolver(
                pools, its_by_pool, topo,
                config=SolverConfig(force_oracle=force),
                encode_cache=cache,
            ).solve(pods)

        kernel = solve(False)
        oracle = solve(True)
        assert len(kernel.pod_errors) == len(oracle.pod_errors) == 0
        assert kernel.node_count() == oracle.node_count()
        delta = (
            kernel.total_price() - oracle.total_price()
        ) / oracle.total_price()
        assert delta <= 0.02, delta


class TestBootstrapAffinityMerge:
    """Indistinguishable zonal self-affinity families merge into one scan
    step per shape (encode._resolve_topology): with no state nodes and
    zero priors every family bootstraps to the same static d_fresh, so the
    merged placement is exact. The diverse benchmark mix creates ~1 such
    family per pod label."""

    def _family_pods(self, n=120, fams=20, seed=7):
        import random

        from karpenter_tpu.api.objects import (
            LabelSelector, ObjectMeta, Pod, PodAffinityTerm, PodSpec,
        )

        rng = random.Random(seed)
        pods = []
        for i in range(n):
            f = rng.randrange(fams)
            lbl = {"fam": f"v{f}"}
            # single-shape families (the realistic Deployment shape — one
            # pod spec per app): shape is a function of the family, so
            # each family is ONE group and the cross-family merge applies
            cpu = [500, 1000, 2000][f % 3]
            pods.append(
                Pod(
                    metadata=ObjectMeta(name=f"fa-{i}", labels=lbl),
                    spec=PodSpec(
                        requests={
                            res.CPU: cpu,
                            res.MEMORY: 2**30 * 1000,
                        },
                        pod_affinity=[
                            PodAffinityTerm(
                                topology_key=labels.TOPOLOGY_ZONE,
                                label_selector=LabelSelector(
                                    match_labels=lbl
                                ),
                            )
                        ],
                    ),
                )
            )
        return pods

    def test_families_collapse_and_match_oracle(self):
        from karpenter_tpu.solver import encode as enc
        from karpenter_tpu.solver.driver import EncodeCache, SolverConfig

        pods = self._family_pods()
        pools = [make_nodepool()]
        its_by_pool = {"default": corpus.generate(30)}
        cache = EncodeCache()

        def solve(force):
            topo = Topology(
                Client(TestClock()), [], pools, its_by_pool, pods
            )
            s = TpuSolver(
                pools, its_by_pool, topo,
                config=SolverConfig(force_oracle=force),
                encode_cache=cache,
            )
            return s, s.solve(pods)

        s, kernel = solve(False)
        groups, rest = enc.partition_and_group(
            pods, topology=s.oracle.topology
        )
        unmerged, _ = enc.partition_and_group(
            pods, topology=s.oracle.topology,
            merge_bootstrap_affinity=False,
        )
        assert not rest
        assert len(groups) <= 3 < len(unmerged)  # one group per shape
        _, oracle = solve(True)
        assert not kernel.pod_errors and not oracle.pod_errors
        assert kernel.node_count() == oracle.node_count()
        assert abs(kernel.total_price() - oracle.total_price()) <= (
            0.02 * oracle.total_price() + 1e-9
        )
        # every family still co-zones
        for fam in {p.metadata.labels["fam"] for p in pods}:
            zones = set()
            for c in kernel.new_node_claims:
                if any(
                    p.metadata.labels.get("fam") == fam for p in c.pods
                ):
                    zr = c.requirements.get(labels.TOPOLOGY_ZONE)
                    zones.add(zr.any() if not zr.complement else None)
            assert len(zones) <= 1, (fam, zones)

    def test_multi_shape_families_do_not_merge(self):
        from karpenter_tpu.api.objects import (
            LabelSelector, ObjectMeta, Pod, PodAffinityTerm, PodSpec,
        )
        from karpenter_tpu.solver import encode as enc

        # one family, two shapes: the small-shape member must NOT merge
        # into another family's primary — d_fresh is shape-dependent, and
        # the big sibling reads the family carry the merged-away member
        # would have written
        def pod(name, fam, cpu):
            lbl = {"fam": fam}
            return Pod(
                metadata=ObjectMeta(name=name, labels=lbl),
                spec=PodSpec(
                    requests={res.CPU: cpu, res.MEMORY: 2**30 * 1000},
                    pod_affinity=[
                        PodAffinityTerm(
                            topology_key=labels.TOPOLOGY_ZONE,
                            label_selector=LabelSelector(match_labels=lbl),
                        )
                    ],
                ),
            )

        pods = [
            pod("b1", "multi", 500), pod("b2", "multi", 4000),  # 2 shapes
            pod("a1", "solo", 500), pod("a2", "solo2", 500),  # mergeable
        ]
        pools = [make_nodepool()]
        its_by_pool = {"default": corpus.generate(20)}
        topo = Topology(Client(TestClock()), [], pools, its_by_pool, pods)
        groups, rest = enc.partition_and_group(pods, topology=topo)
        assert not rest
        # solo + solo2 merge (one group), multi keeps both its groups
        by_count = sorted(len(g.pods) for g in groups)
        assert by_count == [1, 1, 2]

    def test_merge_disabled_with_state_nodes(self):
        from karpenter_tpu.api.objects import Node, ObjectMeta
        from karpenter_tpu.controllers.state import StateNode
        from karpenter_tpu.solver import encode as enc

        # an existing node makes the bootstrap state-dependent (d_exist
        # evolves as nodes fill): families must NOT merge
        node = Node(
            metadata=ObjectMeta(
                name="sn-1",
                labels={
                    labels.TOPOLOGY_ZONE: "test-zone-b",
                    labels.HOSTNAME: "sn-1",
                },
            ),
        )
        node.status.capacity = {
            "cpu": res.parse_quantity("8"),
            "memory": res.parse_quantity("16Gi"),
            "pods": res.parse_quantity("110"),
        }
        node.status.allocatable = dict(node.status.capacity)
        node.status.ready = True
        sn = StateNode(node=node)
        pods = self._family_pods(n=40, fams=8)
        pools = [make_nodepool()]
        its_by_pool = {"default": corpus.generate(30)}
        client = Client(TestClock())
        client.create(node)
        topo = Topology(client, [sn], pools, its_by_pool, pods)
        merged, _ = enc.partition_and_group(pods, topology=topo)
        topo2 = Topology(Client(TestClock()), [], pools, its_by_pool, pods)
        free, _ = enc.partition_and_group(pods, topology=topo2)
        assert len(merged) > len(free)


class TestCostDelta:
    """The kernel's grouped placement beats the oracle's per-pod FFD on
    mixed accelerator batches by avoiding type poisoning (small GPU pods
    landing on CPU-opened claims narrow their options to GPU-capable
    types). Root cause audit: PARITY.md 'Packing-cost delta'."""

    def test_mixed_accelerator_kernel_not_pricier(self):
        from karpenter_tpu.cloudprovider import types as cpt
        from karpenter_tpu.solver.driver import EncodeCache, SolverConfig
        from karpenter_tpu.solver.example import example_nodepool
        from karpenter_tpu.solver.workloads import mixed_pods

        pods = mixed_pods(2_000)
        pools = [example_nodepool()]
        its = corpus.generate(100)
        its_by_pool = {p.name: list(its) for p in pools}
        cache = EncodeCache()

        def solve(force):
            topo = Topology(Client(TestClock()), [], pools, its_by_pool, pods)
            return TpuSolver(
                pools, its_by_pool, topo,
                config=SolverConfig(force_oracle=force),
                encode_cache=cache,
            ).solve(pods)

        kernel = solve(False)
        oracle = solve(True)
        assert not kernel.pod_errors and not oracle.pod_errors
        # equal fleet size; kernel never pricier than the reference FFD
        assert kernel.node_count() == oracle.node_count()
        k_cost, o_cost = kernel.total_price(), oracle.total_price()
        assert k_cost <= o_cost * 1.02, (k_cost, o_cost)
        # the mechanism: the kernel keeps some claims accelerator-free
        def gpu_free_claims(results):
            return sum(
                1
                for c in results.new_node_claims
                if not any(
                    p.spec.requests.get("nvidia.com/gpu", 0) for p in c.pods
                )
            )

        assert gpu_free_claims(kernel) >= gpu_free_claims(oracle)


class TestZonalTopology:
    """Zone/capacity-type-keyed spread and pod affinity ride the TPU fast
    path: self-selecting spread as a per-step domain-quota water-fill,
    affinity as mask gates / the bootstrap single-domain rule
    (ops/packing.py; reference topologygroup.go:205-324)."""

    def _zone_distribution(self, results):
        dist = {}
        for claim in results.new_node_claims:
            zr = claim.requirements.get(labels.TOPOLOGY_ZONE)
            assert not zr.complement and len(zr.values) == 1, (
                "zonal claims must be pinned to a single zone"
            )
            z = next(iter(zr.values))
            dist[z] = dist.get(z, 0) + len(claim.pods)
        return dist

    def test_zonal_spread_rides_fast_path(self):
        from karpenter_tpu.solver import encode as enc
        from helpers import spread_constraint

        app = {"app": "zs"}
        pods = make_pods(
            9, cpu="1", labels=app,
            spread=[spread_constraint(labels.TOPOLOGY_ZONE, labels=app)],
        )
        node_pools = [make_nodepool()]
        its_by_pool = {"default": corpus.generate(20)}
        topo = Topology(Client(TestClock()), [], node_pools, its_by_pool, pods)
        groups, rest = enc.partition_and_group(pods, topology=topo)
        assert not rest and len(groups) == 1
        assert groups[0].topo.dmode == enc.DMODE_SPREAD
        assert groups[0].topo.dkey == labels.TOPOLOGY_ZONE
        assert groups[0].topo.dreg == frozenset(
            ("test-zone-a", "test-zone-b", "test-zone-c")
        )

    def test_zonal_spread_parity(self):
        from helpers import spread_constraint

        app = {"app": "zsp"}
        pods = make_pods(
            12, cpu="1", labels=app,
            spread=[spread_constraint(labels.TOPOLOGY_ZONE, labels=app)],
        )
        oracle_r, tpu_r = run_both(pods)
        assert_parity(oracle_r, tpu_r, cost_tol=0.02)
        dist = self._zone_distribution(tpu_r)
        assert sum(dist.values()) == 12
        assert max(dist.values()) - min(dist.values()) <= 1  # maxSkew honored
        assert len(dist) == 3

    def test_zonal_spread_skew2_parity(self):
        from helpers import spread_constraint

        app = {"app": "zs2"}
        pods = make_pods(
            10, cpu="1", labels=app,
            spread=[spread_constraint(labels.TOPOLOGY_ZONE, max_skew=2, labels=app)],
        )
        oracle_r, tpu_r = run_both(pods)
        assert_parity(oracle_r, tpu_r, cost_tol=0.02)
        dist = self._zone_distribution(tpu_r)
        assert sum(dist.values()) == 10
        assert max(dist.values()) - min(dist.values() if len(dist) == 3 else [0]) <= 2

    def test_zonal_spread_with_plain_pods(self):
        from helpers import spread_constraint

        app = {"app": "zmix"}
        pods = make_pods(8, cpu="2") + make_pods(
            6, cpu="1", labels=app,
            spread=[spread_constraint(labels.TOPOLOGY_ZONE, labels=app)],
        )
        oracle_r, tpu_r = run_both(pods)
        assert tpu_r.all_pods_scheduled()
        assert_parity(oracle_r, tpu_r, cost_tol=0.02)

    def test_zonal_spread_with_cluster_priors(self):
        """Prior selected pods shift the water-fill: zone a starts at 2, so
        new pods favor b and c until counts level (topology.go:322-420)."""
        from karpenter_tpu.api.objects import Node, ObjectMeta
        from helpers import spread_constraint

        app = {"app": "zprior"}
        client = Client(TestClock())
        node = Node(
            metadata=ObjectMeta(
                name="prior-1",
                labels={labels.TOPOLOGY_ZONE: "test-zone-a",
                        labels.HOSTNAME: "prior-1"},
            ),
        )
        node.status.capacity = {
            "cpu": res.parse_quantity("4"),
            "memory": res.parse_quantity("16Gi"),
        }
        node.status.allocatable = dict(node.status.capacity)
        node.status.ready = True
        client.create(node)
        for _ in range(2):
            client.create(
                make_pod(labels=app, node_name="prior-1", phase="Running")
            )

        pods = make_pods(
            7, cpu="1", labels=app,
            spread=[spread_constraint(labels.TOPOLOGY_ZONE, labels=app)],
        )
        node_pools = [make_nodepool()]
        its_by_pool = {"default": corpus.generate(20)}
        topo = Topology(client, [], node_pools, its_by_pool, pods)
        solver = TpuSolver(node_pools, its_by_pool, topo)
        results = solver.solve(pods)
        assert results.all_pods_scheduled()
        dist = self._zone_distribution(results)
        # [a=2 prior] + 7 water-filled = final counts (3,3,3)
        assert dist == {"test-zone-a": 1, "test-zone-b": 3, "test-zone-c": 3}

    def test_min_domains_unsatisfied_pins_min(self):
        """minDomains above the domain count pins the global min to 0: every
        zone caps at maxSkew (topologygroup.go:270-273)."""
        from helpers import spread_constraint

        app = {"app": "zmind"}
        pods = make_pods(
            6, cpu="1", labels=app,
            spread=[
                spread_constraint(
                    labels.TOPOLOGY_ZONE, labels=app, min_domains=5
                )
            ],
        )
        oracle_r, tpu_r = run_both(pods)
        # 3 zones x cap 1 = 3 scheduled, 3 unplaced on both paths
        assert len(oracle_r.pod_errors) == 3
        assert_parity(oracle_r, tpu_r, cost_tol=0.02)

    def test_zonal_affinity_bootstrap_parity(self):
        from helpers import affinity_term

        app = {"app": "zaff"}
        pods = make_pods(
            8, cpu="1", labels=app,
            pod_affinity=[affinity_term(labels.TOPOLOGY_ZONE, app)],
        )
        oracle_r, tpu_r = run_both(pods)
        assert tpu_r.all_pods_scheduled()
        assert_parity(oracle_r, tpu_r, cost_tol=0.02)
        dist = self._zone_distribution(tpu_r)
        assert len(dist) == 1  # bootstrap pins the whole group to one zone

    def test_zonal_affinity_with_prior_gates_to_nonempty(self):
        from karpenter_tpu.api.objects import Node, ObjectMeta
        from helpers import affinity_term

        app = {"app": "zaffp"}
        client = Client(TestClock())
        node = Node(
            metadata=ObjectMeta(
                name="aff-1",
                labels={labels.TOPOLOGY_ZONE: "test-zone-b",
                        labels.HOSTNAME: "aff-1"},
            ),
        )
        node.status.capacity = {
            "cpu": res.parse_quantity("4"),
            "memory": res.parse_quantity("16Gi"),
        }
        node.status.allocatable = dict(node.status.capacity)
        node.status.ready = True
        client.create(node)
        client.create(make_pod(labels=app, node_name="aff-1", phase="Running"))

        pods = make_pods(
            5, cpu="1", labels=app,
            pod_affinity=[affinity_term(labels.TOPOLOGY_ZONE, app)],
        )
        node_pools = [make_nodepool()]
        its_by_pool = {"default": corpus.generate(20)}
        topo = Topology(client, [], node_pools, its_by_pool, pods)
        solver = TpuSolver(node_pools, its_by_pool, topo)
        results = solver.solve(pods)
        assert results.all_pods_scheduled()
        dist = self._zone_distribution(results)
        assert set(dist) == {"test-zone-b"}  # gated to the occupied zone

    def test_two_dynamic_constraints_demote(self):
        from karpenter_tpu.solver import encode as enc
        from helpers import spread_constraint

        app = {"app": "zdouble"}
        pods = make_pods(
            4, cpu="1", labels=app,
            spread=[
                spread_constraint(labels.TOPOLOGY_ZONE, labels=app),
                spread_constraint(labels.CAPACITY_TYPE_LABEL_KEY, labels=app),
            ],
        )
        node_pools = [make_nodepool()]
        its_by_pool = {"default": corpus.generate(20)}
        topo = Topology(Client(TestClock()), [], node_pools, its_by_pool, pods)
        groups, rest = enc.partition_and_group(pods, topology=topo)
        assert not groups and len(rest) == 4  # one quota system per group

    def test_zone_and_hostname_spread_combined(self):
        from helpers import spread_constraint

        app = {"app": "zboth"}
        pods = make_pods(
            6, cpu="1", labels=app,
            spread=[
                spread_constraint(labels.TOPOLOGY_ZONE, labels=app),
                spread_constraint(labels.HOSTNAME, labels=app),
            ],
        )
        oracle_r, tpu_r = run_both(pods)
        assert tpu_r.all_pods_scheduled()
        assert_parity(oracle_r, tpu_r, cost_tol=0.02)
        dist = self._zone_distribution(tpu_r)
        assert max(dist.values()) - min(dist.values()) <= 1
        for claim in tpu_r.new_node_claims:
            assert len(claim.pods) <= 1  # hostname cap rides along

    def test_benchmark_mix_routes_all_classes(self):
        """The reference's 5-class benchmark mix
        (scheduling_benchmark_test.go:236-249): every class now rides the
        TPU fast path."""
        from karpenter_tpu.api.objects import LabelSelector, PodAffinityTerm
        from karpenter_tpu.solver import encode as enc
        from helpers import affinity_term, spread_constraint

        generic = make_pods(10, cpu="1", memory="2Gi")
        zspread = make_pods(
            6, cpu="1", labels={"mix": "zs"},
            spread=[spread_constraint(labels.TOPOLOGY_ZONE, labels={"mix": "zs"})],
        )
        hspread = make_pods(
            6, cpu="1", labels={"mix": "hs"},
            spread=[spread_constraint(labels.HOSTNAME, labels={"mix": "hs"})],
        )
        zaff = make_pods(
            6, cpu="1", labels={"mix": "za"},
            pod_affinity=[affinity_term(labels.TOPOLOGY_ZONE, {"mix": "za"})],
        )
        hanti = make_pods(
            4, cpu="1", labels={"mix": "ha"},
            pod_anti_affinity=[
                PodAffinityTerm(
                    topology_key=labels.HOSTNAME,
                    label_selector=LabelSelector(match_labels={"mix": "ha"}),
                )
            ],
        )
        pods = generic + zspread + hspread + zaff + hanti
        node_pools = [make_nodepool()]
        its_by_pool = {"default": corpus.generate(20)}
        topo = Topology(Client(TestClock()), [], node_pools, its_by_pool, pods)
        groups, rest = enc.partition_and_group(pods, topology=topo)
        assert not rest, "all five benchmark pod classes must tensorize"
        assert len(groups) == 5

        oracle_r, tpu_r = run_both(pods)
        assert tpu_r.all_pods_scheduled()
        assert_parity(oracle_r, tpu_r, cost_tol=0.02)


class TestSharedConstraints:
    """One TopologyGroup spanning several pod groups (multi-shape
    deployments): counting rides the kernel's shared carries instead of
    demoting to the oracle."""

    def _mk(self, pods):
        node_pools = [make_nodepool()]
        its_by_pool = {"default": corpus.generate(20)}
        topo = Topology(Client(TestClock()), [], node_pools, its_by_pool, pods)
        return node_pools, its_by_pool, topo

    def test_multi_shape_anti_affinity_rides_fast_path(self):
        from karpenter_tpu.api.objects import LabelSelector, PodAffinityTerm
        from karpenter_tpu.solver import encode as enc

        app = {"app": "santi"}
        term = PodAffinityTerm(
            topology_key=labels.HOSTNAME,
            label_selector=LabelSelector(match_labels=dict(app)),
        )
        # three request shapes -> three groups sharing one anti constraint
        pods = (
            make_pods(3, cpu="1", memory="1Gi", labels=app, pod_anti_affinity=[term])
            + make_pods(3, cpu="2", memory="2Gi", labels=app, pod_anti_affinity=[term])
            + make_pods(2, cpu="500m", memory="512Mi", labels=app, pod_anti_affinity=[term])
        )
        node_pools, its_by_pool, topo = self._mk(pods)
        groups, rest = enc.partition_and_group(pods, topology=topo)
        assert not rest and len(groups) == 3
        assert all(g.topo is not None and g.topo.shared_h is not None for g in groups)
        shared = {id(g.topo.shared_h) for g in groups}
        assert len(shared) == 1  # one descriptor across all three groups

        oracle_r, tpu_r = run_both(pods)
        assert_parity(oracle_r, tpu_r, cost_tol=0.02)
        # every pod on its own claim, across shapes
        assert tpu_r.node_count() == 8
        for claim in tpu_r.new_node_claims:
            assert len(claim.pods) <= 1

    def test_multi_shape_hostname_spread_parity(self):
        from helpers import spread_constraint

        app = {"app": "shspread"}
        spread = [spread_constraint(labels.HOSTNAME, max_skew=2, labels=app)]
        pods = (
            make_pods(4, cpu="1", memory="1Gi", labels=app, spread=list(spread))
            + make_pods(4, cpu="2", memory="2Gi", labels=app, spread=list(spread))
        )
        oracle_r, tpu_r = run_both(pods)
        assert_parity(oracle_r, tpu_r, cost_tol=0.02)
        # <=2 selected pods per claim ACROSS both shapes
        for claim in tpu_r.new_node_claims:
            assert len(claim.pods) <= 2

    def test_multi_shape_zonal_spread_carry(self):
        from helpers import spread_constraint
        from karpenter_tpu.solver import encode as enc

        app = {"app": "szonal"}
        spread = [spread_constraint(labels.TOPOLOGY_ZONE, labels=app)]
        pods = (
            make_pods(5, cpu="1", memory="1Gi", labels=app, spread=list(spread))
            + make_pods(4, cpu="2", memory="2Gi", labels=app, spread=list(spread))
        )
        node_pools, its_by_pool, topo = self._mk(pods)
        groups, rest = enc.partition_and_group(pods, topology=topo)
        assert not rest and len(groups) == 2
        assert all(g.topo is not None and g.topo.shared_d is not None for g in groups)

        solver = TpuSolver(node_pools, its_by_pool, topo)
        results = solver.solve(pods)
        assert results.all_pods_scheduled()
        # counts accumulate across both groups: 9 pods over 3 zones, skew 1
        dist = {}
        for claim in results.new_node_claims:
            zr = claim.requirements.get(labels.TOPOLOGY_ZONE)
            assert not zr.complement and len(zr.values) == 1
            z = next(iter(zr.values))
            dist[z] = dist.get(z, 0) + len(claim.pods)
        assert sum(dist.values()) == 9
        assert max(dist.values()) - min(dist.values()) <= 1

    def test_multi_shape_zonal_affinity_follows_leader(self):
        from helpers import affinity_term
        from karpenter_tpu.solver import encode as enc

        app = {"app": "saff"}
        terms = [affinity_term(labels.TOPOLOGY_ZONE, app)]
        pods = (
            make_pods(3, cpu="1", memory="1Gi", labels=app, pod_affinity=list(terms))
            + make_pods(3, cpu="2", memory="2Gi", labels=app, pod_affinity=list(terms))
        )
        node_pools, its_by_pool, topo = self._mk(pods)
        groups, rest = enc.partition_and_group(pods, topology=topo)
        assert not rest and len(groups) == 2

        solver = TpuSolver(node_pools, its_by_pool, topo)
        results = solver.solve(pods)
        assert results.all_pods_scheduled()
        zones = set()
        for claim in results.new_node_claims:
            zr = claim.requirements.get(labels.TOPOLOGY_ZONE)
            if not zr.complement and len(zr.values) == 1:
                zones.add(next(iter(zr.values)))
        assert len(zones) == 1  # the second group followed the first's domain

    def test_shared_selector_plain_group_contributes(self):
        from helpers import spread_constraint
        from karpenter_tpu.solver import encode as enc

        # the shared constraint also selects a plain group: that group rides
        # the fast path as a CONTRIBUTOR whose placements feed the carry
        # (round-2 behavior demoted the whole batch to the oracle)
        app = {"app": "smix"}
        spread = [spread_constraint(labels.HOSTNAME, labels=app)]
        pods = (
            make_pods(3, cpu="1", memory="1Gi", labels=app, spread=list(spread))
            + make_pods(3, cpu="2", memory="2Gi", labels=app, spread=list(spread))
            + make_pods(2, cpu="3", memory="3Gi", labels=app)  # selected, no constraint
        )
        node_pools, its_by_pool, topo = self._mk(pods)
        groups, rest = enc.partition_and_group(pods, topology=topo)
        assert not rest and len(groups) == 3
        contrib = [g for g in groups if g.topo is not None and g.topo.contrib_h]
        assert len(contrib) == 1  # the plain group
        solver = TpuSolver(node_pools, its_by_pool, topo)
        results = solver.solve(pods)
        assert results.all_pods_scheduled()
        # maxSkew=1 over a shared hostname carry: every spreader entity
        # allowance is 1 minus the plain pods already counted there, and
        # spreaders of BOTH shapes share the count
        spreaders = pods[:6]
        for claim in results.new_node_claims:
            n_spread = sum(1 for p in claim.pods if p in spreaders)
            assert n_spread <= 1

    def test_shared_selector_oracle_pod_still_demotes(self):
        from helpers import spread_constraint
        from karpenter_tpu.solver import encode as enc

        # an oracle-routed pod (host ports) matching the shared selector
        # keeps the whole selection oracle-side: the carry cannot see its
        # placements
        app = {"app": "smix2"}
        spread = [spread_constraint(labels.HOSTNAME, labels=app)]
        pods = (
            make_pods(3, cpu="1", memory="1Gi", labels=app, spread=list(spread))
            + make_pods(2, cpu="3", memory="3Gi", labels=app, host_ports=(9090,))
        )
        node_pools, its_by_pool, topo = self._mk(pods)
        groups, rest = enc.partition_and_group(pods, topology=topo)
        assert not groups and len(rest) == 5
        solver = TpuSolver(node_pools, its_by_pool, topo)
        results = solver.solve(pods)
        assert results.all_pods_scheduled()

    def test_multi_shape_affinity_with_priors_gates_not_pins(self):
        """Shared affinity whose compatible pods already sit in TWO zones
        must gate to BOTH (the options rule), not pin to one — pods must
        still schedule when the lowest-rank nonempty zone is unusable."""
        from karpenter_tpu.api.objects import Node, ObjectMeta
        from helpers import affinity_term

        app = {"app": "sgate"}
        client = Client(TestClock())
        for zone in ("test-zone-a", "test-zone-b"):
            node = Node(
                metadata=ObjectMeta(
                    name=f"prior-{zone}",
                    labels={labels.TOPOLOGY_ZONE: zone,
                            labels.HOSTNAME: f"prior-{zone}"},
                ),
            )
            node.status.capacity = {
                "cpu": res.parse_quantity("4"),
                "memory": res.parse_quantity("16Gi"),
            }
            node.status.allocatable = dict(node.status.capacity)
            node.status.ready = True
            client.create(node)
            client.create(
                make_pod(labels=app, node_name=node.metadata.name, phase="Running")
            )

        terms = [affinity_term(labels.TOPOLOGY_ZONE, app)]
        pods = (
            make_pods(3, cpu="1", memory="1Gi", labels=app, pod_affinity=list(terms))
            + make_pods(3, cpu="2", memory="2Gi", labels=app, pod_affinity=list(terms))
        )
        node_pools = [make_nodepool()]
        its_by_pool = {"default": corpus.generate(20)}
        topo = Topology(client, [], node_pools, its_by_pool, pods)
        solver = TpuSolver(node_pools, its_by_pool, topo)
        results = solver.solve(pods)
        assert results.all_pods_scheduled()
        for claim in results.new_node_claims:
            zr = claim.requirements.get(labels.TOPOLOGY_ZONE)
            assert set(zr.values) <= {"test-zone-a", "test-zone-b"}


class TestReservedLedgerFastPath:
    """The reservation ledger rides the kernel carry (SURVEY §7.4.5,
    reservationmanager.go:28-85): reserved-capacity snapshots in the
    default fallback mode use the fast path, with reserved offerings
    admitted only while ledger capacity lasts."""

    def _reserved_types(self, capacity=1, n=4):
        from karpenter_tpu.api.requirements import Operator, Requirement
        from karpenter_tpu.cloudprovider import types as cp

        its = corpus.generate(n)
        # reserved offerings on the LARGEST types (the ones the pods'
        # requests actually land on; small types can't fit them)
        for it in its[-2:]:
            res_req = __import__(
                "karpenter_tpu.api.requirements", fromlist=["Requirements"]
            ).Requirements(
                Requirement(labels.CAPACITY_TYPE_LABEL_KEY, Operator.IN,
                            [labels.CAPACITY_TYPE_RESERVED]),
                Requirement(labels.TOPOLOGY_ZONE, Operator.IN, ["test-zone-a"]),
                Requirement(cp.RESERVATION_ID_LABEL, Operator.IN,
                            [f"res-{it.name}"]),
            )
            it.offerings.append(cp.Offering(
                requirements=res_req, price=0.001, available=True,
                reservation_capacity=capacity,
            ))
        return its

    def _solve(self, pods, its, backend="tpu", force_oracle=False):
        from karpenter_tpu.solver.driver import SolverConfig

        pool = make_nodepool()
        its_by_pool = {pool.name: its}
        topo = Topology(Client(TestClock()), [], [pool], its_by_pool, pods)
        solver = TpuSolver(
            [pool], its_by_pool, topo,
            config=SolverConfig(backend=backend, force_oracle=force_oracle),
            reserved_capacity_enabled=True,
        )
        return solver, solver.solve(pods)

    def test_ledger_caps_reserved_claims_on_fast_path(self):
        from karpenter_tpu.solver import encode as enc

        its = self._reserved_types(capacity=1)
        pods = make_pods(6, cpu="1")
        solver, results = self._solve(pods, its)
        # the fast path handled everything (no oracle fallback)
        groups, rest = enc.partition_and_group(
            pods, topology=solver.oracle.topology
        )
        assert not rest
        assert results.all_pods_scheduled()
        held = [c for c in results.new_node_claims if c.reserved_offerings]
        # reservation is pessimistic: each claim reserves EVERY compatible
        # offering (reservationmanager.go:28-48), so the first claim drains
        # both capacity-1 reservations and holds two offerings
        assert len(held) == 1
        assert len(held[0].reserved_offerings) == 2
        # the oracle agrees on the held-claim count
        _, oracle_r = self._solve(pods, its, force_oracle=True)
        assert (
            sum(1 for c in oracle_r.new_node_claims if c.reserved_offerings)
            == 1
        )

    def test_ledger_parity_with_oracle(self):
        its = self._reserved_types(capacity=2)
        pods = make_pods(8, cpu="1")
        _, tpu_r = self._solve(pods, its)
        _, oracle_r = self._solve(pods, its, force_oracle=True)
        assert tpu_r.all_pods_scheduled() and oracle_r.all_pods_scheduled()
        assert tpu_r.node_count() == oracle_r.node_count()

    def test_native_backend_ledger_agreement(self):
        its = self._reserved_types(capacity=1)
        pods = make_pods(6, cpu="1")
        _, r_t = self._solve(pods, its, backend="tpu")
        its2 = self._reserved_types(capacity=1)
        _, r_n = self._solve(pods, its2, backend="native")
        assert r_n.node_count() == r_t.node_count()
        held_t = sum(1 for c in r_t.new_node_claims if c.reserved_offerings)
        held_n = sum(1 for c in r_n.new_node_claims if c.reserved_offerings)
        assert held_t == held_n

    def test_strict_mode_routes_to_oracle(self):
        from karpenter_tpu.scheduling.inflight import (
            RESERVED_OFFERING_MODE_STRICT,
        )

        its = self._reserved_types(capacity=1)
        pods = make_pods(3, cpu="1")
        pool = make_nodepool()
        its_by_pool = {pool.name: its}
        topo = Topology(Client(TestClock()), [], [pool], its_by_pool, pods)
        solver = TpuSolver(
            [pool], its_by_pool, topo,
            reserved_capacity_enabled=True,
            reserved_offering_mode=RESERVED_OFFERING_MODE_STRICT,
        )
        called = []
        orig = solver.oracle.solve

        def spy(p):
            called.append(len(p))
            return orig(p)

        solver.oracle.solve = spy
        solver.solve(pods)
        assert called == [3]  # the whole batch went through the oracle

    def test_mixed_batch_does_not_double_book(self):
        """Fast-path holdings must debit the oracle's ReservationManager
        before the oracle solves the non-tensorizable remainder — a mixed
        batch may not hand the same reserved slot to two claims."""
        from karpenter_tpu.api.objects import HostPort

        its = self._reserved_types(capacity=1)
        oracle_side = make_pods(2, cpu="1")
        for i, p in enumerate(oracle_side):
            # host ports route to the host oracle (is_tensorizable)
            p.spec.host_ports.append(HostPort(port=6000 + i))
        pods = make_pods(4, cpu="1") + oracle_side
        solver, results = self._solve(pods, its)
        assert results.all_pods_scheduled()
        held = [
            c for c in results.new_node_claims
            if getattr(c, "reserved_offerings", None)
        ]
        # 2 reservation ids x capacity 1: at most... each claim reserves
        # every compatible offering, so ONE claim drains both; no second
        # claim (from either path) may hold the same slots
        total_by_rid = {}
        for c in held:
            for o in c.reserved_offerings:
                rid = o.reservation_id()
                total_by_rid[rid] = total_by_rid.get(rid, 0) + 1
        assert all(v <= 1 for v in total_by_rid.values()), total_by_rid


class TestTiledFeasibility:
    """tile_feasibility (SURVEY §7.4.6): the HBM-scaling mode computes
    per-group feasibility rows inside the scan instead of materializing
    [P, G, T] tables — an execution strategy, so outputs must be
    IDENTICAL to the precomputed-table program."""

    def _state_node(self, name="tiled-n1", zone="test-zone-a"):
        from karpenter_tpu.api.objects import Node, ObjectMeta
        from karpenter_tpu.controllers.state import StateNode

        node = Node(
            metadata=ObjectMeta(
                name=name,
                labels={labels.TOPOLOGY_ZONE: zone, labels.HOSTNAME: name},
            ),
        )
        node.status.capacity = {
            "cpu": res.parse_quantity("8"),
            "memory": res.parse_quantity("32Gi"),
        }
        node.status.allocatable = dict(node.status.capacity)
        node.status.ready = True
        return StateNode(node=node)

    @pytest.mark.parametrize(
        "workload", ["plain", "topology", "existing-nodes"]
    )
    def test_tiled_outputs_identical(self, workload):
        import jax

        from karpenter_tpu.ops.solve import solve_all
        from helpers import snapshot_args, spread_constraint

        state_nodes = ()
        node_pools = [
            make_nodepool("low", weight=1),
            make_nodepool("high", weight=50, limits={"cpu": "64"}),
        ]
        if workload == "plain":
            pods = make_pods(60, cpu="1", memory="1Gi") + make_pods(
                30, cpu="2", memory="4Gi"
            )
        elif workload == "existing-nodes":
            pods = make_pods(20, cpu="1", memory="1Gi") + make_pods(
                6, cpu="2", memory="4Gi"
            )
            state_nodes = (self._state_node("t-n1"), self._state_node("t-n2"))
        else:
            app = {"t": "zs"}
            pods = (
                make_pods(40, cpu="1")
                + make_pods(
                    12, cpu="1", labels=app,
                    spread=[spread_constraint(labels.TOPOLOGY_ZONE, labels=app)],
                )
                + make_pods(
                    8, cpu="2", labels={"t": "hs"},
                    spread=[
                        spread_constraint(labels.HOSTNAME, labels={"t": "hs"})
                    ],
                )
            )
        args, statics = snapshot_args(
            pods, node_pools=node_pools, n_types=24, state_nodes=state_nodes
        )
        if workload == "existing-nodes":
            assert args[0].shape[0] and len(state_nodes)  # N > 0 exercised
        dense = jax.device_get(solve_all(*args, **statics))
        tiled = jax.device_get(
            solve_all(*args, tile_feasibility=True, **statics)
        )
        assert len(dense) == len(tiled)
        for i, (a, b) in enumerate(zip(dense, tiled)):
            np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b), err_msg=f"output {i}"
            )


class TestHashSeedDeterminism:
    """The encode side is PYTHONHASHSEED-independent (ISSUE 14 satellite):
    constrained packing costs used to vary ~0.2% across processes because
    Requirement.values set-iteration order fed the vocab's value-id
    assignment, and every kernel argmin tie-break over value ids followed
    it. Vocab.observe now interns in content (sorted) order; two processes
    with different hash seeds must produce byte-identical solve args."""

    _PROBE = r"""
import hashlib
import numpy as np
from karpenter_tpu.cloudprovider import corpus
from karpenter_tpu.kube import Client, TestClock
from karpenter_tpu.scheduling.topology import Topology
from karpenter_tpu.solver import TpuSolver
from karpenter_tpu.solver import encode as enc
from karpenter_tpu.solver.example import example_nodepool
from karpenter_tpu.solver.workloads import constrained_mix

pods = constrained_mix(800)
pools = [example_nodepool()]
its = {pools[0].name: corpus.generate(40)}
topology = Topology(Client(TestClock()), [], pools, its, pods)
solver = TpuSolver(pools, its, topology)
groups, rest = enc.partition_and_group(pods, topology=topology)
assert not rest, len(rest)
templates = solver.oracle.templates
snap = enc.encode(
    groups, templates,
    {t.node_pool_name: t.instance_type_options for t in templates},
    daemon_overhead=solver.oracle.daemon_overhead,
)
a_tzc, res_cap0, a_res = solver._offering_availability(snap)
h = hashlib.blake2b(digest_size=16)
for arr in snap.solve_args(a_tzc, res_cap0, a_res):
    a = np.ascontiguousarray(np.asarray(arr))
    h.update(str(a.dtype).encode() + str(a.shape).encode() + a.tobytes())
h.update(repr(snap.vocab.values).encode())
print(h.hexdigest())
"""

    def test_two_process_encode_identical(self):
        import os
        import subprocess
        import sys

        digests = []
        # six seeds, not two: a single unordered 2-element set (the zonal
        # In pairs) flips order with ~1/2 probability per seed, so a
        # 2-seed compare false-passes a real regression half the time;
        # six independent seeds push that below 1/32 (the seeded-unsorted
        # mutation diverges at seeds 1 vs 2 on g_mask/g_drank/o_zone)
        for seed in ("1", "2", "3", "7", "99", "4242"):
            env = dict(os.environ)
            env["PYTHONHASHSEED"] = seed
            env["JAX_PLATFORMS"] = "cpu"
            env.setdefault("TF_CPP_MIN_LOG_LEVEL", "2")
            out = subprocess.run(
                [sys.executable, "-c", self._PROBE],
                capture_output=True, text=True, timeout=240,
                cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                env=env,
            )
            assert out.returncode == 0, out.stderr[-2000:]
            digests.append(out.stdout.strip().splitlines()[-1])
        assert len(set(digests)) == 1, (
            "encode varies with PYTHONHASHSEED: the vocab interning order "
            f"(or another set walk) regressed — {digests}"
        )
