"""TPU solver vs host oracle parity.

The BASELINE metric is packing-cost delta, so parity is asserted on node
count and total price (exact-assignment equality is not required — FFD
tie-breaks differ legitimately; see SURVEY.md §7.4.4).
"""

import numpy as np
import pytest

from karpenter_tpu.api import labels, resources as res
from karpenter_tpu.api.objects import NodeSelectorRequirement
from karpenter_tpu.cloudprovider import corpus
from karpenter_tpu.kube import Client, TestClock
from karpenter_tpu.scheduling.scheduler import Scheduler
from karpenter_tpu.scheduling.topology import Topology
from karpenter_tpu.solver import TpuSolver

from helpers import make_nodepool, make_pod, make_pods


def run_both(pods, node_pools=None, instance_types=None, limits=None):
    node_pools = node_pools or [make_nodepool(limits=limits)]
    its = instance_types if instance_types is not None else corpus.generate(20)
    its_by_pool = {np_.name: list(its) for np_ in node_pools}

    def fresh_topology(pods_):
        return Topology(Client(TestClock()), [], node_pools, its_by_pool, pods_)

    import copy

    oracle_pods = copy.deepcopy(pods)
    oracle = Scheduler(node_pools, its_by_pool, fresh_topology(oracle_pods))
    oracle_results = oracle.solve(oracle_pods)

    solver = TpuSolver(node_pools, its_by_pool, fresh_topology(pods))
    tpu_results = solver.solve(pods)
    return oracle_results, tpu_results


def assert_parity(oracle_results, tpu_results, cost_tol=0.0):
    assert len(tpu_results.pod_errors) == len(oracle_results.pod_errors)
    assert tpu_results.node_count() == oracle_results.node_count()
    o_cost, t_cost = oracle_results.total_price(), tpu_results.total_price()
    if o_cost > 0:
        assert abs(t_cost - o_cost) <= cost_tol * o_cost + 1e-9, (t_cost, o_cost)


class TestIdenticalPods:
    def test_config0_500_identical(self):
        """BASELINE config[0]: 500 identical pods, 10 types."""
        oracle_r, tpu_r = run_both(
            make_pods(500, cpu="1", memory="2Gi"), instance_types=corpus.generate(10)
        )
        assert_parity(oracle_r, tpu_r)

    def test_small_batch(self):
        oracle_r, tpu_r = run_both(make_pods(7, cpu="2", memory="4Gi"))
        assert_parity(oracle_r, tpu_r)

    def test_single_pod(self):
        oracle_r, tpu_r = run_both([make_pod()])
        assert_parity(oracle_r, tpu_r)


class TestMixedPods:
    def test_two_shapes(self):
        pods = make_pods(20, cpu="1", memory="1Gi") + make_pods(5, cpu="8", memory="16Gi")
        oracle_r, tpu_r = run_both(pods)
        assert_parity(oracle_r, tpu_r)

    def test_many_shapes(self, rng):
        pods = []
        for _ in range(30):
            cpu = int(rng.integers(1, 8))
            mem = int(rng.integers(1, 16))
            count = int(rng.integers(1, 12))
            pods += make_pods(count, cpu=str(cpu), memory=f"{mem}Gi")
        oracle_r, tpu_r = run_both(pods)
        assert_parity(oracle_r, tpu_r)

    def test_gpu_mix(self):
        pods = make_pods(10, cpu="1", memory="1Gi") + make_pods(
            4, cpu="2", memory="8Gi", extra_requests={"nvidia.com/gpu": "1"}
        )
        oracle_r, tpu_r = run_both(pods, instance_types=corpus.generate())
        assert_parity(oracle_r, tpu_r)


class TestConstrainedPods:
    def test_zone_selector(self):
        pods = make_pods(12, cpu="1", node_selector={labels.TOPOLOGY_ZONE: "test-zone-b"})
        oracle_r, tpu_r = run_both(pods)
        assert_parity(oracle_r, tpu_r)
        for claim in tpu_r.new_node_claims:
            assert claim.requirements.get(labels.TOPOLOGY_ZONE).values == {"test-zone-b"}

    def test_capacity_type_selector(self):
        pods = make_pods(
            6,
            cpu="1",
            node_selector={labels.CAPACITY_TYPE_LABEL_KEY: labels.CAPACITY_TYPE_ON_DEMAND},
        )
        oracle_r, tpu_r = run_both(pods)
        assert_parity(oracle_r, tpu_r)

    def test_arch_requirement(self):
        pods = make_pods(
            5,
            requirements=[NodeSelectorRequirement(labels.ARCH, "In", ("arm64",))],
        )
        oracle_r, tpu_r = run_both(pods)
        assert_parity(oracle_r, tpu_r)

    def test_impossible_zone(self):
        pods = make_pods(3, node_selector={labels.TOPOLOGY_ZONE: "mars"})
        oracle_r, tpu_r = run_both(pods)
        assert len(tpu_r.pod_errors) == 3
        assert_parity(oracle_r, tpu_r)

    def test_oversized(self):
        oracle_r, tpu_r = run_both([make_pod(cpu="1000")])
        assert len(tpu_r.pod_errors) == 1
        assert_parity(oracle_r, tpu_r)


class TestNodePoolInteraction:
    def test_weight_order(self):
        pools = [make_nodepool("low", weight=1), make_nodepool("high", weight=50)]
        oracle_r, tpu_r = run_both(make_pods(4), node_pools=pools)
        assert_parity(oracle_r, tpu_r)
        for claim in tpu_r.new_node_claims:
            assert claim.template.node_pool_name == "high"

    def test_limits_cap_claims(self):
        # cap at 40 cpu; each claim pessimistically debits the largest
        # option capacity
        pools = [make_nodepool("limited", limits={"cpu": "40"})]
        pods = make_pods(200, cpu="1", memory="1Gi")
        oracle_r, tpu_r = run_both(pods, node_pools=pools)
        assert_parity(oracle_r, tpu_r)
        assert len(tpu_r.pod_errors) > 0  # limit prevents scheduling them all

    def test_limits_fall_back(self):
        pools = [
            make_nodepool("limited", weight=50, limits={"cpu": "1"}),
            make_nodepool("open", weight=1),
        ]
        oracle_r, tpu_r = run_both(make_pods(3), node_pools=pools)
        assert_parity(oracle_r, tpu_r)
        for claim in tpu_r.new_node_claims:
            assert claim.template.node_pool_name == "open"


class TestHybridRouting:
    def test_spread_pods_fall_back_to_oracle(self):
        from helpers import spread_constraint

        app = {"app": "x"}
        pods = make_pods(6, cpu="1") + make_pods(
            3, labels=app, spread=[spread_constraint(labels.HOSTNAME, labels=app)]
        )
        node_pools = [make_nodepool()]
        its_by_pool = {"default": corpus.generate(20)}
        topo = Topology(Client(TestClock()), [], node_pools, its_by_pool, pods)
        solver = TpuSolver(node_pools, its_by_pool, topo)
        results = solver.solve(pods)
        assert results.all_pods_scheduled()
        # hostname spread forces 3 dedicated nodes via the oracle path
        assert results.node_count() >= 4
