"""Incremental always-warm solving (ISSUE 8): the delta-encode
equivalence suite.

The contract under test: after ANY churn sequence — pod births, pod
deletes, label flips, node adds/removes, node capacity changes,
daemonset-overhead changes, pool-limit edits — the warm path
(ClusterEncoding banks + prior-snapshot fast path + device-resident
delta staging) produces an encoding BYTE-IDENTICAL to a from-scratch
``encode()`` of the same cluster, and decisions identical to a cold
solver's. A corrupt delta must trip the pre-decode invariant guard and
fall back to a full re-encode (the degradation ladder's half-step) —
never commit a stale snapshot.
"""

from __future__ import annotations

import dataclasses
import random

import numpy as np
import pytest

jax = pytest.importorskip("jax")
jax.config.update("jax_platforms", "cpu")

from karpenter_tpu import faults, obs
from karpenter_tpu.cloudprovider import corpus
from karpenter_tpu.kube import Client, TestClock
from karpenter_tpu.scheduling.topology import Topology
from karpenter_tpu.solver import encode as enc
from karpenter_tpu.solver.driver import EncodeCache, SolverConfig, TpuSolver

from helpers import make_nodepool, make_pod, make_state_node

_ITS = corpus.generate(16)


# -- churn harness -----------------------------------------------------------


from karpenter_tpu.api import labels as labels_mod

_POD_SHAPES = [
    dict(cpu="1", memory="2Gi"),
    dict(cpu="2", memory="4Gi"),
    dict(cpu="500m", memory="1Gi", labels={"tier": "web"}),
    dict(
        cpu="1500m", memory="3Gi",
        node_selector={labels_mod.TOPOLOGY_ZONE: "test-zone-a"},
    ),
]
_NODE_SHAPES = [
    dict(cpu="16", memory="64Gi", zone="test-zone-a"),
    dict(cpu="8", memory="32Gi", zone="test-zone-b"),
    dict(cpu="32", memory="128Gi", zone="test-zone-a"),
]
_ZONES = ["test-zone-a", "test-zone-b"]


class ChurnCluster:
    """Mutable cluster description; each tick materializes fresh objects
    (pods are shared — uids must match across solvers; state nodes are
    per-solver fresh copies, like production's deep-copied snapshots)."""

    def __init__(self, rng: random.Random):
        self.rng = rng
        self.pods = [make_pod(**_POD_SHAPES[i % len(_POD_SHAPES)]) for i in range(24)]
        self.nodes = [
            ["churn-n%d" % i, dict(_NODE_SHAPES[i % len(_NODE_SHAPES)])]
            for i in range(5)
        ]
        self.daemon_cpu = "100m"
        self.pool_limit = None  # or a cpu quantity string

    OPS = (
        "pod_birth", "pod_delete", "pod_label_flip",
        "node_add", "node_remove", "node_capacity", "node_zone_flip",
        "daemonset_change", "pool_limit_edit", "noop",
    )

    def tick(self, n_ops: int = 2) -> None:
        for _ in range(n_ops):
            op = self.rng.choice(self.OPS)
            getattr(self, "_op_" + op)()

    def _op_noop(self):
        pass

    def _op_pod_birth(self):
        self.pods.append(make_pod(**self.rng.choice(_POD_SHAPES)))

    def _op_pod_delete(self):
        if len(self.pods) > 4:
            self.pods.pop(self.rng.randrange(len(self.pods)))

    def _op_pod_label_flip(self):
        # a changed node-selector moves the pod to a different group
        p = self.rng.choice(self.pods)
        i = self.pods.index(p)
        shape = dict(self.rng.choice(_POD_SHAPES))
        shape["node_selector"] = {
            labels_mod.TOPOLOGY_ZONE: self.rng.choice(_ZONES)
        }
        self.pods[i] = make_pod(**shape)

    def _op_node_add(self):
        if len(self.nodes) < 9:
            self.nodes.append(
                [
                    "churn-n%d" % self.rng.randrange(100, 1000),
                    dict(self.rng.choice(_NODE_SHAPES)),
                ]
            )

    def _op_node_remove(self):
        if len(self.nodes) > 1:
            self.nodes.pop(self.rng.randrange(len(self.nodes)))

    def _op_node_capacity(self):
        name, shape = self.rng.choice(self.nodes)
        shape["cpu"] = self.rng.choice(["8", "16", "24"])

    def _op_node_zone_flip(self):
        name, shape = self.rng.choice(self.nodes)
        shape["zone"] = self.rng.choice(_ZONES)

    def _op_daemonset_change(self):
        self.daemon_cpu = self.rng.choice(["100m", "200m", "300m"])

    def _op_pool_limit_edit(self):
        self.pool_limit = self.rng.choice([None, "5000", "9000"])

    # -- materialization ---------------------------------------------------

    def pools(self):
        limits = {"cpu": self.pool_limit} if self.pool_limit else None
        return [make_nodepool(limits=limits)]

    def state_nodes(self):
        return [
            make_state_node(name=name, cpu=s["cpu"], memory=s["memory"], zone=s["zone"])
            for name, s in self.nodes
        ]

    def daemonset_pods(self):
        return [make_pod(name=None, cpu=self.daemon_cpu, memory="128Mi")]

    def build_solver(self, cache: EncodeCache) -> TpuSolver:
        pools = self.pools()
        its_by_pool = {pools[0].name: list(_ITS)}
        sns = self.state_nodes()
        topo = Topology(Client(TestClock()), sns, pools, its_by_pool, self.pods)
        return TpuSolver(
            pools,
            its_by_pool,
            topo,
            state_nodes=sns,
            daemonset_pods=self.daemonset_pods(),
            encode_cache=cache,
        )


def _assert_snapshots_identical(a: enc.EncodedSnapshot, b: enc.EncodedSnapshot):
    assert a.resource_names == b.resource_names
    assert a.existing_names == b.existing_names
    for f in dataclasses.fields(a):
        va, vb = getattr(a, f.name), getattr(b, f.name)
        if isinstance(va, np.ndarray):
            assert va.dtype == vb.dtype, f.name
            assert va.shape == vb.shape, f.name
            assert np.array_equal(va, vb), f"delta snapshot diverged in {f.name}"


def _decision_signature(results):
    return (
        sorted(
            (
                c.template.node_pool_name,
                tuple(sorted(p.uid for p in c.pods)),
                tuple(sorted(it.name for it in c.instance_type_options)),
                repr(sorted(map(repr, c.requirements))),
            )
            for c in results.new_node_claims
        ),
        sorted(
            (en.name, tuple(sorted(p.uid for p in en.pods)))
            for en in results.existing_nodes
        ),
        sorted(results.pod_errors),
    )


class TestDeltaEncodeEquivalence:
    @pytest.mark.parametrize("seed", [3, 11])
    def test_churn_script_byte_identical_and_same_decisions(self, seed):
        """Seeded property test: every tick of a random churn script, the
        warm incremental encoding equals a from-scratch encode of the
        same cluster byte-for-byte, and a warm solver's decisions equal a
        cold solver's."""
        rng = random.Random(seed)
        cluster = ChurnCluster(rng)
        warm_cache = EncodeCache()
        saw_reuse = saw_delta = False
        for t in range(14):
            if t:
                cluster.tick(rng.randrange(1, 3))
            warm = cluster.build_solver(warm_cache)
            cold = cluster.build_solver(EncodeCache())
            groups_w, rest_w = enc.partition_and_group(
                cluster.pods, topology=warm.oracle.topology
            )
            groups_c, rest_c = enc.partition_and_group(
                cluster.pods, topology=cold.oracle.topology
            )
            assert not rest_w and not rest_c
            snap_w, _, _, _, delta = warm._encode_batch(groups_w)
            snap_c, _, _, _, _delta_c = cold._encode_batch(groups_c)
            _assert_snapshots_identical(snap_w, snap_c)
            saw_reuse |= delta.reused
            saw_delta |= delta.delta_rows > 0
            # decision equivalence through the full solve (device staging,
            # queue, decode) — fresh solvers, same pod objects
            r_warm = cluster.build_solver(warm_cache).solve(cluster.pods)
            r_cold = cluster.build_solver(EncodeCache()).solve(cluster.pods)
            assert _decision_signature(r_warm) == _decision_signature(r_cold)
        # the script must actually exercise the warm machinery
        assert saw_delta, "churn script never took the delta path"

    def test_unchanged_cluster_reuses_snapshot_verbatim(self):
        cluster = ChurnCluster(random.Random(0))
        cache = EncodeCache()
        s1 = cluster.build_solver(cache)
        r1 = s1.solve(cluster.pods)
        assert not s1.last_encode_reused  # cold
        s2 = cluster.build_solver(cache)
        r2 = s2.solve(cluster.pods)
        assert s2.last_encode_reused
        assert s2.last_delta_rows == 0
        assert _decision_signature(r1) == _decision_signature(r2)
        # the reused snapshot shares the prior arrays by identity (zero
        # host assembly) but binds THIS solve's metadata
        cl = cache.cluster
        assert cl.last_delta.reused
        rec = obs.AUDIT.last()
        assert rec.encode_reused is True
        assert rec.delta_rows == 0

    def test_node_churn_reports_row_level_delta(self):
        cluster = ChurnCluster(random.Random(0))
        cache = EncodeCache()
        cluster.build_solver(cache).solve(cluster.pods)
        # touch ONE node's capacity: the delta must be row-level, not a
        # full re-encode
        cluster.nodes[2][1]["cpu"] = "24"
        s = cluster.build_solver(cache)
        s.solve(cluster.pods)
        d = cache.cluster.last_delta
        assert not d.reused and not d.full
        assert d.node_rows is not None and list(d.node_rows) == [2]
        assert d.groups_unchanged
        assert s.last_delta_rows >= 1

    def test_vocab_growth_falls_back_to_full_encode(self):
        """A genuinely new label value (vocab growth) drops the banks and
        the fast path for that encode — correctness over warmth."""
        cluster = ChurnCluster(random.Random(0))
        cache = EncodeCache()
        cluster.build_solver(cache).solve(cluster.pods)
        cluster.pods.append(
            make_pod(cpu="1", memory="1Gi", node_selector={"brand-new-key": "v"})
        )
        s = cluster.build_solver(cache)
        s.solve(cluster.pods)
        assert cache.cluster.last_delta.full
        assert not cache.cluster.last_delta.reused


class TestStaleBufferGates:
    """Review-hardening regressions: the residency layer must never feed
    the kernel a buffer that is more than one encode behind, and the
    delta contract must not paper over state its tags don't model."""

    def test_unstaged_encode_forces_full_restage(self):
        """An encode WITHOUT a device stage (a scenario batch declining
        after its encode, a native-backend solve) advances the version
        counters; the next stage must detect the gap and restage whole —
        a row delta would patch only the newest encode's rows and leave
        the skipped encode's rows stale on device."""
        cluster = ChurnCluster(random.Random(2))
        cache = EncodeCache()
        cluster.build_solver(cache).solve(cluster.pods)  # stage @ v
        # churn B: encode WITHOUT staging (versions advance, device stays)
        cluster.nodes[1][1]["cpu"] = "24"
        sB = cluster.build_solver(cache)
        groups, rest = enc.partition_and_group(
            cluster.pods, topology=sB.oracle.topology
        )
        assert not rest
        sB._encode_batch(groups)
        # churn C: a full warm solve — decisions must match a cold solver
        cluster.nodes[2][1]["cpu"] = "8"
        r_warm = cluster.build_solver(cache).solve(cluster.pods)
        r_cold = cluster.build_solver(EncodeCache()).solve(cluster.pods)
        assert _decision_signature(r_warm) == _decision_signature(r_cold)

    def test_empty_diff_after_unstaged_bump_restages(self):
        """The sharper shape of the same hazard: after the unstaged
        version-bumping encode, the NEXT encode changes nothing on the
        node axis — its node diff is EMPTY while the node version sits
        one ahead of the buffer. An empty patch must not stamp the buffer
        current (it still holds content from before the unstaged encode);
        the stage must restage whole."""
        cluster = ChurnCluster(random.Random(2))
        cache = EncodeCache()
        cluster.build_solver(cache).solve(cluster.pods)  # stage @ v
        cluster.nodes[1][1]["cpu"] = "24"  # node change...
        sB = cluster.build_solver(cache)
        groups, rest = enc.partition_and_group(
            cluster.pods, topology=sB.oracle.topology
        )
        assert not rest
        sB._encode_batch(groups)  # ...encoded but never staged
        # pods churn only: node tags identical to the unstaged encode's
        cluster.pods = cluster.pods[:-1]
        r_warm = cluster.build_solver(cache).solve(cluster.pods)
        r_cold = cluster.build_solver(EncodeCache()).solve(cluster.pods)
        assert _decision_signature(r_warm) == _decision_signature(r_cold)

    def test_topology_batch_rides_delta_contract(self):
        """ISSUE 10: topology-carrying batches participate in the
        content-tag fast paths. n_hcnt/nh_cnt0/g_dprior now derive from
        TopoSpec content the group sigs model FULLY (topo_content_sigs)
        and node tags carry the hostname — so an UNCHANGED topology batch
        re-encode hits the content-hash REUSE outcome (no forced FULL),
        while a constraint-content change (maxSkew here) still breaks the
        tags, bumps the cross version, and matches a cold solver."""
        cl2 = enc.ClusterEncoding()
        cache = EncodeCache()
        cache.cluster = cl2
        cluster = ChurnCluster(random.Random(4))
        from helpers import spread_constraint
        from karpenter_tpu.api import labels as labels_mod2

        def spread_pods(skew):
            return [
                make_pod(
                    cpu="1", memory="1Gi", labels={"app": "s"},
                    spread=[
                        spread_constraint(
                            labels_mod2.HOSTNAME, labels={"app": "s"},
                            max_skew=skew,
                        )
                    ],
                )
                for _ in range(4)
            ]

        cluster.pods = spread_pods(1)
        cluster.build_solver(cache).solve(cluster.pods)
        v1 = cl2.v_cross
        r_warm = cluster.build_solver(cache).solve(cluster.pods)
        assert cl2.last_delta.reused, (
            "unchanged topology batch must hit the REUSE fast path"
        )
        assert cl2.v_cross == v1, (
            "an unchanged topology encode must not churn the cross version"
        )
        r_cold = cluster.build_solver(EncodeCache()).solve(cluster.pods)
        assert _decision_signature(r_warm) == _decision_signature(r_cold)
        # constraint content change: tags break, cross restages, decisions
        # still match a cold solver
        cluster.pods = spread_pods(2)
        r_warm2 = cluster.build_solver(cache).solve(cluster.pods)
        assert not cl2.last_delta.reused
        assert cl2.v_cross > v1
        r_cold2 = cluster.build_solver(EncodeCache()).solve(cluster.pods)
        assert _decision_signature(r_warm2) == _decision_signature(r_cold2)

    def test_interned_hostname_node_swap_detected(self):
        """With a pod node-selector naming a node (hostname value
        interned), two nodes differing ONLY by hostname encode different
        mask rows — a positional node swap must break the fast path and
        match a cold solver's decisions."""
        from karpenter_tpu.api import labels as labels_mod2

        cluster = ChurnCluster(random.Random(6))
        cluster.pods = cluster.pods[:8] + [
            make_pod(
                cpu="1", memory="1Gi",
                node_selector={labels_mod2.HOSTNAME: "churn-n0"},
            )
        ]
        cache = EncodeCache()
        r1 = cluster.build_solver(cache).solve(cluster.pods)
        # the pinned pod landed on churn-n0
        assert any(
            en.name == "churn-n0" and en.pods for en in r1.existing_nodes
        )
        # swap node identity at position 0: same shape, different hostname
        # same sort position (the oracle orders nodes by name), same
        # shape — ONLY the hostname differs
        cluster.nodes[0][0] = "churn-n0x"
        r_warm = cluster.build_solver(cache).solve(cluster.pods)
        r_cold = cluster.build_solver(EncodeCache()).solve(cluster.pods)
        assert _decision_signature(r_warm) == _decision_signature(r_cold)
        # the pinned pod must NOT have been placed on the swapped node
        assert not any(
            en.name == "churn-n0x"
            and any(
                p.spec.node_selector.get(labels_mod2.HOSTNAME)
                == "churn-n0"
                for p in en.pods
            )
            for en in r_warm.existing_nodes
        )


class TestCorruptDeltaFallback:
    def _solve_with_injector(self, rules, health=None):
        cluster = ChurnCluster(random.Random(0))
        cache = EncodeCache()
        cfg = SolverConfig(health=health)
        cluster.build_solver(cache).solve(cluster.pods)  # warm + stage
        # churn one node so the next stage takes the row-delta path
        cluster.nodes[0][1]["cpu"] = "8"
        pools = cluster.pools()
        its_by_pool = {pools[0].name: list(_ITS)}
        sns = cluster.state_nodes()
        topo = Topology(Client(TestClock()), sns, pools, its_by_pool, cluster.pods)
        solver = TpuSolver(
            pools, its_by_pool, topo, state_nodes=sns,
            daemonset_pods=cluster.daemonset_pods(),
            config=cfg, encode_cache=cache,
        )
        inj = faults.install(faults.FaultInjector(rules, seed=1))
        try:
            results = solver.solve(cluster.pods)
        finally:
            faults.uninstall()
        return cluster, cache, solver, inj, results

    def test_corrupt_delta_trips_guard_and_full_reencode(self):
        """A corrupted delta row (inflated node capacity on the device
        copy) must be caught by the pre-decode invariant guard and
        answered with a full re-encode retry — correct results, nothing
        stale committed, no rung tripped."""
        rules = [
            faults.FaultRule(
                site=faults.ENCODE_DELTA,
                mutate=lambda vals: np.full_like(vals, 10_000_000),
                match=lambda ctx: ctx.get("name") == "n_avail",
                times=1,
            )
        ]
        from karpenter_tpu.faults.breaker import SolverHealth

        health = SolverHealth(TestClock())
        cluster, cache, solver, inj, results = self._solve_with_injector(
            rules, health=health
        )
        assert inj.fired(faults.ENCODE_DELTA) >= 1
        # the half-step: warm state shed, retried clean, rung intact
        assert health.delta_fallbacks == 1
        assert health.level() == 0
        assert not results.pod_errors
        # decisions equal a cold solver's (nothing stale committed)
        r_cold = cluster.build_solver(EncodeCache()).solve(cluster.pods)
        assert _decision_signature(results) == _decision_signature(r_cold)
        # the fallback invalidated the warm encoding: this encode was full
        assert cache.cluster.last_delta.full
        # audit provenance describes the committed (full re-encode)
        # attempt, not the discarded incremental one
        rec = obs.AUDIT.last()
        assert rec.encode_reused is False
        assert rec.delta_rows == 0

    def test_corrupt_delta_without_health_still_recovers(self):
        rules = [
            faults.FaultRule(
                site=faults.ENCODE_DELTA,
                mutate=lambda vals: np.full_like(vals, 10_000_000),
                match=lambda ctx: ctx.get("name") == "n_avail",
                times=1,
            )
        ]
        cluster, cache, solver, inj, results = self._solve_with_injector(rules)
        assert not results.pod_errors
        r_cold = cluster.build_solver(EncodeCache()).solve(cluster.pods)
        assert _decision_signature(results) == _decision_signature(r_cold)


class TestAsyncQueueEquivalence:
    @pytest.mark.parametrize("n_nodes", [40])
    def test_single_node_sweep_identical_with_and_without_prefetch(
        self, monkeypatch, n_nodes
    ):
        """Batched decisions are identical with and without the async
        double-buffered prefetch (the queue is pure overlap, never
        semantics)."""
        from karpenter_tpu.solver.workloads import (
            build_single_consolidation_env,
        )

        def decide(prefetch: str):
            monkeypatch.setenv("KTPU_PREFETCH", prefetch)
            ctx, method, candidates, budgets = build_single_consolidation_env(
                n_nodes
            )
            cmd = method.compute_command(candidates, budgets)
            return (
                cmd.decision,
                sorted(c.node_claim.name for c in cmd.candidates),
                [
                    sorted(it.name for it in r.instance_type_options)
                    for r in cmd.replacements
                ],
            )

        assert decide("0") == decide("1")

    def test_queue_submit_fault_degrades_batched_rung(self):
        """A DISPATCH_QUEUE fault at submit is absorbed like any batched
        dispatch failure: the batch declines, the breaker records it, and
        callers replay per-probe."""
        from karpenter_tpu.faults.breaker import SolverHealth
        from karpenter_tpu.solver.driver import Scenario

        cluster = ChurnCluster(random.Random(0))
        health = SolverHealth(TestClock())
        cache = EncodeCache()
        pools = cluster.pools()
        its_by_pool = {pools[0].name: list(_ITS)}
        sns = cluster.state_nodes()
        topo = Topology(Client(TestClock()), sns, pools, its_by_pool, cluster.pods)
        solver = TpuSolver(
            pools, its_by_pool, topo, state_nodes=sns,
            config=SolverConfig(health=health), encode_cache=cache,
        )
        inj = faults.install(
            faults.FaultInjector(
                [
                    faults.FaultRule(
                        site=faults.DISPATCH_QUEUE,
                        match=lambda ctx: ctx.get("op") == "submit",
                        times=1,
                    )
                ],
                seed=0,
            )
        )
        try:
            out = solver.solve_scenarios(
                [Scenario(pods=list(cluster.pods))]
            )
        finally:
            faults.uninstall()
        assert out is None
        assert inj.fired(faults.DISPATCH_QUEUE) == 1


class TestBankCompaction:
    def test_stale_bank_entries_evicted(self):
        cl = enc.ClusterEncoding(compact_every=4)
        cluster = ChurnCluster(random.Random(5))
        cache = EncodeCache()
        cache.cluster = cl
        cluster.build_solver(cache).solve(cluster.pods)
        assert cl.group_bank
        # a key unique to the first shape (the zone-selector group), then
        # churn the group set away and keep encoding NEW shapes (each
        # tick consults the group bank — the use clock only advances on
        # consulting encodes): the stale entry must age out
        marker = next(k for k in cl.group_bank if k)
        for tick in range(16):
            cpu = "3" if tick % 2 else "4"
            cluster.pods = [
                make_pod(cpu=cpu, memory="6Gi", labels={"gen": "two"})
                for _ in range(6)
            ]
            cluster.build_solver(cache).solve(cluster.pods)
        assert cl._guses >= 12
        assert marker not in cl.group_bank, (
            "stale group-bank entry survived compaction"
        )

    def test_quiet_reuse_does_not_age_live_entries(self):
        """Consecutive content-hash reuses must not age the still-live
        bank entries to eviction: the next churn tick is exactly when the
        banks are supposed to be warm."""
        cl = enc.ClusterEncoding(compact_every=2)
        cluster = ChurnCluster(random.Random(5))
        cache = EncodeCache()
        cache.cluster = cl
        cluster.build_solver(cache).solve(cluster.pods)
        live = set(cl.group_bank) | set(cl.node_bank)
        assert live
        for _ in range(10):  # a quiet cluster: every encode reuses
            cluster.build_solver(cache).solve(cluster.pods)
        assert cl.last_delta.reused
        assert live <= (set(cl.group_bank) | set(cl.node_bank)), (
            "quiet reuse evicted live bank entries"
        )


class TestFaultSiteRegistry:
    def test_new_sites_registered(self):
        assert faults.ENCODE_DELTA in faults.ALL_SITES
        assert faults.DISPATCH_QUEUE in faults.ALL_SITES


class TestTopologyResidencyContract:
    """ISSUE 10 analyzer/pinning satellite: the topology prior rows are
    first-class members of the device-residency contract — classified
    into the residency argument classes, batched by the scenario axis by
    NAME through SOLVE_ARG_NAMES, and reusable on device across warm
    topology solves with no new sanctioned host crossing (the DTX906
    blessed set stays pinned by tests/test_analysis.py)."""

    def test_topology_args_classified(self):
        from karpenter_tpu.ops.solve import SCENARIO_TOPO_BATCHED_ARGS
        from karpenter_tpu.solver import residency

        assert "g_dprior" in residency.GROUP_ARGS
        assert {"n_hcnt", "nh_cnt0"} <= residency.CROSS_ARGS
        assert "dd0" in residency.GROUP_ARGS
        assert "dd0" in residency.NO_ROW_DELTA  # slot axis, never row-delta
        assert set(SCENARIO_TOPO_BATCHED_ARGS) <= set(enc.SOLVE_ARG_NAMES)

    def test_warm_topology_solve_reuses_device_buffers(self):
        """Second solve of an unchanged topology cluster: the residency
        store must report an incremental stage (buffers reused, zero full
        puts for the topology rows) instead of the pre-ISSUE-10 behavior
        of restaging the cross class on every topology encode."""
        from helpers import spread_constraint

        cluster = ChurnCluster(random.Random(11))
        cache = EncodeCache()
        cluster.pods = [
            make_pod(
                cpu="1", memory="1Gi", labels={"app": "rz"},
                spread=[
                    spread_constraint(
                        labels_mod.TOPOLOGY_ZONE, labels={"app": "rz"}
                    )
                ],
            )
            for _ in range(6)
        ]
        cluster.build_solver(cache).solve(cluster.pods)
        store = cache.device_store
        assert store is not None
        cluster.build_solver(cache).solve(cluster.pods)
        assert store.last_incremental, (
            "warm topology solve must reuse device-resident buffers"
        )
        assert store.last_full_puts == 0


class TestGroupChurnCompileCache:
    """ISSUE 13: power-of-two group bucketing must keep the XLA compile
    cache flat under group churn. Groups appearing and disappearing
    across ticks change the REAL group count every solve; because the
    kernel runs at the padded pow2 bucket (and the segment index rides
    pow2 live-pair buckets), every tick reuses one compiled program, and
    the delta encoder keeps serving REUSE / row-level deltas — no full
    re-encodes, no recompiles."""

    def _palette(self):
        shapes = []
        for cpu in ("250m", "500m", "750m", "1", "1250m", "1500m"):
            for mem in ("1Gi", "2Gi", "3Gi"):
                shapes.append(dict(cpu=cpu, memory=mem))
        # two selector shapes keep a stable nonzero live-pair set (their
        # counts churn, their GROUPS never vanish, so the segment-index
        # bucket is exercised without vocab growth)
        shapes.append(
            dict(cpu="2", memory="4Gi",
                 node_selector={labels_mod.TOPOLOGY_ZONE: "test-zone-a"})
        )
        shapes.append(
            dict(cpu="2", memory="2Gi",
                 node_selector={labels_mod.TOPOLOGY_ZONE: "test-zone-b"})
        )
        return shapes

    def test_group_churn_compile_count_flat_and_warm(self):
        from karpenter_tpu.ops.solve import (
            solve_all_classed_packed,
            solve_all_packed,
        )

        rng = random.Random(1234)
        palette = self._palette()
        # every palette shape present once at warmup: the vocab and the
        # static side intern everything up front, so later churn can only
        # move counts and add/remove GROUPS, never grow the vocab
        counts = {i: 2 for i in range(len(palette))}
        cache = EncodeCache()

        def pods_now():
            out = []
            for i in sorted(counts):
                out.extend(
                    make_pod(**palette[i]) for _ in range(counts[i])
                )
            return out

        def solve_once():
            pods = pods_now()
            pools = [make_nodepool()]
            its_by_pool = {pools[0].name: list(_ITS)}
            topo = Topology(Client(TestClock()), [], pools, its_by_pool, pods)
            s = TpuSolver(pools, its_by_pool, topo, encode_cache=cache)
            r = s.solve(pods)
            assert not r.pod_errors
            return s

        dead: set = set()

        def churn():
            # swap which plain shapes are ABSENT (groups removed AND
            # re-added every tick) and move counts around; selector
            # shapes only ever change counts. The real group count moves
            # inside one pow2 bucket — crossing a bucket boundary is a
            # legitimate recompile and not what this test exercises.
            plain = list(range(len(palette) - 2))
            for i in dead:
                counts[i] = rng.randrange(1, 3)
            dead.clear()
            dead.update(rng.sample(plain, 2))
            for i in dead:
                counts[i] = 0
            for i in rng.sample(plain, 3):
                if counts[i]:
                    counts[i] += rng.randrange(1, 3)
            for i in (len(palette) - 2, len(palette) - 1):
                counts[i] = rng.randrange(1, 4)

        # warmup: a-priori NMAX + adaptive NMAX shapes compile here
        solve_once()
        solve_once()
        churn()
        solve_once()  # first churned shape, still within the warm buckets

        def cache_sizes():
            return (
                solve_all_packed._cache_size()
                + solve_all_classed_packed._cache_size()
            )

        baseline = cache_sizes()
        for _ in range(6):
            churn()
            s = solve_once()
            # warm path intact: the encoder served the solve from the
            # banks (row-delta or verbatim REUSE), never a full restage
            assert s._last_incremental, "group churn lost the warm path"
            assert cache.cluster.last_delta.full is False
        assert cache_sizes() == baseline, (
            "group churn forked the XLA compile cache despite pow2 "
            "bucketing"
        )
