"""Concurrency stress: the race-hunting tier.

The reference runs every suite under the Go race detector with randomized
ordering (Makefile:76-93). Python has no -race, so this is the analog: the
lock-guarded store and watch-fed Cluster are hammered from many threads
while a reader thread continuously takes snapshots, and invariants are
checked at every step. Failures here are real races (torn snapshots, lost
watch events, inconsistent indexes), not flakes.
"""

import threading

import pytest

from karpenter_tpu.api import labels
from karpenter_tpu.api.objects import Node, NodeClaim, NodeClaimSpec, ObjectMeta, Pod
from karpenter_tpu.api import resources as res
from karpenter_tpu.controllers.state import Cluster
from karpenter_tpu.kube import Client, TestClock

from helpers import make_pod

N_THREADS = 6
N_OPS = 150


def _node(i: int) -> Node:
    node = Node(
        metadata=ObjectMeta(
            name=f"race-n{i}",
            labels={
                labels.HOSTNAME: f"race-n{i}",
                labels.TOPOLOGY_ZONE: "test-zone-a",
            },
        ),
        provider_id=f"race://{i}",
    )
    node.status.capacity = {
        "cpu": res.parse_quantity("8"),
        "memory": res.parse_quantity("16Gi"),
    }
    node.status.allocatable = dict(node.status.capacity)
    node.status.ready = True
    return node


class TestStoreAndClusterRaces:
    def test_concurrent_churn_keeps_cluster_consistent(self):
        clock = TestClock()
        client = Client(clock)
        cluster = Cluster(client)
        errors = []
        barrier = threading.Barrier(N_THREADS + 1)

        def churn(tid: int):
            try:
                barrier.wait()
                for i in range(N_OPS):
                    ident = tid * N_OPS + i
                    node = _node(ident)
                    claim = NodeClaim(
                        metadata=ObjectMeta(name=f"race-n{ident}"),
                        spec=NodeClaimSpec(),
                    )
                    claim.status.provider_id = node.provider_id
                    client.create(claim)
                    client.create(node)
                    pod = make_pod(
                        name=f"race-p{ident}", node_name=node.name,
                        phase="Running",
                    )
                    client.create(pod)
                    if i % 3 == 0:
                        pod.status.phase = "Succeeded"
                        client.update(pod)
                    if i % 5 == 0:
                        claim.metadata.finalizers.clear()
                        client.delete(pod)
                        client.delete(node)
                        client.delete(claim)
            except Exception as exc:  # pragma: no cover - failure reporting
                errors.append(exc)

        stop = threading.Event()

        def reader():
            try:
                barrier.wait()
                while not stop.is_set():
                    # deep-copied snapshots must never tear: every node
                    # carries consistent identity and bindings
                    for sn in cluster.nodes():
                        assert sn.name, "torn snapshot: unnamed node"
                        sn.available()  # must not raise mid-copy
                        for p in sn.pods:
                            assert p.spec.node_name == sn.name, (
                                "torn snapshot: pod bound elsewhere"
                            )
                    cluster.synced()
            except Exception as exc:  # pragma: no cover - race reporting
                errors.append(exc)

        threads = [
            threading.Thread(target=churn, args=(t,)) for t in range(N_THREADS)
        ]
        rd = threading.Thread(target=reader)
        for t in threads:
            t.start()
        rd.start()
        for t in threads:
            t.join(60)
        stop.set()
        rd.join(30)
        assert not errors, errors

        # steady state: the cluster converged to exactly the store's view
        assert cluster.synced()
        live_nodes = {n.provider_id for n in client.list(Node)}
        tracked = {sn.provider_id for sn in cluster.nodes()}
        assert tracked == live_nodes
        # pod bindings settled onto the right nodes
        for sn in cluster.nodes():
            for p in sn.pods:
                assert p.spec.node_name == sn.name

    def test_file_store_churn_no_deadlock(self, tmp_path):
        """The file backend persists under the store lock but must notify
        watchers OUTSIDE it: the cluster cache takes its own lock in
        handlers and calls back into client reads (the ABBA pair). Churn
        + a synced()-polling reader would deadlock in seconds if
        notification ever moved back under the lock."""
        from karpenter_tpu.kube import FileClient

        clock = TestClock()
        client = FileClient(clock, root=str(tmp_path / "store"))
        cluster = Cluster(client)
        errors: list = []
        stop = threading.Event()
        barrier = threading.Barrier(4)

        def churn(tid: int):
            try:
                barrier.wait()
                for i in range(60):
                    ident = tid * 1000 + i
                    node = _node(ident)
                    client.create(node)
                    node.status.ready = i % 2 == 0
                    client.update(node)
                    if i % 3 == 0:
                        client.delete(node)
            except Exception as exc:  # pragma: no cover
                errors.append(exc)

        def reader():
            try:
                barrier.wait()
                while not stop.is_set():
                    cluster.synced()  # cluster lock -> client.list
                    for sn in cluster.nodes():
                        sn.available()
            except Exception as exc:  # pragma: no cover
                errors.append(exc)

        # daemon threads: if the deadlock this test hunts regresses, the
        # assertion below reports it and the interpreter can still exit
        # (non-daemon wedged threads would hang pytest shutdown instead)
        threads = [
            threading.Thread(target=churn, args=(t,), daemon=True)
            for t in range(3)
        ]
        rd = threading.Thread(target=reader, daemon=True)
        for t in threads:
            t.start()
        rd.start()
        for t in threads:
            t.join(60)
        alive = [t for t in threads if t.is_alive()]
        stop.set()
        rd.join(30)
        assert not alive, "deadlock: churn threads never finished"
        assert not rd.is_alive(), "reader wedged"
        assert not errors, errors
        # a fresh client over the directory resumes the EXACT final state
        # — versions included: the lost-update hazard _atomic prevents
        # keeps the name set intact but resurrects older resource versions
        client2 = FileClient(clock, root=str(tmp_path / "store"))
        assert {
            (n.name, n.metadata.resource_version)
            for n in client2.list(Node)
        } == {
            (n.name, n.metadata.resource_version)
            for n in client.list(Node)
        }

    def test_provisioner_disruption_orchestration_triangle(self):
        """The triangle VERDICT r4 #8 names: provisioning solves,
        disruption decisions (which mutate the orchestration queue), and
        the lifecycle/GC pair all reconciling CONCURRENTLY over one store
        and cluster cache, with the GIL switch interval cranked down so
        interleavings actually happen. The reference runs this under
        `go test -race` (Makefile:78); here every controller invariant
        violation surfaces as an exception in some thread."""
        import sys

        from karpenter_tpu.cloudprovider import corpus
        from karpenter_tpu.cloudprovider.kwok import KwokCloudProvider
        from karpenter_tpu.operator import Operator, OperatorOptions
        from karpenter_tpu.sim import Binder

        from helpers import make_nodepool, make_pods

        old_interval = sys.getswitchinterval()
        sys.setswitchinterval(1e-5)  # injected yields
        try:
            clock = TestClock()
            client = Client(clock)
            provider = KwokCloudProvider(client, corpus.generate(16))
            op = Operator(client, provider, OperatorOptions())
            binder = Binder(client)
            client.create(make_nodepool())
            errors: list = []
            stop = threading.Event()
            barrier = threading.Barrier(5)

            def guarded(fn):
                def run():
                    try:
                        barrier.wait()
                        while not stop.is_set():
                            fn()
                    except Exception as exc:  # pragma: no cover
                        errors.append(exc)

                return run

            def provision():
                provider.process_registrations()
                op.provisioner.reconcile(force=True)
                binder.bind_all()
                clock.step(0.5)

            def disrupt():
                op.nodeclaim_disruption.reconcile_all()
                op.disruption.reconcile(force=True)

            def lifecycle_gc():
                op.lifecycle.reconcile_all()
                op.garbage_collection.reconcile()
                op.termination.reconcile_all()

            def housekeeping():
                op.nodepool_status.reconcile_all()
                op.expiration.reconcile_all()
                op.consistency.reconcile_all()

            threads = [
                threading.Thread(target=guarded(fn))
                for fn in (provision, disrupt, lifecycle_gc, housekeeping)
            ]
            for t in threads:
                t.start()

            # workload churn from the main thread: waves of pods arriving
            # and completing while every controller races
            barrier.wait()
            for wave in range(4):
                pods = make_pods(12, cpu="1", memory="1Gi")
                for i, p in enumerate(pods):
                    p.metadata.name = f"tri-{wave}-{i}"
                    client.create(p)
                deadline = __import__("time").time() + 30
                while __import__("time").time() < deadline:
                    pending = [
                        p for p in client.list(Pod)
                        if p.metadata.name.startswith(f"tri-{wave}")
                        and not p.spec.node_name
                    ]
                    if not pending or errors:
                        break
                    __import__("time").sleep(0.02)
            stop.set()
            for t in threads:
                t.join(60)
            assert not errors, errors

            # convergence: a few quiet serial passes settle everything,
            # and the cluster cache exactly mirrors the store
            for _ in range(6):
                op.step(force_provision=True)
                binder.bind_all()
                clock.step(1)
            unbound = [p for p in client.list(Pod) if not p.spec.node_name]
            assert not unbound, [p.metadata.name for p in unbound]
            assert op.cluster.synced()
            live = {n.provider_id for n in client.list(Node)}
            tracked = {sn.provider_id for sn in op.cluster.nodes()}
            assert tracked == live
        finally:
            sys.setswitchinterval(old_interval)

    def test_orchestration_queue_mutation_during_validation(self):
        """Commands enqueued while the queue reconciles (validation's 15s
        TTL window, orchestration/queue.go): adds from one thread, drains
        from another, no lost or doubled commands."""
        import sys

        from karpenter_tpu.controllers.disruption.controller import (
            OrchestrationQueue,
        )
        from karpenter_tpu.controllers.disruption.types import Command

        class Ctx:
            def __init__(self, clock):
                self.clock = clock
                self.cluster = None
                self.client = None
                self.recorder = None

        old_interval = sys.getswitchinterval()
        sys.setswitchinterval(1e-5)
        try:
            clock = TestClock()
            queue = OrchestrationQueue(Ctx(clock))
            errors: list = []
            N = 400

            def producer():
                try:
                    for i in range(N):
                        queue.add(Command(), [])
                except Exception as exc:  # pragma: no cover
                    errors.append(exc)

            def scanner():
                try:
                    for _ in range(N):
                        # has_provider_id walks items while add() appends
                        queue.has_provider_id("nope")
                except Exception as exc:  # pragma: no cover
                    errors.append(exc)

            threads = [
                threading.Thread(target=producer),
                threading.Thread(target=scanner),
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(60)
            assert not errors, errors
            assert len(queue.items) == N
        finally:
            sys.setswitchinterval(old_interval)

    def test_concurrent_solves_share_encode_cache(self):
        """Many threads solving through one shared EncodeCache (the
        provisioner/disruption topology) must not corrupt the vocab or the
        static arrays."""
        import jax

        jax.config.update("jax_platforms", "cpu")
        from karpenter_tpu.cloudprovider import corpus
        from karpenter_tpu.scheduling.topology import Topology
        from karpenter_tpu.solver import TpuSolver
        from karpenter_tpu.solver.driver import EncodeCache, SolverConfig

        from helpers import make_nodepool, make_pods

        cache = EncodeCache()
        pools = [make_nodepool()]
        its = {pools[0].name: corpus.generate(12)}
        results = []
        errors = []
        barrier = threading.Barrier(4)

        def solve(tid: int):
            try:
                barrier.wait()
                for _ in range(5):
                    pods = make_pods(40 + tid, cpu="1", memory="1Gi")
                    topo = Topology(
                        Client(TestClock()), [], pools, its, pods
                    )
                    # relax=False pins the exact route: the hint records
                    # the EXACT kernel's claim count (bulk claims the
                    # relaxation places are excluded by design), and this
                    # plain identical-pod batch would otherwise route
                    # entirely through the bulk pre-solver, recording 0
                    solver = TpuSolver(
                        pools, its, topo,
                        config=SolverConfig(relax=False),
                        encode_cache=cache,
                    )
                    r = solver.solve(pods)
                    assert r.all_pods_scheduled(), r.pod_errors
                    results.append(r.node_count())
            except Exception as exc:  # pragma: no cover
                errors.append(exc)

        threads = [threading.Thread(target=solve, args=(t,)) for t in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(120)
        assert not errors, errors
        assert len(results) == 20
        # the adaptive NMAX hint is a max-merge under the cache lock: after
        # 20 concurrent solves it must hold the LARGEST observed claim
        # count (a lost update would leave a smaller thread's value and
        # re-trigger the overflow ladder on the next big solve)
        hint = cache.cache.get("nmax_hint")
        assert hint is not None and hint >= max(results), (hint, results)
