"""File-backed store semantics: persistence, restart resume, copy
isolation (kube/filestore.py — the second backend behind the Client seam,
analog of the reference's envtest-against-a-real-apiserver tier)."""

import pytest

from karpenter_tpu.api.objects import NodeClaim, ObjectMeta
from karpenter_tpu.kube import FileClient, NotFoundError, TestClock

from helpers import make_nodepool, make_pod


def _client(tmp_path, clock=None):
    return FileClient(clock or TestClock(), root=str(tmp_path / "store"))


class TestPersistence:
    def test_restart_resumes_state(self, tmp_path):
        clock = TestClock()
        c1 = _client(tmp_path, clock)
        pool = make_nodepool()
        c1.create(pool)
        c1.create(make_pod(name="p-1"))
        pool2 = c1.get("NodePool", pool.metadata.name)
        pool2.spec.weight = 42
        c1.update(pool2)

        # a NEW client over the same directory sees everything, including
        # the update, with resource versions preserved
        c2 = _client(tmp_path, clock)
        got = c2.get("NodePool", pool.metadata.name)
        assert got.spec.weight == 42
        assert got.metadata.resource_version == pool2.metadata.resource_version
        assert len(c2.list("Pod")) == 1

    def test_delete_removes_from_disk(self, tmp_path):
        c1 = _client(tmp_path)
        pod = make_pod(name="gone")
        c1.create(pod)
        c1.delete(pod)
        c2 = _client(tmp_path)
        assert c2.try_get("Pod", "gone") is None

    def test_finalizer_two_phase_survives_restart(self, tmp_path):
        clock = TestClock()
        c1 = _client(tmp_path, clock)
        claim = NodeClaim(metadata=ObjectMeta(name="nc-1"))
        claim.metadata.finalizers.append("karpenter/termination")
        c1.create(claim)
        c1.delete(claim)  # phase 1: marks deletion, keeps the object

        c2 = _client(tmp_path, clock)
        stored = c2.get("NodeClaim", "nc-1")
        assert stored.metadata.deletion_timestamp is not None
        c2.remove_finalizer(stored, "karpenter/termination")
        with pytest.raises(NotFoundError):
            c2.get("NodeClaim", "nc-1")
        # phase 2 completed on disk too
        c3 = _client(tmp_path, clock)
        assert c3.try_get("NodeClaim", "nc-1") is None


class TestCopySemantics:
    def test_reads_are_isolated_copies(self, tmp_path):
        c = _client(tmp_path)
        pool = make_nodepool()
        c.create(pool)
        a = c.get("NodePool", pool.metadata.name)
        a.spec.weight = 99  # mutating a read must NOT leak into the store
        b = c.get("NodePool", pool.metadata.name)
        assert b.spec.weight != 99

    def test_caller_handle_gets_server_metadata(self, tmp_path):
        c = _client(tmp_path)
        pod = make_pod(name="stamped")
        c.create(pod)
        assert pod.metadata.resource_version > 0
        assert pod.metadata.creation_timestamp is not None

    def test_watch_events_carry_copies(self, tmp_path):
        c = _client(tmp_path)
        seen = []
        c.watch(seen.append)
        pod = make_pod(name="w-1")
        c.create(pod)
        assert seen and seen[-1].object is not pod
        seen[-1].object.metadata.name = "corrupted"
        assert c.try_get("Pod", "w-1") is not None


class TestIndexedReads:
    """kube/store.py inverted label/field indexes (ISSUE 14 satellite):
    selector reads must return exactly what a full scan filters to, in the
    same (insertion) order, across every CRUD shape — on both backends.
    The 100k-node twin's informer rebuilds read these indexes; a stale
    entry here is a silently-wrong roster, so the pin is an oracle diff
    under seeded churn, not a handful of point cases."""

    def _backend(self, which, tmp_path):
        from karpenter_tpu.kube import Client, FileClient, TestClock

        if which == "memory":
            return Client(TestClock())
        return FileClient(TestClock(), root=str(tmp_path / "idx"))

    @staticmethod
    def _ids(objs):
        return [(o.metadata.name, o.metadata.resource_version) for o in objs]

    def _oracle(self, client, kind, label_selector=None, field_selector=None):
        """The full-scan definition of a selector read."""
        from karpenter_tpu.kube.store import _FIELD_EXTRACTORS

        kind_name = kind if isinstance(kind, str) else kind.__name__
        out = []
        for o in client.list(kind):
            labels = o.metadata.labels or {}
            if any(
                labels.get(k) != v
                for k, v in (label_selector or {}).items()
            ):
                continue
            if any(
                (_FIELD_EXTRACTORS[kind_name][f](o) or None) != v
                for f, v in (field_selector or {}).items()
            ):
                continue
            out.append(o)
        return out

    def _assert_matches_oracle(self, client):
        from karpenter_tpu.api.objects import Pod

        probes = [
            ({"app": "a"}, None),
            ({"app": "b"}, None),
            ({"app": "a", "tier": "web"}, None),
            (None, {"spec.nodeName": "n-0"}),
            (None, {"spec.nodeName": "n-1"}),
            ({"app": "b"}, {"spec.nodeName": "n-0"}),
        ]
        for label_sel, field_sel in probes:
            got = client.list(
                Pod, label_selector=label_sel, field_selector=field_sel
            )
            want = self._oracle(
                client, Pod, label_selector=label_sel, field_selector=field_sel
            )
            assert self._ids(got) == self._ids(want), (label_sel, field_sel)

    @pytest.mark.parametrize("which", ["memory", "file"])
    def test_seeded_churn_matches_full_scan(self, which, tmp_path):
        """Creates, label flips (copy-mutate AND stored-reference-mutate,
        the twin's shape), binds/unbinds, plain deletes, and two-phase
        finalizer deletes: after every step the indexed read equals the
        full-scan oracle."""
        import random

        from karpenter_tpu.api.objects import Pod

        rng = random.Random(4242)
        client = self._backend(which, tmp_path)
        live = []
        for step in range(120):
            op = rng.randrange(6)
            if op in (0, 1) or not live:  # create
                name = f"p-{step}"
                pod = make_pod(
                    name=name,
                    labels={
                        "app": rng.choice("ab"),
                        "tier": rng.choice(("web", "db")),
                    },
                    node_name=rng.choice(("", "n-0", "n-1")),
                )
                if rng.randrange(3) == 0:
                    pod.metadata.finalizers.append("ktpu/test")
                client.create(pod)
                live.append(name)
            elif op == 2:  # label flip via the stored handle (twin shape)
                pod = client.try_get(Pod, rng.choice(live))
                if pod is not None:
                    pod.metadata.labels["app"] = rng.choice("ab")
                    client.update(pod)
            elif op == 3:  # bind/unbind
                pod = client.try_get(Pod, rng.choice(live))
                if pod is not None:
                    pod.spec.node_name = rng.choice(("", "n-0", "n-1"))
                    client.update(pod)
            elif op == 4:  # delete (two-phase when finalized)
                name = live[rng.randrange(len(live))]
                pod = client.try_get(Pod, name)
                if pod is not None:
                    client.delete(pod)
                    if pod.metadata.finalizers and rng.randrange(2):
                        client.remove_finalizer(pod, "ktpu/test")
                if client.try_get(Pod, name) is None:
                    live.remove(name)
            else:  # label ADD (new key) then flip back off via update
                pod = client.try_get(Pod, rng.choice(live))
                if pod is not None:
                    if "extra" in (pod.metadata.labels or {}):
                        del pod.metadata.labels["extra"]
                    else:
                        pod.metadata.labels["extra"] = "x"
                    client.update(pod)
            if step % 10 == 9:
                self._assert_matches_oracle(client)
        self._assert_matches_oracle(client)

    def test_unknown_field_selector_raises(self, tmp_path):
        from karpenter_tpu.api.objects import Pod
        from karpenter_tpu.kube import Client, TestClock

        c = Client(TestClock())
        with pytest.raises(ValueError, match="not indexed"):
            c.list(Pod, field_selector={"status.phase": "Running"})

    def test_export_import_rebuilds_index(self):
        from karpenter_tpu.api.objects import Pod
        from karpenter_tpu.kube import Client, TestClock

        c1 = Client(TestClock())
        for i in range(8):
            c1.create(
                make_pod(
                    name=f"p{i}",
                    labels={"app": "a" if i % 2 else "b"},
                    node_name="n-0" if i < 4 else "",
                )
            )
        c2 = Client(TestClock())
        c2.import_objects(c1.export_objects())
        self._assert_matches_oracle(c2)
        got = c2.list(
            Pod, label_selector={"app": "a"},
            field_selector={"spec.nodeName": "n-0"},
        )
        assert [o.metadata.name for o in got] == ["p1", "p3"]

    def test_filestore_restart_rebuilds_index(self, tmp_path):
        from karpenter_tpu.api.objects import Pod
        from karpenter_tpu.kube import FileClient, TestClock

        c1 = self._backend("file", tmp_path)
        for i in range(6):
            c1.create(
                make_pod(
                    name=f"p{i}",
                    labels={"app": "a" if i % 2 else "b"},
                    node_name="n-1",
                )
            )
        c2 = FileClient(TestClock(), root=str(tmp_path / "idx"))
        self._assert_matches_oracle(c2)
        assert len(c2.list(Pod, field_selector={"spec.nodeName": "n-1"})) == 6

    def test_selector_read_cost_is_match_proportional(self):
        """The point of the index: a narrow selector over a big store must
        not touch every object. Pinned structurally — the object map is
        swapped for a counting dict, and the selector read may perform
        only match-many key lookups and ZERO full iterations (a
        regression to scan-plus-filter trips either counter), not on
        wall-clock."""
        from karpenter_tpu.api.objects import Pod
        from karpenter_tpu.kube import Client, TestClock

        class CountingDict(dict):
            def __init__(self, *a):
                super().__init__(*a)
                self.gets = 0
                self.scans = 0

            def __getitem__(self, k):
                self.gets += 1
                return super().__getitem__(k)

            def items(self):
                self.scans += 1
                return super().items()

            def values(self):
                self.scans += 1
                return super().values()

        c = Client(TestClock())
        for i in range(500):
            c.create(
                make_pod(
                    name=f"p{i}",
                    labels={"app": "hot" if i % 100 == 0 else "cold"},
                )
            )
        c._objects = CountingDict(c._objects)
        got = c.list(Pod, label_selector={"app": "hot"})
        assert len(got) == 5
        assert c._objects.gets == 5, "selector read touched non-matches"
        assert c._objects.scans == 0, "selector read fell back to a scan"
        term = ("l", "Pod", "app", "hot")
        assert len(c._label_idx[term]) == 5

    def test_injected_update_conflict_keeps_index_consistent(self):
        """The chaos seam fires BEFORE the index maintenance runs: a
        caller that mutated the stored reference in place (the binder's
        bind-then-update shape) and then hits an injected conflict must
        not leave the inverted index describing the pre-mutation object
        while a full scan sees the mutation — update()/delete() re-derive
        the stored object's terms before re-raising."""
        from karpenter_tpu import faults
        from karpenter_tpu.api.objects import Pod
        from karpenter_tpu.kube import Client, TestClock

        client = Client(TestClock())
        pod = make_pod(name="bindme", labels={"app": "a"})
        client.create(pod)
        inj = faults.install(
            faults.FaultInjector(
                [
                    faults.FaultRule(
                        "store.update",
                        error=lambda: __import__(
                            "karpenter_tpu.kube.store", fromlist=["x"]
                        ).ConflictError("injected"),
                        times=1,
                    ),
                    faults.FaultRule("store.delete", times=1),
                ]
            )
        )
        try:
            stored = client.get(Pod, "bindme")
            stored.spec.node_name = "n-9"  # in-place, pre-update (binder shape)
            with pytest.raises(Exception):
                client.update(stored)
            # index == full scan, even though the update failed
            self._assert_matches_oracle(client)
            got = client.list(
                Pod, field_selector={"spec.nodeName": "n-9"}
            )
            assert [o.metadata.name for o in got] == ["bindme"]
            # same healing on the delete seam
            stored.metadata.labels["app"] = "b"
            with pytest.raises(Exception):
                client.delete(stored)
            self._assert_matches_oracle(client)
            assert [
                o.metadata.name
                for o in client.list(Pod, label_selector={"app": "b"})
            ] == ["bindme"]
        finally:
            faults.uninstall()
