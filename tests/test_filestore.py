"""File-backed store semantics: persistence, restart resume, copy
isolation (kube/filestore.py — the second backend behind the Client seam,
analog of the reference's envtest-against-a-real-apiserver tier)."""

import pytest

from karpenter_tpu.api.objects import NodeClaim, ObjectMeta
from karpenter_tpu.kube import FileClient, NotFoundError, TestClock

from helpers import make_nodepool, make_pod


def _client(tmp_path, clock=None):
    return FileClient(clock or TestClock(), root=str(tmp_path / "store"))


class TestPersistence:
    def test_restart_resumes_state(self, tmp_path):
        clock = TestClock()
        c1 = _client(tmp_path, clock)
        pool = make_nodepool()
        c1.create(pool)
        c1.create(make_pod(name="p-1"))
        pool2 = c1.get("NodePool", pool.metadata.name)
        pool2.spec.weight = 42
        c1.update(pool2)

        # a NEW client over the same directory sees everything, including
        # the update, with resource versions preserved
        c2 = _client(tmp_path, clock)
        got = c2.get("NodePool", pool.metadata.name)
        assert got.spec.weight == 42
        assert got.metadata.resource_version == pool2.metadata.resource_version
        assert len(c2.list("Pod")) == 1

    def test_delete_removes_from_disk(self, tmp_path):
        c1 = _client(tmp_path)
        pod = make_pod(name="gone")
        c1.create(pod)
        c1.delete(pod)
        c2 = _client(tmp_path)
        assert c2.try_get("Pod", "gone") is None

    def test_finalizer_two_phase_survives_restart(self, tmp_path):
        clock = TestClock()
        c1 = _client(tmp_path, clock)
        claim = NodeClaim(metadata=ObjectMeta(name="nc-1"))
        claim.metadata.finalizers.append("karpenter/termination")
        c1.create(claim)
        c1.delete(claim)  # phase 1: marks deletion, keeps the object

        c2 = _client(tmp_path, clock)
        stored = c2.get("NodeClaim", "nc-1")
        assert stored.metadata.deletion_timestamp is not None
        c2.remove_finalizer(stored, "karpenter/termination")
        with pytest.raises(NotFoundError):
            c2.get("NodeClaim", "nc-1")
        # phase 2 completed on disk too
        c3 = _client(tmp_path, clock)
        assert c3.try_get("NodeClaim", "nc-1") is None


class TestCopySemantics:
    def test_reads_are_isolated_copies(self, tmp_path):
        c = _client(tmp_path)
        pool = make_nodepool()
        c.create(pool)
        a = c.get("NodePool", pool.metadata.name)
        a.spec.weight = 99  # mutating a read must NOT leak into the store
        b = c.get("NodePool", pool.metadata.name)
        assert b.spec.weight != 99

    def test_caller_handle_gets_server_metadata(self, tmp_path):
        c = _client(tmp_path)
        pod = make_pod(name="stamped")
        c.create(pod)
        assert pod.metadata.resource_version > 0
        assert pod.metadata.creation_timestamp is not None

    def test_watch_events_carry_copies(self, tmp_path):
        c = _client(tmp_path)
        seen = []
        c.watch(seen.append)
        pod = make_pod(name="w-1")
        c.create(pod)
        assert seen and seen[-1].object is not pod
        seen[-1].object.metadata.name = "corrupted"
        assert c.try_get("Pod", "w-1") is not None
