"""Scenario-batched consolidation: batched == sequential equivalence.

The scenario axis (ops/solve.py:solve_all_scenarios_packed, driver
solve_scenarios, helpers.ScenarioSimulator) must produce EXACTLY the
Command the sequential per-probe loop produces — decision, disrupted set,
replacement instance-type options — across seeded clusters, including the
filterOutSameType and timeout paths. The sequential loop stays the
semantic reference (it is the reference's multinodeconsolidation.go
shape); these suites pin the batched path to it.
"""

from __future__ import annotations

import random

import pytest

from karpenter_tpu.api import labels as labels_mod
from karpenter_tpu.api import resources as res
from karpenter_tpu.api.objects import (
    COND_CONSOLIDATABLE,
    COND_INITIALIZED,
    COND_LAUNCHED,
    COND_REGISTERED,
    Node,
    NodeClaim,
    NodeClaimSpec,
    NodePool,
    NodePoolSpec,
    ObjectMeta,
    Pod,
    PodSpec,
)
from karpenter_tpu.api.objects import NodeClaimTemplate as NodeClaimTemplateSpec
from karpenter_tpu.cloudprovider import corpus
from karpenter_tpu.cloudprovider.kwok import KwokCloudProvider
from karpenter_tpu.controllers.disruption.controller import DisruptionContext
from karpenter_tpu.controllers.disruption.helpers import (
    ScenarioSimulator,
    build_budget_mapping,
    get_candidates,
    simulate_scheduling,
)
from karpenter_tpu.controllers.disruption import methods as methods_mod
from karpenter_tpu.controllers.disruption.methods import (
    MultiNodeConsolidation,
    SingleNodeConsolidation,
    _bsearch_tree_mids,
)
from karpenter_tpu.controllers.state import Cluster
from karpenter_tpu.events.recorder import Recorder
from karpenter_tpu.kube import Client, TestClock

_MI = 2**20 * res.MILLI


def _pod(name, cpu_m, mem_mi, node_name="", phase="Pending"):
    p = Pod(
        metadata=ObjectMeta(name=name),
        spec=PodSpec(requests={res.CPU: cpu_m, res.MEMORY: mem_mi * _MI}),
    )
    if node_name:
        p.spec.node_name = node_name
        p.status.phase = phase
    return p


def build_env(
    n_nodes: int,
    seed: int = 0,
    n_types: int = 40,
    pending_pods: int = 0,
    pods_per_node=(1, 2),
    pod_cpus=(250, 500, 750, 1200),
    pod_mems=(256, 512, 1024),
):
    """A seeded consolidatable cluster: ``n_nodes`` nodes of a mid-priced
    type, each loaded with a random set of small pods, plus optional
    pending pods — underutilized enough that delete/replace decisions vary
    with the seed."""
    rng = random.Random(seed)
    clock = TestClock()
    clock.step(3600.0)
    client = Client(clock)
    its = corpus.generate(n_types)
    provider = KwokCloudProvider(client, its)
    cluster = Cluster(client)

    pool = NodePool(
        metadata=ObjectMeta(name="default"),
        spec=NodePoolSpec(template=NodeClaimTemplateSpec(spec=NodeClaimSpec())),
    )
    pool.spec.disruption.consolidate_after = 10.0
    client.create(pool)

    sized = sorted(
        (
            it
            for it in its
            if it.capacity.get(res.CPU, 0) >= 4000
            and it.capacity.get(res.MEMORY, 0) >= 8 * 1024 * _MI
        ),
        key=lambda it: min(
            (o.price for o in it.offerings if o.available), default=1e9
        ),
    )
    it = sized[len(sized) // 2]
    offering = min(
        (o for o in it.offerings if o.available), key=lambda o: o.price
    )

    for i in range(n_nodes):
        name = f"n-{i}"
        pid = f"test://{i}"
        node_labels = {
            labels_mod.HOSTNAME: name,
            labels_mod.INSTANCE_TYPE: it.name,
            labels_mod.TOPOLOGY_ZONE: offering.zone(),
            labels_mod.CAPACITY_TYPE_LABEL_KEY: offering.capacity_type(),
            labels_mod.NODEPOOL_LABEL_KEY: pool.name,
        }
        claim = NodeClaim(
            metadata=ObjectMeta(name=name, labels=dict(node_labels)),
            spec=NodeClaimSpec(),
        )
        claim.status.provider_id = pid
        claim.status.capacity = dict(it.capacity)
        claim.status.allocatable = dict(it.allocatable())
        now = clock.now()
        for cond in (COND_LAUNCHED, COND_REGISTERED, COND_INITIALIZED,
                     COND_CONSOLIDATABLE):
            claim.conds().set(cond, "True", now=now)
        node = Node(
            metadata=ObjectMeta(name=name, labels=node_labels),
            provider_id=pid,
        )
        node.status.capacity = dict(it.capacity)
        node.status.allocatable = dict(it.allocatable())
        node.status.ready = True
        client.create(claim)
        client.create(node)
        for j in range(rng.choice(pods_per_node)):
            client.create(
                _pod(
                    f"fill-{i}-{j}",
                    rng.choice(pod_cpus),
                    rng.choice(pod_mems),
                    node_name=name,
                    phase="Running",
                )
            )
    for j in range(pending_pods):
        client.create(
            _pod(f"pend-{j}", rng.choice(pod_cpus), rng.choice(pod_mems))
        )

    ctx = DisruptionContext(
        client=client,
        cluster=cluster,
        cloud_provider=provider,
        clock=clock,
        recorder=Recorder(clock),
        spot_to_spot_enabled=True,
    )
    return ctx


def _candidates_and_budgets(ctx, method):
    candidates = [
        c
        for c in get_candidates(
            ctx.client, ctx.cluster, ctx.cloud_provider, ctx.clock
        )
        if method.should_disrupt(c)
    ]
    budgets = build_budget_mapping(
        ctx.client, ctx.cluster, method.reason, ctx.clock.now()
    )
    return candidates, budgets


def _command_signature(cmd):
    return (
        cmd.decision,
        sorted(c.name for c in cmd.candidates),
        [
            [it.name for it in rep.instance_type_options]
            for rep in cmd.replacements
        ],
    )


def _run_multi(env_args, batched: bool):
    ctx = build_env(**env_args)
    ctx.scenario_batch = batched
    method = MultiNodeConsolidation(ctx)
    candidates, budgets = _candidates_and_budgets(ctx, method)
    cmd = method.compute_command(candidates, budgets)
    return cmd, method


class TestMidpointTree:
    def test_levels_cover_search_prefix(self):
        # every actual binary-search path's first probes are tree nodes
        for n in (2, 3, 7, 13, 50, 100):
            mids = _bsearch_tree_mids(n, budget=15)
            assert mids[0] == (1 + n) // 2
            assert len(set(mids)) == len(mids)
            assert all(1 <= m <= n for m in mids)

    def test_small_n_fully_enumerated(self):
        assert sorted(_bsearch_tree_mids(7, budget=15)) == [1, 2, 3, 4, 5, 6, 7]


class TestMultiNodeEquivalence:
    @pytest.mark.parametrize("seed", range(6))
    def test_seeded_clusters(self, seed):
        env_args = dict(
            n_nodes=6 + (seed * 5) % 19,
            seed=seed,
            pending_pods=(seed % 3),
        )
        cmd_b, method_b = _run_multi(env_args, batched=True)
        cmd_s, method_s = _run_multi(env_args, batched=False)
        assert _command_signature(cmd_b) == _command_signature(cmd_s)
        if method_b.last_probes:
            # the whole probe set rode the batch, in at most 2 dispatches
            assert method_b.last_dispatches <= 2

    def test_filter_out_same_type_path(self):
        # every candidate is the same instance type; a replacement's options
        # must exclude it (filterOutSameType), in both paths identically
        env_args = dict(n_nodes=12, seed=3)
        cmd_b, _ = _run_multi(env_args, batched=True)
        cmd_s, _ = _run_multi(env_args, batched=False)
        assert _command_signature(cmd_b) == _command_signature(cmd_s)
        ctx = build_env(**env_args)
        deleted_types = {
            c.instance_type.name
            for c in _candidates_and_budgets(ctx, MultiNodeConsolidation(ctx))[0]
        }
        for cmd in (cmd_b, cmd_s):
            for rep in cmd.replacements:
                assert not deleted_types & {
                    it.name for it in rep.instance_type_options
                }

    def test_immediate_timeout(self, monkeypatch):
        monkeypatch.setattr(
            methods_mod, "MULTI_NODE_CONSOLIDATION_TIMEOUT", -1.0
        )
        cmd_b, _ = _run_multi(dict(n_nodes=10, seed=1), batched=True)
        cmd_s, _ = _run_multi(dict(n_nodes=10, seed=1), batched=False)
        assert cmd_b.decision == "no-op"
        assert _command_signature(cmd_b) == _command_signature(cmd_s)

    @pytest.mark.parametrize("probes_before_timeout", [1, 2, 3])
    def test_mid_search_timeout(self, monkeypatch, probes_before_timeout):
        """The replay consults the injected clock once per probe, exactly
        like the sequential loop — an auto-advancing clock times out after
        the same number of probes either way."""
        monkeypatch.setattr(
            methods_mod,
            "MULTI_NODE_CONSOLIDATION_TIMEOUT",
            probes_before_timeout * 10.0 + 5.0,
        )

        class AdvancingClock(TestClock):
            def now(self):
                t = super().now()
                self.step(10.0)
                return t

        def run(batched):
            ctx = build_env(n_nodes=14, seed=2)
            adv = AdvancingClock()
            adv.step(ctx.clock.now())
            ctx.clock = adv
            ctx.scenario_batch = batched
            method = MultiNodeConsolidation(ctx)
            candidates, budgets = _candidates_and_budgets(ctx, method)
            return method.compute_command(candidates, budgets)

        assert _command_signature(run(True)) == _command_signature(run(False))


class TestSingleNodeEquivalence:
    @pytest.mark.parametrize("seed", range(4))
    def test_seeded_clusters(self, seed):
        def run(batched):
            ctx = build_env(
                n_nodes=5 + (seed * 7) % 14, seed=seed,
                pods_per_node=(1,), pod_cpus=(250, 400),
            )
            ctx.scenario_batch = batched
            method = SingleNodeConsolidation(ctx)
            candidates, budgets = _candidates_and_budgets(ctx, method)
            return method.compute_command(candidates, budgets)

        assert _command_signature(run(True)) == _command_signature(run(False))

    def test_chunked_sweep_no_success(self):
        # fully-loaded nodes: no candidate consolidates; the batched sweep
        # must walk every chunk and reach the same no-op + bookkeeping
        def run(batched):
            ctx = build_env(
                n_nodes=8, seed=5, pods_per_node=(3,),
                pod_cpus=(1200,), pod_mems=(2048,),
            )
            ctx.scenario_batch = batched
            method = SingleNodeConsolidation(ctx)
            candidates, budgets = _candidates_and_budgets(ctx, method)
            cmd = method.compute_command(candidates, budgets)
            return cmd, method.suppress_memoization

        (cmd_b, sup_b) = run(True)
        (cmd_s, sup_s) = run(False)
        assert _command_signature(cmd_b) == _command_signature(cmd_s)
        assert sup_b == sup_s


class TestScenarioSimulatorFallback:
    def test_volume_pods_fall_back(self):
        ctx = build_env(n_nodes=6, seed=0)
        method = MultiNodeConsolidation(ctx)
        candidates, _ = _candidates_and_budgets(ctx, method)
        assert candidates
        # inject a pending pod with a volume: the shared encoding cannot
        # carry per-scenario deep copies, so the simulator must decline
        from karpenter_tpu.api.objects import PersistentVolumeClaimRef

        vol_pod = _pod("vol-pod", 100, 128)
        vol_pod.spec.volumes = [PersistentVolumeClaimRef(claim_name="pvc-1")]
        ctx.client.create(vol_pod)
        sim = ScenarioSimulator(
            ctx.client, ctx.cluster, ctx.cloud_provider, candidates,
            encode_cache=ctx.encode_cache,
        )
        assert not sim.available
        assert sim.solve([[candidates[0]]]) is None

    def test_fallback_still_decides(self):
        # with the batched path declined, compute_command must still return
        # the sequential decision
        ctx = build_env(n_nodes=10, seed=1)
        from karpenter_tpu.api.objects import PersistentVolumeClaimRef

        vol_pod = _pod("vol-pod", 100, 128)
        vol_pod.spec.volumes = [PersistentVolumeClaimRef(claim_name="pvc-1")]
        ctx.client.create(vol_pod)
        ctx.scenario_batch = True
        method = MultiNodeConsolidation(ctx)
        candidates, budgets = _candidates_and_budgets(ctx, method)
        cmd_b = method.compute_command(candidates, budgets)

        ctx2 = build_env(n_nodes=10, seed=1)
        vol_pod2 = _pod("vol-pod", 100, 128)
        vol_pod2.spec.volumes = [PersistentVolumeClaimRef(claim_name="pvc-1")]
        ctx2.client.create(vol_pod2)
        ctx2.scenario_batch = False
        method2 = MultiNodeConsolidation(ctx2)
        candidates2, budgets2 = _candidates_and_budgets(ctx2, method2)
        cmd_s = method2.compute_command(candidates2, budgets2)
        assert _command_signature(cmd_b) == _command_signature(cmd_s)


class TestSimulatorResultsEquivalence:
    def test_results_match_sequential_simulate(self):
        """Per-subset Results from one batched dispatch must match the
        sequential simulate_scheduling claim-for-claim."""
        ctx = build_env(n_nodes=14, seed=4, pending_pods=2)
        method = MultiNodeConsolidation(ctx)
        candidates, _ = _candidates_and_budgets(ctx, method)
        assert len(candidates) >= 4
        snapshot = ctx.cluster.nodes()
        subsets = [candidates[:1], candidates[:2], candidates[:4]]
        sim = ScenarioSimulator(
            ctx.client, ctx.cluster, ctx.cloud_provider, candidates,
            encode_cache=ctx.encode_cache, state_snapshot=snapshot,
        )
        batched = sim.solve(subsets)
        assert batched is not None
        for subset, br in zip(subsets, batched):
            sr = simulate_scheduling(
                ctx.client, ctx.cluster, ctx.cloud_provider, subset,
                encode_cache=ctx.encode_cache, state_snapshot=snapshot,
            )
            assert set(br.pod_errors) == set(sr.pod_errors)
            a = sorted(
                (
                    len(c.pods),
                    tuple(it.name for it in c.instance_type_options),
                )
                for c in br.new_node_claims
            )
            b = sorted(
                (
                    len(c.pods),
                    tuple(it.name for it in c.instance_type_options),
                )
                for c in sr.new_node_claims
            )
            assert a == b
            # existing-node fills must match too (which nodes took pods)
            fa = {
                en.name: len(en.pods)
                for en in br.existing_nodes
                if en.pods
            }
            fb = {
                en.name: len(en.pods)
                for en in sr.existing_nodes
                if en.pods
            }
            assert fa == fb

    def test_scenarios_isolated(self):
        """One scenario's fills must not leak into another's Results (the
        per-scenario node clones)."""
        ctx = build_env(n_nodes=8, seed=6, pods_per_node=(2,))
        method = MultiNodeConsolidation(ctx)
        candidates, _ = _candidates_and_budgets(ctx, method)
        assert len(candidates) >= 2
        sim = ScenarioSimulator(
            ctx.client, ctx.cluster, ctx.cloud_provider, candidates,
            encode_cache=ctx.encode_cache,
        )
        out = sim.solve([[candidates[0]], [candidates[0]]])
        assert out is not None
        r1, r2 = out
        f1 = {en.name: len(en.pods) for en in r1.existing_nodes if en.pods}
        f2 = {en.name: len(en.pods) for en in r2.existing_nodes if en.pods}
        assert f1 == f2  # identical scenarios, identical (isolated) fills


class TestNodeModelCacheIsolation:
    def test_fills_do_not_pollute_cached_node_models(self):
        """Decode's existing-node fill commit mutates the ExistingNode's
        requirements container; the cross-solve node-model cache must hand
        every solve a FRESH container over the shared entries, or one
        probe's fills (e.g. a DoesNotExist pod requirement) leak into the
        next probe's node model and wrongly reject future pods."""
        from karpenter_tpu.api.objects import (
            NodeAffinity,
            NodeSelectorRequirement,
        )

        ctx = build_env(n_nodes=4, seed=0, pods_per_node=(1,))
        method = MultiNodeConsolidation(ctx)
        candidates, _ = _candidates_and_budgets(ctx, method)
        assert candidates
        snapshot = ctx.cluster.nodes()
        p = _pod("dne-pod", 100, 128)
        p.spec.node_affinity = NodeAffinity(
            required=[
                (NodeSelectorRequirement("example.com/team", "DoesNotExist", ()),)
            ]
        )
        ctx.client.create(p)

        def run():
            return simulate_scheduling(
                ctx.client, ctx.cluster, ctx.cloud_provider, candidates[:1],
                encode_cache=ctx.encode_cache, state_snapshot=snapshot,
            )

        r1 = run()
        host = [
            en
            for en in r1.existing_nodes
            if any(pp.metadata.name == "dne-pod" for pp in en.pods)
        ]
        assert host, "the pending pod must land on an existing node"
        assert host[0].requirements.has("example.com/team")
        # the pod is gone from the cluster; the next solve's node model is
        # built from the cache hit and must not carry the previous solve's
        # fill-merged requirement
        ctx.client.delete(p)
        r2 = run()
        fresh = [en for en in r2.existing_nodes if en.name == host[0].name]
        assert fresh
        assert not fresh[0].requirements.has("example.com/team")


class TestSolveArgNames:
    def test_names_track_solve_args(self):
        """SOLVE_ARG_NAMES must mirror EncodedSnapshot.solve_args exactly —
        the scenario axis selects batched positions by name through it."""
        import numpy as np

        from karpenter_tpu.solver import encode as enc

        ctx = build_env(n_nodes=3, seed=0)
        method = MultiNodeConsolidation(ctx)
        candidates, _ = _candidates_and_budgets(ctx, method)
        pods = [p for c in candidates for p in c.reschedulable_pods]
        groups, rest = enc.partition_and_group(pods)
        assert groups and not rest
        its = ctx.cloud_provider.get_instance_types(None)
        from karpenter_tpu.api.objects import NodePool
        from karpenter_tpu.scheduling.topology import Topology
        from karpenter_tpu.solver.driver import TpuSolver

        pools = ctx.client.list(NodePool)
        its_by_pool = {p.name: its for p in pools}
        topo = Topology(ctx.client, [], pools, its_by_pool, pods)
        solver = TpuSolver(pools, its_by_pool, topo)
        snap, avail, _, _, _delta = solver._encode_batch(groups)
        args = snap.solve_args(*avail)
        assert len(args) == len(enc.SOLVE_ARG_NAMES)
        assert args[enc.SOLVE_ARG_NAMES.index("g_count")] is snap.g_count
        assert args[enc.SOLVE_ARG_NAMES.index("n_tol")] is snap.n_tol
        assert args[enc.SOLVE_ARG_NAMES.index("well_known")] is snap.well_known


class TestScenarioEnvCache:
    """ISSUE 12 satellite: the built simulation environment (Topology +
    solver + warm encode) is content-keyed and reused across consolidation
    searches over an unchanged cluster — the scenario.build warm path."""

    def _sim(self, ctx, candidates, snapshot):
        from karpenter_tpu.controllers.disruption.helpers import (
            ScenarioSimulator,
        )

        return ScenarioSimulator(
            ctx.client, ctx.cluster, ctx.cloud_provider, candidates,
            encode_cache=ctx.encode_cache, state_snapshot=snapshot,
            solver_config=ctx.solver_config,
            env_cache=ctx.scenario_envs,
        )

    def test_second_search_reuses_environment(self):
        ctx = build_env(n_nodes=10, seed=3)
        method = MultiNodeConsolidation(ctx)
        candidates, _ = _candidates_and_budgets(ctx, method)
        snapshot = ctx.cluster.nodes()
        a = self._sim(ctx, candidates, snapshot)
        assert not a.env_reused
        b = self._sim(ctx, candidates, snapshot)
        assert b.env_reused
        assert b._solver is a._solver
        # decisions from the reused environment match a fresh build
        subsets = [candidates[:1], candidates[:2]]
        res_a = a.solve(subsets)
        res_b = b.solve(subsets)
        assert res_a is not None and res_b is not None
        for ra, rb in zip(res_a, res_b):
            assert len(ra.new_node_claims) == len(rb.new_node_claims)
            assert sorted(
                it.name
                for c in ra.new_node_claims
                for it in c.instance_type_options[:1]
            ) == sorted(
                it.name
                for c in rb.new_node_claims
                for it in c.instance_type_options[:1]
            )

    def test_cluster_mutation_busts_the_cache(self):
        from karpenter_tpu.api.objects import Pod

        ctx = build_env(n_nodes=8, seed=5)
        method = MultiNodeConsolidation(ctx)
        candidates, _ = _candidates_and_budgets(ctx, method)
        snapshot = ctx.cluster.nodes()
        a = self._sim(ctx, candidates, snapshot)
        assert not a.env_reused
        # any store change that bumps a workload pod's resource version
        # must miss: the environment baked the old content
        pod = next(p for p in ctx.client.list(Pod) if p.spec.node_name)
        ctx.client.update(pod)
        snapshot2 = ctx.cluster.nodes()
        candidates2, _ = _candidates_and_budgets(ctx, method)
        b = self._sim(ctx, candidates2, snapshot2)
        assert not b.env_reused

    def test_ice_masked_catalog_busts_the_cache(self):
        ctx = build_env(n_nodes=8, seed=6)
        method = MultiNodeConsolidation(ctx)
        candidates, _ = _candidates_and_budgets(ctx, method)
        snapshot = ctx.cluster.nodes()
        a = self._sim(ctx, candidates, snapshot)
        assert not a.env_reused
        # an ICE entry makes get_instance_types return fresh masked
        # copies: identity-keyed catalog signature must miss
        it = ctx.cloud_provider.get_instance_types(None)[0]
        o = next(o for o in it.offerings if o.available)
        ctx.cloud_provider.ice_cache.mark_unavailable(
            it.name, o.zone(), o.capacity_type()
        )
        b = self._sim(ctx, candidates, snapshot)
        assert not b.env_reused

    def test_full_search_decisions_unchanged_by_cache(self):
        """End-to-end: the same multi-node search with and without the
        env cache produces identical commands."""
        sigs = []
        for enabled in (True, False):
            ctx = build_env(n_nodes=12, seed=7)
            if not enabled:
                ctx.scenario_envs = None
            method = MultiNodeConsolidation(ctx)
            candidates, budgets = _candidates_and_budgets(ctx, method)
            cmd = method.compute_command(candidates, budgets)
            # a second search over the unchanged cluster (the twin-tick
            # shape the cache serves)
            cmd2 = method.compute_command(candidates, budgets)
            sigs.append(
                (_command_signature(cmd), _command_signature(cmd2))
            )
        assert sigs[0] == sigs[1]


class TestProbeBudget:
    """DisruptionContext.probe_budget: the deterministic per-pass probe
    cap (the injected-clock analog of the reference's wall-clock sweep
    timeouts)."""

    def test_single_node_sweep_stops_at_budget(self):
        ctx = build_env(n_nodes=16, seed=8)
        ctx.probe_budget = 4
        method = SingleNodeConsolidation(ctx)
        candidates, budgets = _candidates_and_budgets(ctx, method)
        assert len(candidates) > 4
        cmd = method.compute_command(candidates, budgets)
        assert method.last_probes <= 4 + 16  # budget + one chunk
        if cmd.decision == "no-op":
            # bailed like a timeout: no consolidated memo, unseen pools
            # resume next pass
            assert method.suppress_memoization

    def test_multi_node_search_stops_at_budget(self):
        ctx = build_env(n_nodes=14, seed=9)
        ctx.probe_budget = 3
        method = MultiNodeConsolidation(ctx)
        candidates, budgets = _candidates_and_budgets(ctx, method)
        method.compute_command(candidates, budgets)
        # the batched prime may exceed the cap by one dispatch's worth,
        # but the search loop itself stops consuming probes past it
        assert method.last_probes <= 15 + 3

    def test_unbudgeted_behavior_unchanged(self):
        cmd_a, m_a = _run_multi(dict(n_nodes=12, seed=10), batched=True)
        ctx = build_env(n_nodes=12, seed=10)
        ctx.probe_budget = None
        ctx.scenario_batch = True
        method = MultiNodeConsolidation(ctx)
        candidates, budgets = _candidates_and_budgets(ctx, method)
        cmd_b = method.compute_command(candidates, budgets)
        assert _command_signature(cmd_a) == _command_signature(cmd_b)
