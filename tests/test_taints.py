"""Taint / toleration matching tests (reference: pkg/scheduling/taints.go)."""

from karpenter_tpu.api import taints
from karpenter_tpu.api.objects import Pod, PodSpec, Taint, Toleration


def taint(key="k", value="v", effect=taints.NO_SCHEDULE):
    return Taint(key=key, value=value, effect=effect)


class TestToleratesTaint:
    def test_exact_equal(self):
        t = Toleration(key="k", operator="Equal", value="v", effect=taints.NO_SCHEDULE)
        assert taints.tolerates_taint(t, taint())

    def test_value_mismatch(self):
        t = Toleration(key="k", operator="Equal", value="other", effect=taints.NO_SCHEDULE)
        assert not taints.tolerates_taint(t, taint())

    def test_exists_ignores_value(self):
        t = Toleration(key="k", operator="Exists", effect=taints.NO_SCHEDULE)
        assert taints.tolerates_taint(t, taint())

    def test_empty_effect_matches_all(self):
        t = Toleration(key="k", operator="Exists")
        assert taints.tolerates_taint(t, taint(effect=taints.NO_EXECUTE))

    def test_empty_key_exists_matches_everything(self):
        t = Toleration(operator="Exists")
        assert taints.tolerates_taint(t, taint(key="anything"))

    def test_effect_mismatch(self):
        t = Toleration(key="k", operator="Exists", effect=taints.NO_SCHEDULE)
        assert not taints.tolerates_taint(t, taint(effect=taints.NO_EXECUTE))


class TestTolerates:
    def test_all_taints_must_be_tolerated(self):
        ts = [taint(key="a"), taint(key="b")]
        tols = [Toleration(key="a", operator="Exists", effect=taints.NO_SCHEDULE)]
        err = taints.tolerates(ts, tols)
        assert err is not None and "b" in err

    def test_pod_path(self):
        pod = Pod(spec=PodSpec(tolerations=[Toleration(operator="Exists")]))
        assert taints.tolerates_pod([taint()], pod) is None

    def test_empty_taints_ok(self):
        assert taints.tolerates([], []) is None


class TestMerge:
    def test_first_wins_per_key_effect(self):
        a = [taint(key="k", value="v1")]
        b = [taint(key="k", value="v2"), taint(key="other")]
        merged = taints.merge(a, b)
        assert len(merged) == 2
        assert merged[0].value == "v1"


class TestEphemeral:
    def test_known_ephemeral(self):
        assert taints.is_ephemeral(
            Taint(key=taints.TAINT_NODE_NOT_READY, effect=taints.NO_SCHEDULE)
        )

    def test_unregistered_taint(self):
        from karpenter_tpu.api import labels

        assert taints.is_ephemeral(
            Taint(key=labels.UNREGISTERED_TAINT_KEY, effect=taints.NO_EXECUTE)
        )

    def test_ordinary_not_ephemeral(self):
        assert not taints.is_ephemeral(taint())


class TestKubernetesParity:
    def test_exists_with_value_still_tolerates(self):
        # upstream ToleratesTaint matches unconditionally on Exists; API
        # validation (not matching) forbids a value with Exists
        t = Toleration(key="k", operator="Exists", value="v", effect=taints.NO_SCHEDULE)
        assert taints.tolerates_taint(t, taint())

    def test_unknown_operator_never_tolerates(self):
        t = Toleration(key="k", operator="Equals", value="v", effect=taints.NO_SCHEDULE)
        assert not taints.tolerates_taint(t, taint())
