"""Parity: the C++ host solver core vs the JAX kernel.

The native core (native/solve_core.cc) implements the identical decision
problem as ops/solve.py::solve_core; these tests assert exact agreement on
the packing outputs over a range of snapshot shapes, then drive the full
TpuSolver with backend='native' and compare end-to-end Results.
"""

import os
import subprocess

import numpy as np
import pytest

from karpenter_tpu import native
from karpenter_tpu.solver.driver import SolverConfig
from karpenter_tpu.solver.example import example_snapshot_arrays, example_solver


requires_native = pytest.mark.skipif(
    not native.available(), reason="native toolchain unavailable"
)


@requires_native
class TestKernelParity:
    @pytest.mark.parametrize(
        "n_pods,n_types,shapes",
        [(16, 4, 1), (64, 16, 4), (200, 40, 8), (500, 10, 1), (1000, 60, 25)],
    )
    def test_exact_output_parity(self, n_pods, n_types, shapes):
        import jax

        from karpenter_tpu.ops.solve import solve_all

        args, statics = example_snapshot_arrays(n_pods, n_types, shapes)
        jout = [np.asarray(x) for x in jax.device_get(solve_all(*args, **statics))]
        nout = native.solve_core_native(*args, **statics)

        j_pool, j_tmask, j_open, j_over = jout[0], jout[1], int(jout[2]), bool(jout[3])
        n_pool, n_tmask, n_open, n_over = nout[0], nout[1], int(nout[2]), nout[3]
        assert n_over == j_over
        assert n_open == j_open
        np.testing.assert_array_equal(n_pool[:n_open], j_pool[:j_open])
        np.testing.assert_array_equal(
            n_tmask[:n_open], j_tmask[:j_open].astype(bool)
        )
        np.testing.assert_array_equal(nout[4], jout[4])  # exist_fills
        np.testing.assert_array_equal(nout[5], jout[5])  # claim_fills
        np.testing.assert_array_equal(nout[6], jout[6])  # unplaced


@requires_native
class TestDriverBackend:
    def test_native_backend_matches_tpu_backend(self):
        solver_t, pods = example_solver(300, 30, 6)
        results_t = solver_t.solve(pods)

        solver_n, pods_n = example_solver(300, 30, 6)
        solver_n.config = SolverConfig(backend="native")
        results_n = solver_n.solve(pods_n)

        assert results_n.node_count() == results_t.node_count()
        assert results_n.total_price() == pytest.approx(results_t.total_price())
        assert len(results_n.pod_errors) == len(results_t.pod_errors)

    def test_unknown_backend_rejected(self):
        solver, pods = example_solver(16, 4, 1)
        solver.config = SolverConfig(backend="cpu")
        with pytest.raises(ValueError, match="unknown solver backend"):
            solver.solve(pods)

    def test_native_backend_all_pods_placed(self):
        solver, pods = example_solver(500, 10, 1)
        solver.config = SolverConfig(backend="native")
        results = solver.solve(pods)
        assert not results.pod_errors
        assert sum(len(c.pods) for c in results.new_node_claims) == 500


def _topo_snapshot_args(pods):
    """Kernel args for a topology-carrying pod batch (zonal/hostname
    constraints active)."""
    import os
    import sys
    sys.path.insert(0, os.path.dirname(__file__))
    from helpers import snapshot_args

    args, statics = snapshot_args(pods, n_types=20)
    statics.pop("has_domains", None)  # native core branches at runtime
    return args, statics


@requires_native
class TestTopologyParity:
    """The C++ hostname-cap and domain-quota paths against the JAX kernel
    (round-2 gap: the native g_hcap path shipped untested)."""

    def _pods_zonal_mix(self):
        import os
        import sys
        sys.path.insert(0, os.path.dirname(__file__))
        from helpers import make_pods, spread_constraint, affinity_term
        from karpenter_tpu.api import labels

        return (
            make_pods(10, cpu="1", memory="2Gi")
            + make_pods(
                7, cpu="1", labels={"nm": "zs"},
                spread=[spread_constraint(labels.TOPOLOGY_ZONE, labels={"nm": "zs"})],
            )
            + make_pods(
                5, cpu="1", labels={"nm": "hs"},
                spread=[spread_constraint(labels.HOSTNAME, labels={"nm": "hs"})],
            )
            + make_pods(
                4, cpu="1", labels={"nm": "za"},
                pod_affinity=[affinity_term(labels.TOPOLOGY_ZONE, {"nm": "za"})],
            )
        )

    def test_exact_output_parity_topology(self):
        import jax

        from karpenter_tpu.ops.solve import solve_all

        args, statics = _topo_snapshot_args(self._pods_zonal_mix())
        # the hostname-cap AND domain-quota paths must both be active
        g_hcap, g_dmode = np.asarray(args[5]), np.asarray(args[7])
        assert (g_hcap < 2**30).any(), "hostname cap path not exercised"
        assert (g_dmode > 0).any(), "domain-quota path not exercised"

        jout = [np.asarray(x) for x in jax.device_get(solve_all(*args, **statics))]
        nout = native.solve_core_native(*args, **statics)
        j_open, n_open = int(jout[2]), int(nout[2])
        assert n_open == j_open
        assert nout[3] == bool(jout[3])
        np.testing.assert_array_equal(nout[0][:n_open], jout[0][:j_open])
        np.testing.assert_array_equal(
            nout[1][:n_open], jout[1][:j_open].astype(bool)
        )
        np.testing.assert_array_equal(nout[4], jout[4])  # exist_fills
        np.testing.assert_array_equal(nout[5], jout[5])  # claim_fills
        np.testing.assert_array_equal(nout[6], jout[6])  # unplaced
        np.testing.assert_array_equal(nout[7], jout[7])  # c_dzone pins
        np.testing.assert_array_equal(nout[8], jout[8])  # c_dct pins

    def test_native_backend_zonal_end_to_end(self):
        from karpenter_tpu.api import labels
        from karpenter_tpu.cloudprovider import corpus
        from karpenter_tpu.kube import Client, TestClock
        from karpenter_tpu.scheduling.topology import Topology
        from karpenter_tpu.solver import TpuSolver
        from karpenter_tpu.solver.driver import SolverConfig

        import os
        import sys
        sys.path.insert(0, os.path.dirname(__file__))
        from helpers import make_nodepool

        def run(backend):
            pods = self._pods_zonal_mix()
            node_pools = [make_nodepool()]
            its_by_pool = {"default": corpus.generate(20)}
            topo = Topology(Client(TestClock()), [], node_pools, its_by_pool, pods)
            solver = TpuSolver(
                node_pools, its_by_pool, topo, config=SolverConfig(backend=backend)
            )
            return solver.solve(pods)

        r_t, r_n = run("tpu"), run("native")
        assert r_n.all_pods_scheduled() and r_t.all_pods_scheduled()
        assert r_n.node_count() == r_t.node_count()
        assert abs(r_n.total_price() - r_t.total_price()) < 1e-6

        def zone_dist(results):
            out = {}
            for claim in results.new_node_claims:
                zr = claim.requirements.get(labels.TOPOLOGY_ZONE)
                if not zr.complement and len(zr.values) == 1:
                    z = next(iter(zr.values))
                    out[z] = out.get(z, 0) + len(claim.pods)
            return out

        assert zone_dist(r_n) == zone_dist(r_t)


class TestBuildLifecycle:
    """build()/available() behavior around a missing, stale, or unbuildable
    shared library — and the pure-Python (JAX) path staying serviceable
    when the native toolchain is gone. No real compiler is invoked: the
    g++ subprocess is replaced with a recorder."""

    class _Recorder:
        def __init__(self, returncode=0, stderr=""):
            self.calls = []
            self.returncode = returncode
            self.stderr = stderr

        def __call__(self, cmd, capture_output=True, text=True):
            self.calls.append(cmd)
            if self.returncode == 0:
                # the -o argument is the library path build() expects
                out = cmd[cmd.index("-o") + 1]
                with open(out, "wb") as fh:
                    fh.write(b"\x7fELF fake")
            return subprocess.CompletedProcess(
                cmd, self.returncode, stdout="", stderr=self.stderr
            )

    @pytest.fixture
    def sandbox(self, tmp_path, monkeypatch):
        """Redirect the module's source/library paths into tmp and reset
        the cached ctypes handle."""
        src = tmp_path / "solve_core.cc"
        src.write_text("// stand-in source\n")
        lib = tmp_path / "libkt_solver.so"
        monkeypatch.setattr(native, "_SRC", str(src))
        monkeypatch.setattr(native, "_LIB", str(lib))
        monkeypatch.setattr(native, "_lib", None)
        return src, lib

    def test_missing_library_triggers_build(self, sandbox, monkeypatch):
        src, lib = sandbox
        recorder = self._Recorder()
        monkeypatch.setattr(native.subprocess, "run", recorder)
        assert not lib.exists()
        path = native.build()
        assert path == str(lib) and lib.exists()
        assert len(recorder.calls) == 1
        assert recorder.calls[0][0] == "g++"

    def test_stale_library_rebuilt(self, sandbox, monkeypatch):
        src, lib = sandbox
        lib.write_bytes(b"old")
        stale = os.path.getmtime(str(src)) - 60
        os.utime(str(lib), (stale, stale))
        recorder = self._Recorder()
        monkeypatch.setattr(native.subprocess, "run", recorder)
        native.build()
        assert len(recorder.calls) == 1, "stale .so must be recompiled"

    def test_fresh_library_not_rebuilt(self, sandbox, monkeypatch):
        src, lib = sandbox
        lib.write_bytes(b"fresh")
        fresh = os.path.getmtime(str(src)) + 60
        os.utime(str(lib), (fresh, fresh))
        recorder = self._Recorder()
        monkeypatch.setattr(native.subprocess, "run", recorder)
        assert native.build() == str(lib)
        assert recorder.calls == [], "fresh .so must be reused"
        native.build(force=True)
        assert len(recorder.calls) == 1, "force=True bypasses the mtime check"

    def test_failed_build_raises_and_available_is_false(
        self, sandbox, monkeypatch
    ):
        recorder = self._Recorder(returncode=1, stderr="fatal: no compiler")
        monkeypatch.setattr(native.subprocess, "run", recorder)
        with pytest.raises(native.NativeBuildError, match="no compiler"):
            native.build()
        assert native.available() is False
        assert native._lib is None, "failed build must not cache a handle"

    def test_pure_python_path_survives_missing_toolchain(
        self, sandbox, monkeypatch
    ):
        """With the native core unbuildable, the default (JAX) backend still
        schedules: native is an accelerator for the host path, not a
        dependency of it."""
        recorder = self._Recorder(returncode=1, stderr="g++: not found")
        monkeypatch.setattr(native.subprocess, "run", recorder)
        assert native.available() is False
        solver, pods = example_solver(16, 4, 1)
        results = solver.solve(pods)
        assert results.all_pods_scheduled()
