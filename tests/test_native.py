"""Parity: the C++ host solver core vs the JAX kernel.

The native core (native/solve_core.cc) implements the identical decision
problem as ops/solve.py::solve_core; these tests assert exact agreement on
the packing outputs over a range of snapshot shapes, then drive the full
TpuSolver with backend='native' and compare end-to-end Results.
"""

import numpy as np
import pytest

from karpenter_tpu import native
from karpenter_tpu.solver.driver import SolverConfig
from karpenter_tpu.solver.example import example_snapshot_arrays, example_solver


requires_native = pytest.mark.skipif(
    not native.available(), reason="native toolchain unavailable"
)


@requires_native
class TestKernelParity:
    @pytest.mark.parametrize(
        "n_pods,n_types,shapes",
        [(16, 4, 1), (64, 16, 4), (200, 40, 8), (500, 10, 1), (1000, 60, 25)],
    )
    def test_exact_output_parity(self, n_pods, n_types, shapes):
        import jax

        from karpenter_tpu.ops.solve import solve_all

        args, statics = example_snapshot_arrays(n_pods, n_types, shapes)
        jout = [np.asarray(x) for x in jax.device_get(solve_all(*args, **statics))]
        nout = native.solve_core_native(*args, **statics)

        j_pool, j_tmask, j_open, j_over = jout[0], jout[1], int(jout[2]), bool(jout[3])
        n_pool, n_tmask, n_open, n_over = nout[0], nout[1], int(nout[2]), nout[3]
        assert n_over == j_over
        assert n_open == j_open
        np.testing.assert_array_equal(n_pool[:n_open], j_pool[:j_open])
        np.testing.assert_array_equal(
            n_tmask[:n_open], j_tmask[:j_open].astype(bool)
        )
        np.testing.assert_array_equal(nout[4], jout[4])  # exist_fills
        np.testing.assert_array_equal(nout[5], jout[5])  # claim_fills
        np.testing.assert_array_equal(nout[6], jout[6])  # unplaced


@requires_native
class TestDriverBackend:
    def test_native_backend_matches_tpu_backend(self):
        solver_t, pods = example_solver(300, 30, 6)
        results_t = solver_t.solve(pods)

        solver_n, pods_n = example_solver(300, 30, 6)
        solver_n.config = SolverConfig(backend="native")
        results_n = solver_n.solve(pods_n)

        assert results_n.node_count() == results_t.node_count()
        assert results_n.total_price() == pytest.approx(results_t.total_price())
        assert len(results_n.pod_errors) == len(results_t.pod_errors)

    def test_unknown_backend_rejected(self):
        solver, pods = example_solver(16, 4, 1)
        solver.config = SolverConfig(backend="cpu")
        with pytest.raises(ValueError, match="unknown solver backend"):
            solver.solve(pods)

    def test_native_backend_all_pods_placed(self):
        solver, pods = example_solver(500, 10, 1)
        solver.config = SolverConfig(backend="native")
        results = solver.solve(pods)
        assert not results.pod_errors
        assert sum(len(c.pods) for c in results.new_node_claims) == 500
