"""Instance-type selection behavior matrix.

Mirrors the reference's scheduling/instance_selection_test.go: cheapest-
instance selection under every combination of pod- and pool-level
constraints (arch, os, zone, capacity type), no-match failures, resource
fit, and the minValues discipline (In/NotIn/Gt/Lt, max-of-minValues,
truncation interplay). Scenarios run through BOTH the host oracle and the
TPU solver — the cheapest-launch discipline must hold on each path.
"""

import pytest

from karpenter_tpu.api import labels, resources as res
from karpenter_tpu.api.objects import NodeSelectorRequirement
from karpenter_tpu.cloudprovider import corpus
from karpenter_tpu.cloudprovider import types as cp
from karpenter_tpu.kube import Client, TestClock
from karpenter_tpu.scheduling.topology import Topology
from karpenter_tpu.solver import TpuSolver
from karpenter_tpu.solver.driver import SolverConfig

from helpers import make_nodepool, make_pod, make_pods

AMD = labels.ARCHITECTURE_AMD64
ARM = labels.ARCHITECTURE_ARM64


def diverse_catalog():
    """Deterministic grid: {c,m,r} x {2,4,8,16} cpus x {amd64,arm64} x
    {linux,windows}, spot+od in three zones — every selection axis the
    reference's matrix exercises, with a known price model."""
    its = []
    for family in ("c", "m", "r"):
        for cpu in (2, 4, 8, 16):
            for arch in (AMD, ARM):
                for os in ("linux", "windows"):
                    its.append(
                        corpus.make_instance_type(
                            family, cpu, arch=arch, os=os
                        )
                    )
    return its


def run(pods, pools=None, its=None, force_oracle=False):
    pools = pools or [make_nodepool()]
    its = diverse_catalog() if its is None else its
    its_by_pool = {p.name: list(its) for p in pools}
    topo = Topology(Client(TestClock()), [], pools, its_by_pool, pods)
    solver = TpuSolver(
        pools, its_by_pool, topo,
        config=SolverConfig(force_oracle=force_oracle),
    )
    return solver.solve(pods), its


def launch_price(claim) -> float:
    return min(
        cp.min_compatible_price(it, claim.requirements)
        for it in claim.instance_type_options
    )


def cheapest_feasible(its, claim) -> float:
    """Cheapest price over catalog types launchable for the claim AND able
    to host its pod set — the floor every claim's launch price must reach
    (the reference asserts the node lands on one of the cheapest
    instances)."""
    total = (
        res.merge(*(p.spec.requests for p in claim.pods))
        if claim.pods
        else {}
    )
    best = float("inf")
    for it in its:
        if not claim.requirements.is_compatible(
            it.requirements, labels.WELL_KNOWN_LABELS
        ):
            continue
        if not res.fits(total, it.allocatable()):
            continue
        p = cp.min_compatible_price(it, claim.requirements)
        best = min(best, p)
    return best


def assert_cheapest(results, its):
    assert not results.pod_errors, results.pod_errors
    assert results.new_node_claims
    for claim in results.new_node_claims:
        lp = launch_price(claim)
        floor = cheapest_feasible(its, claim)
        assert lp <= floor + 1e-9, (lp, floor)


BOTH = pytest.mark.parametrize("force_oracle", [False, True])


class TestCheapestSelection:
    @BOTH
    def test_unconstrained(self, force_oracle):
        results, its = run(make_pods(3, cpu="1"), force_oracle=force_oracle)
        assert_cheapest(results, its)

    @BOTH
    @pytest.mark.parametrize("arch", [AMD, ARM])
    def test_pod_arch(self, force_oracle, arch):
        pods = make_pods(
            3,
            requirements=[NodeSelectorRequirement(labels.ARCH, "In", (arch,))],
        )
        results, its = run(pods, force_oracle=force_oracle)
        assert_cheapest(results, its)
        for claim in results.new_node_claims:
            assert claim.requirements.get(labels.ARCH).has(arch)
            for it in claim.instance_type_options:
                assert it.requirements.get(labels.ARCH).has(arch)

    @BOTH
    @pytest.mark.parametrize("arch", [AMD, ARM])
    def test_pool_arch(self, force_oracle, arch):
        pool = make_nodepool(
            requirements=[NodeSelectorRequirement(labels.ARCH, "In", (arch,))]
        )
        results, its = run(
            make_pods(3), pools=[pool], force_oracle=force_oracle
        )
        assert_cheapest(results, its)
        for claim in results.new_node_claims:
            for it in claim.instance_type_options:
                assert it.requirements.get(labels.ARCH).has(arch)

    @BOTH
    @pytest.mark.parametrize("os", ["linux", "windows"])
    def test_pod_os(self, force_oracle, os):
        pods = make_pods(
            3, requirements=[NodeSelectorRequirement(labels.OS, "In", (os,))]
        )
        results, its = run(pods, force_oracle=force_oracle)
        assert_cheapest(results, its)
        for claim in results.new_node_claims:
            for it in claim.instance_type_options:
                assert it.requirements.get(labels.OS).has(os)

    @BOTH
    @pytest.mark.parametrize("os", ["linux", "windows"])
    def test_pool_os(self, force_oracle, os):
        pool = make_nodepool(
            requirements=[NodeSelectorRequirement(labels.OS, "In", (os,))]
        )
        results, its = run(
            make_pods(3), pools=[pool], force_oracle=force_oracle
        )
        assert_cheapest(results, its)

    @BOTH
    def test_pool_zone(self, force_oracle):
        pool = make_nodepool(
            requirements=[
                NodeSelectorRequirement(
                    labels.TOPOLOGY_ZONE, "In", ("test-zone-b",)
                )
            ]
        )
        results, its = run(
            make_pods(3), pools=[pool], force_oracle=force_oracle
        )
        assert_cheapest(results, its)
        for claim in results.new_node_claims:
            assert claim.requirements.get(labels.TOPOLOGY_ZONE).has(
                "test-zone-b"
            )

    @BOTH
    def test_pod_zone(self, force_oracle):
        pods = make_pods(
            3, node_selector={labels.TOPOLOGY_ZONE: "test-zone-b"}
        )
        results, its = run(pods, force_oracle=force_oracle)
        assert_cheapest(results, its)

    @BOTH
    @pytest.mark.parametrize("ct", ["spot", "on-demand"])
    def test_pool_capacity_type(self, force_oracle, ct):
        pool = make_nodepool(
            requirements=[
                NodeSelectorRequirement(
                    labels.CAPACITY_TYPE_LABEL_KEY, "In", (ct,)
                )
            ]
        )
        results, its = run(
            make_pods(3), pools=[pool], force_oracle=force_oracle
        )
        assert_cheapest(results, its)

    @BOTH
    @pytest.mark.parametrize("ct", ["spot", "on-demand"])
    def test_pod_capacity_type(self, force_oracle, ct):
        pods = make_pods(
            3, node_selector={labels.CAPACITY_TYPE_LABEL_KEY: ct}
        )
        results, its = run(pods, force_oracle=force_oracle)
        assert_cheapest(results, its)

    @BOTH
    def test_pool_ct_and_zone(self, force_oracle):
        pool = make_nodepool(
            requirements=[
                NodeSelectorRequirement(
                    labels.CAPACITY_TYPE_LABEL_KEY, "In", ("on-demand",)
                ),
                NodeSelectorRequirement(
                    labels.TOPOLOGY_ZONE, "In", ("test-zone-a",)
                ),
            ]
        )
        results, its = run(
            make_pods(3), pools=[pool], force_oracle=force_oracle
        )
        assert_cheapest(results, its)

    @BOTH
    def test_pod_ct_and_zone(self, force_oracle):
        pods = make_pods(
            3,
            node_selector={
                labels.CAPACITY_TYPE_LABEL_KEY: "spot",
                labels.TOPOLOGY_ZONE: "test-zone-a",
            },
        )
        results, its = run(pods, force_oracle=force_oracle)
        assert_cheapest(results, its)

    @BOTH
    def test_pool_ct_pod_zone_cross(self, force_oracle):
        pool = make_nodepool(
            requirements=[
                NodeSelectorRequirement(
                    labels.CAPACITY_TYPE_LABEL_KEY, "In", ("spot",)
                )
            ]
        )
        pods = make_pods(
            3, node_selector={labels.TOPOLOGY_ZONE: "test-zone-b"}
        )
        results, its = run(pods, pools=[pool], force_oracle=force_oracle)
        assert_cheapest(results, its)

    @BOTH
    def test_pool_quad_constraint(self, force_oracle):
        pool = make_nodepool(
            requirements=[
                NodeSelectorRequirement(
                    labels.CAPACITY_TYPE_LABEL_KEY, "In", ("on-demand",)
                ),
                NodeSelectorRequirement(
                    labels.TOPOLOGY_ZONE, "In", ("test-zone-a",)
                ),
                NodeSelectorRequirement(labels.ARCH, "In", (ARM,)),
                NodeSelectorRequirement(labels.OS, "In", ("windows",)),
            ]
        )
        results, its = run(
            make_pods(3), pools=[pool], force_oracle=force_oracle
        )
        assert_cheapest(results, its)
        for claim in results.new_node_claims:
            for it in claim.instance_type_options:
                assert it.requirements.get(labels.ARCH).has(ARM)
                assert it.requirements.get(labels.OS).has("windows")

    @BOTH
    def test_pod_quad_constraint(self, force_oracle):
        pods = make_pods(
            3,
            node_selector={
                labels.CAPACITY_TYPE_LABEL_KEY: "spot",
                labels.TOPOLOGY_ZONE: "test-zone-b",
                labels.ARCH: AMD,
                labels.OS: "linux",
            },
        )
        results, its = run(pods, force_oracle=force_oracle)
        assert_cheapest(results, its)


class TestNoMatchFailures:
    @BOTH
    def test_unknown_arch_fails(self, force_oracle):
        pods = make_pods(
            2,
            requirements=[NodeSelectorRequirement(labels.ARCH, "In", ("arm",))],
        )
        results, _ = run(pods, force_oracle=force_oracle)
        assert len(results.pod_errors) == 2
        assert not results.new_node_claims

    @BOTH
    def test_arch_zone_cross_product_empty(self, force_oracle):
        # arm64 types exist and zone-b types exist, but catalog has both;
        # restrict to a combination the catalog lacks: strip arm64 from
        # zone-b by building a catalog where arm64 only offers zone-a
        its = [
            corpus.make_instance_type("c", 4, arch=AMD),
            corpus.make_instance_type(
                "c", 4, arch=ARM, zones=("test-zone-a",)
            ),
        ]
        pods = make_pods(
            2,
            requirements=[NodeSelectorRequirement(labels.ARCH, "In", (ARM,))],
            node_selector={labels.TOPOLOGY_ZONE: "test-zone-b"},
        )
        results, _ = run(pods, its=its, force_oracle=force_oracle)
        assert len(results.pod_errors) == 2

    @BOTH
    def test_pool_arch_pod_zone_conflict(self, force_oracle):
        its = [
            corpus.make_instance_type(
                "m", 4, arch=ARM, zones=("test-zone-a",)
            ),
            corpus.make_instance_type("m", 4, arch=AMD),
        ]
        pool = make_nodepool(
            requirements=[NodeSelectorRequirement(labels.ARCH, "In", (ARM,))]
        )
        pods = make_pods(
            2, node_selector={labels.TOPOLOGY_ZONE: "test-zone-c"}
        )
        results, _ = run(pods, pools=[pool], its=its, force_oracle=force_oracle)
        assert len(results.pod_errors) == 2


class TestResourceFit:
    @BOTH
    def test_large_pod_skips_small_cheap_types(self, force_oracle):
        # 12-cpu pod: 2/4/8-cpu types are cheaper but infeasible; the claim
        # must land on a 16-cpu type and still pick the cheapest of those
        pods = [make_pod(cpu="12", memory="16Gi")]
        results, its = run(pods, force_oracle=force_oracle)
        assert_cheapest(results, its)
        for claim in results.new_node_claims:
            for it in claim.instance_type_options:
                assert it.capacity[res.CPU] >= 12 * res.MILLI

    @BOTH
    def test_od_cheaper_than_other_spot(self, force_oracle):
        # spot of a big memory-heavy type is pricier than on-demand of a
        # small compute type: price ordering must cross capacity types
        # (instance_selection_test.go:600)
        its = [
            corpus.make_instance_type(
                "r", 16, capacity_types=("spot",)
            ),
            corpus.make_instance_type(
                "c", 2, capacity_types=("on-demand",)
            ),
        ]
        results, its2 = run(make_pods(1, cpu="1"), its=its,
                            force_oracle=force_oracle)
        assert_cheapest(results, its2)
        # the cheap on-demand c-2x beats the big spot r-16x
        claim = results.new_node_claims[0]
        assert any(
            it.name.startswith("c-2x") for it in claim.instance_type_options
        )
        assert launch_price(claim) == min(
            o.price for o in its[1].offerings
        )


class TestMinValues:
    def _family_pool(self, op, values, min_values):
        return make_nodepool(
            requirements=[
                NodeSelectorRequirement(
                    corpus.INSTANCE_FAMILY_LABEL, op, tuple(values),
                    min_values=min_values,
                )
            ]
        )

    @BOTH
    def test_in_min_values_spans_families(self, force_oracle):
        # minValues=2 over instance-family: each claim keeps options from
        # >= 2 distinct families even though one family is cheapest
        pool = self._family_pool("In", ("c", "m", "r"), 2)
        results, its = run(
            make_pods(4, cpu="1"), pools=[pool], force_oracle=force_oracle
        )
        assert not results.pod_errors
        for claim in results.new_node_claims:
            fams = {
                it.requirements.get(corpus.INSTANCE_FAMILY_LABEL).any()
                for it in claim.instance_type_options
            }
            assert len(fams) >= 2

    @BOTH
    def test_in_min_values_unsatisfiable(self, force_oracle):
        pool = self._family_pool("In", ("c", "m", "r"), 4)  # only 3 exist
        results, _ = run(
            make_pods(2, cpu="1"), pools=[pool], force_oracle=force_oracle
        )
        assert len(results.pod_errors) == 2

    @BOTH
    def test_gt_min_values(self, force_oracle):
        # Gt over instance-cpu with minValues: enough distinct cpu values
        # above the bound must survive (instance_selection_test.go:739)
        pool = make_nodepool(
            requirements=[
                NodeSelectorRequirement(
                    corpus.INSTANCE_CPU_LABEL, "Gt", ("2",), min_values=2
                )
            ]
        )
        results, _ = run(
            make_pods(2, cpu="1"), pools=[pool], force_oracle=force_oracle
        )
        assert not results.pod_errors
        for claim in results.new_node_claims:
            cpus = {
                it.requirements.get(corpus.INSTANCE_CPU_LABEL).any()
                for it in claim.instance_type_options
            }
            assert len(cpus) >= 2
            assert all(int(c) > 2 for c in cpus)

    @BOTH
    def test_gt_min_values_unsatisfiable(self, force_oracle):
        # only one cpu value above 8 in this catalog (16): minValues=2 fails
        its = [
            corpus.make_instance_type("c", c) for c in (2, 4, 8, 16)
        ]
        pool = make_nodepool(
            requirements=[
                NodeSelectorRequirement(
                    corpus.INSTANCE_CPU_LABEL, "Gt", ("8",), min_values=2
                )
            ]
        )
        results, _ = run(
            make_pods(2, cpu="1"), pools=[pool], its=its,
            force_oracle=force_oracle,
        )
        assert len(results.pod_errors) == 2

    @BOTH
    def test_lt_min_values(self, force_oracle):
        pool = make_nodepool(
            requirements=[
                NodeSelectorRequirement(
                    corpus.INSTANCE_CPU_LABEL, "Lt", ("16",), min_values=3
                )
            ]
        )
        results, _ = run(
            make_pods(2, cpu="1"), pools=[pool], force_oracle=force_oracle
        )
        assert not results.pod_errors
        for claim in results.new_node_claims:
            cpus = {
                int(it.requirements.get(corpus.INSTANCE_CPU_LABEL).any())
                for it in claim.instance_type_options
            }
            assert len(cpus) >= 3 and all(c < 16 for c in cpus)

    @BOTH
    def test_lt_min_values_unsatisfiable(self, force_oracle):
        its = [corpus.make_instance_type("c", c) for c in (2, 4, 8)]
        pool = make_nodepool(
            requirements=[
                NodeSelectorRequirement(
                    corpus.INSTANCE_CPU_LABEL, "Lt", ("4",), min_values=2
                )
            ]
        )
        results, _ = run(
            make_pods(2, cpu="1"), pools=[pool], its=its,
            force_oracle=force_oracle,
        )
        assert len(results.pod_errors) == 2

    @BOTH
    def test_max_of_min_values_same_key(self, force_oracle):
        # two requirements on one key: the STRICTER minValues governs
        # (types.go SatisfiesMinValues takes the max per key)
        pool = make_nodepool(
            requirements=[
                NodeSelectorRequirement(
                    corpus.INSTANCE_FAMILY_LABEL, "In", ("c", "m", "r"),
                    min_values=1,
                ),
                NodeSelectorRequirement(
                    corpus.INSTANCE_FAMILY_LABEL, "NotIn", ("g",),
                    min_values=3,
                ),
            ]
        )
        results, _ = run(
            make_pods(2, cpu="1"), pools=[pool], force_oracle=force_oracle
        )
        assert not results.pod_errors
        for claim in results.new_node_claims:
            fams = {
                it.requirements.get(corpus.INSTANCE_FAMILY_LABEL).any()
                for it in claim.instance_type_options
            }
            assert len(fams) >= 3

    @BOTH
    def test_multiple_keys_min_values(self, force_oracle):
        pool = make_nodepool(
            requirements=[
                NodeSelectorRequirement(
                    corpus.INSTANCE_FAMILY_LABEL, "In", ("c", "m", "r"),
                    min_values=2,
                ),
                NodeSelectorRequirement(
                    corpus.INSTANCE_CPU_LABEL, "Exists", (), min_values=3
                ),
            ]
        )
        results, _ = run(
            make_pods(2, cpu="1"), pools=[pool], force_oracle=force_oracle
        )
        assert not results.pod_errors
        for claim in results.new_node_claims:
            fams = {
                it.requirements.get(corpus.INSTANCE_FAMILY_LABEL).any()
                for it in claim.instance_type_options
            }
            cpus = {
                it.requirements.get(corpus.INSTANCE_CPU_LABEL).any()
                for it in claim.instance_type_options
            }
            assert len(fams) >= 2 and len(cpus) >= 3

    def test_min_values_pool_keeps_fast_path(self):
        # ISSUE 10: minValues pools no longer serialize the solve
        # host-side — reachable or not, the batch rides the kernel (dense
        # distinct-value counting) and records NO sequential fallback
        from karpenter_tpu.api.objects import Taint, Toleration
        from karpenter_tpu.kube import Client, TestClock
        from karpenter_tpu.scheduling.topology import Topology
        from karpenter_tpu.solver import TpuSolver

        mv_pool = make_nodepool(
            name="mv",
            taints=[Taint(key="team", value="x", effect="NoSchedule")],
            requirements=[
                NodeSelectorRequirement(
                    corpus.INSTANCE_FAMILY_LABEL, "In", ("c", "m"),
                    min_values=2,
                )
            ],
        )
        open_pool = make_nodepool(name="open")
        pools = [mv_pool, open_pool]
        its = diverse_catalog()
        its_by_pool = {p.name: list(its) for p in pools}
        pods = make_pods(4, cpu="1") + make_pods(
            2, cpu="1",
            tolerations=[Toleration(key="team", operator="Exists")],
        )
        topo = Topology(Client(TestClock()), [], pools, its_by_pool, pods)
        solver = TpuSolver(pools, its_by_pool, topo)
        results = solver.solve(pods)
        assert not results.pod_errors
        assert solver.fallback_solves == 0, solver.last_fallback_reasons
        # the tolerating pods' claims honor the mv pool's floor when they
        # land there
        for claim in results.new_node_claims:
            if not claim.template.requirements.has_min_values():
                continue
            fams = {
                it.requirements.get(corpus.INSTANCE_FAMILY_LABEL).any()
                for it in claim.instance_type_options
            }
            assert len(fams) >= 2

    def test_min_values_survives_60_type_truncation(self):
        # the 60-type truncation (nodeclaimtemplate 60-type cap) keeps the
        # cheapest types; minValues must be evaluated AFTER truncation
        # (instance_selection_test.go:1337) — build > 60 types where the
        # 60 cheapest span only 1 family, with minValues=2 over families
        its = []
        for v in range(70):
            its.append(
                corpus.make_instance_type("c", 2, variant=v)
            )
        its += [corpus.make_instance_type("r", 96)]  # expensive 2nd family
        pool = make_nodepool(
            requirements=[
                NodeSelectorRequirement(
                    corpus.INSTANCE_FAMILY_LABEL, "In", ("c", "r"),
                    min_values=2,
                )
            ]
        )
        results, _ = run(make_pods(2, cpu="1"), pools=[pool], its=its)
        # every solve ends with Results.truncate_instance_types
        # (scheduler.go:249-267): the 60 cheapest types span one family,
        # so the solve itself must refuse — not silently drop to 1 family
        assert len(results.pod_errors) == 2
        assert not results.new_node_claims
        for err in results.pod_errors.values():
            assert "minValues" in err and "truncation" in err


class TestProviderLabels:
    """Provider-registered instance labels (karpenter.tpu/instance-*) are
    well-known: legal in pod selectors and pool requirements, honored at
    provisioning, and stamped onto launched claims so in-flight capacity
    matches pre-registration (no double-provisioning)."""

    def test_pool_requirement_passes_validation(self):
        from karpenter_tpu.api import validation

        pool = make_nodepool(
            requirements=[
                NodeSelectorRequirement(
                    corpus.INSTANCE_CPU_LABEL, "In", ("8", "16")
                )
            ]
        )
        assert not validation.validate_node_pool(pool)

    def test_no_double_provision_before_registration(self):
        from karpenter_tpu.api.objects import NodeClaim
        from karpenter_tpu.cloudprovider.kwok import KwokCloudProvider
        from karpenter_tpu.operator import Operator
        from karpenter_tpu.sim import Binder

        clock = TestClock()
        client = Client(clock)
        provider = KwokCloudProvider(client, corpus.generate(24))
        op = Operator(client, provider)
        binder = Binder(client)
        client.create(make_nodepool())
        pod = make_pod(
            cpu="1", node_selector={corpus.INSTANCE_CPU_LABEL: "8"}
        )
        client.create(pod)
        counts = []
        for _ in range(6):
            op.step(force_provision=True)
            binder.bind_all()
            clock.step(1)
            counts.append(len(client.list(NodeClaim)))
        # in-flight claims carry the chosen type's labels, so the second
        # forced cycle packs onto them instead of re-provisioning
        assert counts == [1] * 6, counts
        assert pod.spec.node_name
