"""Disruption validation tests (validation.go:56-215 behaviors)."""

import pytest

from karpenter_tpu.api import labels
from karpenter_tpu.api.objects import Budget, NodeClaim, Node, Pod
from karpenter_tpu.cloudprovider import corpus
from karpenter_tpu.cloudprovider.kwok import KwokCloudProvider
from karpenter_tpu.controllers.disruption.helpers import get_candidates
from karpenter_tpu.controllers.disruption.types import Command
from karpenter_tpu.controllers.disruption.validation import Validator
from karpenter_tpu.kube import Client, TestClock
from karpenter_tpu.operator import Operator
from karpenter_tpu.sim import Binder

from helpers import make_nodepool, make_pod


@pytest.fixture
def env():
    clock = TestClock()
    client = Client(clock)
    provider = KwokCloudProvider(client, corpus.generate(20))
    operator = Operator(client, provider)
    binder = Binder(client)
    return clock, client, provider, operator, binder


def provision_cycle(env, n_steps=6):
    clock, client, provider, operator, binder = env
    for _ in range(n_steps):
        operator.step(force_provision=True)
        binder.bind_all()
        clock.step(1)


def make_empty_node_command(env, budget_nodes=None):
    """Provision one node, complete its pod, return an Empty command."""
    clock, client, provider, operator, binder = env
    pool = make_nodepool()
    pool.spec.disruption.consolidate_after = 10.0
    if budget_nodes is not None:
        pool.spec.disruption.budgets = [Budget(nodes=budget_nodes)]
    client.create(pool)
    pod = make_pod()
    client.create(pod)
    provision_cycle(env)
    pod.status.phase = "Succeeded"
    client.update(pod)
    clock.step(25)  # past consolidate_after AND the 20s nomination window
    operator.nodeclaim_disruption.reconcile_all()
    candidates = get_candidates(
        client, operator.cluster, provider, clock,
    )
    assert candidates
    return Command(candidates=candidates, reason="Empty"), pod


class TestValidator:
    def test_valid_empty_command(self, env):
        clock, client, provider, operator, binder = env
        cmd, _ = make_empty_node_command(env)
        v = Validator(operator.disruption.ctx)
        assert v.is_valid(cmd) is None

    def test_stale_when_node_regains_pods(self, env):
        clock, client, provider, operator, binder = env
        cmd, _ = make_empty_node_command(env)
        # a new pod binds to the candidate during the TTL window
        node = client.list(Node)[0]
        newcomer = make_pod()
        newcomer.spec.node_name = node.name
        client.create(newcomer)
        v = Validator(operator.disruption.ctx)
        assert v.is_valid(cmd) is not None

    def test_stale_when_candidate_deleted(self, env):
        clock, client, provider, operator, binder = env
        cmd, _ = make_empty_node_command(env)
        for claim in client.list(NodeClaim):
            client.delete(claim)
        for _ in range(4):
            operator.lifecycle.reconcile_all()
            operator.termination.reconcile_all()
            clock.step(1)
        v = Validator(operator.disruption.ctx)
        assert v.is_valid(cmd) is not None

    def test_stale_when_budget_tightens(self, env):
        clock, client, provider, operator, binder = env
        cmd, _ = make_empty_node_command(env)
        pool = client.list(type(make_nodepool()))[0]
        pool.spec.disruption.budgets = [Budget(nodes="0")]
        client.update(pool)
        v = Validator(operator.disruption.ctx)
        stale = v.is_valid(cmd)
        assert stale is not None and "budget" in stale

    def test_stale_when_node_nominated(self, env):
        clock, client, provider, operator, binder = env
        cmd, _ = make_empty_node_command(env)
        node = client.list(Node)[0]
        operator.cluster.nominate_node(node.name, clock.now())
        v = Validator(operator.disruption.ctx)
        assert v.is_valid(cmd) is not None


class TestValidationDeferred:
    def _computed_pending(self, env):
        clock, client, provider, operator, binder = env
        pool = make_nodepool()
        pool.spec.disruption.consolidate_after = 10.0
        client.create(pool)
        pod = make_pod()
        client.create(pod)
        provision_cycle(env)
        pod.status.phase = "Succeeded"
        client.update(pod)
        clock.step(25)
        operator.nodeclaim_disruption.reconcile_all()
        cmd = operator.disruption.reconcile(force=True)
        assert cmd is not None and cmd.decision == "delete"
        # the command is pending validation, not yet executed
        assert operator.disruption._pending is not None
        assert len(client.list(Node)) == 1
        return cmd

    def test_command_executes_after_ttl(self, env):
        clock, client, provider, operator, binder = env
        self._computed_pending(env)
        clock.step(16)  # past VALIDATION_TTL
        cmd2 = operator.disruption.reconcile(force=True)
        assert cmd2 is not None and cmd2.decision == "delete"
        assert operator.disruption._pending is None
        for _ in range(5):
            operator.step()
            clock.step(1)
        assert client.list(Node) == []

    def test_nomination_during_ttl_blocks_emptiness(self, env):
        """State that changes during the TTL window abandons the command."""
        clock, client, provider, operator, binder = env
        self._computed_pending(env)
        node = client.list(Node)[0]
        operator.cluster.nominate_node(node.name, clock.now())
        clock.step(16)
        cmd2 = operator.disruption.reconcile(force=True)
        # validation failed; nothing executed this pass
        assert cmd2 is None or cmd2.decision == "no-op"
        assert len(client.list(Node)) == 1

    def test_policy_change_during_ttl_blocks(self, env):
        """Disabling consolidation mid-TTL abandons the pending command
        (eligibility is re-filtered through the method, validation.go:83-149)."""
        clock, client, provider, operator, binder = env
        self._computed_pending(env)
        from karpenter_tpu.api.objects import NodePool

        pool = client.list(NodePool)[0]
        pool.spec.disruption.consolidation_policy = "WhenEmptyOrUnderutilized"
        pool.spec.disruption.consolidate_after = None
        client.update(pool)
        clock.step(16)
        cmd2 = operator.disruption.reconcile(force=True)
        assert cmd2 is None or cmd2.decision == "no-op"
        assert len(client.list(Node)) == 1

    def test_not_executed_before_ttl(self, env):
        clock, client, provider, operator, binder = env
        self._computed_pending(env)
        clock.step(5)  # inside the TTL window
        assert operator.disruption.reconcile(force=True) is None
        assert operator.disruption._pending is not None
        assert len(client.list(Node)) == 1


class TestSingleNodeOrdering:
    def _candidate(self, pool_name, cost):
        from types import SimpleNamespace

        return SimpleNamespace(
            node_pool=SimpleNamespace(name=pool_name), disruption_cost=cost
        )

    def _method(self):
        from types import SimpleNamespace

        from karpenter_tpu.controllers.disruption.methods import (
            SingleNodeConsolidation,
        )

        ctx = SimpleNamespace(
            client=None, cluster=None, cloud_provider=None,
            clock=None, recorder=None, spot_to_spot_enabled=False,
        )
        return SingleNodeConsolidation(ctx)

    def test_interweaves_across_pools(self):
        m = self._method()
        cands = [
            self._candidate("a", 1), self._candidate("a", 2),
            self._candidate("b", 3), self._candidate("b", 4),
        ]
        ordered = m.sort_candidates(cands)
        pools = [c.node_pool.name for c in ordered]
        assert pools == ["a", "b", "a", "b"]
        costs = [c.disruption_cost for c in ordered]
        assert costs == [1, 3, 2, 4]

    def test_unseen_pools_first(self):
        m = self._method()
        m.previously_unseen_node_pools = {"b"}
        cands = [
            self._candidate("a", 1), self._candidate("b", 10),
        ]
        ordered = m.sort_candidates(cands)
        assert [c.node_pool.name for c in ordered] == ["b", "a"]


class TestSpotToSpotRule:
    """consolidation.go:232-305: single-node spot->spot needs >= 15 cheaper
    spot types (churn protection) and caps launch flexibility at 15;
    multi-node skips the floor; disabled gate refuses outright."""

    def _method(self, spot_enabled=True):
        from karpenter_tpu.controllers.disruption.controller import (
            DisruptionContext,
        )
        from karpenter_tpu.controllers.disruption.methods import (
            SingleNodeConsolidation,
        )
        from karpenter_tpu.kube import Client, TestClock

        clock = TestClock()
        ctx = DisruptionContext(
            client=Client(clock), cluster=None, cloud_provider=None,
            clock=clock, recorder=None, spot_to_spot_enabled=spot_enabled,
        )
        return SingleNodeConsolidation(ctx)

    def _replacement(self, n_types):
        from karpenter_tpu.api.requirements import (
            Operator, Requirement, Requirements,
        )
        from karpenter_tpu.cloudprovider import corpus
        from karpenter_tpu.scheduling.template import NodeClaimTemplate
        from karpenter_tpu.solver.driver import DecodedClaim

        from helpers import make_nodepool

        its = [
            corpus.make_instance_type("c", 2, variant=v)
            for v in range(n_types)
        ]
        return DecodedClaim(
            template=NodeClaimTemplate(make_nodepool()),
            pods=[],
            instance_type_options=its,
            requirements=Requirements(
                Requirement(
                    labels.CAPACITY_TYPE_LABEL_KEY,
                    Operator.IN,
                    [labels.CAPACITY_TYPE_SPOT, labels.CAPACITY_TYPE_ON_DEMAND],
                )
            ),
        )

    def test_single_node_needs_15_cheaper_spot_types(self):
        m = self._method()
        rep = self._replacement(10)
        cmd = m._spot_to_spot([object()], rep, candidate_price=1e9)
        assert cmd.decision == "no-op"

    def test_single_node_caps_flexibility_at_15(self):
        m = self._method()
        rep = self._replacement(40)
        cmd = m._spot_to_spot([object()], rep, candidate_price=1e9)
        assert cmd.decision == "replace"
        assert len(cmd.replacements[0].instance_type_options) == 15

    def test_multi_node_skips_the_floor(self):
        m = self._method()
        rep = self._replacement(3)
        cmd = m._spot_to_spot([object(), object()], rep, candidate_price=1e9)
        assert cmd.decision == "replace"

    def test_gate_off_refuses(self):
        m = self._method(spot_enabled=False)
        rep = self._replacement(40)
        cmd = m._spot_to_spot([object()], rep, candidate_price=1e9)
        assert cmd.decision == "no-op"

    def test_pricier_types_never_survive(self):
        m = self._method()
        rep = self._replacement(40)
        cmd = m._spot_to_spot([object()], rep, candidate_price=0.0001)
        assert cmd.decision == "no-op"  # nothing strictly cheaper remains
