"""Housekeeping controller tests: node repair, consistency, registration
health (health/controller.go, consistency/nodeshape.go,
registrationhealth/controller.go shapes)."""

import pytest

from karpenter_tpu.api.objects import (
    COND_CONSISTENT_STATE_FOUND,
    COND_NODE_REGISTRATION_HEALTHY,
    Node,
    NodeClaim,
    NodePool,
    PodCondition,
)
from karpenter_tpu.cloudprovider import corpus
from karpenter_tpu.cloudprovider.kwok import KwokCloudProvider
from karpenter_tpu.cloudprovider.types import RepairPolicy
from karpenter_tpu.kube import Client, FileClient, TestClock
from karpenter_tpu.operator import Operator, OperatorOptions
from karpenter_tpu.sim import Binder

from helpers import make_nodepool, make_pod, make_pods


class RepairingProvider(KwokCloudProvider):
    def repair_policies(self):
        return [
            RepairPolicy(
                condition_type="Ready",
                condition_status="False",
                toleration_duration=30.0,
            )
        ]


@pytest.fixture(params=["memory", "file"])
def env(request, tmp_path):
    """The full controller suite runs over BOTH store backends: the
    in-process reference store and the file-backed one with copy
    semantics (kube/filestore.py) — the Client surface is a seam, not a
    binding to in-process dicts (VERDICT r4 #6)."""
    clock = TestClock()
    if request.param == "file":
        client = FileClient(clock, root=str(tmp_path / "store"))
    else:
        client = Client(clock)
    provider = RepairingProvider(client, corpus.generate(20))
    operator = Operator(client, provider, OperatorOptions(node_repair=True))
    binder = Binder(client)
    return clock, client, provider, operator, binder


def provision(env, n_pods=1, n_steps=6):
    clock, client, provider, operator, binder = env
    client.create(make_nodepool())
    pods = make_pods(n_pods)
    for p in pods:
        client.create(p)
    for _ in range(n_steps):
        operator.step(force_provision=True)
        binder.bind_all()
        clock.step(1)
    return pods


def mark_unhealthy(client, clock, node):
    node.status.conditions.append(
        PodCondition(type="Ready", status="False",
                     last_transition_time=clock.now())
    )
    client.update(node)


class TestNodeRepair:
    def test_unhealthy_node_repaired_after_toleration(self, env):
        clock, client, provider, operator, binder = env
        provision(env)
        node = client.list(Node)[0]
        mark_unhealthy(client, clock, node)
        operator.health.reconcile_all()
        assert client.try_get(Node, node.name) is not None  # inside toleration
        clock.step(31)
        operator.health.reconcile_all()
        for _ in range(6):
            operator.step()
            clock.step(1)
        assert client.try_get(Node, node.name) is None

    def test_repair_capped_at_20_percent(self, env):
        clock, client, provider, operator, binder = env
        client.create(make_nodepool(name="pool"))
        # 5 nodes, all unhealthy: only 1 (20%) may repair per pass
        for _ in range(5):
            pod = make_pod(cpu="7")  # big enough to force one node each
            client.create(pod)
            for _ in range(6):
                operator.step(force_provision=True)
                binder.bind_all()
                clock.step(1)
        nodes = client.list(Node)
        assert len(nodes) == 5
        for n in nodes:
            mark_unhealthy(client, clock, n)
        clock.step(31)
        operator.health.reconcile_all()
        deleting = [
            n for n in client.list(Node) if n.metadata.deletion_timestamp is not None
        ]
        assert len(deleting) == 1

    def test_no_repair_without_gate(self, env):
        clock, client, provider, operator, binder = env
        operator.options.node_repair = False
        provision(env)
        node = client.list(Node)[0]
        mark_unhealthy(client, clock, node)
        clock.step(31)
        for _ in range(3):
            operator.step()
            clock.step(1)
        assert client.try_get(Node, node.name) is not None


class TestConsistency:
    def test_undersized_node_flagged(self, env):
        clock, client, provider, operator, binder = env
        provision(env)
        claim = client.list(NodeClaim)[0]
        node = client.try_get(Node, claim.status.node_name)
        # shrink the node to 50% of the claim's expected capacity
        node.status.capacity = {
            k: v // 2 for k, v in node.status.capacity.items()
        }
        client.update(node)
        operator.consistency.reconcile_all()
        # re-read: a store with copy semantics (file backend) never
        # reflects controller writes into objects read before reconcile
        claim = client.get("NodeClaim", claim.metadata.name)
        assert claim.conds().get(COND_CONSISTENT_STATE_FOUND).status == "False"

    def test_well_shaped_node_passes(self, env):
        clock, client, provider, operator, binder = env
        provision(env)
        claim = client.list(NodeClaim)[0]
        operator.consistency.reconcile_all()
        assert claim.conds().is_true(COND_CONSISTENT_STATE_FOUND)


class TestRegistrationHealth:
    def test_healthy_after_registration(self, env):
        clock, client, provider, operator, binder = env
        provision(env)
        pool = client.list(NodePool)[0]
        assert pool.conds().is_true(COND_NODE_REGISTRATION_HEALTHY)

    def test_spec_change_resets_condition(self, env):
        clock, client, provider, operator, binder = env
        provision(env)
        pool = client.list(NodePool)[0]
        assert pool.conds().is_true(COND_NODE_REGISTRATION_HEALTHY)
        pool.spec.template.labels["team"] = "new"
        client.update(pool)
        operator.nodepool_status.reconcile_all()
        pool = client.get("NodePool", pool.metadata.name)
        assert pool.conds().get(COND_NODE_REGISTRATION_HEALTHY).status == "Unknown"
        # a claim launched from the NEW spec re-proves health
        pod = make_pod()
        client.create(pod)
        for _ in range(6):
            operator.step(force_provision=True)
            binder.bind_all()
            clock.step(1)
        pool = client.get("NodePool", pool.metadata.name)
        assert pool.conds().is_true(COND_NODE_REGISTRATION_HEALTHY)
