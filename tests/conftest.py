"""Test configuration: force an 8-device virtual CPU platform.

Multi-chip hardware is unavailable in CI; sharding tests run over a virtual
8-device CPU mesh exactly as the driver's dryrun does.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import pytest  # noqa: E402


@pytest.fixture
def rng():
    import numpy as np

    return np.random.default_rng(42)
