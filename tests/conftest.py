"""Test configuration: force an 8-device virtual CPU platform.

Multi-chip hardware is unavailable in CI; sharding tests run over a virtual
8-device CPU mesh exactly as the driver's dryrun does.
"""

import os

# The environment pre-imports jax (sitecustomize) with JAX_PLATFORMS=axon —
# the tunneled TPU — so env vars alone are too late; the platform must be
# switched through jax.config. XLA_FLAGS is still read lazily at CPU-backend
# init, so setting it here gives the virtual 8-device mesh.
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# Respect an explicit non-axon platform request (e.g. a real multi-chip TPU
# host); only the tunneled single-chip axon default is overridden.
if os.environ.get("JAX_PLATFORMS", "axon") == "axon":
    jax.config.update("jax_platforms", "cpu")

# persistent compilation cache: the big packing-scan programs take tens of
# seconds to compile; cache them across test processes
jax.config.update(
    "jax_compilation_cache_dir", os.path.expanduser("~/.cache/jax")
)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

import pytest  # noqa: E402


@pytest.fixture
def rng():
    import numpy as np

    return np.random.default_rng(42)
