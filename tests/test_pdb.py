"""PodDisruptionBudget enforcement (reference: pkg/utils/pdb/limits.go and
the eviction API's 429 handling in terminator/eviction.go:117-226)."""

import pytest

from karpenter_tpu.api.objects import (
    LabelSelector,
    Node,
    NodeClaim,
    ObjectMeta,
    PodDisruptionBudget,
)
from karpenter_tpu.cloudprovider import corpus
from karpenter_tpu.cloudprovider.kwok import KwokCloudProvider
from karpenter_tpu.kube import Client, TestClock
from karpenter_tpu.operator import Operator
from karpenter_tpu.sim import Binder
from karpenter_tpu.utils.pdb import Limits

from helpers import make_nodepool, make_pod


def pdb(name="pdb", labels=None, min_available=None, max_unavailable=None):
    return PodDisruptionBudget(
        metadata=ObjectMeta(name=name),
        selector=LabelSelector(match_labels=dict(labels or {"app": "web"})),
        min_available=min_available,
        max_unavailable=max_unavailable,
    )


def bound_pods(n, labels=None):
    pods = []
    for i in range(n):
        p = make_pod(labels=dict(labels or {"app": "web"}), node_name=f"node-{i % 3}")
        p.status.phase = "Running"
        pods.append(p)
    return pods


class TestLimitsComputation:
    def test_min_available_absolute(self):
        pods = bound_pods(5)
        limits = Limits([pdb(min_available="3")], pods)
        # 5 healthy - 3 required = 2 evictions allowed
        assert limits.can_evict_pods(pods[:2]) is None
        assert limits.can_evict_pods(pods[:3]) is not None

    def test_min_available_percent_rounds_up(self):
        pods = bound_pods(5)
        # 50% of 5 rounds up to 3 -> 2 allowed
        limits = Limits([pdb(min_available="50%")], pods)
        assert limits.can_evict_pods(pods[:2]) is None
        assert limits.can_evict_pods(pods[:3]) is not None

    def test_max_unavailable(self):
        pods = bound_pods(4)
        limits = Limits([pdb(max_unavailable="1")], pods)
        assert limits.can_evict_pods(pods[:1]) is None
        assert limits.can_evict_pods(pods[:2]) is not None

    def test_zero_allowance_blocks_all(self):
        pods = bound_pods(2)
        limits = Limits([pdb(max_unavailable="0")], pods)
        assert limits.can_evict_pods(pods[:1]) is not None

    def test_non_matching_pods_unaffected(self):
        pods = bound_pods(3)
        other = make_pod(labels={"app": "db"}, node_name="node-0")
        limits = Limits([pdb(max_unavailable="0")], pods)
        assert limits.can_evict_pods([other]) is None

    def test_multiple_pdbs_refuse(self):
        pods = bound_pods(3)
        limits = Limits(
            [pdb("a", min_available="1"), pdb("b", max_unavailable="1")], pods
        )
        assert "multiple PDBs" in limits.can_evict_pods(pods[:1])

    def test_record_eviction_consumes_allowance(self):
        pods = bound_pods(4)
        limits = Limits([pdb(max_unavailable="2")], pods)
        assert limits.can_evict_pods(pods[:2]) is None
        limits.record_eviction(pods[0])
        limits.record_eviction(pods[1])
        assert limits.can_evict_pods(pods[2:3]) is not None


class TestDrainHonorsPdb:
    @pytest.fixture
    def env(self):
        clock = TestClock()
        client = Client(clock)
        provider = KwokCloudProvider(client, corpus.generate(20))
        operator = Operator(client, provider)
        binder = Binder(client)
        return clock, client, provider, operator, binder

    def test_drain_stops_at_budget(self, env):
        clock, client, provider, operator, binder = env
        client.create(make_nodepool())
        for _ in range(4):
            client.create(make_pod(cpu="1", memory="1Gi", labels={"app": "web"}))
        client.create(pdb(max_unavailable="1"))
        for _ in range(6):
            operator.step(force_provision=True)
            binder.bind_all()
            clock.step(1)
        nodes = client.list(Node)
        assert nodes
        from karpenter_tpu.api.objects import Pod as PodKind

        node = nodes[0]
        on_node = [
            p for p in client.list(PodKind) if p.spec.node_name == node.name
        ]
        assert len(on_node) >= 2
        # drain the node: only 1 eviction is allowed by the budget
        client.delete(node)
        operator.termination.reconcile_all()
        remaining = [
            p for p in client.list(PodKind) if p.spec.node_name == node.name
        ]
        assert len(remaining) == len(on_node) - 1
