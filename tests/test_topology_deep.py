"""Topology behavior breadth: combined constraints and policies.

Mirrors the reference's scheduling/topology_test.go scenario classes —
combined hostname+zonal+capacity-type spread, spread composed with node
affinity, NodeTaintsPolicy / NodeAffinityPolicy, and pod affinity/anti
interplay — at the behavior level (placements, skews, failures), through
both the oracle and the TPU solver paths where the shape tensorizes.
"""

import pytest

from karpenter_tpu.api import labels, resources as res
from karpenter_tpu.api.objects import (
    NodeSelectorRequirement, Taint, Toleration, TopologySpreadConstraint,
    LabelSelector,
)
from karpenter_tpu.cloudprovider import corpus
from karpenter_tpu.kube import Client, TestClock
from karpenter_tpu.scheduling.topology import Topology
from karpenter_tpu.solver import TpuSolver
from karpenter_tpu.solver.driver import SolverConfig

from helpers import (
    affinity_term, make_nodepool, make_pod, make_pods, spread_constraint,
)

BOTH = pytest.mark.parametrize("force_oracle", [False, True])


def run(pods, pools=None, its=None, force_oracle=False, n_types=20):
    pools = pools or [make_nodepool()]
    its = corpus.generate(n_types) if its is None else its
    its_by_pool = {p.name: list(its) for p in pools}
    topo = Topology(Client(TestClock()), [], pools, its_by_pool, pods)
    solver = TpuSolver(
        pools, its_by_pool, topo,
        config=SolverConfig(force_oracle=force_oracle),
    )
    return solver.solve(pods)


def zone_of(claim):
    r = claim.requirements.get(labels.TOPOLOGY_ZONE)
    return r.any() if not r.complement else None


def ct_of(claim):
    r = claim.requirements.get(labels.CAPACITY_TYPE_LABEL_KEY)
    return r.any() if not r.complement else None


def counts_by(results, keyfn, selector=None):
    out = {}
    for claim in results.new_node_claims:
        k = keyfn(claim)
        n = sum(
            1 for p in claim.pods
            if selector is None or selector(p)
        )
        if n:
            out[k] = out.get(k, 0) + n
    return out


class TestCombinedSpread:
    @BOTH
    def test_hostname_and_zonal_and_ct(self, force_oracle):
        """All three spread keys at once (topology_test.go:1714): hostname
        forces wide nodes, zones and capacity types balance."""
        app = {"app": "tri"}
        pods = make_pods(
            12, cpu="1", labels=app,
            spread=[
                spread_constraint(labels.HOSTNAME, max_skew=2, labels=app),
                spread_constraint(labels.TOPOLOGY_ZONE, max_skew=1, labels=app),
                spread_constraint(
                    labels.CAPACITY_TYPE_LABEL_KEY, max_skew=1, labels=app
                ),
            ],
        )
        results = run(pods, force_oracle=force_oracle)
        assert results.all_pods_scheduled()
        for claim in results.new_node_claims:
            assert len(claim.pods) <= 2  # hostname skew
        zc = counts_by(results, zone_of)
        assert max(zc.values()) - min(zc.values()) <= 1
        cc = counts_by(results, ct_of)
        assert max(cc.values()) - min(cc.values()) <= 1

    @BOTH
    def test_zonal_and_ct(self, force_oracle):
        app = {"app": "zc"}
        pods = make_pods(
            6, cpu="1", labels=app,
            spread=[
                spread_constraint(labels.TOPOLOGY_ZONE, max_skew=1, labels=app),
                spread_constraint(
                    labels.CAPACITY_TYPE_LABEL_KEY, max_skew=1, labels=app
                ),
            ],
        )
        results = run(pods, force_oracle=force_oracle)
        assert results.all_pods_scheduled()
        zc = counts_by(results, zone_of)
        assert len(zc) == 3 and max(zc.values()) - min(zc.values()) <= 1
        cc = counts_by(results, ct_of)
        assert max(cc.values()) - min(cc.values()) <= 1

    @BOTH
    def test_zonal_spread_with_node_affinity_restriction(self, force_oracle):
        """Spread composed with node affinity restricting zones
        (topology_test.go:1752): only the affinity-admitted zones count."""
        app = {"app": "za"}
        pods = make_pods(
            4, cpu="1", labels=app,
            requirements=[
                NodeSelectorRequirement(
                    labels.TOPOLOGY_ZONE, "In",
                    ("test-zone-a", "test-zone-b"),
                )
            ],
            spread=[
                spread_constraint(labels.TOPOLOGY_ZONE, max_skew=1, labels=app)
            ],
        )
        results = run(pods, force_oracle=force_oracle)
        assert results.all_pods_scheduled()
        zc = counts_by(results, zone_of)
        assert set(zc) == {"test-zone-a", "test-zone-b"}
        assert sorted(zc.values()) == [2, 2]

    @BOTH
    def test_ct_spread_with_node_affinity(self, force_oracle):
        """Capacity-type spread + affinity pinning one zone
        (topology_test.go:1869)."""
        app = {"app": "ca"}
        pods = make_pods(
            4, cpu="1", labels=app,
            node_selector={labels.TOPOLOGY_ZONE: "test-zone-a"},
            spread=[
                spread_constraint(
                    labels.CAPACITY_TYPE_LABEL_KEY, max_skew=1, labels=app
                )
            ],
        )
        results = run(pods, force_oracle=force_oracle)
        assert results.all_pods_scheduled()
        for claim in results.new_node_claims:
            assert zone_of(claim) == "test-zone-a"
        cc = counts_by(results, ct_of)
        assert sorted(cc.values()) == [2, 2]

    @BOTH
    def test_spread_ignores_unrelated_pods(self, force_oracle):
        """Only selector-matched pods count toward skew: a flood of
        unrelated pods in one zone doesn't unbalance the spread."""
        app = {"app": "sel"}
        flood = make_pods(
            9, cpu="1",
            node_selector={labels.TOPOLOGY_ZONE: "test-zone-a"},
        )
        spreaders = make_pods(
            3, cpu="1", labels=app,
            spread=[
                spread_constraint(labels.TOPOLOGY_ZONE, max_skew=1, labels=app)
            ],
        )
        results = run(flood + spreaders, force_oracle=force_oracle)
        assert results.all_pods_scheduled()
        zc = counts_by(
            results, zone_of,
            selector=lambda p: p.metadata.labels.get("app") == "sel",
        )
        assert len(zc) == 3 and set(zc.values()) == {1}

    @BOTH
    def test_two_apps_spread_independently(self, force_oracle):
        a, b = {"app": "a"}, {"app": "b"}
        pods = make_pods(
            3, cpu="1", labels=a,
            spread=[spread_constraint(labels.TOPOLOGY_ZONE, labels=a)],
        ) + make_pods(
            6, cpu="2", labels=b,
            spread=[spread_constraint(labels.TOPOLOGY_ZONE, labels=b)],
        )
        results = run(pods, force_oracle=force_oracle)
        assert results.all_pods_scheduled()
        for app in ("a", "b"):
            zc = counts_by(
                results, zone_of,
                selector=lambda p, app=app: p.metadata.labels.get("app") == app,
            )
            assert max(zc.values()) - min(zc.values()) <= 1


class TestNodeTaintsPolicy:
    def _tainted_pool_env(self):
        tainted = make_nodepool(
            name="tainted",
            weight=10,
            taints=[Taint(key="team", value="x", effect="NoSchedule")],
        )
        open_ = make_nodepool(name="open", weight=1)
        return [tainted, open_]

    def test_honor_excludes_tainted_domains(self):
        """NodeTaintsPolicy=Honor: domains only reachable through tainted
        nodes don't count for the intolerant pod (topology_test.go:1186).
        Honor-policy shapes serialize host-side by design."""
        app = {"app": "tp"}
        pods = make_pods(
            2, cpu="1", labels=app,
            spread=[
                TopologySpreadConstraint(
                    max_skew=1,
                    topology_key=labels.TOPOLOGY_ZONE,
                    when_unsatisfiable="DoNotSchedule",
                    label_selector=LabelSelector(match_labels=app),
                    node_taints_policy="Honor",
                )
            ],
        )
        results = run(pods, pools=self._tainted_pool_env())
        assert results.all_pods_scheduled()
        for claim in results.new_node_claims:
            assert claim.template.node_pool_name == "open"

    def test_ignore_counts_tainted_domains(self):
        app = {"app": "ti"}
        pods = make_pods(
            3, cpu="1", labels=app,
            tolerations=[Toleration(key="team", operator="Exists")],
            spread=[
                spread_constraint(labels.TOPOLOGY_ZONE, max_skew=1, labels=app)
            ],
        )
        results = run(pods, pools=self._tainted_pool_env())
        assert results.all_pods_scheduled()
        zc = counts_by(results, zone_of)
        assert len(zc) == 3


class TestPodAffinityInterplay:
    @BOTH
    def test_zonal_affinity_groups_colocate(self, force_oracle):
        """Self-affinity on zone: each app's pods share one zone, distinct
        apps may differ (topology_test.go:1938 class)."""
        pods = []
        for app in ("x", "y", "z"):
            lbl = {"grp": app}
            pods += make_pods(
                3, cpu="1", labels=lbl,
                pod_affinity=[affinity_term(labels.TOPOLOGY_ZONE, lbl)],
            )
        results = run(pods, force_oracle=force_oracle)
        assert results.all_pods_scheduled()
        for app in ("x", "y", "z"):
            zones = {
                zone_of(c)
                for c in results.new_node_claims
                if any(p.metadata.labels.get("grp") == app for p in c.pods)
            }
            assert len(zones) == 1

    @BOTH
    def test_hostname_anti_one_per_node_with_bystanders(self, force_oracle):
        """Hostname anti-affinity pods singleton per node while unrelated
        pods pack densely alongside."""
        lbl = {"app": "singleton"}
        anti = make_pods(
            3, cpu="1", labels=lbl,
            pod_anti_affinity=[affinity_term(labels.HOSTNAME, lbl)],
        )
        bulk = make_pods(9, cpu="1")
        results = run(anti + bulk, force_oracle=force_oracle)
        assert results.all_pods_scheduled()
        for claim in results.new_node_claims:
            n_anti = sum(1 for p in claim.pods if p in anti)
            assert n_anti <= 1

    @BOTH
    def test_zonal_affinity_with_spread_partner(self, force_oracle):
        """An affinity app and a spread app coexist: affinity pods in one
        zone, spread pods balanced regardless."""
        aff_l, spr_l = {"grp": "aff"}, {"app": "spr"}
        aff = make_pods(
            4, cpu="1", labels=aff_l,
            pod_affinity=[affinity_term(labels.TOPOLOGY_ZONE, aff_l)],
        )
        spr = make_pods(
            3, cpu="1", labels=spr_l,
            spread=[spread_constraint(labels.TOPOLOGY_ZONE, labels=spr_l)],
        )
        results = run(aff + spr, force_oracle=force_oracle)
        assert results.all_pods_scheduled()
        aff_zones = {
            zone_of(c)
            for c in results.new_node_claims
            if any(p in aff for p in c.pods)
        }
        assert len(aff_zones) == 1
        zc = counts_by(
            results, zone_of, selector=lambda p: p in spr
        )
        assert len(zc) == 3

    def test_inverse_anti_affinity_blocks_selected_pod(self):
        """Anti-affinity is symmetric: zone-pinned anti pods occupy all
        three zones, so a plain pod MATCHING their selector cannot land
        anywhere (topology_test.go:2476 'inverse')."""
        lbl = {"security": "s2"}
        anti = [affinity_term(labels.TOPOLOGY_ZONE, lbl)]
        zoned = [
            make_pod(
                cpu="2",
                pod_anti_affinity=anti,
                node_selector={labels.TOPOLOGY_ZONE: z},
            )
            for z in ("test-zone-a", "test-zone-b", "test-zone-c")
        ]
        selected = make_pod(cpu="1", labels=dict(lbl))
        results = run(zoned + [selected])
        for p in zoned:
            assert p.uid not in results.pod_errors
        assert selected.uid in results.pod_errors

    def test_schroedinger_anti_affinity_blocks_until_committal(self):
        """An unpinned anti pod could land in ANY zone, so a selected pod
        in the same batch cannot schedule (topology_test.go:2512); once
        the anti pod's node is real (zone committed), a later batch
        schedules the selected pod in a different zone."""
        from helpers import make_state_node

        lbl = {"security": "s2"}
        anywhere = make_pod(
            cpu="2",
            pod_anti_affinity=[affinity_term(labels.TOPOLOGY_ZONE, lbl)],
        )
        selected = make_pod(cpu="1", labels=dict(lbl))
        results = run([anywhere, selected])
        assert anywhere.uid not in results.pod_errors
        assert selected.uid in results.pod_errors

        # second batch: the anti pod is bound to a real node in zone-a —
        # the selected pod must now schedule, in a different zone
        sn = make_state_node(name="committed", cpu="4", memory="8Gi")
        bound = make_pod(
            cpu="2",
            pod_anti_affinity=[affinity_term(labels.TOPOLOGY_ZONE, lbl)],
            node_name="committed",
            phase="Running",
        )
        sn.update_pod(bound, is_daemon=False)
        client = Client(TestClock())
        client.create(sn.node)
        client.create(bound)
        pools = [make_nodepool()]
        its_by_pool = {p.name: corpus.generate(20) for p in pools}
        selected2 = make_pod(cpu="1", labels=dict(lbl))
        topo = Topology(client, [sn], pools, its_by_pool, [selected2])
        solver = TpuSolver(
            pools, its_by_pool, topo, state_nodes=[sn]
        )
        results2 = solver.solve([selected2])
        assert selected2.uid not in results2.pod_errors
        zones = {
            zone_of(c) for c in results2.new_node_claims if c.pods
        }
        assert zones and "test-zone-a" not in zones

    def test_zonal_anti_affinity_late_committal(self):
        """Zonal anti-affinity within ONE batch schedules only one pod:
        the first claim's zone is uncommitted, so the oracle pessimistically
        records every admitted zone as occupied — the reference documents
        this 'downside of late committal' and expects the rest to resolve
        over subsequent batches (topology_test.go:2678-2722)."""
        lbl = {"app": "zanti"}
        pods = make_pods(
            3, cpu="1", labels=lbl,
            pod_anti_affinity=[affinity_term(labels.TOPOLOGY_ZONE, lbl)],
        )
        results = run(pods)
        scheduled = [c for c in results.new_node_claims if c.pods]
        assert len(scheduled) == 1
        assert len(results.pod_errors) == 2


class TestSpreadEdgeCases:
    @BOTH
    def test_skew_respected_across_batches(self, force_oracle):
        """Second batch sees the first batch's claims via topology priors:
        a fresh solve on a cluster state is out of scope here, but within
        one batch a 7-pod spread over 3 zones lands 3/2/2."""
        app = {"app": "seven"}
        pods = make_pods(
            7, cpu="1", labels=app,
            spread=[
                spread_constraint(labels.TOPOLOGY_ZONE, max_skew=1, labels=app)
            ],
        )
        results = run(pods, force_oracle=force_oracle)
        assert results.all_pods_scheduled()
        zc = counts_by(results, zone_of)
        assert sorted(zc.values()) == [2, 2, 3]

    @BOTH
    def test_zone_limited_catalog_bounds_spread(self, force_oracle):
        """Types only offer two zones: the spread universe is what the
        catalog registers, not the static zone list."""
        its = [
            corpus.make_instance_type(
                "m", c, zones=("test-zone-a", "test-zone-b")
            )
            for c in (4, 8)
        ]
        app = {"app": "2z"}
        pods = make_pods(
            4, cpu="1", labels=app,
            spread=[
                spread_constraint(labels.TOPOLOGY_ZONE, max_skew=1, labels=app)
            ],
        )
        results = run(pods, its=its, force_oracle=force_oracle)
        assert results.all_pods_scheduled()
        zc = counts_by(results, zone_of)
        assert set(zc) == {"test-zone-a", "test-zone-b"}
        assert sorted(zc.values()) == [2, 2]

    @BOTH
    def test_schedule_anyway_never_blocks(self, force_oracle):
        """ScheduleAnyway spread is preference-only: a zone-pinned workload
        still schedules fully (relaxation host-side)."""
        app = {"app": "anyway"}
        pods = make_pods(
            6, cpu="1", labels=app,
            node_selector={labels.TOPOLOGY_ZONE: "test-zone-a"},
            spread=[
                spread_constraint(
                    labels.TOPOLOGY_ZONE, max_skew=1, labels=app,
                    when_unsatisfiable="ScheduleAnyway",
                )
            ],
        )
        results = run(pods, force_oracle=force_oracle)
        assert results.all_pods_scheduled()
        for claim in results.new_node_claims:
            if claim.pods:
                assert zone_of(claim) == "test-zone-a"
