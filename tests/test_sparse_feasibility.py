"""Sparse/segment feasibility twins vs the dense tables: bit-exact.

The encoder's compacted nonzero-mask index (encode.build_segment_index)
drives segment-sum feasibility (ops/feasibility.py:*_sparse); every entry
of (compat_pg, type_ok, n_fit, cap_ng) must match the dense kernels on
real encoded snapshots — including groups with node selectors (defined
keys), negated requirements, zone/capacity-type constraints (the merged
offering correction), padded group rows, and existing nodes.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from karpenter_tpu.ops.feasibility import (  # noqa: E402
    existing_node_feasibility,
    existing_node_feasibility_sparse,
    fresh_claim_feasibility,
    fresh_claim_feasibility_sparse,
)
from karpenter_tpu.solver import encode as enc  # noqa: E402


def _snap_for(pods, existing_nodes=()):
    from karpenter_tpu.cloudprovider import corpus
    from karpenter_tpu.kube import Client, TestClock
    from karpenter_tpu.scheduling.topology import Topology
    from karpenter_tpu.solver import TpuSolver
    from karpenter_tpu.solver.example import example_nodepool

    pools = [example_nodepool()]
    its = {pools[0].name: corpus.generate(24)}
    topology = Topology(Client(TestClock()), [], pools, its, pods)
    solver = TpuSolver(pools, its, topology)
    groups, _ = enc.partition_and_group(pods, topology=solver.oracle.topology)
    snap, avail, *_rest = solver._encode_batch(groups)
    return solver, snap


def _dense_vs_sparse(snap):
    dense = fresh_claim_feasibility(
        snap.g_def, snap.g_neg, snap.g_mask, snap.g_req,
        snap.p_def, snap.p_neg, snap.p_mask, snap.p_daemon, snap.p_tol,
        snap.p_titype_ok,
        snap.t_def, snap.t_mask, snap.t_alloc,
        snap.o_avail, snap.o_zone, snap.o_ct,
        snap.well_known,
        zone_kid=snap.zone_kid, ct_kid=snap.ct_kid,
    )
    sparse = fresh_claim_feasibility_sparse(
        snap.g_def, snap.g_neg, snap.g_mask, snap.g_req,
        snap.p_def, snap.p_neg, snap.p_mask, snap.p_daemon, snap.p_tol,
        snap.p_titype_ok,
        snap.t_def, snap.t_mask, snap.t_alloc,
        snap.o_avail, snap.o_zone, snap.o_ct,
        snap.well_known,
        snap.gk_g, snap.gk_k, snap.gk_w, snap.goff_idx,
        zone_kid=snap.zone_kid, ct_kid=snap.ct_kid,
    )
    for name, d, s in zip(("compat_pg", "type_ok", "n_fit"), dense, sparse):
        d, s = np.asarray(d), np.asarray(s)
        assert d.shape == s.shape, name
        mism = np.argwhere(d != s)
        assert not mism.size, f"{name} diverges at {mism[:5]}"


class TestSparseFeasibility:
    def test_constrained_mix_bit_exact(self):
        from karpenter_tpu.solver.workloads import constrained_mix

        _, snap = _snap_for(constrained_mix(300, seed=5))
        assert int(snap.gk_w.sum()) > 0  # selectors define keys
        _dense_vs_sparse(snap)

    def test_diverse_mix_bit_exact(self):
        from karpenter_tpu.solver.workloads import diverse_reference_mix

        _, snap = _snap_for(diverse_reference_mix(250, seed=7))
        _dense_vs_sparse(snap)

    def test_padded_groups_bit_exact(self):
        from karpenter_tpu.solver.workloads import constrained_mix

        _, snap = _snap_for(constrained_mix(200, seed=11))
        G = enc._next_pow2(len(snap.groups) + 5, floor=8)
        _dense_vs_sparse(snap.padded(G, 0))

    def test_zone_constrained_offering_correction(self):
        # pods pinned to one zone: the merged offering row must differ
        # from the template base, exercising the goff scatter path
        from karpenter_tpu.api import labels as labels_mod
        from karpenter_tpu.solver.workloads import mixed_pods

        pods = mixed_pods(60, gpu_fraction=0.0)
        for p in pods[:20]:
            p.spec.node_selector = {labels_mod.TOPOLOGY_ZONE: "zone-a"}
        _, snap = _snap_for(pods)
        assert int((snap.goff_idx > 0).sum()) > 0
        _dense_vs_sparse(snap)

    def test_existing_nodes_bit_exact(self):
        from karpenter_tpu.solver.workloads import constrained_mix

        solver, snap = _snap_for(constrained_mix(150, seed=3))
        # synthesize node rows from the type side so no cluster is needed:
        # strict node compatibility only reads def/mask/avail/base/tol
        T = snap.t_def.shape[0]
        N = min(6, T)
        rng = np.random.default_rng(0)
        n_def = snap.t_def[:N].copy()
        n_mask = snap.t_mask[:N].copy()
        n_avail = snap.t_alloc[:N].copy()
        n_base = np.zeros_like(n_avail)
        n_tol = rng.random((N, len(snap.g_count))) < 0.8
        dense = existing_node_feasibility(
            snap.g_def, snap.g_neg, snap.g_mask, snap.g_req,
            n_def, n_mask, n_avail, n_base, n_tol,
            snap.well_known,
        )
        sparse = existing_node_feasibility_sparse(
            snap.g_def, snap.g_neg, snap.g_mask, snap.g_req,
            n_def, n_mask, n_avail, n_base, n_tol,
            snap.gk_g, snap.gk_k, snap.gk_w,
        )
        assert (np.asarray(dense) == np.asarray(sparse)).all()

    def test_index_is_pow2_bucketed(self):
        from karpenter_tpu.solver.workloads import constrained_mix

        _, snap = _snap_for(constrained_mix(120, seed=9))
        for arr in (snap.gk_g, snap.gk_k, snap.gk_w):
            n = len(arr)
            assert n >= 8 and (n & (n - 1)) == 0
        n = len(snap.goff_idx)
        assert n >= 8 and (n & (n - 1)) == 0
