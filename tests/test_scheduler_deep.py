"""Deep scheduler scenarios.

Second tier of behavior coverage mirroring the reference's
scheduling/topology_test.go (minDomains, maxSkew, capacity-type spread,
combined constraints) and scheduling/instance_selection_test.go (minValues,
price ordering/truncation, reserved offerings).
"""

import pytest

from karpenter_tpu.api import labels, resources as res
from karpenter_tpu.api.objects import NodeSelectorRequirement
from karpenter_tpu.api.requirements import Operator, Requirement, Requirements
from karpenter_tpu.cloudprovider import corpus
from karpenter_tpu.cloudprovider import types as cp
from karpenter_tpu.kube import Client, TestClock
from karpenter_tpu.scheduling.scheduler import Scheduler
from karpenter_tpu.scheduling.topology import Topology

from helpers import make_nodepool, make_pod, make_pods, spread_constraint
from test_scheduler import solve


def zone_counts(results):
    counts = {}
    for claim in results.new_node_claims:
        req = claim.requirements.get(labels.TOPOLOGY_ZONE)
        zone = req.any() if not req.complement else "?"
        counts[zone] = counts.get(zone, 0) + len(claim.pods)
    return counts


class TestSpreadDeep:
    def test_max_skew_two_allows_imbalance(self):
        # maxSkew=2: counts may differ by up to 2 across zones
        # (topologygroup.go:205-251)
        pods = make_pods(
            8, labels={"app": "x"},
            spread=[spread_constraint(labels.TOPOLOGY_ZONE, max_skew=2,
                                      labels={"app": "x"})],
        )
        results = solve(pods)
        assert results.all_pods_scheduled()
        counts = zone_counts(results)
        assert max(counts.values()) - min(counts.values()) <= 2

    def test_min_domains_unsatisfied_pins_min_to_zero(self):
        # minDomains=4 but only 3 zones exist: the global min is treated as
        # 0 (topologygroup.go:270-273), so with maxSkew=1 each zone takes
        # exactly one pod and the 4th pod cannot land anywhere
        pods = make_pods(
            4, labels={"app": "x"},
            spread=[spread_constraint(labels.TOPOLOGY_ZONE, max_skew=1,
                                      labels={"app": "x"}, min_domains=4)],
        )
        results = solve(pods)
        assert len(results.pod_errors) == 1
        counts = zone_counts(results)
        assert sorted(counts.values()) == [1, 1, 1]

    def test_min_domains_satisfied(self):
        pods = make_pods(
            3, labels={"app": "x"},
            spread=[spread_constraint(labels.TOPOLOGY_ZONE, max_skew=1,
                                      labels={"app": "x"}, min_domains=3)],
        )
        results = solve(pods)
        assert results.all_pods_scheduled()
        assert len(zone_counts(results)) == 3

    def test_capacity_type_spread(self):
        # spread over karpenter.sh/capacity-type splits spot/on-demand
        # (well-known domain from offerings)
        pods = make_pods(
            4, labels={"app": "x"},
            spread=[spread_constraint(labels.CAPACITY_TYPE_LABEL_KEY,
                                      max_skew=1, labels={"app": "x"})],
        )
        results = solve(pods)
        assert results.all_pods_scheduled()
        cts = {}
        for claim in results.new_node_claims:
            ct = claim.requirements.get(labels.CAPACITY_TYPE_LABEL_KEY).any()
            cts[ct] = cts.get(ct, 0) + len(claim.pods)
        assert max(cts.values()) - min(cts.values()) <= 1
        assert set(cts) == {"spot", "on-demand"}

    def test_combined_zone_and_hostname_spread(self):
        pods = make_pods(
            6, labels={"app": "x"},
            spread=[
                spread_constraint(labels.TOPOLOGY_ZONE, max_skew=1,
                                  labels={"app": "x"}),
                spread_constraint(labels.HOSTNAME, max_skew=1,
                                  labels={"app": "x"}),
            ],
        )
        results = solve(pods)
        assert results.all_pods_scheduled()
        # hostname skew 1 forces one pod per node
        assert results.node_count() == 6
        counts = zone_counts(results)
        assert max(counts.values()) - min(counts.values()) <= 1

    def test_spread_with_zone_restricted_pool(self):
        # NodePool restricted to 2 zones: spread only counts those domains
        pool = make_nodepool(
            requirements=[NodeSelectorRequirement(
                labels.TOPOLOGY_ZONE, "In", ["test-zone-a", "test-zone-b"])],
        )
        pods = make_pods(
            4, labels={"app": "x"},
            spread=[spread_constraint(labels.TOPOLOGY_ZONE, max_skew=1,
                                      labels={"app": "x"})],
        )
        results = solve(pods, node_pools=[pool])
        assert results.all_pods_scheduled()
        counts = zone_counts(results)
        assert set(counts) <= {"test-zone-a", "test-zone-b"}
        assert max(counts.values()) - min(counts.values()) <= 1


class TestInstanceSelectionDeep:
    def test_min_values_keeps_enough_types(self):
        # minValues on instance-type requirement: claims must retain >= 3
        # type options (types.go:186-233 SatisfiesMinValues)
        pool = make_nodepool(
            requirements=[NodeSelectorRequirement(
                labels.INSTANCE_TYPE, "Exists", [], min_values=3)],
        )
        results = solve(make_pods(4, cpu="1"), node_pools=[pool])
        assert results.all_pods_scheduled()
        for claim in results.new_node_claims:
            assert len(claim.instance_type_options) >= 3

    def test_min_values_unsatisfiable_fails(self):
        pool = make_nodepool(
            requirements=[NodeSelectorRequirement(
                labels.INSTANCE_TYPE, "Exists", [], min_values=500)],
        )
        results = solve(make_pods(2, cpu="1"), node_pools=[pool],
                        instance_types=corpus.generate(6))
        assert len(results.pod_errors) == 2

    def test_cheapest_type_first_after_finalize(self):
        results = solve(make_pods(3, cpu="1"))
        for claim in results.new_node_claims:
            claim.finalize()
            options = claim.instance_type_options
            prices = [
                min(o.price for o in it.offerings if o.available)
                for it in options
            ]
            assert prices == sorted(prices)

    def test_unavailable_offerings_excluded(self):
        its = corpus.generate(6)
        for it in its:
            for o in it.offerings:
                if o.zone() == "test-zone-a":
                    o.available = False
        pods = make_pods(
            2,
            requirements=[NodeSelectorRequirement(
                labels.TOPOLOGY_ZONE, "In", ["test-zone-a"])],
        )
        results = solve(pods, instance_types=its)
        assert len(results.pod_errors) == 2

    def test_gt_lt_requirement_bounds(self):
        # integer Gt/Lt bounds on a custom label (requirement.go:33-84)
        pool = make_nodepool(labels={"gen": "5"})
        ok = make_pod(requirements=[
            NodeSelectorRequirement("gen", "Gt", ["4"]),
            NodeSelectorRequirement("gen", "Lt", ["6"]),
        ])
        bad = make_pod(requirements=[
            NodeSelectorRequirement("gen", "Gt", ["5"]),
        ])
        results = solve([ok, bad], node_pools=[pool])
        assert ok.uid not in results.pod_errors
        assert bad.uid in results.pod_errors


class TestReservedOfferings:
    def _reserved_types(self, capacity=2):
        its = corpus.generate(4)
        out = []
        for it in its[:2]:
            res_req = Requirements(
                Requirement(labels.CAPACITY_TYPE_LABEL_KEY, Operator.IN,
                            [labels.CAPACITY_TYPE_RESERVED]),
                Requirement(labels.TOPOLOGY_ZONE, Operator.IN, ["test-zone-a"]),
                Requirement(cp.RESERVATION_ID_LABEL, Operator.IN,
                            [f"res-{it.name}"]),
            )
            it.offerings.append(cp.Offering(
                requirements=res_req, price=0.001, available=True,
                reservation_capacity=capacity,
            ))
            out.append(it)
        return its

    def test_reserved_capacity_ledger_limits_claims(self):
        # 2 reserved slots per offering; extra claims fall back to
        # non-reserved capacity (reservationmanager.go:28-85)
        its = self._reserved_types(capacity=1)
        pool = make_nodepool()
        pods = make_pods(4, cpu="1")
        client = Client(TestClock())
        its_by_pool = {pool.name: its}
        topology = Topology(client, [], [pool], its_by_pool, pods)
        scheduler = Scheduler(
            [pool], its_by_pool, topology, reserved_capacity_enabled=True,
        )
        results = scheduler.solve(pods)
        assert results.all_pods_scheduled()
        reserved_claims = [
            c for c in results.new_node_claims
            if c.requirements.has(labels.CAPACITY_TYPE_LABEL_KEY)
            and c.requirements.get(labels.CAPACITY_TYPE_LABEL_KEY).has(
                labels.CAPACITY_TYPE_RESERVED)
        ]
        # the ledger caps reserved claims at total reservation capacity
        assert len(reserved_claims) <= 2
