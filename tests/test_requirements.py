"""Requirements algebra parity tests.

Behavioral tables mirror the reference's pkg/scheduling/requirements_test.go
and requirement.go semantics: operator pair intersections, complement sets,
Gt/Lt bounds, compatibility asymmetry for custom vs well-known labels, and
the double-negation exemption.
"""

from karpenter_tpu.api import labels
from karpenter_tpu.api.requirements import Operator, Requirement, Requirements

A_IN = lambda *v: Requirement("key", Operator.IN, v)
A_NOT_IN = lambda *v: Requirement("key", Operator.NOT_IN, v)
EXISTS = lambda: Requirement("key", Operator.EXISTS)
DOES_NOT_EXIST = lambda: Requirement("key", Operator.DOES_NOT_EXIST)
GT = lambda v: Requirement("key", Operator.GT, [str(v)])
LT = lambda v: Requirement("key", Operator.LT, [str(v)])


class TestOperatorInference:
    def test_operators(self):
        assert A_IN("a").operator() is Operator.IN
        assert A_NOT_IN("a").operator() is Operator.NOT_IN
        assert EXISTS().operator() is Operator.EXISTS
        assert DOES_NOT_EXIST().operator() is Operator.DOES_NOT_EXIST
        assert GT(1).operator() is Operator.GT
        assert LT(1).operator() is Operator.LT

    def test_in_empty_is_does_not_exist(self):
        assert Requirement("key", Operator.IN, []).operator() is Operator.DOES_NOT_EXIST


class TestHas:
    def test_in(self):
        r = A_IN("a", "b")
        assert r.has("a") and r.has("b") and not r.has("c")

    def test_not_in(self):
        r = A_NOT_IN("a")
        assert not r.has("a") and r.has("b")

    def test_exists_and_does_not_exist(self):
        assert EXISTS().has("anything")
        assert not DOES_NOT_EXIST().has("anything")

    def test_gt_lt(self):
        assert GT(5).has("6") and not GT(5).has("5")
        assert LT(5).has("4") and not LT(5).has("5")
        # non-numeric values fail bounds (requirement.go:313-326)
        assert not GT(5).has("abc")


class TestIntersection:
    def test_in_in(self):
        r = A_IN("a", "b").intersection(A_IN("b", "c"))
        assert r.values == {"b"} and not r.complement

    def test_in_in_disjoint(self):
        r = A_IN("a").intersection(A_IN("b"))
        assert r.operator() is Operator.DOES_NOT_EXIST

    def test_in_not_in(self):
        r = A_IN("a", "b").intersection(A_NOT_IN("b"))
        assert r.values == {"a"} and not r.complement

    def test_not_in_not_in(self):
        r = A_NOT_IN("a").intersection(A_NOT_IN("b"))
        assert r.complement and r.values == {"a", "b"}

    def test_exists_in(self):
        r = EXISTS().intersection(A_IN("a"))
        assert not r.complement and r.values == {"a"}

    def test_does_not_exist_absorbs(self):
        r = DOES_NOT_EXIST().intersection(A_IN("a"))
        assert r.operator() is Operator.DOES_NOT_EXIST

    def test_gt_lt_band(self):
        r = GT(1).intersection(LT(5))
        assert r.complement
        assert r.has("2") and r.has("4")
        assert not r.has("1") and not r.has("5")

    def test_gt_lt_empty_band(self):
        # greaterThan >= lessThan collapses to DoesNotExist (requirement.go:160-166)
        r = GT(5).intersection(LT(5))
        assert r.operator() is Operator.DOES_NOT_EXIST

    def test_bounds_filter_concrete_values(self):
        r = A_IN("1", "3", "7").intersection(GT(2))
        assert r.values == {"3", "7"} and not r.complement

    def test_bounds_dropped_for_concrete_result(self):
        # reference: requirement.go:184-187
        r = A_IN("3").intersection(GT(1))
        assert r.greater_than is None and r.less_than is None

    def test_min_values_max_wins(self):
        a = Requirement("key", Operator.IN, ["a", "b"], min_values=1)
        b = Requirement("key", Operator.IN, ["a", "b"], min_values=2)
        assert a.intersection(b).min_values == 2

    def test_commutative_on_allowed_sets(self):
        import itertools

        universe = ["a", "b", "c", "1", "5", "9"]
        reqs = [
            A_IN("a", "1"),
            A_IN("b", "5", "9"),
            A_NOT_IN("a", "9"),
            EXISTS(),
            DOES_NOT_EXIST(),
            GT(2),
            LT(7),
        ]
        for x, y in itertools.product(reqs, reqs):
            lhs, rhs = x.intersection(y), y.intersection(x)
            for v in universe:
                assert lhs.has(v) == rhs.has(v), (x, y, v)


class TestHasIntersection:
    def test_matches_intersection_nonemptiness(self):
        import itertools

        reqs = [
            A_IN("a", "1"),
            A_IN("b"),
            A_NOT_IN("a"),
            EXISTS(),
            DOES_NOT_EXIST(),
            GT(0),
            LT(2),
        ]
        for x, y in itertools.product(reqs, reqs):
            got = x.has_intersection(y)
            inter = x.intersection(y)
            # complement results are never empty; concrete results are
            # non-empty iff values remain
            expected = inter.complement or bool(inter.values)
            assert got == expected, (x, y)

    def test_complement_pair_always_intersects(self):
        assert A_NOT_IN("a").has_intersection(A_NOT_IN("b"))
        assert EXISTS().has_intersection(GT(1000000))


class TestRequirements:
    def test_add_intersects_same_key(self):
        reqs = Requirements(A_IN("a", "b"))
        reqs.add(A_IN("b", "c"))
        assert reqs.get("key").values == {"b"}

    def test_get_undefined_is_exists(self):
        reqs = Requirements()
        assert reqs.get("missing").operator() is Operator.EXISTS

    def test_label_normalization(self):
        r = Requirement("beta.kubernetes.io/arch", Operator.IN, ["amd64"])
        assert r.key == labels.ARCH

    def test_from_labels(self):
        reqs = Requirements.from_labels({"a": "1", "b": "2"})
        assert reqs.get("a").values == {"1"}


class TestCompatible:
    """Asymmetric compatibility (requirements.go:177-196)."""

    def test_well_known_undefined_allowed(self):
        node = Requirements()
        pod = Requirements(Requirement(labels.TOPOLOGY_ZONE, Operator.IN, ["zone-1"]))
        assert node.compatible(pod, labels.WELL_KNOWN_LABELS) is None

    def test_custom_undefined_denied(self):
        node = Requirements()
        pod = Requirements(Requirement("example.com/team", Operator.IN, ["infra"]))
        assert node.compatible(pod, labels.WELL_KNOWN_LABELS) is not None

    def test_custom_defined_must_intersect(self):
        node = Requirements(Requirement("example.com/team", Operator.IN, ["web"]))
        pod = Requirements(Requirement("example.com/team", Operator.IN, ["infra"]))
        assert node.compatible(pod, labels.WELL_KNOWN_LABELS) is not None
        pod2 = Requirements(Requirement("example.com/team", Operator.IN, ["web"]))
        assert node.compatible(pod2, labels.WELL_KNOWN_LABELS) is None

    def test_custom_undefined_negative_op_allowed(self):
        # NotIn/DoesNotExist on an undefined custom label is satisfiable
        node = Requirements()
        pod = Requirements(Requirement("example.com/team", Operator.NOT_IN, ["infra"]))
        assert node.compatible(pod, labels.WELL_KNOWN_LABELS) is None

    def test_without_allow_undefined_well_known_denied(self):
        # the strict direction: no allowance set
        node = Requirements()
        pod = Requirements(Requirement(labels.TOPOLOGY_ZONE, Operator.IN, ["zone-1"]))
        assert node.compatible(pod) is not None


class TestIntersects:
    def test_disjoint_errors(self):
        a = Requirements(A_IN("a"))
        b = Requirements(A_IN("b"))
        assert a.intersects(b) is not None

    def test_double_negation_exempt(self):
        # NotIn vs DoesNotExist has empty intersection but is allowed
        # (requirements.go:247-254)
        a = Requirements(DOES_NOT_EXIST())
        b = Requirements(A_NOT_IN("x"))
        assert a.intersects(b) is None

    def test_negative_vs_positive_not_exempt(self):
        a = Requirements(A_IN("x"))
        b = Requirements(DOES_NOT_EXIST())
        assert a.intersects(b) is not None

    def test_non_overlapping_keys_ignored(self):
        a = Requirements(Requirement("k1", Operator.IN, ["a"]))
        b = Requirements(Requirement("k2", Operator.IN, ["b"]))
        assert a.intersects(b) is None


class TestLabelPolicy:
    def test_well_known_is_restricted_node_label(self):
        # reference labels.go:120-138: well-known labels are cloud-provider
        # owned and must not be injected from requirements
        assert labels.is_restricted_node_label(labels.TOPOLOGY_ZONE)
        assert labels.is_restricted_label(labels.TOPOLOGY_ZONE) is None

    def test_unprefixed_key_unrestricted(self):
        # GetLabelDomain returns "" for slash-less keys (labels.go:140-145)
        assert not labels.is_restricted_node_label("my.kubernetes.io")

    def test_restricted_domain(self):
        assert labels.is_restricted_node_label("kubernetes.io/foo")
        assert labels.is_restricted_label("kubernetes.io/foo") is not None

    def test_domain_exception(self):
        assert not labels.is_restricted_node_label("node-restriction.kubernetes.io/team")

    def test_labels_omit_well_known(self):
        reqs = Requirements(
            Requirement(labels.TOPOLOGY_ZONE, Operator.EXISTS),
            Requirement("example.com/team", Operator.IN, ["web"]),
        )
        assert reqs.labels() == {"example.com/team": "web"}


class TestAnyAndLazyErrors:
    def test_any_gt_operator_returns_empty(self):
        # reference Any() only generates values for In/NotIn/Exists
        # (requirement.go:231-247); Gt/Lt return ""
        assert Requirement("key", Operator.GT, [str(2**31)]).any() == ""

    def test_any_not_in_never_crashes(self):
        r = A_NOT_IN("0", "1", "2")
        v = r.any()
        assert v not in {"0", "1", "2"} and v != ""

    def test_any_empty_band_returns_empty(self):
        r = GT(5).intersection(LT(7))  # only "6" allowed... complement band
        assert r.has("6")
        r2 = Requirement("key", Operator.GT, [str(2**62)]).intersection(
            Requirement("key", Operator.LT, [str(2**62 + 1)])
        )
        assert r2.any() == ""

    def test_intersects_error_is_lazy_and_stringable(self):
        a = Requirements(A_IN("a"))
        b = Requirements(A_IN("b"))
        err = a.intersects(b)
        assert err is not None
        assert "key" in str(err) and "not in" in str(err)
