"""Volume topology + CSI volume-limit behavior.

Mirrors the reference's volumetopology.go / volumeusage.go test coverage:
zonal PVCs constrain pods to the volume's zone, missing PVCs exclude pods
from provisioning, and CSI attach limits cap pods per existing node.
"""

import pytest

from karpenter_tpu.api import labels, resources as res
from karpenter_tpu.api.objects import (
    CSINode,
    Node,
    NodeClaim,
    ObjectMeta,
    PersistentVolume,
    PersistentVolumeClaim,
    PersistentVolumeClaimRef,
    StorageClass,
)
from karpenter_tpu.cloudprovider import corpus
from karpenter_tpu.cloudprovider.kwok import KwokCloudProvider
from karpenter_tpu.kube import Client, TestClock
from karpenter_tpu.operator import Operator
from karpenter_tpu.scheduling.volumetopology import VolumeTopology
from karpenter_tpu.scheduling.volumeusage import VolumeResolver, VolumeUsage
from karpenter_tpu.sim import Binder

from helpers import make_nodepool, make_pod


@pytest.fixture
def env():
    clock = TestClock()
    client = Client(clock)
    provider = KwokCloudProvider(client, corpus.generate(20))
    operator = Operator(client, provider)
    binder = Binder(client)
    return clock, client, provider, operator, binder


def provision_cycle(env, n_steps=6):
    clock, client, provider, operator, binder = env
    for _ in range(n_steps):
        operator.step(force_provision=True)
        binder.bind_all()
        clock.step(1)


def pod_with_claim(claim_name, **kwargs):
    pod = make_pod(**kwargs)
    pod.spec.volumes = [PersistentVolumeClaimRef(claim_name=claim_name)]
    return pod


class TestVolumeTopologyInjection:
    def test_bound_pvc_zone_injected(self, env):
        _, client, *_ = env
        client.create(
            PersistentVolume(
                metadata=ObjectMeta(name="pv-1"), zones=("zone-2",), driver="csi.test"
            )
        )
        client.create(
            PersistentVolumeClaim(metadata=ObjectMeta(name="claim-1"), volume_name="pv-1")
        )
        pod = pod_with_claim("claim-1")
        VolumeTopology(client).inject(pod)
        reqs = [
            r
            for term in pod.spec.node_affinity.required
            for r in term
            if r.key == labels.TOPOLOGY_ZONE
        ]
        assert reqs and reqs[0].values == ("zone-2",)

    def test_storage_class_zones_injected_for_unbound_pvc(self, env):
        _, client, *_ = env
        client.create(
            StorageClass(
                metadata=ObjectMeta(name="fast"),
                zones=("zone-1", "zone-3"),
                provisioner="csi.test",
            )
        )
        client.create(
            PersistentVolumeClaim(
                metadata=ObjectMeta(name="claim-1"), storage_class_name="fast"
            )
        )
        pod = pod_with_claim("claim-1")
        VolumeTopology(client).inject(pod)
        reqs = [
            r
            for term in pod.spec.node_affinity.required
            for r in term
            if r.key == labels.TOPOLOGY_ZONE
        ]
        assert reqs and set(reqs[0].values) == {"zone-1", "zone-3"}

    def test_existing_affinity_terms_each_get_zone(self, env):
        _, client, *_ = env
        client.create(
            PersistentVolume(metadata=ObjectMeta(name="pv-1"), zones=("zone-1",))
        )
        client.create(
            PersistentVolumeClaim(metadata=ObjectMeta(name="claim-1"), volume_name="pv-1")
        )
        from karpenter_tpu.api.objects import NodeSelectorRequirement

        pod = pod_with_claim(
            "claim-1",
            requirements=[
                NodeSelectorRequirement(labels.ARCH, "In", ("c",))
            ],
        )
        VolumeTopology(client).inject(pod)
        for term in pod.spec.node_affinity.required:
            assert any(r.key == labels.TOPOLOGY_ZONE for r in term)

    def test_pod_scheduled_into_volume_zone(self, env):
        clock, client, provider, operator, binder = env
        client.create(make_nodepool())
        client.create(
            PersistentVolume(metadata=ObjectMeta(name="pv-1"), zones=("test-zone-b",))
        )
        client.create(
            PersistentVolumeClaim(metadata=ObjectMeta(name="claim-1"), volume_name="pv-1")
        )
        client.create(pod_with_claim("claim-1", cpu="1", memory="1Gi"))
        provision_cycle(env)
        claims = client.list(NodeClaim)
        assert len(claims) == 1
        zone_req = [
            r for r in claims[0].spec.requirements if r.key == labels.TOPOLOGY_ZONE
        ]
        assert zone_req and set(zone_req[0].values) <= {"test-zone-b"}

    def test_missing_pvc_excludes_pod(self, env):
        clock, client, provider, operator, binder = env
        client.create(make_nodepool())
        client.create(pod_with_claim("no-such-claim"))
        provision_cycle(env)
        assert client.list(NodeClaim) == []

    def test_missing_storage_class_excludes_pod(self, env):
        clock, client, provider, operator, binder = env
        client.create(make_nodepool())
        client.create(
            PersistentVolumeClaim(
                metadata=ObjectMeta(name="claim-1"), storage_class_name="no-such-sc"
            )
        )
        client.create(pod_with_claim("claim-1"))
        provision_cycle(env)
        assert client.list(NodeClaim) == []


class TestVolumeUsage:
    def test_limit_enforced(self):
        usage = VolumeUsage()
        limits = {"csi.test": 2}
        p1 = make_pod()
        usage.add(p1, [("csi.test", "vol-1"), ("csi.test", "vol-2")])
        assert usage.validate([("csi.test", "vol-3")], limits) is not None
        # an already-attached volume doesn't count again
        assert usage.validate([("csi.test", "vol-2")], limits) is None
        # other drivers are unaffected
        assert usage.validate([("csi.other", "vol-9")], limits) is None

    def test_delete_pod_releases_unshared_volumes(self):
        usage = VolumeUsage()
        p1, p2 = make_pod(), make_pod()
        usage.add(p1, [("d", "shared"), ("d", "own-1")])
        usage.add(p2, [("d", "shared")])
        usage.delete_pod(p1.uid)
        assert usage.validate([("d", "own-1")], {"d": 2}) is None  # re-addable
        # shared volume is still attached via p2
        assert usage.validate([("d", "x"), ("d", "y")], {"d": 2}) is not None

    def test_existing_node_respects_csi_limit(self, env):
        clock, client, provider, operator, binder = env
        client.create(make_nodepool())
        client.create(
            StorageClass(metadata=ObjectMeta(name="fast"), provisioner="csi.test")
        )
        for i in range(3):
            client.create(
                PersistentVolumeClaim(
                    metadata=ObjectMeta(name=f"claim-{i}"), storage_class_name="fast"
                )
            )
        # first pod lands on a fresh node
        client.create(pod_with_claim("claim-0", cpu="1", memory="1Gi"))
        provision_cycle(env)
        nodes = client.list(Node)
        assert len(nodes) == 1
        # driver allows only 1 volume on this node
        client.create(
            CSINode(
                metadata=ObjectMeta(name=nodes[0].name),
                driver_limits={"csi.test": 1},
            )
        )
        # second volume pod can't fit on the node despite free cpu/memory
        client.create(pod_with_claim("claim-1", cpu="1", memory="1Gi"))
        provision_cycle(env)
        assert len(client.list(Node)) == 2

    def test_resolver_missing_pvc_errors(self, env):
        _, client, *_ = env
        resolver = VolumeResolver(client)
        _, err = resolver.resolve(pod_with_claim("absent"))
        assert err is not None

    def test_rebind_retracts_previous_volume_identity(self):
        # a PVC binding changes its volume id from ns/claim to the PV name;
        # re-adding the pod must not leak the old id into the driver count
        usage = VolumeUsage()
        pod = make_pod()
        usage.add(pod, [("d", "default/claim-1")])
        usage.add(pod, [("d", "pv-1")])  # PVC bound
        assert usage.validate([("d", "pv-2")], {"d": 2}) is None

    def test_cluster_scoped_lookup_ignores_pod_namespace(self, env):
        # PV/SC are cluster-scoped: a pod in another namespace still resolves
        _, client, *_ = env
        client.create(
            StorageClass(
                metadata=ObjectMeta(name="fast"),
                zones=("zone-9",),
                provisioner="csi.test",
            )
        )
        client.create(
            PersistentVolumeClaim(
                metadata=ObjectMeta(name="claim-1", namespace="prod"),
                storage_class_name="fast",
            )
        )
        pod = pod_with_claim("claim-1")
        pod.metadata.namespace = "prod"
        vt = VolumeTopology(client)
        assert vt.validate_persistent_volume_claims(pod) is None
        vt.inject(pod)
        reqs = [
            r
            for term in pod.spec.node_affinity.required
            for r in term
            if r.key == labels.TOPOLOGY_ZONE
        ]
        assert reqs and reqs[0].values == ("zone-9",)
        resolved, err = VolumeResolver(client).resolve(pod)
        assert err is None and len(resolved) == 1
        assert resolved[0].driver == "csi.test"
        assert resolved[0].volume_id == "prod/claim-1"
        assert resolved[0].zones == ("zone-9",)
