"""Round-trip tests for the declarative schema artifacts (api/schema.py).

Two guarantees, mirroring the reference's generated-CRD discipline
(pkg/apis/crds/*.yaml is regenerated and diffed in CI):

1. The checked-in YAML artifacts match a fresh generation — schema drift
   without regeneration fails.
2. The artifact's rule CONTENT agrees with the runtime validator
   (api/validation.py): the same constants, and the same accept/reject
   verdicts on behavioral probes.
"""

import json
import os
import re

import yaml

from karpenter_tpu.api import labels as labels_mod
from karpenter_tpu.api import schema as schema_mod
from karpenter_tpu.api import validation as val
from karpenter_tpu.api.objects import (
    Budget, NodeSelectorRequirement, Taint,
)


def _load(name):
    with open(os.path.join(schema_mod.CRD_DIR, name)) as fh:
        return fh.read()


class TestArtifactsUpToDate:
    def test_regeneration_matches_checked_in(self, tmp_path):
        generated = schema_mod.generate(str(tmp_path))
        for name, text in generated.items():
            assert _load(name) == text, (
                f"{name} is stale — run `python -m karpenter_tpu.api.schema`"
            )


class TestRuleContentMatchesValidator:
    def setup_method(self):
        self.np_schema = yaml.safe_load(_load("karpenter_tpu_nodepools.yaml"))
        self.nc_schema = yaml.safe_load(_load("karpenter_tpu_nodeclaims.yaml"))

    def _req_schema(self, root):
        props = root["spec"]["properties"]
        if "template" in props:
            return (
                props["template"]["properties"]["spec"]["properties"]
                ["requirements"]["items"]
            )
        return props["requirements"]["items"]

    def test_operator_enum_matches(self):
        enum = self._req_schema(self.np_schema)["properties"]["operator"]["enum"]
        assert set(enum) == set(val.SUPPORTED_OPERATORS)

    def test_taint_effects_match(self):
        taints = (
            self.np_schema["spec"]["properties"]["template"]["properties"]
            ["spec"]["properties"]["taints"]
        )
        enum = taints["items"]["properties"]["effect"]["enum"]
        # the validator's accepted effects (validation.py:_validate_taints)
        for effect in enum:
            errs = val._validate_taints(
                [Taint(key="k", value="v", effect=effect)], "taints"
            )
            assert not errs
        errs = val._validate_taints(
            [Taint(key="k", value="v", effect="Bogus")], "taints"
        )
        assert errs

    def test_budget_nodes_pattern_matches(self):
        budget = (
            self.np_schema["spec"]["properties"]["disruption"]["properties"]
            ["budgets"]["items"]
        )
        pattern = re.compile(budget["properties"]["nodes"]["pattern"])
        for nodes, ok in (
            ("10", True), ("100%", True), ("0%", True), ("55%", True),
            ("101%", False), ("-1", False), ("ten", False),
        ):
            b = Budget(nodes=nodes)
            assert bool(pattern.match(nodes)) == ok
            assert (not val._validate_budget(b)) == ok

    def test_schedule_duration_pairing_rule(self):
        budget = (
            self.np_schema["spec"]["properties"]["disruption"]["properties"]
            ["budgets"]["items"]
        )
        rules = [r["rule"] for r in budget["x-validations"]]
        assert any("schedule" in r and "duration" in r for r in rules)
        assert val._validate_budget(Budget(nodes="10", schedule="@daily"))
        assert not val._validate_budget(
            Budget(nodes="10", schedule="@daily", duration="4h")
        )

    def test_weight_bounds_match(self):
        w = self.np_schema["spec"]["properties"]["weight"]
        assert (w["minimum"], w["maximum"]) == (1, 100)
        from karpenter_tpu.solver.example import example_nodepool

        pool = example_nodepool()
        pool.spec.weight = 0
        assert any("weight" in e for e in val.validate_node_pool(pool))
        pool.spec.weight = 100
        assert not any("weight" in e for e in val.validate_node_pool(pool))

    def test_restricted_domains_match(self):
        req = self._req_schema(self.np_schema)
        restricted_rule = next(
            r for r in req["x-validations"] if "x-restricted-domains" in r
        )
        assert set(restricted_rule["x-restricted-domains"]) == set(
            labels_mod.RESTRICTED_LABEL_DOMAINS
        )
        assert set(restricted_rule["x-domain-exceptions"]) == set(
            labels_mod.LABEL_DOMAIN_EXCEPTIONS
        )

    def test_requirement_behavior_probes(self):
        """jsonschema-validatable subset agrees with validate_requirement."""
        import jsonschema

        req_schema = dict(self._req_schema(self.nc_schema))
        # the x-* extensions are CEL analogs; the structural subset is
        # directly jsonschema-checkable
        probes = [
            ({"key": "k", "operator": "In", "values": ["a"]}, True),
            ({"key": "k", "operator": "Bogus", "values": []}, False),
            ({"key": "k", "operator": "Exists", "values": [],
              "minValues": 0}, False),  # minValues >= 1
        ]
        for obj, ok in probes:
            try:
                jsonschema.validate(obj, req_schema)
                valid = True
            except jsonschema.ValidationError:
                valid = False
            assert valid == ok, obj
        # runtime validator agrees on the operator probe
        assert not val.validate_requirement(
            NodeSelectorRequirement("k", "In", ("a",))
        )
        assert val.validate_requirement(
            NodeSelectorRequirement("k", "Bogus", ())
        )

    def test_min_values_rule_agrees(self):
        errs = val.validate_requirement(
            NodeSelectorRequirement("k", "In", ("a",), min_values=2)
        )
        assert errs
        rules = [
            r["rule"] for r in self._req_schema(self.nc_schema)["x-validations"]
        ]
        assert any("minValues" in r for r in rules)
