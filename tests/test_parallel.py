"""Multi-chip sharding tests over the virtual 8-device CPU mesh."""

import jax
import numpy as np
import pytest

from karpenter_tpu.parallel.mesh import make_mesh, sharded_solve_fn
from karpenter_tpu.ops.solve import solve_all


def _example(n_pods=64, n_types=16, shapes=8):
    from karpenter_tpu.solver.example import example_snapshot_arrays

    return example_snapshot_arrays(n_pods=n_pods, n_types=n_types, shapes=shapes)


@pytest.fixture(scope="module")
def mesh():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    return make_mesh(8)


class TestMesh:
    def test_mesh_shape(self, mesh):
        assert mesh.axis_names == ("data", "model")
        assert int(np.prod(mesh.devices.shape)) == 8

    def test_sharded_matches_single_device(self, mesh):
        import __graft_entry__ as graft

        args, statics = _example()
        single = solve_all(*args, **statics)
        padded = graft._pad_for_mesh(args, mesh)
        fn = sharded_solve_fn(mesh, **statics)
        with mesh:
            sharded = fn(*padded)
        # claims opened and per-group placement identical
        assert int(single[2]) == int(sharded[2])
        np.testing.assert_array_equal(
            np.asarray(single[6]), np.asarray(sharded[6])[: np.asarray(single[6]).shape[0]]
        )

    def test_dryrun_entrypoint(self, mesh):
        import __graft_entry__ as graft

        graft.dryrun_multichip(8)


class TestEntry:
    def test_entry_compiles_and_runs(self):
        import __graft_entry__ as graft

        fn, args = graft.entry()
        out = jax.jit(fn)(*args)
        jax.block_until_ready(out)
        assert int(out[2]) > 0
