"""Multi-chip sharding tests over the virtual 8-device CPU mesh."""

import jax
import numpy as np
import pytest

from karpenter_tpu.parallel.mesh import make_mesh, pad_args_for_mesh, sharded_solve_fn
from karpenter_tpu.ops.solve import solve_all


def _example(n_pods=64, n_types=16, shapes=8):
    from karpenter_tpu.solver.example import example_snapshot_arrays

    return example_snapshot_arrays(n_pods=n_pods, n_types=n_types, shapes=shapes)


@pytest.fixture(scope="module")
def mesh():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    return make_mesh(8)


class TestMesh:
    def test_mesh_shape(self, mesh):
        assert mesh.axis_names == ("data", "model")
        assert int(np.prod(mesh.devices.shape)) == 8

    def _assert_full_equality(self, single, sharded, n_groups):
        """ALL solver outputs agree between the single-device and sharded
        programs: pool ids, type masks, fills, unplaced, domain pins,
        reservation flags (round-2 gap: only claim count + unplaced were
        checked)."""
        n_open = int(single[2])
        assert n_open == int(sharded[2])
        assert bool(single[3]) == bool(sharded[3])
        g = n_groups
        for idx, name in (
            (0, "c_pool"), (1, "c_tmask"), (7, "c_dzone"), (8, "c_dct"),
            (9, "c_resv"),
        ):
            a = np.asarray(single[idx])[:n_open]
            b = np.asarray(sharded[idx])[:n_open]
            np.testing.assert_array_equal(a, b, err_msg=name)
        for idx, name in ((4, "exist_fills"), (5, "claim_fills"), (6, "unplaced")):
            a = np.asarray(single[idx])
            b = np.asarray(sharded[idx])[:g] if np.asarray(sharded[idx]).ndim else np.asarray(sharded[idx])
            np.testing.assert_array_equal(a, b[: a.shape[0]], err_msg=name)

    def test_sharded_matches_single_device(self, mesh):
        args, statics = _example()
        single = solve_all(*args, **statics)
        padded = pad_args_for_mesh(args, mesh)
        fn = sharded_solve_fn(mesh, **statics)
        with mesh:
            sharded = fn(*padded)
        self._assert_full_equality(single, sharded, args[0].shape[0])

    def test_sharded_matches_single_device_many_groups(self, mesh):
        """G far beyond the data axis (hundreds of groups over data=2):
        every output must still match the single-device program exactly."""
        from karpenter_tpu.api import resources as res
        from karpenter_tpu.api.objects import ObjectMeta, Pod, PodSpec
        from karpenter_tpu.cloudprovider import corpus
        from karpenter_tpu.kube import Client, TestClock
        from karpenter_tpu.scheduling.topology import Topology
        from karpenter_tpu.solver import TpuSolver
        from karpenter_tpu.solver import encode as enc
        from karpenter_tpu.solver.example import example_nodepool

        # 400 genuinely distinct request shapes -> 400 groups
        pods = [
            Pod(
                metadata=ObjectMeta(name=f"g-{i}"),
                spec=PodSpec(
                    requests={
                        res.CPU: (100 + i) * res.MILLI // 100,
                        res.MEMORY: (64 + i) * 2**20 * res.MILLI,
                    }
                ),
            )
            for i in range(400)
        ]
        pools = [example_nodepool()]
        its = {pools[0].name: corpus.generate(24)}
        topology = Topology(Client(TestClock()), [], pools, its, pods)
        solver = TpuSolver(pools, its, topology)
        groups, rest = enc.partition_and_group(pods, topology=topology)
        assert not rest
        templates = solver.oracle.templates
        snap = enc.encode(
            groups, templates,
            {t.node_pool_name: t.instance_type_options for t in templates},
            daemon_overhead=solver.oracle.daemon_overhead,
        )
        a_tzc, res_cap0, a_res = solver._offering_availability(snap)
        nmax = solver._estimate_nmax(snap, solver._fit_matrix(snap))
        statics = dict(
            nmax=nmax, zone_kid=snap.zone_kid, ct_kid=snap.ct_kid,
            has_domains=False,
        )
        args = snap.solve_args(a_tzc, res_cap0, a_res)
        G = args[0].shape[0]
        assert G >= 300
        single = solve_all(*args, **statics)
        padded = pad_args_for_mesh(args, mesh)
        fn = sharded_solve_fn(mesh, **statics)
        with mesh:
            sharded = fn(*padded)
        self._assert_full_equality(single, sharded, G)

    def test_driver_mesh_matches_single_device(self, mesh):
        """THROUGH THE DRIVER: TpuSolver with SolverConfig(mesh=...) must
        produce identical Results (claims, pods, types, requirements,
        errors) to the single-device TpuSolver, at G >> data axis."""
        from karpenter_tpu.cloudprovider import corpus
        from karpenter_tpu.kube import Client, TestClock
        from karpenter_tpu.scheduling.topology import Topology
        from karpenter_tpu.solver import TpuSolver
        from karpenter_tpu.solver.driver import SolverConfig
        from karpenter_tpu.solver.example import example_nodepool
        from karpenter_tpu.solver.workloads import constrained_mix

        # constrained mix: zonal + hostname spread ride the domain-quota
        # and per-entity-cap kernel paths under GSPMD
        pods = constrained_mix(600)
        pools = [example_nodepool()]
        its_by_pool = {p.name: corpus.generate(24) for p in pools}

        def solve(cfg):
            topology = Topology(
                Client(TestClock()), [], pools, its_by_pool, pods
            )
            return TpuSolver(
                pools, its_by_pool, topology, config=cfg
            ).solve(pods)

        single = solve(SolverConfig())
        sharded = solve(SolverConfig(mesh=mesh))
        assert not single.pod_errors and not sharded.pod_errors
        assert single.node_count() == sharded.node_count()
        a = sorted(
            (
                c.template.node_pool_name,
                tuple(sorted(p.uid for p in c.pods)),
                tuple(sorted(t.name for t in c.instance_type_options)),
                repr(sorted(c.requirements.keys())),
            )
            for c in single.new_node_claims
        )
        b = sorted(
            (
                c.template.node_pool_name,
                tuple(sorted(p.uid for p in c.pods)),
                tuple(sorted(t.name for t in c.instance_type_options)),
                repr(sorted(c.requirements.keys())),
            )
            for c in sharded.new_node_claims
        )
        assert a == b

    def test_driver_mesh_matches_single_device_10k(self, mesh):
        """North-star-scale through the driver (VERDICT r4 #3): 10k
        constrained pods over the full 8-device mesh must produce Results
        identical to single-device — same claims, same pod assignment,
        same types."""
        from karpenter_tpu.cloudprovider import corpus
        from karpenter_tpu.kube import Client, TestClock
        from karpenter_tpu.scheduling.topology import Topology
        from karpenter_tpu.solver import TpuSolver
        from karpenter_tpu.solver.driver import EncodeCache, SolverConfig
        from karpenter_tpu.solver.example import example_nodepool
        from karpenter_tpu.solver.workloads import constrained_mix

        pods = constrained_mix(10_000)
        pools = [example_nodepool()]
        its_by_pool = {p.name: corpus.generate(100) for p in pools}
        cache = EncodeCache()

        def solve(cfg):
            topology = Topology(
                Client(TestClock()), [], pools, its_by_pool, pods
            )
            return TpuSolver(
                pools, its_by_pool, topology, config=cfg, encode_cache=cache
            ).solve(pods)

        single = solve(SolverConfig())
        sharded = solve(SolverConfig(mesh=mesh))
        assert not single.pod_errors and not sharded.pod_errors
        assert single.node_count() == sharded.node_count()
        a = sorted(
            (tuple(sorted(p.uid for p in c.pods)),
             tuple(sorted(t.name for t in c.instance_type_options)))
            for c in single.new_node_claims
        )
        b = sorted(
            (tuple(sorted(p.uid for p in c.pods)),
             tuple(sorted(t.name for t in c.instance_type_options)))
            for c in sharded.new_node_claims
        )
        assert a == b

    def test_dryrun_entrypoint(self, mesh):
        import __graft_entry__ as graft

        graft.dryrun_multichip(8)


class TestEntry:
    def test_entry_compiles_and_runs(self):
        import __graft_entry__ as graft

        fn, args = graft.entry()
        out = jax.jit(fn)(*args)
        jax.block_until_ready(out)
        assert int(out[2]) > 0
