"""Multi-chip sharding tests over the virtual 8-device CPU mesh.

The r06 layout (parallel/mesh.py): scenario-major consolidation, the
segment live-pair axis on 'data', types on 'model', group/node state
replicated so the sequential packing scan never pays per-step collectives
— pinned structurally on the compiled HLO, not on wall-clock."""

import jax
import numpy as np
import pytest

from karpenter_tpu.ops.solve import solve_all
from karpenter_tpu.parallel.mesh import (
    ARG_SPECS,
    make_mesh,
    pad_args_for_mesh,
    scan_collective_report,
    scenario_mesh,
    sharded_scenarios_fn,
    sharded_solve_fn,
    sharded_solve_packed_fn,
)
from karpenter_tpu.solver.encode import SOLVE_ARG_NAMES


def _example(n_pods=64, n_types=16, shapes=8):
    from karpenter_tpu.solver.example import example_snapshot_arrays

    return example_snapshot_arrays(n_pods=n_pods, n_types=n_types, shapes=shapes)


@pytest.fixture(scope="module")
def mesh():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    return make_mesh(8)


def _claim_key(results):
    return sorted(
        (
            tuple(sorted(p.metadata.name for p in c.pods)),
            tuple(sorted(t.name for t in c.instance_type_options)),
        )
        for c in results.new_node_claims
    )


class TestMesh:
    def test_mesh_shape(self, mesh):
        assert mesh.axis_names == ("scenario", "data", "model")
        assert int(np.prod(mesh.devices.shape)) == 8
        # the measured default: every device on the segment ('data') axis,
        # the only single-solve factorization with a collective-free scan
        assert dict(zip(mesh.axis_names, mesh.devices.shape)) == {
            "scenario": 1, "data": 8, "model": 1,
        }

    def test_arg_specs_cover_solve_args(self):
        assert set(ARG_SPECS) == set(SOLVE_ARG_NAMES)
        # the fixed layout: group- and node-major state replicated (the
        # scan reads/carries it), the segment index on 'data', types on
        # 'model' — a g_*/n_* entry growing a mesh axis is the r05
        # regression coming back
        for name, spec in ARG_SPECS.items():
            if name.startswith(("g_", "n_")) or name in (
                "nh_cnt0", "dd0", "dtg_key", "well_known",
            ):
                assert all(s is None for s in spec), (name, spec)
        for name in ("gk_g", "gk_k", "gk_w"):
            assert ARG_SPECS[name] == ("data",)
        for name in ("t_def", "t_mask", "t_alloc", "t_cap", "t_mvoh"):
            assert ARG_SPECS[name] == ("model",)

    def _assert_full_equality(self, single, sharded, n_groups):
        """ALL solver outputs agree between the single-device and sharded
        programs: pool ids, type masks, fills, unplaced, domain pins,
        reservation flags (round-2 gap: only claim count + unplaced were
        checked)."""
        n_open = int(single[2])
        assert n_open == int(sharded[2])
        assert bool(single[3]) == bool(sharded[3])
        g = n_groups
        for idx, name in (
            (0, "c_pool"), (1, "c_tmask"), (7, "c_dzone"), (8, "c_dct"),
            (9, "c_resv"),
        ):
            a = np.asarray(single[idx])[:n_open]
            b = np.asarray(sharded[idx])[:n_open]
            np.testing.assert_array_equal(a, b, err_msg=name)
        for idx, name in ((4, "exist_fills"), (5, "claim_fills"), (6, "unplaced")):
            a = np.asarray(single[idx])
            b = np.asarray(sharded[idx])[:g] if np.asarray(sharded[idx]).ndim else np.asarray(sharded[idx])
            np.testing.assert_array_equal(a, b[: a.shape[0]], err_msg=name)

    def test_sharded_matches_single_device(self, mesh):
        args, statics = _example()
        single = solve_all(*args, **statics)
        padded = pad_args_for_mesh(args, mesh)
        fn = sharded_solve_fn(mesh, **statics)
        with mesh:
            sharded = fn(*padded)
        self._assert_full_equality(single, sharded, args[0].shape[0])

    def test_every_factorization_matches(self, mesh):
        """Every (scenario=1, data, model) factorization of 8 devices —
        including the mixed ones and the sparse segment path — produces
        the single-device outputs exactly."""
        args, statics = _example()
        statics = dict(statics, sparse_groups=True)
        single = solve_all(*args, **statics)
        for data in (1, 2, 4, 8):
            m = make_mesh(8, data=data)
            padded = pad_args_for_mesh(args, m)
            fn = sharded_solve_fn(m, **statics)
            with m:
                sharded = fn(*padded)
            self._assert_full_equality(single, sharded, args[0].shape[0])

    def test_sharded_matches_single_device_many_groups(self, mesh):
        """G far beyond the old data-axis semantics (hundreds of groups):
        every output must still match the single-device program exactly."""
        from karpenter_tpu.api import resources as res
        from karpenter_tpu.api.objects import ObjectMeta, Pod, PodSpec
        from karpenter_tpu.cloudprovider import corpus
        from karpenter_tpu.kube import Client, TestClock
        from karpenter_tpu.scheduling.topology import Topology
        from karpenter_tpu.solver import TpuSolver
        from karpenter_tpu.solver import encode as enc
        from karpenter_tpu.solver.example import example_nodepool

        # 400 genuinely distinct request shapes -> 400 groups
        pods = [
            Pod(
                metadata=ObjectMeta(name=f"g-{i}"),
                spec=PodSpec(
                    requests={
                        res.CPU: (100 + i) * res.MILLI // 100,
                        res.MEMORY: (64 + i) * 2**20 * res.MILLI,
                    }
                ),
            )
            for i in range(400)
        ]
        pools = [example_nodepool()]
        its = {pools[0].name: corpus.generate(24)}
        topology = Topology(Client(TestClock()), [], pools, its, pods)
        solver = TpuSolver(pools, its, topology)
        groups, rest = enc.partition_and_group(pods, topology=topology)
        assert not rest
        templates = solver.oracle.templates
        snap = enc.encode(
            groups, templates,
            {t.node_pool_name: t.instance_type_options for t in templates},
            daemon_overhead=solver.oracle.daemon_overhead,
        )
        a_tzc, res_cap0, a_res = solver._offering_availability(snap)
        nmax = solver._estimate_nmax(snap, solver._fit_matrix(snap))
        statics = dict(
            nmax=nmax, zone_kid=snap.zone_kid, ct_kid=snap.ct_kid,
            has_domains=False, sparse_groups=True,
        )
        args = snap.solve_args(a_tzc, res_cap0, a_res)
        G = args[0].shape[0]
        assert G >= 300
        single = solve_all(*args, **statics)
        padded = pad_args_for_mesh(args, mesh)
        fn = sharded_solve_fn(mesh, **statics)
        with mesh:
            sharded = fn(*padded)
        self._assert_full_equality(single, sharded, G)

    def test_driver_mesh_matches_single_device(self, mesh):
        """THROUGH THE DRIVER: TpuSolver with SolverConfig(mesh=...) must
        produce identical Results (claims, pods, types, requirements,
        errors) to the single-device TpuSolver, at G >> data axis."""
        from karpenter_tpu.cloudprovider import corpus
        from karpenter_tpu.kube import Client, TestClock
        from karpenter_tpu.scheduling.topology import Topology
        from karpenter_tpu.solver import TpuSolver
        from karpenter_tpu.solver.driver import SolverConfig
        from karpenter_tpu.solver.example import example_nodepool
        from karpenter_tpu.solver.workloads import constrained_mix

        # constrained mix: zonal + hostname spread ride the domain-quota
        # and per-entity-cap kernel paths under GSPMD
        pods = constrained_mix(600)
        pools = [example_nodepool()]
        its_by_pool = {p.name: corpus.generate(24) for p in pools}

        def solve(cfg):
            topology = Topology(
                Client(TestClock()), [], pools, its_by_pool, pods
            )
            return TpuSolver(
                pools, its_by_pool, topology, config=cfg
            ).solve(pods)

        single = solve(SolverConfig())
        sharded = solve(SolverConfig(mesh=mesh))
        assert not single.pod_errors and not sharded.pod_errors
        assert single.node_count() == sharded.node_count()
        assert _claim_key(single) == _claim_key(sharded)

    def test_driver_mesh_matches_single_device_10k(self, mesh):
        """North-star-scale through the driver (VERDICT r4 #3): 10k
        constrained pods over the full 8-device mesh must produce Results
        identical to single-device — same claims, same pod assignment,
        same types."""
        from karpenter_tpu.cloudprovider import corpus
        from karpenter_tpu.kube import Client, TestClock
        from karpenter_tpu.scheduling.topology import Topology
        from karpenter_tpu.solver import TpuSolver
        from karpenter_tpu.solver.driver import EncodeCache, SolverConfig
        from karpenter_tpu.solver.example import example_nodepool
        from karpenter_tpu.solver.workloads import constrained_mix

        pods = constrained_mix(10_000)
        pools = [example_nodepool()]
        its_by_pool = {p.name: corpus.generate(100) for p in pools}
        cache = EncodeCache()

        def solve(cfg):
            topology = Topology(
                Client(TestClock()), [], pools, its_by_pool, pods
            )
            return TpuSolver(
                pools, its_by_pool, topology, config=cfg, encode_cache=cache
            ).solve(pods)

        single = solve(SolverConfig())
        sharded = solve(SolverConfig(mesh=mesh))
        assert not single.pod_errors and not sharded.pod_errors
        assert single.node_count() == sharded.node_count()
        assert _claim_key(single) == _claim_key(sharded)

    def test_dense_mesh_refactorizes_for_sparse_off(self, mesh, monkeypatch):
        """With the sparse segment path off (KTPU_SPARSE_FEAS=0, the
        tiled-mode shape), 'data' sharding would shard only the unused
        gk_* index — the driver must re-factorize the devices onto
        'model' (the dense layout that actually shards the type tables)
        and still match single-device decisions."""
        from karpenter_tpu.parallel.mesh import dense_mesh
        from karpenter_tpu.solver.driver import EncodeCache, SolverConfig

        dm = dense_mesh(mesh)
        assert dict(zip(dm.axis_names, dm.devices.shape)) == {
            "scenario": 1, "data": 1, "model": 8,
        }
        assert dense_mesh(dm) is dm  # already dense: identity

        monkeypatch.setenv("KTPU_SPARSE_FEAS", "0")
        from karpenter_tpu.cloudprovider import corpus
        from karpenter_tpu.kube import Client, TestClock
        from karpenter_tpu.scheduling.topology import Topology
        from karpenter_tpu.solver import TpuSolver
        from karpenter_tpu.solver.example import example_nodepool
        from karpenter_tpu.solver.workloads import mixed_pods

        pods = mixed_pods(300, gpu_fraction=0.0)
        pools = [example_nodepool()]
        its_by_pool = {p.name: corpus.generate(16) for p in pools}

        def solve(cfg, cache):
            topology = Topology(
                Client(TestClock()), [], pools, its_by_pool, pods
            )
            s = TpuSolver(
                pools, its_by_pool, topology, config=cfg,
                encode_cache=cache,
            )
            return s, s.solve(pods)

        cache = EncodeCache()
        _, r_mesh = solve(SolverConfig(mesh=mesh), cache)
        _, r_one = solve(SolverConfig(), EncodeCache())
        assert _claim_key(r_mesh) == _claim_key(r_one)
        # the staged buffers live on the DENSE re-factorization, not the
        # data-major base mesh
        assert cache.device_store._mesh_key == dm

    def test_dryrun_entrypoint(self, mesh):
        import __graft_entry__ as graft

        graft.dryrun_multichip(8)


class TestScanStructure:
    """The per-scan-step-collectives regression, pinned on dispatch
    STRUCTURE (compiled HLO), not wall-clock — CPU CI cannot flake it."""

    def test_scan_body_has_no_collectives(self, mesh):
        """The default (data-major) layout: the sharded feasibility stage
        folds into replicated tables at the scan boundary, and the
        while-loop bodies of the packing scan carry ZERO collective ops.
        (r05 measured the opposite layout at 12x single-device: the scan
        paid an all-gather per step.)"""
        args, statics = _example()
        statics = dict(statics, sparse_groups=True)
        padded = pad_args_for_mesh(args, mesh)
        fn = sharded_solve_fn(mesh, **statics)
        report = scan_collective_report(
            fn.lower(*padded).compile().as_text()
        )
        assert report["computations"] > 0
        assert report["scan_computations"] > 0, "no while loop found"
        # the feasibility stage DOES communicate (segment sums fold over
        # the sharded live-pair axis) — proves the parse sees collectives
        assert report["collectives_total"] > 0
        assert report["collectives_in_scan"] == 0, report["offenders"]

    def test_scenario_dispatch_scan_is_local(self, mesh):
        """The scenario-major mesh: each scenario shard runs the whole
        solve locally; its scan bodies carry zero collectives too."""
        import jax.numpy as jnp

        args, statics = _example()
        statics = dict(statics, sparse_groups=True)
        smesh = scenario_mesh(mesh, 8)
        assert dict(zip(smesh.axis_names, smesh.devices.shape)) == {
            "scenario": 8, "data": 1, "model": 1,
        }
        # model sharding is never folded away by the scenario
        # re-factorization: its HBM-headroom purpose (catalogs too large
        # for one chip) must survive a consolidation search
        model_mesh = make_mesh(8, data=1)
        assert dict(
            zip(model_mesh.axis_names, model_mesh.devices.shape)
        ) == {"scenario": 1, "data": 1, "model": 8}
        sm = scenario_mesh(model_mesh, 8)
        assert dict(zip(sm.axis_names, sm.devices.shape)) == {
            "scenario": 1, "data": 1, "model": 8,
        }
        S = 8
        g_count_s = np.repeat(np.asarray(args[0])[None], S, axis=0)
        idx_n_tol = SOLVE_ARG_NAMES.index("n_tol")
        n_tol_s = np.repeat(np.asarray(args[idx_n_tol])[None], S, axis=0)
        sargs = list(pad_args_for_mesh(args, smesh))
        sargs[0] = g_count_s
        sargs[idx_n_tol] = n_tol_s
        fn = sharded_scenarios_fn(
            smesh, jnp.int32, False, **statics
        )
        report = scan_collective_report(
            fn.lower(*sargs).compile().as_text()
        )
        assert report["scan_computations"] > 0
        # the scenario axis's only in-scan communication is the scalar
        # "are all shards done" trip vote (O(1) bytes per step) — zero
        # DATA collectives, which is what the r05 regression was made of
        assert report["collectives_in_scan_data"] == 0, report["offenders"]
        # parity of the sharded scenario outputs against the plain solve
        single = solve_all(*args, **statics)
        with smesh:
            out = fn(*sargs)
        n_open = int(single[2])
        for si in range(S):
            assert int(np.asarray(out[2])[si]) == n_open

    def test_scenario_mixed_factorization_scan_is_local(self, mesh):
        """A scenario mesh that RETAINS data>1 (devices exceed the
        scenario bucket, e.g. 16 devices / 8 scenarios): the sharded
        feasibility tables must still fold at the scan boundary — without
        the table constraint on the scenario program this pays the r05
        all-gather every scan step."""
        import jax.numpy as jnp

        args, statics = _example()
        statics = dict(statics, sparse_groups=True)
        smesh = make_mesh(8, data=2, scenario=4)
        assert dict(zip(smesh.axis_names, smesh.devices.shape)) == {
            "scenario": 4, "data": 2, "model": 1,
        }
        S = 8
        idx_n_tol = SOLVE_ARG_NAMES.index("n_tol")
        sargs = list(pad_args_for_mesh(args, smesh))
        sargs[0] = np.repeat(np.asarray(args[0])[None], S, axis=0)
        sargs[idx_n_tol] = np.repeat(
            np.asarray(args[idx_n_tol])[None], S, axis=0
        )
        fn = sharded_scenarios_fn(smesh, jnp.int32, False, **statics)
        report = scan_collective_report(
            fn.lower(*sargs).compile().as_text()
        )
        assert report["scan_computations"] > 0
        assert report["collectives_in_scan_data"] == 0, report["offenders"]
        single = solve_all(*args, **statics)
        with smesh:
            out = fn(*sargs)
        for si in range(S):
            assert int(np.asarray(out[2])[si]) == int(single[2])


class TestDeltaApplySharded:
    """delta_apply_rows on mesh-resident buffers: global row index ->
    (shard, local row), applied shard-locally."""

    def test_delta_apply_shard_local(self, mesh):
        from jax.sharding import NamedSharding, PartitionSpec as P

        from karpenter_tpu.ops import solve as ops_solve

        rng = np.random.default_rng(11)
        host = rng.standard_normal((64, 16)).astype(np.float32)
        arr = jax.device_put(host, NamedSharding(mesh, P("data")))
        idx = np.asarray([0, 5, 9, 17, 33, 34, 63], np.int32)
        rows = rng.standard_normal((len(idx), 16)).astype(np.float32)
        out = ops_solve.delta_apply_rows(arr, idx, rows)
        want = host.copy()
        want[idx] = rows
        np.testing.assert_array_equal(np.asarray(out), want)
        # the update keeps the buffer's sharding (the next dispatch reuses
        # it without a reshard)
        assert out.sharding.spec == arr.sharding.spec
        # structural: the compiled shard-local apply has NO collectives
        lidx, lrows, live = ops_solve._decompose_rows_by_shard(
            idx, rows, host.shape[0] // 8, 8
        )
        fn = ops_solve._apply_rows_shard_fn(mesh, "data", donate=False)
        report = scan_collective_report(
            fn.lower(arr, lidx, lrows, live).compile().as_text()
        )
        assert report["collectives_total"] == 0, report["offenders"]

    def test_delta_apply_row_zero_with_padding(self, mesh):
        """A real update to a shard's LOCAL ROW 0 while another shard
        carries more rows (so this shard's bucket has padding slots):
        padding must be idempotent repeats of the shard's own first
        entry, never masked rewrites of the current row-0 value — under
        duplicate-index scatter the old value could win and silently
        revert the delta."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        from karpenter_tpu.ops.solve import delta_apply_rows

        rng = np.random.default_rng(5)
        host = rng.standard_normal((64, 4)).astype(np.float32)
        arr = jax.device_put(host, NamedSharding(mesh, P("data")))
        # shard 0 (block 0..7): only row 0 -> 3 padding slots in a
        # bucket of 4; shard 1 (block 8..15): four rows, fills the bucket
        idx = np.asarray([0, 8, 9, 10, 11], np.int32)
        rows = rng.standard_normal((len(idx), 4)).astype(np.float32)
        out = delta_apply_rows(arr, idx, rows)
        want = host.copy()
        want[idx] = rows
        np.testing.assert_array_equal(np.asarray(out), want)

    def test_delta_apply_shard_local_donated(self, mesh, monkeypatch):
        """KTPU_DONATE_DELTA=1 keeps its HBM contract on the sharded
        path: the update is correct and the input buffer is donated
        (deleted) rather than left as a second resident copy."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        from karpenter_tpu.ops.solve import delta_apply_rows

        monkeypatch.setenv("KTPU_DONATE_DELTA", "1")
        host = np.arange(32 * 4, dtype=np.float32).reshape(32, 4)
        arr = jax.device_put(host, NamedSharding(mesh, P("data")))
        idx = np.asarray([0, 3, 17], np.int32)
        rows = -np.ones((3, 4), np.float32)
        out = delta_apply_rows(arr, idx, rows)
        want = host.copy()
        want[idx] = rows
        np.testing.assert_array_equal(np.asarray(out), want)
        assert arr.is_deleted(), "donated input buffer survived"

    def test_delta_apply_replicated_buffer(self, mesh):
        """A replicated mesh buffer (the r06 layout's group/node arrays)
        takes the plain path: every device applies the full row set."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        from karpenter_tpu.ops.solve import delta_apply_rows

        host = np.arange(48, dtype=np.int32).reshape(16, 3)
        arr = jax.device_put(host, NamedSharding(mesh, P()))
        idx = np.asarray([2, 7, 11], np.int32)
        rows = -np.ones((3, 3), np.int32)
        out = delta_apply_rows(arr, idx, rows)
        want = host.copy()
        want[idx] = rows
        np.testing.assert_array_equal(np.asarray(out), want)


class TestMeshWarmPath:
    """The PR-8 warm path survives partitioning: REUSE and row-delta
    outcomes on the mesh match the single-device solver exactly."""

    def _fixtures(self, n_pods=400, workload="mixed"):
        from karpenter_tpu.cloudprovider import corpus
        from karpenter_tpu.solver.example import example_nodepool
        from karpenter_tpu.solver.workloads import (
            constrained_mix, diverse_reference_mix, mixed_pods,
        )

        pools = [example_nodepool()]
        its_by_pool = {p.name: corpus.generate(24) for p in pools}
        pods = {
            "mixed": lambda n: mixed_pods(n, gpu_fraction=0.0),
            "constrained": constrained_mix,
            "diverse": diverse_reference_mix,
        }[workload](n_pods)
        return pools, its_by_pool, pods

    def _solver(self, pools, its_by_pool, pods, cfg, cache):
        from karpenter_tpu.kube import Client, TestClock
        from karpenter_tpu.scheduling.topology import Topology
        from karpenter_tpu.solver import TpuSolver

        topology = Topology(Client(TestClock()), [], pools, its_by_pool, pods)
        return TpuSolver(
            pools, its_by_pool, topology, config=cfg, encode_cache=cache
        )

    def _churn_script(self, pods, ticks=3, k=8):
        import random

        rng = random.Random(13)
        regen = list(pods)
        out = [list(pods)]
        cur = list(pods)
        for _ in range(ticks):
            cur = list(cur)
            idx = rng.sample(range(len(cur)), k)
            jdx = rng.sample(range(len(regen)), k)
            for i, j in zip(idx, jdx):
                # a shape-preserving swap: counts shift between groups —
                # the steady-state delta the row banks turn into a
                # count/node row update
                cur[i] = regen[jdx[0] if j == i else j]
            out.append(cur)
        return out

    @pytest.mark.parametrize("workload", ["mixed", "constrained", "diverse"])
    def test_reuse_and_row_delta_survive_mesh(self, mesh, workload):
        from karpenter_tpu.solver.driver import EncodeCache, SolverConfig

        pools, its_by_pool, pods = self._fixtures(
            n_pods=400 if workload == "mixed" else 240, workload=workload
        )
        script = self._churn_script(pods)

        def run(cfg):
            cache = EncodeCache()
            out = []
            for tick_pods in script:
                s = self._solver(pools, its_by_pool, tick_pods, cfg, cache)
                r = s.solve(tick_pods)
                out.append(
                    (
                        bool(s.last_encode_reused),
                        int(s.last_delta_rows),
                        s.fallback_solves,
                        _claim_key(r),
                    )
                )
            return out, cache

        single, _ = run(SolverConfig())
        sharded, cache = run(SolverConfig(mesh=mesh))
        assert single == sharded
        # the script exercised the warm outcomes, not just cold solves
        assert any(reused for reused, *_ in single[1:]) or any(
            rows for _, rows, *_ in single[1:]
        )
        # staged buffers live on the mesh with their ARG_SPECS shardings
        store = cache.device_store
        assert store is not None and store._mesh_key == mesh
        gk = store._dev_buffers.get("gk_g")
        if gk is not None:
            assert tuple(gk.sharding.spec) == ("data",)

    def test_mesh_to_single_device_switch_restages(self, mesh):
        """One EncodeCache serving a mesh solve then a single-device solve
        (a failover shape): the store sheds the mesh buffers and both
        answers stay correct."""
        from karpenter_tpu.solver.driver import EncodeCache, SolverConfig

        pools, its_by_pool, pods = self._fixtures(n_pods=200)
        cache = EncodeCache()
        s1 = self._solver(
            pools, its_by_pool, pods, SolverConfig(mesh=mesh), cache
        )
        r1 = s1.solve(pods)
        s2 = self._solver(pools, its_by_pool, pods, SolverConfig(), cache)
        r2 = s2.solve(pods)
        assert _claim_key(r1) == _claim_key(r2)
        assert cache.device_store._mesh_key is None


class TestMeshScenarios:
    """The scenario axis shards: a consolidation-shaped scenario batch
    under the mesh stays <= 2 dispatches and matches the unsharded batch."""

    def test_scenario_batch_parity_under_mesh(self, mesh):
        from karpenter_tpu.cloudprovider import corpus
        from karpenter_tpu.kube import Client, TestClock
        from karpenter_tpu.scheduling.topology import Topology
        from karpenter_tpu.solver import TpuSolver
        from karpenter_tpu.solver.driver import (
            EncodeCache, Scenario, SolverConfig,
        )
        from karpenter_tpu.solver.example import example_nodepool
        from karpenter_tpu.solver.workloads import mixed_pods

        pods = mixed_pods(300, gpu_fraction=0.0)
        pools = [example_nodepool()]
        its_by_pool = {p.name: corpus.generate(24) for p in pools}
        scens = [Scenario(pods=pods[: 50 * (i + 1)]) for i in range(5)]

        def run(cfg):
            topology = Topology(
                Client(TestClock()), [], pools, its_by_pool, pods
            )
            s = TpuSolver(
                pools, its_by_pool, topology, config=cfg,
                encode_cache=EncodeCache(),
            )
            return s, s.solve_scenarios(scens)

        s1, r1 = run(SolverConfig())
        s2, r2 = run(SolverConfig(mesh=mesh))
        assert r1 is not None and r2 is not None
        assert [_claim_key(r) for r in r1] == [_claim_key(r) for r in r2]
        assert s2.last_scenario_dispatches <= 2
        assert s2.fallback_solves == 0


class TestEntry:
    def test_entry_compiles_and_runs(self):
        import __graft_entry__ as graft

        fn, args = graft.entry()
        out = jax.jit(fn)(*args)
        jax.block_until_ready(out)
        assert int(out[2]) > 0
