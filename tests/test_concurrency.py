"""Warm-path concurrency stress: the GRD/ATM passes' dynamic counterpart.

The static tier (analysis/guarded.py, analysis/atomicity.py) proves the
lock discipline on paper; this suite hammers the actual shared warm-path
objects — one EncodeCache and its DeviceResidentArgs under two solving
threads, a shared DispatchQueue driven submit/drain from both sides, the
metrics registry scraped mid-update — and pins the contract the passes
guard: decisions byte-identical to serial replay, zero warm-state
corruption, no torn snapshots. Everything is seeded; a failure here is a
real race, not a flake.
"""

import sys
import threading
from types import SimpleNamespace

import numpy as np
import pytest

from karpenter_tpu.kube import Client, TestClock

from helpers import decision_signature, make_nodepool, make_pods

# canonical serialization now shared with tests/test_tenants.py
_decision_signature = decision_signature


class TestSharedCacheChurn:
    N_THREADS = 2
    N_ITERS = 3

    def test_threaded_decisions_byte_identical_to_serial(self):
        """Two threads churning through ONE shared EncodeCache must make
        exactly the decisions a serial replay of the same pod batches
        makes, and the warm state they leave behind must still serve a
        clean follow-up solve — cache contention may cost encode reuse,
        never correctness."""
        import jax

        jax.config.update("jax_platforms", "cpu")
        from karpenter_tpu.cloudprovider import corpus
        from karpenter_tpu.scheduling.topology import Topology
        from karpenter_tpu.solver import TpuSolver
        from karpenter_tpu.solver.driver import EncodeCache, SolverConfig

        pools = [make_nodepool()]
        its = {pools[0].name: corpus.generate(12)}
        # the SAME pod objects feed both runs: uids are generated at
        # construction, so rebuilding batches would trivially diverge
        batches = {
            (t, i): make_pods(8 + 3 * t + 2 * i, cpu="1", memory="1Gi")
            for t in range(self.N_THREADS)
            for i in range(self.N_ITERS)
        }

        def solve_one(cache, pods):
            topo = Topology(Client(TestClock()), [], pools, its, pods)
            # relax=False pins the exact-kernel route (the bulk pre-solver
            # would swallow these identical-pod batches and skip the
            # warm-path machinery under test)
            solver = TpuSolver(
                pools, its, topo,
                config=SolverConfig(relax=False),
                encode_cache=cache,
            )
            r = solver.solve(pods)
            assert r.all_pods_scheduled(), r.pod_errors
            return _decision_signature(r)

        # serial oracle: fresh cache, every batch in order
        serial_cache = EncodeCache()
        serial = {
            key: solve_one(serial_cache, pods)
            for key, pods in sorted(batches.items())
        }

        shared = EncodeCache()
        threaded = {}
        errors = []
        barrier = threading.Barrier(self.N_THREADS)
        old_interval = sys.getswitchinterval()
        sys.setswitchinterval(1e-5)  # injected yields

        def churn(tid):
            try:
                barrier.wait()
                for i in range(self.N_ITERS):
                    threaded[(tid, i)] = solve_one(shared, batches[(tid, i)])
            except Exception as exc:  # pragma: no cover
                errors.append(exc)

        try:
            threads = [
                threading.Thread(target=churn, args=(t,))
                for t in range(self.N_THREADS)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(300)
        finally:
            sys.setswitchinterval(old_interval)
        assert not errors, errors
        assert threaded == serial

        # zero warm-state corruption: the contended cache must still
        # produce the canonical answer for a batch it has never seen
        probe = make_pods(11, cpu="1", memory="1Gi")
        want = solve_one(EncodeCache(), probe)
        assert solve_one(shared, probe) == want
        # and the adaptive NMAX hint survived the storm (a torn update
        # would re-trigger the overflow ladder on the next big solve)
        assert shared.cache.get("nmax_hint") is not None


class TestDispatchQueueConcurrent:
    def test_submit_drain_from_two_threads_serialized(self):
        """DispatchQueue is documented driver-serialized (no internal
        lock); concurrent sidecar solves serialize its edges on the
        EncodeCache lock. This mirrors that topology with an explicit
        edge lock: each thread must always drain exactly the outputs it
        submitted, and the two-slot window must end the storm empty."""
        from karpenter_tpu.solver.residency import DispatchQueue

        queue = DispatchQueue()
        edge_lock = threading.Lock()
        errors = []
        barrier = threading.Barrier(2)
        old_interval = sys.getswitchinterval()
        sys.setswitchinterval(1e-5)

        def pump(tid):
            try:
                barrier.wait()
                for i in range(50):
                    payload = np.full(4, tid * 1000 + i)

                    def dispatch(p=payload):
                        return p

                    with edge_lock:
                        slot = queue.submit(f"t{tid}-{i}", dispatch)
                    with edge_lock:
                        out = queue.drain(slot)
                    assert out is payload, (tid, i)
            except Exception as exc:  # pragma: no cover
                errors.append(exc)

        try:
            threads = [
                threading.Thread(target=pump, args=(t,)) for t in range(2)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(60)
        finally:
            sys.setswitchinterval(old_interval)
        assert not errors, errors
        assert queue.depth() == 0


class TestResidencyHammer:
    def test_stage_reset_storm_returns_staged_content(self):
        """Two threads staging disjoint arg names through ONE shared
        DeviceResidentArgs while interleaving reset(): every stage must
        hand back buffers equal to the host arrays passed in THAT call,
        and the buffer/meta maps must never tear (a lost lock here shows
        up as KeyError or dict-changed-size, the GRD1301 shape)."""
        import jax

        jax.config.update("jax_platforms", "cpu")
        from karpenter_tpu.solver.residency import DeviceResidentArgs

        dra = DeviceResidentArgs()
        errors = []
        barrier = threading.Barrier(2)
        old_interval = sys.getswitchinterval()
        sys.setswitchinterval(1e-5)

        def delta(version):
            # every name falls through _class_of to the static class:
            # one version counter drives the reuse/restage decision
            return SimpleNamespace(
                v_nodes=version, node_rows=None,
                v_cross=version, cross_rows=None,
                v_gcount=version, count_rows=None,
                v_groups=version, group_rows=None,
                v_static=version,
            )

        def hammer(tid):
            try:
                barrier.wait()
                names = (f"t{tid}_a", f"t{tid}_b")
                for i in range(40):
                    hosts = [
                        np.full(6, tid * 100 + i, dtype=np.int32),
                        np.arange(i, i + 5, dtype=np.float32),
                    ]
                    d = delta(tid * 1000 + i)
                    for _attempt in range(2):  # second pass takes reuse
                        out = dra.stage(names, hosts, d)
                        for host, buf in zip(hosts, out):
                            assert np.array_equal(np.asarray(buf), host)
                    if i % 7 == 6:
                        dra.reset()
            except Exception as exc:  # pragma: no cover
                errors.append(exc)

        try:
            threads = [
                threading.Thread(target=hammer, args=(t,)) for t in range(2)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(120)
        finally:
            sys.setswitchinterval(old_interval)
        assert not errors, errors


class TestAuditLogUnderFire:
    def test_len_and_query_during_record_churn(self):
        """__len__/query/to_json snapshot under AuditLog._lock: reading
        the trail while another solve thread appends must never tear,
        and the final count must equal the records that landed (the
        obs/audit.py GRD1301 dogfood fix)."""
        from karpenter_tpu.obs.audit import AuditLog

        log = AuditLog(maxlen=4096, clock=lambda: 0.0)
        fields = dict(
            kind="solve", trace_id="t", duration_ms=1.0, encode_hash="h",
            pods=1, claims=1, errors=0, scenario_count=0, dispatches=1,
            rung="kernel", guard="ok",
        )
        errors = []
        stop = threading.Event()
        barrier = threading.Barrier(2)
        old_interval = sys.getswitchinterval()
        sys.setswitchinterval(1e-5)

        def writer():
            try:
                barrier.wait()
                for _ in range(2000):
                    log.record(**fields)
            except Exception as exc:  # pragma: no cover
                errors.append(exc)
            finally:
                stop.set()

        def reader():
            try:
                barrier.wait()
                while not stop.is_set():
                    n = len(log)
                    assert 0 <= n <= 4096
                    log.query(kind="solve")
                    log.to_json()
            except Exception as exc:  # pragma: no cover
                errors.append(exc)

        try:
            threads = [
                threading.Thread(target=writer),
                threading.Thread(target=reader),
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(120)
        finally:
            sys.setswitchinterval(old_interval)
        assert not errors, errors
        assert len(log) == 2000
        assert log.last().decision_id == "d002000"


class TestMetricsSnapshotUnderFire:
    def test_collect_and_render_during_label_churn(self):
        """collect()/render() snapshot each series map under its metric's
        lock: a scrape racing an inc() that inserts NEW label keys must
        never raise (the exact dict-changed-size RuntimeError the GRD1301
        dogfood found in metrics/registry.py before the snapshot fix)."""
        from karpenter_tpu.metrics.registry import (
            Counter, Histogram, Registry,
        )

        reg = Registry()
        counter = Counter("conc_test_total", registry=reg)
        histo = Histogram("conc_test_seconds", registry=reg)
        errors = []
        stop = threading.Event()
        barrier = threading.Barrier(2)
        old_interval = sys.getswitchinterval()
        sys.setswitchinterval(1e-5)

        def writer():
            try:
                barrier.wait()
                for i in range(4000):
                    counter.inc({"k": f"v{i % 60}"})
                    histo.observe(0.001 * (i % 10), {"k": f"v{i % 60}"})
            except Exception as exc:  # pragma: no cover
                errors.append(exc)
            finally:
                stop.set()

        def scraper():
            try:
                barrier.wait()
                while not stop.is_set():
                    reg.collect()
                    reg.render()
            except Exception as exc:  # pragma: no cover
                errors.append(exc)

        try:
            threads = [
                threading.Thread(target=writer),
                threading.Thread(target=scraper),
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(120)
        finally:
            sys.setswitchinterval(old_interval)
        assert not errors, errors
        # the final scrape is consistent: every series landed
        assert counter.value({"k": "v0"}) > 0
        assert histo.count({"k": "v0"}) > 0


class TestTenantStorm:
    """N-tenant storm through ONE multi-tenant service: T tenants x K
    threads of seeded churn, every tenant's decisions byte-identical to
    its own serial replay, every tenant's warm state clean enough to
    serve a post-storm probe — contention may cost encode reuse or
    batching opportunities, never a decision bit."""

    N_TENANTS = 3
    K_THREADS = 2  # concurrent threads PER tenant
    N_ITERS = 2

    def test_tenant_storm_byte_identical_per_tenant(self):
        import jax

        jax.config.update("jax_platforms", "cpu")
        from karpenter_tpu.cloudprovider import corpus
        from karpenter_tpu.kube import TestClock
        from karpenter_tpu.solver import wire
        from karpenter_tpu.solver.driver import SolverConfig
        from karpenter_tpu.solver.service import TenantService
        from karpenter_tpu.solver.tenancy import TenantQoS, TenantRegistry

        pools = [make_nodepool()]
        its = {pools[0].name: corpus.generate(10)}
        tenants = [f"t{n}" for n in range(self.N_TENANTS)]

        # request bytes encoded ONCE per (tenant, thread, iter): decoding
        # the same bytes in the storm and the serial replay pins pod uids
        requests = {}
        for tn, tid in enumerate(tenants):
            for k in range(self.K_THREADS):
                for i in range(self.N_ITERS):
                    pods = make_pods(
                        4 + 2 * tn + k + i, cpu="1", memory="1Gi"
                    )
                    requests[(tid, k, i)] = wire.encode_solve_request(
                        pods, pools, its,
                        solver_options={
                            "reserved_capacity_enabled": False
                        },
                    )

        def service():
            # generous QoS: the storm measures isolation under
            # contention, not admission (rejections would fork the
            # serial comparison)
            return TenantService(
                registry=TenantRegistry(
                    clock=TestClock(),
                    max_inflight=64,
                    qos={
                        "standard": TenantQoS(
                            rate=1000.0, burst=1000.0, max_queue=64
                        )
                    },
                ),
                config=SolverConfig(relax=False),
            )

        # serial oracle: each tenant's requests in order, fresh service
        serial_svc = service()
        serial = {
            key: decision_signature(
                serial_svc.solve_for(
                    key[0], wire.decode_solve_request(req)
                )
            )
            for key, req in sorted(requests.items())
        }

        storm_svc = service()
        stormed = {}
        errors = []
        n_threads = self.N_TENANTS * self.K_THREADS
        barrier = threading.Barrier(n_threads)
        old_interval = sys.getswitchinterval()
        sys.setswitchinterval(1e-5)  # injected yields

        def churn(tid, k):
            try:
                barrier.wait()
                for i in range(self.N_ITERS):
                    stormed[(tid, k, i)] = decision_signature(
                        storm_svc.solve_for(
                            tid,
                            wire.decode_solve_request(
                                requests[(tid, k, i)]
                            ),
                        )
                    )
            except Exception as exc:  # pragma: no cover
                errors.append(exc)

        try:
            threads = [
                threading.Thread(target=churn, args=(tid, k))
                for tid in tenants
                for k in range(self.K_THREADS)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(300)
        finally:
            sys.setswitchinterval(old_interval)
        assert not errors, errors
        assert set(stormed) == set(serial)
        for key in sorted(serial):
            assert stormed[key] == serial[key], (
                f"tenant {key[0]} diverged from its serial replay at {key}"
            )

        # post-storm probe: every tenant's warm state still serves a
        # clean solve, rung batched, zero fallbacks, zero overcommit
        for tid in tenants:
            probe = make_pods(5, cpu="1", memory="1Gi")
            req = wire.encode_solve_request(
                probe, pools, its,
                solver_options={"reserved_capacity_enabled": False},
            )
            results = storm_svc.solve_for(
                tid, wire.decode_solve_request(req)
            )
            assert results.all_pods_scheduled(), results.pod_errors
            state = storm_svc.registry.get(tid)
            assert state.health.level() == 0
            assert state.stats()["fallback_solves"] == 0
            assert state.stats()["rejected"] == 0
            assert state.stats()["inflight"] == 0
