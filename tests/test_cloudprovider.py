"""CloudProvider SPI tests: ordering, truncation, minValues, kwok/fake providers."""

import pytest

from karpenter_tpu.api import labels
from karpenter_tpu.api.objects import NodeClaim, NodeClaimSpec, NodeSelectorRequirement, ObjectMeta
from karpenter_tpu.api.requirements import Operator, Requirement, Requirements
from karpenter_tpu.cloudprovider import corpus, fake, types as cp
from karpenter_tpu.cloudprovider.kwok import KwokCloudProvider
from karpenter_tpu.kube import Client, TestClock


def reqs(*rs):
    return Requirements(*rs)


class TestCorpus:
    def test_grid_size(self):
        its = corpus.generate()
        assert len(its) == len(corpus.FAMILIES) * len(corpus.SIZES) * 2

    def test_unique_names_extended(self):
        its = corpus.generate(400)
        names = [it.name for it in its]
        assert len(set(names)) == 400

    def test_offerings_cover_zones_and_capacity_types(self):
        it = corpus.generate(1)[0]
        zones = {o.zone() for o in it.offerings}
        cts = {o.capacity_type() for o in it.offerings}
        assert zones == set(corpus.DEFAULT_ZONES)
        assert cts == {labels.CAPACITY_TYPE_SPOT, labels.CAPACITY_TYPE_ON_DEMAND}

    def test_spot_cheaper_than_on_demand(self):
        it = corpus.generate(1)[0]
        spot = [o for o in it.offerings if o.capacity_type() == labels.CAPACITY_TYPE_SPOT]
        od = [o for o in it.offerings if o.capacity_type() == labels.CAPACITY_TYPE_ON_DEMAND]
        assert max(o.price for o in spot) < min(o.price for o in od)

    def test_allocatable_below_capacity(self):
        it = corpus.generate(1)[0]
        alloc = it.allocatable()
        assert alloc["cpu"] < it.capacity["cpu"]
        assert alloc["memory"] < it.capacity["memory"]


class TestOrderingAndTruncation:
    def test_order_by_price_spot_first(self):
        its = corpus.generate(10)
        ordered = cp.order_by_price(its, Requirements())
        prices = [cp.min_compatible_price(it, Requirements()) for it in ordered]
        assert prices == sorted(prices)

    def test_order_by_price_respects_capacity_type(self):
        its = corpus.generate(10)
        od_only = reqs(
            Requirement(
                labels.CAPACITY_TYPE_LABEL_KEY, Operator.IN, [labels.CAPACITY_TYPE_ON_DEMAND]
            )
        )
        ordered = cp.order_by_price(its, od_only)
        prices = [cp.min_compatible_price(it, od_only) for it in ordered]
        assert prices == sorted(prices)
        # on-demand prices are used, not spot
        spot_price = cp.min_compatible_price(ordered[0], Requirements())
        assert prices[0] > spot_price

    def test_truncate(self):
        its = corpus.generate(100)
        truncated, err = cp.truncate(its, Requirements(), 60)
        assert err is None and len(truncated) == 60

    def test_truncate_min_values_violation(self):
        its = corpus.generate(4)
        # require more distinct instance types than truncation would keep
        r = reqs(
            Requirement(
                labels.INSTANCE_TYPE,
                Operator.IN,
                [it.name for it in its],
                min_values=4,
            )
        )
        truncated, err = cp.truncate(its, r, 2)
        assert err is not None
        assert len(truncated) == 4  # untruncated on violation

    def test_satisfies_min_values_counts_prefix(self):
        its = corpus.generate(6)
        r = reqs(
            Requirement(
                labels.INSTANCE_TYPE,
                Operator.IN,
                [it.name for it in its],
                min_values=3,
            )
        )
        n, err = cp.satisfies_min_values(its, r)
        assert err is None and n == 3

    def test_no_min_values_is_trivially_satisfied(self):
        n, err = cp.satisfies_min_values(corpus.generate(2), Requirements())
        assert (n, err) == (0, None)


class TestWorstLaunchPrice:
    def test_precedence_spot_over_on_demand(self):
        it = corpus.generate(1)[0]
        # with no capacity-type constraint, spot offerings exist -> spot worst
        worst = cp.worst_launch_price(it.offerings, Requirements())
        spot_prices = [
            o.price for o in it.offerings if o.capacity_type() == labels.CAPACITY_TYPE_SPOT
        ]
        assert worst == max(spot_prices)


def make_claim(name="claim-1", requirements=()):
    return NodeClaim(
        metadata=ObjectMeta(name=name, labels={labels.NODEPOOL_LABEL_KEY: "default"}),
        spec=NodeClaimSpec(requirements=list(requirements)),
    )


class TestKwokProvider:
    def test_create_picks_cheapest(self):
        client = Client(TestClock())
        provider = KwokCloudProvider(client, corpus.generate(20))
        claim = provider.create(make_claim())
        assert claim.status.provider_id.startswith("kwok://")
        assert claim.metadata.labels[labels.CAPACITY_TYPE_LABEL_KEY] == labels.CAPACITY_TYPE_SPOT
        # cheapest = smallest spot offering among compatible types
        its = cp.order_by_price(provider.get_instance_types(None), Requirements())
        assert claim.metadata.labels[labels.INSTANCE_TYPE] == its[0].name

    def test_create_respects_requirements(self):
        client = Client(TestClock())
        provider = KwokCloudProvider(client, corpus.generate(20))
        claim = provider.create(
            make_claim(
                requirements=[
                    NodeSelectorRequirement(labels.TOPOLOGY_ZONE, "In", ("test-zone-b",)),
                    NodeSelectorRequirement(
                        labels.CAPACITY_TYPE_LABEL_KEY, "In", (labels.CAPACITY_TYPE_ON_DEMAND,)
                    ),
                ]
            )
        )
        assert claim.metadata.labels[labels.TOPOLOGY_ZONE] == "test-zone-b"
        assert claim.metadata.labels[labels.CAPACITY_TYPE_LABEL_KEY] == labels.CAPACITY_TYPE_ON_DEMAND

    def test_registration_delay(self):
        clock = TestClock()
        client = Client(clock)
        provider = KwokCloudProvider(client, corpus.generate(5), registration_delay=30)
        provider.create(make_claim())
        assert provider.process_registrations() == []
        clock.step(31)
        nodes = provider.process_registrations()
        assert len(nodes) == 1
        # node carries the unregistered NoExecute taint until lifecycle strips it
        assert any(t.key == labels.UNREGISTERED_TAINT_KEY for t in nodes[0].taints)
        from karpenter_tpu.api.objects import Node

        assert client.get(Node, nodes[0].name) is nodes[0]

    def test_delete_then_get_raises(self):
        client = Client(TestClock())
        provider = KwokCloudProvider(client, corpus.generate(5))
        claim = provider.create(make_claim())
        provider.delete(claim)
        with pytest.raises(cp.NodeClaimNotFoundError):
            provider.get(claim.status.provider_id)

    def test_incompatible_requirements_raise(self):
        client = Client(TestClock())
        provider = KwokCloudProvider(client, corpus.generate(5))
        with pytest.raises(cp.InsufficientCapacityError):
            provider.create(
                make_claim(
                    requirements=[
                        NodeSelectorRequirement(labels.TOPOLOGY_ZONE, "In", ("nowhere",))
                    ]
                )
            )


class TestFakeProvider:
    def test_error_injection(self):
        provider = fake.FakeCloudProvider()
        provider.next_create_err = cp.InsufficientCapacityError("boom")
        with pytest.raises(cp.InsufficientCapacityError):
            provider.create(make_claim())
        # next call succeeds
        claim = provider.create(make_claim("claim-2"))
        assert claim.status.provider_id

    def test_allowed_create_calls(self):
        provider = fake.FakeCloudProvider()
        provider.allowed_create_calls = 1
        provider.create(make_claim("a"))
        with pytest.raises(cp.InsufficientCapacityError):
            provider.create(make_claim("b"))

    def test_list_and_delete(self):
        provider = fake.FakeCloudProvider()
        claim = provider.create(make_claim())
        assert len(provider.list()) == 1
        provider.delete(claim)
        assert provider.list() == []


class TestKubeStore:
    def test_crud_and_watch(self):
        client = Client(TestClock())
        events = []
        client.watch(events.append)
        claim = make_claim()
        client.create(claim)
        got = client.get(NodeClaim, "claim-1")
        assert got is claim
        client.update(claim)
        client.delete(claim)
        assert [e.type for e in events] == ["ADDED", "MODIFIED", "DELETED"]

    def test_finalizer_two_phase_delete(self):
        client = Client(TestClock())
        claim = make_claim()
        claim.metadata.finalizers.append("karpenter.tpu/termination")
        client.create(claim)
        client.delete(claim)
        # still present, marked deleting
        assert client.get(NodeClaim, "claim-1").metadata.deletion_timestamp is not None
        client.remove_finalizer(claim, "karpenter.tpu/termination")
        assert client.try_get(NodeClaim, "claim-1") is None

    def test_duplicate_create_raises(self):
        from karpenter_tpu.kube import AlreadyExistsError

        client = Client(TestClock())
        client.create(make_claim())
        with pytest.raises(AlreadyExistsError):
            client.create(make_claim())


class TestClusterStateRegressions:
    def test_terminal_pod_releases_usage(self):
        from karpenter_tpu.controllers.state import Cluster
        from karpenter_tpu.api.objects import Node, NodeStatus
        from karpenter_tpu.api import resources as res
        from helpers import make_pod

        client = Client(TestClock())
        cluster = Cluster(client)
        node = Node(metadata=ObjectMeta(name="n1"), provider_id="p://n1")
        node.status.allocatable = {"cpu": 4000}
        client.create(node)
        pod = make_pod(cpu="3", node_name="n1", phase="Running")
        client.create(pod)
        sn = cluster.node_for_name("n1")
        assert sn.available()["cpu"] == 1000
        pod.status.phase = "Succeeded"
        client.update(pod)
        assert sn.available()["cpu"] == 4000

    def test_provider_id_change_drops_synthetic_entry(self):
        from karpenter_tpu.controllers.state import Cluster
        from karpenter_tpu.api.objects import Node

        client = Client(TestClock())
        cluster = Cluster(client)
        node = Node(metadata=ObjectMeta(name="n2"))
        client.create(node)
        assert len(cluster.nodes()) == 1
        node.provider_id = "gce://n2"
        client.update(node)
        assert len(cluster.nodes()) == 1


class TestMetricsDecorator:
    def test_instrumented_calls_and_errors(self):
        from karpenter_tpu.cloudprovider.fake import FakeCloudProvider
        from karpenter_tpu.cloudprovider.metrics import (
            METHOD_DURATION,
            METHOD_ERRORS,
            MetricsCloudProvider,
        )
        from karpenter_tpu.cloudprovider.types import InsufficientCapacityError

        inner = FakeCloudProvider()
        provider = MetricsCloudProvider(inner)
        labels = {"method": "List", "provider": inner.name()}
        before = METHOD_DURATION.count(labels)
        provider.list()
        assert METHOD_DURATION.count(labels) == before + 1

        inner.next_create_err = InsufficientCapacityError("no capacity")
        from helpers import make_nodepool
        from karpenter_tpu.api.objects import NodeClaim

        err_labels = {
            "method": "Create",
            "provider": inner.name(),
            "error": "InsufficientCapacityError",
        }
        before_err = METHOD_ERRORS.value(err_labels)
        import pytest as _pytest

        with _pytest.raises(InsufficientCapacityError):
            provider.create(NodeClaim())
        assert METHOD_ERRORS.value(err_labels) == before_err + 1

    def test_extension_passthrough(self):
        from karpenter_tpu.cloudprovider import corpus
        from karpenter_tpu.cloudprovider.kwok import KwokCloudProvider
        from karpenter_tpu.cloudprovider.metrics import MetricsCloudProvider
        from karpenter_tpu.kube import Client, TestClock

        provider = MetricsCloudProvider(
            KwokCloudProvider(Client(TestClock()), corpus.generate(4))
        )
        provider.process_registrations()  # kwok extension reachable


class TestTypedNotFound:
    """Regression: unknown provider ids and double-deletes surface as
    typed NodeClaimNotFoundError through every provider path — never a
    bare KeyError leaking through the termination controller."""

    def _provider(self):
        client = Client(TestClock())
        return client, KwokCloudProvider(client, corpus.generate(6))

    def test_kwok_double_delete_is_typed(self):
        _, provider = self._provider()
        claim = provider.create(make_claim())
        provider.delete(claim)
        with pytest.raises(cp.NodeClaimNotFoundError) as exc_info:
            provider.delete(claim)
        assert not isinstance(exc_info.value, KeyError)
        assert "already terminated" in str(exc_info.value)

    def test_kwok_unknown_and_empty_provider_id(self):
        _, provider = self._provider()
        ghost = make_claim("ghost")
        ghost.status.provider_id = "kwok://never-created-1"
        with pytest.raises(cp.NodeClaimNotFoundError):
            provider.delete(ghost)
        blank = make_claim("blank")  # no provider id at all
        with pytest.raises(cp.NodeClaimNotFoundError):
            provider.delete(blank)
        with pytest.raises(cp.NodeClaimNotFoundError):
            provider.get("")
        with pytest.raises(cp.NodeClaimNotFoundError):
            provider.get("kwok://never-created-1")

    def test_get_after_delete_is_typed(self):
        _, provider = self._provider()
        claim = provider.create(make_claim())
        pid = claim.status.provider_id
        provider.delete(claim)
        with pytest.raises(cp.NodeClaimNotFoundError):
            provider.get(pid)

    def test_fake_double_delete_is_typed(self):
        provider = fake.FakeCloudProvider(corpus.generate(4))
        claim = provider.create(make_claim())
        provider.delete(claim)
        with pytest.raises(cp.NodeClaimNotFoundError) as exc_info:
            provider.delete(claim)
        assert "already terminated" in str(exc_info.value)

    def test_termination_path_survives_vanished_instance(self):
        """Full controller path: the cloud instance disappears (or was
        already deleted) mid-termination — the claim still finalizes and
        the node goes away, with no exception escaping reconcile."""
        from karpenter_tpu.controllers.lifecycle import LifecycleController
        from karpenter_tpu.controllers.termination import (
            TerminationController,
        )

        client, provider = self._provider()
        lifecycle = LifecycleController(client, provider)
        termination = TerminationController(client, provider)
        claim = make_claim()
        claim.metadata.finalizers.append(labels.TERMINATION_FINALIZER)
        client.create(claim)
        lifecycle.reconcile_all()       # launch
        provider.process_registrations()
        lifecycle.reconcile_all()       # register + initialize
        node = client.list(__import__(
            "karpenter_tpu.api.objects", fromlist=["Node"]
        ).Node)[0]
        # the instance dies out from under the controller
        provider.delete(claim)
        client.delete(node)
        client.delete(claim)
        termination.reconcile_all()
        lifecycle.reconcile_all()       # finalize: second delete -> typed
        termination.reconcile_all()     # claim gone -> node finalizer drops
        from karpenter_tpu.api.objects import Node, NodeClaim as NC

        assert client.list(NC) == []
        assert client.list(Node) == []
