"""ISSUE 10 equivalence suites: dense in-kernel topology-spread, minValues,
volume, and reservation constraints vs the sequential reference.

The four constraint families that used to gate a sequential fallback now
ride the batched kernel: topology-spread priors batch per scenario
(driver._plan_scenario_topology), minValues floors count distinct values
densely (ops/packing.py:minvalues_cap), volumes consume attach-slot ledger
columns, and default-mode reservations replay per scenario. These suites
pin each family's batched decisions to the sequential path that remains
the semantic reference — per-probe simulate_scheduling for the scenario
axis (exact command signatures: both sides run the same kernel per probe)
and the host oracle for single solves (node count / cost / constraint
semantics, the established parity discipline).
"""

from __future__ import annotations

import random

import pytest

from karpenter_tpu.api import labels as labels_mod
from karpenter_tpu.api import resources as res
from karpenter_tpu.api.objects import (
    COND_CONSOLIDATABLE,
    COND_INITIALIZED,
    COND_LAUNCHED,
    COND_REGISTERED,
    Node,
    NodeClaim,
    NodeClaimSpec,
    NodePool,
    NodePoolSpec,
    NodeSelectorRequirement,
    ObjectMeta,
    PersistentVolume,
    PersistentVolumeClaim,
    PersistentVolumeClaimRef,
)
from karpenter_tpu.api.objects import NodeClaimTemplate as NodeClaimTemplateSpec
from karpenter_tpu.api.requirements import Operator, Requirement, Requirements
from karpenter_tpu.cloudprovider import corpus
from karpenter_tpu.cloudprovider import types as cp
from karpenter_tpu.cloudprovider.kwok import KwokCloudProvider
from karpenter_tpu.controllers.disruption.controller import DisruptionContext
from karpenter_tpu.controllers.disruption.methods import MultiNodeConsolidation
from karpenter_tpu.controllers.state import Cluster
from karpenter_tpu.events.recorder import Recorder
from karpenter_tpu.kube import Client, TestClock
from karpenter_tpu.scheduling.topology import Topology
from karpenter_tpu.scheduling.volumeusage import VolumeResolver
from karpenter_tpu.solver import TpuSolver
from karpenter_tpu.solver.driver import Scenario, SolverConfig

from helpers import make_nodepool, make_pod, make_pods, spread_constraint
from test_scenario_batch import (
    _candidates_and_budgets,
    _command_signature,
    _pod,
)

_MI = 2**20 * res.MILLI


def build_topo_env(
    n_nodes: int,
    seed: int = 0,
    n_types: int = 30,
    pending_pods: int = 2,
    spread_keys=(labels_mod.TOPOLOGY_ZONE,),
    min_values_pool: bool = False,
):
    """A seeded consolidatable cluster whose fill pods carry SELF-SELECTING
    spread constraints (one 'deployment' label per constraint family across
    nodes), nodes spread over the catalog's zones — the shape whose
    consolidation search used to fall off the scenario-batched path."""
    rng = random.Random(seed)
    clock = TestClock()
    clock.step(3600.0)
    client = Client(clock)
    its = corpus.generate(n_types)
    provider = KwokCloudProvider(client, its)
    cluster = Cluster(client)

    pool = NodePool(
        metadata=ObjectMeta(name="default"),
        spec=NodePoolSpec(template=NodeClaimTemplateSpec(spec=NodeClaimSpec())),
    )
    if min_values_pool:
        pool.spec.template.spec.requirements = [
            NodeSelectorRequirement(
                corpus.INSTANCE_FAMILY_LABEL, "Exists", (), min_values=2
            )
        ]
    pool.spec.disruption.consolidate_after = 10.0
    client.create(pool)

    sized = sorted(
        (
            it
            for it in its
            if it.capacity.get(res.CPU, 0) >= 4000
            and it.capacity.get(res.MEMORY, 0) >= 8 * 1024 * _MI
        ),
        key=lambda it: min(
            (o.price for o in it.offerings if o.available), default=1e9
        ),
    )
    it = sized[len(sized) // 2]
    zoned = {}
    for o in it.offerings:
        if o.available and o.zone() not in zoned:
            zoned[o.zone()] = o
    zones = sorted(zoned)
    assert len(zones) >= 2, "topology env needs a multi-zone type"

    deployments = [
        {"app": f"d{j}", "key": key}
        for j, key in enumerate(
            list(spread_keys) * 2
        )  # two deployments per key
    ]

    for i in range(n_nodes):
        name = f"n-{i}"
        pid = f"test://{i}"
        offering = zoned[zones[i % len(zones)]]
        node_labels = {
            labels_mod.HOSTNAME: name,
            labels_mod.INSTANCE_TYPE: it.name,
            labels_mod.TOPOLOGY_ZONE: offering.zone(),
            labels_mod.CAPACITY_TYPE_LABEL_KEY: offering.capacity_type(),
            labels_mod.NODEPOOL_LABEL_KEY: pool.name,
        }
        claim = NodeClaim(
            metadata=ObjectMeta(name=name, labels=dict(node_labels)),
            spec=NodeClaimSpec(),
        )
        claim.status.provider_id = pid
        claim.status.capacity = dict(it.capacity)
        claim.status.allocatable = dict(it.allocatable())
        now = clock.now()
        for cond in (COND_LAUNCHED, COND_REGISTERED, COND_INITIALIZED,
                     COND_CONSOLIDATABLE):
            claim.conds().set(cond, "True", now=now)
        node = Node(
            metadata=ObjectMeta(name=name, labels=node_labels),
            provider_id=pid,
        )
        node.status.capacity = dict(it.capacity)
        node.status.allocatable = dict(it.allocatable())
        node.status.ready = True
        client.create(claim)
        client.create(node)
        for j in range(rng.choice((1, 2))):
            dep = deployments[(i + j) % len(deployments)]
            p = make_pod(
                name=f"fill-{i}-{j}",
                cpu=str(rng.choice((0.25, 0.5, 0.75))),
                memory=f"{rng.choice((256, 512, 1024))}Mi",
                labels={"app": dep["app"]},
                spread=[
                    spread_constraint(dep["key"], labels={"app": dep["app"]})
                ],
                node_name=name,
                phase="Running",
            )
            client.create(p)
    for j in range(pending_pods):
        dep = deployments[j % len(deployments)]
        client.create(
            make_pod(
                name=f"pend-{j}",
                cpu="0.5",
                memory="512Mi",
                labels={"app": dep["app"]},
                spread=[
                    spread_constraint(dep["key"], labels={"app": dep["app"]})
                ],
            )
        )

    return DisruptionContext(
        client=client,
        cluster=cluster,
        cloud_provider=provider,
        clock=clock,
        recorder=Recorder(clock),
        spot_to_spot_enabled=True,
    )


def _run_multi_env(env_args, batched: bool):
    ctx = build_topo_env(**env_args)
    ctx.scenario_batch = batched
    method = MultiNodeConsolidation(ctx)
    candidates, budgets = _candidates_and_budgets(ctx, method)
    cmd = method.compute_command(candidates, budgets)
    return cmd, method


class TestScenarioTopologyEquivalence:
    """Topology-constrained consolidation searches ride the batched kernel
    (per-scenario prior corrections) and decide EXACTLY what the
    sequential per-probe loop decides."""

    @pytest.mark.parametrize("seed", range(4))
    def test_zonal_spread_clusters(self, seed):
        env_args = dict(
            n_nodes=5 + (seed * 3) % 9,
            seed=seed,
            spread_keys=(labels_mod.TOPOLOGY_ZONE,),
            pending_pods=seed % 3,
        )
        cmd_b, method_b = _run_multi_env(env_args, batched=True)
        cmd_s, _ = _run_multi_env(env_args, batched=False)
        assert _command_signature(cmd_b) == _command_signature(cmd_s)
        if method_b.last_probes:
            # the topology-carrying search stayed batched, <= 2 dispatches
            assert 1 <= method_b.last_dispatches <= 2

    @pytest.mark.parametrize("seed", range(3))
    def test_hostname_spread_clusters(self, seed):
        env_args = dict(
            n_nodes=6 + seed * 2,
            seed=10 + seed,
            spread_keys=(labels_mod.HOSTNAME,),
            pending_pods=1,
        )
        cmd_b, method_b = _run_multi_env(env_args, batched=True)
        cmd_s, _ = _run_multi_env(env_args, batched=False)
        assert _command_signature(cmd_b) == _command_signature(cmd_s)
        if method_b.last_probes:
            assert 1 <= method_b.last_dispatches <= 2

    def test_mixed_keys_cluster(self, ):
        env_args = dict(
            n_nodes=8,
            seed=21,
            spread_keys=(labels_mod.TOPOLOGY_ZONE, labels_mod.HOSTNAME),
            pending_pods=2,
        )
        cmd_b, method_b = _run_multi_env(env_args, batched=True)
        cmd_s, _ = _run_multi_env(env_args, batched=False)
        assert _command_signature(cmd_b) == _command_signature(cmd_s)
        if method_b.last_probes:
            assert 1 <= method_b.last_dispatches <= 2

    def test_min_values_pool_rides_batch(self):
        env_args = dict(
            n_nodes=6, seed=5, min_values_pool=True, pending_pods=1
        )
        cmd_b, method_b = _run_multi_env(env_args, batched=True)
        cmd_s, _ = _run_multi_env(env_args, batched=False)
        assert _command_signature(cmd_b) == _command_signature(cmd_s)
        if method_b.last_probes:
            assert 1 <= method_b.last_dispatches <= 2

    def test_anti_affinity_candidates_decline_to_sequential(self):
        """Documented remnant: candidate pods OWNING anti-affinity gate
        through the oracle's inverse machinery — the batch must decline
        (and the decline must be counted), never guess."""
        from karpenter_tpu.api.objects import PodAffinityTerm, LabelSelector

        ctx = build_topo_env(n_nodes=4, seed=7, pending_pods=0)
        anti = make_pod(
            name="anti-0",
            cpu="0.25",
            memory="256Mi",
            labels={"app": "nginx"},
            pod_anti_affinity=[
                PodAffinityTerm(
                    topology_key=labels_mod.HOSTNAME,
                    label_selector=LabelSelector(
                        match_labels={"app": "nginx"}
                    ),
                )
            ],
            node_name="n-0",
            phase="Running",
        )
        ctx.client.create(anti)
        ctx.scenario_batch = True
        method = MultiNodeConsolidation(ctx)
        candidates, budgets = _candidates_and_budgets(ctx, method)
        cmd_b = method.compute_command(candidates, budgets)
        ctx2 = build_topo_env(n_nodes=4, seed=7, pending_pods=0)
        ctx2.client.create(anti)
        ctx2.scenario_batch = False
        method_s = MultiNodeConsolidation(ctx2)
        candidates2, budgets2 = _candidates_and_budgets(ctx2, method_s)
        cmd_s = method_s.compute_command(candidates2, budgets2)
        assert _command_signature(cmd_b) == _command_signature(cmd_s)


class TestMaxSkewBoundary:
    """Single-solve kernel-vs-oracle parity at the skew boundary and with
    unschedulable domains (the decision shapes the old gate serialized)."""

    def _run_both(self, pods, pools=None, its=None):
        import copy

        pools = pools or [make_nodepool()]
        its = its if its is not None else corpus.generate(12)
        its_by_pool = {p.name: list(its) for p in pools}

        def topo(ps):
            return Topology(Client(TestClock()), [], pools, its_by_pool, ps)

        o_pods = copy.deepcopy(pods)
        o = TpuSolver(
            pools, its_by_pool, topo(o_pods),
            config=SolverConfig(force_oracle=True),
        ).solve(o_pods)
        solver = TpuSolver(pools, its_by_pool, topo(pods))
        k = solver.solve(pods)
        assert solver.fallback_solves == 0, solver.last_fallback_reasons
        return o, k

    def _zone_spread(self, n, skew):
        lbl = {"app": "sk"}
        return make_pods(
            n, cpu="1", labels=lbl,
            spread=[
                spread_constraint(
                    labels_mod.TOPOLOGY_ZONE, labels=lbl, max_skew=skew
                )
            ],
        )

    @pytest.mark.parametrize("skew", [1, 2])
    @pytest.mark.parametrize("n", [3, 7, 10])
    def test_boundary_counts(self, n, skew):
        o, k = self._run_both(self._zone_spread(n, skew))
        assert not k.pod_errors and not o.pod_errors
        # per-zone counts honor the skew in both paths, identically spread
        def zone_counts(results):
            counts = {}
            for c in results.new_node_claims:
                z = c.requirements.get(labels_mod.TOPOLOGY_ZONE)
                zone = next(iter(z.values)) if len(z.values) == 1 else "?"
                counts[zone] = counts.get(zone, 0) + len(c.pods)
            return counts

        for counts in (zone_counts(o), zone_counts(k)):
            vals = list(counts.values())
            assert max(vals) - min(vals) <= skew

    def test_unschedulable_domain(self):
        # zone-c offerings unavailable but REGISTERED (the catalog provides
        # the domain): its empty count pins the global min at 0, so both
        # paths place exactly one pod per schedulable zone and error the
        # rest — identically (kubernetes spread semantics: an empty
        # registered domain constrains skew even when nothing can land
        # there)
        its = corpus.generate(8)
        for it in its:
            for o in it.offerings:
                if o.zone() == "test-zone-c":
                    o.available = False
        o, k = self._run_both(self._zone_spread(6, 1), its=its)
        assert len(k.pod_errors) == len(o.pod_errors)
        assert k.node_count() == o.node_count()

        def zones_of(results):
            out = set()
            for c in results.new_node_claims:
                out |= set(
                    c.requirements.get(labels_mod.TOPOLOGY_ZONE).values
                )
            return out

        assert zones_of(k) == zones_of(o)
        assert "test-zone-c" not in zones_of(k)


class TestMinValuesPartialReach:
    """minValues pools reachable by only part of the batch: the reachable
    pods' claims honor the floor, the rest pack normally, nothing
    serializes host-side (the old gate sent the WHOLE batch to the
    oracle)."""

    def test_split_batch(self):
        from karpenter_tpu.api.objects import Taint, Toleration

        mv_pool = make_nodepool(
            name="mv",
            weight=10,
            taints=[Taint(key="team", value="x", effect="NoSchedule")],
            requirements=[
                NodeSelectorRequirement(
                    corpus.INSTANCE_FAMILY_LABEL, "Exists", (), min_values=2
                )
            ],
        )
        open_pool = make_nodepool(name="open")
        pools = [mv_pool, open_pool]
        its = corpus.generate(16)
        its_by_pool = {p.name: list(its) for p in pools}
        pods = make_pods(6, cpu="1") + make_pods(
            3, cpu="1",
            tolerations=[Toleration(key="team", operator="Exists")],
        )
        import copy

        o_pods = copy.deepcopy(pods)
        o = TpuSolver(
            pools, its_by_pool,
            Topology(Client(TestClock()), [], pools, its_by_pool, o_pods),
            config=SolverConfig(force_oracle=True),
        ).solve(o_pods)
        solver = TpuSolver(
            pools, its_by_pool,
            Topology(Client(TestClock()), [], pools, its_by_pool, pods),
        )
        k = solver.solve(pods)
        assert solver.fallback_solves == 0, solver.last_fallback_reasons
        assert len(k.pod_errors) == len(o.pod_errors) == 0
        assert k.node_count() == o.node_count()
        for claim in k.new_node_claims:
            if claim.template.requirements.has_min_values():
                fams = {
                    it.requirements.get(corpus.INSTANCE_FAMILY_LABEL).any()
                    for it in claim.instance_type_options
                }
                assert len(fams) >= 2

    def test_min_values_edit_busts_encode_cache(self):
        """A NodePool minValues edit (same keys, same values, different
        floor) must reset the shared EncodeCache: the dense floor tables
        live in the leased static cache, and repr(requirements) — the old
        fingerprint content — does not print min_values."""
        from karpenter_tpu.solver.driver import EncodeCache

        def pool_with_floor(floor):
            return make_nodepool(
                requirements=[
                    NodeSelectorRequirement(
                        corpus.INSTANCE_FAMILY_LABEL, "In", ("c", "m", "r"),
                        min_values=floor,
                    )
                ]
            )

        its = [
            corpus.make_instance_type(f, c)
            for f in ("c", "m", "r")
            for c in (2, 4)
        ]
        cache = EncodeCache()

        def solve(floor):
            pool = pool_with_floor(floor)
            its_by_pool = {pool.name: list(its)}
            pods = make_pods(2, cpu="1")
            solver = TpuSolver(
                [pool], its_by_pool,
                Topology(
                    Client(TestClock()), [], [pool], its_by_pool, pods
                ),
                encode_cache=cache,
            )
            return solver.solve(pods)

        k2 = solve(2)
        assert not k2.pod_errors
        k4 = solve(4)  # only 3 families exist: now unsatisfiable
        assert len(k4.pod_errors) == 2, (
            "stale p_mvmin served after a minValues edit"
        )

    def test_unsatisfiable_floor_matches_oracle(self):
        pool = make_nodepool(
            requirements=[
                NodeSelectorRequirement(
                    corpus.INSTANCE_FAMILY_LABEL, "In", ("c",), min_values=3
                )
            ]
        )
        its = [corpus.make_instance_type("c", c) for c in (2, 4)]
        its_by_pool = {pool.name: list(its)}
        pods = make_pods(2, cpu="1")
        solver = TpuSolver(
            [pool], its_by_pool,
            Topology(Client(TestClock()), [], [pool], its_by_pool, pods),
        )
        k = solver.solve(pods)
        assert solver.fallback_solves == 0
        assert len(k.pod_errors) == 2 and not k.new_node_claims


class TestVolumeLedger:
    """Volumes as pack-phase capacity ledgers: fresh unshared volumes ride
    the kernel (attach-slot columns); sharing/attachment shapes route
    host-side, exactly like the oracle's per-node dedup."""

    def _client_with_volumes(self, n, driver="csi.test", shared=False):
        clock = TestClock()
        client = Client(clock)
        for i in range(n):
            name = "pv-shared" if shared else f"pv-{i}"
            if not shared or i == 0:
                client.create(
                    PersistentVolume(
                        metadata=ObjectMeta(name=name), driver=driver
                    )
                )
            client.create(
                PersistentVolumeClaim(
                    metadata=ObjectMeta(name=f"claim-{i}"),
                    volume_name=name,
                )
            )
        return client

    def _vol_pods(self, n):
        pods = []
        for i in range(n):
            p = make_pod(cpu="1", memory="1Gi")
            p.spec.volumes = [PersistentVolumeClaimRef(claim_name=f"claim-{i}")]
            pods.append(p)
        return pods

    def test_fresh_volumes_ride_kernel(self):
        client = self._client_with_volumes(4)
        pool = make_nodepool()
        its = corpus.generate(10)
        its_by_pool = {pool.name: list(its)}
        pods = self._vol_pods(4) + make_pods(3, cpu="1")
        solver = TpuSolver(
            [pool], its_by_pool,
            Topology(client, [], [pool], its_by_pool, pods),
            volume_resolver=VolumeResolver(client),
        )
        k = solver.solve(pods)
        assert solver.fallback_solves == 0, solver.last_fallback_reasons
        assert not k.pod_errors

    def test_attach_limit_respected_on_existing_node(self):
        from helpers import make_state_node

        client = self._client_with_volumes(3)
        sn = make_state_node(name="node-1", cpu="64", memory="256Gi")
        sn.volume_limits = {"csi.test": 1}
        pool = make_nodepool()
        its = corpus.generate(10)
        its_by_pool = {pool.name: list(its)}
        pods = self._vol_pods(3)
        solver = TpuSolver(
            [pool], its_by_pool,
            Topology(client, [sn], [pool], its_by_pool, pods),
            state_nodes=[sn],
            volume_resolver=VolumeResolver(client),
        )
        k = solver.solve(pods)
        assert solver.fallback_solves == 0, solver.last_fallback_reasons
        assert not k.pod_errors
        # at most one volume pod landed on the limited node
        on_node = sum(
            1
            for en in k.existing_nodes
            if en.name == "node-1"
            for p in en.pods
            if p.spec.volumes
        )
        assert on_node <= 1
        # and its usage ledger recorded the attachment for the next pass
        en = next(e for e in k.existing_nodes if e.name == "node-1")
        attached = (
            sum(en.volume_usage.attached_counts().values())
            if en.volume_usage
            else 0
        )
        assert attached == on_node

    def test_storage_named_driver_quantizes_whole_units(self):
        """Regression: a real-world CSI driver name containing 'storage'
        (pd.csi.storage.gke.io) must quantize attach slots as WHOLE units,
        not memory-like MiB — else the ledger rounds to ~0 and over-packs
        past the node's attach limit."""
        from helpers import make_state_node

        driver = "pd.csi.storage.gke.io"
        client = self._client_with_volumes(3, driver=driver)
        sn = make_state_node(name="node-1", cpu="64", memory="256Gi")
        sn.volume_limits = {driver: 1}
        pool = make_nodepool()
        its = corpus.generate(10)
        its_by_pool = {pool.name: list(its)}
        pods = self._vol_pods(3)
        solver = TpuSolver(
            [pool], its_by_pool,
            Topology(client, [sn], [pool], its_by_pool, pods),
            state_nodes=[sn],
            volume_resolver=VolumeResolver(client),
        )
        k = solver.solve(pods)
        assert solver.fallback_solves == 0
        assert not k.pod_errors
        on_node = sum(
            1
            for en in k.existing_nodes
            if en.name == "node-1"
            for p in en.pods
            if p.spec.volumes
        )
        assert on_node <= 1, "attach limit over-packed (quantization bug)"

    def test_shared_volume_routes_host_side(self):
        client = self._client_with_volumes(2, shared=True)
        pool = make_nodepool()
        its = corpus.generate(10)
        its_by_pool = {pool.name: list(its)}
        pods = self._vol_pods(2)
        solver = TpuSolver(
            [pool], its_by_pool,
            Topology(client, [], [pool], its_by_pool, pods),
            volume_resolver=VolumeResolver(client),
        )
        k = solver.solve(pods)
        assert not k.pod_errors
        # RWX sharing breaks the dense ledger: counted as a fallback
        assert solver.fallback_solves >= 1


class TestScenarioReservations:
    """Default-mode reservations ride the scenario batch: each scenario
    consumes a fresh ledger replay, matching per-scenario sequential
    solves on fresh solvers."""

    def _reserved_types(self, capacity=1, n=4):
        its = corpus.generate(n)
        for it in its[-2:]:
            res_req = Requirements(
                Requirement(
                    labels_mod.CAPACITY_TYPE_LABEL_KEY, Operator.IN,
                    [labels_mod.CAPACITY_TYPE_RESERVED],
                ),
                Requirement(
                    labels_mod.TOPOLOGY_ZONE, Operator.IN, ["test-zone-a"]
                ),
                Requirement(
                    cp.RESERVATION_ID_LABEL, Operator.IN, [f"res-{it.name}"]
                ),
            )
            it.offerings.append(
                cp.Offering(
                    requirements=res_req, price=0.001, available=True,
                    reservation_capacity=capacity,
                )
            )
        return its

    def _build(self, pods, its):
        pool = make_nodepool()
        its_by_pool = {pool.name: list(its)}
        topo = Topology(Client(TestClock()), [], [pool], its_by_pool, pods)
        return TpuSolver(
            [pool], its_by_pool, topo, reserved_capacity_enabled=True
        )

    def _sig(self, results):
        return (
            len(results.new_node_claims),
            sorted(
                len(c.reserved_offerings) for c in results.new_node_claims
            ),
            len(results.pod_errors),
        )

    def test_batched_matches_per_scenario_sequential(self):
        its = self._reserved_types(capacity=1)
        pods = make_pods(6, cpu="1")
        subsets = [pods[:2], pods[:4], pods]
        solver = self._build(pods, its)
        batched = solver.solve_scenarios(
            [Scenario(pods=s) for s in subsets]
        )
        assert batched is not None, "reservations must ride the batch now"
        assert solver.last_scenario_dispatches >= 1
        for subset, r_b in zip(subsets, batched):
            its2 = self._reserved_types(capacity=1)
            seq = self._build(subset, its2).solve(subset)
            assert self._sig(r_b) == self._sig(seq)

    def test_strict_mode_still_declines(self):
        from karpenter_tpu.scheduling.inflight import (
            RESERVED_OFFERING_MODE_STRICT,
        )

        its = self._reserved_types(capacity=1)
        pods = make_pods(3, cpu="1")
        pool = make_nodepool()
        its_by_pool = {pool.name: list(its)}
        topo = Topology(Client(TestClock()), [], [pool], its_by_pool, pods)
        solver = TpuSolver(
            [pool], its_by_pool, topo,
            reserved_capacity_enabled=True,
            reserved_offering_mode=RESERVED_OFFERING_MODE_STRICT,
        )
        assert solver.solve_scenarios([Scenario(pods=pods)]) is None
        assert solver.fallback_solves >= 1
