"""Headline benchmark: the BASELINE.json north-star shape.

Schedules 50k pending pods (100 distinct shapes) against 800 instance types
through the full TpuSolver path (grouping -> encoding -> fused TPU kernel ->
decode) and reports pods/sec against the reference's asserted floor of
100 pods/sec (scheduling_benchmark_test.go:51).

Prints exactly one JSON line.
"""

from __future__ import annotations

import json
import sys
import time
from typing import Tuple

N_PODS = 50_000
N_TYPES = 800
N_SHAPES = 100
BASELINE_PODS_PER_SEC = 100.0  # reference floor, scheduling_benchmark_test.go:51


PROBE_TIMEOUT_S = 90.0  # tunnel backend init is seconds when healthy


def _probe_tpu() -> bool:
    """Can the default (axon TPU tunnel) backend actually come up?

    A dead tunnel makes jax.devices() HANG rather than raise, so the probe
    runs in a disposable subprocess with a timeout; the parent's backend
    stays uninitialized and can still be switched to CPU.
    """
    import subprocess

    probe = (
        "import jax; d = jax.devices();"
        "print(d[0].platform, len(d))"
    )
    for attempt in range(2):
        try:
            out = subprocess.run(
                [sys.executable, "-c", probe],
                capture_output=True,
                timeout=PROBE_TIMEOUT_S,
                text=True,
            )
            if out.returncode == 0:
                print(f"bench: TPU probe ok: {out.stdout.strip()}", file=sys.stderr)
                return True
            print(
                f"bench: TPU probe attempt {attempt + 1} failed rc={out.returncode}:"
                f" {out.stderr.strip()[-500:]}",
                file=sys.stderr,
            )
        except subprocess.TimeoutExpired:
            print(
                f"bench: TPU probe attempt {attempt + 1} hung"
                f" >{PROBE_TIMEOUT_S:.0f}s (tunnel down?)",
                file=sys.stderr,
            )
        if attempt == 0:
            time.sleep(5.0)
    return False


def init_backend() -> Tuple[str, bool]:
    """Bring up the JAX backend, loudly. Returns (platform, fell_back).

    The benchmark wants the real TPU (the environment's default `axon`
    platform, a tunneled single chip).  If the tunnel is down — which
    manifests as a hang, not an error — fall back to CPU so a perf number
    is still recorded, and say so on stderr + in the metric name.
    """
    import jax

    # NB: the JAX_PLATFORMS env var is unreliable here — the environment's
    # sitecustomize pins jax.config.jax_platforms to 'axon,cpu' regardless;
    # only jax.config.update switches platforms. Probe iff axon leads.
    platforms = (jax.config.jax_platforms or "axon").split(",")
    fell_back = False
    if platforms[0] == "axon" and not _probe_tpu():
        print(
            "bench: TPU backend unavailable; falling back to CPU so a number"
            " is still captured",
            file=sys.stderr,
        )
        jax.config.update("jax_platforms", "cpu")
        fell_back = True
    devs = jax.devices()
    plat = devs[0].platform
    print(f"bench: platform={plat} devices={len(devs)}", file=sys.stderr)
    return plat, fell_back


def run_once():
    from karpenter_tpu.solver.example import example_solver

    solver, pods = example_solver(N_PODS, N_TYPES, N_SHAPES)
    t0 = time.perf_counter()
    results = solver.solve(pods)
    dt = time.perf_counter() - t0
    if results.pod_errors:
        print(
            f"bench: {len(results.pod_errors)} pods failed to schedule",
            file=sys.stderr,
        )
        sys.exit(1)
    return dt, results


def main():
    plat, fell_back = init_backend()
    # warm-up: compile the kernels for the bench shapes
    run_once()
    best = min(run_once()[0] for _ in range(3))
    value = N_PODS / best
    suffix = "-cpufallback" if fell_back else ""
    print(
        json.dumps(
            {
                "metric": f"scheduling-throughput-{N_PODS}pods-{N_TYPES}types{suffix}",
                "value": round(value, 1),
                "unit": "pods/sec",
                "vs_baseline": round(value / BASELINE_PODS_PER_SEC, 2),
            }
        )
    )


if __name__ == "__main__":
    main()
