"""Headline benchmark: the BASELINE.json north-star shape.

Schedules 50k pending pods (100 distinct shapes) against 800 instance types
through the full TpuSolver path (grouping -> encoding -> fused TPU kernel ->
decode) and reports pods/sec against the reference's asserted floor of
100 pods/sec (scheduling_benchmark_test.go:51).

Prints exactly one JSON line.
"""

from __future__ import annotations

import json
import sys
import time

N_PODS = 50_000
N_TYPES = 800
N_SHAPES = 100
BASELINE_PODS_PER_SEC = 100.0  # reference floor, scheduling_benchmark_test.go:51


def run_once():
    from karpenter_tpu.solver.example import example_solver

    solver, pods = example_solver(N_PODS, N_TYPES, N_SHAPES)
    t0 = time.perf_counter()
    results = solver.solve(pods)
    dt = time.perf_counter() - t0
    if results.pod_errors:
        print(
            f"bench: {len(results.pod_errors)} pods failed to schedule",
            file=sys.stderr,
        )
        sys.exit(1)
    return dt, results


def main():
    # warm-up: compile the kernels for the bench shapes
    run_once()
    best = min(run_once()[0] for _ in range(3))
    value = N_PODS / best
    print(
        json.dumps(
            {
                "metric": f"scheduling-throughput-{N_PODS}pods-{N_TYPES}types",
                "value": round(value, 1),
                "unit": "pods/sec",
                "vs_baseline": round(value / BASELINE_PODS_PER_SEC, 2),
            }
        )
    )


if __name__ == "__main__":
    main()
