"""BASELINE-contract benchmark: the full metric, not just one number.

BASELINE.json's metric is "pods-scheduled/sec + p99 Solve() latency;
packing-cost delta" over five configs. This driver:

- runs every BASELINE config (identical / mixed+gpu / constrained-50k /
  multi-node consolidation / spot+od with limits) plus a size grid
  ({500, 5k, 10k, 50k} pods x {10, 400, 800} types), reporting pods/sec
  and p99 solve latency per entry;
- computes the packing-cost delta vs the host oracle (the Go-FFD-equivalent
  semantic reference, scheduling/scheduler.py) for every config where the
  oracle run is affordable, asserting the <=2% bound from BASELINE.json;
- prints exactly ONE JSON line to stdout — the north-star config
  (50k constrained pods x 800 types) — and writes the full grid to
  bench_grid.json next to this file (stderr carries a readable table).

The reference's own benchmark harness is scheduling_benchmark_test.go:70-133
(grid + in-test floor); tests/test_perf_floor.py carries the in-test
equivalents of its assertions.
"""

from __future__ import annotations

import json
import os
import statistics
import sys
import time
from typing import Dict, List, Optional, Tuple

# silence XLA's ~2 KB host-feature-mismatch warning ("This could lead to
# execution errors such as SIGILL"): it fires when the persistent
# compilation cache replays an executable compiled on a different host and
# floods the captured BENCH_*.json stderr tail with CPU feature flags.
# Must be set before the first jax import in this process AND is inherited
# by the TPU-probe subprocess. Level 2 filters INFO+WARNING; real errors
# still surface.
os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "2")

N_HEADLINE_PODS = 50_000
N_HEADLINE_TYPES = 800
BASELINE_PODS_PER_SEC = 100.0  # reference floor, scheduling_benchmark_test.go:51
COST_DELTA_BOUND = 0.02  # BASELINE.json: <=2% packing-cost regression
ORACLE_POD_BUDGET = 12_000  # largest batch we run through the host oracle

PROBE_TIMEOUT_S = 90.0  # tunnel backend init is seconds when healthy


def _probe_tpu() -> bool:
    """Can the default (axon TPU tunnel) backend actually come up?

    A dead tunnel makes jax.devices() HANG rather than raise, so the probe
    runs in a disposable subprocess with a timeout; the parent's backend
    stays uninitialized and can still be switched to CPU.
    """
    import subprocess

    probe = (
        "import jax; d = jax.devices();"
        "print(d[0].platform, len(d))"
    )
    # force the log level into the child: the probe replays the persistent
    # compilation cache and its host-feature-mismatch warning blob
    # otherwise floods the captured BENCH_*.json stderr tail (a parent
    # environment that EXPORTS a lower level would win over setdefault)
    env = dict(os.environ)
    env["TF_CPP_MIN_LOG_LEVEL"] = "2"
    for attempt in range(2):
        try:
            out = subprocess.run(
                [sys.executable, "-c", probe],
                capture_output=True,
                timeout=PROBE_TIMEOUT_S,
                text=True,
                env=env,
            )
            if out.returncode == 0:
                print(f"bench: TPU probe ok: {out.stdout.strip()}", file=sys.stderr)
                return True
            print(
                f"bench: TPU probe attempt {attempt + 1} failed rc={out.returncode}:"
                f" {out.stderr.strip()[-500:]}",
                file=sys.stderr,
            )
        except subprocess.TimeoutExpired:
            print(
                f"bench: TPU probe attempt {attempt + 1} hung"
                f" >{PROBE_TIMEOUT_S:.0f}s (tunnel down?)",
                file=sys.stderr,
            )
        if attempt == 0:
            time.sleep(5.0)
    return False


def init_backend() -> Tuple[str, bool]:
    """Bring up the JAX backend, loudly. Returns (platform, fell_back)."""
    import jax

    # NB: the JAX_PLATFORMS env var is unreliable here — the environment's
    # sitecustomize pins jax.config.jax_platforms to 'axon,cpu' regardless;
    # only jax.config.update switches platforms. Probe iff axon leads.
    platforms = (jax.config.jax_platforms or "axon").split(",")
    fell_back = False
    if platforms[0] == "axon" and not _probe_tpu():
        print(
            "bench: TPU backend unavailable; falling back to CPU so a number"
            " is still captured",
            file=sys.stderr,
        )
        jax.config.update("jax_platforms", "cpu")
        fell_back = True
    # persistent compilation cache: each grid config compiles its own shape
    # bucket; cache across runs so repeat benches skip straight to execution
    jax.config.update(
        "jax_compilation_cache_dir", os.path.expanduser("~/.cache/jax")
    )
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    devs = jax.devices()
    plat = devs[0].platform
    print(f"bench: platform={plat} devices={len(devs)}", file=sys.stderr)
    return plat, fell_back


# -- workload builders ------------------------------------------------------


def _build(config: str, n_pods: int, n_types: int):
    """(solver_factory, pods) for a named config. A fresh solver per run
    keeps solves independent; the EncodeCache is shared so catalog encoding
    amortizes exactly as it does in the provisioner."""
    from karpenter_tpu.cloudprovider import corpus
    from karpenter_tpu.kube import Client, TestClock
    from karpenter_tpu.scheduling.topology import Topology
    from karpenter_tpu.solver import TpuSolver
    from karpenter_tpu.solver.driver import EncodeCache
    from karpenter_tpu.solver.example import example_nodepool
    from karpenter_tpu.solver.workloads import (
        constrained_mix, diverse_reference_mix, identical_pods, mixed_pods,
        spot_od_pools,
    )

    if config == "identical":
        pods = identical_pods(n_pods)
        pools = [example_nodepool()]
    elif config == "mixed":
        pods = mixed_pods(n_pods)
        pools = [example_nodepool()]
    elif config == "mixed-cpu":
        # small type corpora carry no GPU types; keep the mix schedulable
        pods = mixed_pods(n_pods, gpu_fraction=0.0)
        pools = [example_nodepool()]
    elif config == "constrained":
        pods = constrained_mix(n_pods)
        pools = [example_nodepool()]
    elif config == "diverse-ref":
        pods = diverse_reference_mix(n_pods)
        pools = [example_nodepool()]
    elif config == "spot-od-limits":
        pods = mixed_pods(n_pods)
        pools = spot_od_pools()
    else:
        raise ValueError(config)

    its = corpus.generate(n_types)
    its_by_pool = {p.name: list(its) for p in pools}
    cache = EncodeCache()

    def make_solver(force_oracle: bool = False):
        from karpenter_tpu.solver.driver import SolverConfig

        topology = Topology(Client(TestClock()), [], pools, its_by_pool, pods)
        return TpuSolver(
            pools,
            its_by_pool,
            topology,
            config=SolverConfig(force_oracle=force_oracle),
            encode_cache=cache,
        )

    return make_solver, pods


def _phase_columns(run_fn) -> Dict:
    """Per-phase wall-time columns from ONE traced pass of ``run_fn`` —
    run OUTSIDE the timed trials, so the bench numbers stay untraced and
    the acceptance no-regression bound applies to the production path.
    The columns split the end-to-end decision the way the ROADMAP's
    delta-encode item needs: host-side encode, host→device transfer,
    kernel dispatch (compute + readback), and decode."""
    from karpenter_tpu import obs

    tracer = obs.install(obs.Tracer(obs.PerfClock()))
    try:
        run_fn()
    finally:
        obs.uninstall()
    totals = tracer.phase_totals()

    def ms(phase: str) -> float:
        return round(totals.get(phase, 0.0) * 1000, 2)

    return {
        "encode_ms": ms("solve.encode"),
        "transfer_ms": ms("solve.transfer"),
        "kernel_ms": ms("solve.dispatch"),
        "decode_ms": ms("solve.decode"),
    }


def _routed_fraction(solver, pods) -> float:
    from karpenter_tpu.solver import encode as enc

    groups, rest = enc.partition_and_group(pods, topology=solver.oracle.topology)
    routed = sum(g.count for g in groups)
    return routed / max(len(pods), 1)


def group_shape_columns(solver, pods) -> Dict:
    """Group-axis shape of one encoded batch (ISSUE 13): how fragmented
    the group axis is, what the pow2 bucket runs at, how many live
    (group, key) pairs the segment index carries, and the anti-affinity
    claim demand (pods of self-counted shared-hostname groups — each
    forces up to cap-many claims, the diverse mix's ~1k one-pod claims).
    Encodes against a throwaway vocab/cache so the solver's warm state
    (prior snapshot, row banks, device buffers) is untouched — one cold
    encode per grid row, outside the timed trials."""
    import numpy as np

    from karpenter_tpu.solver import encode as enc

    groups, _ = enc.partition_and_group(pods, topology=solver.oracle.topology)
    if not groups:
        return {
            "groups": 0, "bucketed_groups": 0, "live_gt_pairs": 0,
            "antiaffinity_claims": 0,
        }
    templates = solver.oracle.templates
    snap = enc.encode(
        groups,
        templates,
        {t.node_pool_name: t.instance_type_options for t in templates},
        daemon_overhead=solver.oracle.daemon_overhead,
        pool_limits=solver.pool_limits,
    )
    anti = (np.asarray(snap.g_hstg) >= 0) & np.asarray(snap.g_hself)
    return {
        "groups": len(snap.groups),
        "bucketed_groups": enc._next_pow2(len(snap.groups), floor=8),
        "live_gt_pairs": int(np.asarray(snap.gk_w).sum()),
        "antiaffinity_claims": int(np.asarray(snap.g_count)[anti].sum()),
    }


def run_config(
    config: str, n_pods: int, n_types: int, trials: int, with_oracle: bool
) -> Dict:
    make_solver, pods = _build(config, n_pods, n_types)
    solver = make_solver()
    routed = _routed_fraction(solver, pods)

    # warm-up compiles the kernels for this shape bucket — twice: the
    # first solve runs at the a-priori NMAX estimate and records the
    # observed claim count in the shared EncodeCache; the second compiles
    # the adaptive (smaller) shape the timed trials will actually run
    make_solver().solve(pods)
    warm = make_solver().solve(pods)
    if warm.pod_errors:
        print(
            f"bench[{config}]: {len(warm.pod_errors)} pods failed to schedule",
            file=sys.stderr,
        )
        sys.exit(1)

    times: List[float] = []
    tpu_results = warm
    s = None
    for _ in range(trials):
        s = make_solver()
        t0 = time.perf_counter()
        tpu_results = s.solve(pods)
        times.append(time.perf_counter() - t0)
    best = min(times)
    p99 = (
        statistics.quantiles(times, n=100)[98]
        if len(times) >= 5
        else max(times)
    )

    entry = {
        "config": config,
        "pods": n_pods,
        "types": n_types,
        "pods_per_sec": round(n_pods / best, 1),
        "best_ms": round(best * 1000, 1),
        "p99_ms": round(p99 * 1000, 1),
        "nodes": tpu_results.node_count(),
        "cost": round(tpu_results.total_price(), 4),
        "tpu_routed_fraction": round(routed, 4),
        # ISSUE 10: sequential-fallback gate count of one solve — the
        # reference configs (diverse-ref, constrained) must report 0 now
        # that topology/minValues/volumes/reservations ride the kernel
        "fallback_solves": s.fallback_solves if s is not None else 0,
        # ISSUE 13: relaxation pre-solver telemetry — the fraction of the
        # batch the bulk pre-solver placed and the residual the exact
        # kernel kept (0 / full on non-separable shapes), plus guard
        # rejections (must stay 0: a reject means a full exact re-solve)
        "relax_routed_fraction": round(
            (s.last_relax_pods if s is not None else 0) / max(len(pods), 1),
            4,
        ),
        "residual_pods": (
            s.last_relax_residual_pods
            if s is not None and s.last_relax_pods
            else len(pods)
        ),
        "relax_rejects": s.relax_rejects if s is not None else 0,
    }
    # phase attribution from one extra traced solve (compiled shapes are
    # already warm, so this costs one execution, not a compile)
    entry.update(_phase_columns(lambda: make_solver().solve(pods)))
    entry.update(group_shape_columns(solver, pods))

    if with_oracle and n_pods <= ORACLE_POD_BUDGET:
        t0 = time.perf_counter()
        oracle_results = make_solver(force_oracle=True).solve(pods)
        entry["oracle_ms"] = round((time.perf_counter() - t0) * 1000, 1)
        o_cost = oracle_results.total_price()
        t_cost = tpu_results.total_price()
        delta = (t_cost - o_cost) / o_cost if o_cost > 0 else 0.0
        entry["oracle_cost"] = round(o_cost, 4)
        entry["cost_delta"] = round(delta, 5)
        entry["oracle_nodes"] = oracle_results.node_count()
        if delta > COST_DELTA_BOUND:
            # record the violation and keep benching: one config over the
            # bound must not throw away the whole grid (the run still
            # exits nonzero at the end). Known case: PARITY.md
            # "Known cost-gap" — constrained 10k x 400 at ~+10%.
            print(
                f"bench[{config}]: cost delta {delta:.4f} exceeds"
                f" {COST_DELTA_BOUND:.2f} bound (recorded; bench continues)",
                file=sys.stderr,
            )
            entry["cost_bound_violated"] = True
    return entry


def run_churn(
    n_pods: int, churn_pct: int, n_types: int = 400, ticks: int = 5
) -> Dict:
    """Steady-state reconcile under pod churn (ISSUE 8's warm path).

    After the cold first solve, every tick replaces ``churn_pct``% of the
    pods with fresh ones and re-solves on the SAME EncodeCache — the
    shape a reconcile loop sees at millions-of-pods churn, where encode
    and host↔device transfer (not the kernel) dominate unless they
    amortize. The entry reports the warm per-phase columns from one
    traced warm tick (encode_ms/transfer_ms/kernel_ms/decode_ms plus
    delta_rows and encode_reused), and the SAME snapshot's cold columns
    (fresh cluster encoding, statics and compile cache warm — the
    pre-incremental steady-state cost) as ``cold_encode_ms``/
    ``cold_transfer_ms`` for the >=5x warm-path acceptance bound."""
    import random as _random

    from karpenter_tpu.cloudprovider import corpus
    from karpenter_tpu.kube import Client, TestClock
    from karpenter_tpu.scheduling.topology import Topology
    from karpenter_tpu.solver import TpuSolver
    from karpenter_tpu.solver.driver import EncodeCache
    from karpenter_tpu.solver.example import example_nodepool
    from karpenter_tpu.solver.workloads import mixed_pods

    pools = [example_nodepool()]
    its_by_pool = {pools[0].name: corpus.generate(n_types)}
    warm_cache = EncodeCache()
    rng = _random.Random(42)
    pods = mixed_pods(n_pods, gpu_fraction=0.0)

    def solver_for(current_pods, cache):
        topo = Topology(
            Client(TestClock()), [], pools, its_by_pool, current_pods
        )
        return TpuSolver(pools, its_by_pool, topo, encode_cache=cache)

    def churn(current_pods):
        """Steady-state churn: k pods die, k new pods of shapes already
        in the workload arrive (a deployment's pods being replaced /
        rebalanced). Group SHAPES stay, counts shift — the delta the
        incremental encoder turns into a tiny count-row update. Runs
        outside the timed region: churn is cluster change, not solver
        work."""
        k = max(1, n_pods * churn_pct // 100)
        regen = mixed_pods(n_pods, gpu_fraction=0.0)  # same seed: same shapes
        idx = rng.sample(range(len(current_pods)), k)
        jdx = rng.sample(range(len(regen)), k)
        out = list(current_pods)
        for i, j in zip(idx, jdx):
            out[i] = regen[j]
        return out

    # cold warm-ups: a-priori + adaptive NMAX shapes compile here
    solver_for(pods, warm_cache).solve(pods)
    solver_for(pods, warm_cache).solve(pods)

    times: List[float] = []
    delta_rows: List[int] = []
    reused = 0
    for _ in range(ticks):
        pods = churn(pods)
        s = solver_for(pods, warm_cache)
        t0 = time.perf_counter()
        s.solve(pods)
        times.append(time.perf_counter() - t0)
        delta_rows.append(s.last_delta_rows)
        reused += bool(s.last_encode_reused)

    # warm phase columns: one traced churn tick on the warm cache
    pods = churn(pods)
    warm_solver = solver_for(pods, warm_cache)
    warm_phases = _phase_columns(lambda: warm_solver.solve(pods))
    # cold phase columns of the SAME snapshot: the pre-incremental
    # steady-state cost — deep catalog fingerprint, full cluster encode,
    # full host->device transfer every reconcile (compiled kernels kept;
    # compilation was always amortized)
    cold_cache = EncodeCache()
    solver_for(pods, cold_cache).solve(pods)

    def cold_solve():
        cold_cache.cluster.invalidate("bench cold baseline")
        if cold_cache.device_store is not None:
            cold_cache.device_store.reset()
        cold_cache._prekey = None  # re-pay the deep lease fingerprint
        for its in its_by_pool.values():
            for it in its:
                # the per-type static-fingerprint memo is part of the warm
                # machinery too: the cold column documents the PRE-
                # incremental steady state, which re-derived it per solve
                if hasattr(it, "_ktpu_static_fp"):
                    try:
                        object.__delattr__(it, "_ktpu_static_fp")
                    except AttributeError:
                        pass
        solver_for(pods, cold_cache).solve(pods)

    cold_phases = _phase_columns(cold_solve)

    best = min(times)
    return {
        "config": f"churn-{churn_pct}pct",
        "pods": n_pods,
        "types": n_types,
        "pods_per_sec": round(n_pods / best, 1),
        "best_ms": round(best * 1000, 1),
        "p99_ms": round(max(times) * 1000, 1),
        "encode_reused_fraction": round(reused / max(ticks, 1), 2),
        "delta_rows": int(statistics.median(delta_rows)),
        "traced_delta_rows": warm_solver.last_delta_rows,
        **warm_phases,
        "cold_encode_ms": cold_phases["encode_ms"],
        "cold_transfer_ms": cold_phases["transfer_ms"],
        **group_shape_columns(warm_solver, pods),
    }


def run_constraint_churn(
    config: str, n_pods: int, n_types: int = 400, ticks: int = 4
) -> Dict:
    """Steady-state reconcile under churn for the CONSTRAINED workloads
    (ISSUE 10): topology-carrying batches now participate in the
    delta-encode contract (content-tagged TopoSpecs), so a repeat solve of
    an unchanged constrained cluster must hit the REUSE outcome and churn
    ticks must ride row deltas instead of forcing FULL re-encodes — and
    the whole workload must report zero sequential fallbacks."""
    import random as _random

    from karpenter_tpu.cloudprovider import corpus
    from karpenter_tpu.kube import Client, TestClock
    from karpenter_tpu.scheduling.topology import Topology
    from karpenter_tpu.solver import TpuSolver
    from karpenter_tpu.solver.driver import EncodeCache
    from karpenter_tpu.solver.example import example_nodepool
    from karpenter_tpu.solver.workloads import (
        constrained_mix, diverse_reference_mix,
    )

    mix = (
        constrained_mix
        if config == "constrained-churn"
        else diverse_reference_mix
    )
    pools = [example_nodepool()]
    its_by_pool = {pools[0].name: corpus.generate(n_types)}
    cache = EncodeCache()
    rng = _random.Random(7)
    pods = mix(n_pods)

    def solver_for(current_pods):
        topo = Topology(
            Client(TestClock()), [], pools, its_by_pool, current_pods
        )
        return TpuSolver(pools, its_by_pool, topo, encode_cache=cache)

    def churn(current_pods):
        # same-seed regeneration keeps the shape pool identical; swapping
        # k pods shifts group counts (and occasionally the label-keyed
        # group set — the topology delta the content tags must absorb)
        k = max(1, n_pods // 100)
        regen = mix(n_pods)
        idx = rng.sample(range(len(current_pods)), k)
        jdx = rng.sample(range(len(regen)), k)
        out = list(current_pods)
        for i, j in zip(idx, jdx):
            out[i] = regen[j]
        return out

    solver_for(pods).solve(pods)
    solver_for(pods).solve(pods)  # a-priori + adaptive NMAX warm-ups

    times: List[float] = []
    delta_rows: List[int] = []
    fallbacks = 0
    full_encodes = 0
    for _ in range(ticks):
        pods = churn(pods)
        s = solver_for(pods)
        t0 = time.perf_counter()
        s.solve(pods)
        times.append(time.perf_counter() - t0)
        delta_rows.append(s.last_delta_rows)
        fallbacks += s.fallback_solves
        full_encodes += int(
            not s.last_encode_reused and s.last_delta_rows == 0
        )
    # the REUSE proof: an unchanged re-solve of the topology-carrying
    # cluster must hit the content-hash fast path (PR-8 contract extended)
    s2 = solver_for(pods)
    s2.solve(pods)
    repeat_reused = bool(s2.last_encode_reused)
    fallbacks += s2.fallback_solves

    best = min(times)
    return {
        "config": config,
        "pods": n_pods,
        "types": n_types,
        "pods_per_sec": round(n_pods / best, 1),
        "best_ms": round(best * 1000, 1),
        "p99_ms": round(max(times) * 1000, 1),
        "delta_rows": int(statistics.median(delta_rows)),
        "full_encodes": full_encodes,
        "repeat_reused": repeat_reused,
        "fallback_solves": fallbacks,
        **group_shape_columns(s2, pods),
    }


def _run_consolidation_method(config: str, build_env, n_nodes: int) -> Dict:
    """Warm + best-of-2 timed passes over fresh envs. The scenario-batched
    search (methods.py) evaluates every probe point of the replacement
    search in <= 2 kernel dispatches; the entry records the probe count,
    per-DISPATCH wall times, and the dispatch count alongside the
    decision."""
    import gc

    ctx, method, candidates, budgets = build_env(n_nodes)
    # warm pass compiles the scenario shape buckets (both dispatches of
    # the search run here, so the timed passes hit the compile cache)
    method.compute_command(candidates, budgets)
    best = None
    stats = {}
    for _ in range(2):
        # fresh env so memoization doesn't carry; collect the previous
        # env's garbage OUTSIDE the timed region (a GC pause mid-decision
        # is allocator noise, not solver latency)
        ctx, method, candidates, budgets = build_env(n_nodes)
        gc.collect()
        t0 = time.perf_counter()
        cmd = method.compute_command(candidates, budgets)
        dt = time.perf_counter() - t0
        if best is None or dt < best:
            best = dt
            stats = {
                "candidates": len(candidates),
                "decision": cmd.decision if cmd else "no-op",
                "disrupted": len(cmd.candidates) if cmd else 0,
                "probes": getattr(method, "last_probes", 0),
                "probe_ms": getattr(method, "last_probe_ms", []),
                "dispatches": getattr(method, "last_dispatches", 0),
            }
    # phase attribution: one traced decision over a fresh env (the whole
    # probe set's encode/transfer/kernel/decode, summed across dispatches)
    ctx, method, candidates, budgets = build_env(n_nodes)
    phases = _phase_columns(
        lambda: method.compute_command(candidates, budgets)
    )
    return {
        "config": config,
        "nodes": n_nodes,
        "best_ms": round(best * 1000, 1),
        "pods_per_sec": None,
        "p99_ms": round(best * 1000, 1),
        **stats,
        **phases,
    }


def run_consolidation(n_nodes: int) -> Dict:
    """BASELINE config[3]: multi-node consolidation over an underutilized
    cluster — every probe point of the binary search rides the scenario
    axis in <= 2 kernel dispatches (multinodeconsolidation.go:112-167 is
    the decision shape)."""
    from karpenter_tpu.solver.workloads import build_consolidation_env

    return _run_consolidation_method(
        "consolidation", build_consolidation_env, n_nodes
    )


def run_single_consolidation(n_nodes: int) -> Dict:
    """Single-node consolidation over the same cluster: the per-candidate
    sweep (singlenodeconsolidation.go:34-174) evaluated in scenario-batched
    chunks."""
    from karpenter_tpu.solver.workloads import build_single_consolidation_env

    return _run_consolidation_method(
        "consolidation-single", build_single_consolidation_env, n_nodes
    )


def run_twin(n_nodes: int = 2000, minutes: int = 10) -> Dict:
    """Twin row (ISSUE 12): a deterministic churn replay over a fabricated
    fleet — sustained solves/sec across the whole roster plus the
    worst-minute SLO margins (the numbers the day-scale soak asserts,
    measured at bench scale). ``best_ms`` is roster wall time per
    simulated minute so ``--compare`` can gate twin-loop regressions the
    way it gates solver ones."""
    from karpenter_tpu.sim import trace as twin_trace
    from karpenter_tpu.sim.slo import SLOConfig
    from karpenter_tpu.sim.twin import ClusterProfile, ClusterTwin, TwinConfig

    profile = ClusterProfile(nodes=n_nodes, pods_per_node=10)
    events = twin_trace.generate(
        7,
        twin_trace.ChurnProfile(
            minutes=minutes, pods_per_minute=8,
            reclaim_minutes=(2,), reclaim_count=4, ice_minutes=(4,),
        ),
    )
    slo = SLOConfig(p99_decision_latency_ms=10_000.0)
    cfg = TwinConfig(
        seed=7, minutes=minutes, slo=slo, assert_slos=False,
    )
    with ClusterTwin(events, profile=profile, config=cfg) as twin:
        reports = twin.run()
        # the compare-gated number is pure roster wall per simulated
        # minute: bootstrap/fabrication cost is setup, not the replay
        # loop, and folding it in would let a loop regression hide
        # behind amortized setup (or a setup change trip the gate)
        wall = twin.roster_wall_s()
        worst = twin.worst_minute()
        worst_cost = max(
            (
                r.fleet_price / r.cost_lower_bound
                for r in reports
                if r.cost_lower_bound > 0
            ),
            default=0.0,
        )
        return {
            "config": "twin",
            "nodes": n_nodes,
            "pods": n_nodes * profile.pods_per_node,
            "minutes": minutes,
            "best_ms": round(wall * 1000 / max(minutes, 1), 1),
            "pods_per_sec": None,
            "p99_ms": round(worst.p99_latency_ms, 1) if worst else 0.0,
            "solves_per_sec": round(twin.solves_per_sec(), 2),
            "decisions": len(twin.audit.query()),
            "worst_minute_p99_ms": (
                round(worst.p99_latency_ms, 1) if worst else 0.0
            ),
            "p99_margin_ms": round(
                slo.p99_decision_latency_ms
                - (worst.p99_latency_ms if worst else 0.0),
                1,
            ),
            "worst_cost_ratio": round(worst_cost, 3),
            "cost_margin": round(slo.max_cost_vs_lower_bound - worst_cost, 3),
            "fallback_solves": sum(r.fallback_solves for r in reports),
            "delta_fallbacks": sum(r.delta_fallbacks for r in reports),
            "slo_violations": sum(len(r.violations) for r in reports),
            "reclaimed": twin.reclaimed,
            "iced_cells": twin.iced_cells,
        }


def _decision_key(results) -> tuple:
    """Canonical decision content of a Results: per-claim (pods, type
    options) plus the open-node fill set — what "byte-identical decisions"
    compares across the mesh/single-device pair."""
    return (
        tuple(
            sorted(
                (
                    tuple(sorted(p.metadata.name for p in c.pods)),
                    tuple(sorted(t.name for t in c.instance_type_options)),
                )
                for c in results.new_node_claims
            )
        ),
        results.node_count(),
        round(results.total_price(), 6),
    )


def run_mesh(
    n_pods: int = 500_000,
    n_types: int = 2_000,
    device_counts=(1, 2, 4, 8),
    trials: int = 1,
) -> List[Dict]:
    """Fleet-scale weak-scaling rows (ISSUE 14): a region's pending pods in
    ONE sharded dispatch. Pod count grows with the device count (constant
    pods-per-chip — weak scaling), the solve runs THROUGH the driver with
    ``SolverConfig(mesh=...)`` on the r06 layout (segment live-pair axis on
    'data', types on 'model', scan state replicated), and the largest row
    is checked decision-identical against the single-device solver. On the
    virtual host-device mesh every "chip" shares the host's cores, so
    pods_per_sec measures GSPMD partitioning overhead (the scaling SHAPE);
    the per-step-collective structure itself is pinned by
    tests/test_parallel.py, host-independently."""
    import jax

    from karpenter_tpu.cloudprovider import corpus
    from karpenter_tpu.kube import Client, TestClock
    from karpenter_tpu.parallel.mesh import make_mesh
    from karpenter_tpu.scheduling.topology import Topology
    from karpenter_tpu.solver import TpuSolver
    from karpenter_tpu.solver.driver import EncodeCache, SolverConfig
    from karpenter_tpu.solver.example import example_nodepool
    from karpenter_tpu.solver.workloads import constrained_mix

    avail = len(jax.devices())
    counts = [d for d in device_counts if d <= avail]
    dmax = max(counts)
    pools = [example_nodepool()]
    its_by_pool = {pools[0].name: corpus.generate(n_types)}

    rows: List[Dict] = []
    for d in counts:
        pods = constrained_mix(max(1, n_pods * d // dmax))
        mesh = make_mesh(d)

        def solver_for(cfg, cache):
            topo = Topology(
                Client(TestClock()), [], pools, its_by_pool, pods
            )
            return TpuSolver(
                pools, its_by_pool, topo, config=cfg, encode_cache=cache
            )

        cfg = SolverConfig(mesh=mesh)
        cache = EncodeCache()
        # a-priori NMAX + adaptive-shape warm-ups (compile both buckets)
        solver_for(cfg, cache).solve(pods)
        solver_for(cfg, cache).solve(pods)
        times: List[float] = []
        s = None
        results = None
        reused = False
        fallbacks = 0
        for _ in range(trials):
            # the timed trial doubles as the sharding-aware warm-path
            # proof: the cache is warm, so the unchanged re-solve must hit
            # the content-hash REUSE outcome with the buffers still
            # mesh-resident — fallback_solves stays 0 throughout
            s = solver_for(cfg, cache)
            t0 = time.perf_counter()
            results = s.solve(pods)
            times.append(time.perf_counter() - t0)
            reused = bool(s.last_encode_reused)
            fallbacks += s.fallback_solves
        best = min(times)
        pps = len(pods) / best
        entry = {
            "config": "mesh-weak",
            "pods": len(pods),
            "types": n_types,
            "devices": d,
            "mesh": "x".join(str(x) for x in mesh.devices.shape),
            "pods_per_sec": round(pps, 1),
            "pods_per_chip_per_sec": round(pps / d, 1),
            "best_ms": round(best * 1000, 1),
            "p99_ms": round(max(times) * 1000, 1),
            "fallback_solves": fallbacks,
            "repeat_reused": reused,
            "delta_rows": int(s.last_delta_rows),
        }
        if d == dmax and results is not None:
            # the parity verdict: the region-scale mesh solve must commit
            # the SAME decisions as the single-device program
            single = solver_for(SolverConfig(), EncodeCache())
            entry["parity"] = bool(
                _decision_key(single.solve(pods)) == _decision_key(results)
            )
            entry["fallback_solves"] += single.fallback_solves
        print(
            "bench[mesh]: "
            + " ".join(f"{k}={v}" for k, v in entry.items()),
            file=sys.stderr,
        )
        rows.append(entry)
    return rows


def run_tenants(
    n_tenants: int = 4,
    n_pods: int = 200,
    n_types: int = 100,
    rounds: int = 4,
) -> Dict:
    """Sustained multi-tenant traffic through ONE TenantService (ISSUE
    20): ``n_tenants`` concurrent control planes each issuing ``rounds``
    solves of ``n_pods`` pods against their own warm state. Reports
    aggregate solves/sec, the per-tenant p50/p99 solve latency, and the
    noisy-neighbor delta — the p50 shift a bystander tenant sees while
    an extra tenant hammers oversized batches alongside it. The
    isolation gates ride the row: zero in-process fallbacks, zero
    admission rejections, every tenant still on the batched rung."""
    import threading

    from karpenter_tpu.cloudprovider import corpus
    from karpenter_tpu.kube import TestClock
    from karpenter_tpu.solver import wire
    from karpenter_tpu.solver.example import example_nodepool
    from karpenter_tpu.solver.service import TenantService
    from karpenter_tpu.solver.tenancy import TenantQoS, TenantRegistry
    from karpenter_tpu.solver.workloads import constrained_mix

    pools = [example_nodepool()]
    its_by_pool = {pools[0].name: corpus.generate(n_types)}
    tenants = [f"tenant-{i}" for i in range(n_tenants)]

    def request(n: int) -> bytes:
        return wire.encode_solve_request(
            constrained_mix(n), pools, its_by_pool,
            solver_options={"reserved_capacity_enabled": False},
        )

    def service() -> TenantService:
        return TenantService(
            registry=TenantRegistry(
                clock=TestClock(),
                max_inflight=max(32, 2 * n_tenants),
                qos={
                    "standard": TenantQoS(
                        rate=10_000.0, burst=10_000.0,
                        max_queue=max(32, 2 * n_tenants),
                    )
                },
            )
        )

    svc = service()
    reqs = {tid: request(n_pods) for tid in tenants}
    # warm every tenant's cache + compile outside the timed phase
    for tid in tenants:
        svc.solve_for(tid, wire.decode_solve_request(reqs[tid]))

    def drive(extra_noise: bool) -> Dict[str, List[float]]:
        latencies: Dict[str, List[float]] = {tid: [] for tid in tenants}
        errors: List[Exception] = []
        stop = threading.Event()
        n_threads = n_tenants + (1 if extra_noise else 0)
        barrier = threading.Barrier(n_threads)

        def tenant_loop(tid):
            try:
                barrier.wait()
                for _ in range(rounds):
                    snap = wire.decode_solve_request(reqs[tid])
                    t0 = time.perf_counter()
                    svc.solve_for(tid, snap)
                    latencies[tid].append(time.perf_counter() - t0)
            except Exception as exc:  # pragma: no cover - bench resilience
                errors.append(exc)
            finally:
                stop.set()

        def noise_loop():
            noisy_req = request(4 * n_pods)
            try:
                barrier.wait()
                while not stop.is_set():
                    svc.solve_for(
                        "noisy", wire.decode_solve_request(noisy_req)
                    )
            except Exception as exc:  # pragma: no cover - bench resilience
                errors.append(exc)

        threads = [
            threading.Thread(target=tenant_loop, args=(tid,))
            for tid in tenants
        ]
        if extra_noise:
            threads.append(threading.Thread(target=noise_loop))
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join(600)
        if errors:
            raise errors[0]
        latencies["_wall"] = [time.perf_counter() - t0]
        return latencies

    quiet = drive(extra_noise=False)
    noisy = drive(extra_noise=True)

    def flat(lat: Dict[str, List[float]]) -> List[float]:
        return sorted(
            s for tid, ls in lat.items() if tid != "_wall" for s in ls
        )

    q = flat(quiet)
    nz = flat(noisy)
    p50 = statistics.median(q)
    noisy_p50 = statistics.median(nz)
    total_solves = len(q)
    stats = svc.registry.stats()
    entry = {
        "config": "tenants",
        "tenants": n_tenants,
        "pods": n_pods,
        "types": n_types,
        "solves_per_sec": round(total_solves / quiet["_wall"][0], 2),
        "best_ms": round(min(q) * 1000, 1),
        "p50_ms": round(p50 * 1000, 1),
        "p99_ms": round(q[max(0, int(len(q) * 0.99) - 1)] * 1000, 1),
        "noisy_p50_ms": round(noisy_p50 * 1000, 1),
        "noisy_delta_ms": round((noisy_p50 - p50) * 1000, 1),
        "fallback_solves": sum(
            s["fallback_solves"] for s in stats if s["tenant"] != "noisy"
        ),
        "rejections": sum(
            s["rejected"] for s in stats if s["tenant"] != "noisy"
        ),
        "degraded_tenants": sum(
            1
            for tid in tenants
            if svc.registry.get(tid).health.level() > 0
        ),
    }
    print(
        "bench[tenants]: "
        + " ".join(f"{k}={v}" for k, v in entry.items()),
        file=sys.stderr,
    )
    return entry


def _entry_key(e: Dict) -> tuple:
    return (
        e.get("config"), e.get("pods"), e.get("types"), e.get("nodes"),
        e.get("devices"), e.get("tenants"),
    )


def compare_grids(
    old_path: str, new_path: str, max_regression: float = 0.20,
    noise_floor_ms: float = 100.0,
) -> int:
    """benchstat-style per-config comparison of two bench_grid.json files
    (the reference documents benchstat as its perf workflow,
    scheduling_benchmark_test.go:57-69). Exits nonzero when any matching
    config's best_ms regresses by more than ``max_regression``.

    Grids from different platforms (a CPU-fallback run vs a TPU run) are
    reported but never enforced — the delta would be meaningless. Configs
    whose timings sit under ``noise_floor_ms`` on both sides are reported
    but not enforced either: a 23 -> 29 ms swing is scheduler jitter, not
    a kernel regression (benchstat's statistical gate plays this role in
    the reference).
    """
    try:
        with open(old_path) as fh:
            old = json.load(fh)
        with open(new_path) as fh:
            new = json.load(fh)
    except (OSError, ValueError) as exc:
        # a truncated grid (crash mid-write) must not wedge the gate
        print(f"bench-compare: unreadable grid ({exc}); skipping",
              file=sys.stderr)
        return 0
    old_by_key = {_entry_key(e): e for e in old.get("grid", [])}
    same_platform = old.get("platform") == new.get("platform")
    if not same_platform:
        print(
            f"bench-compare: platform mismatch ({old.get('platform')} vs"
            f" {new.get('platform')}) — informational only, not enforced",
            file=sys.stderr,
        )
    print(
        f"{'config':<28} {'old ms':>10} {'new ms':>10} {'delta':>8}",
        file=sys.stderr,
    )
    worst = 0.0
    matched = 0
    for e in new.get("grid", []):
        o = old_by_key.get(_entry_key(e))
        if o is None or not o.get("best_ms") or not e.get("best_ms"):
            continue
        matched += 1
        delta = (e["best_ms"] - o["best_ms"]) / o["best_ms"]
        # jitter exemption, not a blind spot: both sides under the floor
        # AND the absolute swing under half of it — a 20 -> 95 ms (4.7x)
        # slowdown stays enforced even though both sit under the floor
        noisy = (
            o["best_ms"] < noise_floor_ms
            and e["best_ms"] < noise_floor_ms
            and abs(e["best_ms"] - o["best_ms"]) < noise_floor_ms / 2
        )
        if not noisy:
            worst = max(worst, delta)
        name = f"{e['config']}-{e.get('pods') or e.get('nodes')}x{e.get('types') or ''}"
        flag = ""
        if delta > max_regression:
            flag = (
                "  (sub-noise-floor, not enforced)"
                if noisy
                else "  <-- REGRESSION"
            )
        print(
            f"{name:<28} {o['best_ms']:>10.1f} {e['best_ms']:>10.1f}"
            f" {delta:>+7.1%}{flag}",
            file=sys.stderr,
        )
    if not matched:
        print("bench-compare: no matching configs", file=sys.stderr)
        return 0
    if same_platform and worst > max_regression:
        print(
            f"bench-compare: worst regression {worst:+.1%} exceeds"
            f" {max_regression:.0%} bound",
            file=sys.stderr,
        )
        return 1
    return 0


def record_floors() -> None:
    """Measure the in-test floor configs on THIS platform and write
    bench_floors.json; tests/test_perf_floor.py asserts half the recorded
    throughput thereafter (the grid-pinned floor VERDICT r4 asked for)."""
    plat, _ = init_backend()
    import time as _t

    from karpenter_tpu.cloudprovider import corpus
    from karpenter_tpu.kube import Client, TestClock
    from karpenter_tpu.scheduling.topology import Topology
    from karpenter_tpu.solver import TpuSolver
    from karpenter_tpu.solver.example import example_nodepool
    from karpenter_tpu.solver.workloads import constrained_mix, mixed_pods

    def measure(pods):
        pools = [example_nodepool()]
        its = {pools[0].name: corpus.generate(100)}

        def one():
            topo = Topology(Client(TestClock()), [], pools, its, pods)
            s = TpuSolver(pools, its, topo)
            t0 = _t.perf_counter()
            s.solve(pods)
            return _t.perf_counter() - t0

        one(); one()  # a-priori + adaptive shape warm-ups
        return len(pods) / min(one(), one())

    floors = {
        "mixed-500": round(measure(mixed_pods(500, gpu_fraction=0.0)), 1),
        "mixed-2000": round(measure(mixed_pods(2000, gpu_fraction=0.0)), 1),
        "constrained-2000": round(measure(constrained_mix(2000)), 1),
    }
    path = os.path.join(os.path.dirname(__file__) or ".", "bench_floors.json")
    try:
        with open(path) as fh:
            data = json.load(fh)
    except (OSError, ValueError):
        data = {}
    data[plat] = floors
    with open(path, "w") as fh:
        json.dump(data, fh, indent=1)
    print(f"bench: recorded {plat} floors: {floors}", file=sys.stderr)


def main() -> None:
    if len(sys.argv) >= 2 and sys.argv[1] == "--record-floors":
        record_floors()
        return
    if len(sys.argv) >= 2 and sys.argv[1] == "--twin":
        # bench.py --twin [nodes] [minutes]: just the twin row, as JSON
        init_backend()
        entry = run_twin(
            int(sys.argv[2]) if len(sys.argv) > 2 else 2000,
            int(sys.argv[3]) if len(sys.argv) > 3 else 10,
        )
        print(json.dumps(entry, indent=1))
        return
    if len(sys.argv) >= 2 and sys.argv[1] == "--mesh":
        # bench.py --mesh [n_pods] [n_types]: the fleet-scale weak-scaling
        # rows + MULTICHIP_r06.json (measured claims — devices, mesh
        # shape, parity verdict, pods/s — replacing the r05 dry-run
        # format). Forces 8 virtual host devices when nothing set them:
        # must happen before the first jax import in this process.
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()
        plat, fell_back = init_backend()
        rows = run_mesh(
            int(sys.argv[2]) if len(sys.argv) > 2 else 500_000,
            int(sys.argv[3]) if len(sys.argv) > 3 else 2_000,
        )
        out = {
            "platform": plat + ("-virtual" if fell_back else ""),
            "layout": "r06",
            "grid": rows,
        }
        path = os.path.join(
            os.path.dirname(__file__) or ".", "MULTICHIP_r06.json"
        )
        with open(path, "w") as fh:
            json.dump(out, fh, indent=1)
        print(json.dumps(out, indent=1))
        if any(e["fallback_solves"] for e in rows) or not all(
            e.get("parity", True) for e in rows
        ):
            sys.exit(1)
        return
    if len(sys.argv) >= 2 and sys.argv[1] == "--tenants":
        # bench.py --tenants [N] [n_pods]: just the multi-tenant
        # sustained-traffic row, as JSON
        init_backend()
        entry = run_tenants(
            int(sys.argv[2]) if len(sys.argv) > 2 else 4,
            int(sys.argv[3]) if len(sys.argv) > 3 else 200,
        )
        print(json.dumps(entry, indent=1))
        if entry["fallback_solves"] or entry["degraded_tenants"]:
            sys.exit(1)
        return
    if len(sys.argv) >= 3 and sys.argv[1] == "--compare":
        # bench.py --compare old_grid.json [new_grid.json]
        old = sys.argv[2]
        new = (
            sys.argv[3]
            if len(sys.argv) > 3
            else os.path.join(os.path.dirname(__file__) or ".", "bench_grid.json")
        )
        sys.exit(compare_grids(old, new))
    plat, fell_back = init_backend()
    full_grid = os.environ.get("BENCH_FULL_GRID", "1") != "0"

    grid: List[Dict] = []

    if fell_back:
        # CPU-fallback survival mode: the scan kernels are latency-tuned
        # for the accelerator; on host the big grid would run for hours.
        # Keep a slim grid that still proves the contract end-to-end and
        # ALWAYS reach the headline JSON line.
        print(
            "bench: CPU fallback — slim grid (1-2 trials, largest shapes"
            " skipped)",
            file=sys.stderr,
        )
        grid.append(run_config("identical", 500, 10, trials=2, with_oracle=True))
        grid.append(run_config("mixed", 5_000, 400, trials=2, with_oracle=True))
        # the diverse 5-class mix joined the survival grid in round 5: the
        # class-batched kernel + truncation memo brought it from 56 s (r3
        # same-host) to ~1 s, so even the fallback grid can afford the
        # shape the round's structural work targeted
        grid.append(
            run_config("diverse-ref", 5_000, 400, trials=2, with_oracle=False)
        )
        for fn in (run_consolidation, run_single_consolidation):
            try:
                grid.append(fn(2_000))
            except Exception as exc:  # pragma: no cover - bench resilience
                print(
                    f"bench: {fn.__name__} config failed: {exc}",
                    file=sys.stderr,
                )
        # steady-state churn rows (warm ticks are cheap even on host):
        # the warm-path acceptance bound lives on the 5k 1% row
        for pct in (1, 10):
            try:
                grid.append(run_churn(5_000, pct, ticks=3))
            except Exception as exc:  # pragma: no cover - bench resilience
                print(f"bench: churn-{pct}pct failed: {exc}", file=sys.stderr)
        # ISSUE 10: constrained-workload churn — topology batches on the
        # delta/REUSE contract with zero sequential fallbacks
        for cfg in ("constrained-churn", "diverse-churn"):
            try:
                grid.append(run_constraint_churn(cfg, 5_000, ticks=3))
            except Exception as exc:  # pragma: no cover - bench resilience
                print(f"bench: {cfg} failed: {exc}", file=sys.stderr)
        # twin row at survival scale: the replay loop itself end-to-end
        try:
            grid.append(run_twin(500, minutes=6))
        except Exception as exc:  # pragma: no cover - bench resilience
            print(f"bench: twin row failed: {exc}", file=sys.stderr)
        # ISSUE 20: multi-tenant sustained traffic at survival scale
        try:
            grid.append(run_tenants(2, n_pods=100, n_types=50, rounds=2))
        except Exception as exc:  # pragma: no cover - bench resilience
            print(f"bench: tenants row failed: {exc}", file=sys.stderr)
        headline = run_config(
            "constrained", N_HEADLINE_PODS, N_HEADLINE_TYPES, trials=1,
            with_oracle=False,
        )
        grid.append(headline)
        _emit(plat, fell_back, grid, headline)
        return

    # BASELINE configs 0, 1, 4 (oracle cost-delta asserted)
    grid.append(run_config("identical", 500, 10, trials=10, with_oracle=True))
    grid.append(run_config("mixed", 10_000, 400, trials=7, with_oracle=True))
    grid.append(
        run_config("spot-od-limits", 5_000, 400, trials=7, with_oracle=True)
    )
    # the reference's literal 5-class diverse mix (cross-selecting spread
    # serializes via the host oracle by design; routed fraction reported)
    grid.append(run_config("diverse-ref", 5_000, 400, trials=5, with_oracle=True))
    # constrained shape WITH the oracle cost delta: the north-star config
    # itself is beyond the oracle budget, so its cost discipline is proven
    # at 10k pods on the same constraint mix
    grid.append(run_config("constrained", 10_000, 400, trials=5, with_oracle=True))

    # size grid (reference harness shape, scheduling_benchmark_test.go:70-96)
    if full_grid:
        for cfg, n_pods, n_types, trials in (
            ("mixed", 500, 400, 10),
            ("mixed", 5_000, 400, 7),
            ("mixed", 10_000, 800, 5),
            ("mixed-cpu", 50_000, 10, 5),
            ("mixed", 50_000, 400, 5),
        ):
            grid.append(
                run_config(cfg, n_pods, n_types, trials=trials,
                           with_oracle=False)
            )

    # BASELINE config[3]: consolidation search over 2k nodes (multi-node
    # binary search + the single-node sweep, both scenario-batched)
    for fn in (run_consolidation, run_single_consolidation):
        try:
            grid.append(fn(2_000))
        except Exception as exc:  # pragma: no cover - bench resilience
            print(f"bench: {fn.__name__} config failed: {exc}", file=sys.stderr)

    # steady-state churn rows (ISSUE 8): warm reconciles over a churning
    # cluster — the incremental encoder's claim is that these amortize
    for n_pods, pct in ((5_000, 1), (5_000, 10), (50_000, 1), (50_000, 10)):
        try:
            grid.append(run_churn(n_pods, pct))
        except Exception as exc:  # pragma: no cover - bench resilience
            print(
                f"bench: churn {n_pods}x{pct}pct failed: {exc}",
                file=sys.stderr,
            )
    # ISSUE 10: constrained-workload churn rows — the topology delta/REUSE
    # contract and the zero-fallback gate, at the reference shapes
    for cfg, n_pods in (
        ("constrained-churn", 5_000),
        ("diverse-churn", 5_000),
        ("constrained-churn", 50_000),
    ):
        try:
            grid.append(run_constraint_churn(cfg, n_pods))
        except Exception as exc:  # pragma: no cover - bench resilience
            print(f"bench: {cfg}-{n_pods} failed: {exc}", file=sys.stderr)

    # ISSUE 12: the cluster-twin row — sustained roster throughput and
    # worst-minute SLO margins over a deterministic churn replay
    try:
        grid.append(run_twin(2_000, minutes=10))
    except Exception as exc:  # pragma: no cover - bench resilience
        print(f"bench: twin row failed: {exc}", file=sys.stderr)

    # ISSUE 20: multi-tenant sustained traffic — N isolated control
    # planes through one service, with the noisy-neighbor delta column
    try:
        grid.append(run_tenants(4, n_pods=200, n_types=100))
    except Exception as exc:  # pragma: no cover - bench resilience
        print(f"bench: tenants row failed: {exc}", file=sys.stderr)

    # the north star: 50k constrained pods x 800 types (BASELINE config[2])
    headline = run_config(
        "constrained", N_HEADLINE_PODS, N_HEADLINE_TYPES, trials=5,
        with_oracle=False,
    )
    grid.append(headline)
    _emit(plat, fell_back, grid, headline)


def _emit(plat: str, fell_back: bool, grid: List[Dict], headline: Dict) -> None:

    for e in grid:
        print(
            "bench: "
            + " ".join(f"{k}={v}" for k, v in e.items() if v is not None),
            file=sys.stderr,
        )
    grid_path = os.path.join(
        os.path.dirname(__file__) or ".", "bench_grid.json"
    )
    # keep the previous grid for mechanical regression comparison
    # (`bench.py --compare bench_grid_prev.json`)
    if os.path.exists(grid_path):
        os.replace(grid_path, grid_path.replace(".json", "_prev.json"))
    with open(grid_path, "w") as fh:
        json.dump({"platform": plat, "grid": grid}, fh, indent=1)

    value = headline["pods_per_sec"]
    suffix = "-cpufallback" if fell_back else ""
    print(
        json.dumps(
            {
                "metric": (
                    f"scheduling-throughput-{N_HEADLINE_PODS}pods-"
                    f"{N_HEADLINE_TYPES}types-constrained{suffix}"
                ),
                "value": value,
                "unit": "pods/sec",
                "vs_baseline": round(value / BASELINE_PODS_PER_SEC, 2),
            }
        )
    )
    violated = [e["config"] for e in grid if e.get("cost_bound_violated")]
    if violated:
        print(
            f"bench: cost bound violated by: {violated}", file=sys.stderr
        )
        sys.exit(1)


if __name__ == "__main__":
    main()
