"""Decision audit trail: one structured record per completed solve.

Every decision the solver commits (a provisioning solve, a scenario-batched
consolidation dispatch, a quarantined solve that fell to the oracle) leaves
an ``AuditRecord`` in a ring-buffer ``AuditLog``: decision id, trace id (so
the record correlates with the span trace and the XProf device timeline),
encode content hash, scenario count, dispatch count, the degradation rung
that produced the answer, the invariant-guard verdict, and the fault sites
that fired during the solve (correlated against the PR-5 injector log).

The chaos soak and the PARITY.md cost-gap workflow query this instead of
scraping logs: ``AUDIT.query(kind=..., rung=...)`` answers "which decisions
did the oracle rung make while the kernel sat quarantined" directly.

The log is always on — appending one small record per solve is noise next
to the solve itself, and the records never influence decisions (the
byte-identical-decisions contract in tests/test_obs.py covers the tracer
AND the audit path). ``maxlen`` bounds memory like the tracer's span
buffer does.
"""

from __future__ import annotations

import json
import threading
from collections import deque
from dataclasses import asdict, dataclass, field
from typing import Deque, Dict, List, Optional

from ..kube.clock import RealClock

# THE wall-time fallback for the observability tier — one object, one
# seam: obs/__init__ imports this same instance for its audit-timestamp
# fallback (CLK10xx whitelists exactly the RealClock class, nothing
# else in the tier reads the wall clock)
_REAL_CLOCK = RealClock()


@dataclass
class AuditRecord:
    """One solver decision, in the shape the soak/parity workflows query.

    ``rung`` names the degradation-ladder rung that produced the committed
    answer ("batched" | "kernel" | "oracle" | "dropped"); ``guard`` is the
    invariant-guard verdict ("ok" or "quarantined: <violations>");
    ``fault_sites`` lists the injector sites that fired during this solve
    (empty outside chaos runs). ``oracle_cost`` is filled only where an
    oracle reference run is affordable (bench.py's cost-delta configs)."""

    decision_id: str
    kind: str  # "solve" | "scenarios"
    trace_id: str
    timestamp: float
    duration_ms: float
    encode_hash: str
    pods: int
    claims: int
    errors: int
    scenario_count: int
    dispatches: int
    rung: str
    guard: str
    # packing cost of the committed decision. None when tracing is off:
    # total_price() walks every claim's option list, and the always-on
    # audit path must stay O(1) next to the solve (the <2% bench budget)
    cost: Optional[float] = None
    fault_sites: List[str] = field(default_factory=list)
    oracle_cost: Optional[float] = None
    # incremental-encode provenance (ISSUE 8): whether the solve reused
    # the prior cluster encoding verbatim (content-hash fast path) and
    # how many axis rows rode the device delta instead of a full
    # transfer. None on records that never touched the encode path
    # (consolidation decision-level records aggregate their solves).
    encode_reused: Optional[bool] = None
    delta_rows: Optional[int] = None
    attrs: Dict[str, object] = field(default_factory=dict)


class AuditLog:
    """Bounded, thread-safe decision trail. Decision ids are sequential
    ("d000001", ...) — deterministic under replay, unlike uuids.

    ``clock`` is a zero-arg callable providing ``timestamp`` for records
    that don't pass one — ONE timebase per log, so ``query(since=...)``
    compares like with like (obs.__init__ wires the installed tracer's
    clock, falling back to wall time)."""

    def __init__(self, maxlen: int = 1024, clock=None):
        self._records: Deque[AuditRecord] = deque(maxlen=maxlen)
        self._lock = threading.Lock()
        self._seq = 0
        self._clock = clock
        # append observers (the twin's wall-latency sampler): called AFTER
        # the record lands, outside the lock — observers may query the log
        self._observers: List = []

    def _now(self) -> float:
        if self._clock is not None:
            return self._clock()
        return _REAL_CLOCK.now()

    def record(self, **fields) -> AuditRecord:
        fields.setdefault("timestamp", self._now())
        with self._lock:
            self._seq += 1
            rec = AuditRecord(decision_id=f"d{self._seq:06d}", **fields)
            self._records.append(rec)
        for cb in list(self._observers):
            cb(rec)
        return rec

    def on_record(self, callback) -> None:
        """Register an append observer ``callback(record)`` — how the
        cluster twin joins wall-clock latency samples to decision ids
        without adding non-deterministic fields to the records."""
        self._observers.append(callback)

    def remove_observer(self, callback) -> None:
        try:
            self._observers.remove(callback)
        except ValueError:
            pass

    def __len__(self) -> int:
        with self._lock:
            return len(self._records)

    def last(self) -> Optional[AuditRecord]:
        with self._lock:
            return self._records[-1] if self._records else None

    def query(
        self,
        kind: Optional[str] = None,
        rung: Optional[str] = None,
        trace_id: Optional[str] = None,
        since: Optional[float] = None,
        until: Optional[float] = None,
    ) -> List[AuditRecord]:
        """Filtered records. ``since`` is inclusive, ``until`` exclusive —
        the half-open [since, until) window the twin's per-minute SLO wall
        slices the trail into (adjacent minutes never double-count a
        record)."""
        with self._lock:
            records = list(self._records)
        return [
            r
            for r in records
            if (kind is None or r.kind == kind)
            and (rung is None or r.rung == rung)
            and (trace_id is None or r.trace_id == trace_id)
            and (since is None or r.timestamp >= since)
            and (until is None or r.timestamp < until)
        ]

    def window(self, since: float, until: float) -> List[AuditRecord]:
        """All records in the half-open [since, until) window — one
        simulated minute of the twin's SLO wall."""
        return self.query(since=since, until=until)

    def export_state(self) -> dict:
        """Serializable full state (records + sequence counter) — the
        twin checkpoints this so a resumed replay continues decision ids
        ("d%06d") exactly where the interrupted run stopped."""
        with self._lock:
            return {
                "seq": self._seq,
                "maxlen": self._records.maxlen,
                "records": [asdict(r) for r in self._records],
            }

    def restore_state(self, state: dict) -> None:
        with self._lock:
            self._seq = int(state["seq"])
            self._records = deque(
                (AuditRecord(**r) for r in state["records"]),
                maxlen=state.get("maxlen") or self._records.maxlen,
            )

    def clear(self) -> None:
        with self._lock:
            self._records.clear()
            self._seq = 0

    def to_json(self) -> str:
        with self._lock:
            records = list(self._records)
        return json.dumps([asdict(r) for r in records], indent=1)

    def dump(self, path: str) -> None:
        with open(path, "w", encoding="utf-8") as fh:
            fh.write(self.to_json())


__all__ = ["AuditRecord", "AuditLog"]
