"""Clock-injected span tracer for the decision path.

The solve hot path crosses five machines' worth of seams — operator
reconcile, scenario build, cluster encode, host↔device transfer, kernel
dispatch, decode, invariant guard, commit — and BENCH_r05 shows the kernel
at 2.4–25 ms while the end-to-end decision costs ~286 ms. This module is
the instrument that splits that gap: every phase runs inside a ``Span``,
spans nest into traces, and a completed trace exports as Chrome
trace-event JSON (loadable in Perfetto / chrome://tracing) plus per-phase
duration histograms in ``metrics.REGISTRY``.

Design constraints (mirroring faults/__init__.py, the sibling seam):

- **Zero overhead when off.** Instrumented call sites go through the
  module-level ``span()``/``event()`` helpers, which cost one global
  ``None`` check and return a shared no-op context manager when no tracer
  is installed. With tracing off the solver's decisions are byte-identical
  to an uninstrumented run (pinned by tests/test_obs.py, the same
  contract tests/test_faults.py pins for the injector).
- **Deterministic.** Span/trace ids come from a seeded ``random.Random``;
  timestamps come from the injected clock. The same seed over the same
  call sequence replays the exact same trace, so chaos replays produce
  identical traces (the property the fault log already has).
- **Thread-correct.** The active-span stack is thread-local (the gRPC
  sidecar solves on a thread pool); the finished-span buffer is
  lock-guarded and bounded.

Trace context crosses the RemoteSolver gRPC hop as metadata
(``ktpu-trace-id``/``ktpu-parent-id``, solver/service.py) so sidecar spans
stitch into the caller's trace.
"""

from __future__ import annotations

import json
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..metrics import Histogram

# per-phase duration histograms: the span taxonomy is a bounded set of
# names (see README "Observability"), so the phase label stays well under
# the registry's cardinality guard
PHASE_DURATION = Histogram(
    "trace_phase_duration_seconds",
    "Span durations by phase (decision-path tracing)",
)

# gRPC metadata keys carrying trace context across the RemoteSolver hop
TRACE_ID_METADATA_KEY = "ktpu-trace-id"
PARENT_ID_METADATA_KEY = "ktpu-parent-id"


class PerfClock:
    """Wall-clock for standalone tracing (bench, the trace smoke): the
    operator injects its own Clock; this is for callers without one.

    One of the two documented RealClock seams (with kube.clock.RealClock)
    that the clock-discipline analysis (CLK10xx) whitelists — the ONLY
    places in controllers/faults/obs/solver allowed to read ``time.*``
    directly. Everything else threads an injected clock or obs.now()."""

    @staticmethod
    def now() -> float:
        return time.perf_counter()

    @staticmethod
    def sleep(seconds: float) -> None:
        time.sleep(seconds)


@dataclass
class Span:
    """One timed phase. Use as a context manager (the OBS801 analysis rule
    flags spans opened without one)."""

    tracer: "Tracer"
    name: str
    trace_id: str
    span_id: str
    parent_id: Optional[str]
    start: float
    end: Optional[float] = None
    attrs: Dict[str, object] = field(default_factory=dict)
    events: List[Tuple[float, str, Dict[str, object]]] = field(
        default_factory=list
    )

    def annotate(self, **attrs) -> "Span":
        self.attrs.update(attrs)
        return self

    def add_event(self, name: str, **attrs) -> None:
        self.events.append((self.tracer.clock.now(), name, attrs))

    def __enter__(self) -> "Span":
        self.tracer._push(self)
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        if exc_type is not None:
            self.attrs.setdefault("error", exc_type.__name__)
        self.tracer._finish(self)
        return False

    @property
    def duration(self) -> float:
        return (self.end if self.end is not None else self.start) - self.start


class _NoopSpan:
    """Shared do-nothing span: what ``span()`` hands out when tracing is
    off. Stateless, so one instance serves every call site."""

    __slots__ = ()

    trace_id = ""
    span_id = ""
    parent_id = None

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        return False

    def annotate(self, **attrs) -> "_NoopSpan":
        return self

    def add_event(self, name: str, **attrs) -> None:
        pass


NOOP_SPAN = _NoopSpan()


class Tracer:
    """Seeded, clock-injected span tracer.

    ``span(name)`` returns a context-managed Span parented on the calling
    thread's current span; ``dump(path)``/``export_chrome()`` emit the
    Chrome trace-event form. ``max_spans`` bounds the finished buffer
    (ring semantics: oldest spans drop first), so a long-lived operator
    can leave tracing on without unbounded growth.
    """

    def __init__(self, clock=None, seed: int = 0, max_spans: int = 100_000):
        self.clock = clock if clock is not None else PerfClock()
        self.seed = seed
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self._local = threading.local()
        self._finished: List[Span] = []
        self.max_spans = max_spans
        self.dropped = 0

    # -- ids ----------------------------------------------------------------

    def _new_id(self) -> str:
        # drawn under the lock by callers; deterministic per (seed, call
        # sequence) so chaos replays produce identical traces
        return f"{self._rng.getrandbits(64):016x}"

    # -- span lifecycle ------------------------------------------------------

    def _stack(self) -> List[Span]:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack

    def current(self) -> Optional[Span]:
        stack = self._stack()
        return stack[-1] if stack else None

    def span(
        self,
        name: str,
        *,
        trace_id: Optional[str] = None,
        parent_id: Optional[str] = None,
        **attrs,
    ) -> Span:
        """A new span, parented on the thread's current span unless an
        explicit ``trace_id``/``parent_id`` is given (the sidecar passes
        the caller's ids from gRPC metadata so its spans stitch into the
        remote trace). Explicitly-parented spans are marked
        ``remote_parent`` — their parent may live in ANOTHER process's
        tracer, so this process's trace dump legitimately lacks it and
        the validator's dangling-parent check exempts it."""
        remote_parent = parent_id is not None
        parent = self.current()
        with self._lock:
            span_id = self._new_id()
            if trace_id is None:
                trace_id = (
                    parent.trace_id if parent is not None else self._new_id()
                )
        if parent_id is None and parent is not None:
            parent_id = parent.span_id
        span = Span(
            tracer=self,
            name=name,
            trace_id=trace_id,
            span_id=span_id,
            parent_id=parent_id,
            start=self.clock.now(),
            attrs=dict(attrs),
        )
        if remote_parent:
            span.attrs["remote_parent"] = True
        return span

    def event(self, name: str, **attrs) -> None:
        """Attach an instant event to the calling thread's current span
        (dropped when no span is open)."""
        cur = self.current()
        if cur is not None:
            cur.add_event(name, **attrs)

    def _push(self, span: Span) -> None:
        self._stack().append(span)

    def _finish(self, span: Span) -> None:
        span.end = self.clock.now()
        stack = self._stack()
        if stack and stack[-1] is span:
            stack.pop()
        elif span in stack:  # defensive: out-of-order close
            stack.remove(span)
        PHASE_DURATION.observe(span.duration, labels={"phase": span.name})
        with self._lock:
            self._finished.append(span)
            if len(self._finished) > self.max_spans:
                drop = len(self._finished) - self.max_spans
                del self._finished[:drop]
                self.dropped += drop

    # -- introspection / export ---------------------------------------------

    def finished(self, name: Optional[str] = None) -> List[Span]:
        with self._lock:
            spans = list(self._finished)
        if name is not None:
            spans = [s for s in spans if s.name == name]
        return spans

    def clear(self) -> None:
        with self._lock:
            self._finished.clear()
            # under the same lock as _finish's `dropped +=`: a reset
            # racing a drop must not resurrect the pre-clear count
            self.dropped = 0

    def phase_totals(self) -> Dict[str, float]:
        """{span name: summed duration seconds} over the finished buffer —
        the aggregation bench.py's per-phase columns read."""
        out: Dict[str, float] = {}
        for s in self.finished():
            out[s.name] = out.get(s.name, 0.0) + s.duration
        return out

    def export_chrome(self) -> dict:
        """Chrome trace-event JSON (the Perfetto-loadable form): one
        complete ("X") event per finished span, μs timestamps from the
        injected clock, span/trace/parent ids in ``args``; span events
        ride as instant ("i") events."""
        events: List[dict] = []
        for s in sorted(self.finished(), key=lambda s: (s.start, s.span_id)):
            events.append(
                {
                    "name": s.name,
                    "ph": "X",
                    "ts": round(s.start * 1e6, 3),
                    "dur": round(max(s.duration, 0.0) * 1e6, 3),
                    "pid": 1,
                    "tid": 1,
                    "cat": "ktpu",
                    "args": {
                        "span_id": s.span_id,
                        "trace_id": s.trace_id,
                        "parent_id": s.parent_id,
                        **{k: _jsonable(v) for k, v in s.attrs.items()},
                    },
                }
            )
            for ts, name, attrs in s.events:
                events.append(
                    {
                        "name": name,
                        "ph": "i",
                        "ts": round(ts * 1e6, 3),
                        "dur": 0,
                        "pid": 1,
                        "tid": 1,
                        "cat": "ktpu",
                        "s": "t",
                        "args": {
                            "span_id": s.span_id,
                            "trace_id": s.trace_id,
                            "parent_id": s.span_id,
                            **{k: _jsonable(v) for k, v in attrs.items()},
                        },
                    }
                )
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def dump(self, path: str) -> None:
        with open(path, "w", encoding="utf-8") as fh:
            json.dump(self.export_chrome(), fh, indent=1)


def _jsonable(v):
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    return str(v)


# -- trace validation --------------------------------------------------------


def validate_chrome_trace(doc: dict, schema: dict) -> List[str]:
    """Violations of the checked-in minimal trace schema
    (hack/trace_schema.json) plus the structural invariants no schema can
    express: no dangling parent span ids, non-negative durations,
    monotonic (non-decreasing) timestamps in export order under the
    injected clock. Returns [] when the trace is valid."""
    problems: List[str] = []
    if not isinstance(doc, dict) or "traceEvents" not in doc:
        return ["trace document missing 'traceEvents'"]
    events = doc["traceEvents"]
    req_keys = schema.get("required_event_keys", [])
    req_args = schema.get("required_arg_keys", [])
    allowed_ph = set(schema.get("ph", []))
    span_ids = {
        e.get("args", {}).get("span_id")
        for e in events
        if e.get("ph") == "X"
    }
    last_ts = None
    for i, e in enumerate(events):
        for k in req_keys:
            if k not in e:
                problems.append(f"event {i} missing key {k!r}")
        args = e.get("args", {})
        for k in req_args:
            if k not in args:
                problems.append(f"event {i} args missing {k!r}")
        if allowed_ph and e.get("ph") not in allowed_ph:
            problems.append(f"event {i} has unknown ph {e.get('ph')!r}")
        if e.get("dur", 0) < 0:
            problems.append(f"event {i} has negative duration")
        ts = e.get("ts")
        if e.get("ph") == "X":
            if last_ts is not None and ts is not None and ts < last_ts:
                problems.append(
                    f"event {i} timestamp {ts} regresses below {last_ts}"
                )
            if ts is not None:
                last_ts = ts
        parent = args.get("parent_id")
        if (
            parent is not None
            and parent not in span_ids
            and not args.get("remote_parent")
        ):
            # remote_parent spans were stitched from gRPC metadata: their
            # parent lives in the CALLER process's tracer, so its absence
            # from this dump is correct, not a leak
            problems.append(
                f"event {i} ({e.get('name')!r}) has dangling parent span id"
                f" {parent}"
            )
        if args.get("span_id") in (None, "") or args.get("trace_id") in (
            None,
            "",
        ):
            problems.append(f"event {i} missing span/trace id")
    return problems


__all__ = [
    "Span", "Tracer", "PerfClock", "NOOP_SPAN", "PHASE_DURATION",
    "TRACE_ID_METADATA_KEY", "PARENT_ID_METADATA_KEY",
    "validate_chrome_trace",
]
