"""Observability: decision-path span tracing + the decision audit trail.

Two instruments over the solve path, both designed to cost nothing when
idle (the faults/ zero-overhead discipline):

- ``trace.Tracer`` — clock-injected, seeded-deterministic span tracer
  threaded through reconcile → encode → transfer → dispatch → decode →
  guard → commit (and across the RemoteSolver gRPC hop via metadata).
  Installed process-globally like the fault injector; call sites use the
  module-level ``span()``/``event()`` helpers, which are a single global
  ``None`` check when no tracer is installed.
- ``audit.AuditLog`` — ring-buffer decision trail; the module-global
  ``AUDIT`` receives one record per completed solve from
  solver/driver.py.

See README "Observability" for the span taxonomy and the audit-record
schema.
"""

from __future__ import annotations

from typing import Optional

from .audit import AuditLog, AuditRecord
from .audit import _REAL_CLOCK
from .trace import (
    NOOP_SPAN,
    PARENT_ID_METADATA_KEY,
    PHASE_DURATION,
    TRACE_ID_METADATA_KEY,
    PerfClock,
    Span,
    Tracer,
    validate_chrome_trace,
)

# -- process-global installation seam (mirrors faults.install) ---------------

_TRACER: Optional[Tracer] = None

# _REAL_CLOCK (imported from audit.py so the tier has exactly ONE
# wall-time fallback object): every "else wall time" stamp routes
# through that single named kube.clock.RealClock seam, so the
# clock-discipline analysis (CLK10xx) has one sanctioned source to
# whitelist and the determinism contract has one seam to replace under
# replay.

# monotonic fallback for DURATION measurement: wall time (RealClock) may
# step under NTP, so deltas never ride it — PerfClock is the documented
# monotonic seam
_PERF_CLOCK = PerfClock()


def now() -> float:
    """Timestamp for the solve path: the installed tracer's injected
    clock when tracing is on, the named RealClock seam otherwise. The
    only way the solve path may read time — raw ``time.*`` reads in
    controllers/faults/obs/solver are CLK10xx findings."""
    if _TRACER is not None:
        return _TRACER.clock.now()
    return _REAL_CLOCK.now()


def duration_clock():
    """The clock to measure durations with: the installed tracer's
    injected clock under tracing (replay-deterministic), the monotonic
    PerfClock seam otherwise (NEVER RealClock: an NTP step between two
    reads would record negative durations). Callers capture the clock
    ONCE per measured interval so an install/uninstall racing the
    interval cannot mix timebases."""
    if _TRACER is not None:
        return _TRACER.clock
    return _PERF_CLOCK


def _audit_now() -> float:
    """One timebase for every audit record in the log: the installed
    tracer's clock when tracing is on, the RealClock seam otherwise —
    never a mix WITHIN a record source, so ``AUDIT.query(since=...)``
    is coherent."""
    return now()


# the process-wide decision trail; always on (records never influence
# decisions, and one small append per solve is noise next to the solve)
_DEFAULT_AUDIT = AuditLog(clock=_audit_now)
AUDIT = _DEFAULT_AUDIT


def install_audit(log: Optional[AuditLog] = None, maxlen: int = 65536) -> AuditLog:
    """Swap the process-global decision trail for ``log`` (or a fresh,
    larger-ring one) and return it — the cluster twin's isolation seam:
    a twin run starts its audit trail at d000001 regardless of what the
    process solved before, so canonical audit artifacts from two runs
    compare byte-for-byte. Pair with :func:`uninstall_audit`. Call sites
    read ``obs.AUDIT`` per record, so the swap takes effect immediately."""
    global AUDIT
    AUDIT = log if log is not None else AuditLog(maxlen=maxlen, clock=_audit_now)
    return AUDIT


def uninstall_audit() -> None:
    """Restore the default process-wide trail after a twin run."""
    global AUDIT
    AUDIT = _DEFAULT_AUDIT


def install(tracer: Tracer) -> Tracer:
    global _TRACER
    _TRACER = tracer
    return tracer


def uninstall() -> None:
    global _TRACER
    _TRACER = None


def active() -> Optional[Tracer]:
    return _TRACER


def span(name: str, **attrs):
    """A context-managed span on the installed tracer; the shared no-op
    span (one global read, no allocation) when tracing is off."""
    if _TRACER is None:
        return NOOP_SPAN
    return _TRACER.span(name, **attrs)


def event(name: str, **attrs) -> None:
    """Attach an instant event to the installed tracer's current span;
    no-op (one global read) when tracing is off or no span is open."""
    if _TRACER is not None:
        _TRACER.event(name, **attrs)


def current_span():
    """The calling thread's open span, or None (also when tracing is
    off) — what the RemoteSolver reads to propagate trace context."""
    if _TRACER is None:
        return None
    return _TRACER.current()


__all__ = [
    "Span", "Tracer", "PerfClock", "NOOP_SPAN", "PHASE_DURATION",
    "AuditLog", "AuditRecord", "AUDIT", "install_audit", "uninstall_audit",
    "TRACE_ID_METADATA_KEY", "PARENT_ID_METADATA_KEY",
    "install", "uninstall", "active", "span", "event", "current_span",
    "now", "duration_clock", "validate_chrome_trace",
]
