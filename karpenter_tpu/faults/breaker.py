"""Circuit breaker + the solver's degradation ladder.

The solve hot path has three rungs, fastest first:

1. **batched** — the scenario-batched kernel (one vmapped dispatch for a
   whole consolidation probe set, ops/solve.py:solve_all_scenarios_packed);
2. **kernel** — the per-probe fused kernel (solve_all_packed /
   solve_all_classed_packed, or the native C++ core);
3. **oracle** — the exact host scheduler (scheduling/scheduler.py), the
   semantic source of truth. Always available; never guarded.

Each guarded rung sits behind a ``CircuitBreaker``: consecutive failures
trip it open, a clock-driven cool-down admits a half-open probe, and a
probe success closes it again — so the solver drops DOWN the ladder when
a rung misbehaves and re-probes UPWARD once the cool-down passes
(CvxCluster's degradation argument for LP allocators; the reference
treats provider errors as first-class state the same way). An integrity
violation caught by faults/guard.py trips the rung immediately
(quarantine) instead of counting toward the threshold: a kernel emitting
garbage must not get ``failure_threshold`` chances to corrupt a commit.

``SolverHealth`` is the shared handle threaded through ``SolverConfig``:
one instance per operator, surviving the per-solve TpuSolver instances,
publishing rung changes as events and metrics.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

from .. import obs
from ..events import (
    REASON_SOLVER_DEGRADED,
    REASON_SOLVER_QUARANTINED,
    REASON_SOLVER_RESTORED,
)
from ..metrics import Counter, Gauge

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half-open"

DEGRADATION_RUNG = Gauge(
    "solver_degradation_rung",
    "Current solver rung: 0=scenario-batched, 1=per-probe kernel, 2=host oracle",
)
BREAKER_TRIPS = Counter(
    "solver_breaker_trips_total",
    "Circuit-breaker trips per solver rung",
)
QUARANTINES = Counter(
    "solver_quarantines_total",
    "Solves discarded by the post-solve invariant guard",
)
DELTA_FALLBACKS = Counter(
    "solver_delta_fallbacks_total",
    "Guard-rejected incremental solves retried on a full re-encode "
    "(the degradation ladder's half-step: warm encode state shed, "
    "no rung tripped)",
)


class CircuitBreaker:
    """Consecutive-failure breaker with a clock-driven cool-down.

    closed → open after ``failure_threshold`` consecutive failures (or an
    explicit ``trip()``); open → half-open once ``cooldown`` seconds pass
    on the injected clock; a half-open success closes, a half-open
    failure re-opens and restarts the cool-down."""

    def __init__(self, clock, failure_threshold: int = 3, cooldown: float = 60.0):
        self.clock = clock
        self.failure_threshold = failure_threshold
        self.cooldown = cooldown
        self.state = CLOSED
        self.failures = 0
        self.trips = 0
        self._opened_at = 0.0

    def allow(self) -> bool:
        if self.state == OPEN and (
            self.clock.now() - self._opened_at >= self.cooldown
        ):
            self.state = HALF_OPEN
        return self.state != OPEN

    def record_success(self) -> None:
        self.state = CLOSED
        self.failures = 0

    def record_failure(self) -> None:
        self.failures += 1
        if self.state == HALF_OPEN or self.failures >= self.failure_threshold:
            self.trip()

    def trip(self) -> None:
        self.state = OPEN
        self.trips += 1
        self.failures = 0
        self._opened_at = self.clock.now()


class DegradationLadder:
    """Ordered rungs, fastest first; every rung but the last sits behind a
    breaker, and the last is unconditional."""

    def __init__(
        self,
        clock,
        rungs: Sequence[str] = ("batched", "kernel", "oracle"),
        failure_threshold: int = 2,
        cooldown: float = 120.0,
    ):
        self.rungs = tuple(rungs)
        self.breakers: Dict[str, CircuitBreaker] = {
            rung: CircuitBreaker(clock, failure_threshold, cooldown)
            for rung in self.rungs[:-1]
        }

    def allows(self, rung: str) -> bool:
        breaker = self.breakers.get(rung)
        return breaker is None or breaker.allow()

    def record(self, rung: str, ok: bool) -> None:
        breaker = self.breakers.get(rung)
        if breaker is None:
            return
        if ok:
            breaker.record_success()
        else:
            breaker.record_failure()

    def trip(self, rung: str) -> None:
        breaker = self.breakers.get(rung)
        if breaker is not None:
            breaker.trip()

    def current(self) -> str:
        for rung in self.rungs:
            if self.allows(rung):
                return rung
        return self.rungs[-1]

    def level(self) -> int:
        return self.rungs.index(self.current())


class SolverHealth:
    """The solver path's ladder, shared across TpuSolver instances.

    ``allow_batched``/``allow_kernel`` gate the two accelerated rungs
    (a quarantined kernel also takes the batched rung with it — both run
    the same kernels); ``record_*`` feed successes/failures to the
    breakers; ``quarantine`` trips a rung immediately on an integrity
    violation. Rung changes are published as events through ``recorder``
    (events/recorder.py) and mirrored in the metrics above."""

    RUNGS = ("batched", "kernel", "oracle")

    def __init__(
        self,
        clock,
        recorder=None,
        failure_threshold: int = 2,
        cooldown: float = 120.0,
        metric_labels: Optional[Dict[str, str]] = None,
    ):
        self.clock = clock
        self.recorder = recorder
        self.ladder = DegradationLadder(
            clock, self.RUNGS, failure_threshold, cooldown
        )
        self.quarantines = 0
        self.delta_fallbacks = 0
        self._last_level = 0
        # multi-tenant service (solver/tenancy.py): each tenant's ladder
        # publishes its own metric series (tenant=<id>). None keeps the
        # original unlabeled series — the single-operator deployments and
        # every existing dashboard/test read those unchanged. Cardinality
        # stays bounded because TenantRegistry.max_tenants bounds who can
        # mint a labeled SolverHealth.
        self._labels = dict(metric_labels) if metric_labels else None
        DEGRADATION_RUNG.set(0.0, labels=self._labels)

    def _rung_labels(self, rung: str) -> Dict[str, str]:
        if self._labels is None:
            return {"rung": rung}
        merged = {"rung": rung}
        merged.update(self._labels)
        return merged

    # -- gates --------------------------------------------------------------

    def allow_batched(self) -> bool:
        return self.ladder.allows("batched") and self.ladder.allows("kernel")

    def allow_kernel(self) -> bool:
        return self.ladder.allows("kernel")

    # -- outcomes -----------------------------------------------------------

    def record_batched(self, ok: bool, reason: str = "") -> None:
        self._record("batched", ok, reason)

    def record_kernel(self, ok: bool, reason: str = "") -> None:
        self._record("kernel", ok, reason)

    def delta_fallback(self, reason: str) -> None:
        """The ladder's half-step (between "incremental kernel" and a
        quarantine): the invariant guard rejected a solve that ran on a
        delta-applied / reused encoding, the driver shed the warm state
        (row banks, prior snapshot, device buffers) and is retrying once
        on a full re-encode. No breaker trips — if the fresh encoding
        solves clean the rung keeps its standing; if it trips the guard
        again, quarantine() follows as usual."""
        self.delta_fallbacks += 1
        DELTA_FALLBACKS.inc(labels=self._labels)
        obs.event("solver.delta_fallback", reason=reason[:200])
        self._publish(
            REASON_SOLVER_DEGRADED,
            f"incremental encoding shed after guard rejection: {reason}",
        )

    def quarantine(self, rung: str, reason: str) -> None:
        """Integrity violation: trip the rung NOW and drop to the oracle
        (the violating solve is discarded by the caller, never committed)."""
        self.quarantines += 1
        obs.event("solver.quarantine", rung=rung, reason=reason)
        QUARANTINES.inc(labels=self._labels)
        self.ladder.trip(rung)
        BREAKER_TRIPS.inc(labels=self._rung_labels(rung))
        self._publish(
            REASON_SOLVER_QUARANTINED,
            f"solver {rung} rung quarantined: {reason}",
        )
        self._observe(probe_succeeded=False)

    def _record(self, rung: str, ok: bool, reason: str) -> None:
        breaker = self.ladder.breakers[rung]
        trips_before = breaker.trips
        self.ladder.record(rung, ok)
        if breaker.trips > trips_before:
            # breaker trips land on the open span so a trace of a degraded
            # decision shows exactly which phase tripped which rung
            obs.event("solver.breaker_trip", rung=rung, reason=reason)
            BREAKER_TRIPS.inc(labels=self._rung_labels(rung))
            self._publish(
                REASON_SOLVER_DEGRADED,
                f"solver {rung} rung opened after repeated failures"
                + (f": {reason}" if reason else ""),
            )
        self._observe(probe_succeeded=ok)

    def level(self) -> int:
        """Effective rung index (0=batched, 1=kernel, 2=oracle) from the
        composite gates — what the NEXT solve will try. Public: the
        decision audit trail (obs/audit.py) records it per decision."""
        return self._level()

    def _level(self) -> int:
        """Effective rung index from the composite gates (a quarantined
        kernel takes the batched rung with it, which the raw ladder's
        per-breaker view can't see)."""
        if self.allow_batched():
            return 0
        if self.allow_kernel():
            return 1
        return 2

    def _observe(self, probe_succeeded: bool) -> None:
        """Refresh the rung gauge (it reports what the NEXT solve will
        try, half-open probes included), but only announce a restore when
        an actual probe SUCCEEDED — a cool-down lapsing merely admits a
        probe, it proves nothing yet."""
        level = self._level()
        if probe_succeeded and level < self._last_level:
            self._publish(
                REASON_SOLVER_RESTORED,
                f"solver re-probed upward to the {self.RUNGS[level]} rung",
            )
        # after a failed probe the observation-time half-open flip of an
        # unrelated breaker must not lower the remembered level, or the
        # NEXT success would miss its restore announcement
        if probe_succeeded or level > self._last_level:
            self._last_level = level
        DEGRADATION_RUNG.set(float(level), labels=self._labels)

    # -- checkpoint (sim/twin.py) -------------------------------------------

    def export_state(self) -> dict:
        """Breaker/ladder state a resumed twin replay must carry over —
        a half-open cool-down or a pending quarantine changes which rung
        the NEXT solve tries, so losing it would fork the replay."""
        return {
            "quarantines": self.quarantines,
            "delta_fallbacks": self.delta_fallbacks,
            "last_level": self._last_level,
            "breakers": {
                rung: {
                    "state": b.state,
                    "failures": b.failures,
                    "trips": b.trips,
                    "opened_at": b._opened_at,
                }
                for rung, b in self.ladder.breakers.items()
            },
        }

    def restore_state(self, state: dict) -> None:
        self.quarantines = int(state["quarantines"])
        self.delta_fallbacks = int(state["delta_fallbacks"])
        self._last_level = int(state["last_level"])
        for rung, bs in state["breakers"].items():
            b = self.ladder.breakers[rung]
            b.state = bs["state"]
            b.failures = int(bs["failures"])
            b.trips = int(bs["trips"])
            b._opened_at = float(bs["opened_at"])
        DEGRADATION_RUNG.set(float(self._level()), labels=self._labels)

    def _publish(self, reason: str, message: str) -> None:
        if self.recorder is None:
            return
        from ..events import Event

        self.recorder.publish(
            Event(
                object_uid="solver",
                type=(
                    "Normal" if reason == REASON_SOLVER_RESTORED
                    else "Warning"
                ),
                reason=reason,
                message=message,
            )
        )


__all__ = [
    "CircuitBreaker", "DegradationLadder", "SolverHealth",
    "CLOSED", "OPEN", "HALF_OPEN",
    "DEGRADATION_RUNG", "BREAKER_TRIPS", "QUARANTINES",
]
