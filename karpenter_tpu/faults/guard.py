"""Post-solve invariant guard: refuse to commit a kernel solve that lies.

The fused kernel's outputs drive NodeClaim creation and existing-node
nomination; a kernel returning garbage (NaN propagation, a miscompiled
``.so``, an injected corruption from faults/) must be caught BEFORE any of
it is decoded onto the scheduler's node models — the checks here run on
the raw output arrays, so a violation costs nothing to roll back: the
caller quarantines the rung (faults/breaker.py) and re-solves on the host
oracle, whose results are correct by construction (PARITY.md).

Checked invariants, all array-level:

- shape/range sanity: finite values, non-negative fills, ``0 <= n_open <=
  nmax``, claim template ids within range;
- **conservation**: per group, existing fills + claim fills + unplaced
  equals the group's pod count — the property that makes the decode's
  cursor walk place every pod exactly once (decode round-trips);
- **capacity**: each open claim's accumulated requests fit at least one
  instance type the claim's type mask still allows, and each existing
  node's fills fit its available allocatable (daemon overhead is charged
  by the kernel on top of these, so the checks are strictly lenient —
  an honest solve can never trip them);
- **pool limits**: per NodePool, the batch's newly claimed requests stay
  within the pool's remaining limit (the kernel's own ``p_limit`` rows).

Float comparisons carry a small relative tolerance: requests/allocatable
are quantized float32 on device, exact on host float64.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

_EPS = 1e-3  # quantized units; fills are integer counts of integer units


class SolverIntegrityError(RuntimeError):
    """A kernel solve violated a post-solve invariant; the solve must be
    discarded, never committed."""

    def __init__(self, violations: Sequence[str]):
        self.violations = list(violations)
        super().__init__(
            "kernel solve failed the invariant guard: "
            + "; ".join(self.violations[:5])
            + (f" (+{len(self.violations) - 5} more)"
               if len(self.violations) > 5 else "")
        )


class DecodeCommitError(RuntimeError):
    """Decode crashed AFTER committing existing-node fills onto the live
    scheduler models. The batch must be dropped (pods re-queue against a
    fresh solver next cycle) — an oracle re-solve in THIS solve would run
    on the polluted models and double-count the aborted placements."""


def _unpack_tmask(c_tmask: np.ndarray, n_open: int, T: int) -> np.ndarray:
    """[n_open, T] bool mask from either the raw bool mask or the
    bit-packed uint8 wire layout (ops/solve.py:_wire_pack). Columns past
    T are mesh padding (parallel/mesh.py pads the type axis to divide the
    mesh); padded types have zero allocatable, so trimming them can only
    make the capacity check stricter, never hide a violation."""
    rows = np.asarray(c_tmask[:n_open])
    if rows.dtype == np.uint8 and rows.shape[1] != T:
        rows = np.unpackbits(rows, axis=1)
    return rows[:, :T].astype(bool)


def check_solution(
    g_count: np.ndarray,          # [G] run-shape group counts
    g_req: np.ndarray,            # [G, R] quantized requests
    c_pool: np.ndarray,           # [NMAX]
    c_tmask: np.ndarray,          # [NMAX, T] bool or [NMAX, ceil(T/8)] u8
    n_open: int,
    exist_fills: np.ndarray,      # [G, N]
    claim_fills: np.ndarray,      # [G, NMAX]
    unplaced: np.ndarray,         # [G]
    t_alloc: np.ndarray,          # [T, R] quantized allocatable
    n_avail: np.ndarray,          # [N_real, R] quantized node headroom
    nmax: int,
    P: int,
    templates_pool: Optional[Sequence[str]] = None,
    p_limit: Optional[np.ndarray] = None,       # [P, R] remaining pool limit
    p_has_limit: Optional[np.ndarray] = None,   # [P, R] limit applies
    c_dzone: Optional[np.ndarray] = None,       # [NMAX] pinned zone ids
    c_dct: Optional[np.ndarray] = None,         # [NMAX] pinned ct ids
    zone_vals: int = 0,                         # valid zone-id bound
    ct_vals: int = 0,                           # valid ct-id bound
) -> List[str]:
    """Violation descriptions for one solve's raw outputs (empty = clean)."""
    v: List[str] = []
    g_count = np.asarray(g_count, np.float64)
    g_req = np.asarray(g_req, np.float64)
    exist_fills = np.asarray(exist_fills, np.float64)
    claim_fills = np.asarray(claim_fills, np.float64)
    unplaced = np.asarray(unplaced, np.float64)

    for name, arr in (
        ("exist_fills", exist_fills), ("claim_fills", claim_fills),
        ("unplaced", unplaced),
    ):
        if arr.size and not np.isfinite(arr).all():
            v.append(f"{name} contains non-finite values")
        elif arr.size and (arr < 0).any():
            v.append(f"{name} contains negative fills")
    if not (0 <= int(n_open) <= nmax):
        v.append(f"n_open={int(n_open)} outside [0, nmax={nmax}]")
    if v:
        return v  # arithmetic below would just cascade from the same rot

    n_open = int(n_open)
    if n_open and (
        (np.asarray(c_pool[:n_open]) < 0).any()
        or (np.asarray(c_pool[:n_open]) >= P).any()
    ):
        v.append(f"claim template ids outside [0, {P})")
        return v

    # domain pins drive vocab lookups in decode: an out-of-range pin would
    # crash mid-commit, so it must be caught here, pre-commit
    for name, pins, bound in (
        ("c_dzone", c_dzone, zone_vals), ("c_dct", c_dct, ct_vals),
    ):
        if pins is None or not n_open:
            continue
        rows = np.asarray(pins[:n_open], np.int64)
        if (rows < -1).any() or (rows >= bound).any():
            v.append(f"{name} pin ids outside [-1, {bound})")
    if v:
        return v

    # conservation: every pod of every group accounted for exactly once
    placed = exist_fills.sum(axis=1) + claim_fills.sum(axis=1) + unplaced
    bad = np.nonzero(np.abs(placed - g_count) > 0.5)[0]
    if bad.size:
        v.append(
            f"{bad.size} group(s) violate pod conservation "
            f"(e.g. group {int(bad[0])}: placed+unplaced="
            f"{placed[bad[0]]:.0f} != count={g_count[bad[0]]:.0f})"
        )

    # capacity: claim slots fit an allowed type; node fills fit headroom
    t_alloc = np.asarray(t_alloc, np.float64)
    T = t_alloc.shape[0]
    if n_open:
        req_slot = claim_fills[:, :n_open].T @ g_req  # [n_open, R]
        mask = _unpack_tmask(c_tmask, n_open, T)      # [n_open, T]
        if not mask.any(axis=1).all():
            v.append("open claim with an empty instance-type mask")
        else:
            fits = (
                req_slot[:, None, :] <= t_alloc[None, :, :] + _EPS
            ).all(axis=2)  # [n_open, T]
            bad = np.nonzero(~(fits & mask).any(axis=1))[0]
            if bad.size:
                v.append(
                    f"{bad.size} claim(s) exceed every allowed instance "
                    f"type's allocatable (e.g. slot {int(bad[0])})"
                )
    n_avail = np.asarray(n_avail, np.float64)
    N_real = n_avail.shape[0]
    if exist_fills.shape[1] > N_real and exist_fills[:, N_real:].any():
        v.append("fills on padded (nonexistent) node rows")
    if N_real:
        req_node = exist_fills[:, :N_real].T @ g_req  # [N_real, R]
        bad = np.nonzero((req_node > n_avail + _EPS).any(axis=1))[0]
        if bad.size:
            v.append(
                f"{bad.size} existing node(s) filled beyond available "
                f"capacity (e.g. node {int(bad[0])})"
            )

    # pool limits: new claims alone must stay within the remaining limit
    if (
        n_open
        and templates_pool is not None
        and p_limit is not None
        and p_has_limit is not None
        and np.asarray(p_has_limit).any()
    ):
        p_limit = np.asarray(p_limit, np.float64)
        p_has_limit = np.asarray(p_has_limit, bool)
        req_slot = claim_fills[:, :n_open].T @ g_req
        pools = {}
        for slot in range(n_open):
            p = int(np.asarray(c_pool)[slot])
            pools.setdefault(templates_pool[p], [p, np.zeros(g_req.shape[1])])
            pools[templates_pool[p]][1] += req_slot[slot]
        for pool, (p, total) in pools.items():
            over = p_has_limit[p] & (total > p_limit[p] + _EPS)
            if over.any():
                v.append(f"claims for pool {pool!r} exceed its limits")
    return v


__all__ = ["SolverIntegrityError", "check_solution"]
