"""Clock-driven exponential backoff — the one retry primitive every seam
shares.

Two shapes of retry exist in a level-triggered control plane:

- **In-cycle** (``Backoff.call``): a transient store conflict is worth a
  couple of immediate bounded retries inside the same reconcile pass —
  waiting happens through the *injected* clock (``Clock.sleep``), so tests
  on a TestClock advance simulated time instead of blocking, and the
  BLK3xx analysis tier stays green (no ``time.sleep`` anywhere).
- **Cross-pass** (``RetryTracker``): a failed cloud create should not be
  re-attempted on every tick. The tracker records a failure per key and
  gates the next attempt behind an exponentially growing, jittered
  deadline read off the injected clock — the in-process analog of
  controller-runtime's rate-limited requeue.

Jitter is drawn from a seeded per-instance RNG so chaos runs replay
exactly (see faults/__init__.py's determinism contract).
"""

from __future__ import annotations

import random
from dataclasses import dataclass
from typing import Callable, Dict, Optional, Tuple


class Backoff:
    """Exponential backoff schedule with deterministic jitter.

    ``delay(attempt)`` is ``min(max_delay, initial * factor**attempt)``
    scaled by ``1 + jitter*u`` with ``u`` from the seeded RNG. ``call``
    runs a callable with at most ``max_attempts`` tries, sleeping the
    schedule on the injected clock between them, and re-raises the last
    retriable error when the budget is spent."""

    def __init__(
        self,
        clock,
        initial: float = 1.0,
        factor: float = 2.0,
        max_delay: float = 60.0,
        jitter: float = 0.1,
        max_attempts: int = 4,
        seed: int = 0,
    ):
        self.clock = clock
        self.initial = initial
        self.factor = factor
        self.max_delay = max_delay
        self.jitter = jitter
        self.max_attempts = max_attempts
        self._rng = random.Random(seed)

    def delay(self, attempt: int) -> float:
        base = min(self.max_delay, self.initial * self.factor ** attempt)
        if self.jitter:
            base *= 1.0 + self.jitter * self._rng.random()
        return base

    def export_rng(self):
        """Jitter-RNG state for the twin checkpoint: post-resume delays
        must draw the same jitter the uninterrupted run would have."""
        return self._rng.getstate()

    def restore_rng(self, state) -> None:
        self._rng.setstate(state)

    def call(self, fn: Callable[[], object], retriable=(Exception,)):
        attempt = 0
        while True:
            try:
                return fn()
            except retriable:
                attempt += 1
                if attempt >= self.max_attempts:
                    raise
                self.clock.sleep(self.delay(attempt - 1))


@dataclass
class _RetryState:
    attempts: int
    next_at: float


class RetryTracker:
    """Per-key cross-pass retry gate for level-triggered controllers.

    ``ready(key)`` says whether the key may be attempted now;
    ``failure(key)`` records a failure and schedules the next attempt
    (returning the delay); ``success(key)`` clears the key's state. Keys
    with no recorded failure are always ready, so the tracker costs
    nothing on the healthy path."""

    def __init__(
        self,
        clock,
        initial: float = 2.0,
        factor: float = 2.0,
        max_delay: float = 300.0,
        jitter: float = 0.1,
        seed: int = 0,
    ):
        self.clock = clock
        self._backoff = Backoff(
            clock, initial=initial, factor=factor, max_delay=max_delay,
            jitter=jitter, seed=seed,
        )
        self._state: Dict[object, _RetryState] = {}

    def ready(self, key) -> bool:
        st = self._state.get(key)
        return st is None or self.clock.now() >= st.next_at

    def failure(self, key) -> float:
        st = self._state.get(key)
        attempts = st.attempts + 1 if st is not None else 1
        delay = self._backoff.delay(attempts - 1)
        self._state[key] = _RetryState(attempts, self.clock.now() + delay)
        return delay

    def success(self, key) -> None:
        self._state.pop(key, None)

    def attempts(self, key) -> int:
        st = self._state.get(key)
        return st.attempts if st is not None else 0

    def prune(self, live_keys) -> None:
        """Drop state for keys that no longer exist (deleted claims)."""
        live = set(live_keys)
        for key in [k for k in self._state if k not in live]:
            del self._state[key]

    # -- checkpoint (sim/twin.py) -------------------------------------------

    def export_state(self) -> dict:
        """Per-key backoff deadlines + jitter-RNG state: a controller that
        was mid-backoff at checkpoint time must stay backed off exactly as
        long after resume, or the replay forks."""
        return {
            "rng": self._backoff.export_rng(),
            "state": {
                k: (st.attempts, st.next_at) for k, st in self._state.items()
            },
        }

    def restore_state(self, state: dict) -> None:
        self._backoff.restore_rng(state["rng"])
        self._state = {
            k: _RetryState(attempts, next_at)
            for k, (attempts, next_at) in state["state"].items()
        }


__all__ = ["Backoff", "RetryTracker"]
