"""Seeded, deterministic fault injection for the control plane's seams.

The north-star deployment puts a gRPC sidecar, an XLA compile cache, an
optional native ``.so``, and a cloud provider on the reconcile hot path —
any of them can fail mid-solve. This package injects those failures on
purpose so the machinery that survives them (faults/backoff.py,
faults/breaker.py, faults/guard.py) is exercised by tests instead of by
outages.

Design constraints:

- **Zero overhead when off.** Every instrumented seam costs one
  module-global ``None`` check (``hit``/``mutate`` below). With no injector
  installed the solver's outputs are byte-identical to an uninstrumented
  build (pinned by tests/test_faults.py).
- **Deterministic.** A ``FaultInjector`` owns a seeded ``random.Random``
  plus per-site call counters, and reads time only from the injected
  clock — the same seed over the same call sequence replays the exact
  same fault schedule (the chaos soak asserts this).
- **Typed.** Rules raise the same exception types the real seam would
  (``ConflictError``, ``InsufficientCapacityError``, gRPC status errors),
  so the handling code under test is the production code.

Sites are plain strings, named here so call sites and fault plans can't
drift apart. Instrumented seams: the object store CRUD
(kube/store.py), cloud provider create/delete/registration
(cloudprovider/kwok.py, fake.py), kernel dispatch + output
(ops/solve.py), the scenario-batched dispatch, the gRPC RemoteSolver
(solver/service.py), and the native ``.so`` load (native/__init__.py).
"""

from __future__ import annotations

import random
import threading
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

# -- named sites ------------------------------------------------------------

STORE_CREATE = "store.create"
STORE_UPDATE = "store.update"
STORE_DELETE = "store.delete"
PROVIDER_CREATE = "cloudprovider.create"
PROVIDER_DELETE = "cloudprovider.delete"
PROVIDER_REGISTER = "cloudprovider.register"
SOLVER_DISPATCH = "solver.dispatch"
SOLVER_OUTPUT = "solver.output"
SOLVER_SCENARIOS = "solver.scenarios"
# incremental-encode seams (ISSUE 8): the delta-encode bookkeeping
# (hit: every ClusterEncoding reuse/finish; mutate: the gathered delta
# rows on their way to the device — a corrupt delta must trip the
# pre-decode invariant guard and fall back to a full re-encode) and the
# two-slot async dispatch queue (hit: submit and drain)
ENCODE_DELTA = "solver.encode_delta"
DISPATCH_QUEUE = "solver.dispatch_queue"
REMOTE_SOLVE = "remote.solve"
NATIVE_LOAD = "native.load"
# relaxation bulk pre-solver (ops/relax.py): mutate corrupts the bulk
# outputs before the merge — the combined solve must trip the invariant
# guard and shed to the full exact kernel, never commit
RELAX_OUTPUT = "solver.relax_output"
# multi-tenant service seams (solver/tenancy.py + service.py): admission
# (hit with tenant= ctx — latency rules model admission stalls, error
# rules model a rejecting policy backend) and the per-tenant solve entry
# (hit with tenant= ctx inside the tenant's ambient scope — latency
# rules on the registry clock model deadline overruns, error rules model
# per-tenant solve crashes)
TENANT_ADMIT = "tenant.admit"
TENANT_SOLVE = "tenant.solve"

ALL_SITES = (
    STORE_CREATE, STORE_UPDATE, STORE_DELETE,
    PROVIDER_CREATE, PROVIDER_DELETE, PROVIDER_REGISTER,
    SOLVER_DISPATCH, SOLVER_OUTPUT, SOLVER_SCENARIOS,
    ENCODE_DELTA, DISPATCH_QUEUE,
    REMOTE_SOLVE, NATIVE_LOAD, RELAX_OUTPUT,
    TENANT_ADMIT, TENANT_SOLVE,
)

# -- ambient context ---------------------------------------------------------
# Deep sites (ENCODE_DELTA, SOLVER_DISPATCH, RELAX_OUTPUT) fire far below
# any code that knows WHICH tenant's solve is running. The ambient scope
# threads that identity down without touching every signature: rules use
# ``match=lambda ctx: ctx.get("tenant") == "a"`` to pin a fault plan to
# one tenant. Per-thread (the sidecar's thread pool runs one solve per
# thread), layered (inner scopes win), and merged into hit/mutate ctx
# only when an injector is installed — the zero-overhead-when-off
# contract still costs exactly one module-global None check.

_AMBIENT = threading.local()


class ambient:
    """Context manager layering ambient fault-site context (e.g.
    ``tenant="a"``) onto every ``hit``/``mutate`` ctx in its dynamic
    extent, for the current thread. Explicit call-site kwargs win over
    ambient keys; inner scopes win over outer ones."""

    __slots__ = ("_ctx",)

    def __init__(self, **ctx):
        self._ctx = ctx

    def __enter__(self) -> "ambient":
        stack = getattr(_AMBIENT, "stack", None)
        if stack is None:
            stack = _AMBIENT.stack = []
        stack.append(self._ctx)
        return self

    def __exit__(self, *exc) -> bool:
        _AMBIENT.stack.pop()
        return False


def ambient_ctx() -> dict:
    """The current thread's merged ambient context (outer → inner)."""
    stack = getattr(_AMBIENT, "stack", None)
    if not stack:
        return {}
    merged: dict = {}
    for frame in stack:
        merged.update(frame)
    return merged


class InjectedFault(Exception):
    """Default exception for rules without an ``error`` factory. Seams that
    absorb a fault in place (e.g. kwok's registration defer) catch exactly
    this type so a typed production error can never be mistaken for an
    injected one."""


@dataclass
class FaultRule:
    """One fault behavior at one site.

    ``error`` is a zero-arg factory returning the exception to raise
    (default: ``InjectedFault``); ``mutate`` instead transforms the value
    passed through ``mutate()`` at output-corruption sites (a rule is one
    or the other). Scheduling knobs: ``probability`` (per matching call,
    drawn from the injector's seeded RNG), ``after`` (skip the first N
    calls at the site), ``times`` (stop after firing N times), ``until``
    (fire only while the injected clock is before this instant — how a
    chaos plan "clears"), ``match`` (predicate over the call-site context
    kwargs), and ``latency`` (seconds slept on the injected clock before
    the error/mutation, or alone for a pure-latency rule)."""

    site: str
    error: Optional[Callable[[], BaseException]] = None
    mutate: Optional[Callable[[object], object]] = None
    probability: float = 1.0
    after: int = 0
    times: Optional[int] = None
    until: Optional[float] = None
    match: Optional[Callable[[dict], bool]] = None
    latency: Optional[float] = None
    fired: int = field(default=0, compare=False)


class FaultInjector:
    """Seeded, clock-injected fault schedule over named sites.

    ``hit(site, **ctx)`` raises when an error rule fires; ``mutate(site,
    value)`` passes ``value`` through any firing mutation rules. ``log``
    records every firing as ``(site, rule_index, site_call_number)`` —
    two runs with the same seed and call sequence produce identical logs.
    ``clear()`` makes the injector inert (the "faults clear" phase of a
    chaos soak) without losing the log."""

    def __init__(
        self,
        rules: List[FaultRule],
        seed: int = 0,
        clock=None,
    ):
        self.rules = list(rules)
        self.seed = seed
        self.rng = random.Random(seed)
        self.clock = clock
        self.enabled = True
        self.calls: Dict[str, int] = {}
        self.log: List[Tuple[str, int, int]] = []

    # -- schedule -----------------------------------------------------------

    def _fires(self, rule: FaultRule, idx: int, n: int, ctx: dict) -> bool:
        if not self.enabled:
            return False
        if n <= rule.after:
            return False
        if rule.times is not None and rule.fired >= rule.times:
            return False
        if (
            rule.until is not None
            and self.clock is not None
            and self.clock.now() >= rule.until
        ):
            return False
        if rule.match is not None and not rule.match(ctx):
            return False
        if rule.probability < 1.0 and self.rng.random() >= rule.probability:
            return False
        rule.fired += 1
        self.log.append((rule.site, idx, n))
        # chaos ↔ trace correlation: a firing leaves an instant event on
        # the open span, so a Perfetto view of a chaos replay shows WHERE
        # in the decision path each fault landed (no-op without a tracer)
        from .. import obs

        obs.event("fault.fired", site=rule.site, rule=idx, call=n)
        return True

    def hit(self, site: str, **ctx) -> None:
        amb = ambient_ctx()
        if amb:
            ctx = {**amb, **ctx}
        n = self.calls[site] = self.calls.get(site, 0) + 1
        for idx, rule in enumerate(self.rules):
            if rule.site != site or rule.mutate is not None:
                continue
            if self._fires(rule, idx, n, ctx):
                if rule.latency is not None and self.clock is not None:
                    self.clock.sleep(rule.latency)
                if rule.error is not None:
                    raise rule.error()
                if rule.latency is None:
                    raise InjectedFault(f"injected fault at {site}")
                # latency-only rule: slept, nothing to raise

    def mutate(self, site: str, value, **ctx):
        amb = ambient_ctx()
        if amb:
            ctx = {**amb, **ctx}
        n = self.calls[site] = self.calls.get(site, 0) + 1
        for idx, rule in enumerate(self.rules):
            if rule.site != site or rule.mutate is None:
                continue
            if self._fires(rule, idx, n, ctx):
                if rule.latency is not None and self.clock is not None:
                    self.clock.sleep(rule.latency)
                value = rule.mutate(value)
        return value

    # -- bookkeeping --------------------------------------------------------

    def fired(self, site: Optional[str] = None) -> int:
        if site is None:
            return len(self.log)
        return sum(1 for s, _, _ in self.log if s == site)

    def clear(self) -> None:
        """Stop all rules from firing (chaos phase over); the log survives
        for replay assertions."""
        self.enabled = False

    # -- checkpoint (sim/twin.py) -------------------------------------------

    def export_state(self) -> dict:
        """RNG state + per-site counters + per-rule fired counts + the
        firing log: a resumed twin replay reconstructs the injector from
        the SAME rule plan and restores this, so the fault schedule
        continues exactly where the interrupted run stopped."""
        return {
            "rng": self.rng.getstate(),
            "enabled": self.enabled,
            "calls": dict(self.calls),
            "fired": [rule.fired for rule in self.rules],
            "log": list(self.log),
        }

    def restore_state(self, state: dict) -> None:
        self.rng.setstate(state["rng"])
        self.enabled = bool(state["enabled"])
        self.calls = dict(state["calls"])
        for rule, fired in zip(self.rules, state["fired"]):
            rule.fired = fired
        self.log = [tuple(entry) for entry in state["log"]]


# -- process-global installation seam ---------------------------------------

_INJECTOR: Optional[FaultInjector] = None


def install(injector: FaultInjector) -> FaultInjector:
    global _INJECTOR
    _INJECTOR = injector
    return injector


def uninstall() -> None:
    global _INJECTOR
    _INJECTOR = None


def active() -> Optional[FaultInjector]:
    return _INJECTOR


def hit(site: str, **ctx) -> None:
    """Consult the installed injector at a named site; no-op (one global
    read) when none is installed."""
    if _INJECTOR is not None:
        _INJECTOR.hit(site, **ctx)


def mutate(site: str, value, **ctx):
    """Pass an output value through the installed injector's mutation
    rules; identity (one global read) when none is installed."""
    if _INJECTOR is None:
        return value
    return _INJECTOR.mutate(site, value, **ctx)


__all__ = [
    "FaultInjector", "FaultRule", "InjectedFault",
    "install", "uninstall", "active", "hit", "mutate",
    "ambient", "ambient_ctx",
    "STORE_CREATE", "STORE_UPDATE", "STORE_DELETE",
    "PROVIDER_CREATE", "PROVIDER_DELETE", "PROVIDER_REGISTER",
    "SOLVER_DISPATCH", "SOLVER_OUTPUT", "SOLVER_SCENARIOS", "RELAX_OUTPUT",
    "ENCODE_DELTA", "DISPATCH_QUEUE",
    "REMOTE_SOLVE", "NATIVE_LOAD", "TENANT_ADMIT", "TENANT_SOLVE",
    "ALL_SITES",
]
