"""Operator configuration: CLI flags with environment-variable fallback.

Plays the role of pkg/operator/options (options.go:50-161): every knob is a
flag whose default comes from an env var, durations parse Go-style strings
("10s", "1m30s"), and feature gates arrive as one "Name=bool,..." string
(options.go:128-148). Instead of riding on a context.Context, the parsed
``Options`` object is passed explicitly to the Operator.
"""

from __future__ import annotations

import argparse
import os
import re
from dataclasses import dataclass, field, fields
from typing import List, Optional

_DURATION_RE = re.compile(r"(\d+(?:\.\d+)?)(h|ms|m|s|us|µs|ns)")
_DURATION_UNIT = {
    "h": 3600.0,
    "m": 60.0,
    "s": 1.0,
    "ms": 1e-3,
    "us": 1e-6,
    "µs": 1e-6,
    "ns": 1e-9,
}

VALID_LOG_LEVELS = ("", "debug", "info", "error")  # options.go:34
KNOWN_FEATURE_GATES = ("NodeRepair", "ReservedCapacity", "SpotToSpotConsolidation")


def parse_duration(s: str) -> float:
    """Parse a Go duration string ("10s", "1m30s", "100ms") to seconds."""
    if isinstance(s, (int, float)):
        return float(s)
    s = s.strip()
    neg = s.startswith("-")
    if neg:
        s = s[1:]
    if not s:
        raise ValueError("empty duration")
    pos = 0
    total = 0.0
    for m in _DURATION_RE.finditer(s):
        if m.start() != pos:
            raise ValueError(f"invalid duration {s!r}")
        total += float(m.group(1)) * _DURATION_UNIT[m.group(2)]
        pos = m.end()
    if pos != len(s):
        raise ValueError(f"invalid duration {s!r}")
    return -total if neg else total


def _env_str(name: str, default: str) -> str:
    return os.environ.get(name, default)


def _env_int(name: str, default: int) -> int:
    raw = os.environ.get(name)
    return int(raw) if raw is not None else default


def _env_bool(name: str, default: bool) -> bool:
    raw = os.environ.get(name)
    if raw is None:
        return default
    if raw not in ("true", "false"):
        raise ValueError(f"{name}={raw!r} is not a valid value, must be true or false")
    return raw == "true"


def _env_duration(name: str, default: float) -> float:
    raw = os.environ.get(name)
    return parse_duration(raw) if raw is not None else default


@dataclass
class FeatureGates:
    """Feature-gate map parsed from "Name=bool,..." (options.go:41-47, 128-148)."""

    node_repair: bool = False
    reserved_capacity: bool = False
    spot_to_spot_consolidation: bool = False

    @classmethod
    def parse(cls, s: str) -> "FeatureGates":
        gates = cls()
        if not s.strip():
            return gates
        attr = {
            "NodeRepair": "node_repair",
            "ReservedCapacity": "reserved_capacity",
            "SpotToSpotConsolidation": "spot_to_spot_consolidation",
        }
        for part in s.split(","):
            part = part.strip()
            if not part:
                continue
            if "=" not in part:
                raise ValueError(f"invalid feature gate {part!r}, expected Name=bool")
            name, _, val = part.partition("=")
            name, val = name.strip(), val.strip().lower()
            if val not in ("true", "false"):
                raise ValueError(f"feature gate {name}={val!r} must be true or false")
            if name in attr:
                setattr(gates, attr[name], val == "true")
            # unknown gates are tolerated (forward compatibility), like
            # utilflag.NewMapStringBool which only errs on malformed syntax
        return gates


@dataclass
class Options:
    """All operator knobs (options.go:50-67). These dataclass defaults are
    the single source of truth; build_parser() reads them, then env vars
    override defaults and explicit flags override env vars."""

    service_name: str = ""
    metrics_port: int = 8080
    health_probe_port: int = 8081
    kube_client_qps: int = 200
    kube_client_burst: int = 300
    enable_profiling: bool = False
    disable_leader_election: bool = False
    leader_election_name: str = "karpenter-leader-election"
    leader_election_namespace: str = ""
    memory_limit: int = -1
    log_level: str = "info"
    log_output_paths: str = "stdout"
    log_error_output_paths: str = "stderr"
    batch_max_duration: float = 10.0  # options.go:100
    batch_idle_duration: float = 1.0  # options.go:101
    feature_gates: FeatureGates = field(default_factory=FeatureGates)
    # kwok-style extension (kwok/options/options.go)
    instance_types_file_path: str = ""
    # solver: "tpu" (jitted JAX kernels) or "native" (C++ host core);
    # solver-mesh "auto" shards solves over every local device when more
    # than one is present (SolverConfig.mesh), "" = single device
    solver_backend: str = "tpu"
    solver_mesh: str = ""
    # gRPC solver-sidecar target (host:port); "" = solve in-process
    solver_address: str = ""
    # decision-path span tracing (obs/): off by default; the seed keeps
    # replayed chaos runs producing identical traces
    enable_tracing: bool = False
    trace_seed: int = 0
    # shutdown artifact paths ("" skips): Chrome trace-event JSON and the
    # Prometheus text exposition of the metrics registry
    trace_path: str = ""
    metrics_dump_path: str = ""

    def validate(self) -> None:
        if self.log_level not in VALID_LOG_LEVELS:
            raise ValueError(
                f"invalid log level {self.log_level!r}, must be one of {VALID_LOG_LEVELS}"
            )
        if self.batch_max_duration <= 0:
            raise ValueError("batch-max-duration must be positive")
        if self.batch_idle_duration <= 0:
            raise ValueError("batch-idle-duration must be positive")
        if self.solver_backend not in ("tpu", "native"):
            raise ValueError(
                f"invalid solver backend {self.solver_backend!r},"
                " must be 'tpu' or 'native'"
            )
        if self.solver_mesh not in ("", "auto"):
            raise ValueError(
                f"invalid solver mesh {self.solver_mesh!r},"
                " must be '' or 'auto'"
            )


def build_parser() -> argparse.ArgumentParser:
    """Flag set with env fallback for every flag (FlagSet, options.go:69-103).
    Defaults come from the Options dataclass so they are defined once."""
    d = Options()
    p = argparse.ArgumentParser(prog="karpenter-tpu", add_help=True)
    p.add_argument("--karpenter-service", dest="service_name",
                   default=_env_str("KARPENTER_SERVICE", d.service_name))
    p.add_argument("--metrics-port", dest="metrics_port", type=int,
                   default=_env_int("METRICS_PORT", d.metrics_port))
    p.add_argument("--health-probe-port", dest="health_probe_port", type=int,
                   default=_env_int("HEALTH_PROBE_PORT", d.health_probe_port))
    p.add_argument("--kube-client-qps", dest="kube_client_qps", type=int,
                   default=_env_int("KUBE_CLIENT_QPS", d.kube_client_qps))
    p.add_argument("--kube-client-burst", dest="kube_client_burst", type=int,
                   default=_env_int("KUBE_CLIENT_BURST", d.kube_client_burst))
    p.add_argument("--enable-profiling", dest="enable_profiling",
                   choices=("true", "false"),
                   default=str(_env_bool("ENABLE_PROFILING", d.enable_profiling)).lower())
    p.add_argument("--disable-leader-election", dest="disable_leader_election",
                   choices=("true", "false"),
                   default=str(_env_bool(
                       "DISABLE_LEADER_ELECTION", d.disable_leader_election)).lower())
    p.add_argument("--leader-election-name", dest="leader_election_name",
                   default=_env_str("LEADER_ELECTION_NAME", d.leader_election_name))
    p.add_argument("--leader-election-namespace", dest="leader_election_namespace",
                   default=_env_str(
                       "LEADER_ELECTION_NAMESPACE", d.leader_election_namespace))
    p.add_argument("--memory-limit", dest="memory_limit", type=int,
                   default=_env_int("MEMORY_LIMIT", d.memory_limit))
    p.add_argument("--log-level", dest="log_level",
                   default=_env_str("LOG_LEVEL", d.log_level))
    p.add_argument("--log-output-paths", dest="log_output_paths",
                   default=_env_str("LOG_OUTPUT_PATHS", d.log_output_paths))
    p.add_argument("--log-error-output-paths", dest="log_error_output_paths",
                   default=_env_str("LOG_ERROR_OUTPUT_PATHS", d.log_error_output_paths))
    p.add_argument("--batch-max-duration", dest="batch_max_duration",
                   default=os.environ.get(
                       "BATCH_MAX_DURATION", f"{d.batch_max_duration}s"))
    p.add_argument("--batch-idle-duration", dest="batch_idle_duration",
                   default=os.environ.get(
                       "BATCH_IDLE_DURATION", f"{d.batch_idle_duration}s"))
    p.add_argument("--feature-gates", dest="feature_gates",
                   default=_env_str(
                       "FEATURE_GATES",
                       "NodeRepair=false,ReservedCapacity=false,SpotToSpotConsolidation=false",
                   ))
    p.add_argument("--instance-types-file-path", dest="instance_types_file_path",
                   default=_env_str(
                       "INSTANCE_TYPES_FILE_PATH", d.instance_types_file_path))
    p.add_argument("--solver-backend", dest="solver_backend",
                   default=_env_str("SOLVER_BACKEND", d.solver_backend))
    p.add_argument("--solver-mesh", dest="solver_mesh",
                   default=_env_str("SOLVER_MESH", d.solver_mesh))
    p.add_argument("--solver-address", dest="solver_address",
                   default=_env_str(
                       "KARPENTER_SOLVER_ADDRESS", d.solver_address))
    p.add_argument("--enable-tracing", dest="enable_tracing",
                   choices=("true", "false"),
                   default=str(_env_bool(
                       "ENABLE_TRACING", d.enable_tracing)).lower())
    p.add_argument("--trace-seed", dest="trace_seed", type=int,
                   default=_env_int("TRACE_SEED", d.trace_seed))
    p.add_argument("--trace-path", dest="trace_path",
                   default=_env_str("TRACE_PATH", d.trace_path))
    p.add_argument("--metrics-dump-path", dest="metrics_dump_path",
                   default=_env_str(
                       "METRICS_DUMP_PATH", d.metrics_dump_path))
    return p


def parse_options(argv: Optional[List[str]] = None) -> Options:
    """Parse argv into validated Options; None means sys.argv[1:] (standard
    argparse convention)."""
    ns = build_parser().parse_args(argv)
    opts = Options(
        service_name=ns.service_name,
        metrics_port=ns.metrics_port,
        health_probe_port=ns.health_probe_port,
        kube_client_qps=ns.kube_client_qps,
        kube_client_burst=ns.kube_client_burst,
        enable_profiling=ns.enable_profiling == "true",
        disable_leader_election=ns.disable_leader_election == "true",
        leader_election_name=ns.leader_election_name,
        leader_election_namespace=ns.leader_election_namespace,
        memory_limit=ns.memory_limit,
        log_level=ns.log_level,
        log_output_paths=ns.log_output_paths,
        log_error_output_paths=ns.log_error_output_paths,
        batch_max_duration=parse_duration(ns.batch_max_duration),
        batch_idle_duration=parse_duration(ns.batch_idle_duration),
        feature_gates=FeatureGates.parse(ns.feature_gates),
        instance_types_file_path=ns.instance_types_file_path,
        solver_backend=ns.solver_backend,
        solver_mesh=ns.solver_mesh,
        solver_address=ns.solver_address,
        enable_tracing=ns.enable_tracing == "true",
        trace_seed=ns.trace_seed,
        trace_path=ns.trace_path,
        metrics_dump_path=ns.metrics_dump_path,
    )
    opts.validate()
    return opts


__all__ = [
    "FeatureGates",
    "Options",
    "build_parser",
    "parse_duration",
    "parse_options",
]
